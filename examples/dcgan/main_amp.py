"""DCGAN with amp — the multi-model / multi-optimizer / multi-loss path.

Capability port of the reference example (examples/dcgan/main_amp.py):
two models (G, D), two optimizers, three backward passes per iteration
(D-real, D-fake, G) with ``num_losses=3`` per-loss scalers — the
reference's ``amp.scale_loss(..., loss_id=k)`` pattern — on synthetic
data.

Run (install the package first — ``pip install -e .`` from the repo root):
    python examples/dcgan/main_amp.py --steps 5 -b 16
"""

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

from apex_tpu import amp
from apex_tpu.models import Discriminator, Generator


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("data", nargs="?", default=None,
                   help="image-folder root (reference: --dataset folder; "
                        "omit for synthetic data)")
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--opt-level", type=str, default="O1")
    p.add_argument("--image-size", type=int, default=64)
    return p.parse_args(argv)


def bce_logits(logits, target):
    return jnp.mean(optax.sigmoid_binary_cross_entropy(
        logits.astype(jnp.float32), jnp.full(logits.shape, target)))


def main(argv=None):
    args = parse_args(argv)
    netG = Generator(nz=args.nz, ngf=args.ngf)
    netD = Discriminator(ndf=args.ndf)
    key = jax.random.PRNGKey(0)
    z0 = jnp.zeros((args.batch_size, 1, 1, args.nz))
    x0 = jnp.zeros((args.batch_size, args.image_size, args.image_size, 3))

    varsG = netG.init(key, z0, train=False)
    varsD = netD.init(key, x0, train=False)
    pG, sG = varsG["params"], varsG["batch_stats"]
    pD, sD = varsD["params"], varsD["batch_stats"]

    txG = optax.adam(args.lr, b1=args.beta1)
    txD = optax.adam(args.lr, b1=args.beta1)
    # two models, two optimizers, three losses (reference: amp.initialize
    # with num_losses=3, loss_id 0/1/2)
    pG, optG = amp.initialize(pG, txG, opt_level=args.opt_level,
                              num_losses=3)
    pD, optD = amp.initialize(pD, txD, opt_level=args.opt_level,
                              num_losses=3)
    stG, stD = optG.init(pG), optD.init(pD)

    @jax.jit
    def train_step(pG, sG, stG, pD, sD, stD, real, z):
        # --- D step: real (loss_id 0) + fake (loss_id 1) ---
        def d_loss_real(p):
            out, newv = netD.apply({"params": p, "batch_stats": sD}, real,
                                   train=True, mutable=["batch_stats"])
            return bce_logits(out, 1.0), newv["batch_stats"]

        f0 = amp.value_and_scaled_grad(d_loss_real, optD, loss_id=0,
                                       has_aux=True)
        (lossD_real, sD1), g0, inf0 = f0(pD, stD)

        # fake pass runs on the stats updated by the real pass (sequential
        # backward passes, as in the reference example)
        def d_loss_fake(p, fake):
            out, newv = netD.apply({"params": p, "batch_stats": sD1}, fake,
                                   train=True, mutable=["batch_stats"])
            return bce_logits(out, 0.0), newv["batch_stats"]

        fake, newsG = netG.apply({"params": pG, "batch_stats": sG}, z,
                                 train=True, mutable=["batch_stats"])
        newsG = newsG["batch_stats"]

        f1 = amp.value_and_scaled_grad(
            lambda p: d_loss_fake(p, jax.lax.stop_gradient(fake)), optD,
            loss_id=1, has_aux=True)
        (lossD_fake, sD2), g1, inf1 = f1(pD, stD)
        gD = jax.tree_util.tree_map(jnp.add, g0, g1)
        # per-loss scaler discipline under a shared step: the skip
        # predicate ORs both flags, but each loss's dynamic scale
        # advances from its OWN overflow only
        stD = optD.update_scaler(stD, inf1, loss_id=1)
        pD, stD, _ = optD.apply_gradients(
            gD, stD, pD, loss_id=0, grads_already_unscaled=True,
            found_inf=inf0 | inf1, scaler_found_inf=inf0)

        # --- G step (loss_id 2): non-saturating loss through D; G stats
        # continue from the D-step forward (newsG), as in the reference ---
        def g_loss(p):
            fake, newv = netG.apply({"params": p, "batch_stats": newsG}, z,
                                    train=True, mutable=["batch_stats"])
            out, _ = netD.apply({"params": pD, "batch_stats": sD2}, fake,
                                train=True, mutable=["batch_stats"])
            return bce_logits(out, 1.0), newv["batch_stats"]

        f2 = amp.value_and_scaled_grad(g_loss, optG, loss_id=2,
                                       has_aux=True)
        (lossG, newsG2), gG, inf2 = f2(pG, stG)
        pG, stG, _ = optG.apply_gradients(
            gG, stG, pG, loss_id=2, grads_already_unscaled=True,
            found_inf=inf2)
        return (pG, newsG2, stG, pD, sD2, stD,
                jnp.stack([lossD_real + lossD_fake, lossG]))

    rs = np.random.RandomState(0)

    def real_batches():
        """Synthetic noise images, or the reference's image-folder path
        (dcgan/main_amp.py --dataset folder: ImageFolder + resize/crop +
        [-1, 1] normalization) via apex_tpu.data."""
        if not args.data:
            while True:
                yield jnp.asarray(
                    rs.rand(args.batch_size, args.image_size,
                            args.image_size, 3) * 2 - 1, jnp.float32)
        from apex_tpu import data as apex_data

        ds = apex_data.ImageFolder(args.data)
        if len(ds) < args.batch_size:
            raise ValueError(
                f"{len(ds)} images under {args.data} is fewer than batch "
                f"size {args.batch_size}")
        # reference pipeline: Resize(image_size) + CenterCrop(image_size)
        # — no resize headroom
        tf = apex_data.eval_transform(args.image_size, args.image_size)
        epoch = 0
        while True:  # cycle epochs until the step budget is spent
            for images, _ in apex_data.prefetch(
                    ds, args.batch_size, tf, shuffle=True, drop_last=True,
                    seed=0, epoch=epoch):
                yield jnp.asarray(images * 2.0 - 1.0)  # [0,1) → [-1,1)
            epoch += 1

    reals = real_batches()
    t0 = time.perf_counter()
    for i in range(args.steps):
        real = next(reals)
        z = jnp.asarray(rs.randn(args.batch_size, 1, 1, args.nz),
                        jnp.float32)
        pG, sG, stG, pD, sD, stD, losses = train_step(
            pG, sG, stG, pD, sD, stD, real, z)
        losses = np.asarray(losses)
        print(f"[{i}/{args.steps}] Loss_D {losses[0]:.4f} "
              f"Loss_G {losses[1]:.4f}", flush=True)
    print(f"DONE {args.steps / (time.perf_counter() - t0):.2f} it/s")
    return float(losses[0]), float(losses[1])


if __name__ == "__main__":
    main()
