"""ImageNet training with amp + data-parallel mesh + SyncBatchNorm.

Capability port of the reference example (examples/imagenet/main_amp.py,
882 LoC tree): same CLI surface (arch, O-levels, keep-batchnorm-fp32,
loss-scale, print-freq metering, checkpoint/resume, --prof), re-shaped for
TPU: ONE jitted SPMD train step inside shard_map over the "data" mesh axis
replaces the DDP-hook + stream machinery; images/sec and prec@1/5 metering
match the reference's AverageMeter output format.

Run (synthetic data smoke; install the package first — ``pip install -e .``
from the repo root):
    python examples/imagenet/main_amp.py --synthetic --steps 20 -b 32
Real data expects an ImageFolder-style numpy loader — see make_loader.
"""

import argparse
import itertools
import os
import pickle
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import resnet18, resnet50
from apex_tpu.parallel.multiproc import init_distributed
from apex_tpu.optimizers.fused_sgd import fused_sgd
from apex_tpu.parallel.distributed import (
    allreduce_gradients,
)

ARCHS = {"resnet50": resnet50, "resnet18": resnet18}


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="JAX/TPU ImageNet Training (apex main_amp port)")
    p.add_argument("data", nargs="?", default=None,
                   help="path to dataset (omit with --synthetic)")
    p.add_argument("--arch", "-a", default="resnet50", choices=ARCHS)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("-b", "--batch-size", type=int, default=256,
                   help="PER-PROCESS batch size (one process per host; "
                        "the global batch is batch_size x processes, "
                        "split across the data mesh axis)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", "--wd", type=float, default=1e-4)
    p.add_argument("--print-freq", "-p", type=int, default=10)
    p.add_argument("--resume", default="", type=str)
    p.add_argument("--opt-level", type=str, default="O1")
    p.add_argument("--keep-batchnorm-fp32", type=str, default=None)
    p.add_argument("--loss-scale", type=str, default=None)
    p.add_argument("--prof", type=int, default=-1,
                   help="profile this many steps with jax.profiler")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--evaluate", "-e", action="store_true",
                   help="evaluate on the validation set and exit")
    p.add_argument("--synthetic", action="store_true",
                   help="random data (no input pipeline)")
    p.add_argument("--steps", type=int, default=None,
                   help="cap steps per epoch (smoke runs)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--checkpoint", default="checkpoint.pkl")
    return p.parse_args(argv)


class AverageMeter:
    """Reference: main_amp.py AverageMeter."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = self.avg = self.sum = 0.0
        self.count = 0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


def make_lr_schedule(base_lr, len_epoch):
    """The reference example's adjust_learning_rate (main_amp.py): /10
    step decay at epochs 30/60/80 with a 5-epoch linear warmup, as a
    jit-safe step->lr callable for the fused optimizer."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        epoch = step / float(len_epoch)
        factor = (jnp.floor(epoch / 30.0)
                  + (epoch >= 80.0).astype(jnp.float32))
        lr = base_lr * jnp.power(0.1, factor)
        warm = base_lr * (1.0 + step) / (5.0 * len_epoch)
        return jnp.where(epoch < 5.0, jnp.minimum(warm, lr), lr)

    return sched


def _loss_and_metrics(logits, labels):
    """CE loss + prec@1/5 (shared by the train and eval steps; reference
    metering main_amp.py:380-420)."""
    one_hot = jax.nn.one_hot(labels, logits.shape[-1])
    loss = -jnp.mean(jnp.sum(
        jax.nn.log_softmax(logits.astype(jnp.float32)) * one_hot, axis=-1))
    preds = jnp.argsort(logits, axis=-1)[:, -5:]
    top1 = jnp.mean((preds[:, -1] == labels).astype(jnp.float32))
    top5 = jnp.mean(jnp.any(preds == labels[:, None],
                            axis=-1).astype(jnp.float32))
    return loss, top1, top5


_COMMON_SEED = None


def _common_seed(args):
    """One seed shared by EVERY process (init params, shuffle order):
    entropy from process 0 broadcast to all — divergent seeds would break
    the replicated-params DDP invariant. --deterministic pins it to 0."""
    if args.deterministic:
        return 0  # never the cached entropy seed of an earlier run
    global _COMMON_SEED
    if _COMMON_SEED is None:
        seed = np.random.randint(2 ** 31)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            seed = int(multihost_utils.broadcast_one_to_all(
                np.int32(seed)))
        _COMMON_SEED = seed
    return _COMMON_SEED


def make_synthetic_loader(args, steps):
    # rank-distinct synthetic data (each process is its own DDP shard);
    # --deterministic keeps it reproducible per rank
    rs = np.random.RandomState(jax.process_index() if args.deterministic
                               else None)
    h = args.image_size

    def gen():
        for _ in range(steps):
            images = rs.rand(args.batch_size, h, h, 3).astype(np.float32)
            labels = rs.randint(0, args.num_classes, (args.batch_size,))
            yield images, labels

    return gen


_DATASETS = {}  # root -> ImageFolder (the ~1.28M-entry scan runs once)


def _image_folder(root):
    from apex_tpu import data as apex_data

    if root not in _DATASETS:
        _DATASETS[root] = apex_data.ImageFolder(root)
    return _DATASETS[root]


def _to_global_batch(mesh, x):
    """Single-process: plain device array. Multi-process (launched via
    apex_tpu.parallel.multiproc): stitch each process's local batch into
    the data-sharded GLOBAL batch the jitted step takes — the functional
    analog of the reference's DistributedSampler feeding per-rank shards
    (examples/imagenet/main_amp.py --local_rank path)."""
    if jax.process_count() == 1:
        return jnp.asarray(x)
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), np.asarray(x))


def _split_root(data, split):
    """torchvision convention root/<split>/<class>/... with a fallback to
    the flat root/<class>/... layout."""
    root = os.path.join(data, split)
    return root if os.path.isdir(root) else data


def make_loader(args, steps, train=True, epoch=0):
    """Dispatch: synthetic pipeline, or the real ImageFolder pipeline
    (apex_tpu.data — the torchvision ImageFolder/DataLoader analog of the
    reference's main_amp.py) when a data path is given. Returns
    (generator, steps)."""
    if args.synthetic or not args.data:
        return make_synthetic_loader(args, steps)(), steps

    from apex_tpu import data as apex_data

    rank, world = jax.process_index(), jax.process_count()
    root = _split_root(args.data, "train" if train else "val")
    ds = _image_folder(root)
    # main() resolves num_classes from the train folder before building
    # the model; a mismatch here (e.g. a val tree with different classes)
    # would silently mis-index labels against the model head
    if len(ds.classes) != args.num_classes:
        raise ValueError(
            f"{len(ds.classes)} classes under {root} vs --num-classes "
            f"{args.num_classes}")
    tf = (apex_data.train_transform(args.image_size) if train
          else apex_data.eval_transform(max(args.image_size + 32, 256),
                                        args.image_size))
    # per-RANK step count: every process feeds batch_size of the global
    # batch and the common-shuffle shard partitions the dataset
    n = len(ds) // (args.batch_size * world)
    if n == 0:
        raise ValueError(
            f"{len(ds)} images under {root} is fewer than the global "
            f"batch ({args.batch_size} x {world} processes)")
    tail = len(ds) - n * args.batch_size * world
    if not train and tail and epoch == 0 and rank == 0:
        print(f"NOTE: {tail} tail validation samples are not evaluated "
              f"({len(ds)} images, global batch "
              f"{args.batch_size * world})", flush=True)
    steps = min(steps, n) if steps else n
    gen = apex_data.prefetch(
        ds, args.batch_size, tf, shuffle=train, drop_last=True,
        seed=_common_seed(args), epoch=epoch, shard=(rank, world))
    return itertools.islice(gen, steps), steps


def build_train_step(model, opt, mesh, compute_dtype=jnp.float32):
    """The whole apex train iteration as one SPMD program.

    ``compute_dtype`` is the amp policy's compute dtype: input images are
    cast to it on entry (the reference casts incoming fp32 inputs to half
    under O2/O3 — apex/amp/_initialize.py:176-201)."""

    def step(params, batch_stats, amp_state, images, labels):
        def local(params, batch_stats, amp_state, images, labels):
            images = images.astype(compute_dtype)

            def loss_fn(p):
                logits, new_vars = model.apply(
                    {"params": p, "batch_stats": batch_stats}, images,
                    train=True, mutable=["batch_stats"])
                loss = _loss_and_metrics(logits, labels)[0]
                return loss, (new_vars["batch_stats"], logits)

            f = amp.value_and_scaled_grad(loss_fn, opt, has_aux=True)
            (loss, (new_bstats, logits)), grads, found_inf = f(
                params, amp_state)
            # DDP: one fused allreduce (apex DDP bucket machinery → psum)
            grads = allreduce_gradients(grads, "data")
            found_inf = lax.pmax(found_inf.astype(jnp.float32),
                                 "data") > 0
            params, amp_state, info = opt.apply_gradients(
                grads, amp_state, params, grads_already_unscaled=True,
                found_inf=found_inf)

            _, top1, top5 = _loss_and_metrics(logits, labels)
            metrics = lax.pmean(
                jnp.stack([loss, top1 * 100, top5 * 100]), "data")
            return params, new_bstats, amp_state, metrics, info["overflow"]

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P(), P()), check_vma=False)(
            params, batch_stats, amp_state, images, labels)

    # no donation: under O2 the fp32 (keep_batchnorm_fp32) param leaves
    # alias their master copies in amp_state across the jit boundary, and
    # donating aliased buffers is an XLA error
    return jax.jit(step)


def build_eval_step(model, mesh, compute_dtype=jnp.float32):
    """Validation step (reference: main_amp.py validate()/AverageMeter):
    eval-mode forward (running BN stats), mean loss + prec@1/5 over the
    data axis."""

    def step(params, batch_stats, images, labels):
        def local(params, batch_stats, images, labels):
            images = images.astype(compute_dtype)
            logits = model.apply(
                {"params": params, "batch_stats": batch_stats}, images,
                train=False)
            loss, top1, top5 = _loss_and_metrics(logits, labels)
            return lax.pmean(jnp.stack([loss, top1 * 100, top5 * 100]),
                             "data")

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")), out_specs=P(),
            check_vma=False)(params, batch_stats, images, labels)

    return jax.jit(step)


def validate(args, model, mesh, params, batch_stats, compute_dtype,
             steps=None):
    """Reference: main_amp.py validate() — eval loop with metering."""
    eval_step = build_eval_step(model, mesh, compute_dtype)
    losses, top1, top5 = AverageMeter(), AverageMeter(), AverageMeter()
    # synthetic: default 8 smoke batches; real data: the FULL val set
    # unless --steps caps it
    steps = steps or args.steps
    if args.synthetic or not args.data:
        steps = steps or 8
    loader, steps = make_loader(args, steps, train=False)
    for i, (images, labels) in enumerate(loader):
        m = np.asarray(eval_step(params, batch_stats,
                                 _to_global_batch(mesh, images),
                                 _to_global_batch(mesh, labels)))
        losses.update(float(m[0]), args.batch_size)
        top1.update(float(m[1]), args.batch_size)
        top5.update(float(m[2]), args.batch_size)
        if i % args.print_freq == 0:
            print(f"Test: [{i}/{steps}]  Loss {losses.val:.4f} "
                  f"({losses.avg:.4f})  Prec@1 {top1.val:.2f} ({top1.avg:.2f})"
                  f"  Prec@5 {top5.val:.2f} ({top5.avg:.2f})", flush=True)
    print(f" * Prec@1 {top1.avg:.3f} Prec@5 {top5.avg:.3f}", flush=True)
    return losses.avg, top1.avg, top5.avg


def main(argv=None):
    # no-op unless launched by ``python -m apex_tpu.parallel.multiproc``
    # (the torch.distributed.launch analog); afterwards jax.devices() is
    # the GLOBAL device list and the mesh below spans all hosts
    init_distributed()
    args = parse_args(argv)
    if args.data and not args.synthetic:
        # resolve the real class count BEFORE the model is built
        troot = _split_root(args.data, "train")
        found = len(_image_folder(troot).classes)
        if found != args.num_classes:
            print(f"NOTE: {found} classes under {troot} "
                  f"(--num-classes {args.num_classes}); using the folder "
                  "count", flush=True)
            args.num_classes = found
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("data",))
    ndev = len(devices)
    nproc = jax.process_count()
    # -b is the PER-PROCESS batch (reference: per-rank batch under
    # torch.distributed.launch); the global batch must split over devices
    assert (args.batch_size * nproc) % ndev == 0

    # resolve the amp properties ONCE, before building the model: the
    # policy's compute dtype is the conv/matmul dtype (flax ``dtype=``),
    # which is what makes O1/O2/O3 actually compute in bf16 on the MXU (the
    # functional analog of the reference's model cast,
    # apex/amp/_initialize.py:176-201). The same override values go to
    # amp.initialize below so there is a single source of truth.
    from apex_tpu.amp.frontend import Properties, build_policy, opt_levels

    loss_scale = args.loss_scale
    if loss_scale is not None and loss_scale != "dynamic":
        loss_scale = float(loss_scale)
    keep_bn = args.keep_batchnorm_fp32
    if isinstance(keep_bn, str):
        keep_bn = {"True": True, "False": False}.get(keep_bn, None)

    properties = opt_levels[args.opt_level](Properties())
    for name, value in (("keep_batchnorm_fp32", keep_bn),
                        ("loss_scale", loss_scale)):
        if value is not None:
            setattr(properties, name, value)
    policy = build_policy(properties)
    model = ARCHS[args.arch](num_classes=args.num_classes,
                             norm_axis_name="data",
                             dtype=policy.compute_dtype)
    # numpy (not device-committed): multi-process jit accepts host arrays
    # as replicated inputs; a process-local jnp array would not be global
    rs_img = np.zeros((2 * nproc, args.image_size, args.image_size, 3),
                      np.float32)

    # --deterministic: fixed init/data seeds -> bitwise-reproducible runs
    # (the reference flag sets cudnn.deterministic + torch.manual_seed)
    init_seed = _common_seed(args)

    def init(x):
        return model.init(jax.random.PRNGKey(init_seed), x, train=False)

    variables = jax.jit(shard_map(
        init, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False))(rs_img)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # steps/epoch feeds the reference lr schedule (warmup epochs 0-5,
    # /10 decay at 30/60/80); the optimizer reads lr(count) on-device.
    # Use the REAL epoch length (the reference passes len(train_loader)):
    # the actual dataset size for real data, the loader's cap otherwise.
    if args.data and not args.synthetic:
        full_len = len(_image_folder(_split_root(args.data, "train"))) \
            // (args.batch_size * nproc)
    else:
        full_len = 1281167 // (args.batch_size * nproc)
    steps = min(args.steps, full_len) if args.steps else full_len
    tx = fused_sgd(learning_rate=make_lr_schedule(args.lr, steps),
                   momentum=args.momentum,
                   weight_decay=args.weight_decay)
    params, opt = amp.initialize(
        params, tx, opt_level=args.opt_level,
        keep_batchnorm_fp32=keep_bn, loss_scale=loss_scale)
    # jitted so the state inherits the params' (global) sharding — eager
    # init would make process-local scalars a multi-host jit rejects
    amp_state = jax.jit(opt.init)(params)

    start_epoch = 0
    if args.resume:
        have = os.path.isfile(args.resume)
        if nproc > 1:
            # checkpoints are rank-0-written: every process must see the
            # same file (shared filesystem) or resume silently
            # desynchronizes the replicas — fail loudly instead
            from jax.experimental import multihost_utils

            have0 = bool(multihost_utils.broadcast_one_to_all(
                np.int32(have)))
            if have0 != have:
                raise RuntimeError(
                    f"--resume {args.resume} visible on some processes "
                    "only; checkpoints must live on a shared filesystem")
            have = have0
        if have:
            with open(args.resume, "rb") as f:
                ckpt = pickle.load(f)
            params, batch_stats, amp_state = (
                ckpt["params"], ckpt["batch_stats"], ckpt["amp_state"])
            start_epoch = ckpt["epoch"]
            print(f"=> loaded checkpoint (epoch {start_epoch})")

    if args.evaluate:
        return validate(args, model, mesh, params, batch_stats,
                        policy.compute_dtype)[0]

    train_step = build_train_step(model, opt, mesh,
                                  compute_dtype=policy.compute_dtype)

    batch_time, losses = AverageMeter(), AverageMeter()
    top1, top5 = AverageMeter(), AverageMeter()
    for epoch in range(start_epoch, args.epochs):
        batch_time.reset(), losses.reset(), top1.reset(), top5.reset()
        loader, steps = make_loader(args, steps, train=True, epoch=epoch)
        end = time.perf_counter()
        for i, (images, labels) in enumerate(loader):
            if i == args.prof:
                jax.profiler.start_trace("/tmp/jax_trace")
            params, batch_stats, amp_state, metrics, overflow = train_step(
                params, batch_stats, amp_state,
                _to_global_batch(mesh, images),
                _to_global_batch(mesh, labels))
            if i == 0:
                jax.block_until_ready(metrics)  # exclude compile
                end = time.perf_counter()
                continue
            jax.block_until_ready(metrics)
            batch_time.update(time.perf_counter() - end)
            end = time.perf_counter()
            m = np.asarray(metrics)
            losses.update(float(m[0]), args.batch_size)
            top1.update(float(m[1]), args.batch_size)
            top5.update(float(m[2]), args.batch_size)
            if i % args.print_freq == 0:
                ips = args.batch_size * nproc / batch_time.avg
                print(f"Epoch: [{epoch}][{i}/{steps}]  "
                      f"Time {batch_time.val:.3f} ({batch_time.avg:.3f})  "
                      f"Speed {ips:.1f} img/s  "
                      f"Loss {losses.val:.4f} ({losses.avg:.4f})  "
                      f"Prec@1 {top1.val:.2f} ({top1.avg:.2f})  "
                      f"Prec@5 {top5.val:.2f} ({top5.avg:.2f})",
                      flush=True)
        if args.prof >= 0 and args.prof < steps:
            jax.profiler.stop_trace()
        if jax.process_index() == 0:  # rank-0 save, as the reference
            with open(args.checkpoint, "wb") as f:
                pickle.dump({"params": jax.device_get(params),
                             "batch_stats": jax.device_get(batch_stats),
                             "amp_state": jax.device_get(amp_state),
                             "epoch": epoch + 1}, f)
    ips = (args.batch_size * nproc / batch_time.avg) if batch_time.count \
        else 0.0
    print(f"DONE images/sec={ips:.1f} loss={losses.avg:.4f}")
    return losses.avg


if __name__ == "__main__":
    main()
