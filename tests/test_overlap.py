"""Overlap subsystem (apex_tpu.overlap, ISSUE 14) — the proof surface.

All on the conftest 8-device CPU mesh, no TPU window required:

* knob home (CLAUDE.md asymmetry): per-call raises on un-honorable
  requests; setter/env preferences fall back; bucket count resolves
  per-call > setter > env > dispatch table > built-in;
* jaxpr-level schedule proof: with ``APEX_OVERLAP_GRAD=bucketed`` the
  per-bucket dp collectives INTERLEAVE with remaining-backward compute
  (``costs.collective_schedule`` verdict), terminal with it off — and
  with every knob off the emitted programs are byte-identical to the
  pre-overlap pair;
* 20-step trajectory parity bucketed-vs-terminal on the dp mesh,
  plain (exact) and composed with the int8 + hierarchical collectives
  (tolerance band — per-bucket quantization boundaries differ);
* prefetch determinism / order / backpressure / error propagation;
* serving overlap: token-for-token parity vs the serial engine under
  admit/evict churn (prefix cache + sampling composed), lifecycle
  event order + the one-compile contract preserved, ``flush()``
  semantics, the spec-decode raise/fallback;
* check 10 (tools/check_bench_labels.overlap_problems) both
  directions, and the profile_overlap smoke CLI end-to-end (on the
  session-shared smoke compile cache — the PR 6 fast-tier rule:
  deeper cache sharing, not demotion).
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import dispatch
from apex_tpu import overlap as overlap_mod
from apex_tpu.overlap import bucketed as bucketed_mod
from apex_tpu.overlap import prefetch as prefetch_mod
from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    allreduce_gradients,
)
from apex_tpu.telemetry import costs
from apex_tpu.transformer.parallel_state import (
    PIPELINE_AXIS,
    TENSOR_AXIS,
)
from apex_tpu.transformer.testing import TransformerConfig
from apex_tpu.transformer.testing.minimal import (
    dp_axes_of,
    dp_axis_arg,
    gpt_train_step_fn,
    make_gpt_fns,
    toy_batch,
    training_collective_schedule,
    training_comm_bytes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_knobs(monkeypatch):
    for k in ("APEX_OVERLAP_GRAD", "APEX_OVERLAP_BUCKETS",
              "APEX_PREFETCH", "APEX_SERVE_OVERLAP", "APEX_DISPATCH",
              "APEX_DISPATCH_TABLE", "APEX_GRAD_COMPRESS",
              "APEX_HIER_ALLREDUCE", "APEX_SPEC_DECODE"):
        monkeypatch.delenv(k, raising=False)
    overlap_mod._reset_for_tests()
    dispatch._reset_for_tests()
    yield
    overlap_mod._reset_for_tests()
    dispatch._reset_for_tests()


def _jx(fn, *args):
    """Trace with a FRESH function object (jax trace caches key on
    identity; knob resolution is trace-time)."""
    return str(jax.make_jaxpr(lambda *a: fn(*a))(*args))


def _mesh(n, names=("dp",), shape=None):
    return Mesh(np.array(jax.devices()[:n]).reshape(shape or (n,)), names)


MINI_CFG = TransformerConfig(
    hidden_size=32, num_layers=2, num_attention_heads=4,
    vocab_size=64, max_position_embeddings=16,
    hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
    apply_query_key_layer_scaling=False)


# ------------------------------------------------------------- knobs

def test_grad_overlap_resolution(monkeypatch):
    with pytest.raises(ValueError, match="unknown grad-overlap"):
        overlap_mod.resolve_grad_overlap("greedy")
    with pytest.raises(ValueError, match="unknown grad-overlap"):
        overlap_mod.set_grad_overlap("greedy")
    assert overlap_mod.resolve_grad_overlap() == "off"
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    assert overlap_mod.resolve_grad_overlap() == "bucketed"
    # an unknown env value is a preference: warn once, stay off
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "sideways")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert overlap_mod.resolve_grad_overlap() == "off"
    assert any("sideways" in str(x.message) for x in w)
    # setter beats env; per-call beats setter
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    overlap_mod.set_grad_overlap("off")
    assert overlap_mod.resolve_grad_overlap() == "off"
    assert overlap_mod.resolve_grad_overlap("bucketed") == "bucketed"


def test_buckets_resolution_precedence(tmp_path, monkeypatch):
    for bad in (0, -1, True, 2.5):
        with pytest.raises(ValueError):
            overlap_mod.resolve_buckets(bad)
    assert overlap_mod.resolve_buckets() == overlap_mod.DEFAULT_BUCKETS
    # dispatch-table tier (op "overlap_buckets", keyed on the payload)
    table = tmp_path / "table.jsonl"
    entry = dispatch.make_entry("overlap_buckets", {"n": 1000},
                                "float32", "cpu", "8", "lg-x")
    table.write_text(json.dumps(entry) + "\n")
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(table))
    assert overlap_mod.resolve_buckets(nelems=1000) == 8
    # non-digit table choice degrades to the built-in default
    entry["choice"] = "many"
    table.write_text(json.dumps(entry) + "\n")
    dispatch._reset_for_tests()
    assert overlap_mod.resolve_buckets(nelems=1000) == \
        overlap_mod.DEFAULT_BUCKETS
    # env beats table, setter beats env, per-call beats setter
    entry["choice"] = "8"
    table.write_text(json.dumps(entry) + "\n")
    dispatch._reset_for_tests()
    monkeypatch.setenv("APEX_OVERLAP_BUCKETS", "6")
    assert overlap_mod.resolve_buckets(nelems=1000) == 6
    overlap_mod.set_overlap_buckets(5)
    assert overlap_mod.resolve_buckets(nelems=1000) == 5
    assert overlap_mod.resolve_buckets(3, nelems=1000) == 3
    with pytest.raises(ValueError):
        overlap_mod.set_overlap_buckets(-2)


def test_prefetch_resolution(monkeypatch):
    assert overlap_mod.resolve_prefetch() == 0
    monkeypatch.setenv("APEX_PREFETCH", "3")
    assert overlap_mod.resolve_prefetch() == 3
    monkeypatch.setenv("APEX_PREFETCH", "0")
    assert overlap_mod.resolve_prefetch() == 0
    monkeypatch.setenv("APEX_PREFETCH", "deep")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert overlap_mod.resolve_prefetch() == 0
    assert any("deep" in str(x.message) for x in w)
    assert overlap_mod.resolve_prefetch(2) == 2
    assert overlap_mod.resolve_prefetch(0) == 0
    for bad in (-1, True, 1.5):
        with pytest.raises(ValueError):
            overlap_mod.resolve_prefetch(bad)


def test_serve_overlap_resolution(monkeypatch):
    assert overlap_mod.resolve_serve_overlap() is False
    monkeypatch.setenv("APEX_SERVE_OVERLAP", "1")
    assert overlap_mod.resolve_serve_overlap() is True
    # preference falls back when speculation is engaged; a per-call
    # demand raises instead (the count-function contract)
    assert overlap_mod.resolve_serve_overlap(spec_k=3) is False
    with pytest.raises(ValueError, match="speculative"):
        overlap_mod.resolve_serve_overlap(True, spec_k=3)
    with pytest.raises(ValueError):
        overlap_mod.resolve_serve_overlap("yes")
    assert overlap_mod.resolve_serve_overlap(False, spec_k=3) is False


# ----------------------------------------------------- bucketed core

def test_bucket_partition_properties():
    leaves = [jnp.zeros((s,)) for s in (100, 1, 1, 50, 200, 3, 7)]
    for nb in (1, 2, 3, len(leaves), len(leaves) + 5):
        bounds = bucketed_mod._partition(leaves, nb)
        # contiguous, covering, ordered
        assert bounds[0][0] == 0 and bounds[-1][1] == len(leaves)
        for (a, b), (c, d) in zip(bounds, bounds[1:]):
            assert b == c and a < b
        assert len(bounds) == min(nb, len(leaves))


def test_bucketed_value_and_grad_off_is_byte_identical():
    """Knobs off, the helper emits the EXACT historical program —
    jax.value_and_grad + one terminal allreduce_gradients (the ISSUE
    14 byte-identity acceptance criterion)."""
    mesh = _mesh(4)
    params = {"a": jnp.ones((8, 4), jnp.float32),
              "b": jnp.ones((4,), jnp.float32)}
    x = jnp.ones((2, 8), jnp.float32)

    def loss_fn(p, x):
        return jnp.sum(jnp.tanh(x @ p["a"]) + p["b"])

    def manual(p, x):
        loss, grads = jax.value_and_grad(loss_fn)(p, x)
        return loss, allreduce_gradients(grads, "dp")

    helper = bucketed_mod.bucketed_value_and_grad(loss_fn, "dp")
    sm = lambda f: shard_map(f, mesh=mesh, in_specs=(P(), P()),
                             out_specs=(P(), P()), check_vma=False)
    off_jx = _jx(sm(helper), params, x)
    assert off_jx == _jx(sm(manual), params, x)
    bucketed = bucketed_mod.bucketed_value_and_grad(
        loss_fn, "dp", overlap="bucketed", buckets=2)
    assert _jx(sm(bucketed), params, x) != off_jx


def test_bucketed_grads_match_and_interleave():
    """The core schedule claim on a layered model: bucketed grads ==
    terminal grads numerically, and the jaxpr-order verdict flips
    terminal -> interleaved (later-layer buckets reduce first)."""
    mesh = _mesh(8)
    ws = {f"layer_{i}": jnp.eye(8) * 0.3 + 0.01 for i in range(4)}
    x = jnp.arange(16, dtype=jnp.float32).reshape(2, 8) / 16.0

    def loss_fn(ws, x):
        h = x
        for i in range(4):
            h = jnp.tanh(h @ ws[f"layer_{i}"])
        return jnp.sum(h)

    def run(fn):
        g = shard_map(fn, mesh=mesh, in_specs=(P(), P()),
                      out_specs=(P(), P()), check_vma=False)
        verdict = costs.collective_schedule(
            jax.make_jaxpr(g)(ws, x), axes=("dp",))
        loss, grads = jax.jit(g)(ws, x)
        return verdict, np.asarray(loss), grads

    v_t, l_t, g_t = run(bucketed_mod.bucketed_value_and_grad(
        loss_fn, "dp"))
    v_b, l_b, g_b = run(bucketed_mod.bucketed_value_and_grad(
        loss_fn, "dp", overlap="bucketed", buckets=4))
    assert v_t["verdict"] == "terminal"
    assert v_b["verdict"] == "interleaved"
    assert v_b["compute_after_first_collective"] > 0
    assert np.allclose(l_t, l_b)
    for k in g_t:
        assert np.allclose(np.asarray(g_t[k]), np.asarray(g_b[k]),
                           rtol=1e-6, atol=1e-6), k


def test_minimal_step_schedule_verdicts_and_comm(monkeypatch):
    """The committed acceptance proof: the minimal-GPT dp train step's
    per-bucket collectives interleave with remaining-backward compute
    under APEX_OVERLAP_GRAD=bucketed and stay terminal off — judged on
    the dp axes (costs.collective_schedule) — including composed with
    int8 + the hierarchical dp pair; the bucketed per-microbatch
    reduction's M-times dp payload is counted honestly."""
    devs = jax.devices()[:8]
    term = training_collective_schedule(devs, MINI_CFG, (1, 8, 1),
                                        num_microbatches=2)
    buck = training_collective_schedule(devs, MINI_CFG, (1, 8, 1),
                                        num_microbatches=2,
                                        overlap_grad="bucketed")
    assert term["verdict"] == "terminal"
    assert buck["verdict"] == "interleaved"
    assert buck["compute_after_first_collective"] > 0
    # ...the env preference selects the same program as the per-call
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    via_env = training_collective_schedule(devs, MINI_CFG, (1, 8, 1),
                                           num_microbatches=2)
    assert via_env["verdict"] == "interleaved"
    monkeypatch.delenv("APEX_OVERLAP_GRAD")
    # composed with the PR 8 collectives over a factored dp pair
    both = training_collective_schedule(
        devs, MINI_CFG, (1, (2, 4), 1), num_microbatches=2,
        overlap_grad="bucketed", compress="int8", hierarchical=True)
    assert both["verdict"] == "interleaved"
    # hook-per-backward semantics: M microbatches -> M reductions
    c_t = training_comm_bytes(devs, MINI_CFG, (1, 8, 1),
                              num_microbatches=2)
    c_b = training_comm_bytes(devs, MINI_CFG, (1, 8, 1),
                              num_microbatches=2,
                              overlap_grad="bucketed")
    assert c_b["dp"] > 1.9 * c_t["dp"]


def test_minimal_step_off_knob_leaves_jaxpr_unchanged(monkeypatch):
    """APEX_OVERLAP_GRAD=off (and unset) emit byte-identical minimal
    train-step programs — the knob's disabled mode costs nothing.
    (The model's pre-existing custom_vjp equations print live object
    ADDRESSES in their params, so the comparison scrubs `0x...` — the
    program structure and every literal must still match byte for
    byte.)"""
    import re

    devs = jax.devices()[:8]
    from apex_tpu.transformer.testing.minimal import \
        _traced_training_jaxpr

    def scrub(jx):
        return re.sub(r"0x[0-9a-f]+", "0xADDR", str(jx))

    default, _, _, _ = _traced_training_jaxpr(devs, MINI_CFG, (1, 8, 1),
                                              num_microbatches=2)
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "off")
    explicit_off, _, _, _ = _traced_training_jaxpr(
        devs, MINI_CFG, (1, 8, 1), num_microbatches=2)
    assert scrub(default) == scrub(explicit_off)


def test_pp_pipeline_demand_raises_preference_falls_back(monkeypatch):
    with pytest.raises(ValueError, match="pp=2"):
        gpt_train_step_fn(MINI_CFG, 2, 2, overlap_grad="bucketed")
    # the env preference falls back silently (still builds)
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    step, _, _ = gpt_train_step_fn(
        TransformerConfig(
            hidden_size=32, num_layers=4, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
            apply_query_key_layer_scaling=False), 2, 2)
    assert step is not None


def test_ddp_ctor_overlap_knobs():
    with pytest.raises(ValueError, match="unknown grad-overlap"):
        DistributedDataParallel(overlap_grad="greedy")
    with pytest.raises(ValueError):
        DistributedDataParallel(overlap_buckets=0)
    mesh = _mesh(4)
    params = {"w": jnp.ones((6, 2), jnp.float32)}
    x = jnp.ones((3, 6), jnp.float32)

    def loss_fn(p, x):
        return jnp.sum(x @ p["w"])

    ddp = DistributedDataParallel(axis_name="dp")

    def manual(p, x):
        loss, grads = jax.value_and_grad(loss_fn)(p, x)
        return loss, allreduce_gradients(grads, "dp")

    sm = lambda f: shard_map(f, mesh=mesh, in_specs=(P(), P()),
                             out_specs=(P(), P()), check_vma=False)
    assert _jx(sm(ddp.value_and_grad(loss_fn)), params, x) \
        == _jx(sm(manual), params, x)


def _run_traj(overlap, steps, compress=None, hier=None, dp_decl=8):
    devs = jax.devices()[:8]
    dp_size, dp_names, dp_sizes = dp_axes_of(dp_decl)
    mesh = Mesh(np.asarray(devs).reshape(1, *dp_sizes, 1),
                (PIPELINE_AXIS, *dp_names, TENSOR_AXIS))
    dp_axes = dp_axis_arg(dp_names)
    _, init_params = make_gpt_fns(MINI_CFG, 1)
    step, tx, scaler = gpt_train_step_fn(
        MINI_CFG, 1, 2, dp_axes=dp_axes, compress=compress,
        hierarchical=hier, overlap_grad=overlap)
    batch = toy_batch(MINI_CFG.vocab_size, 2, 2 * dp_size, 16)
    spec = P(None, dp_axes)

    def whole(batch):
        params = init_params(jax.random.PRNGKey(0),
                             {k: v[0] for k, v in batch.items()})
        o, ss = tx.init(params), scaler.init()

        def body(carry, _):
            p, o, ss = carry
            p, o, ss, loss = step(p, o, ss, batch)[:4]
            return (p, o, ss), lax.pmean(loss, dp_axes)

        _, losses = lax.scan(body, (params, o, ss), jnp.arange(steps))
        return losses

    f = jax.jit(shard_map(whole, mesh=mesh,
                          in_specs=({"ids": spec, "labels": spec},),
                          out_specs=P(), check_vma=False))
    return np.asarray(jax.block_until_ready(f(batch)))


def test_trajectory_parity_bucketed_vs_terminal_20_steps():
    """Bucketed-vs-terminal over 20 steps on the 8-device dp mesh:
    the plain path is EXACT (per-microbatch psum-then-accumulate is
    the same float program as accumulate-then-psum here); composed
    with int8 + the hierarchical dp pair the trajectories track
    inside a tolerance band (per-bucket quantization block boundaries
    differ from the one-flat-buffer terminal path)."""
    t = _run_traj("off", 20)
    b = _run_traj("bucketed", 20)
    assert np.allclose(t, b, rtol=0, atol=0), np.abs(t - b).max()
    tq = _run_traj("off", 20, compress="int8", hier=True,
                   dp_decl=(2, 4))
    bq = _run_traj("bucketed", 20, compress="int8", hier=True,
                   dp_decl=(2, 4))
    assert np.all(np.isfinite(tq)) and np.all(np.isfinite(bq))
    assert np.allclose(tq, bq, rtol=2e-3, atol=2e-3), \
        np.abs(tq - bq).max()


# ----------------------------------------------------- costs helpers

def test_collective_schedule_axes_and_degradation():
    mesh = _mesh(8, names=("dp",))

    def with_fwd_psum(w, x):
        # a forward collective over another axis must not drown the
        # dp grad verdict when the axes filter names dp only
        h = jnp.tanh(x @ w)
        loss = jnp.sum(h)
        g = jax.grad(lambda w: jnp.sum(jnp.tanh(x @ w)))(w)
        return loss, lax.psum(g, "dp")

    jx = jax.make_jaxpr(shard_map(
        with_fwd_psum, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(), P()), check_vma=False))(
            jnp.ones((4, 4)), jnp.ones((2, 4)))
    assert costs.collective_schedule(jx, axes=("dp",))["verdict"] \
        == "terminal"
    # no collectives / unwalkable input degrade, never raise
    none = costs.collective_schedule(
        jax.make_jaxpr(lambda x: x * 2)(jnp.ones(3)))
    assert none["verdict"] == "no-collectives"
    assert costs.collective_schedule(object())["verdict"] \
        == "no-collectives"


def test_comm_ms_from_axis_bytes():
    assert costs.comm_ms_from_axis_bytes(None, "tpu") is None
    assert costs.comm_ms_from_axis_bytes({}, "tpu") == 0.0
    assert costs.comm_ms_from_axis_bytes({"dp": 1}, "cpu") is None
    ms = costs.comm_ms_from_axis_bytes(
        {"dp": costs.V5E_ICI_BYTES_PER_S_ENVELOPE}, "tpu")
    assert abs(ms - 1e3) < 1e-6


def test_capture_overlap_bound_passthrough():
    block = costs.capture(steps=2, platform="tpu", host_ms=0.5,
                          comm_ms=0.25)
    ob = block["overlap_bound"]
    assert ob["host_ms"] == 0.5 and ob["comm_ms"] == 0.25
    assert ob["comm_host_ms"] == 0.75
    assert not costs.validate(block)
    from apex_tpu.telemetry import ledger
    rec = ledger.make_record("t", "cpu", None, None,
                             extra={"cost": block})
    assert not ledger.validate_record(rec)


# ----------------------------------------------------------- prefetch

def test_prefetch_order_and_determinism(monkeypatch):
    batches = [np.full((4,), i, np.int32) for i in range(7)]
    want = [list(b) for b in batches]
    for depth in (0, 1, 2, 5):
        got = [list(np.asarray(x))
               for x in prefetch_mod.prefetch(iter(batches),
                                              depth=depth)]
        assert got == want, depth
    # env resolution drives the same path
    monkeypatch.setenv("APEX_PREFETCH", "2")
    got = [list(np.asarray(x)) for x in
           prefetch_mod.prefetch(iter(batches))]
    assert got == want


def test_prefetch_backpressure_bounded():
    produced = []

    def gen():
        for i in range(8):
            produced.append(i)
            yield np.full((2,), i, np.int32)

    it = prefetch_mod.prefetch(gen(), depth=2)
    first = next(it)
    deadline = time.time() + 5.0
    # producer may run at most depth ahead of the consumer (+1 for
    # the item blocked in q.put)
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)
    assert len(produced) <= 4, produced  # 1 consumed + 2 queued + 1 blocked
    rest = [int(np.asarray(x)[0]) for x in it]
    assert [int(np.asarray(first)[0])] + rest == list(range(8))


def test_prefetch_error_propagates_and_early_close():
    def bad():
        yield np.zeros((2,), np.int32)
        raise RuntimeError("decode exploded")

    it = prefetch_mod.prefetch(bad(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="decode exploded"):
        next(it)
    # a consumer that stops early must not leave a blocked producer
    n_threads = threading.active_count()
    it2 = prefetch_mod.prefetch(
        (np.full((2,), i, np.int32) for i in range(100)), depth=1)
    next(it2)
    it2.close()
    deadline = time.time() + 5.0
    while threading.active_count() > n_threads and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= n_threads


def test_staging_seconds_measures():
    s = prefetch_mod.staging_seconds(np.zeros((64, 64), np.float32),
                                     reps=2)
    assert isinstance(s, float) and s > 0


# ------------------------------------------------------------ serving

SERVE_CFG = TransformerConfig(
    hidden_size=64, num_layers=2, num_attention_heads=4,
    vocab_size=128, max_position_embeddings=64,
    hidden_dropout=0.0, attention_dropout=0.0,
    apply_query_key_layer_scaling=False, bf16=True)


@pytest.fixture(scope="module")
def serve_params():
    from apex_tpu.serving import model as smodel

    return smodel.init_gpt_params(SERVE_CFG, 0)


def _clone(reqs):
    from apex_tpu.serving import Request

    return [Request(rid=r.rid, prompt=list(r.prompt),
                    max_new_tokens=r.max_new_tokens, arrival=r.arrival)
            for r in reqs]


def test_serve_overlap_token_parity_and_lifecycle(serve_params):
    from apex_tpu.serving import ServingEngine, lifecycle
    from apex_tpu.serving.scheduler import synthetic_trace

    reqs, _ = synthetic_trace(seed=3, n_requests=10, vocab=128,
                              prompt_lo=4, prompt_hi=16, new_lo=2,
                              new_hi=12, mean_interarrival=0.7)
    lifecycle.enable()
    try:
        serial = ServingEngine(SERVE_CFG, params=serve_params,
                               num_slots=3, page_size=8, num_pages=48,
                               max_seq=64, prefill_len=32,
                               overlap=False)
        done_s = serial.run_trace(_clone(reqs))
        ov = ServingEngine(SERVE_CFG, params=serve_params, num_slots=3,
                           page_size=8, num_pages=48, max_seq=64,
                           prefill_len=32, overlap=True)
        done_o = ov.run_trace(_clone(reqs))
    finally:
        lifecycle.reset_enabled()
    assert ov.overlap and not serial.overlap
    s = {r.rid: r.out_tokens for r in done_s}
    o = {r.rid: r.out_tokens for r in done_o}
    assert s == o
    assert None not in [t for ts in o.values() for t in ts]
    assert ov.tick == serial.tick  # same per-round schedule
    assert ov.decode_cache_size() == 1
    assert not ov.events.validate_order()
    for r in done_o:
        got = [e["event"] for e in ov.events.request_events(r.rid)]
        assert got == list(lifecycle.CORE_EVENTS), (r.rid, got)
    ov.allocator.check_invariants()


def test_serve_overlap_composes_with_prefix_and_sampling(serve_params):
    from apex_tpu.serving import ServingEngine
    from apex_tpu.serving.scheduler import synthetic_trace

    reqs, _ = synthetic_trace(seed=5, n_requests=8, vocab=128,
                              prompt_lo=4, prompt_hi=14, new_lo=2,
                              new_hi=10, mean_interarrival=0.6,
                              system_prompt=[7] * 9)
    a = ServingEngine(SERVE_CFG, params=serve_params, num_slots=3,
                      page_size=8, num_pages=48, max_seq=64,
                      prefill_len=32, prefix_cache=True, sampling=True,
                      overlap=False)
    da = a.run_trace(_clone(reqs))
    b = ServingEngine(SERVE_CFG, params=serve_params, num_slots=3,
                      page_size=8, num_pages=48, max_seq=64,
                      prefill_len=32, prefix_cache=True, sampling=True,
                      overlap=True)
    db = b.run_trace(_clone(reqs))
    assert {r.rid: r.out_tokens for r in da} \
        == {r.rid: r.out_tokens for r in db}
    assert b.generation_stats()["prefix_hit_rate"] > 0
    b.allocator.check_invariants()
    b.prefix.check_invariants()
    assert b.decode_cache_size() == 1 and b.prefill_cache_size() == 1


def test_serve_overlap_flush_fills_placeholders(serve_params):
    from apex_tpu.serving import Request, ServingEngine

    eng = ServingEngine(SERVE_CFG, params=serve_params, num_slots=2,
                        page_size=8, num_pages=32, max_seq=64,
                        prefill_len=32, overlap=True)
    req = Request(rid=0, prompt=[3, 1, 4, 1, 5], max_new_tokens=4)
    eng.submit(req)
    eng.step()   # admit + prefill + dispatch decode (in flight)
    assert req.out_tokens[0] is not None  # prefill's token is real
    eng.step()   # round 2: resolves round 1, dispatches round 2
    assert req.out_tokens[1] is not None
    assert req.out_tokens[-1] is None     # round 2 still in flight
    eng.flush()
    assert None not in req.out_tokens
    eng.flush()  # idempotent
    # done() is count-based: stepping to completion then flushing
    while not req.done():
        eng.step()
    eng.flush()
    assert len(req.out_tokens) == 4
    assert None not in req.out_tokens


def test_serve_overlap_spec_raises_env_falls_back(serve_params, monkeypatch):
    from apex_tpu.serving import ServingEngine

    # two per-call DEMANDS conflict: no honorable order, raise
    with pytest.raises(ValueError, match="speculative"):
        ServingEngine(SERVE_CFG, params=serve_params, num_slots=2,
                      page_size=8, num_pages=32, max_seq=64,
                      prefill_len=32, spec_decode=3, overlap=True)
    # overlap env PREFERENCE vs spec demand: overlap falls back
    monkeypatch.setenv("APEX_SERVE_OVERLAP", "1")
    eng = ServingEngine(SERVE_CFG, params=serve_params, num_slots=2,
                        page_size=8, num_pages=32, max_seq=64,
                        prefill_len=32, spec_decode=3)
    assert eng.overlap is False  # preference fell back, spec kept
    assert eng.spec_k == 3
    # overlap DEMAND vs spec env preference: the preference falls back
    # (speculation is token-identical to plain decode, so the demand
    # is honorable), overlap engages
    monkeypatch.delenv("APEX_SERVE_OVERLAP")
    monkeypatch.setenv("APEX_SPEC_DECODE", "3")
    eng2 = ServingEngine(SERVE_CFG, params=serve_params, num_slots=2,
                         page_size=8, num_pages=32, max_seq=64,
                         prefill_len=32, overlap=True)
    assert eng2.overlap is True and eng2.spec_k == 0


# ------------------------------------------------- check 10 + the CLI

def _cbl():
    tool = os.path.join(REPO, "tools", "check_bench_labels.py")
    spec = importlib.util.spec_from_file_location("cbl_overlap", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check10_overlap_pin_match_both_directions():
    cbl = _cbl()
    ob_cost = {"overlap_bound": {"host_ms": 1.0, "comm_ms": None}}

    def rec(knobs, claim, cost=ob_cost):
        r = {"id": "lg-t", "knobs": knobs, "cost": cost}
        if claim is not None:
            r["overlap"] = claim
        return r

    claim = {"grad": "bucketed", "buckets": 4, "prefetch": "2",
             "serve": "1"}
    pins = {"APEX_OVERLAP_GRAD": "bucketed", "APEX_OVERLAP_BUCKETS": "4",
            "APEX_PREFETCH": "2", "APEX_SERVE_OVERLAP": "1"}
    assert cbl.overlap_problems(rec(pins, claim), "lg-t") == []
    # claimed but unpinned
    probs = cbl.overlap_problems(rec({}, claim), "lg-t")
    assert len(probs) == 4 and all("does not pin" in p for p in probs)
    # claimed one thing, pinned another
    drift = dict(pins, APEX_OVERLAP_GRAD="off")
    assert any("different schedules" in p for p in
               cbl.overlap_problems(rec(drift, claim), "lg-t"))
    # reverse direction: engaged pin, silent claim — including the
    # bucket count, which has no off value (any pin is engaged)
    probs = cbl.overlap_problems(
        rec({"APEX_PREFETCH": "2"}, {"grad": "off"}), "lg-t")
    assert any("omits" in p for p in probs)
    probs = cbl.overlap_problems(
        rec({"APEX_OVERLAP_BUCKETS": "8"}, {"grad": "off"}), "lg-t")
    assert any("omits 'buckets'" in p for p in probs)
    # legacy rows (no claim block) are skipped; so are rows whose
    # overlap_bound carries no measured host/comm side
    assert cbl.overlap_problems(rec({}, None), "lg-t") == []
    assert cbl.overlap_problems(
        rec({}, claim, cost={"overlap_bound": {"host_ms": None,
                                               "comm_ms": None}}),
        "lg-t") == []
    # span-level cost blocks trigger the teeth too
    span_rec = {"id": "lg-t", "knobs": {}, "overlap": claim,
                "spans": [{"extra": {"cost": ob_cost}}]}
    assert cbl.overlap_problems(span_rec, "lg-t")


def test_profile_overlap_smoke_cli(tmp_path, shared_smoke_cache_dir):
    """The harness contract end-to-end at smoke shapes, on the
    session-shared smoke compile cache (the PR 6 fast-tier rule):
    one run, one validated ledger record carrying the overlap claim,
    the collective-schedule verdict, and a check-10-clean pin set."""
    ledger_path = tmp_path / "ledger.jsonl"
    env = dict(os.environ, APEX_BENCH_SMOKE="1",
               APEX_TELEMETRY_LEDGER=str(ledger_path),
               APEX_COMPILE_CACHE="1",
               APEX_COMPILE_CACHE_DIR=shared_smoke_cache_dir,
               APEX_OVERLAP_GRAD="bucketed", APEX_PREFETCH="1",
               APEX_SERVE_OVERLAP="1")
    env.pop("APEX_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "profile_overlap.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "collective schedule          interleaved" in proc.stdout
    from apex_tpu.telemetry import ledger as ledger_mod

    recs = ledger_mod.read_ledger(str(ledger_path))
    assert len(recs) == 1
    rec = recs[0]
    assert not ledger_mod.validate_record(rec)
    assert rec["overlap"]["grad"] == "bucketed"
    assert rec["collective_schedule"]["verdict"] == "interleaved"
    assert rec["knobs"]["APEX_OVERLAP_GRAD"] == "bucketed"
    assert _cbl().overlap_problems(rec, rec["id"]) == []
