"""tools/apexlint — the AST-level invariant gate (ISSUE 12).

Three surfaces under test:

1. **The committed tree is clean** — the tier-1 acceptance: zero
   findings over the real repo, every surviving pragma reasoned AND
   load-bearing (hits > 0), and the APX003 registry exactness holds.
2. **Each rule detects / passes / suppresses** — fixture twins per
   rule (``tests/fixtures/apexlint/``: violation, clean, pragma'd)
   run against a scaffolded mini-repo, plus pragma accounting
   (APX000: reasonless and unknown-rule pragmas are findings;
   unused pragmas are reported, never failures).
3. **The gates** — the CLI rc convention (0 clean / 1 findings /
   2 crash-as-finding), the ``--json`` machine line, and both
   collection shells refusing to arm on a dirty lint
   (``APEX_APEXLINT_ROOT`` fixture redirect — the APEX_PROBE_*
   isolation pattern).

No jax needed anywhere here: the linter is stdlib+AST by design.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.apexlint import run  # noqa: E402
from tools.apexlint import config as lint_config  # noqa: E402
from tools.apexlint.cli import main as lint_main  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "apexlint")

# ---------------------------------------------------------------------------
# mini-repo scaffold: the smallest tree that is APX003-clean, so each
# rule test adds exactly its fixture and asserts exactly its findings
# ---------------------------------------------------------------------------

# the mini ledger carries the raw reads the real allowlist designates
# for this path (else those entries would read as stale over the
# fixture tree); both knobs are infra-prefix-covered for APX003
SCAFFOLD_LEDGER = (
    "import os\n\n"
    'INFRA_KNOB_PREFIXES = ("APEX_INFRA_", "APEX_TELEMETRY_LEDGER",\n'
    '                       "APEX_FAULT_PLAN")\n\n\n'
    "def ledger_path():\n"
    "    return os.environ.get(\"APEX_TELEMETRY_LEDGER\")\n\n\n"
    "def fault_stamp():\n"
    "    return os.environ.get(\"APEX_FAULT_PLAN\")\n")
SCAFFOLD_API = """# mini API
<!-- apexlint: knob-table begin -->
| Env | Effect |
|---|---|
| `APEX_DOCED=1` | documented fixture knob |
<!-- apexlint: knob-table end -->
"""
SCAFFOLD_READER = (
    "from apex_tpu.dispatch.tiles import env_flag, env_int\n\n\n"
    "def f():\n"
    "    return env_flag(\"APEX_DOCED\") or env_int(\"APEX_INFRA_X\")\n")


def make_tree(tmp_path, files=None, api_md=SCAFFOLD_API):
    """Build a scaffolded mini-repo; ``files`` maps repo-relative
    paths to content or to a fixture basename to copy."""
    base = {
        "apex_tpu/telemetry/ledger.py": SCAFFOLD_LEDGER,
        "apex_tpu/reader.py": SCAFFOLD_READER,
        "docs/API.md": api_md,
    }
    base.update(files or {})
    for rel, content in base.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        src = os.path.join(FIXTURES, content)
        if "\n" not in content and os.path.exists(src):
            shutil.copy(src, p)
        else:
            p.write_text(content)
    return str(tmp_path)


def rule_findings(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. the committed tree
# ---------------------------------------------------------------------------

def test_repo_is_clean():
    """THE acceptance gate: zero findings over the committed tree —
    APX001-006 hold, the knob registry is exact, and no reasonless
    pragma survives (a reasonless pragma is an APX000 finding)."""
    report = run(REPO)
    assert report.ok, "\n" + report.render()


def test_repo_pragmas_are_reasoned_and_load_bearing():
    """Every surviving pragma carries a reason AND suppresses at least
    one live finding — a pragma that eats nothing is rot the report
    names (unused), and this tree must carry none."""
    report = run(REPO)
    assert report.pragmas, "the tree documents its suppressions inline"
    for p in report.pragmas:
        assert p.reason and len(p.reason) > 10, (p.path, p.line)
        assert p.hits > 0, f"unused pragma {p.path}:{p.line}"


def test_config_paths_exist_in_repo():
    """Deletion rot: every DESIGNATED_READERS / STDLIB_ONLY_CLAIMED
    path must exist (the rules skip absent paths so fixture trees can
    carry subsets — this test is where a stale path fails)."""
    for path, _spec, reason in lint_config.DESIGNATED_READERS:
        assert os.path.exists(os.path.join(REPO, path)), path
        assert reason.strip(), path
    for spec in lint_config.STDLIB_ONLY_CLAIMED:
        assert os.path.exists(os.path.join(REPO, spec.rstrip("/"))), spec


# ---------------------------------------------------------------------------
# 2. per-rule fixtures
# ---------------------------------------------------------------------------

def test_apx001_violation_clean_pragma(tmp_path):
    root = make_tree(tmp_path, {
        "apex_tpu/v.py": "apx001_violation.py",
        "apex_tpu/c.py": "apx001_clean.py",
        "apex_tpu/p.py": "apx001_pragma.py",
    })
    report = run(root, rules=["APX001"])
    found = rule_findings(report, "APX001")
    # module-level read, the default-argument read, and the
    # module-level env_flag helper call — never the clean twin's
    # function-body reads
    assert {f.path for f in found} == {"apex_tpu/v.py"}
    assert len(found) == 3
    assert any("APEX_FIX_HELPER" in f.msg for f in found)
    assert [f for f in report.suppressed if f.path == "apex_tpu/p.py"]


def test_apx002_violation_clean_pragma(tmp_path):
    root = make_tree(tmp_path, {
        "apex_tpu/v.py": "apx002_violation.py",
        "apex_tpu/c.py": "apx002_clean.py",
        "apex_tpu/p.py": "apx002_pragma.py",
    })
    report = run(root, rules=["APX002"])
    found = rule_findings(report, "APX002")
    assert {f.path for f in found} == {"apex_tpu/v.py"}
    # .get, the module-constant subscript, and the `in` presence test
    assert len(found) == 3
    assert any("APEX_FIX_CONST" in f.msg for f in found), \
        "NAME = 'APEX_FIX_CONST' must resolve through the constant map"
    assert [f for f in report.suppressed if f.path == "apex_tpu/p.py"]


def test_apx002_designated_reader_allows(tmp_path):
    # drop the violation at a path the real allowlist designates for
    # this knob: apex_tpu/telemetry/costs.py owns APEX_COST_ANALYSIS
    root = make_tree(tmp_path, {
        "apex_tpu/telemetry/costs.py":
            "import os\n\n\ndef f():\n"
            "    return os.environ.get(\"APEX_COST_ANALYSIS\")\n",
    })
    report = run(root, rules=["APX002"])
    assert not rule_findings(report, "APX002"), report.render()


def test_apx003_exactness_both_directions(tmp_path):
    api = SCAFFOLD_API.replace(
        "| `APEX_DOCED=1` | documented fixture knob |",
        "| `APEX_DOCED=1` | documented fixture knob |\n"
        "| `APEX_NEVER_READ` | a no-op row |")
    root = make_tree(tmp_path, {
        "apex_tpu/u.py":
            "from apex_tpu.dispatch.tiles import env_flag\n\n\n"
            "def f():\n"
            "    return env_flag(\"APEX_UNDOCUMENTED\")\n",
    }, api_md=api)
    report = run(root, rules=["APX003"])
    msgs = [f.msg for f in rule_findings(report, "APX003")]
    assert any("APEX_UNDOCUMENTED" in m and "absent from" in m
               for m in msgs), msgs
    assert any("APEX_NEVER_READ" in m and "never read" in m
               for m in msgs), msgs
    assert len(msgs) == 2


def test_apx003_infra_prefix_coverage_and_staleness(tmp_path):
    # APEX_INFRA_X is read but undocumented — covered by the prefix, no
    # finding; a prefix nothing matches is stale
    root = make_tree(tmp_path, files={
        "apex_tpu/telemetry/ledger.py":
            'INFRA_KNOB_PREFIXES = ("APEX_INFRA_", "APEX_GONE_")\n'})
    report = run(root, rules=["APX003"])
    msgs = [f.msg for f in rule_findings(report, "APX003")]
    assert len(msgs) == 1 and "APEX_GONE_" in msgs[0], msgs


def test_apx003_counts_shell_uses(tmp_path):
    api = SCAFFOLD_API.replace(
        "| `APEX_DOCED=1` | documented fixture knob |",
        "| `APEX_DOCED=1` | documented fixture knob |\n"
        "| `APEX_SHELL_ONLY=1` | read by the collection shell |")
    root = make_tree(tmp_path, {
        "benchmarks/run_all_tpu.sh":
            '#!/bin/bash\nif [ -n "${APEX_SHELL_ONLY:-}" ]; then echo y; fi\n',
    }, api_md=api)
    report = run(root, rules=["APX003"])
    assert not rule_findings(report, "APX003"), report.render()


def test_apx003_missing_markers_is_a_finding(tmp_path):
    root = make_tree(tmp_path, api_md="# no markers here\n")
    report = run(root, rules=["APX003"])
    assert any("markers missing" in f.msg
               for f in rule_findings(report, "APX003"))


def test_apx004_violation_clean_pragma(tmp_path):
    root = make_tree(tmp_path, {
        "benchmarks/v.py": "apx004_violation.py",
        "benchmarks/c.py": "apx004_clean.py",
        "benchmarks/p.py": "apx004_pragma.py",
        "benchmarks/pf.py": "apx004_pragma_file.py",
    })
    report = run(root, rules=["APX004"])
    found = rule_findings(report, "APX004")
    # time.time, the from-imported perf_counter, block_until_ready
    assert {f.path for f in found} == {"benchmarks/v.py"}
    assert len(found) == 3
    sup = {f.path for f in report.suppressed}
    assert {"benchmarks/p.py", "benchmarks/pf.py"} <= sup
    # the file-level pragma ate BOTH of pf.py's calls
    assert sum(f.path == "benchmarks/pf.py"
               for f in report.suppressed) == 2


def test_apx004_ignores_package_and_tools(tmp_path):
    root = make_tree(tmp_path, {
        "apex_tpu/t.py": "apx004_violation.py",
    })
    report = run(root, rules=["APX004"])
    assert not rule_findings(report, "APX004"), \
        "APX004 scopes benchmarks/ (tracing.py IS the implementation)"


@pytest.fixture()
def ref_tree(tmp_path_factory):
    ref = tmp_path_factory.mktemp("reference")
    (ref / "pkg").mkdir()
    (ref / "pkg" / "ok.py").write_text("\n".join(
        f"# line {i}" for i in range(1, 11)) + "\n")
    (ref / "pkg" / "sub").mkdir()
    (ref / "pkg" / "sub" / "deep.py").write_text("a = 1\nb = 2\nc = 3\nd = 4\n")
    return str(ref)


def test_apx005_violation_clean_pragma(tmp_path, ref_tree):
    root = make_tree(tmp_path, {
        "apex_tpu/v.py": "apx005_violation.py",
        "apex_tpu/c.py": "apx005_clean.py",
        "apex_tpu/p.py": "apx005_pragma.py",
    })
    report = run(root, rules=["APX005"], reference_root=ref_tree)
    found = rule_findings(report, "APX005")
    assert {f.path for f in found} == {"apex_tpu/v.py"}
    msgs = " ".join(f.msg for f in found)
    assert "does not resolve" in msgs and "out of range" in msgs
    assert len(found) == 2
    assert [f for f in report.suppressed if f.path == "apex_tpu/p.py"]


def test_apx005_skips_without_reference_tree(tmp_path):
    root = make_tree(tmp_path, {"apex_tpu/v.py": "apx005_violation.py"})
    report = run(root, rules=["APX005"],
                 reference_root=str(tmp_path / "nowhere"))
    assert not rule_findings(report, "APX005")
    assert any("APX005 skipped" in n for n in report.notes)


def test_apx006_direct_transitive_clean(tmp_path):
    # fixtures land AT claimed paths (config.STDLIB_ONLY_CLAIMED)
    root = make_tree(tmp_path, {
        "apex_tpu/serving/scheduler.py": "apx006_violation.py",
        "apex_tpu/serving/lifecycle.py": "apx006_transitive.py",
        "apex_tpu/helper_mod.py": "apx006_helper_jax.py",
        "apex_tpu/dispatch/tiles.py": "apx006_clean.py",
    })
    report = run(root, rules=["APX006"])
    found = rule_findings(report, "APX006")
    by_path = {f.path: f.msg for f in found}
    assert "apex_tpu/serving/scheduler.py" in by_path
    assert "numpy" in by_path["apex_tpu/serving/scheduler.py"]
    # the transitive chain is named end-to-end
    assert "apex_tpu/serving/lifecycle.py" in by_path
    assert "helper_mod" in by_path["apex_tpu/serving/lifecycle.py"]
    assert "apex_tpu/dispatch/tiles.py" not in by_path, \
        "function-level jax import is the sanctioned lazy pattern"
    assert len(found) == 2


def test_apx006_resolves_relative_imports(tmp_path):
    """`from .helper_rel import x` at module level must be walked like
    its absolute spelling — the silent false-negative a relative
    re-spelling of the scheduler's kv_cache import would open."""
    root = make_tree(tmp_path, {
        "apex_tpu/serving/scheduler.py": "apx006_relative.py",
        "apex_tpu/serving/helper_rel.py": "apx006_helper_jax.py",
    })
    report = run(root, rules=["APX006"])
    found = rule_findings(report, "APX006")
    assert len(found) == 1 and "helper_rel" in found[0].msg, \
        report.render()


def test_apx003_shell_comment_mention_is_not_a_use(tmp_path):
    api = SCAFFOLD_API.replace(
        "| `APEX_DOCED=1` | documented fixture knob |",
        "| `APEX_DOCED=1` | documented fixture knob |\n"
        "| `APEX_COMMENTED` | named only in a shell comment |")
    root = make_tree(tmp_path, {
        "benchmarks/run_all_tpu.sh":
            "#!/bin/bash\n# APEX_COMMENTED is prose, not a use\n",
    }, api_md=api)
    report = run(root, rules=["APX003"])
    msgs = [f.msg for f in rule_findings(report, "APX003")]
    assert any("APEX_COMMENTED" in m and "never read" in m
               for m in msgs), msgs


# ---------------------------------------------------------------------------
# pragma machinery (APX000 + accounting)
# ---------------------------------------------------------------------------

def test_pragma_without_reason_is_a_finding(tmp_path):
    root = make_tree(tmp_path, {"benchmarks/n.py": "apx000_noreason.py"})
    report = run(root, rules=["APX004"])
    # the reasonless pragma does NOT suppress, and is itself flagged
    assert rule_findings(report, "APX004")
    assert any(f.rule == "APX000" and "without a reason" in f.msg
               for f in report.findings)


def test_pragma_with_unknown_rule_is_a_finding(tmp_path):
    root = make_tree(tmp_path, {"apex_tpu/u.py": "apx000_unknown.py"})
    report = run(root, rules=["APX001"])
    assert any(f.rule == "APX000" and "unknown rule" in f.msg
               for f in report.findings)


def test_unused_pragma_reported_not_failing(tmp_path):
    root = make_tree(tmp_path, {"benchmarks/u.py": "apx000_unused.py"})
    report = run(root, rules=["APX004"])
    assert report.ok
    assert len(report.unused_pragmas()) == 1
    assert "UNUSED" in report.render()


def test_pragma_accounting_in_json(tmp_path):
    root = make_tree(tmp_path, {
        "benchmarks/p.py": "apx004_pragma.py",
        "benchmarks/v.py": "apx004_violation.py",
    })
    report = run(root, rules=["APX004"])
    blob = report.as_json()
    assert blob["ok"] is False
    assert blob["findings"]["APX004"] == 3
    assert blob["suppressed"]["APX004"] == 1
    assert blob["pragmas"] == 1 and blob["unused_pragmas"] == 0


# ---------------------------------------------------------------------------
# 3. CLI + shell gates
# ---------------------------------------------------------------------------

def test_cli_json_machine_line_on_repo():
    """ONE real subprocess for the script surface (`python -m
    tools.apexlint --json`): rc 0 on the committed tree and one
    parseable machine line — the window_report/CI trending hook."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    blob = json.loads(out.stdout.strip().splitlines()[-1])
    assert blob["ok"] is True and blob["total"] == 0
    assert blob["pragmas"] >= 1 and blob["unused_pragmas"] == 0
    # rule skips are visible in the machine line: an "ok" that skipped
    # APX005 (no reference tree) must not read like a validated one
    assert isinstance(blob["notes"], list)
    if not os.path.isdir(lint_config.REFERENCE_ROOT):
        assert any("APX005 skipped" in n for n in blob["notes"])


def test_cli_rc1_on_findings(tmp_path):
    root = make_tree(tmp_path, {"apex_tpu/v.py": "apx001_violation.py"})
    rc = lint_main(["--root", root, "--rule", "APX001"])
    assert rc == 1


def test_cli_rc2_crash_as_finding(tmp_path):
    """A linter that dies must exit 2 with a message, never a silent
    pass (docs/API.md as a DIRECTORY makes the registry parse blow
    up past the per-file guards)."""
    root = make_tree(tmp_path)
    os.remove(tmp_path / "docs" / "API.md")
    (tmp_path / "docs" / "API.md").mkdir()
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--root", root],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "CRASH: apexlint error" in out.stderr
    # under --json the stdout contract stays one parseable line
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--root", root, "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    blob = json.loads(out.stdout.strip().splitlines()[-1])
    assert blob["ok"] is False and "CRASH" in blob["crash"]


def test_cli_rejects_unknown_rule_id():
    """A typo'd --rule must not select zero rules and report a green
    gate (explicit request ≠ preference — it raises)."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--rule", "APX04"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "unknown rule id" in out.stderr


def _shell_env(tmp_path, lint_root):
    return dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        APEX_APEXLINT_ROOT=lint_root,
        APEX_PROBE_DRYRUN="1",
        APEX_PROBE_PIDFILE=str(tmp_path / "probe.pid"),
        APEX_PROBE_DISARM=str(tmp_path / "probe.disarm"),
        APEX_PROBE_STATE=str(tmp_path / "probe.state"),
    )


def test_probe_shell_refuses_to_arm_on_dirty_lint(tmp_path):
    dirty = make_tree(tmp_path / "tree", {
        "apex_tpu/v.py": "apx001_violation.py"})
    out = subprocess.run(
        ["bash", os.path.join(REPO, "benchmarks", "probe_and_collect.sh")],
        env=_shell_env(tmp_path, dirty),
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "REFUSING TO ARM" in out.stderr and "apexlint" in out.stderr


def test_probe_shell_arms_on_clean_lint(tmp_path):
    clean = make_tree(tmp_path / "tree")
    out = subprocess.run(
        ["bash", os.path.join(REPO, "benchmarks", "probe_and_collect.sh")],
        env=_shell_env(tmp_path, clean),
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ARM OK (dryrun)" in out.stdout


def test_run_all_shell_refuses_on_dirty_lint(tmp_path):
    dirty = make_tree(tmp_path / "tree", {
        "apex_tpu/v.py": "apx001_violation.py"})
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
               APEX_APEXLINT_ROOT=dirty)
    out = subprocess.run(
        ["bash", os.path.join(REPO, "benchmarks", "run_all_tpu.sh"),
         str(tmp_path / "out")],
        env=env, capture_output=True, text=True, timeout=180)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "REFUSING TO COLLECT" in out.stderr and "APX001" in out.stderr


def test_redirect_cannot_neuter_the_gate(tmp_path):
    """A leftover APEX_APEXLINT_ROOT export must never arm a REAL
    pass, even when the fixture tree lints clean — the stale-test-env
    bypass class the APEX_FAULT_PLAN refusal also guards."""
    clean = make_tree(tmp_path / "tree")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
               APEX_APEXLINT_ROOT=clean)
    out = subprocess.run(
        ["bash", os.path.join(REPO, "benchmarks", "run_all_tpu.sh"),
         str(tmp_path / "out")],
        env=env, capture_output=True, text=True, timeout=180)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "test-only" in out.stderr
    # probe shell: same refusal for a non-dryrun arm
    probe_env = _shell_env(tmp_path, clean)
    del probe_env["APEX_PROBE_DRYRUN"]
    out = subprocess.run(
        ["bash", os.path.join(REPO, "benchmarks", "probe_and_collect.sh")],
        env=probe_env, capture_output=True, text=True, timeout=180)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "REFUSING TO ARM" in out.stderr and "test-only" in out.stderr
