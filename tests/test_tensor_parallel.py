"""Tensor/sequence-parallel tests on the 8-device CPU mesh.

Ports: tests/L0/run_transformer/test_parallel_state.py, test_mapping.py,
test_layers.py (column/row/embedding parity vs unsheared references incl.
sequence_parallel), test_cross_entropy.py, test_random.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (
    RngStateTracker,
    get_rng_state_tracker,
    model_parallel_rng_seed,
)

NDEV = 8


def tp_mesh(tp=NDEV):
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


def smap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


# ----------------------------- parallel_state ------------------------------

def test_initialize_model_parallel_sizes():
    """Port of test_parallel_state.py size checks."""
    parallel_state.initialize_model_parallel(2, 2)
    assert parallel_state.model_parallel_is_initialized()
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_data_parallel_world_size() == 2
    mesh = parallel_state.get_mesh()
    assert mesh.axis_names == ("pp", "dp", "tp")
    assert mesh.devices.shape == (2, 2, 2)
    parallel_state.destroy_model_parallel()
    assert not parallel_state.model_parallel_is_initialized()


def test_initialize_model_parallel_invalid():
    with pytest.raises(AssertionError):
        parallel_state.initialize_model_parallel(3, 1)  # 8 % 3 != 0
    parallel_state.destroy_model_parallel()


def test_rank_getters_inside_shard_map():
    parallel_state.initialize_model_parallel(2, 2)
    mesh = parallel_state.get_mesh()

    def ranks():
        return (parallel_state.get_tensor_model_parallel_rank(),
                parallel_state.get_pipeline_model_parallel_rank(),
                parallel_state.get_data_parallel_rank())

    f = shard_map(lambda: [jnp.stack(ranks())], mesh=mesh, in_specs=(),
                  out_specs=[P(("pp", "dp", "tp"))], check_vma=False)
    [out] = f()
    out = np.asarray(out).reshape(2, 2, 2, 3)
    for pp in range(2):
        for dp in range(2):
            for tp in range(2):
                np.testing.assert_array_equal(out[pp, dp, tp], [tp, pp, dp])
    parallel_state.destroy_model_parallel()


# -------------------------------- mappings ---------------------------------

def test_copy_to_region_fwd_and_bwd():
    """id fwd / psum bwd (test_mapping.py analog)."""
    mesh = tp_mesh()
    x = jnp.ones((4,))

    def fn(x):
        y = mappings.copy_to_tensor_model_parallel_region(x, "tp")
        return jnp.sum(y)

    def grad_fn(x):
        return jax.grad(fn)(x)

    g = smap(grad_fn, mesh, (P(),), P(None))(x)
    # bwd all-reduces the per-rank ones → NDEV
    np.testing.assert_allclose(np.asarray(g), NDEV)


def test_reduce_from_region_fwd_and_bwd():
    mesh = tp_mesh()
    xs = jnp.arange(NDEV * 4, dtype=jnp.float32).reshape(NDEV, 4)

    f = smap(lambda x: mappings.reduce_from_tensor_model_parallel_region(x, "tp"),
             mesh, (P("tp"),), P(None))
    np.testing.assert_allclose(np.asarray(f(xs)),
                               np.asarray(xs).sum(0, keepdims=True))

    # bwd is identity: grad of sum(psum(x)) wrt local x is all-ones
    def loss(x):
        return jnp.sum(
            mappings.reduce_from_tensor_model_parallel_region(x, "tp"))

    g = smap(jax.grad(loss), mesh, (P("tp"),), P("tp"))(xs)
    np.testing.assert_allclose(np.asarray(g), 1.0)


def test_scatter_gather_last_dim_roundtrip():
    mesh = tp_mesh()
    x = jnp.arange(2 * NDEV * 3, dtype=jnp.float32).reshape(2, NDEV * 3)

    def roundtrip(x):
        local = mappings.scatter_to_tensor_model_parallel_region(x, "tp")
        assert local.shape == (2, 3)
        return mappings.gather_from_tensor_model_parallel_region(local, "tp")

    out = smap(roundtrip, mesh, (P(),), P(None))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_scatter_bwd_is_gather():
    mesh = tp_mesh()
    x = jnp.ones((NDEV * 2,))

    def loss(x):
        local = mappings.scatter_to_tensor_model_parallel_region(x, "tp")
        rank = jax.lax.axis_index("tp").astype(jnp.float32)
        return jnp.sum(local * (rank + 1.0))

    # d/dx_i = (rank owning i) + 1
    g = smap(jax.grad(loss), mesh, (P(),), P(None))(x)
    want = np.repeat(np.arange(NDEV) + 1.0, 2)
    np.testing.assert_allclose(np.asarray(g), want)


def test_sequence_parallel_scatter_gather_roundtrip():
    mesh = tp_mesh()
    x = jnp.arange(NDEV * 2 * 3, dtype=jnp.float32).reshape(NDEV * 2, 3)

    def roundtrip(x):
        local = mappings.scatter_to_sequence_parallel_region(x, "tp")
        assert local.shape == (2, 3)
        return mappings.gather_from_sequence_parallel_region(local, "tp", True)

    out = smap(roundtrip, mesh, (P(),), P(None))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_to_sequence_parallel():
    mesh = tp_mesh()
    xs = jnp.ones((NDEV * NDEV * 2, 3))  # per-rank [seq=16, 3]

    f = smap(lambda x: mappings.reduce_scatter_to_sequence_parallel_region(x, "tp"),
             mesh, (P("tp"),), P("tp"))
    out = f(xs)
    # each rank ends with seq/NDEV=2 rows of the sum (=NDEV)
    assert out.shape == (NDEV * 2, 3)
    np.testing.assert_allclose(np.asarray(out), NDEV)


def test_gather_sequence_parallel_bwd_reduce_scatter():
    mesh = tp_mesh()
    x = jnp.ones((2, 3))  # per-rank seq shard

    def loss(x):
        full = mappings.gather_from_sequence_parallel_region(x, "tp", True)
        return jnp.sum(full)  # same on all ranks

    g = smap(jax.grad(loss), mesh, (P(),), P(None))(x)
    # reduce-scatter of the all-ones grads of the full seq → NDEV per element
    np.testing.assert_allclose(np.asarray(g), NDEV)


# --------------------------------- layers ----------------------------------

def test_column_parallel_linear_parity():
    """Column output (gathered) == dense with the gathered master weight
    (port of test_layers.py:26-130)."""
    mesh = tp_mesh(2)
    x = jnp.asarray(np.random.RandomState(0).randn(5, 16), jnp.float32)
    mod = ColumnParallelLinear(input_size=16, output_size=32,
                               gather_output=True)

    def run(x):
        y, variables = mod.init_with_output(jax.random.PRNGKey(1), x)
        return y, variables["params"]["weight"], variables["params"]["bias"]

    y, w_full, b_full = smap(run, mesh, (P(),),
                             (P(None), P("tp", None), P("tp")))(x)
    # weight shards are [out/tp, in]; gathered along dim 0
    w_full = np.asarray(w_full)
    want = np.asarray(x) @ w_full.T + np.asarray(b_full)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-5)


def test_column_parallel_linear_grad_x():
    mesh = tp_mesh(2)
    x = jnp.asarray(np.random.RandomState(2).randn(4, 16), jnp.float32)
    mod = ColumnParallelLinear(input_size=16, output_size=32,
                               gather_output=True, bias=False)

    def run(x):
        variables = mod.init(jax.random.PRNGKey(1), x)
        w = variables["params"]["weight"]
        g = jax.grad(lambda x: jnp.sum(mod.apply(variables, x)))(x)
        return g, w

    g, w_full = smap(run, mesh, (P(),), (P(None), P("tp", None)))(x)
    w_full = np.asarray(w_full)
    want = np.ones((4, 32)) @ w_full
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5, atol=1e-5)


def test_row_parallel_linear_parity():
    mesh = tp_mesh(2)
    x = jnp.asarray(np.random.RandomState(3).randn(5, 32), jnp.float32)
    mod = RowParallelLinear(input_size=32, output_size=16,
                            input_is_parallel=False)

    def run(x):
        y, variables = mod.init_with_output(jax.random.PRNGKey(4), x)
        return y, variables["params"]["weight"], variables["params"]["bias"]

    y, w_full, b = smap(run, mesh, (P(),),
                        (P(None), P(None, "tp"), P(None)))(x)
    # weight shards are [out, in/tp]; gathered along dim 1 → [out, NDEV*in/tp]
    # shards correspond to contiguous input chunks in rank order
    w_full = np.asarray(w_full).reshape(16, 32)
    want = np.asarray(x) @ w_full.T + np.asarray(b)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # the full sp MLP chain compile; per-layer
# column/row parity and the sp mapping round-trips stay fast
def test_column_row_sequence_parallel_mlp():
    """SP end-to-end: seq-sharded input → Column(SP) → Row(SP) → seq-sharded
    output equals the dense computation (test_layers.py sequence_parallel)."""
    mesh = tp_mesh()
    seq, hidden, ffn = NDEV * 2, 16, 64
    x = jnp.asarray(np.random.RandomState(5).randn(seq, hidden), jnp.float32)

    col = ColumnParallelLinear(input_size=hidden, output_size=ffn,
                               gather_output=False, bias=False,
                               sequence_parallel_enabled=True)
    row = RowParallelLinear(input_size=ffn, output_size=hidden,
                            input_is_parallel=True, bias=False,
                            sequence_parallel_enabled=True)

    def run(x_local):
        h, col_vars = col.init_with_output(jax.random.PRNGKey(6), x_local)
        y, row_vars = row.init_with_output(jax.random.PRNGKey(7), h)
        return (y, col_vars["params"]["weight"],
                row_vars["params"]["weight"])

    y, wc, wr = smap(run, mesh, (P("tp"),),
                     (P("tp"), P("tp", None), P(None, "tp")))(x)
    wc = np.asarray(wc)
    wr = np.asarray(wr)
    want = (np.asarray(x) @ wc.T) @ wr.T
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding_parity():
    mesh = tp_mesh(2)
    vocab, dim = NDEV * 4, 8
    ids = jnp.asarray(np.random.RandomState(8).randint(0, vocab, (3, 5)))
    mod = VocabParallelEmbedding(num_embeddings=vocab, embedding_dim=dim)

    def run(ids):
        y, variables = mod.init_with_output(jax.random.PRNGKey(9), ids)
        return y, variables["params"]["weight"]

    y, w_full = smap(run, mesh, (P(),), (P(None), P(("tp",))))(ids)
    w_full = np.asarray(w_full).reshape(vocab, dim)
    want = w_full[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-6)


# ------------------------------ cross entropy ------------------------------

def _ref_ce(logits, target, smoothing=0.0):
    logits = logits - logits.max(-1, keepdims=True)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    nll = -np.take_along_axis(logp, target[..., None], -1)[..., 0]
    if smoothing > 0:
        V = logits.shape[-1]
        s = smoothing * V / (V - 1)
        nll = (1 - s) * nll - s * logp.mean(-1)
    return nll


@pytest.mark.parametrize("smoothing", [
    0.0, pytest.param(0.1, marks=pytest.mark.slow)])
def test_vocab_parallel_cross_entropy(smoothing):
    """Port of test_cross_entropy.py: sharded CE == full-vocab CE."""
    mesh = tp_mesh(2)
    B, V = 6, NDEV * 4
    rng = np.random.RandomState(10)
    logits = rng.randn(B, V).astype(np.float32)
    target = rng.randint(0, V, (B,))

    f = smap(lambda l, t: vocab_parallel_cross_entropy(l, t, smoothing, "tp"),
             mesh, (P(None, "tp"), P()), P(None))
    got = f(jnp.asarray(logits), jnp.asarray(target))
    want = _ref_ce(logits, target, smoothing)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("smoothing", [
    0.0, pytest.param(0.1, marks=pytest.mark.slow)])
def test_vocab_parallel_cross_entropy_grad(smoothing):
    mesh = tp_mesh(2)
    B, V = 4, NDEV * 2
    rng = np.random.RandomState(11)
    logits = rng.randn(B, V).astype(np.float32)
    target = rng.randint(0, V, (B,))

    def sharded(l, t):
        return jax.grad(
            lambda l: jnp.sum(
                vocab_parallel_cross_entropy(l, t, smoothing, "tp")))(l)

    got = smap(sharded, mesh, (P(None, "tp"), P()), P(None, "tp"))(
        jnp.asarray(logits), jnp.asarray(target))

    def full(l):
        return jnp.sum(_jax_ref_ce(l, jnp.asarray(target), smoothing))

    want = jax.grad(full)(jnp.asarray(logits))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def _jax_ref_ce(logits, target, smoothing):
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, target[..., None], -1)[..., 0]
    if smoothing > 0:
        V = logits.shape[-1]
        s = smoothing * V / (V - 1)
        nll = (1 - s) * nll - s * jnp.mean(logp, -1)
    return nll


# --------------------------------- random ----------------------------------

def test_rng_tracker_fork_advances():
    tr = RngStateTracker()
    tr.add("default", 123)
    k1 = tr.fork("default")
    k2 = tr.fork("default")
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_rng_tracker_duplicate_seed_raises():
    tr = RngStateTracker()
    tr.add("a", 1)
    with pytest.raises(Exception, match="already exists"):
        tr.add("b", 1)
    with pytest.raises(Exception, match="is not added"):
        tr.fork("nope")


def test_model_parallel_seed_differs_per_rank():
    """model-parallel stream differs across tp; default stream identical
    (port of test_random.py semantics)."""
    mesh = tp_mesh()

    def run():
        model_parallel_rng_seed(1234, "tp")
        tr = get_rng_state_tracker()
        default = jax.random.normal(tr.fork("default"), (1,))
        mp = jax.random.normal(tr.fork("model-parallel-rng"), (1,))
        return jnp.concatenate([default, mp])

    out = np.asarray(
        shard_map(lambda: run(), mesh=mesh, in_specs=(),
                  out_specs=P("tp"), check_vma=False)()
    ).reshape(NDEV, 2)
    # default column identical across ranks
    assert np.ptp(out[:, 0]) == 0.0
    # model-parallel column all distinct
    assert len(np.unique(out[:, 1])) == NDEV
