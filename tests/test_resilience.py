"""Chaos suite for the resilience subsystem (`apex_tpu/resilience/`).

Every recorded round-3/4/5 relay failure mode (PERF.md §6) is replayed
through the REAL drivers on CPU via scripted ``APEX_FAULT_PLAN`` plans
(apex_tpu.resilience.faults), asserting the committed behaviors:

* the watchdog ladder picks the healthy b=8 line over a starved b=16,
* the lazy wedge cap arms only on the structured ``timed_out`` stamp,
* an injected degraded run is stamped ``degraded_kind: relay`` and
  REFUSED by the BENCH_BASELINE seeding gate,
* autotune drops rungs LOUDLY when the budget is injected away,
* SIGTERM still flushes a well-formed JSON line + a ledger record,
* the probe arm-guard refuses a silent start after a disarm,
* an inflated dispatch-overhead calibration yields the honest
  calibration-flap error line,
* a remote-compile HTTP-500 crashes the attempt and the watchdog
  crash-retries,
* a truncated JSON line is treated as no measurement (crash-retry).

Fast-keeping rule: fault plans that hang/crash/fabricate fire BEFORE
any backend work (a few seconds per inner process); only the faults
that live deep in the measured path (calibration inflation, the
degraded verdict, the compile-site 500) pay a real CPU smoke run, and
those share one persistent compile-cache dir.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import resilience  # noqa: E402
from apex_tpu.resilience import faults, probe as probe_cli  # noqa: E402
from apex_tpu.telemetry import ledger as tledger  # noqa: E402

BENCH = os.path.join(REPO, "bench.py")
PROBE_SH = os.path.join(REPO, "benchmarks", "probe_and_collect.sh")
RUN_ALL_SH = os.path.join(REPO, "benchmarks", "run_all_tpu.sh")

HEALTHY_TPU_REC = {
    "metric": "gpt2s_train_tokens_per_sec (tpu)", "value": 100.0,
    "unit": "tokens/s", "vs_baseline": 1.0, "mfu": 0.4,
    "config": {"batch": 8},
}


# --------------------------------------------------------------- unit layer

def test_classify_recorded_failure_shapes():
    """The §6 catalogue of record shapes maps to the five verdicts."""
    c = resilience.classify
    assert c(None) == resilience.WEDGED  # no output at all (init hang)
    # fabricated full-timeout record (wedge signature)
    assert c({"timed_out": True, "relay_degraded": True,
              "error": "bench timed out"}) == resilience.WEDGED
    # ...the same record next to healthy small-HBM evidence = §6
    # selective starvation
    assert c({"timed_out": True}, small_hbm_ok=True) \
        == resilience.DEGRADED_LARGE_HBM
    # round-5 degraded line (5.5k tok/s, honest note)
    assert c({"metric": "x (tpu)", "value": 5568, "note": "relay",
              "degraded_kind": "relay",
              "relay_degraded": True}) == resilience.DEGRADED_RELAY
    # calibration-straddle artifact
    assert c({"metric": "x (tpu)", "value": 9e9, "note": "implausible",
              "degraded_kind": "implausible",
              "relay_degraded": True}) == resilience.IMPLAUSIBLE
    # calibration-flap error line (non-positive step time)
    assert c({"metric": "x (tpu)", "value": 0, "relay_degraded": True,
              "error": "non-positive step time"}) \
        == resilience.DEGRADED_RELAY
    # silent CPU fallback on a TPU request vs an honest CPU smoke
    assert c({"metric": "x (cpu)", "value": 200.0}) \
        == resilience.DEGRADED_RELAY
    assert c({"metric": "x (cpu)", "value": 200.0}, smoke=True) \
        == resilience.HEALTHY
    assert c(HEALTHY_TPU_REC) == resilience.HEALTHY


def test_rank_healthy_beats_degraded_beats_implausible():
    healthy = dict(HEALTHY_TPU_REC)
    degraded = {"metric": "x (tpu)", "value": 5e3, "note": "n",
                "degraded_kind": "relay"}
    implausible = {"metric": "x (tpu)", "value": 9e9, "note": "n",
                   "degraded_kind": "implausible"}
    assert resilience.rank(healthy) > resilience.rank(degraded) \
        > resilience.rank(implausible)
    # within a tier, higher throughput wins
    assert resilience.rank(dict(healthy, value=200.0)) \
        > resilience.rank(healthy)


def test_classify_measurement_envelope():
    cm = resilience.classify_measurement
    assert cm(True, 0.376, 8) is None            # the §1 device envelope
    assert cm(True, 0.02, 8) == "relay"          # tunnel-dominated
    assert cm(True, 0.02, 16) == "relay"
    assert cm(True, 0.02, 2) is None             # tiny-batch exemption
    assert cm(True, 0.7, 8) == "implausible"     # calibration straddle
    assert cm(False, None, 2) is None            # no CPU detector
    assert cm(False, 0.0, 2) is None


def test_retry_policy_lazy_cap_state_machine():
    p = resilience.RetryPolicy(attempts=3, retry_wait_s=100)
    assert p.timeout_cap is None
    # a completed degraded attempt (rc 0) never arms the cap
    assert p.note_attempt({"note": "relay degraded"}, 0) is None
    # a REAL error record forwarded with rc None (teardown wedge after
    # printing) never arms it either — only the structured stamp does
    assert p.note_attempt({"error": "calibration flap"}, None) is None
    assert p.timeout_cap is None
    assert p.note_attempt({"timed_out": True}, None) \
        == resilience.WEDGE_CAP_S
    assert p.timeout_cap == resilience.WEDGE_CAP_S
    # arming is one-shot
    assert p.note_attempt({"timed_out": True}, None) is None
    # crash retries take the short wait once, then the full backoff
    p.note_crash()
    assert p.pop_wait() == resilience.CRASH_RETRY_WAIT_S
    assert p.pop_wait() == 100


def test_fault_plan_parsing_hash_and_matchers(monkeypatch, tmp_path):
    monkeypatch.delenv("APEX_FAULT_PLAN", raising=False)
    assert not faults.active() and faults.plan_hash() is None
    plan = [{"site": "verdict", "kind": "degraded",
             "degraded_kind": "relay",
             "match_env": {"APEX_CHAOS_MARK": "1"}}]
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(plan))
    h = faults.plan_hash()
    assert h and h.startswith("fp-")
    # env matcher gates the fault
    monkeypatch.delenv("APEX_CHAOS_MARK", raising=False)
    assert faults.injected_degraded() is None
    monkeypatch.setenv("APEX_CHAOS_MARK", "1")
    assert faults.injected_degraded() == "relay"
    # a path-valued plan parses to the same hash as the inline text
    p = tmp_path / "plan.json"
    p.write_text(json.dumps({"faults": plan}))
    monkeypatch.setenv("APEX_FAULT_PLAN", str(p))
    assert faults.plan_hash() == h
    # transform faults
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "calibration_overhead", "kind": "inflate", "add_s": 5},
         {"site": "emit", "kind": "truncate", "bytes": 7}]))
    assert faults.transform("calibration_overhead", 1.0) == 6.0
    assert faults.transform_output('{"value": 1234567}') == '{"value'


def test_ledger_stamps_sentinel_for_unresolvable_plan(monkeypatch,
                                                      tmp_path):
    """An ACTIVE-but-unresolvable APEX_FAULT_PLAN (deleted plan file,
    malformed JSON) must still stamp the record — a sentinel, never a
    silent omission that would let a record written under injection
    masquerade as clean."""
    monkeypatch.setenv("APEX_FAULT_PLAN", str(tmp_path / "gone.json"))
    rec = tledger.make_record("bench", "cpu", 1.0, 3, git="abc", ts=1.0)
    assert rec["fault_plan"] == "fp-unresolvable"
    monkeypatch.setenv("APEX_FAULT_PLAN", "{not json")
    rec = tledger.make_record("bench", "cpu", 1.0, 3, git="abc", ts=1.0)
    assert rec["fault_plan"] == "fp-unresolvable"


def test_ledger_stamps_fault_plan_inside_content_id(monkeypatch):
    """The stamp is computed BEFORE the content hash: stripping it (or
    adding it after the fact) breaks the record's own id — the checker
    flags exactly that as tampering."""
    monkeypatch.setenv("APEX_FAULT_PLAN",
                       json.dumps([{"site": "verdict", "kind": "degraded"}]))
    rec = tledger.make_record("bench", "cpu", 1.0, 3, git="abc", ts=1.0)
    assert rec["fault_plan"] == faults.plan_hash()
    assert tledger.validate_record(rec) == []
    stripped = {k: v for k, v in rec.items() if k != "fault_plan"}
    assert any("does not match record content" in p
               for p in tledger.validate_record(stripped))


# -------------------------------------------------- watchdog chaos (fast:
# every inner attempt hangs/fabricates before any backend work)

def _watchdog_env(tmp_path, plan, attempts, timeout, wait=1):
    env = dict(os.environ)
    for k in ("APEX_BENCH_SMOKE", "APEX_BENCH_INNER", "APEX_WARM_ONLY",
              "APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        env.pop(k, None)
    env.update(
        PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        APEX_FAULT_PLAN=json.dumps(plan),
        APEX_BENCH_ATTEMPTS=str(attempts),
        APEX_BENCH_TIMEOUT=str(timeout),
        APEX_BENCH_RETRY_WAIT=str(wait),
        APEX_TELEMETRY_LEDGER=str(tmp_path / "ledger.jsonl"),
        APEX_BENCH_BASELINE=str(tmp_path / "baseline.json"))
    return env


def _run_watchdog(tmp_path, plan, attempts=2, timeout=10, wait=1):
    return subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=300, env=_watchdog_env(tmp_path, plan, attempts, timeout,
                                       wait))


def _stdout_json_lines(out):
    return [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]


def test_chaos_ladder_picks_b8_over_starved_b16(tmp_path):
    """§6 selective large-HBM starvation: the default-config (b=8)
    attempt measures healthy while the b=16 ladder rung rides its whole
    budget — the best line is the healthy b=8 one, the starvation
    signature is named, and the fabricated window's stamp rides the
    printed line."""
    plan = [
        {"site": "backend_init", "kind": "fabricate",
         "match_env": {"APEX_BENCH_BATCH": None},
         "record": HEALTHY_TPU_REC},
        {"site": "backend_init", "kind": "hang",
         "match_env": {"APEX_BENCH_BATCH": "16"}},
    ]
    out = _run_watchdog(tmp_path, plan, attempts=2, timeout=8)
    lines = _stdout_json_lines(out)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert len(lines) == 1  # the one-JSON-line contract survives chaos
    rec = lines[0]
    assert rec["value"] == 100.0 and rec["config"]["batch"] == 8
    assert rec["fault_plan"].startswith("fp-")
    assert "large-HBM starvation signature" in out.stderr
    assert "degraded_large_hbm" in out.stderr


# re-promoted to tier-1 (ISSUE 7 fast-tier trim): the budget the ISSUE-5
# demotion bought is now covered by the in-process check_bench_labels
# conversion, and the all-attempts-hang composition (~11s — the plan
# fires pre-backend, nothing compiles) is the one watchdog path no other
# tier-1 test walks end-to-end
def test_chaos_full_timeout_wedge_arms_lazy_cap(tmp_path):
    """Backend-init hang on every attempt: each rides its entire budget,
    the first arms the 900s wedge cap (visible in the liveness log),
    and the flushed line is the honest fabricated timeout record."""
    plan = [{"site": "backend_init", "kind": "hang"}]
    out = _run_watchdog(tmp_path, plan, attempts=2, timeout=4)
    lines = _stdout_json_lines(out)
    assert out.returncode == 1  # error line only: no real measurement
    assert len(lines) == 1
    rec = lines[0]
    assert rec["timed_out"] is True and rec["relay_degraded"] is True
    assert "timed out" in rec["error"]
    assert rec["fault_plan"].startswith("fp-")  # injected wedge is stamped
    assert out.stderr.count(
        f"capping remaining attempts at {resilience.WEDGE_CAP_S}s") == 1
    assert resilience.classify(rec) == resilience.WEDGED


def test_chaos_sigterm_flushes_best_line_and_ledger_record(tmp_path):
    """Mid-attempt SIGTERM (the outer driver's budget firing): the
    watchdog flushes the best line seen so far — well-formed JSON — and
    appends a bench_watchdog ledger record naming the termination."""
    plan = [
        {"site": "backend_init", "kind": "fabricate",
         "match_env": {"APEX_BENCH_ATTEMPT": "0"},
         "record": HEALTHY_TPU_REC},
        {"site": "backend_init", "kind": "sigterm_parent",
         "match_env": {"APEX_BENCH_ATTEMPT": "1"}},
    ]
    out = _run_watchdog(tmp_path, plan, attempts=2, timeout=60)
    lines = _stdout_json_lines(out)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert len(lines) == 1 and lines[0]["value"] == 100.0
    records = tledger.read_ledger(str(tmp_path / "ledger.jsonl"))
    wd = [r for r in records if r.get("harness") == "bench_watchdog"]
    assert len(wd) == 1
    assert wd[0]["terminated"] == "SIGTERM"
    assert wd[0]["flushed"]["value"] == 100.0
    assert wd[0]["fault_plan"].startswith("fp-")
    assert tledger.validate_record(wd[0]) == []


def test_chaos_truncated_json_is_no_measurement_then_retried(tmp_path):
    """A truncated/corrupt JSON line (wedging-teardown class) parses to
    NO measurement: the watchdog crash-retries and the healthy retry
    becomes the headline."""
    plan = [
        {"site": "backend_init", "kind": "fabricate",
         "match_env": {"APEX_BENCH_ATTEMPT": "0"},
         "record": HEALTHY_TPU_REC, "truncate_bytes": 25},
        {"site": "backend_init", "kind": "fabricate",
         "match_env": {"APEX_BENCH_ATTEMPT": "1"},
         "record": HEALTHY_TPU_REC},
    ]
    out = _run_watchdog(tmp_path, plan, attempts=2, timeout=60)
    lines = _stdout_json_lines(out)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert len(lines) == 1 and lines[0]["value"] == 100.0
    assert "inner bench process crashed" in out.stderr


# re-promoted to tier-1 (ISSUE 7 fast-tier trim): ~7s, fabricate-only
# (no compile), and it is the one tier-1 walk of the rc!=0 exit style
# through the crash-wait branch
def test_chaos_relay_init_crash_is_retried_with_short_wait(tmp_path):
    """A relay-init crash (connection reset instead of a hang — the
    watchdog docstring's round-3 mode): non-zero exit, no JSON, short
    crash wait, healthy retry wins."""
    plan = [
        {"site": "backend_init", "kind": "exit", "rc": 7,
         "match_env": {"APEX_BENCH_ATTEMPT": "0"}},
        {"site": "backend_init", "kind": "fabricate",
         "match_env": {"APEX_BENCH_ATTEMPT": "1"},
         "record": HEALTHY_TPU_REC},
    ]
    out = _run_watchdog(tmp_path, plan, attempts=2, timeout=60)
    lines = _stdout_json_lines(out)
    assert out.returncode == 0
    assert len(lines) == 1 and lines[0]["value"] == 100.0
    assert "crashed (rc=7)" in out.stderr


# ------------------------------------------ real-driver chaos (one CPU
# smoke run each; they share a persistent compile cache to stay fast)

@pytest.fixture
def chaos_cache_dir(shared_smoke_cache_dir):
    # the suite-wide shared smoke cache (tests/conftest.py): the chaos
    # deep paths run the SAME smoke bench program test_compile_cache's
    # scored-line test already compiled — re-compiling it here was the
    # fast tier's single biggest avoidable cost
    return shared_smoke_cache_dir


def _run_inner_smoke(tmp_path, plan, chaos_cache_dir, extra_env=None):
    env = dict(os.environ)
    env.pop("APEX_WARM_ONLY", None)
    env.pop("APEX_FAULT_PLAN", None)  # plan=None = uninjected control
    env.update(
        PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        APEX_BENCH_SMOKE="1", APEX_BENCH_INNER="1",
        APEX_COMPILE_CACHE="1", APEX_COMPILE_CACHE_DIR=chaos_cache_dir,
        APEX_TELEMETRY_LEDGER=str(tmp_path / "ledger.jsonl"),
        APEX_BENCH_BASELINE=str(tmp_path / "baseline.json"),
        **(extra_env or {}))
    if plan is not None:
        env["APEX_FAULT_PLAN"] = json.dumps(plan)
    return subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=300, env=env)


def test_chaos_inflated_overhead_yields_calibration_flap_line(
        tmp_path, chaos_cache_dir):
    """Relay-degraded dispatch overhead: the injected inflation makes
    the overhead subtraction go non-positive — bench prints the honest
    calibration-flap error line (relay_degraded, value 0), classified
    degraded_relay, fault-stamped in both the line and the ledger."""
    plan = [{"site": "calibration_overhead", "kind": "inflate",
             "add_s": 1e6}]
    out = _run_inner_smoke(tmp_path, plan, chaos_cache_dir)
    assert out.returncode == 0, out.stderr[-2000:]
    _, rec = resilience.last_json(out.stdout)
    assert rec is not None
    assert "non-positive step time" in rec["error"]
    assert rec["relay_degraded"] is True and rec["value"] == 0
    assert rec["fault_plan"].startswith("fp-")
    assert resilience.classify(rec, smoke=True) \
        == resilience.DEGRADED_RELAY
    records = tledger.read_ledger(str(tmp_path / "ledger.jsonl"))
    assert records[-1]["fault_plan"] == rec["fault_plan"]
    assert records[-1]["relay"] == {"degraded": True,
                                    "kind": "calibration-flap"}


def test_chaos_degraded_stamp_refused_by_baseline_seeding_gate(
        tmp_path, chaos_cache_dir):
    """An injected relay-degraded verdict: the record carries
    ``degraded_kind: relay`` + the honest note, and the BENCH_BASELINE
    seeding gate REFUSES to seed a series from it (vs_baseline falls to
    the 0 sentinel); the same run without the fault seeds normally."""
    plan = [{"site": "verdict", "kind": "degraded",
             "degraded_kind": "relay"}]
    out = _run_inner_smoke(tmp_path, plan, chaos_cache_dir)
    assert out.returncode == 0, out.stderr[-2000:]
    _, rec = resilience.last_json(out.stdout)
    assert rec["degraded_kind"] == "relay"
    assert rec["relay_degraded"] is True and "note" in rec
    assert rec["fault_plan"].startswith("fp-")
    assert resilience.classify(rec, smoke=True) \
        == resilience.DEGRADED_RELAY
    assert not os.path.exists(tmp_path / "baseline.json"), \
        "a degraded run must never seed a baseline series"
    # ...and with no series seeded, vs_baseline falls to the honest
    # "not comparable" 0 sentinel (the healthy-run seeding path itself
    # is long-standing behavior — the committed BENCH_BASELINE.json's
    # cpu series — and the slow-tier bench contract smoke covers it)
    assert rec["vs_baseline"] == 0.0


def test_chaos_remote_compile_http500_crashes_attempt(
        tmp_path, chaos_cache_dir):
    """The remote-compile helper's HTTP-500 mode (the round-3 b=32
    stall class): the attempt dies with the error on stderr and NO JSON
    line — exactly the no-measurement crash the watchdog retries."""
    plan = [{"site": "compile", "kind": "raise",
             "message": "remote compile failed: HTTP 500"}]
    out = _run_inner_smoke(tmp_path, plan, chaos_cache_dir)
    assert out.returncode != 0
    assert "HTTP 500" in out.stderr
    _, rec = resilience.last_json(out.stdout)
    assert rec is None  # no parseable measurement line


# ------------------------------------------------------- autotune chaos

def test_chaos_autotune_budget_injected_away_drops_loudly(
        tmp_path, monkeypatch, capsys):
    """Budget starved to zero by the fault plan: every rung is dropped
    BY NAME (no silent caps), the pass exits non-zero, and the summary
    carries the fault stamp."""
    from benchmarks import autotune_steps

    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "autotune_budget", "kind": "set_budget",
          "budget_s": 0}]))

    def boom(*a, **k):  # the budget gate must stop every launch
        raise AssertionError("no rung subprocess may launch at budget 0")

    rc = autotune_steps.main(
        ["--smoke", "--table", str(tmp_path / "table.jsonl"),
         "--ledger", str(tmp_path / "ledger.jsonl")], runner=boom)
    out = capsys.readouterr().out
    assert rc == 1
    assert "BUDGET DROPPED" in out
    for g in autotune_steps.rung_groups(True):
        assert g["name"] in out, f"dropped rung {g['name']} not named"
    summary = json.loads(out.splitlines()[-1].split("autotune: ", 1)[1])
    assert summary["fault_plan"] == faults.plan_hash()
    assert sorted(summary["dropped"]) == sorted(
        g["name"] for g in autotune_steps.rung_groups(True))


def test_autotune_refuses_committed_table_under_fault_plan(monkeypatch):
    from benchmarks import autotune_steps

    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "autotune_budget", "kind": "set_budget", "budget_s": 0}]))
    with pytest.raises(SystemExit, match="refusing to write the committed"):
        autotune_steps.main(["--smoke"])


# ----------------------------------------------------- probe CLI verdicts

def test_probe_cli_log_gate(tmp_path, capsys):
    healthy = tmp_path / "bench.log"
    healthy.write_text("# noise\n" + json.dumps(HEALTHY_TPU_REC) + "\n")
    assert probe_cli.main(["log", str(healthy)]) == 0
    assert "healthy" in capsys.readouterr().out
    wedged = tmp_path / "wedged.log"
    wedged.write_text(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec (tpu)", "value": 0,
        "timed_out": True, "relay_degraded": True, "error": "timed out"}))
    assert probe_cli.main(["log", str(wedged)]) == 1
    assert "wedged" in capsys.readouterr().out
    assert probe_cli.main(["log", str(tmp_path / "missing.log")]) == 1
    capsys.readouterr()


def test_probe_cli_stamp_and_status_verdicts(tmp_path, capsys):
    state = str(tmp_path / "state.json")
    # healthy probe
    assert probe_cli.main(["stamp", "--rc", "0", "--detail",
                           "probe: marginal 186.2 TF/s", "--out",
                           state]) == 0
    capsys.readouterr()
    assert probe_cli.main(["status", "--state", state]) == 0
    out = capsys.readouterr().out
    assert "last probe: healthy" in out and "age" in out
    # out-of-band marginal = degraded relay; timeout kill = wedged
    assert probe_cli.main(["stamp", "--rc", "1", "--detail",
                           "probe: ... -> marginal 42.0 TF/s", "--out",
                           state]) == 1
    capsys.readouterr()
    assert probe_cli.main(["status", "--state", state]) == 1
    assert "last probe: degraded_relay" in capsys.readouterr().out
    assert probe_cli.main(["stamp", "--rc", "124", "--out", state]) == 1
    capsys.readouterr()
    assert probe_cli.main(["status", "--state", state]) == 1
    assert "last probe: wedged" in capsys.readouterr().out


def test_probe_cli_status_names_large_hbm_starvation(tmp_path, capsys):
    """Healthy probe + starved bench log = the §6 selective-starvation
    verdict, named in --status output."""
    state = str(tmp_path / "state.json")
    probe_cli.main(["stamp", "--rc", "0", "--detail",
                    "probe: marginal 186.2 TF/s", "--out", state])
    bench_log = tmp_path / "bench.log"
    bench_log.write_text(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec (tpu)", "value": 0,
        "timed_out": True, "relay_degraded": True, "error": "timed out"}))
    capsys.readouterr()
    assert probe_cli.main(["status", "--state", state,
                           "--bench", str(bench_log)]) == 0
    out = capsys.readouterr().out
    assert "last probe: healthy" in out
    assert resilience.DEGRADED_LARGE_HBM in out
    assert "selective starvation" in out


# ------------------------------------------------------- shell arm guard

def _sh(args, env_extra, timeout=60):
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               **env_extra)
    return subprocess.run(["bash", *args], capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=REPO)


@pytest.fixture
def guard_env(tmp_path):
    return {
        "APEX_PROBE_PIDFILE": str(tmp_path / "probe.pid"),
        "APEX_PROBE_DISARM": str(tmp_path / "DISARMED"),
        "APEX_PROBE_STATE": str(tmp_path / "probe_state"),
        "APEX_PROBE_DRYRUN": "1",
    }


def test_chaos_arm_guard_refuses_silent_start_after_disarm(tmp_path,
                                                           guard_env):
    """The round-5 failure mode: a window opening against a loop left
    disarmed. After `disarm` the sticky marker makes a plain start
    REFUSE loudly; only an explicit --rearm clears it."""
    out = _sh([PROBE_SH, "disarm"], guard_env)
    assert out.returncode == 0, out.stderr
    assert os.path.exists(guard_env["APEX_PROBE_DISARM"])
    # plain start refuses — a round cannot silently begin disarmed
    out = _sh([PROBE_SH], guard_env)
    assert out.returncode == 2
    assert "REFUSING TO START" in out.stderr
    assert "--rearm" in out.stderr
    # --status reports the disarmed state and exits non-zero
    out = _sh([PROBE_SH, "--status", str(tmp_path / "noout")], guard_env,
              timeout=120)
    assert out.returncode == 1
    assert "DISARMED" in out.stdout
    # explicit re-arm clears the marker and passes the guards
    out = _sh([PROBE_SH, "--rearm"], guard_env)
    assert out.returncode == 0
    assert "ARM OK (dryrun)" in out.stdout
    assert not os.path.exists(guard_env["APEX_PROBE_DISARM"])


def test_status_picks_latest_pass_numerically(tmp_path, guard_env):
    """pass10 must beat pass2..pass9 in --status (lexicographic globbing
    would report an hours-old pass as the current window)."""
    sout = tmp_path / "collect"
    for n in (2, 9, 10):
        (sout / f"pass{n}").mkdir(parents=True)
    out = _sh([PROBE_SH, "--status", str(sout)], guard_env, timeout=120)
    assert f"latest pass: {sout}/pass10" in out.stdout, out.stdout


def test_collection_shells_refuse_fault_plans(tmp_path, guard_env):
    """Scored collection must never run injected: both shell drivers
    refuse outright when APEX_FAULT_PLAN is set."""
    env = dict(guard_env, APEX_FAULT_PLAN="[]")
    out = _sh([PROBE_SH], env)
    assert out.returncode == 2 and "APEX_FAULT_PLAN" in out.stderr
    out = _sh([RUN_ALL_SH, str(tmp_path / "out")], env)
    assert out.returncode == 2 and "APEX_FAULT_PLAN" in out.stderr


def test_shell_drivers_pass_bash_syntax_gate():
    """`bash -n` over the collection shells: a broken quoting edit must
    fail tier-1, not brick the next unattended window."""
    for script in (PROBE_SH, RUN_ALL_SH):
        out = subprocess.run(["bash", "-n", script], capture_output=True,
                             text=True, timeout=60)
        assert out.returncode == 0, f"{script}: {out.stderr}"


# ------------------------------------------- durable collection manifest

from apex_tpu.resilience import manifest as manifest_mod  # noqa: E402


def test_manifest_pass_rows_match_run_all_tpu_sh():
    """The manifest's canonical row list must equal the `run <name>`
    lines of run_all_tpu.sh, in order — a row added to one cannot
    silently vanish from the other's cashed/owed account."""
    import re

    with open(RUN_ALL_SH) as f:
        rows = re.findall(r"^run\s+(\S+)\s", f.read(), re.MULTILINE)
    assert tuple(rows) == manifest_mod.PASS_ROWS


def test_manifest_classify_row_shapes(tmp_path):
    """Bench-style logs classify by their JSON line; table-printing
    harnesses by exit status; timeout statuses are the wedge."""
    healthy = json.dumps(HEALTHY_TPU_REC)
    degraded = json.dumps({"metric": "x (tpu)", "value": 5,
                           "note": "relay", "degraded_kind": "relay",
                           "relay_degraded": True})
    assert manifest_mod.classify_row(healthy, 0) == resilience.HEALTHY
    assert manifest_mod.classify_row(degraded, 0) \
        == resilience.DEGRADED_RELAY
    assert manifest_mod.classify_row("table output\n", 0) \
        == resilience.HEALTHY
    assert manifest_mod.classify_row("", 1) == resilience.DEGRADED_RELAY
    for rc in (124, 137, 143):
        assert manifest_mod.classify_row("", rc) == resilience.WEDGED
    # autotune's summary line is JSON but not a measurement line — the
    # rc carries its pass/fail
    summary = json.dumps({"done": [], "dropped": ["gpt_rows"]})
    assert manifest_mod.classify_row(summary, 1) \
        == resilience.DEGRADED_RELAY


def test_manifest_record_check_status_roundtrip(tmp_path, capsys):
    """The CLI surface run_all_tpu.sh consults: record banks a healthy
    row, check gates on it, a later degraded run never downgrades it,
    and status reports the cashed/owed account."""
    p = str(tmp_path / "manifest.json")
    log = tmp_path / "bench_first.log"
    log.write_text(json.dumps(HEALTHY_TPU_REC) + "\n")
    assert manifest_mod.main(["record", "bench_first", "--manifest", p,
                              "--log", str(log), "--rc", "0",
                              "--pass", str(tmp_path / "pass1")]) == 0
    assert manifest_mod.main(["check", "bench_first",
                              "--manifest", p]) == 0
    assert manifest_mod.main(["check", "gpt", "--manifest", p]) == 1
    # a degraded re-run must not downgrade the banked row
    log.write_text(json.dumps({"metric": "x (tpu)", "value": 5,
                               "note": "relay",
                               "relay_degraded": True}) + "\n")
    manifest_mod.main(["record", "bench_first", "--manifest", p,
                       "--log", str(log), "--rc", "0"])
    assert manifest_mod.is_cashed(p, "bench_first")
    # a wedged row stays owed with its verdict named
    manifest_mod.main(["record", "xent", "--manifest", p, "--rc", "124"])
    capsys.readouterr()
    assert manifest_mod.main(["status", "--manifest", p]) == 1
    out = capsys.readouterr().out
    n_rows = len(manifest_mod.PASS_ROWS)
    assert f"1/{n_rows} rows cashed" in out and "xent(wedged)" in out
    entry = manifest_mod.load(p)["rows"]["bench_first"]
    assert entry["pass"] == "pass1"


def test_manifest_corrupt_file_degrades_to_rerun(tmp_path):
    """A torn/corrupt manifest must degrade to re-running rows (empty
    account), never to skipping un-banked ones or crashing."""
    p = tmp_path / "manifest.json"
    p.write_text('{"rows": {"bench_first"')
    assert manifest_mod.cashed_rows(str(p)) == set()
    assert manifest_mod.main(["check", "bench_first",
                              "--manifest", str(p)]) == 1


def test_run_all_tpu_skips_cashed_rows_and_records_new_ones(tmp_path):
    """run_all_tpu.sh end-to-end on a stubbed run() queue is too heavy
    for the fast tier, but the shell's manifest contract is two CLI
    calls — exercise exactly those through a fake row the way run()
    issues them, against one manifest across two 'passes' (the
    continue-the-round property)."""
    p = str(tmp_path / "manifest.json")
    log = tmp_path / "gpt.log"
    # pass 1: the row wedges (timeout rc) -> owed
    log.write_text("no json\n")
    assert manifest_mod.main(["record", "gpt", "--manifest", p,
                              "--log", str(log), "--rc", "124",
                              "--pass", str(tmp_path / "pass1")]) == 1
    assert manifest_mod.main(["check", "gpt", "--manifest", p]) == 1
    # pass 2 (next window): the row lands healthy -> cashed, and a
    # third pass's check now skips it
    log.write_text("fine table output\n")
    assert manifest_mod.main(["record", "gpt", "--manifest", p,
                              "--log", str(log), "--rc", "0",
                              "--pass", str(tmp_path / "pass2")]) == 0
    assert manifest_mod.main(["check", "gpt", "--manifest", p]) == 0
    entry = manifest_mod.load(p)["rows"]["gpt"]
    assert entry["verdict"] == resilience.HEALTHY
    assert entry["pass"] == "pass2"


def test_manifest_probe_state_gates_rc_only_rows(tmp_path):
    """A table-printing harness (no measurement line) that exits 0
    inside a window whose LAST stamped probe was unhealthy must NOT be
    banked as healthy — exit status alone cannot tell a device-speed
    table from a ~40x tunnel-bound one. Measurement-line rows keep
    their own classifier verdict regardless of the probe."""
    degraded_probe = tmp_path / "probe_state"
    degraded_probe.write_text(json.dumps(
        {"ts": 1.0, "verdict": resilience.DEGRADED_RELAY, "rc": 1}))
    healthy_probe = tmp_path / "probe_state_ok"
    healthy_probe.write_text(json.dumps(
        {"ts": 1.0, "verdict": resilience.HEALTHY, "rc": 0}))
    # rc-only row: downgraded to the probe's verdict / banked when ok
    assert manifest_mod.classify_row(
        "table\n", 0, probe_state=str(degraded_probe)) \
        == resilience.DEGRADED_RELAY
    assert manifest_mod.classify_row(
        "table\n", 0, probe_state=str(healthy_probe)) \
        == resilience.HEALTHY
    # absent/corrupt probe state never blocks a standalone run
    assert manifest_mod.classify_row(
        "table\n", 0, probe_state=str(tmp_path / "missing")) \
        == resilience.HEALTHY
    # a bench-style measurement line is never overridden by the probe
    assert manifest_mod.classify_row(
        json.dumps(HEALTHY_TPU_REC) + "\n", 0,
        probe_state=str(degraded_probe)) == resilience.HEALTHY
    # ...and the CLI wires --probe-state through
    p = str(tmp_path / "manifest.json")
    log = tmp_path / "gpt.log"
    log.write_text("table output\n")
    assert manifest_mod.main(
        ["record", "gpt", "--manifest", p, "--log", str(log),
         "--rc", "0", "--probe-state", str(degraded_probe)]) == 1
    assert not manifest_mod.is_cashed(p, "gpt")
