"""Sharded checkpoint/resume over the 8-device CPU mesh.

Covers the three-part apex recipe (params + optimizer state + amp scaler
state as one pytree), shard-preserving restore, resharding restore, and
manager retention — the sharded capability the reference lacks (its only
distributed-state path is gather-to-rank-0).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers.fused_adam import fused_adam

pytestmark = pytest.mark.skipif(not ckpt.HAVE_ORBAX,
                                reason="orbax not installed")


def _mesh():
    return Mesh(np.asarray(jax.devices()).reshape(4, 2), ("dp", "tp"))


def _sharded_state(mesh):
    rs = np.random.RandomState(0)
    params = {
        "w": jax.device_put(jnp.asarray(rs.randn(16, 8), jnp.float32),
                            NamedSharding(mesh, P("dp", "tp"))),
        "b": jax.device_put(jnp.asarray(rs.randn(8), jnp.float32),
                            NamedSharding(mesh, P("tp"))),
    }
    tx = fused_adam(learning_rate=1e-3)
    opt_state = tx.init(params)
    scaler_state = LossScaler().init()
    return {"params": params, "opt": opt_state, "amp": scaler_state}


def test_sharded_roundtrip_preserves_values_and_sharding(tmp_path):
    mesh = _mesh()
    state = _sharded_state(mesh)
    ckpt.save_checkpoint(tmp_path / "step1", state)
    restored = ckpt.restore_checkpoint(tmp_path / "step1", state)

    for k in ("w", "b"):
        np.testing.assert_array_equal(np.asarray(restored["params"][k]),
                                      np.asarray(state["params"][k]))
        assert restored["params"][k].sharding == state["params"][k].sharding
    # optimizer + scaler state ride the same pytree
    assert int(restored["amp"].unskipped) == int(state["amp"].unskipped)
    assert float(restored["amp"].loss_scale) == float(state["amp"].loss_scale)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        restored["opt"], state["opt"])


def test_restore_onto_different_sharding(tmp_path):
    """A checkpoint written under one layout restores onto another —
    e.g. resuming a dp-sharded run with tp sharding (the re-layout case
    the reference's gather-based state_dict cannot express)."""
    mesh = _mesh()
    state = _sharded_state(mesh)
    ckpt.save_checkpoint(tmp_path / "c", state)

    new_shard = NamedSharding(mesh, P("tp", "dp"))
    template = {
        "params": {
            "w": jax.ShapeDtypeStruct((16, 8), jnp.float32,
                                      sharding=new_shard),
            "b": jax.ShapeDtypeStruct((8,), jnp.float32,
                                      sharding=NamedSharding(mesh, P())),
        },
        "opt": ckpt.abstract_like(state["opt"]),
        "amp": ckpt.abstract_like(state["amp"]),
    }
    restored = ckpt.restore_checkpoint(tmp_path / "c", template)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert restored["params"]["w"].sharding == new_shard
    assert restored["params"]["b"].sharding.is_fully_replicated


def test_manager_tree_keys_and_force_save(tmp_path):
    """tree_keys reads the saved pytree's top-level keys (None for a
    missing step); save(force=True) bypasses the interval throttle."""
    mesh = _mesh()
    state = _sharded_state(mesh)
    with ckpt.CheckpointManager(tmp_path / "k",
                                save_interval_steps=100) as mgr:
        assert mgr.save(1, state)          # InitialSavePolicy: first save
        assert not mgr.save(2, state)      # throttled (interval 100)
        assert mgr.save(2, state, force=True)
        assert mgr.all_steps() == [1, 2]
    with ckpt.CheckpointManager(tmp_path / "k") as mgr:
        assert mgr.tree_keys(1) == ["amp", "opt", "params"]
        assert mgr.tree_keys(99) is None   # missing step → None
    # params-only checkpoint advertises only its params
    with ckpt.CheckpointManager(tmp_path / "slim") as mgr:
        mgr.save(1, {"params": state["params"]})
    with ckpt.CheckpointManager(tmp_path / "slim") as mgr:
        assert mgr.tree_keys(1) == ["params"]


def test_manager_partial_restore(tmp_path):
    """partial=True restores a named subtree (params-only from a full
    {params, opt, amp} checkpoint — the --no-load-optim case)."""
    mesh = _mesh()
    state = _sharded_state(mesh)
    with ckpt.CheckpointManager(tmp_path / "p") as mgr:
        mgr.save(1, state)
    # a fresh manager, as a real resume would use: orbax pins one
    # handler type per manager instance, so partial (PyTree) restore
    # cannot follow a Standard save on the same manager
    with ckpt.CheckpointManager(tmp_path / "p") as mgr:
        only = mgr.restore(1, {"params": state["params"]}, partial=True)
    assert set(only.keys()) == {"params"}
    np.testing.assert_array_equal(np.asarray(only["params"]["w"]),
                                  np.asarray(state["params"]["w"]))


@pytest.mark.slow  # compile-heavy (4 shard_map programs + 2 orbax IOs);
# the 3D no-gather roundtrip below keeps checkpoint/resume in the fast tier
def test_zero_sharded_optimizer_state_roundtrip(tmp_path):
    """ZeRO-2 (DistributedFusedAdam) state — per-rank flat shards living
    on a dp axis — checkpoints and resumes WITHOUT a gather: saved as a
    P('dp')-sharded global array, restored onto the same sharding, and
    training continues bitwise-identically to an uninterrupted run (the
    capability the reference's gather-based state_dict lacks)."""
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistAdamState, distributed_fused_adam)
    from jax import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    rs = np.random.RandomState(1)
    params = {"w": jnp.asarray(rs.randn(24, 4), jnp.float32),
              "b": jnp.asarray(rs.randn(4), jnp.float32)}
    grads = {"w": jnp.asarray(rs.randn(24, 4) * 0.1, jnp.float32),
             "b": jnp.asarray(rs.randn(4) * 0.1, jnp.float32)}
    tx = distributed_fused_adam(learning_rate=0.05, num_shards=n,
                                axis_name="dp")

    state_specs = DistAdamState(count=P(), m=P("dp"), v=P("dp"),
                                master=P("dp"))

    init = shard_map(lambda p: tx.init(p), mesh=mesh, in_specs=(P(),),
                     out_specs=state_specs, check_vma=False)

    def steps2(params, grads, state):
        for _ in range(2):
            updates, state = tx.update(grads, state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, state

    step = shard_map(steps2, mesh=mesh,
                     in_specs=(P(), P(), state_specs),
                     out_specs=(P(), state_specs), check_vma=False)

    s0 = init(params)
    assert s0.m.shape[0] % n == 0 and s0.m.sharding.spec == P("dp")

    p2, s2 = step(params, grads, s0)
    ckpt.save_checkpoint(tmp_path / "zero", {"params": p2, "opt": s2})
    p4_direct, _ = step(p2, grads, s2)

    restored = ckpt.restore_checkpoint(tmp_path / "zero",
                                       {"params": p2, "opt": s2})
    assert restored["opt"].m.sharding == s2.m.sharding
    p4_resumed, _ = step(restored["params"], grads, restored["opt"])
    for k in params:
        np.testing.assert_array_equal(np.asarray(p4_direct[k]),
                                      np.asarray(p4_resumed[k]))


@pytest.mark.slow  # compile-heavy end-to-end variant
def test_3d_parallel_state_checkpoint_roundtrip(tmp_path):
    """Full (pp=2, dp=2, tp=2) GPT training state — stage-local,
    tp-sharded params and optimizer moments — checkpoints as
    P('pp','tp')-sharded global arrays and resumes bitwise-identically to
    an uninterrupted run: the 3D-parallel version of the no-gather
    checkpoint story."""
    from apex_tpu.transformer.parallel_state import (
        DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS)
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.minimal import (
        gpt_train_step_fn, make_gpt_fns)

    pp = dp = tp = 2
    mesh = Mesh(np.asarray(jax.devices()).reshape(pp, dp, tp),
                (PIPELINE_AXIS, DATA_AXIS, TENSOR_AXIS))
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * pp, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=16, hidden_dropout=0.0,
        attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)
    _, init_params = make_gpt_fns(cfg, pp)
    step, tx, scaler = gpt_train_step_fn(cfg, pp, num_microbatches=2)

    rs = np.random.RandomState(0)
    batch = {
        "ids": jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 2 * dp, 16)),
                           jnp.int32),
        "labels": jnp.asarray(rs.randint(0, cfg.vocab_size,
                                         (2, 2 * dp, 16)), jnp.int32),
    }
    batch_specs = {"ids": P(None, DATA_AXIS), "labels": P(None, DATA_AXIS)}

    def stack(tree):
        # local stage/tp shard -> leading (pp, tp) axes for the out_specs
        return jax.tree_util.tree_map(lambda x: x[None, None], tree)

    def unstack(tree):
        return jax.tree_util.tree_map(lambda x: x[0, 0], tree)

    def specs_like(tree):
        return jax.tree_util.tree_map(
            lambda _: P(PIPELINE_AXIS, TENSOR_AXIS), tree)

    def init_run(batch):
        params = init_params(jax.random.PRNGKey(0),
                             {k: v[0] for k, v in batch.items()})
        return stack(params), stack(tx.init(params)), stack(scaler.init())

    def one_step(params, opt_state, scaler_state, batch):
        p, o, ss, loss = step(unstack(params), unstack(opt_state),
                              unstack(scaler_state), batch)
        return stack(p), stack(o), stack(ss), jax.lax.pmean(
            loss, DATA_AXIS)

    # shapes of the stacked trees (for out_specs) come from eval_shape
    shapes = jax.eval_shape(
        lambda b: jax.shard_map(init_run, mesh=mesh,
                                in_specs=(batch_specs,),
                                out_specs=(P(), P(), P()),
                                check_vma=False)(b), batch)
    sspecs = tuple(specs_like(s) for s in shapes)

    f_init = jax.jit(jax.shard_map(init_run, mesh=mesh,
                                   in_specs=(batch_specs,),
                                   out_specs=sspecs, check_vma=False))
    f_step = jax.jit(jax.shard_map(
        one_step, mesh=mesh, in_specs=sspecs + (batch_specs,),
        out_specs=sspecs + (P(),), check_vma=False))

    params, opt_state, scaler_state = f_init(batch)
    params, opt_state, scaler_state, l1 = f_step(params, opt_state,
                                                 scaler_state, batch)
    assert np.isfinite(float(l1))
    state = {"params": params, "opt": opt_state, "scaler": scaler_state}
    ckpt.save_checkpoint(tmp_path / "p3d", state)

    # uninterrupted continuation
    p_direct, *_ = f_step(params, opt_state, scaler_state, batch)

    restored = ckpt.restore_checkpoint(tmp_path / "p3d", state)
    leaf = jax.tree_util.tree_leaves(restored["params"])[0]
    assert leaf.sharding.spec == P(PIPELINE_AXIS, TENSOR_AXIS)
    p_resumed, *_ = f_step(restored["params"], restored["opt"],
                           restored["scaler"], batch)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        p_direct, p_resumed)


def test_manager_retention_and_resume(tmp_path):
    mesh = _mesh()
    state = _sharded_state(mesh)
    with ckpt.CheckpointManager(tmp_path / "run", max_to_keep=2) as mgr:
        assert mgr.latest_step() is None
        for step in (1, 2, 3):
            scaled = jax.tree_util.tree_map(
                lambda x: (x * (1.0 + step)).astype(x.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                state)
            assert mgr.save(step, scaled)
        assert mgr.latest_step() == 3
        assert mgr.all_steps() == [2, 3]  # max_to_keep=2 dropped step 1
        restored = mgr.restore(3, state)
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               np.asarray(state["params"]["w"]) * 4.0)
