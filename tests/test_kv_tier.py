"""KV-cache memory hierarchy suite (serving/kv_tier.py, ISSUE 20):
the int8 codec's numeric contract (roundtrip band, non-finite
poisoning, the null-page-0 invariant, scatter-quantize vs the dense
reference), dequantize-at-read parity of both decode-attention impls
across swept tiles, the three-legged ``kv_restore`` resolver, and the
engine acceptance — quant greedy parity, swap-restore streams
token-for-token identical to BOTH the recompute-restored and the
never-preempted streams (greedy AND sampled), the serve_swap chaos
fallbacks, knob asymmetry, and the one-compile contract under every
enabled combination."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import dispatch
from apex_tpu.ops import decode_attention_pallas as dap
from apex_tpu.resilience import faults
from apex_tpu.serving import Request, ServingEngine, kv_cache, kv_tier
from apex_tpu.serving import lifecycle
from apex_tpu.serving.sampling import SamplingParams


# ---------------------------------------------------------- the codec


def _scales(x):
    """Per-(leading dims) amax/127 scales over the trailing two dims,
    in the wire dtype (bf16) — what both scatter paths derive."""
    amax = np.max(np.abs(np.asarray(x, np.float32)), axis=(-2, -1))
    return jnp.asarray(amax / kv_tier.QMAX, kv_tier.SCALE_DTYPE)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_roundtrip_stays_in_the_quantization_band(dtype):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 5, 4, 8) * 3.0, dtype)
    scale = _scales(x)
    q = kv_tier.quantize(x, scale)
    assert q.dtype == kv_tier.CODE_DTYPE
    y = kv_tier.dequantize(q, scale, dtype)
    assert y.dtype == dtype
    # error ≤ one code step per page (0.5 rounding + the bf16 scale's
    # own representation error), measured against the fp32 original
    band = np.asarray(scale, np.float32)[..., None, None] * 1.0 + 1e-6
    err = np.abs(np.asarray(y, np.float32) - np.asarray(x, np.float32))
    assert np.all(err <= band), float(np.max(err - band))


def test_nonfinite_inputs_poison_to_zero_codes():
    x = np.ones((1, 2, 4, 4), np.float32)
    x[0, 0, 1, 2] = np.nan
    x[0, 1, 0, 0] = np.inf
    xj = jnp.asarray(x)
    scale = _scales(kv_tier.finite(xj))
    q = np.asarray(kv_tier.quantize(xj, scale))
    # the poisoned entries became exact-zero codes, their neighbors
    # quantized normally — one NaN never zeroed (or NaN'd) a page
    assert q[0, 0, 1, 2] == 0 and q[0, 1, 0, 0] == 0
    assert np.all(q[0, 0, 0] != 0)
    assert np.all(np.isfinite(np.asarray(scale, np.float32)))


def test_zero_scale_is_a_dead_page_not_a_nan_factory():
    # inv_scale guards the reciprocal: 0 scale -> 0 inverse
    inv = np.asarray(kv_tier.inv_scale(jnp.asarray([0.0, 2.0])))
    assert inv[0] == 0.0 and inv[1] == pytest.approx(0.5)
    # quantizing real content under a zero scale emits exact zeros
    # (the null-page route), and dequantizing returns exact zeros
    x = jnp.ones((2, 4, 4))
    z = jnp.zeros((2,), kv_tier.SCALE_DTYPE)
    assert np.all(np.asarray(kv_tier.quantize(x, z)) == 0)
    q = jnp.full((2, 4, 4), 7, kv_tier.CODE_DTYPE)
    assert np.all(np.asarray(kv_tier.dequantize(q, z)) == 0.0)


def _quant_cache(layers=1, heads=2, pages=6, ps=4, d=8):
    return kv_cache.init_cache(layers, heads, pages, ps, d,
                               kv_quant=True)


def test_prefill_scatter_quant_matches_dense_and_pins_page0():
    rs = np.random.RandomState(1)
    cache = _quant_cache()
    ps = 4
    # 6 packed rows: 4 fill page 1, 2 start page 2; rows routed to
    # page 0 are the packer's padding lanes and must stay dead
    val = jnp.asarray(rs.randn(8, 2, 8), jnp.float32)
    dest_page = jnp.asarray([1, 1, 1, 1, 2, 2, 0, 0], jnp.int32)
    dest_off = jnp.asarray([0, 1, 2, 3, 0, 1, 0, 0], jnp.int32)
    keep = jnp.zeros((6,), jnp.float32).at[jnp.asarray([3, 4, 5])].set(1.0)
    cache = kv_tier.prefill_scatter_quant(
        cache, 0, "k", val, dest_page, dest_off, keep)
    got = np.asarray(kv_tier.dequantize(
        cache["k"][0], cache["k_scale"][0]), np.float32)
    want = np.asarray(val, np.float32)
    band = np.asarray(cache["k_scale"][0], np.float32) + 1e-6
    for r in range(6):
        p, o = int(dest_page[r]), int(dest_off[r])
        err = np.abs(got[:, p, o, :] - want[r])
        assert np.all(err <= band[:, p, None]), (r, float(err.max()))
    # null page 0 stays all-zero with a pinned-zero scale, even though
    # two padding rows were "scattered" there
    assert np.all(np.asarray(cache["k"])[0, :, 0] == 0)
    assert np.all(np.asarray(cache["k_scale"], np.float32)[0, :, 0] == 0)
    # untouched pages never grew a scale
    assert np.all(np.asarray(cache["k_scale"], np.float32)
                  [0, :, [3, 4, 5]] == 0)
    # a verify re-cover of page 2 (keep=1 there now) preserves page 1
    # verbatim: same scale -> ratio 1 -> bit-identical codes
    before = np.asarray(cache["k"])[0, :, 1].copy()
    val2 = jnp.asarray(rs.randn(2, 2, 8) * 0.1, jnp.float32)
    keep2 = jnp.ones((6,), jnp.float32).at[0].set(0.0)
    cache = kv_tier.prefill_scatter_quant(
        cache, 0, "k", val2, jnp.asarray([2, 2], jnp.int32),
        jnp.asarray([2, 3], jnp.int32), keep2)
    assert np.array_equal(np.asarray(cache["k"])[0, :, 1], before)
    # the small rows landed without blowing up page 2's earlier rows
    got2 = np.asarray(kv_tier.dequantize(
        cache["k"][0], cache["k_scale"][0]), np.float32)
    err = np.abs(got2[:, 2, :2, :] - want[4:6].transpose(1, 0, 2))
    band2 = np.asarray(cache["k_scale"], np.float32)[0, :, 2]
    assert np.all(err <= band2[:, None, None] + 1e-6)


def test_decode_scatter_quant_rmw_preserves_and_zeroes():
    rs = np.random.RandomState(2)
    cache = _quant_cache()
    seedrows = jnp.asarray(rs.randn(2, 2, 8), jnp.float32)
    cache = kv_tier.prefill_scatter_quant(
        cache, 0, "v", seedrows, jnp.asarray([3, 3], jnp.int32),
        jnp.asarray([0, 1], jnp.int32), jnp.zeros((6,), jnp.float32))
    # two decode lanes: lane 0 appends row 2 of page 3; lane 1 is an
    # inactive slot routed to page 0
    new = jnp.asarray(rs.randn(2, 2, 8), jnp.float32)
    cache = kv_tier.decode_scatter_quant(
        cache, 0, "v", new, jnp.asarray([3, 0], jnp.int32),
        jnp.asarray([2, 0], jnp.int32))
    got = np.asarray(kv_tier.dequantize(
        cache["v"][0], cache["v_scale"][0]), np.float32)
    band = np.asarray(cache["v_scale"], np.float32)[0, :, 3] + 1e-6
    # earlier rows survived the read-modify-write, the new row landed
    want = np.asarray(seedrows, np.float32)
    for o in range(2):
        assert np.all(np.abs(got[:, 3, o] - want[o])
                      <= band[:, None])
    assert np.all(np.abs(got[:, 3, 2] - np.asarray(new)[0])
                  <= band[:, None])
    # rows at/beyond the write offset were zeroed (stale garbage dies)
    assert np.all(got[:, 3, 3] == 0)
    # the inactive lane re-wrote page 0 with exact zeros
    assert np.all(np.asarray(cache["v"])[0, :, 0] == 0)
    assert np.all(np.asarray(cache["v_scale"], np.float32)[0, :, 0] == 0)


# -------------------------------- dequantize-at-read attention parity


def _attn_data(seed=3):
    B, H, P, PS, D, MAXP = 4, 4, 16, 32, 64, 4
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, H, D), jnp.float32)
    kf = rs.randn(H, P, PS, D).astype(np.float32)
    vf = rs.randn(H, P, PS, D).astype(np.float32)
    kf[:, 0] = vf[:, 0] = 0.0  # null page
    k_scale, v_scale = _scales(jnp.asarray(kf)), _scales(jnp.asarray(vf))
    k8 = kv_tier.quantize(jnp.asarray(kf), k_scale)
    v8 = kv_tier.quantize(jnp.asarray(vf), v_scale)
    pt = jnp.asarray(np.stack([
        rs.permutation(np.arange(1, P))[:MAXP] for _ in range(B)]),
        jnp.int32)
    lens = jnp.asarray([5, PS, MAXP * PS, 0], jnp.int32)
    sm = 1.0 / np.sqrt(D)
    return (q, jnp.asarray(kf), jnp.asarray(vf), k8, v8, k_scale,
            v_scale, pt, lens, sm)


@pytest.mark.parametrize("bh", [1, 2, 4])
def test_decode_attention_int8_parity_across_block_h(bh):
    (q, kf, vf, k8, v8, ks, vs, pt, lens, sm) = _attn_data()
    ref8 = dap.decode_attention_reference(q, k8, v8, pt, lens, sm,
                                          k_scale=ks, v_scale=vs)
    got = dap.decode_attention_pallas(q, k8, v8, pt, lens, sm,
                                      k_scale=ks, v_scale=vs,
                                      block_h=bh, interpret=True)
    # kernel vs jnp reference: same dequantize-at-read math -> tight
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref8),
                               atol=1e-4)
    # int8 tier vs the float cache: inside the quantization band
    reff = dap.decode_attention_reference(q, kf, vf, pt, lens, sm)
    np.testing.assert_allclose(np.asarray(ref8), np.asarray(reff),
                               atol=0.12)
    # the fully-masked lane still produces exact zeros
    assert np.all(np.asarray(got)[3] == 0.0)


def test_int8_pages_without_scales_raise():
    (q, _, _, k8, v8, ks, vs, pt, lens, sm) = _attn_data()
    with pytest.raises(ValueError, match="come as a pair"):
        dap.decode_attention(q, k8, v8, pt, lens, sm_scale=sm,
                             k_scale=ks)
    with pytest.raises(ValueError, match="int8"):
        dap.decode_attention(q, k8, v8, pt, lens, sm_scale=sm)


# ------------------------------------------- the kv_restore resolver


def test_resolver_demand_legs_raise_unhonorable(monkeypatch):
    r = kv_tier.resolve_kv_restore
    with pytest.raises(ValueError, match="unknown kv_restore"):
        r("mmap", swap_enabled=True, tokens=8, dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="never banked"):
        r("swap", swap_enabled=False, tokens=8, dtype=jnp.bfloat16)
    # honorable demands pass through untouched
    assert r("recompute", swap_enabled=True, tokens=8,
             dtype=jnp.bfloat16) == "recompute"
    # tier off: every preference leg collapses to recompute
    monkeypatch.setenv("APEX_SERVE_KV_RESTORE", "swap")
    assert r(None, swap_enabled=False, tokens=8,
             dtype=jnp.bfloat16) == "recompute"


def test_resolver_env_table_builtin_legs(tmp_path, monkeypatch):
    r = kv_tier.resolve_kv_restore
    path = tmp_path / "table.jsonl"
    path.write_text(json.dumps(dispatch.make_entry(
        "kv_restore", {"s": 10}, jnp.bfloat16, "cpu", "recompute",
        "lg-" + "0" * 10)) + "\n")
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(path))
    dispatch._reset_for_tests()
    try:
        # table leg: bucket s16 has a committed recompute crossover
        assert r(None, swap_enabled=True, tokens=10, dtype=jnp.bfloat16,
                 backend="cpu") == "recompute"
        # table miss (s128): the tier's built-in is swap
        assert r(None, swap_enabled=True, tokens=100,
                 dtype=jnp.bfloat16, backend="cpu") == "swap"
        # env preference outranks the table
        monkeypatch.setenv("APEX_SERVE_KV_RESTORE", "swap")
        assert r(None, swap_enabled=True, tokens=10, dtype=jnp.bfloat16,
                 backend="cpu") == "swap"
    finally:
        monkeypatch.delenv("APEX_DISPATCH_TABLE")
        dispatch._reset_for_tests()


# ------------------------------------------------- engine acceptance


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from apex_tpu.serving import model as smodel

    params = smodel.init_gpt_params(cfg)
    ref = _engine(cfg, params)  # the never-preempted reference
    reqs = _requests()
    _drive(ref, reqs)
    return cfg, params, {r.rid: list(r.out_tokens) for r in reqs}


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("APEX_FAULT_PLAN", raising=False)
    faults._cache["fired"] = {}
    yield
    faults._cache["fired"] = {}


def _requests():
    return [Request(rid=0, prompt=[1, 2, 3, 4, 5, 6],
                    max_new_tokens=10),
            Request(rid=1, prompt=[7, 8, 9, 10, 11, 12],
                    max_new_tokens=10)]


def _drive(eng, reqs, guard=300):
    for r in reqs:
        eng.submit(r)
    n = 0
    while not all(r.done() for r in reqs):
        eng.step()
        n += 1
        assert n < guard, ("engine did not drain",
                           [r.out_tokens for r in reqs])
    eng.step()


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 16)
    if kw.get("preempt") or kw.get("kv_swap"):
        lifecycle.enable()
        try:
            return ServingEngine(cfg, params=params, **kw)
        finally:
            lifecycle.reset_enabled()
    return ServingEngine(cfg, params=params, **kw)


def _contract(eng):
    assert eng.decode_cache_size() == 1, eng.decode_cache_size()
    assert eng.prefill_cache_size() <= 1, eng.prefill_cache_size()
    eng.allocator.check_invariants()


def test_kv_quant_greedy_parity_one_compile(setup):
    cfg, params, ref = setup
    eng = _engine(cfg, params, kv_quant=True)
    assert eng.kv_quant and kv_tier.is_quantized(eng.cache)
    reqs = _requests()
    _drive(eng, reqs)
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    _contract(eng)


def test_swap_restore_token_identical_to_both_references(setup):
    """THE swap acceptance: under real KV pressure the swap-restored
    streams match token-for-token BOTH the recompute-restored engine
    and the never-preempted reference — restore is a pure latency
    decision, never a numerics one — and the handle economics close
    (live pages drain to 0, high-water recorded, rates surfaced)."""
    cfg, params, ref = setup
    pool = dict(num_pages=6, max_seq=16, preempt=True)
    rec_eng = _engine(cfg, params, **pool)
    rec_reqs = _requests()
    _drive(rec_eng, rec_reqs)
    assert rec_eng.resilience.preempted >= 1
    eng = _engine(cfg, params, kv_swap=True, **pool)
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.preempted >= 1
    st = eng.kv_stats
    assert st.swap_outs >= 1 and st.swap_ins >= 1, vars(st)
    assert st.restores_swap >= 1 and st.swap_in_failures == 0, vars(st)
    assert st.swapped_pages_live == 0 and st.swapped_bytes_live == 0
    assert st.swapped_pages_high_water >= 1
    for r, rr in zip(reqs, rec_reqs):
        assert r.out_tokens == rr.out_tokens, (r.rid, r.out_tokens)
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.events.validate_order() == []
    rates = eng.kv_tier_rates()
    assert rates["swap_rate"] and 0 < rates["swap_rate"] <= 1
    assert rates["swapped_pages_high_water"] >= 1
    _contract(eng)


def test_quant_swap_composed_parity(setup):
    cfg, params, ref = setup
    eng = _engine(cfg, params, num_pages=6, max_seq=16, preempt=True,
                  kv_swap=True, kv_quant=True)
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.preempted >= 1
    assert eng.kv_stats.swap_ins >= 1, vars(eng.kv_stats)
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    _contract(eng)


def test_swap_restore_sampled_parity(setup):
    """The sampled half of the acceptance: a seeded stochastic stream
    swap-restores to the SAME tokens it draws never-preempted — the
    sampling counter is the request's own generation index and
    ``resume_tokens`` carries the pending draw, so the restored slot
    re-enters the decode program at an identical lane state."""
    cfg, params, _ = setup

    def _sampled():
        return [Request(rid=0, prompt=[1, 2, 3, 4, 5, 6],
                        max_new_tokens=10,
                        sampling=SamplingParams(temperature=0.9,
                                                top_k=20, seed=7)),
                Request(rid=1, prompt=[7, 8, 9, 10, 11, 12],
                        max_new_tokens=10,
                        sampling=SamplingParams(temperature=1.1,
                                                seed=11))]

    ref_eng = _engine(cfg, params, sampling=True)
    ref_reqs = _sampled()
    _drive(ref_eng, ref_reqs)
    eng = _engine(cfg, params, num_pages=6, max_seq=16, preempt=True,
                  kv_swap=True, sampling=True)
    reqs = _sampled()
    _drive(eng, reqs)
    assert eng.resilience.preempted >= 1
    assert eng.kv_stats.restores_swap >= 1, vars(eng.kv_stats)
    for r, rr in zip(reqs, ref_reqs):
        assert r.out_tokens == rr.out_tokens, (r.rid, r.out_tokens)
    _contract(eng)


def test_swap_out_fault_falls_back_to_recompute(setup, monkeypatch):
    """serve_swap chaos, swap-out leg: the banking copy raises ONCE —
    the victim restores by recompute instead (degraded latency, same
    tokens), the failure is counted AND classified (a ``swap_failed``
    event between preempted and resubmitted), order stays valid."""
    cfg, params, ref = setup
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "serve_swap", "kind": "raise", "times": 1,
          "match_ctx": {"phase": "swap_out"}}]))
    eng = _engine(cfg, params, num_pages=6, max_seq=16, preempt=True,
                  kv_swap=True)
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.kv_stats.swap_out_failures >= 1, vars(eng.kv_stats)
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    victim = next(r for r in reqs if r.preemptions)
    chain = [e["event"] for e in eng.events.request_events(victim.rid)]
    i = chain.index("swap_failed")
    assert chain[i - 1] == "preempted" and chain[i + 1] == "resubmitted"
    assert eng.events.validate_order() == []
    _contract(eng)


def test_corrupt_banked_bytes_caught_by_checksum(setup, monkeypatch):
    """serve_swap chaos, swap-in leg: a bit flipped in the banked host
    bytes is caught by the handle's seal BEFORE any page lands on
    device — the stream falls back to recompute with the same tokens,
    never a corrupted cache."""
    cfg, params, ref = setup
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "serve_swap", "kind": "corrupt", "times": 1,
          "match_ctx": {"phase": "swap_in"}}]))
    eng = _engine(cfg, params, num_pages=6, max_seq=16, preempt=True,
                  kv_swap=True)
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.kv_stats.swap_in_failures >= 1, vars(eng.kv_stats)
    assert eng.kv_stats.restores_recompute >= 1
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.events.validate_order() == []
    assert eng.kv_stats.swapped_pages_live == 0  # failed handle freed
    _contract(eng)


def test_handle_seal_detects_tampering():
    h = kv_tier.SwappedPages(
        leaves={"k": np.arange(16, dtype=np.int8).reshape(2, 8)},
        page_count=1, tokens=3, quant=True).seal()
    assert h.intact() and h.nbytes() == 16
    h.leaves["k"].view(np.uint8).ravel()[5] ^= 0xFF
    assert not h.intact()


def test_kv_knob_asymmetry(setup, monkeypatch):
    cfg, params, _ = setup
    # kv_swap demand without preemption: no honorable answer
    with pytest.raises(ValueError, match="preempt"):
        _engine(cfg, params, kv_swap=True)
    # kv_restore='swap' demand on a swap-less engine raises at build
    with pytest.raises(ValueError, match="never banked"):
        _engine(cfg, params, kv_restore="swap")
    # env preferences fall back / engage without raising
    monkeypatch.setenv("APEX_SERVE_KV_SWAP", "1")
    eng = _engine(cfg, params)
    assert not eng.kv_swap  # pref dropped: preemption is off
    monkeypatch.setenv("APEX_SERVE_KV_QUANT", "1")
    eng2 = _engine(cfg, params)
    assert eng2.kv_quant and kv_tier.is_quantized(eng2.cache)
    # the resolver legs behind the engine knobs
    monkeypatch.delenv("APEX_SERVE_KV_QUANT")
    assert kv_tier.resolve_kv_quant() is False
    assert kv_tier.resolve_kv_quant(True) is True
    assert kv_tier.resolve_kv_swap() is True  # env still set
    monkeypatch.delenv("APEX_SERVE_KV_SWAP")
    assert kv_tier.resolve_kv_swap() is False


def test_one_compile_contract_under_every_combination(setup):
    cfg, params, ref = setup
    combos = [
        dict(kv_quant=True, decode_k=2),
        dict(kv_quant=True, num_pages=6, max_seq=16, preempt=True,
             kv_swap=True),
        dict(num_pages=6, max_seq=16, preempt=True, kv_swap=True,
             kv_restore="recompute"),
    ]
    for kw in combos:
        eng = _engine(cfg, params, **kw)
        reqs = _requests()
        _drive(eng, reqs)
        for r in reqs:
            assert r.out_tokens == ref[r.rid], (kw, r.rid, r.out_tokens)
        _contract(eng)
