"""apex_tpu.ops.fused_attention tests (dense path on the CPU mesh; the
Pallas path is exercised by bench/verify runs on TPU — both paths share
semantics by construction and the flash kernel is parity-tested upstream).
"""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_attention


def _naive(q, k, v, causal, scale, seg=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    s = np.einsum("bhqd,bhkd->bhqk", q, k, dtype=np.float64) * scale
    mask = np.zeros((b, h, sq, sk), bool)
    if causal:
        mask |= np.triu(np.ones((sq, sk), bool), 1)
    if seg is not None:
        sq_ids, skv_ids = seg
        mask |= (sq_ids[:, None, :, None] != skv_ids[:, None, None, :])
    s = np.where(mask, -1e30, s)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = np.where(mask, 0, p)
    denom = p.sum(-1, keepdims=True)
    p = np.where(denom > 0, p / np.where(denom > 0, denom, 1), 0)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def test_fused_attention_causal():
    rs = np.random.RandomState(0)
    q, k, v = [rs.randn(2, 3, 16, 8).astype(np.float32) for _ in range(3)]
    out = fused_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True)
    want = _naive(q, k, v, True, 1 / np.sqrt(8))
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)


def test_fused_attention_segment_ids():
    rs = np.random.RandomState(1)
    q, k, v = [rs.randn(1, 2, 12, 8).astype(np.float32) for _ in range(3)]
    seg = np.asarray([[0] * 5 + [1] * 4 + [7] * 3])  # 7 = padding sentinel
    out = fused_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          segment_ids=(jnp.asarray(seg), jnp.asarray(seg)))
    want = _naive(q, k, v, False, 1 / np.sqrt(8), (seg, seg))
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)


def test_fused_attention_grads_match_dense_autodiff():
    rs = np.random.RandomState(2)
    q, k, v = [jnp.asarray(rs.randn(1, 2, 8, 4), jnp.float32)
               for _ in range(3)]

    def f(q, k, v):
        return jnp.sum(fused_attention(q, k, v, causal=True) ** 2)

    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


def test_impl_knob_validation_and_fallthrough():
    import pytest as _pytest

    from apex_tpu.ops.attention import set_default_impl

    q = jnp.ones((1, 1, 8, 4), jnp.float32)
    with _pytest.raises(ValueError):
        fused_attention(q, q, q, impl="row")  # typo must not silently flash
    with _pytest.raises(ValueError):
        set_default_impl("dense")
    # on the CPU backend both impls fall through to the dense path and agree
    a = fused_attention(q, q, q, causal=True, impl="rows")
    b = fused_attention(q, q, q, causal=True, impl="flash")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
