"""Data-parallel tests on the 8-device CPU mesh.

Ports: tests/distributed/DDP/ddp_race_condition_test.py (math-check of
reduced grads), tests/distributed/synced_batchnorm/ (synced BN == full-batch
BN parity, fwd+bwd), tests/L0/run_amp/test_larc.py.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.parallel import (
    convert_syncbn_model,
    DistributedDataParallel, allreduce_gradients, broadcast_params,
    SyncBatchNorm, sync_batch_norm, LARC, larc, pvary,
)
from apex_tpu.optimizers import FusedSGD

NDEV = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:NDEV]), ("data",))


def test_allreduce_gradients_mean():
    mesh = _mesh()
    grads = {"w": jnp.arange(NDEV * 3, dtype=jnp.float32).reshape(NDEV, 3)}

    f = shard_map(
        lambda g: allreduce_gradients(g, "data"),
        mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    out = f(grads)
    want = np.mean(np.arange(NDEV * 3, dtype=np.float32).reshape(NDEV, 3),
                   axis=0)
    for i in range(NDEV):
        np.testing.assert_allclose(np.asarray(out["w"][i]), want, rtol=1e-6)


def test_allreduce_predivide_and_fp32():
    mesh = _mesh()
    grads = {"w": jnp.ones((NDEV, 4), jnp.bfloat16)}
    ddp = DistributedDataParallel(allreduce_always_fp32=True,
                                  gradient_predivide_factor=2.0)
    f = shard_map(ddp.average_gradients, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"))
    out = f(grads)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), 1.0)


def test_allreduce_sum_mode():
    mesh = _mesh()
    grads = jnp.ones((NDEV, 2))
    f = shard_map(
        lambda g: allreduce_gradients(g, "data", gradient_average=False),
        mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(f(grads)), 8.0)


def test_broadcast_params():
    mesh = _mesh()
    params = {"w": jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)}
    f = shard_map(lambda p: broadcast_params(p, "data"), mesh=mesh,
                  in_specs=(P("data"),), out_specs=P("data"))
    out = f(params)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.0)  # rank 0's value


def test_ddp_warns_on_bucket_knobs():
    with pytest.warns(UserWarning, match="message_size"):
        DistributedDataParallel(message_size=1)


def test_ddp_grad_math_check():
    """Port of ddp_race_condition_test.py:28-40: grad of sum(w*x) over the
    axis must equal mean of per-rank x."""
    mesh = _mesh()
    w = jnp.ones((4,), jnp.float32)
    xs = jnp.arange(NDEV * 4, dtype=jnp.float32).reshape(NDEV, 4)

    def step(w, x):
        # pvary = each replica owns its copy (the DDP model); grads are then
        # per-replica and the explicit allreduce averages them.
        w = pvary(w, "data")
        g = jax.grad(lambda w: jnp.sum(w * x))(w)
        return allreduce_gradients(g, "data")

    f = shard_map(step, mesh=mesh, in_specs=(P(), P("data")),
                  out_specs=P("data"))
    out = np.asarray(f(w, xs)).reshape(NDEV, 4)  # concatenated (4,) outputs
    want = np.mean(np.arange(NDEV * 4, dtype=np.float32).reshape(NDEV, 4), 0)
    for i in range(NDEV):
        np.testing.assert_allclose(out[i], want, rtol=1e-6)


@pytest.mark.slow  # compile-heavy end-to-end variant
def test_amp_o2_master_params_identical_across_ranks():
    """Port of tests/distributed/amp_master_params/: after DDP-averaged
    O2 training steps on rank-DIFFERENT data, the fp32 master params (and
    the bf16 model params) must be bitwise identical on every rank."""
    from apex_tpu import amp
    from apex_tpu.optimizers import fused_sgd

    mesh = _mesh()
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(4, 2), jnp.float32)}
    params, opt = amp.initialize(params, fused_sgd(learning_rate=0.1),
                                 opt_level="O2", verbosity=0)
    state = opt.init(params)
    xs = jnp.asarray(rs.randn(NDEV, 3, 4), jnp.float32)  # per-rank data

    def steps(params, state, x):
        params = pvary(params, "data")
        state = pvary(state, "data")
        for _ in range(3):
            def loss_fn(p):
                return jnp.sum((x.astype(p["w"].dtype) @ p["w"])
                               .astype(jnp.float32) ** 2)

            f = amp.value_and_scaled_grad(loss_fn, opt)
            _, grads, found_inf = f(params, state)
            grads = allreduce_gradients(grads, "data")
            params, state, _ = opt.apply_gradients(
                grads, state, params, grads_already_unscaled=True,
                found_inf=found_inf)
        # leading rank axis so out_specs=P("data") stacks all ranks
        return (params["w"][None], state.master_params["w"][None])

    f = shard_map(steps, mesh=mesh, in_specs=(P(), P(), P("data")),
                  out_specs=(P("data"), P("data")), check_vma=False)
    model_w, master_w = f(params, state, xs)
    model_w, master_w = np.asarray(model_w), np.asarray(master_w)
    assert master_w.dtype == np.float32
    assert model_w.dtype == jnp.bfloat16
    for r in range(1, NDEV):
        np.testing.assert_array_equal(master_w[r], master_w[0])
        np.testing.assert_array_equal(model_w[r], model_w[0])
    # and training actually moved them
    assert not np.array_equal(master_w[0],
                              np.asarray(state.master_params["w"]))


# ------------------------------ SyncBatchNorm ------------------------------

def test_syncbn_matches_full_batch_bn():
    """The core parity property: BN over the full batch == SyncBN over the
    per-device shards (reference: tests/distributed/synced_batchnorm/
    single_gpu_unit_test.py equivalence)."""
    rng = np.random.RandomState(0)
    x = rng.randn(NDEV * 4, 16).astype(np.float32)  # [B, C]
    mesh = _mesh()

    # reference: plain full-batch BN
    mean = x.mean(0)
    var = x.var(0)
    want = (x - mean) / np.sqrt(var + 1e-5)

    f = shard_map(
        lambda x: sync_batch_norm(x, None, None, axis_name="data",
                                  training=True)[0],
        mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    got = f(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # grad-of-syncbn compile is the cost; the forward
# full-batch parity test keeps SyncBN in the fast tier
def test_syncbn_backward_matches_full_batch():
    rng = np.random.RandomState(1)
    x = rng.randn(NDEV * 2, 8).astype(np.float32)
    scale = rng.rand(8).astype(np.float32) + 0.5
    bias = rng.randn(8).astype(np.float32)
    mesh = _mesh()

    def full_loss(x):
        m = jnp.mean(x, 0)
        v = jnp.mean((x - m) ** 2, 0)
        y = (x - m) * jax.lax.rsqrt(v + 1e-5) * scale + bias
        return jnp.sum(y ** 2)

    want = jax.grad(full_loss)(jnp.asarray(x))

    def sharded_loss_grad(x):
        def loss(x):
            y, _, _ = sync_batch_norm(x, scale, bias, axis_name="data",
                                      training=True)
            return jax.lax.psum(jnp.sum(y ** 2), "data")
        return jax.grad(loss)(x)

    f = shard_map(sharded_loss_grad, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"))
    got = f(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-4)


def test_syncbn_module_running_stats_and_eval():
    mod = SyncBatchNorm(num_features=4, axis_name=None, momentum=0.5)
    x = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)
    variables = mod.init(jax.random.PRNGKey(0), x)
    y, updated = mod.apply(variables, x, mutable=["batch_stats"])
    rm = np.asarray(updated["batch_stats"]["running_mean"])
    np.testing.assert_allclose(rm, 0.5 * np.asarray(x).mean(0), rtol=1e-5)
    # eval uses running stats
    y_eval = mod.apply(
        {"params": variables["params"], "batch_stats": updated["batch_stats"]},
        x, use_running_average=True)
    assert y_eval.shape == x.shape


def test_syncbn_fuse_relu():
    x = jnp.asarray(np.random.RandomState(3).randn(8, 4), jnp.float32)
    y, _, _ = sync_batch_norm(x, None, None, axis_name=None, training=True,
                              fuse_relu=True)
    assert float(jnp.min(y)) >= 0.0


def test_syncbn_channels_first():
    x = jnp.asarray(np.random.RandomState(4).randn(6, 4, 5, 5), jnp.float32)
    y, _, _ = sync_batch_norm(x, None, None, axis_name=None, training=True,
                              channel_axis=1)
    got = np.asarray(y)
    assert abs(got.mean(axis=(0, 2, 3))).max() < 1e-5  # normalized per channel


# --------------------------------- LARC ---------------------------------

def test_larc_scaling_math():
    p = {"w": jnp.full((4,), 2.0)}
    g = {"w": jnp.full((4,), 0.1)}
    tx = larc(trust_coefficient=0.02, clip=False, eps=0.0)
    scaled, _ = tx.update(g, None, p)
    # adaptive = 0.02 * |p| / |g| = 0.02 * 4 / 0.2 = 0.4 → g*0.4
    np.testing.assert_allclose(np.asarray(scaled["w"]), 0.04, rtol=1e-5)


def test_larc_clip_mode():
    p = {"w": jnp.full((4,), 2.0)}
    g = {"w": jnp.full((4,), 0.1)}
    tx = larc(trust_coefficient=10.0, clip=True, eps=0.0, learning_rate=0.1)
    scaled, _ = tx.update(g, None, p)
    # adaptive huge → clipped at 1 → grads unchanged
    np.testing.assert_allclose(np.asarray(scaled["w"]), 0.1, rtol=1e-5)


def test_larc_wrapping_fused_sgd():
    params = [jnp.full((4,), 2.0)]
    opt = LARC(FusedSGD(params, lr=0.1), trust_coefficient=0.02, clip=False)
    out = opt.step([jnp.full((4,), 0.1)])
    # scaled grad 0.04 → p - 0.1*0.04 = 1.996
    np.testing.assert_allclose(np.asarray(out[0]), 1.996, rtol=1e-5)


def test_convert_syncbn_model_from_flax_bn():
    """Converted flax BatchNorm must infer features and actually run
    (regression: num_features used to default to 0)."""
    from flax import linen as nn
    bn = nn.BatchNorm(use_running_average=False)
    sbn = convert_syncbn_model(bn)
    assert isinstance(sbn, SyncBatchNorm)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3), jnp.float32)
    variables = sbn.init(jax.random.PRNGKey(0), x)
    y, _ = sbn.apply(variables, x, mutable=["batch_stats"])
    assert y.shape == (4, 3)
    assert variables["params"]["weight"].shape == (3,)
