"""Fused optimizer parity tests vs torch.optim references
(reference: tests/L0/run_optimizers/test_fused_optimizer.py, test_lamb.py —
fused vs torch.optim step-by-step closeness)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from apex_tpu.optimizers import (
    FusedAdam, FusedSGD, FusedAdagrad, FusedLAMB, FusedNovoGrad,
    fused_adam, fused_sgd, FusedMixedPrecisionLamb,
)

SHAPES = [(5,), (3, 4), (2, 3, 2)]
N_STEPS = 8


def _gen(seed=0):
    rng = np.random.RandomState(seed)
    params = [rng.randn(*s).astype(np.float32) for s in SHAPES]
    grads = [
        [rng.randn(*s).astype(np.float32) for s in SHAPES] for _ in range(N_STEPS)
    ]
    return params, grads


def _run_torch(opt_cls, params_np, grads_np, **kwargs):
    params = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    opt = opt_cls(params, **kwargs)
    for g_step in grads_np:
        opt.zero_grad()
        for p, g in zip(params, g_step):
            p.grad = torch.tensor(g)
        opt.step()
    return [p.detach().numpy() for p in params]


def _run_jax(opt, grads_np):
    for g_step in grads_np:
        out = opt.step([jnp.asarray(g) for g in g_step])
    return [np.asarray(p) for p in out]


@pytest.mark.parametrize("weight_decay", [0.0, 0.1])
@pytest.mark.parametrize("adam_w", [True, False])
def test_fused_adam_vs_torch(weight_decay, adam_w):
    params_np, grads_np = _gen()
    torch_cls = torch.optim.AdamW if adam_w else torch.optim.Adam
    want = _run_torch(torch_cls, params_np, grads_np, lr=1e-2,
                      betas=(0.9, 0.999), eps=1e-8, weight_decay=weight_decay)
    opt = FusedAdam([jnp.asarray(p) for p in params_np], lr=1e-2,
                    betas=(0.9, 0.999), eps=1e-8, weight_decay=weight_decay,
                    adam_w_mode=adam_w)
    got = _run_jax(opt, grads_np)
    for w, g in zip(want, got):
        np.testing.assert_allclose(w, g, rtol=1e-3, atol=1e-5)


@pytest.mark.parametrize("momentum,nesterov,wd", [
    (0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 0.05),
])
def test_fused_sgd_vs_torch(momentum, nesterov, wd):
    params_np, grads_np = _gen(1)
    want = _run_torch(torch.optim.SGD, params_np, grads_np, lr=0.1,
                      momentum=momentum, nesterov=nesterov, weight_decay=wd)
    opt = FusedSGD([jnp.asarray(p) for p in params_np], lr=0.1,
                   momentum=momentum, nesterov=nesterov, weight_decay=wd)
    got = _run_jax(opt, grads_np)
    for w, g in zip(want, got):
        np.testing.assert_allclose(w, g, rtol=1e-3, atol=1e-5)


def test_fused_adagrad_vs_torch():
    params_np, grads_np = _gen(2)
    want = _run_torch(torch.optim.Adagrad, params_np, grads_np, lr=1e-2,
                      eps=1e-10)
    opt = FusedAdagrad([jnp.asarray(p) for p in params_np], lr=1e-2, eps=1e-10)
    got = _run_jax(opt, grads_np)
    for w, g in zip(want, got):
        np.testing.assert_allclose(w, g, rtol=1e-3, atol=1e-5)


def _reference_lamb_step(params, grads, m, v, step, lr, b1, b2, eps, wd,
                         max_grad_norm, use_nvlamb):
    """NumPy reference of multi_tensor_lamb.cu semantics."""
    gnorm = np.sqrt(sum(np.sum(g * g) for g in grads))
    clip = max(gnorm / max_grad_norm, 1.0) if max_grad_norm else 1.0
    new_params, new_m, new_v = [], [], []
    for p, g, mi, vi in zip(params, grads, m, v):
        g = g / clip
        mi = b1 * mi + (1 - b1) * g
        vi = b2 * vi + (1 - b2) * g * g
        mhat = mi / (1 - b1 ** step)
        vhat = vi / (1 - b2 ** step)
        u = mhat / (np.sqrt(vhat) + eps) + wd * p
        wn = np.linalg.norm(p.ravel())
        un = np.linalg.norm(u.ravel())
        if (wd != 0.0 or use_nvlamb) and wn > 0 and un > 0:
            ratio = wn / un
        else:
            ratio = 1.0
        new_params.append(p - lr * ratio * u)
        new_m.append(mi)
        new_v.append(vi)
    return new_params, new_m, new_v


@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_fused_lamb_vs_reference(wd):
    params_np, grads_np = _gen(3)
    m = [np.zeros_like(p) for p in params_np]
    v = [np.zeros_like(p) for p in params_np]
    want = [p.copy() for p in params_np]
    for i, g_step in enumerate(grads_np):
        want, m, v = _reference_lamb_step(
            want, g_step, m, v, i + 1, 1e-2, 0.9, 0.999, 1e-6, wd, 1.0, False)
    opt = FusedLAMB([jnp.asarray(p) for p in params_np], lr=1e-2,
                    weight_decay=wd, eps=1e-6, max_grad_norm=1.0)
    got = _run_jax(opt, grads_np)
    for w, g in zip(want, got):
        np.testing.assert_allclose(w, g, rtol=1e-4, atol=1e-5)


def test_fused_novograd_decreases_loss():
    target = np.zeros((8,), np.float32)
    p = [jnp.asarray(np.full((8,), 5.0, np.float32))]
    # NovoGrad normalizes grads per layer, so steps are ~lr/sqrt(dim) in
    # magnitude regardless of loss scale — needs a macroscopic lr on this toy.
    opt = FusedNovoGrad(p, lr=0.5, weight_decay=0.0, grad_averaging=True,
                        bias_correction=False)
    losses = []
    for _ in range(60):
        cur = opt.param_groups[0]["params"][0]
        losses.append(float(jnp.sum((cur - target) ** 2)))
        g = 2 * (cur - target)
        opt.step([g])
    assert losses[-1] < 0.1 * losses[0]


def test_fused_mixed_precision_lamb_halfparams():
    params = [jnp.asarray(np.random.RandomState(5).randn(4, 4), jnp.bfloat16)]
    opt = FusedMixedPrecisionLamb(params, lr=1e-2)
    g = [jnp.ones((4, 4), jnp.bfloat16)]
    out = opt.step(g)
    assert out[0].dtype == jnp.bfloat16
    # master state is fp32
    assert opt.state[0].master_flat.dtype == jnp.float32


def test_optax_transform_interface():
    import optax
    params = {"a": jnp.ones((3,)), "b": jnp.full((2, 2), 2.0)}
    tx = fused_adam(learning_rate=1e-2)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    @jax.jit
    def step(params, state, grads):
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    p2, state = step(params, state, grads)
    assert float(p2["a"][0]) < 1.0


def test_param_groups():
    p1 = [jnp.ones((3,))]
    p2 = [jnp.full((2,), 2.0)]
    opt = FusedAdam([{"params": p1, "lr": 0.1}, {"params": p2, "lr": 0.0}],
                    lr=1e-3)
    g = [[jnp.ones((3,))], [jnp.ones((2,))]]
    out = opt.step(g)
    assert float(out[0][0][0]) < 1.0
    np.testing.assert_allclose(np.asarray(out[1][0]), [2.0, 2.0])  # lr=0 group


def _reference_novograd_step(params, grads, m, v, step, lr, b1, b2, eps, wd,
                             grad_averaging, reg_inside_moment):
    """NumPy reference of multi_tensor_novograd.cu semantics (v stores the
    norm, bc2 = sqrt(1-b2^t), MODE_0 = decay inside moment)."""
    new_params, new_m, new_v = [], [], []
    beta3 = (1 - b1) if grad_averaging else 1.0
    bc1 = 1 - b1 ** step
    bc2 = np.sqrt(1 - b2 ** step)
    for p, g, mi, vi in zip(params, grads, m, v):
        n = np.linalg.norm(g.ravel())
        vi = n if step == 1 else np.sqrt(b2 * vi ** 2 + (1 - b2) * n ** 2)
        denom = vi / bc2 + eps
        if reg_inside_moment:
            rg = g / denom + wd * p
            mi = b1 * mi + beta3 * rg
            p = p - lr * mi / bc1
        else:
            mi = b1 * mi + beta3 * g
            p = p - lr * ((mi / bc1) / denom + wd * p)
        new_params.append(p)
        new_m.append(mi)
        new_v.append(vi)
    return new_params, new_m, new_v


@pytest.mark.parametrize("reg_inside", [False, True])
def test_fused_novograd_vs_reference(reg_inside):
    params_np, grads_np = _gen(7)
    m = [np.zeros_like(p) for p in params_np]
    v = [0.0 for p in params_np]
    want = [p.copy() for p in params_np]
    for i, g_step in enumerate(grads_np):
        want, m, v = _reference_novograd_step(
            want, g_step, m, v, i + 1, 1e-2, 0.9, 0.999, 1e-8, 0.01,
            True, reg_inside)
    opt = FusedNovoGrad([jnp.asarray(p) for p in params_np], lr=1e-2,
                        weight_decay=0.01, reg_inside_moment=reg_inside)
    got = _run_jax(opt, grads_np)
    for w, g in zip(want, got):
        np.testing.assert_allclose(w, g, rtol=1e-4, atol=1e-5)


def test_fused_lamb_l2_mode_applies_decay():
    # adam_w_mode=False must fold decay into the gradient (MOMENT_MODE_0)
    params = [jnp.full((4,), 2.0)]
    opt_l2 = FusedLAMB([params[0]], lr=1e-2, weight_decay=0.1,
                       adam_w_mode=False, max_grad_norm=0.0)
    opt_nodecay = FusedLAMB([params[0]], lr=1e-2, weight_decay=0.0,
                            adam_w_mode=False, max_grad_norm=0.0)
    g = [jnp.full((4,), 0.5)]
    out_l2 = opt_l2.step(g)
    out_nd = opt_nodecay.step(g)
    assert not np.allclose(np.asarray(out_l2[0]), np.asarray(out_nd[0])), \
        "weight_decay had no effect in L2 mode"


def test_unscale_preserves_small_fp16_grads():
    # fp16 grad of 1.0 at scale 2**16 unscales to ~1.5e-5; a further cast
    # back to fp16 would keep it, but 1e-3 → 1.5e-8 underflows fp16.
    from apex_tpu.amp import LossScaler
    s = LossScaler(loss_scale=2.0 ** 16)
    st = s.init()
    g = {"w": jnp.asarray([1e-3 * 2 ** 16], jnp.float16)}
    unscaled, found_inf = s.unscale(g, st)
    assert unscaled["w"].dtype == jnp.float32
    assert not bool(found_inf)
    np.testing.assert_allclose(np.asarray(unscaled["w"]), [1e-3], rtol=1e-3)


# ---- FusedLAMB one-pass flat-buffer impl (APEX_LAMB_IMPL) ----
# The compute-structure knob must be a pure re-structuring: identical
# state layout, same update values (up to flat-vs-per-leaf reduction
# order) — so the profile_optimizers A/B row compares like with like.

def _lamb_tree(seed=0, bf16_leaf=False):
    rng = np.random.RandomState(seed)
    params = {
        "a": jnp.asarray(rng.randn(6, 9), jnp.float32),
        "b": {"w": jnp.asarray(rng.randn(17), jnp.float32),
              "x": jnp.asarray(rng.randn(2, 3, 4), jnp.float32)},
    }
    if bf16_leaf:
        params["h"] = jnp.asarray(rng.randn(8, 5), jnp.bfloat16)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            rng.randn(*p.shape).astype(np.float32) * 1e-2, p.dtype), params)
    return params, grads


@pytest.mark.parametrize("kwargs", [
    dict(),
    dict(adam_w_mode=False),
    dict(weight_decay=0.0),                   # trust-ratio-off branch
    dict(weight_decay=0.0, use_nvlamb=True),  # ...unless nvlamb
    dict(max_grad_norm=0.0),                  # no global clip
    dict(bias_correction=False, grad_averaging=False),
])
def test_fused_lamb_one_pass_matches_two_pass(kwargs):
    import optax
    from apex_tpu.optimizers.fused_lamb import fused_lamb

    params, grads = _lamb_tree(bf16_leaf=True)
    tx2 = fused_lamb(1e-2, impl="two_pass", **kwargs)
    tx1 = fused_lamb(1e-2, impl="one_pass", **kwargs)
    p2, s2 = params, tx2.init(params)
    p1, s1 = params, tx1.init(params)
    for _ in range(3):  # trajectory, not just one step (bias correction)
        u2, s2 = tx2.update(grads, s2, p2)
        p2 = optax.apply_updates(p2, u2)
        u1, s1 = tx1.update(grads, s1, p1)
        p1 = optax.apply_updates(p1, u1)
    for a, b in zip(jax.tree_util.tree_leaves(p2),
                    jax.tree_util.tree_leaves(p1)):
        assert a.dtype == b.dtype
        tol = 2e-2 if a.dtype == jnp.bfloat16 else 2e-5
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=tol, atol=tol)
    # state layout identical: the knob is freely A/B-able mid-run
    assert (jax.tree_util.tree_structure(s2)
            == jax.tree_util.tree_structure(s1))


def test_fused_lamb_impl_knob_resolution(monkeypatch):
    from apex_tpu.optimizers.fused_lamb import _resolve_impl, _table_impl

    monkeypatch.delenv("APEX_LAMB_IMPL", raising=False)
    # unset = UNPINNED (None): resolved per parameter set at trace time
    # — dispatch-table consult, whose miss is the measured two_pass seat
    assert _resolve_impl(None) is None
    monkeypatch.setenv("APEX_DISPATCH", "off")
    assert _table_impl([jnp.zeros((4, 4))]) == "two_pass"
    monkeypatch.delenv("APEX_DISPATCH", raising=False)
    monkeypatch.setenv("APEX_LAMB_IMPL", "one_pass")
    assert _resolve_impl(None) == "one_pass"  # process-wide preference
    assert _resolve_impl("two_pass") == "two_pass"  # explicit arg wins
    # explicit request ≠ preference: a bad explicit value raises...
    with pytest.raises(ValueError):
        _resolve_impl("flat")
    # ...and so does a bad env value (it would silently mislabel an A/B)
    monkeypatch.setenv("APEX_LAMB_IMPL", "bogus")
    with pytest.raises(ValueError):
        _resolve_impl(None)
