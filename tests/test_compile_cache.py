"""Persistent compile-cache + warm-start subsystem (apex_tpu.compile_cache).

The contract under test is the one that makes BENCH scoreable: a program
compiled by ONE process (the probe-time warm) must be served from the
persistent cache to a SECOND, cold process (the driver-scored bench
attempt) — and the telemetry block proving it must be well-formed in the
bench JSON line and the run ledger, with the knob both on and off.

The two-process demonstration uses the real bench program (bench.py in
``APEX_WARM_ONLY=1`` CPU-smoke mode — the same make_one_step scan the
scored run measures, at smoke shapes), spawned exactly the way all local
CPU work must be spawned here (``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu``,
CLAUDE.md relay rule).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _last_json  # noqa: E402  (the ONE driver-line parser)


def _last_rec(text):
    return _last_json(text)[1]


def _spawn_bench(cache_dir, extra_env, args=(), timeout=420):
    env = dict(os.environ)
    # isolate from any ambient telemetry/ledger knobs (the caller's
    # extra_env below re-adds what the test actually wants)
    for k in ("APEX_TELEMETRY", "APEX_TELEMETRY_LEDGER"):
        env.pop(k, None)
    env.update(APEX_BENCH_SMOKE="1",
               PALLAS_AXON_POOL_IPS="",   # never dial the relay locally
               JAX_PLATFORMS="cpu",
               APEX_COMPILE_CACHE_DIR=str(cache_dir),
               **extra_env)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env)
    return out


def test_second_process_served_from_persistent_cache(tmp_path):
    """Process A compiles the bench-shaped program into a fresh cache
    dir; a cold process B gets every program — including the big step
    scan — as a cache hit, counted in the new telemetry."""
    cache = tmp_path / "cache"
    out1 = _spawn_bench(cache, {"APEX_WARM_ONLY": "1",
                                "APEX_COMPILE_CACHE": "1"})
    assert out1.returncode == 0, out1.stderr[-2000:]
    rec1 = _last_rec(out1.stdout)
    assert rec1 and rec1.get("warm_only") is True, out1.stdout[-2000:]
    assert rec1["warm"]["step_scan"]["cached"] is False  # cold compile
    assert rec1["compile_cache"]["enabled"] is True
    assert rec1["compile_cache"]["misses"] > 0

    out2 = _spawn_bench(cache, {"APEX_WARM_ONLY": "1",
                                "APEX_COMPILE_CACHE": "1"})
    assert out2.returncode == 0, out2.stderr[-2000:]
    rec2 = _last_rec(out2.stdout)
    assert rec2["warm"]["step_scan"]["cached"] is True, rec2
    cc = rec2["compile_cache"]
    assert cc["hits"] > 0, cc
    assert cc["misses"] == 0, cc  # identical process: every key warm
    assert cc["dir"] == str(cache)
    assert cc["warm_age_s"] is not None and cc["warm_age_s"] >= 0


def test_bench_json_carries_compile_cache_block_on_and_off(
        tmp_path, shared_smoke_cache_dir):
    """The scored smoke line (exactly ONE JSON line — the driver
    contract) carries a well-formed compile_cache block with the knob on
    (via the ``--smoke`` CLI alias) and with the escape hatch thrown.
    The ON leg compiles into the suite-wide shared smoke cache
    (tests/conftest.py) — the chaos deep-path tests then reuse the
    executable instead of re-compiling it (fast-tier budget); the
    assertions here are cache-state-agnostic (hits + misses > 0)."""
    from apex_tpu.telemetry import ledger

    for on in (True, False):
        out = _spawn_bench(
            shared_smoke_cache_dir,
            {"APEX_BENCH_INNER": "1",
             "APEX_COMPILE_CACHE": "1" if on else "0",
             "APEX_TELEMETRY_LEDGER": str(tmp_path / "ledger.jsonl")},
            args=("--smoke",))
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
        assert len(lines) == 1, out.stdout[-2000:]
        rec = json.loads(lines[0])
        assert "error" not in rec, rec
        cc = rec["compile_cache"]
        assert set(cc) == {"enabled", "dir", "hits", "misses",
                           "warm_age_s"}, cc
        assert cc["enabled"] is on
        if on:
            assert isinstance(cc["dir"], str)
            assert cc["hits"] + cc["misses"] > 0
        else:
            assert cc["dir"] is None
            assert cc["hits"] == 0 and cc["misses"] == 0
            assert cc["warm_age_s"] is None
        # ...and the ledger record carrying the block validates.
        # warm_age_s is wall-clock (the two snapshots are taken ms
        # apart), so compare the block modulo that field.
        records = ledger.read_ledger(str(tmp_path / "ledger.jsonl"))
        mine = [r for r in records if r["id"] == rec["ledger_id"]]
        assert mine, records
        lcc = dict(mine[0]["compile_cache"])
        age = lcc.pop("warm_age_s")
        assert lcc == {k: v for k, v in cc.items() if k != "warm_age_s"}
        assert age is None or age >= 0
        assert ledger.validate_record(mine[0]) == []


def test_ledger_validates_compile_cache_block():
    """Schema teeth: a malformed compile_cache block (which could
    silently claim a number was compile-free) is a finding."""
    from apex_tpu.telemetry import ledger

    def rec_with(cc):
        return ledger.make_record("bench", "cpu", 1.0, 16,
                                  extra={"compile_cache": cc})

    good = {"enabled": True, "dir": "/x", "hits": 3, "misses": 0,
            "warm_age_s": 12.5}
    assert ledger.validate_record(rec_with(good)) == []
    off = {"enabled": False, "dir": None, "hits": 0, "misses": 0,
           "warm_age_s": None}
    assert ledger.validate_record(rec_with(off)) == []

    for bad in (
        "yes",                                      # not a dict
        dict(good, enabled="yes"),                  # enabled not bool
        dict(good, hits=-1),                        # negative counter
        dict(good, misses=None),                    # missing counter
        dict(good, dir=7),                          # dir not a string
        dict(good, warm_age_s="old"),               # age not numeric
    ):
        assert ledger.validate_record(rec_with(bad)) != [], bad


def test_activate_respects_knobs_and_snapshot_shape(tmp_path, monkeypatch):
    """In-process unit surface: requested() tri-state, activate()
    default/escape-hatch resolution, snapshot() well-formedness in both
    states. State is restored so the rest of the suite is unaffected."""
    from apex_tpu import compile_cache as cc

    monkeypatch.setenv("APEX_COMPILE_CACHE_DIR", str(tmp_path / "d"))
    try:
        monkeypatch.delenv("APEX_COMPILE_CACHE", raising=False)
        assert cc.requested() is None
        monkeypatch.setenv("APEX_COMPILE_CACHE", "garbage")
        assert cc.requested() is None  # preference, not a per-call raise
        monkeypatch.setenv("APEX_COMPILE_CACHE", "1")
        assert cc.requested() is True

        monkeypatch.setenv("APEX_COMPILE_CACHE", "0")
        assert cc.activate(default_on=True) is False  # escape hatch wins
        snap = cc.snapshot()
        assert snap == {"enabled": False, "dir": None, "hits": snap["hits"],
                        "misses": snap["misses"], "warm_age_s": None}

        monkeypatch.delenv("APEX_COMPILE_CACHE", raising=False)
        assert cc.activate(default_on=True) is True   # caller default
        snap = cc.snapshot()
        assert snap["enabled"] is True
        assert snap["dir"] == str(tmp_path / "d")
        assert os.path.isdir(snap["dir"])  # created on activation
        assert isinstance(snap["hits"], int) and isinstance(
            snap["misses"], int)
    finally:
        # leave the suite's process with the cache hard-off
        monkeypatch.setenv("APEX_COMPILE_CACHE", "0")
        cc.activate(default_on=False)
        cc._reset_for_tests()
