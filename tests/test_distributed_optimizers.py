"""ZeRO-sharded optimizer tests.

The correctness bar (reference: contrib tests for
DistributedFusedAdam/LAMB): sharded update == unsharded fused update, with
optimizer state 1/N per shard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    distributed_fused_adam,
    distributed_fused_lamb,
)
from apex_tpu.optimizers.fused_adam import fused_adam
from apex_tpu.optimizers.fused_lamb import fused_lamb

NDEV = 8


def _params():
    rs = np.random.RandomState(0)
    return {
        "a": jnp.asarray(rs.randn(13, 7), jnp.float32),   # odd sizes: test
        "b": jnp.asarray(rs.randn(5,), jnp.float32),      # shard padding +
        "c": jnp.asarray(rs.randn(3, 3, 3), jnp.float32), # boundary spans
    }


def _grads():
    rs = np.random.RandomState(1)
    return {
        "a": jnp.asarray(rs.randn(13, 7), jnp.float32),
        "b": jnp.asarray(rs.randn(5,), jnp.float32),
        "c": jnp.asarray(rs.randn(3, 3, 3), jnp.float32),
    }


def _run_sharded(dist_tx, params, grads, steps=3):
    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def run(params, grads):
        state = dist_tx.init(params)
        for _ in range(steps):
            updates, state = dist_tx.update(grads, state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, jnp.asarray(state.m.shape[0])

    # grads replicated: every rank contributes the same grad; the internal
    # reduce-scatter sums then averages over num_shards
    f = shard_map(run, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
                  check_vma=False)
    params, shard_len = f(params, grads)
    return params, int(shard_len)


def _run_reference(tx, params, grads, steps=3):
    state = tx.init(params)
    for _ in range(steps):
        updates, state = tx.update(grads, state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
    return params


@pytest.mark.slow  # compile-heavy exact parity; the distinct-rank-grads
# reduction test keeps the ZeRO mechanism in the fast tier
def test_distributed_adam_matches_fused_adam():
    params, grads = _params(), _grads()
    dist = distributed_fused_adam(learning_rate=0.1, weight_decay=0.01,
                                  num_shards=NDEV, axis_name="dp")
    ref = fused_adam(learning_rate=0.1, weight_decay=0.01)
    got, shard_len = _run_sharded(dist, params, grads)
    want = _run_reference(ref, params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]),
                                   np.asarray(want[k]), rtol=2e-5,
                                   atol=1e-6)
    # ZeRO: state is 1/N (padded)
    total = sum(int(np.prod(v.shape)) for v in params.values())
    assert shard_len == (total + NDEV - 1) // NDEV * NDEV // NDEV


@pytest.mark.slow  # compile-heavy; the fwd/adam parity siblings stay fast
def test_distributed_lamb_matches_fused_lamb():
    params, grads = _params(), _grads()
    dist = distributed_fused_lamb(learning_rate=0.01, weight_decay=0.01,
                                  max_grad_norm=1.0, num_shards=NDEV,
                                  axis_name="dp")
    ref = fused_lamb(learning_rate=0.01, weight_decay=0.01,
                     max_grad_norm=1.0)
    got, _ = _run_sharded(dist, params, grads)
    want = _run_reference(ref, params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]),
                                   np.asarray(want[k]), rtol=2e-4,
                                   atol=1e-6)


def test_distributed_adam_reduces_distinct_rank_grads():
    """Per-rank distinct grads → behaves like mean of grads (the DDP+ZeRO
    composition). 2 shards, not 8: the psum_scatter/all_gather mechanics
    are shard-count-independent and the 8-way program costs 3x the
    compile (fast-tier budget, CLAUDE.md)."""
    n = 2
    params = {"w": jnp.zeros((16,), jnp.float32)}
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    dist = distributed_fused_adam(learning_rate=0.1, num_shards=n,
                                  axis_name="dp")
    ref = fused_adam(learning_rate=0.1)

    # rank r grad = (r+1) * ones → mean = 1.5
    per_rank = jnp.stack([jnp.full((16,), float(r + 1))
                          for r in range(n)])

    def run(params, my_grad):
        g = {"w": my_grad[0]}
        state = dist.init(params)
        updates, state = dist.update(g, state, params)
        return jax.tree_util.tree_map(jnp.add, params, updates)

    got = shard_map(run, mesh=mesh, in_specs=(P(), P("dp")),
                    out_specs=P(), check_vma=False)(params, per_rank)
    state = ref.init(params)
    updates, _ = ref.update({"w": jnp.full((16,), 1.5)}, state, params)
    want = jax.tree_util.tree_map(jnp.add, params, updates)
    np.testing.assert_allclose(np.asarray(got["w"]),
                               np.asarray(want["w"]), rtol=1e-5)
