"""Fused linear+cross-entropy kernel vs the jnp reference (interpret
mode on CPU; TPU timing in benchmarks/profile_xent.py). Reference
envelope: contrib/csrc/xentropy parity tests (apex_tpu's
contrib/xentropy covers the materialized-logits form; this kernel fuses
the LM-head matmul in as well)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops import xent_pallas as xp


def _ref(x, e, labels):
    logits = (x.astype(jnp.float32) @ e.astype(jnp.float32).T)
    lse = jax.scipy.special.logsumexp(logits, axis=1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - tgt


def _data(rs, n, V, h, dtype):
    x = jnp.asarray(rs.randn(n, h) * 0.3, dtype)
    e = jnp.asarray(rs.randn(V, h) * 0.3, dtype)
    labels = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)
    return x, e, labels


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fwd_matches_reference(dtype):
    n, V, h = 64, 768, 128  # two vocab chunks
    rs = np.random.RandomState(0)
    x, e, labels = _data(rs, n, V, h, dtype)
    assert xp.supported(n, V, h)
    got = xp.linear_cross_entropy(x, e, labels, True)
    want = _ref(x, e, labels)
    assert got.shape == (n,) and got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=5e-2 if dtype == jnp.bfloat16 else 1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grads_match_reference(dtype):
    """Multi row-block + multi vocab-chunk grid; non-uniform upstream
    cotangent exercises the dl plumbing in both bwd kernels."""
    n, V, h = 1024, 1280, 128  # nb=2 (row-block 512), nv=5 (chunk 256)
    assert xp._row_block(n, h, xp._v_chunk(V)) == 512  # keep nb > 1
    rs = np.random.RandomState(1)
    x, e, labels = _data(rs, n, V, h, dtype)
    w = jnp.asarray(rs.rand(n) + 0.5, jnp.float32)

    def f(x, e):
        return jnp.mean(w * xp.linear_cross_entropy(x, e, labels, True))

    def r(x, e):
        return jnp.mean(w * _ref(x, e, labels))

    gx, ge = jax.grad(f, argnums=(0, 1))(x, e)
    rx, re = jax.grad(r, argnums=(0, 1))(x, e)
    assert gx.dtype == dtype and ge.dtype == dtype
    tol = 6e-3 if dtype == jnp.bfloat16 else 1e-6
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), atol=tol)
    np.testing.assert_allclose(np.asarray(ge, np.float32),
                               np.asarray(re, np.float32), atol=tol)


def test_value_and_grad_through_mean_loss():
    """The way a training step consumes it: scalar mean loss, finite and
    equal to the reference, and the loss decreases under a GD step."""
    n, V, h = 128, 384, 128
    rs = np.random.RandomState(2)
    x, e, labels = _data(rs, n, V, h, jnp.float32)

    def f(e):
        return jnp.mean(xp.linear_cross_entropy(x, e, labels, True))

    l0, g = jax.value_and_grad(f)(e)
    np.testing.assert_allclose(float(l0),
                               float(jnp.mean(_ref(x, e, labels))),
                               rtol=1e-6)
    l1 = f(e - 0.5 * g)
    assert float(l1) < float(l0)


@pytest.mark.slow
def test_gpt_model_fused_head_matches_materialized():
    """cfg.fused_lm_head swaps the GPT loss head for the fused kernel;
    loss and grads must match the materialized logits+CE path."""
    import dataclasses

    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    base = TransformerConfig(
        hidden_size=128, num_layers=2, num_attention_heads=4,
        vocab_size=384, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0)
    fused = dataclasses.replace(base, fused_lm_head=True,
                                fused_lm_head_interpret=True)
    rs = np.random.RandomState(0)
    b, s = 2, 64
    ids = jnp.asarray(rs.randint(0, 384, (b, s)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    labels = jnp.asarray(rs.randint(0, 384, (b, s)), jnp.int32)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))

    def run(cfg):
        model = GPTModel(cfg)

        def local(ids, pos, labels):
            params = model.init(jax.random.PRNGKey(0), ids, pos, None)[
                "params"]

            def loss_fn(p):
                return jnp.mean(model.apply({"params": p}, ids, pos, None,
                                            labels))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            gnorm = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                        for g in jax.tree_util.tree_leaves(grads))
            return loss, gnorm

        return jax.shard_map(local, mesh=mesh, in_specs=(P(),) * 3,
                             out_specs=P(), check_vma=False)(
            ids, pos, labels)

    l_ref, g_ref = run(base)
    l_fused, g_fused = run(fused)
    np.testing.assert_allclose(float(l_fused), float(l_ref), rtol=1e-6)
    np.testing.assert_allclose(float(g_fused), float(g_ref), rtol=1e-5)


def test_supported_predicate():
    assert xp.supported(8192, 50304, 768)      # GPT-2 bench shape
    assert xp.supported(8192, 30592, 1024)     # BERT-large padded vocab
    assert not xp.supported(8192, 50000, 768)  # no 128-multiple divisor
    assert not xp.supported(7, 50304, 768)     # rows not 8-divisible
    assert not xp.supported(8192, 50304, 760)  # lane-unaligned hidden


# --------------------- vocab-parallel (sharded) head -----------------------

def test_sharded_matches_full_table_with_grads():
    """linear_cross_entropy_sharded over tp=4 vocab shards == the
    single-slab kernel on the full table: loss, dX (psum'd), and the
    concatenated dE shards."""
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n, h, V, tp = 64, 128, 512, 4
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(n, h), jnp.float32)
    e = jnp.asarray(rs.randn(V, h) * 0.1, jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)
    g = jnp.asarray(rs.randn(n), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def sharded(x, e, labels, g):
        def f(args):
            xx, ee = args
            loss = xp.linear_cross_entropy_sharded(
                xx, ee, labels, "tp", True)
            return jnp.sum(loss * g), loss

        (_, loss), grads = jax.value_and_grad(f, has_aux=True)((x, e))
        return loss, grads[0], grads[1]

    loss_s, dx_s, de_s = shard_map(
        sharded, mesh=mesh, in_specs=(P(), P("tp"), P(), P()),
        out_specs=(P(), P(), P("tp")), check_vma=False)(x, e, labels, g)

    def full(args):
        xx, ee = args
        loss = xp.linear_cross_entropy(xx, ee, labels, True)
        return jnp.sum(loss * g), loss

    (_, loss_f), (dx_f, de_f) = jax.value_and_grad(
        full, has_aux=True)((x, e))

    np.testing.assert_allclose(np.asarray(loss_s), np.asarray(loss_f),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(dx_f),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(de_s), np.asarray(de_f),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow  # cross-impl consistency; the sharded-vs-full-table
# parity (with grads) stays fast
def test_sharded_matches_vocab_parallel_materialized():
    """...and the materialized vocab-parallel CE (the tensor_parallel
    reference surface) on the same shards."""
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )

    n, h, V, tp = 64, 128, 512, 4
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(n, h), jnp.float32)
    e = jnp.asarray(rs.randn(V, h) * 0.1, jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def both(x, e, labels):
        fused = xp.linear_cross_entropy_sharded(
            x, e, labels, "tp", True)
        logits_shard = (x @ e.T)[None]  # [1, n, V/tp]
        mat = vocab_parallel_cross_entropy(
            logits_shard, labels[None], axis_name="tp")[0]
        return fused, mat

    fused, mat = shard_map(
        both, mesh=mesh, in_specs=(P(), P("tp"), P()),
        out_specs=(P(), P()), check_vma=False)(x, e, labels)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(mat),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # ~60s/param model compile; the kernel-level sharded
# parity tests above keep the vocab-parallel head in the fast tier
@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_gpt_fused_head_tp2_matches_materialized(sequence_parallel):
    """GPTModel with fused_lm_head under tp=2 (optionally with sequence
    parallelism — the pre-matmul gather composing with reduce_dx=False):
    per-token losses and embedding grads match the materialized
    vocab-parallel path."""
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    b, s = 2, 64
    kw = dict(hidden_size=128, num_layers=1, num_attention_heads=2,
              vocab_size=512, max_position_embeddings=s,
              hidden_dropout=0.0, attention_dropout=0.0,
              sequence_parallel=sequence_parallel)
    m_fused = GPTModel(TransformerConfig(
        fused_lm_head=True, fused_lm_head_interpret=True, **kw))
    m_mat = GPTModel(TransformerConfig(**kw))
    mesh = Mesh(np.array(jax.devices()[:2]), (TENSOR_AXIS,))
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 512, (b, s)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, 512, (b, s)), jnp.int32)

    def run(model):
        def f(ids, pos, labels):
            params = model.init(jax.random.PRNGKey(0), ids, pos,
                                None)["params"]

            def loss_fn(p):
                per_tok = model.apply({"params": p}, ids, pos, None,
                                      labels)
                return jnp.mean(per_tok), per_tok

            (_, per_tok), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return per_tok, grads["embedding"]["position_embeddings"]

        return shard_map(f, mesh=mesh, in_specs=(P(), P(), P()),
                         out_specs=(P(), P()), check_vma=False)(
            ids, pos, labels)

    lt_f, g_f = run(m_fused)
    lt_m, g_m = run(m_mat)
    np.testing.assert_allclose(np.asarray(lt_f), np.asarray(lt_m),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_m),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("smoothing", [0.1, 0.3])
def test_smoothing_matches_contrib_xentropy(smoothing):
    """Fused-head label smoothing == contrib xentropy's materialized
    reference ((1-eps)*nll + eps*(lse - mean logits)): loss and both
    grads."""
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    n, h, V = 64, 128, 512
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(n, h), jnp.float32)
    e = jnp.asarray(rs.randn(V, h) * 0.1, jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)
    g = jnp.asarray(rs.randn(n), jnp.float32)

    def fused(args):
        xx, ee = args
        return jnp.sum(xp.linear_cross_entropy(
            xx, ee, labels, True, smoothing) * g)

    def ref(args):
        xx, ee = args
        return jnp.sum(softmax_cross_entropy_loss(
            xx @ ee.T, labels, smoothing=smoothing) * g)

    lf, (dxf, def_) = jax.value_and_grad(fused)((x, e))
    lr, (dxr, der) = jax.value_and_grad(ref)((x, e))
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(def_), np.asarray(der),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow  # sharded smoothing consistency; unsharded smoothing
# parity and sharded unsmoothed parity stay fast
def test_smoothing_sharded_matches_full():
    """Sharded smoothing: the uniform term's logits-sum partials psum
    into the same global correction."""
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n, h, V, tp, eps = 64, 128, 512, 4, 0.2
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(n, h), jnp.float32)
    e = jnp.asarray(rs.randn(V, h) * 0.1, jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)
    g = jnp.asarray(rs.randn(n), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def sharded(x, e, labels, g):
        def f(args):
            xx, ee = args
            return jnp.sum(xp.linear_cross_entropy_sharded(
                xx, ee, labels, "tp", True, eps) * g)

        l, grads = jax.value_and_grad(f)((x, e))
        return l, grads[0], grads[1]

    l_s, dx_s, de_s = shard_map(
        sharded, mesh=mesh, in_specs=(P(), P("tp"), P(), P()),
        out_specs=(P(), P(), P("tp")), check_vma=False)(x, e, labels, g)

    def full(args):
        xx, ee = args
        return jnp.sum(xp.linear_cross_entropy(
            xx, ee, labels, True, eps) * g)

    l_f, (dx_f, de_f) = jax.value_and_grad(full)((x, e))
    np.testing.assert_allclose(float(l_s), float(l_f), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(dx_f),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(de_s), np.asarray(de_f),
                               atol=1e-5, rtol=1e-4)
