"""Serving resilience chaos suite (ISSUE 15): every recovery path
driven through the REAL ServingEngine on CPU with deterministic fault
plans (``apex_tpu.resilience.faults`` serve_* sites), the same honesty
rules as the collection chaos suite — and the acceptance invariants:

* submit-reject is STRUCTURED (a ``Rejected`` return, never an
  exception escaping the loop) under a scripted burst overload;
* KV-exhaustion preempts and replays token-for-token (natural page
  pressure AND a scripted ``serve_alloc`` deny) with clean
  allocator/prefix invariants across the churn;
* a hung decode dispatch is timed out + classified (``wedged``), a
  crashing one classified ``degraded_relay``, and the engine finishes
  the remaining requests either way — bounded by the round-attempt
  budget (a persistently dead device still fails loudly);
* disabled mode (all four knobs off) is token-for-token identical to
  the all-knobs-on engine under no pressure;
* the one-compile contract (``decode_cache_size()==1``,
  ``prefill_cache_size()<=1``) holds under every enabled combination.
"""

import json

import pytest

from apex_tpu.resilience import faults
from apex_tpu.serving import (
    Rejected,
    Request,
    ServingEngine,
    lifecycle,
)
from apex_tpu.serving import resilience as serve_res


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from apex_tpu.serving import model as smodel

    params = smodel.init_gpt_params(cfg)
    # the uncontended reference streams every parity test pins against
    ref = ServingEngine(cfg, params=params, num_slots=2, page_size=4,
                        num_pages=32, max_seq=32, prefill_len=16)
    reqs = _requests()
    _drive(ref, reqs)
    return cfg, params, {r.rid: list(r.out_tokens) for r in reqs}


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Plan isolation: no fault plan leaks in, and the per-plan
    ``times`` spend counters reset between tests (two tests sharing a
    plan string must each get the full budget)."""
    monkeypatch.delenv("APEX_FAULT_PLAN", raising=False)
    faults._cache["fired"] = {}
    yield
    faults._cache["fired"] = {}


def _requests():
    return [Request(rid=0, prompt=[1, 2, 3, 4, 5, 6],
                    max_new_tokens=10),
            Request(rid=1, prompt=[7, 8, 9, 10, 11, 12],
                    max_new_tokens=10)]


def _drive(eng, reqs, guard=300):
    for r in reqs:
        eng.submit(r)
    n = 0
    while not all(r.done() for r in reqs):
        eng.step()
        n += 1
        assert n < guard, ("engine did not drain",
                           [r.out_tokens for r in reqs])
    eng.step()


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 16)
    return ServingEngine(cfg, params=params, **kw)


def _assert_contract(eng):
    assert eng.decode_cache_size() == 1, eng.decode_cache_size()
    assert eng.prefill_cache_size() <= 1, eng.prefill_cache_size()
    eng.allocator.check_invariants()
    if eng.prefix is not None:
        eng.prefix.check_invariants()


def _plan(monkeypatch, plan):
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(plan))


# ---------------------------------------------------- disabled parity


def test_all_knobs_on_token_identical_without_pressure(setup):
    """The disabled-mode acceptance, stated as its strong converse:
    an engine with EVERY resilience layer armed but nothing
    triggering it (roomy pool, bounded-but-unfull queue, healthy
    dispatches) produces token-for-token the plain engine's streams —
    so the layers are pure additions, not behavior drift."""
    cfg, params, ref = setup
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, admit=16, shed=True, preempt=True,
                      recover=True, dispatch_timeout_s=60,
                      round_retry_wait_s=0)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    _drive(eng, reqs)
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    stats = eng.resilience
    assert (stats.rejected, stats.shed, stats.preempted,
            stats.degraded_rounds) == (0, 0, 0, 0), stats
    assert eng.events.validate_order() == []
    _assert_contract(eng)
    # enabled-but-idle rates are 0.0 / None-never: the slo surface
    assert eng.resilience_rates() == {"shed_rate": 0.0,
                                      "preempt_rate": 0.0,
                                      "degraded_rounds": 0}


# ------------------------------------------- admission control / shed


def test_burst_overload_rejects_structurally(setup, monkeypatch):
    """A scripted submit storm (serve_burst site) against a bounded
    queue: the engine REJECTS the overflow with structured Rejected
    events — no exception ever escapes step(), and the original trace
    still drains to completion with parity."""
    cfg, params, ref = setup
    _plan(monkeypatch, [{"site": "serve_burst", "kind": "burst",
                         "count": 12, "prompt_len": 3, "max_new": 4,
                         "match_ctx": {"tick": 1}}])
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, admit=3)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.rejected > 0
    for req, rej in eng.rejected:
        assert isinstance(rej, Rejected)
        assert rej.reason == "queue_full"
        assert rej.retry_after_ticks >= 1
        chain = [e["event"] for e in eng.events.request_events(req.rid)]
        assert chain == ["submitted", "rejected"], chain
    for r in reqs:
        assert r.out_tokens == ref[r.rid]
    assert eng.events.validate_order() == []
    _assert_contract(eng)


def test_direct_submit_reject_and_off_mode(setup):
    cfg, params, _ = setup
    eng = _engine(cfg, params, num_slots=1, admit=2)
    rs = [Request(rid=i, prompt=[1, 2, 3], max_new_tokens=4)
          for i in range(5)]
    results = [eng.submit(r) for r in rs]
    assert [isinstance(x, Rejected) for x in results] \
        == [False, False, True, True, True]
    # admission control must never mask a malformed request
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(Request(rid=9, prompt=[1], max_new_tokens=0))
    # off mode: the unbounded queue serving always had
    off = _engine(cfg, params, num_slots=1)
    assert all(off.submit(Request(rid=i, prompt=[1, 2],
                                  max_new_tokens=2)) is None
               for i in range(10, 20))


def test_shed_drops_only_hopeless_requests(setup):
    """The deadline shedder: a queued request whose wait already
    exceeds the TTFT threshold is dropped (attainment impossible) —
    with a `shed` event, while requests that got their first token
    are never shed. run_trace counts shed requests as settled."""
    cfg, params, _ = setup
    lifecycle.enable()
    try:
        # 1 slot, long generations: rid 1/2 wait behind rid 0 past
        # the (tiny) threshold and must shed
        eng = _engine(cfg, params, num_slots=1, shed=True,
                      shed_ttft_ms=1.0)
    finally:
        lifecycle.reset_enabled()
    reqs = [Request(rid=i, prompt=[1 + i, 2, 3], max_new_tokens=12,
                    arrival=0) for i in range(3)]
    done = eng.run_trace(reqs)
    assert eng.resilience.shed > 0
    assert len(done) + len(eng.scheduler.shed) == 3
    for r in eng.scheduler.shed:
        assert not r.out_tokens  # only first-token-less requests shed
        chain = [e["event"] for e in eng.events.request_events(r.rid)]
        assert chain[-1] == "shed", chain
        assert r.shed_tick is not None
    assert eng.events.validate_order() == []
    assert eng.resilience_rates()["shed_rate"] > 0
    _assert_contract(eng)


# -------------------------------------------- KV-pressure preemption


def test_page_pressure_preempts_and_replays(setup):
    """Natural KV exhaustion: a pool too small for both streams'
    peaks forces a mid-stream refusal — the youngest slot is
    preempted (pages freed, stream requeued), replays through the
    SAME prefill program, and both streams land token-for-token on
    the uncontended reference. Allocator invariants hold across the
    churn and the preempted request's event chain walks the
    suspension cycle."""
    cfg, params, ref = setup
    lifecycle.enable()
    try:
        # 5 allocatable pages; each stream needs 4 at peak (16
        # positions / 4-token pages)
        eng = _engine(cfg, params, num_pages=6, max_seq=16,
                      preempt=True)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.preempted >= 1, eng.resilience
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.events.validate_order() == []
    victim = next(r for r in reqs if r.preemptions)
    chain = [e["event"] for e in eng.events.request_events(victim.rid)]
    i = chain.index("preempted")
    assert chain[i + 1] == "resubmitted" \
        and "admitted" in chain[i + 2:], chain
    assert eng.resilience_rates()["preempt_rate"] > 0
    _assert_contract(eng)


def test_scripted_alloc_deny_preempts(setup, monkeypatch):
    """The serve_alloc chaos site: ONE scripted mid-stream refusal
    (times=1) in a roomy pool still walks the full preempt -> requeue
    -> replay chain — deterministic page pressure without shrinking
    the pool — and parity holds."""
    cfg, params, ref = setup
    _plan(monkeypatch, [{"site": "serve_alloc", "kind": "deny",
                         "times": 1,
                         "match_ctx": {"phase": "grow"}}])
    eng = _engine(cfg, params, preempt=True)
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.preempted == 1, eng.resilience
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    _assert_contract(eng)


def test_preemption_composes_with_prefix_cache(setup):
    """Preemption must respect prefix-cache refcounts: shared pages
    decref at preemption (never freed under live refs) and the
    resumed stream replays without touching the cache chains."""
    cfg, params, _ = setup
    base = [5, 9, 13, 2]  # shared system-prompt-style prefix
    ref_eng = _engine(cfg, params, num_pages=32, max_seq=16,
                      prefix_cache=True)
    ref_reqs = [Request(rid=i, prompt=base + [20 + i, 30 + i],
                        max_new_tokens=10) for i in range(2)]
    _drive(ref_eng, ref_reqs)
    eng = _engine(cfg, params, num_pages=8, max_seq=16,
                  preempt=True, prefix_cache=True)
    reqs = [Request(rid=i, prompt=base + [20 + i, 30 + i],
                    max_new_tokens=10) for i in range(2)]
    _drive(eng, reqs)
    for r, rr in zip(reqs, ref_reqs):
        assert r.out_tokens == rr.out_tokens, (r.rid, r.out_tokens)
    _assert_contract(eng)


# ------------------------------------- dispatch watchdog / recovery


def _warmed_recover_engine(cfg, params, monkeypatch, plan, **kw):
    """Engine with the watchdog armed and its programs COMPILED
    before the tight timeout arms (compile time must not read as a
    wedge) — the plan is installed only after the warmup rounds."""
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, recover=True,
                      dispatch_timeout_s=60, round_retry_wait_s=0,
                      **kw)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    for r in reqs:
        eng.submit(r)
    eng.step()          # prefill + decode compile (tick 0)
    eng.step()          # a steady-state round (tick 1)
    _plan(monkeypatch, plan)
    eng.dispatch_timeout_s = 0.25
    return eng, reqs


def test_decode_hang_timed_out_classified_and_recovered(
        setup, monkeypatch):
    """A decode dispatch that hangs (the relay wedge) is timed out by
    the watchdog, classified `wedged`, every in-flight request is
    requeued with a degraded_round event, and the engine finishes all
    requests token-for-token."""
    cfg, params, ref = setup
    eng, reqs = _warmed_recover_engine(
        cfg, params, monkeypatch,
        [{"site": "serve_decode", "kind": "hang", "seconds": 1.0,
          "match_ctx": {"tick": 2}}])
    degraded = []
    n = 0
    while not all(r.done() for r in reqs):
        out = eng.step()
        if out.get("degraded"):
            degraded.append(out["degraded"])
        n += 1
        assert n < 100
    eng.step()
    assert len(degraded) == 1
    assert degraded[0]["verdict"] == "wedged"
    assert degraded[0]["phase"] == "decode"
    assert eng.resilience.degraded_rounds == 1
    assert eng.resilience.last_verdict == "wedged"
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.events.validate_order() == []
    rid = degraded[0]["requeued"][0]
    chain = [e["event"] for e in eng.events.request_events(rid)]
    i = chain.index("degraded_round")
    assert chain[i + 1] == "resubmitted", chain
    assert eng.resilience_rates()["degraded_rounds"] == 1
    _assert_contract(eng)


def test_decode_exception_classified_degraded_relay(setup, monkeypatch):
    cfg, params, ref = setup
    eng, reqs = _warmed_recover_engine(
        cfg, params, monkeypatch,
        [{"site": "serve_decode", "kind": "raise",
          "message": "relay reset by peer",
          "match_ctx": {"tick": 2}}])
    n = 0
    while not all(r.done() for r in reqs):
        eng.step()
        n += 1
        assert n < 100
    eng.step()
    assert eng.resilience.degraded_rounds == 1
    assert eng.resilience.last_verdict == "degraded_relay"
    for r in reqs:
        assert r.out_tokens == ref[r.rid]
    _assert_contract(eng)


def test_prefill_failure_mid_admission_recovered(setup, monkeypatch):
    """A prefill dispatch crash mid-admission: the admitted-but-
    unfilled requests are requeued (degraded round), re-admitted and
    prefilled on the retry — parity preserved."""
    cfg, params, ref = setup
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, recover=True,
                      dispatch_timeout_s=60, round_retry_wait_s=0)
    finally:
        lifecycle.reset_enabled()
    _plan(monkeypatch, [{"site": "serve_prefill", "kind": "raise",
                         "message": "compile helper 500",
                         "match_ctx": {"tick": 0}}])
    reqs = _requests()
    _drive(eng, reqs)
    assert eng.resilience.degraded_rounds == 1
    assert eng.resilience.last_verdict == "degraded_relay"
    for r in reqs:
        assert r.out_tokens == ref[r.rid]
    assert eng.events.validate_order() == []
    _assert_contract(eng)


def test_round_attempt_budget_exhausts_loudly(setup, monkeypatch):
    """Bounded recovery: a PERSISTENTLY failing dispatch (every round)
    exhausts SERVE_ROUND_ATTEMPTS and raises — a dead device must
    never spin the engine forever."""
    cfg, params, _ = setup
    eng = _engine(cfg, params, recover=True, dispatch_timeout_s=60,
                  round_attempts=2, round_retry_wait_s=0)
    _plan(monkeypatch, [{"site": "serve_prefill", "kind": "raise",
                         "message": "device is gone"}])
    eng.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    with pytest.raises(RuntimeError, match="budget is exhausted"):
        for _ in range(10):
            eng.step()
    assert eng.resilience.degraded_rounds == 2


def test_without_watchdog_the_engine_dies(setup, monkeypatch):
    """The A/B of the recovery knob: the same injected decode crash
    with recover OFF escapes step() and kills the loop — exactly the
    failure story ISSUE 15 exists to fix."""
    cfg, params, _ = setup
    eng = _engine(cfg, params)
    _plan(monkeypatch, [{"site": "serve_decode", "kind": "raise",
                         "message": "relay reset by peer",
                         "match_ctx": {"tick": 0}}])
    eng.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    with pytest.raises(RuntimeError, match="relay reset"):
        eng.step()


# ------------------------------------------------ combined / overlap


def test_all_layers_under_pressure_and_chaos(setup, monkeypatch):
    """Everything on at once under real pressure AND a scripted
    transient wedge: tight pool (preemption), bounded queue + burst
    (rejections), tiny shed threshold (sheds), one hung decode round
    (recovery) — the engine drains, the contract holds, and every
    surviving stream is greedy-correct vs the reference."""
    cfg, params, ref = setup
    _plan(monkeypatch, [
        {"site": "serve_burst", "kind": "burst", "count": 6,
         "prompt_len": 3, "max_new": 3, "match_ctx": {"tick": 3}},
        {"site": "serve_decode", "kind": "hang", "seconds": 1.0,
         "match_ctx": {"tick": 5}},
    ])
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, num_pages=9, max_seq=16,
                      admit=4, shed=True, shed_ttft_ms=2000.0,
                      preempt=True, recover=True,
                      dispatch_timeout_s=60, round_retry_wait_s=0)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    for r in reqs:
        eng.submit(r)
    eng.step()
    eng.step()
    eng.dispatch_timeout_s = 0.25
    n = 0
    while not all(r.done() for r in reqs):
        eng.step()
        n += 1
        assert n < 200
    eng.step()
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.resilience.degraded_rounds >= 1
    assert eng.events.validate_order() == []
    _assert_contract(eng)


def test_recovery_skips_finished_slots(setup, monkeypatch):
    """A request that FINISHED at this round's prefill (max_new=1)
    must not be requeued by the same round's decode failure: it needs
    no further compute — requeuing would stamp degraded_round after
    finished (forbidden) and replay a completed stream."""
    cfg, params, ref = setup
    # THREE slots: the third stays free through warmup, so `one` is
    # admitted + prefilled (and FINISHES — max_new=1) inside the very
    # round whose decode dispatch hangs
    eng, reqs = _warmed_recover_engine(
        cfg, params, monkeypatch,
        [{"site": "serve_decode", "kind": "hang", "seconds": 1.0,
          "match_ctx": {"tick": 2}}],
        num_slots=3)
    one = Request(rid=7, prompt=[3, 1, 4], max_new_tokens=1)
    eng.submit(one)
    n = 0
    while not (one.done() and all(r.done() for r in reqs)):
        eng.step()
        n += 1
        assert n < 100
    eng.step()
    assert eng.resilience.degraded_rounds == 1
    assert one.done() and len(one.out_tokens) == 1
    chain = [e["event"] for e in eng.events.request_events(7)]
    assert "degraded_round" not in chain, chain
    assert one.preemptions == 0
    assert eng.events.validate_order() == []
    for r in reqs:
        assert r.out_tokens == ref[r.rid]
    _assert_contract(eng)


def test_recovery_with_prefix_refs_on_finished_slot(setup, monkeypatch):
    """Round recovery with the prefix cache on while a FINISHED slot
    still holds shared-page references (a full-page prompt registered
    + acquired at its admission prefill, max_new=1): the recovery
    path must release those refs before flushing the cache — not
    crash on flush's live-reference refusal — and the engine keeps
    serving."""
    cfg, params, ref = setup
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, num_slots=3, recover=True,
                      prefix_cache=True, dispatch_timeout_s=60,
                      round_retry_wait_s=0)
    finally:
        lifecycle.reset_enabled()
    reqs = _requests()
    for r in reqs:
        eng.submit(r)
    eng.step()
    eng.step()
    _plan(monkeypatch, [{"site": "serve_decode", "kind": "raise",
                         "message": "relay reset",
                         "match_ctx": {"tick": 2}}])
    # a FULL page of prompt (page_size=4) registers + acquires into
    # the prefix cache at this round's prefill; max_new=1 finishes it
    # in the same round — then the decode dispatch crashes
    one = Request(rid=7, prompt=[9, 9, 9, 9, 2], max_new_tokens=1)
    eng.submit(one)
    n = 0
    while not (one.done() and all(r.done() for r in reqs)):
        eng.step()
        n += 1
        assert n < 100
    eng.step()
    assert eng.resilience.degraded_rounds == 1
    chain = [e["event"] for e in eng.events.request_events(7)]
    assert "degraded_round" not in chain, chain
    for r in reqs:
        assert r.out_tokens == ref[r.rid]
    assert eng.events.validate_order() == []
    _assert_contract(eng)


def test_shed_composes_with_overlap(setup):
    """The deadline shedder runs in the OVERLAPPED round too (it
    touches queued requests only — no placeholder tokens exist before
    admission): a queue-stuck request sheds, the rest keep parity."""
    cfg, params, ref = setup
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, num_slots=1, overlap=True,
                      shed=True, shed_ttft_ms=1.0)
    finally:
        lifecycle.reset_enabled()
    assert eng.overlap and eng.shed
    reqs = [Request(rid=i, prompt=[1 + i, 2, 3], max_new_tokens=12,
                    arrival=0) for i in range(3)]
    done = eng.run_trace(reqs)
    assert eng.resilience.shed > 0
    assert len(done) + len(eng.scheduler.shed) == 3
    assert eng.events.validate_order() == []
    _assert_contract(eng)


def test_overlap_interplay_asymmetry(setup):
    """overlap=True with preempt/recover demands raises; a demand
    drops the other side's env preference; env-vs-env falls back to
    the serial step (the spec-decode pairing precedent)."""
    cfg, params, _ = setup
    with pytest.raises(ValueError, match="overlap=True"):
        _engine(cfg, params, overlap=True, preempt=True)
    with pytest.raises(ValueError, match="overlap=True"):
        _engine(cfg, params, overlap=True, recover=True)
    # demand vs env preference: the demand wins, the preference drops
    import os
    os.environ["APEX_SERVE_PREEMPT"] = "1"
    try:
        eng = _engine(cfg, params, overlap=True)
        assert eng.overlap and not eng.preempt
        # env overlap vs preempt demand: overlap falls back
        os.environ["APEX_SERVE_OVERLAP"] = "1"
        eng2 = _engine(cfg, params, preempt=True)
        assert eng2.preempt and not eng2.overlap
        # env vs env: serial wins
        eng3 = _engine(cfg, params)
        assert eng3.preempt and not eng3.overlap
    finally:
        os.environ.pop("APEX_SERVE_PREEMPT", None)
        os.environ.pop("APEX_SERVE_OVERLAP", None)
