"""Ring + Ulysses context-parallel attention vs dense reference on the
8-device CPU mesh (sequence sharded over "cp"). Covers fwd parity, grad
parity (the AD-reversed ring), and the non-causal path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.attention import _dense_attention
from apex_tpu.ops.context_parallel import ring_attention, ulysses_attention

CP = 4
B, H, S, D = 2, 4, 32, 16  # S = global sequence, sharded CP-ways


def cp_mesh():
    return Mesh(np.array(jax.devices()[:CP]), ("cp",))


def _data(seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D) * 0.5, jnp.float32)
    return mk(), mk(), mk()


def _run_cp(fn, q, k, v, causal):
    """Run a cp-attention fn with the seq dim sharded over the mesh."""
    f = shard_map(
        lambda q, k, v: fn(q, k, v, "cp", causal=causal),
        mesh=cp_mesh(), in_specs=(P(None, None, "cp"),) * 3,
        out_specs=P(None, None, "cp"), check_vma=False)
    return f(q, k, v)


@pytest.mark.parametrize("causal", [
    True, pytest.param(False, marks=pytest.mark.slow)])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention],
                         ids=["ring", "ulysses"])
def test_matches_dense(fn, causal):
    q, k, v = _data()
    want = _dense_attention(q, k, v, causal, 1.0 / np.sqrt(D), None)
    got = _run_cp(fn, q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # compiling grad-of-ring (scan+ppermute reversal) over
# 4 devices is ~10-20s/impl; fwd parity stays fast and the driver's
# dryrun runs value_and_grad through ring-cp every round
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention],
                         ids=["ring", "ulysses"])
def test_grads_match_dense(fn):
    q, k, v = _data(1)
    g = jnp.asarray(np.random.RandomState(2).randn(B, H, S, D) * 0.1,
                    jnp.float32)

    def loss_cp(q, k, v):
        return jnp.sum(_run_cp(fn, q, k, v, True).astype(jnp.float32) * g)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(
            q, k, v, True, 1.0 / np.sqrt(D), None).astype(jnp.float32) * g)

    got = jax.grad(loss_cp, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_segment_ids_match_dense(causal):
    """The closed CP refusal (ISSUE 10 satellite): packed-varlen
    segment-aware Ulysses — shard-local segment ids ride their own
    all_gather re-shard next to the q/k/v all_to_alls, and the result
    must equal dense attention on the gathered sequence with the SAME
    global ids (per-segment reference semantics: cross-segment pairs
    masked, exactly the serving prefill input shape)."""
    q, k, v = _data(3)
    # 3 packed segments across the global sequence, lengths not
    # aligned to the CP shard boundary (the re-shard must still agree)
    bounds = [0, 10, 21, S]
    seg = np.zeros((B, S), np.int32)
    for i in range(len(bounds) - 1):
        seg[:, bounds[i]:bounds[i + 1]] = i + 1
    seg = jnp.asarray(seg)
    want = _dense_attention(q, k, v, causal, 1.0 / np.sqrt(D),
                            (seg, seg))
    f = shard_map(
        lambda q, k, v, s: ulysses_attention(
            q, k, v, "cp", causal=causal, segment_ids=(s, s)),
        mesh=cp_mesh(),
        in_specs=(P(None, None, "cp"),) * 3 + (P(None, "cp"),),
        out_specs=P(None, None, "cp"), check_vma=False)
    got = f(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_segment_ids_single_array_form():
    """One array for both q and kv ids is accepted (the packed-batch
    convenience form)."""
    q, k, v = _data(4)
    seg = jnp.asarray(
        np.repeat(np.arange(1, 5), S // 4)[None].repeat(B, 0))
    want = _dense_attention(q, k, v, True, 1.0 / np.sqrt(D),
                            (seg, seg))
    f = shard_map(
        lambda q, k, v, s: ulysses_attention(
            q, k, v, "cp", causal=True, segment_ids=s),
        mesh=cp_mesh(),
        in_specs=(P(None, None, "cp"),) * 3 + (P(None, "cp"),),
        out_specs=P(None, None, "cp"), check_vma=False)
    got = f(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_bad_heads():
    q, k, v = _data(3)
    q3 = q[:, :3]  # 3 heads not divisible by cp=4
    with pytest.raises(Exception):
        _run_cp(ulysses_attention, q3, k[:, :3], v[:, :3], True)


def test_ring_bf16_io():
    q, k, v = (x.astype(jnp.bfloat16) for x in _data(4))
    out = _run_cp(ring_attention, q, k, v, True)
    assert out.dtype == jnp.bfloat16
    want = _dense_attention(q, k, v, True, 1.0 / np.sqrt(D), None)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want, np.float32), atol=3e-2)


# ------------------- whole-model context parallelism -----------------------

@pytest.mark.slow
def test_gpt_context_parallel_matches_single():
    """GPTModel with the sequence sharded 4-ways (hidden states [s/cp, b, h],
    ring attention) must reproduce the unsharded loss and grads."""
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    base = dict(hidden_size=64, num_layers=2, num_attention_heads=4,
                vocab_size=128, max_position_embeddings=S,
                hidden_dropout=0.0, attention_dropout=0.0)
    rs = np.random.RandomState(5)
    b = 2
    ids = jnp.asarray(rs.randint(0, 128, (b, S)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (b, S))
    labels = jnp.asarray(rs.randint(0, 128, (b, S)), jnp.int32)

    # the parallel layers need the tp axis in scope: use a 2D (tp=1, cp=4)
    # mesh, sharding only the sequence
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS

    def run2(cfg, shard_seq):
        model = GPTModel(cfg)
        mesh = Mesh(np.array(jax.devices()[:CP]).reshape(1, CP),
                    (TENSOR_AXIS, "cp"))

        def f(ids, pos, labels):
            def loss_fn(params):
                per_tok = model.apply({"params": params}, ids, pos, None,
                                      labels)
                l = jnp.mean(per_tok)
                if shard_seq:
                    l = jax.lax.pmean(l, "cp")
                return l

            params = model.init(jax.random.PRNGKey(0), ids, pos,
                                None)["params"]
            loss, grads = jax.value_and_grad(loss_fn)(params)
            pe = grads["embedding"]["position_embeddings"]
            if shard_seq:
                # replicated param under a pmean'd loss: each rank's local
                # grad is cp x its disjoint share, so the cross-rank
                # reduction is pmean (the DDP grad-average convention,
                # parallel/distributed.py)
                pe = jax.lax.pmean(pe, "cp")
            return loss, pe

        seq = P(None, "cp") if shard_seq else P()
        g = shard_map(f, mesh=mesh, in_specs=(seq, seq, seq),
                      out_specs=(P(), P()), check_vma=False)
        return g(ids, pos, labels)

    cfg_cp = TransformerConfig(context_parallel_axis="cp", **base)
    cfg_single = TransformerConfig(**base)
    loss_cp, pe_cp = run2(cfg_cp, True)
    loss_1, pe_1 = run2(cfg_single, False)
    np.testing.assert_allclose(np.asarray(loss_cp), np.asarray(loss_1),
                               rtol=2e-4)
    np.testing.assert_allclose(np.asarray(pe_cp), np.asarray(pe_1),
                               rtol=5e-3, atol=1e-5)


# ------------------------------ dropout ------------------------------------

def _global_mscale(seed, b, h, s_glob, p):
    """Dense [b, h, s, s] keep-scale from the kernel's own chained hash
    (global coordinates), for the dense reference."""
    from apex_tpu.ops import attention_pallas as ap

    out = np.zeros((b, h, s_glob, s_glob), np.float32)
    for ib in range(b):
        for ih in range(h):
            out[ib, ih] = np.asarray(ap._dropout_mscale(
                jnp.asarray(seed, jnp.int32), jnp.int32(ib), jnp.int32(ih),
                0, s_glob, s_glob, p, h))
    return out


@pytest.mark.slow  # ring compile + dense reconstruction; the
# validation tests and the ring fwd parity stay fast
def test_ring_dropout_matches_dense_with_same_mask():
    """Ring attention with in-ring dropout == dense attention with the
    SAME global hash mask applied to the normalized probs — exact, fwd."""
    p, seed = 0.3, 77
    q, k, v = _data(3)
    got = _run_cp(lambda q_, k_, v_, axis_name, causal: ring_attention(
        q_, k_, v_, axis_name, causal=causal, dropout_p=p,
        dropout_seed=jnp.int32(seed)), q, k, v, True)

    # dense reference: softmax then mask the normalized probs
    ms = _global_mscale(seed, B, H, S, p)
    scale = 1.0 / np.sqrt(D)
    sc = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) * scale
    tri = np.triu(np.ones((S, S), bool), 1)
    sc = np.where(tri, -1e30, sc)
    e = np.exp(sc - sc.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", probs * ms, np.asarray(v))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5)


@pytest.mark.slow  # second ring compile; grads through the AD-reversed ring
def test_ring_dropout_grads_finite_and_deterministic():
    q, k, v = _data(4)

    def run(seed):
        def loss(q, k, v):
            y = _run_cp(lambda q_, k_, v_, axis_name, causal:
                        ring_attention(q_, k_, v_, axis_name, causal=causal,
                                       dropout_p=0.4,
                                       dropout_seed=jnp.int32(seed)),
                        q, k, v, True)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        l, g = jax.value_and_grad(loss, argnums=(0,))(q, k, v)
        return float(l), np.asarray(g[0])

    l1, g1 = run(5)
    l2, g2 = run(5)
    l3, _ = run(6)
    assert np.isfinite(g1).all()
    assert l1 == l2 and l1 != l3
    np.testing.assert_array_equal(g1, g2)


def test_ring_dropout_validation():
    q, k, v = _data(5)
    with pytest.raises(ValueError, match="dropout_seed"):
        _run_cp(lambda q_, k_, v_, a, causal: ring_attention(
            q_, k_, v_, a, causal=causal, dropout_p=0.3), q, k, v, True)
    with pytest.raises(ValueError, match="outside"):
        _run_cp(lambda q_, k_, v_, a, causal: ring_attention(
            q_, k_, v_, a, causal=causal, dropout_p=1.0,
            dropout_seed=jnp.int32(1)), q, k, v, True)


@pytest.mark.slow
def test_gpt_context_parallel_with_dropout_trains():
    """context_parallel_axis + attention_dropout > 0 previously raised
    NotImplementedError; now it routes through the in-ring hash dropout."""
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=64, num_layers=1, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=S,
        hidden_dropout=0.0, attention_dropout=0.3,
        context_parallel_axis="cp")
    model = GPTModel(cfg)
    rs = np.random.RandomState(9)
    b = 2
    ids = jnp.asarray(rs.randint(0, 128, (b, S)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (b, S))
    labels = jnp.asarray(rs.randint(0, 128, (b, S)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:CP]).reshape(1, CP),
                (TENSOR_AXIS, "cp"))

    def f(ids, pos, labels):
        params = model.init(jax.random.PRNGKey(0), ids, pos,
                            None)["params"]

        def loss_fn(p):
            per_tok = model.apply({"params": p}, ids, pos, None, labels,
                                  deterministic=False,
                                  rngs={"dropout": jax.random.PRNGKey(2)})
            return jax.lax.pmean(jnp.mean(per_tok), "cp")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return loss, grads["embedding"]["position_embeddings"]

    seq = P(None, "cp")
    loss, pe = shard_map(f, mesh=mesh, in_specs=(seq, seq, seq),
                         out_specs=(P(), P()), check_vma=False)(
        ids, pos, labels)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(pe)).all()


@pytest.mark.slow  # interpret rows kernel at s=128 x 4 head groups
def test_ulysses_dropout_matches_dense_with_same_masks():
    """Ulysses dropout: each rank applies the rows kernel's hash dropout
    to its DISJOINT global head group with a rank-offset seed — the dense
    reference rebuilds each group's mask from (seed + rank, local head)."""
    from apex_tpu.ops import attention_pallas as ap

    p, seed = 0.25, 31
    # rows kernel needs lane-aligned global seq: use s=128 (local 32 x 4)
    s_glob = 128
    rs = np.random.RandomState(6)
    mk = lambda: jnp.asarray(rs.randn(B, H, s_glob, D) * 0.5, jnp.float32)
    q, k, v = mk(), mk(), mk()

    f = shard_map(
        lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, "cp", causal=True, dropout_p=p,
            dropout_seed=jnp.int32(seed)),
        mesh=cp_mesh(), in_specs=(P(None, None, "cp"),) * 3,
        out_specs=P(None, None, "cp"), check_vma=False)
    got = np.asarray(f(q, k, v))

    # dense reference: per rank r (owning head group r, H/CP heads), the
    # mask stream is _dropout_mscale(seed + r, ib, local_ih, ...)
    hg = H // CP
    scale = 1.0 / np.sqrt(D)
    sc = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) * scale
    tri = np.triu(np.ones((s_glob, s_glob), bool), 1)
    sc = np.where(tri, -1e30, sc)
    e = np.exp(sc - sc.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ms = np.zeros_like(probs)
    for g in range(H):
        r, lh = g // hg, g % hg
        seed_r = np.uint32(seed) ^ np.asarray(ap._fmix32(
            jnp.uint32(r) + jnp.uint32(0x9E3779B9)))
        for ib in range(B):
            ms[ib, g] = np.asarray(ap._dropout_mscale(
                jnp.asarray(seed_r.astype(np.int32)), jnp.int32(ib),
                jnp.int32(lh), 0, s_glob, s_glob, p, hg))
    want = np.einsum("bhqk,bhkd->bhqd", probs * ms, np.asarray(v))
    np.testing.assert_allclose(got, want, atol=2e-5)


@pytest.mark.slow  # interpret rows kernel at s=128, like its sibling
def test_ulysses_dropout_with_segment_ids_matches_dense():
    """The dropout branch of the segment-aware Ulysses path (the
    all_gathered ids thread positionally into fused_attention_rows):
    dense reference = per-head-group hash masks x segment+causal
    exclusion semantics."""
    from apex_tpu.ops import attention_pallas as ap

    p, seed = 0.25, 13
    s_glob = 128
    rs = np.random.RandomState(9)
    mk = lambda: jnp.asarray(rs.randn(B, H, s_glob, D) * 0.5,
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    seg = jnp.asarray(
        np.repeat(np.arange(1, 5), s_glob // 4)[None].repeat(B, 0))

    f = shard_map(
        lambda q_, k_, v_, s_: ulysses_attention(
            q_, k_, v_, "cp", causal=True, dropout_p=p,
            dropout_seed=jnp.int32(seed), segment_ids=(s_, s_)),
        mesh=cp_mesh(),
        in_specs=(P(None, None, "cp"),) * 3 + (P(None, "cp"),),
        out_specs=P(None, None, "cp"), check_vma=False)
    got = np.asarray(f(q, k, v, seg))

    hg = H // CP
    scale = 1.0 / np.sqrt(D)
    sc = np.einsum("bhqd,bhkd->bhqk", np.asarray(q),
                   np.asarray(k)) * scale
    segn = np.asarray(seg)
    mask = np.triu(np.ones((s_glob, s_glob), bool), 1)[None, None] \
        | (segn[:, None, :, None] != segn[:, None, None, :])
    sc = np.where(mask, -1e30, sc)
    e = np.exp(sc - sc.max(-1, keepdims=True))
    e = np.where(mask, 0.0, e)
    tot = e.sum(-1, keepdims=True)
    probs = np.where(tot > 0, e / np.where(tot > 0, tot, 1.0), 0.0)
    ms = np.zeros_like(probs)
    for g in range(H):
        r, lh = g // hg, g % hg
        seed_r = np.uint32(seed) ^ np.asarray(ap._fmix32(
            jnp.uint32(r) + jnp.uint32(0x9E3779B9)))
        for ib in range(B):
            ms[ib, g] = np.asarray(ap._dropout_mscale(
                jnp.asarray(seed_r.astype(np.int32)), jnp.int32(ib),
                jnp.int32(lh), 0, s_glob, s_glob, p, hg))
    want = np.einsum("bhqk,bhkd->bhqd", probs * ms, np.asarray(v))
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_ulysses_dropout_validation():
    q, k, v = _data(7)
    with pytest.raises(ValueError, match="dropout_seed"):
        _run_cp(lambda q_, k_, v_, a, causal: ulysses_attention(
            q_, k_, v_, a, causal=causal, dropout_p=0.3), q, k, v, True)
    # S=32 global: below the rows kernel's lane alignment -> loud refusal
    with pytest.raises(NotImplementedError, match="rows-kernel-supported"):
        _run_cp(lambda q_, k_, v_, a, causal: ulysses_attention(
            q_, k_, v_, a, causal=causal, dropout_p=0.3,
            dropout_seed=jnp.int32(1)), q, k, v, True)


def test_ulysses_dropout_rejects_unhonorable_kwargs():
    q, k, v = _data(8)
    with pytest.raises(ValueError, match="cannot be honored"):
        _run_cp(lambda q_, k_, v_, a, causal: ulysses_attention(
            q_, k_, v_, a, causal=causal, dropout_p=0.2,
            dropout_seed=jnp.int32(1), impl="flash"), q, k, v, True)
