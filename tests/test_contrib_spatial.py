"""Spatial parallelism tests: halo exchangers, SpatialBottleneck parity,
peer halo exchanger (ports of the reference's bottleneck/peer_memory
contrib tests: split output must equal unsplit)."""

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import (
    Bottleneck,
    HaloExchangerAllGather,
    HaloExchangerNoComm,
    HaloExchangerSendRecv,
    SpatialBottleneck,
)
import pytest

from apex_tpu.contrib.peer_memory import PeerHaloExchanger1d, PeerMemoryPool

NDEV = 8


def spatial_mesh(n=NDEV):
    return Mesh(np.array(jax.devices()[:n]), ("spatial",))


@pytest.mark.slow  # two-impl agreement compile; the 1d exchanger's
# fills-padding check stays fast
def test_halo_exchange_sendrecv_and_allgather_agree():
    mesh = spatial_mesh(4)
    rs = np.random.RandomState(0)
    # per-rank halo row [4 ranks, 1, 5]
    tops = jnp.asarray(rs.randn(4, 1, 5), jnp.float32)
    bots = jnp.asarray(rs.randn(4, 1, 5), jnp.float32)

    def run(cls):
        ex = cls("spatial", 4)

        def f(t, b):
            return ex.left_right_halo_exchange(t, b)

        return shard_map(f, mesh=mesh, in_specs=(P("spatial"), P("spatial")),
                         out_specs=(P("spatial"), P("spatial")),
                         check_vma=False)(tops, bots)

    li_s, ri_s = run(HaloExchangerSendRecv)
    li_a, ri_a = run(HaloExchangerAllGather)
    np.testing.assert_allclose(np.asarray(li_s), np.asarray(li_a))
    np.testing.assert_allclose(np.asarray(ri_s), np.asarray(ri_a))
    # rank r's left_input == rank r-1's bottom halo; rank 0 → zeros
    np.testing.assert_array_equal(np.asarray(li_s)[0], 0)
    np.testing.assert_allclose(np.asarray(li_s)[1], np.asarray(bots)[0])
    np.testing.assert_array_equal(np.asarray(ri_s)[3], 0)
    np.testing.assert_allclose(np.asarray(ri_s)[2], np.asarray(tops)[3])

    li_n, ri_n = run(HaloExchangerNoComm)
    np.testing.assert_array_equal(np.asarray(li_n), 0)
    np.testing.assert_array_equal(np.asarray(ri_n), 0)


@pytest.mark.slow  # spatial-split conv compile; the halo-exchange
# agreement test keeps the mechanism fast
def test_spatial_bottleneck_matches_unsplit():
    """H-split over 4 ranks == single-device bottleneck (the substance of
    the reference's spatial bottleneck test)."""
    n_split = 4
    mesh = spatial_mesh(n_split)
    rs = np.random.RandomState(1)
    N, H, W, C = 2, 16, 8, 8
    x = jnp.asarray(rs.randn(N, H, W, C), jnp.float32)

    plain = Bottleneck(in_channels=C, bottleneck_channels=4, out_channels=C)
    variables = plain.init(jax.random.PRNGKey(0), x)
    want = plain.apply(variables, x)

    spatial = SpatialBottleneck(in_channels=C, bottleneck_channels=4,
                                out_channels=C, spatial_axis="spatial",
                                spatial_group_size=n_split)

    def run(xs):
        return spatial.apply(variables, xs)

    # shard H across ranks: [N, H/4, W, C] per rank
    xs = x.reshape(N, n_split, H // n_split, W, C).transpose(1, 0, 2, 3, 4)
    got = shard_map(run, mesh=mesh, in_specs=(P("spatial"),),
                    out_specs=P("spatial"), check_vma=False)(
        xs.reshape(n_split * N, H // n_split, W, C))
    got = got.reshape(n_split, N, H // n_split, W, C).transpose(
        1, 0, 2, 3, 4).reshape(N, H, W, C)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4)


def test_peer_halo_exchanger_1d_fills_padding():
    mesh = spatial_mesh(4)
    rs = np.random.RandomState(2)
    hh = 1
    # per-rank padded tensor [4, N=1, 2+2*hh, 3, 2]
    y = jnp.asarray(rs.randn(4, 2 + 2 * hh, 3, 2), jnp.float32)
    ex = PeerHaloExchanger1d(ranks=list(range(4)), half_halo=hh)

    def run(y):
        # local shard is [1, Hs, 3, 2] — already the NHWC batch form
        return ex(y)

    out = shard_map(run, mesh=mesh, in_specs=(P("spatial"),),
                    out_specs=P("spatial"), check_vma=False)(y)
    out = np.asarray(out).reshape(4, 2 + 2 * hh, 3, 2)
    yn = np.asarray(y).reshape(4, 2 + 2 * hh, 3, 2)
    # interior preserved
    np.testing.assert_allclose(out[:, hh:-hh], yn[:, hh:-hh])
    # rank 1's top padding == rank 0's last interior row
    np.testing.assert_allclose(out[1, 0], yn[0, -2 * hh])
    # rank 0's top padding zero-filled
    np.testing.assert_array_equal(out[0, 0], 0)


def test_peer_memory_pool_arena_accounting():
    """Port of the reference pool's bookkeeping semantics
    (apex/contrib/peer_memory/peer_memory.py:23-63): 256-byte alignment,
    static/dynamic regions, exhaustion asserts, reset()."""
    pool = PeerMemoryPool(static_size=1000, dynamic_size=2000,
                          peer_ranks=[0, 1, 2, 3])
    # sizes round up to the alignment
    assert pool.static_size == 1024 and pool.dynamic_size == 2048

    bufs = pool.allocate_peer_tensors([2, 4], jnp.int32, False, False)
    assert len(bufs) == 4 and bufs[0].shape == (2, 4)
    assert pool.static_offset == 32        # 8 * 4 bytes, from offset 0
    pool.allocate_peer_tensors([2, 4], jnp.int32, False, False)
    assert pool.static_offset == 256 + 32  # next alloc aligns up to 256

    # dynamic region: independent offset, rewound by reset()
    pool.allocate_peer_tensors([100], jnp.float32, False, True)
    assert pool.dynamic_offset == 400 and pool.static_offset == 288
    pool.reset()
    assert pool.dynamic_offset == 0 and pool.static_offset == 288

    with pytest.raises(AssertionError, match="Dynamic peer memory pool"):
        pool.allocate_peer_tensors([600], jnp.float32, False, True)
    with pytest.raises(AssertionError, match="Static peer memory pool"):
        pool.allocate_peer_tensors([300], jnp.float32, False, False)
    with pytest.raises(AssertionError, match="not supported"):
        pool.allocate_peer_tensors([4], jnp.int8, False, False)


def test_peer_memory_pool_rank_group_validation():
    """Reference peer_memory.py:19-21 — peers must be node-local."""
    PeerMemoryPool(256, 256, peer_ranks=[4, 5], rank=5, peer_group_size=4)
    with pytest.raises(AssertionError, match="not on same node"):
        PeerMemoryPool(256, 256, peer_ranks=[3, 4], rank=5,
                       peer_group_size=4)
