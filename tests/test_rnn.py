"""RNN package tests (reference: tests/L0/run_amp/test_rnn.py exercises
cell variants; parity here is vs torch.nn reference math on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from apex_tpu.RNN import GRU, LSTM, ReLU, Tanh, mLSTM


def _torch_parity(cell_type, torch_cls, T=5, B=3, I=4, H=6, layers=2,
                  bidirectional=False):
    rs = np.random.RandomState(0)
    x = rs.randn(T, B, I).astype(np.float32)
    model = {"LSTM": LSTM, "GRU": GRU, "ReLU": ReLU, "Tanh": Tanh}[
        cell_type](I, H, layers, bidirectional=bidirectional)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    kwargs = dict(num_layers=layers, bidirectional=bidirectional)
    if cell_type in ("ReLU", "Tanh"):
        tm = torch.nn.RNN(I, H, nonlinearity=cell_type.lower(), **kwargs)
    else:
        tm = torch_cls(I, H, **kwargs)

    # copy our params into torch
    sd = tm.state_dict()
    p = variables["params"]
    dirs = 2 if bidirectional else 1
    for layer in range(layers):
        for d in range(dirs):
            ours = f"l{layer}{'_rev' if d else ''}"
            theirs = f"_l{layer}{'_reverse' if d else ''}"
            sd[f"weight_ih{theirs}"] = torch.tensor(
                np.asarray(p[f"{ours}_w_ih"]))
            sd[f"weight_hh{theirs}"] = torch.tensor(
                np.asarray(p[f"{ours}_w_hh"]))
            sd[f"bias_ih{theirs}"] = torch.tensor(
                np.asarray(p[f"{ours}_b_ih"]))
            sd[f"bias_hh{theirs}"] = torch.tensor(
                np.asarray(p[f"{ours}_b_hh"]))
    tm.load_state_dict(sd)

    ours_out, _ = model.apply(variables, jnp.asarray(x))
    with torch.no_grad():
        theirs_out, _ = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(ours_out),
                               theirs_out.numpy(), atol=1e-5)


@pytest.mark.parametrize("cell,cls", [
    pytest.param("LSTM", torch.nn.LSTM, marks=pytest.mark.slow),
    ("GRU", torch.nn.GRU),
    ("ReLU", None), ("Tanh", None)])
def test_rnn_matches_torch(cell, cls):
    _torch_parity(cell, cls)


def test_bidirectional_lstm_matches_torch():
    _torch_parity("LSTM", torch.nn.LSTM, bidirectional=True)


def test_mlstm_runs_and_differs_from_lstm():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 2, 5), jnp.float32)
    m = mLSTM(5, 8, 1)
    variables = m.init(jax.random.PRNGKey(0), x)
    out, hidden = m.apply(variables, x)
    assert out.shape == (4, 2, 8)
    assert np.isfinite(np.asarray(out)).all()
    # grads flow through the multiplicative path
    g = jax.grad(lambda v: jnp.sum(m.apply(v, x)[0] ** 2))(variables)
    gm = g["params"]["l0_w_mih"]
    assert np.abs(np.asarray(gm)).sum() > 0


def test_hidden_state_carry():
    """Explicit hidden carry (the reference's init_hidden/reset_hidden
    capability): running two halves with carried state == one run."""
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(6, 2, 4), jnp.float32)
    model = LSTM(4, 5, 1)
    variables = model.init(jax.random.PRNGKey(0), x)
    full, _ = model.apply(variables, x)
    first, h = model.apply(variables, x[:3])
    second, _ = model.apply(variables, x[3:], hidden=h)
    np.testing.assert_allclose(np.asarray(jnp.concatenate([first, second])),
                               np.asarray(full), atol=1e-6)
