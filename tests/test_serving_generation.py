"""Generation subsystem (ISSUE 13): batched sampling lane semantics +
per-request RNG determinism, speculative decode ≡ greedy token parity
through the reused prefill program, prefix-cache COW/refcount
invariants under churn (shared pages prefilled once — allocator
accounting asserted), the priority scheduler policy's aging/no-
starvation rule, jaxpr stability (exactly TWO compiled programs with
every layer enabled), and the ledger/check-8 teeth for the new
serving-block fields."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.serving import (
    ContinuousBatchingScheduler,
    PageAllocator,
    PrefixCache,
    Request,
    SamplingParams,
    ServingEngine,
    speculative,
    synthetic_trace,
)
from apex_tpu.serving import prefix_cache as prefix_mod
from apex_tpu.serving import sampling as sampling_mod
from apex_tpu.telemetry import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from apex_tpu.serving import model as smodel

    return cfg, smodel.init_gpt_params(cfg)


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 48)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prefill_len", 40)
    return ServingEngine(cfg, params=params, **kw)


def _drain(eng, reqs):
    for r in reqs:
        eng.submit(r)
    while any(not r.done() for r in reqs):
        eng.step()
    eng.step()  # final evict round


# ------------------------------------------------------------- sampling


def test_sample_tokens_semantics():
    """Unit semantics of the in-graph op: temp-0 = exact argmax;
    top_k=1 and tiny top_p collapse to argmax; a top-k draw's support
    is the top-k set; same (key, counter) -> same token regardless of
    the surrounding batch; inactive lanes return 0."""
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(4, 32).astype(np.float32))
    key = sampling_mod.request_key(7)

    def draw(temps, top_ks, top_ps, keys, counters,
             active=(True,) * 4):
        return np.asarray(sampling_mod.sample_tokens(
            logits, jnp.asarray(temps, jnp.float32),
            jnp.asarray(top_ks, jnp.int32),
            jnp.asarray(top_ps, jnp.float32),
            jnp.asarray(np.stack(keys).astype(np.uint32)),
            jnp.asarray(counters, jnp.int32),
            jnp.asarray(active)))

    greedy = np.argmax(np.asarray(logits), axis=-1)
    zero = [np.zeros(2, np.uint32)] * 4
    # temperature 0 lanes == argmax exactly
    assert (draw([0.0] * 4, [0] * 4, [1.0] * 4, zero, [0] * 4)
            == greedy).all()
    # top_k=1 / top_p ~ 0 collapse to argmax even at high temperature
    assert (draw([5.0] * 4, [1] * 4, [1.0] * 4, [key] * 4, [0] * 4)
            == greedy).all()
    assert (draw([5.0] * 4, [0] * 4, [1e-6] * 4, [key] * 4, [0] * 4)
            == greedy).all()
    # top-k support: many draws at high temp never leave the top-5 set
    top5 = np.argsort(-np.asarray(logits), axis=-1)[:, :5]
    for ctr in range(20):
        toks = draw([3.0] * 4, [5] * 4, [1.0] * 4, [key] * 4,
                    [ctr] * 4)
        for lane in range(4):
            assert toks[lane] in top5[lane], (ctr, lane)
    # lane-position independence: lane value depends on (key, counter)
    # only — the RNG determinism property at op level
    a = draw([0.9] * 4, [0] * 4, [1.0] * 4, [key] * 4, [3, 0, 0, 0])
    b = draw([0.9] * 4, [0] * 4, [1.0] * 4,
             [np.zeros(2, np.uint32), key, key, key], [0, 3, 5, 3])
    assert a[0] == b[1] == b[3]
    # inactive lanes return 0
    toks = draw([0.0] * 4, [0] * 4, [1.0] * 4, zero, [0] * 4,
                active=(False, True, False, True))
    assert toks[0] == 0 and toks[2] == 0


def test_sampling_knob_asymmetry(monkeypatch):
    with pytest.raises(ValueError):
        sampling_mod.set_sampling("yes")
    with pytest.raises(ValueError):
        sampling_mod.resolve(per_call="on")
    from apex_tpu.dispatch import tiles

    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SERVE_SAMPLING", "maybe")
    with pytest.warns(UserWarning, match="maybe"):
        assert sampling_mod.resolve() is False
    monkeypatch.setenv("APEX_SERVE_SAMPLING", "1")
    assert sampling_mod.resolve() is True
    monkeypatch.delenv("APEX_SERVE_SAMPLING")
    sampling_mod.set_sampling(True)
    try:
        assert sampling_mod.resolve() is True
        assert sampling_mod.resolve(per_call=False) is False
    finally:
        sampling_mod.set_sampling(None)
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-1.0).validate()
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0).validate()


def test_sampling_off_engine_raises_on_stochastic_demand(setup):
    cfg, params = setup
    eng = _engine(cfg, params)
    with pytest.raises(ValueError, match="without sampling"):
        eng.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=2,
                           sampling=SamplingParams(temperature=0.5)))
    # greedy params are honorable on a sampling-off engine
    eng.submit(Request(rid=1, prompt=[1, 2], max_new_tokens=2,
                       sampling=SamplingParams(temperature=0.0)))


def test_sampling_on_all_greedy_reproduces_greedy_engine(setup):
    """The temperature->0 acceptance parity: a sampling-enabled engine
    over default (greedy) requests emits the greedy engine's tokens
    token-for-token, and still compiles exactly one decode program."""
    cfg, params = setup
    trace_kw = dict(seed=5, n_requests=5, vocab=128, prompt_lo=2,
                    prompt_hi=8, new_lo=2, new_hi=8,
                    mean_interarrival=0.5)
    base, _ = synthetic_trace(**trace_kw)
    eng = _engine(cfg, params)
    done = eng.run_trace(base)
    want = {r.rid: r.out_tokens for r in done}
    reqs, _ = synthetic_trace(**trace_kw)
    eng2 = _engine(cfg, params, sampling=True)
    done2 = eng2.run_trace(reqs)
    assert {r.rid: r.out_tokens for r in done2} == want
    assert eng2.decode_cache_size() == 1
    assert eng2.prefill_cache_size() == 1


def test_per_request_rng_determinism_across_batches(setup):
    """THE determinism invariant: same seed + request -> identical
    token stream, whatever the batch composition, slot placement or
    evictions around it."""
    cfg, params = setup
    probe = dict(rid=100, prompt=[3, 5, 7, 9, 11], max_new_tokens=10,
                 sampling=SamplingParams(temperature=0.8, top_k=20,
                                         top_p=0.95, seed=42))

    def run(extra):
        eng = _engine(cfg, params, sampling=True, num_pages=64)
        x = Request(**probe)
        _drain(eng, [x] + extra)
        assert eng.decode_cache_size() == 1
        return x.out_tokens

    solo = run([])
    assert len(solo) == 10
    rs = np.random.RandomState(1)
    noisy = run([
        Request(rid=i, prompt=[int(t) for t in rs.randint(0, 128, 4)],
                max_new_tokens=2 + i,
                sampling=SamplingParams(temperature=1.2, seed=i))
        for i in range(1, 4)])
    assert noisy == solo, "batch composition perturbed a seeded stream"
    # a different seed must (overwhelmingly) give a different stream
    other = dict(probe, sampling=SamplingParams(temperature=0.8,
                                                top_k=20, top_p=0.95,
                                                seed=43))
    eng = _engine(cfg, params, sampling=True)
    y = Request(**other)
    _drain(eng, [y])
    assert y.out_tokens != solo


# ----------------------------------------------------------- speculative


def test_ngram_propose():
    assert speculative.propose([1, 2, 3], 0) == []
    assert speculative.propose([1, 2], 4) == []          # too short
    assert speculative.propose([1, 2, 3, 4, 5], 4) == []  # no repeat
    # period-1 loop: the full-k continuation wins over the short
    # most-recent match
    assert speculative.propose([9, 9, 9, 9, 9, 9], 3) == [9, 9, 9]
    # copies the continuation of the matched bigram
    hist = [1, 2, 3, 4, 1, 2]
    assert speculative.propose(hist, 2) == [3, 4]
    # truncated fallback when no full-k continuation exists
    assert speculative.propose([5, 6, 7, 5, 6], 4) == [7, 5, 6]


def test_accept_arithmetic():
    # all accepted + bonus
    assert speculative.accept([1, 2], [1, 2, 3]) == [1, 2, 3]
    # first rejection: bonus is the greedy correction
    assert speculative.accept([1, 2], [1, 9, 3]) == [1, 9]
    # all rejected: exactly the plain decode round's token
    assert speculative.accept([4], [8, 0]) == [8]
    assert speculative.accept([], [6]) == [6]


def test_resolve_k_asymmetry(monkeypatch):
    for bad in (0, -1, True, "4"):
        with pytest.raises(ValueError):
            speculative.resolve_k(bad)
    monkeypatch.delenv("APEX_SPEC_DECODE", raising=False)
    assert speculative.resolve_k() == 0
    monkeypatch.setenv("APEX_SPEC_DECODE", "0")  # the explicit off-pin
    assert speculative.resolve_k() == 0
    monkeypatch.setenv("APEX_SPEC_DECODE", "4")
    assert speculative.resolve_k() == 4
    assert speculative.resolve_k(2) == 2         # per-call wins
    from apex_tpu.dispatch import tiles

    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SPEC_DECODE", "many")
    with pytest.warns(UserWarning, match="many"):
        assert speculative.resolve_k() == 0


def test_spec_decode_unhonorable_per_call_raises(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="cannot be honored"):
        _engine(cfg, params, spec_decode=12, prefill_len=8)
    # env preference at the same depth falls back per shape instead
    os.environ["APEX_SPEC_DECODE"] = "12"
    try:
        eng = _engine(cfg, params, prefill_len=8)
        assert eng.spec_k == 0
    finally:
        del os.environ["APEX_SPEC_DECODE"]


def test_spec_equals_greedy_token_for_token(setup):
    """The acceptance parity: speculative output ≡ non-speculative
    greedy, token for token, over a churning trace — while the verify
    path demonstrably engaged (acceptance recorded) and the prefill
    program stayed ONE compiled program (no third program)."""
    cfg, params = setup
    trace_kw = dict(seed=11, n_requests=6, vocab=128, prompt_lo=4,
                    prompt_hi=10, new_lo=6, new_hi=14,
                    mean_interarrival=0.5)
    base, _ = synthetic_trace(**trace_kw)
    eng = _engine(cfg, params)
    want = {r.rid: r.out_tokens for r in eng.run_trace(base)}
    reqs, _ = synthetic_trace(**trace_kw)
    eng2 = _engine(cfg, params, spec_decode=4)
    done = eng2.run_trace(reqs)
    assert {r.rid: r.out_tokens for r in done} == want, \
        "speculative decode diverged from greedy"
    assert eng2.verify_calls > 0, "no verify batch ever dispatched"
    st = eng2.spec_stats
    assert st.drafted > 0 and 0 <= st.accepted <= st.drafted
    assert eng2.generation_stats()["spec_acceptance_rate"] is not None
    # the no-third-program proof: one prefill + one decode compile
    assert eng2.prefill_cache_size() == 1
    assert eng2.decode_cache_size() == 1
    eng2.allocator.check_invariants()


def test_spec_skips_stochastic_slots(setup):
    """Speculation is a greedy-path optimization: a stochastic slot
    never drafts, and its seeded stream matches the spec-off engine's
    (same lanes, same draws)."""
    cfg, params = setup
    mk = lambda: Request(  # noqa: E731
        rid=0, prompt=[2, 4, 6, 8], max_new_tokens=8,
        sampling=SamplingParams(temperature=0.9, seed=5))
    eng = _engine(cfg, params, sampling=True)
    a = mk()
    _drain(eng, [a])
    eng2 = _engine(cfg, params, sampling=True, spec_decode=4)
    b = mk()
    _drain(eng2, [b])
    assert b.out_tokens == a.out_tokens
    assert eng2.verify_calls == 0  # nothing drafted for the sampler


# ---------------------------------------------------------- prefix cache


def test_prefix_cache_knob_asymmetry(monkeypatch):
    with pytest.raises(ValueError):
        prefix_mod.set_prefix_cache(1)
    with pytest.raises(ValueError):
        prefix_mod.resolve(per_call="on")
    monkeypatch.setenv("APEX_SERVE_PREFIX_CACHE", "1")
    assert prefix_mod.resolve() is True
    monkeypatch.setenv("APEX_SERVE_PREFIX_CACHE", "0")
    assert prefix_mod.resolve() is False
    monkeypatch.delenv("APEX_SERVE_PREFIX_CACHE")
    prefix_mod.set_prefix_cache(True)
    try:
        assert prefix_mod.resolve() is True
        assert prefix_mod.resolve(per_call=False) is False
    finally:
        prefix_mod.set_prefix_cache(None)


def test_allocator_transfer():
    alloc = PageAllocator(8)
    pages = alloc.alloc(("req", 1), 3)
    alloc.transfer(("req", 1), ("prefix", pages[0]), [pages[0]])
    alloc.check_invariants()
    assert alloc.live_pages(("prefix", pages[0])) == [pages[0]]
    assert sorted(alloc.live_pages(("req", 1))) == sorted(pages[1:])
    with pytest.raises(ValueError, match="not owned"):
        alloc.transfer(("req", 1), ("x",), [pages[0]])
    alloc.check_invariants()
    # freeing each owner returns everything
    alloc.free(("req", 1))
    alloc.free(("prefix", pages[0]))
    assert alloc.free_count == 7


def test_prefix_cache_unit_lookup_register_reclaim():
    alloc = PageAllocator(16)
    pc = PrefixCache(alloc, 4)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # 2 full pages + tail 2
    pages = alloc.alloc(("req", 0), 3)
    adopted, copies = pc.register(prompt, pages, ("req", 0))
    assert adopted == pages[:2]
    assert len(copies) == 1 and copies[0][0] == pages[2]
    pc.acquire(adopted)
    pc.check_invariants()
    alloc.check_invariants()
    # a second registration of the same chain adopts nothing
    pages_b = alloc.alloc(("req", 1), 3)
    adopted_b, copies_b = pc.register(prompt, pages_b, ("req", 1))
    assert adopted_b == [] and copies_b == []
    alloc.free(("req", 1))
    # lookup covers 2 full pages + the 2-token tail of a longer prompt
    full, covered, tail = pc.lookup(prompt + [99, 98])
    assert full == pages[:2] and covered == 10 and tail is not None
    # an identical prompt never covers fully: the tail is dropped
    full, covered, tail = pc.lookup(list(prompt))
    assert covered == 8 and tail is None
    # a diverging page-2 misses past page 1
    full, covered, _ = pc.lookup([1, 2, 3, 4, 99, 6, 7, 8, 9])
    assert covered == 4 and full == pages[:1]
    # reclaim refuses referenced pages; releases unlock them
    live_before = len(alloc.live_pages())
    freed = pc.reclaim(8)
    assert freed == 1  # only the unreferenced tail snapshot
    pc.check_invariants()
    pc.release(adopted)
    assert pc.reclaim(8) == 2
    pc.check_invariants()
    alloc.check_invariants()
    assert len(alloc.live_pages()) == live_before - 3


def test_shared_prefix_prefilled_once_two_request_trace(setup):
    """THE acceptance trace: two requests sharing a system prompt —
    the shared pages are prefilled once (prefill dispatch count and
    allocator accounting asserted), the second request's tokens equal
    the cold oracle's, refcounts track the live holders."""
    cfg, params = setup
    rs = np.random.RandomState(3)
    shared = [int(t) for t in rs.randint(0, 128, 20)]  # 2.5 pages @ 8
    eng0 = _engine(cfg, params)
    o = Request(rid=0, prompt=list(shared), max_new_tokens=6)
    _drain(eng0, [o])

    eng = _engine(cfg, params, prefix_cache=True)
    a = Request(rid=0, prompt=list(shared), max_new_tokens=6)
    eng.submit(a)
    eng.step()
    # registrant live: its 2 full prompt pages are cache-owned with
    # refcount 1 (held by the registrant's own table)
    full_pages = [n["page"] for n in eng.prefix.nodes.values()]
    assert len(full_pages) == 2
    assert all(eng.prefix.refs[p] == 1 for p in full_pages)
    while not a.done():
        eng.step()
    eng.step()  # evict -> refs drop to 0, pages stay cached
    assert all(eng.prefix.refs[p] == 0 for p in full_pages)
    batches_before = eng.prefill_batches
    assert batches_before == 1

    b = Request(rid=1, prompt=list(shared), max_new_tokens=6)
    eng.submit(b)
    eng.step()
    # the hit re-references the SAME pages — shared prompt prefilled
    # once per engine, not once per request
    assert all(eng.prefix.refs[p] == 1 for p in full_pages)
    slot = next(s for s in eng.scheduler.slots if s is not None)
    assert slot.shared_pages == full_pages
    assert slot.prefix_hit > 0
    while not b.done():
        eng.step()
    eng.step()
    assert eng.prefill_batches == batches_before, \
        "the second request re-prefilled the shared prompt"
    assert b.out_tokens == o.out_tokens, \
        "cache-hit continuation diverged from the cold oracle"
    eng.prefix.check_invariants()
    eng.allocator.check_invariants()
    assert eng.generation_stats()["prefix_hit_rate"] > 0
    assert eng.decode_cache_size() == 1
    assert eng.prefill_cache_size() == 1


def test_prefix_cow_refcount_invariants_under_churn(setup):
    """Admit/evict/shared-prefix churn: many requests over a few
    shared system prompts through a small page pool (reclaim under
    pressure engaged) — allocator + prefix invariants hold at every
    round, every request completes, and every hit's tokens equal its
    prompt-twin's."""
    cfg, params = setup
    rs = np.random.RandomState(7)
    prefixes = [[int(t) for t in rs.randint(0, 128, n)]
                for n in (12, 20)]
    reqs = []
    for i in range(10):
        pre = prefixes[i % 2]
        suffix = [int(t) for t in rs.randint(0, 128, 1 + i % 4)]
        reqs.append(Request(rid=i, prompt=pre + suffix,
                            max_new_tokens=3 + i % 5,
                            arrival=float(i)))
    eng = _engine(cfg, params, prefix_cache=True, num_pages=32)
    pending = list(reqs)
    guard = 0
    while len(eng.scheduler.completed) < len(reqs):
        assert guard < 300
        due = [r for r in pending if r.arrival <= eng.tick]
        pending = [r for r in pending if r.arrival > eng.tick]
        eng.step(arrivals=due)
        eng.allocator.check_invariants()
        eng.prefix.check_invariants()
        guard += 1
    eng.step()
    eng.prefix.check_invariants()
    # all refs drained after the final evict
    assert all(n == 0 for n in eng.prefix.refs.values())
    # prompt-twins (same full prompt) must agree token-for-token
    by_prompt = {}
    for r in reqs:
        by_prompt.setdefault(tuple(r.prompt), []).append(r)
    for twins in by_prompt.values():
        n = min(r.max_new_tokens for r in twins)
        streams = {tuple(r.out_tokens[:n]) for r in twins}
        assert len(streams) == 1, "prompt twins diverged"
    assert eng.generation_stats()["prefix_hit_rate"] > 0


def test_admission_reclaim_never_frees_matched_cover():
    """Regression (review finding): under page pressure, the reclaim
    that admission triggers must NEVER free the very pages its own
    request just matched — the matched cover is fenced, so the
    admission either shares intact pages or blocks honestly."""
    alloc = PageAllocator(8)                     # 7 allocatable
    pc = PrefixCache(alloc, 4)
    sch = ContinuousBatchingScheduler(2, 8, 4, alloc, prefix=pc)
    hog = Request(rid=9, prompt=[7] * 8, max_new_tokens=8)  # 4 pages
    sch.submit(hog)
    assert sch.admit(0) == [0]
    # register a 1-full-page + 2-token-tail prefix, registrant gone
    pre = [1, 2, 3, 4, 5, 6]
    pages = alloc.alloc(("req", 0), 2)
    pc.register(pre, pages, ("req", 0))
    alloc.free(("req", 0))
    pc.check_invariants()
    alloc.check_invariants()
    assert alloc.free_count == 1
    chain_page = next(iter(pc.nodes.values()))["page"]
    snap_page = next(iter(pc.tails.values()))["page"]
    # same-prefix request needing 2 private pages over 1 free: the
    # reclaim path engages but must refuse the matched cover -> the
    # request BLOCKS instead of aliasing freed pages into itself
    b = Request(rid=1, prompt=pre + [9], max_new_tokens=4)
    sch.submit(b)
    assert sch.admit(1) == []
    pc.check_invariants()
    alloc.check_invariants()
    assert chain_page in [n["page"] for n in pc.nodes.values()]
    assert snap_page in [t["page"] for t in pc.tails.values()]
    # pressure released -> the admission shares the INTACT cover
    hog.out_tokens.extend([0] * 8)
    sch.evict_done(2)
    admitted = sch.admit(2)
    assert len(admitted) == 1
    slot = sch.slots[admitted[0]]
    assert len(set(slot.pages)) == len(slot.pages), "page aliased"
    assert slot.shared_pages == [chain_page]
    assert pc.refs[chain_page] == 1
    assert slot.cow_copies == [(snap_page, slot.pages[1])]
    pc.check_invariants()
    alloc.check_invariants()


# ------------------------------------------------------- priority policy


def test_priority_policy_orders_and_never_starves():
    """Same-arrival requests admit in priority order; a low-priority
    early request is never starved by a stream of high-priority
    arrivals (the aging rule) — and everything completes."""
    alloc = PageAllocator(16)
    sch = ContinuousBatchingScheduler(1, 8, 8, alloc,
                                      policy="priority")
    reqs = [Request(rid=i, prompt=[1] * 4, max_new_tokens=2,
                    priority=i, arrival=0) for i in range(4)]
    for r in reqs:
        sch.submit(r)
    order = []
    tick = 0
    while len(sch.completed) < len(reqs):
        assert tick < 100
        sch.evict_done(tick)
        for i in sch.admit(tick):
            order.append(sch.slots[i].request.rid)
        for i in sch.active_indices():
            slot = sch.slots[i]
            slot.pos += 1
            slot.request.out_tokens.append(0)
        tick += 1
    assert order == [3, 2, 1, 0], "priority order not honored"

    # aging: an old priority-0 request eventually beats priority-1
    # arrivals (AGING_TICKS=8 -> it outranks them after 8 ticks wait)
    alloc = PageAllocator(16)
    sch = ContinuousBatchingScheduler(1, 8, 8, alloc,
                                      policy="priority")
    old = Request(rid=100, prompt=[1] * 4, max_new_tokens=2,
                  priority=0, arrival=0)
    sch.submit(old)
    tick = 0
    admitted_old_at = None
    while admitted_old_at is None:
        assert tick < 60, "aging never admitted the old request"
        sch.evict_done(tick)
        # a fresh priority-1 competitor arrives every round
        sch.submit(Request(rid=tick, prompt=[1] * 4, max_new_tokens=2,
                           priority=1, arrival=tick))
        for i in sch.admit(tick):
            if sch.slots[i].request.rid == 100:
                admitted_old_at = tick
        for i in sch.active_indices():
            slot = sch.slots[i]
            slot.pos += 1
            slot.request.out_tokens.append(0)
        tick += 1
    assert admitted_old_at is not None
    alloc.check_invariants()


def test_priority_ages_waiting_time_not_absolute_tick():
    """Regression (review finding): the aging base is the tick the
    request ENTERED the queue, not its `arrival` field — a request
    submitted directly at a late engine tick (arrival left at its 0.0
    default) must get NO spurious boost over a waiting higher-priority
    request."""
    alloc = PageAllocator(32)
    sch = ContinuousBatchingScheduler(1, 8, 8, alloc,
                                      policy="priority")
    urgent = Request(rid=1, prompt=[1] * 4, max_new_tokens=2,
                     priority=5, arrival=78.0)
    sch.submit(urgent, tick=78)
    # a fresh zero-priority direct submission at tick 80: without the
    # queued_tick stamp its aging term would be 80/8 = 10 > 5
    late = Request(rid=2, prompt=[1] * 4, max_new_tokens=2, priority=0)
    sch.submit(late, tick=80)
    admitted = sch.admit(80)
    assert [sch.slots[i].request.rid for i in admitted] == [1], \
        "a newcomer's absolute tick outboosted a waiting priority-5"


# ------------------------------------------------- two-program stability


def test_two_compiled_programs_with_everything_enabled(setup):
    """The headline jaxpr-stability acceptance: sampling + speculative
    decode + prefix cache + priority policy all ON over a churning
    mixed trace — the engine still compiles EXACTLY two programs (one
    packed prefill serving admissions AND verifies, one decode), and
    every invariant surface stays clean."""
    cfg, params = setup
    rs = np.random.RandomState(9)
    shared = [int(t) for t in rs.randint(0, 128, 12)]
    reqs = []
    for i in range(8):
        suffix = [int(t) for t in rs.randint(0, 128, 1 + i % 3)]
        reqs.append(Request(
            rid=i, prompt=shared + suffix, max_new_tokens=3 + i % 6,
            arrival=float(i) * 0.7, priority=i % 3,
            sampling=SamplingParams(temperature=0.8, top_k=16, seed=i)
            if i % 2 else None))
    eng = _engine(cfg, params, num_slots=3, num_pages=64,
                  sampling=True, spec_decode=3, prefix_cache=True,
                  policy="priority")
    done = eng.run_trace(reqs)
    eng.step()
    assert len(done) == len(reqs)
    assert eng.decode_cache_size() == 1, \
        "decode recompiled with the generation layers on"
    assert eng.prefill_cache_size() == 1, \
        "prefill recompiled — the verify batch took a third program"
    assert eng.verify_calls > 0 and eng.prefill_batches > 0
    eng.allocator.check_invariants()
    eng.prefix.check_invariants()
    assert eng.generation_stats()["prefix_hit_rate"] > 0


# ------------------------------------------------------- ledger / checks


def _serving_block(**kw):
    blk = {"tokens_per_s": 10.0, "p50_ms": 1.0, "p99_ms": 2.0,
           "trace_id": "tr-0123456789", "kv_pages": 8,
           "spec_acceptance_rate": None, "draft_len": None,
           "prefix_hit_rate": None}
    blk.update(kw)
    return blk


def test_serving_block_generation_field_teeth():
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"serving": _serving_block(spec_acceptance_rate=0.9,
                                         draft_len=2.5,
                                         prefix_hit_rate=0.4)})
    assert ledger_mod.validate_record(rec) == []
    for mut, needle in (
            ({"spec_acceptance_rate": 1.5}, "spec_acceptance_rate"),
            ({"spec_acceptance_rate": True}, "spec_acceptance_rate"),
            ({"prefix_hit_rate": -0.1}, "prefix_hit_rate"),
            ({"draft_len": -1}, "draft_len")):
        r = ledger_mod.make_record(
            "profile_serving", "cpu", 0.1, 2,
            extra={"serving": _serving_block(**mut)})
        assert any(needle in p for p in ledger_mod.validate_record(r)), \
            (mut, ledger_mod.validate_record(r))


BASE_PINS = {"APEX_SERVE_WEIGHT_QUANT": "0",
             "APEX_DECODE_ATTN_IMPL": "jnp",
             # ISSUE 17: serving rows must also pin the decode block
             # size (check 8 — an unpinned K cannot be audited)
             "APEX_SERVE_DECODE_K": "1",
             # ISSUE 20: and the KV-tier knobs (int8 cache + swap
             # restore are different cache tiers)
             "APEX_SERVE_KV_QUANT": "0",
             "APEX_SERVE_KV_SWAP": "0"}


def _check8(tmp_path, knobs, block):
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 knobs=knobs,
                                 extra={"serving": block})
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"generation row cites ledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    from tests.conftest import run_check_bench_labels

    return run_check_bench_labels(
        "--perf", str(perf), "--ledger", str(ledger),
        "--table", str(table))


def test_check8_speculative_row_must_pin_spec_decode(tmp_path):
    out = _check8(tmp_path, dict(BASE_PINS),
                  _serving_block(spec_acceptance_rate=0.9,
                                 draft_len=2.0))
    assert out.returncode == 1
    assert "APEX_SPEC_DECODE" in out.stdout
    # pinned OFF while the block claims a rate is drift too
    out = _check8(tmp_path, dict(BASE_PINS, APEX_SPEC_DECODE="0"),
                  _serving_block(spec_acceptance_rate=0.9,
                                 draft_len=2.0))
    assert out.returncode == 1
    assert "different programs" in out.stdout
    out = _check8(tmp_path, dict(BASE_PINS, APEX_SPEC_DECODE="4"),
                  _serving_block(spec_acceptance_rate=0.9,
                                 draft_len=2.0))
    assert out.returncode == 0, out.stdout


def test_check8_prefix_row_must_pin_prefix_cache(tmp_path):
    out = _check8(tmp_path, dict(BASE_PINS),
                  _serving_block(prefix_hit_rate=0.5))
    assert out.returncode == 1
    assert "APEX_SERVE_PREFIX_CACHE" in out.stdout
    out = _check8(tmp_path,
                  dict(BASE_PINS, APEX_SERVE_PREFIX_CACHE="1"),
                  _serving_block(prefix_hit_rate=0.5))
    assert out.returncode == 0, out.stdout
    # None-when-disabled needs no generation pins (legacy-compatible)
    out = _check8(tmp_path, dict(BASE_PINS), _serving_block())
    assert out.returncode == 0, out.stdout


def test_gauges_carry_generation_counters(setup):
    from apex_tpu.serving import lifecycle
    from apex_tpu.telemetry import metrics

    cfg, params = setup
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, spec_decode=3)
    finally:
        lifecycle.reset_enabled()
    r = Request(rid=0, prompt=[2, 4, 6, 8], max_new_tokens=10)
    _drain(eng, [r])
    assert eng.events.gauges
    last = eng.events.gauges[-1]
    assert last["serve_spec_drafted"] >= last["serve_spec_accepted"] \
        >= 0
    assert last["serve_spec_drafted"] == eng.spec_stats.drafted
    assert last["serve_prefix_hit_tokens"] == 0
    # the names are registered metric specs (strict-writer contract)
    for name in ("serve_spec_drafted", "serve_spec_accepted",
                 "serve_prefix_hit_tokens"):
        assert metrics.spec(name) is not None
