"""Quantized + hierarchical collectives (apex_tpu.parallel.collectives).

The ISSUE-8 proof surface, all on the CPU backend (conftest's 8-device
mesh) — no TPU window required:

* codec + error feedback: the residual recovers sub-quantum signal a
  plain int8 path drops (and the optimization-level twin: GD converges
  with EF where stateless int8 stalls);
* knob asymmetry: per-call raises, setter/env preferences fall back;
* byte-identity: with every knob off, DDP's ``allreduce_gradients``
  emits the exact pre-collectives jaxpr, and the ZeRO update jaxpr
  carries no quantization artifacts;
* the dispatch-table "grad_comm" consult sits strictly below
  per-call/setter/env;
* payload accounting: ``costs.comm_from_jaxpr`` proves the >=3.5x
  dp-axis cut with int8 on, and the hierarchical inter-slice cut;
* ZeRO trajectory parity over >=20 steps of a real objective:
  uncompressed matches the unsharded optimizer bitwise, compressed
  tracks inside the tolerance band;
* the ledger/checker/report plumbing for the ``comm_compression``
  cost-block stamp (costs.validate, check_bench_labels check 7,
  window_report comm rows, the profile_comm/autotune rung wiring).
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import collectives as C
from apex_tpu.parallel.distributed import allreduce_gradients
from apex_tpu import dispatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_knobs(monkeypatch):
    for k in ("APEX_GRAD_COMPRESS", "APEX_HIER_ALLREDUCE",
              "APEX_DISPATCH", "APEX_DISPATCH_TABLE"):
        monkeypatch.delenv(k, raising=False)
    C._reset_for_tests()
    dispatch._reset_for_tests()
    yield
    C._reset_for_tests()
    dispatch._reset_for_tests()


def _jx(fn, *args):
    """Trace with a FRESH function object (jax trace caches key on
    identity; knob resolution is trace-time)."""
    return str(jax.make_jaxpr(lambda *a: fn(*a))(*args))


def _mesh(n, names=("dp",), shape=None):
    return Mesh(np.array(jax.devices()[:n]).reshape(shape or (n,)), names)


# ------------------------------------------------------------- codec

def test_quantize_dequantize_roundtrip_properties():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(300) * 10, jnp.float32)  # pads 300 -> 384
    q, s = C.quantize_blocks(x, block=128)
    assert q.dtype == jnp.int8 and s.dtype == jnp.bfloat16
    assert q.shape == (3, 128) and s.shape == (3,)
    dq = C.dequantize_blocks(q, s, 300)
    assert dq.shape == (300,)
    # error bounded by half a quantum per element (amax/127 per block,
    # + bf16 scale rounding headroom)
    amax = np.abs(np.asarray(x)).reshape(-1)
    err = np.abs(np.asarray(dq) - np.asarray(x))
    assert err.max() <= (np.abs(np.asarray(x)).max() / 127.0) * 0.6

    # values that are exact multiples of a bf16-exact quantum roundtrip
    # exactly: block max 127.0 -> scale 1.0
    v = jnp.asarray([127.0, -127.0, 3.0, -5.0] + [0.0] * 124, jnp.float32)
    q2, s2 = C.quantize_blocks(v, block=128)
    np.testing.assert_array_equal(np.asarray(C.dequantize_blocks(q2, s2, 128)),
                                  np.asarray(v))

    # a non-finite block poisons to non-finite (found_inf survives the
    # wire) instead of flushing to zero — for inf AND for NaN (a NaN
    # amax fails the `> 0` scale test and int8-casts to 0, so without
    # the isfinite guard the block would flush to FINITE zero and the
    # EF residual would turn NaN forever)
    for poison in (jnp.inf, jnp.nan):
        bad = v.at[1].set(poison)
        qb, sb = C.quantize_blocks(bad, block=128)
        dq = np.asarray(C.dequantize_blocks(qb, sb, 128))
        assert not np.isfinite(dq).all(), poison
        # ...and the EF residual stays finite (sanitized to 0 where
        # the dequantized value went non-finite)
        comp, emit = C._compensate(bad, jnp.zeros((128,), jnp.float32))
        res = emit(*C.quantize_blocks(comp, block=128))
        assert np.isfinite(np.asarray(res)).all(), poison


def test_error_feedback_recovers_subquantum_signal():
    """The EF property: a 0.3 signal in a block whose quantum is ~0.79
    (max 100) quantizes to 0 EVERY step without feedback; with the
    residual carried, the emitted sum over N steps approaches N*0.3."""
    x = jnp.zeros((128,), jnp.float32).at[0].set(100.0).at[1].set(0.3)
    n_steps = 16

    def run(residual):
        emitted = np.zeros(128, np.float64)
        res = residual
        for _ in range(n_steps):
            comp, emit = C._compensate(x, res)
            q, s = C.quantize_blocks(comp, block=128)
            dq = C.dequantize_blocks(q, s, 128)
            emitted += np.asarray(dq, np.float64)
            res = emit(q, s) if res is not None else None
        return emitted

    no_ef = run(None)
    with_ef = run(jnp.zeros((128,), jnp.float32))
    assert no_ef[1] == 0.0  # dropped forever
    want = n_steps * 0.3
    assert abs(with_ef[1] - want) <= 100.0 / 127.0 + 0.05, with_ef[1]


def test_error_feedback_converges_where_plain_int8_stalls():
    """Optimization-level EF twin: gradient descent through the
    quantized allreduce on a mesh. The loss surface puts a large
    gradient coordinate in the same block as small ones, so the
    stateless int8 path drops the small coordinates' updates; the
    EF-threaded path recovers them."""
    n = 2
    mesh = _mesh(n)
    w0 = jnp.full((128,), 0.6)
    lr = 0.05

    def make_run(use_ef):
        def run(w):
            res = jnp.zeros((128,), jnp.float32) if use_ef else None
            # each rank adds a PERSISTENT +/-200 to coordinate 0 of its
            # local gradient — antisymmetric across the 2 ranks, so the
            # mean (and w[0]'s trajectory) is untouched, but every
            # sender's block scale stays ~200/127 forever: the true
            # gradient (0.6, decaying) is sub-HALF-quantum from step 0
            sign = 1.0 - 2.0 * lax.axis_index("dp").astype(jnp.float32)

            def body(carry, _):
                w, res = carry
                g = w.at[0].add(sign * 200.0)  # quadratic grad + bias
                rg, new_res = C.quantized_allreduce_flat(
                    g, ("dp",), mean=True, residual=res)
                return (w - lr * rg,
                        new_res if use_ef else res), jnp.sum(w ** 2)

            (w, _), losses = lax.scan(body, (w, res), jnp.arange(40))
            return w, losses
        return run

    def go(use_ef):
        f = shard_map(make_run(use_ef), mesh=mesh, in_specs=(P(),),
                      out_specs=(P(), P()), check_vma=False)
        return jax.jit(f)(w0)

    w_ef, _ = go(True)
    w_plain, _ = go(False)
    small_ef = float(jnp.max(jnp.abs(w_ef[1:])))
    small_plain = float(jnp.min(jnp.abs(w_plain[1:])))
    # EF: the sub-quantum coordinates still descend toward 0; plain
    # int8: they quantize to 0 every step and NEVER move
    assert small_ef < 0.3, small_ef
    assert abs(small_plain - 0.6) < 1e-6, small_plain  # f32 0.6


# ------------------------------------------------------------- knobs

def test_per_call_raises_preferences_fall_back():
    # per-call: explicit request != preference
    with pytest.raises(ValueError):
        C.resolve_compress("fp4")
    with pytest.raises(ValueError):
        C.resolve_hier(True, ("dp",))
    # a setter CALL with an unknown scheme raises too
    with pytest.raises(ValueError):
        C.set_grad_compress("fp4")
    with pytest.raises(ValueError):
        C.set_hier_allreduce("yes")
    # ...but the pinned hier PREFERENCE falls back on an unfactored axis
    C.set_hier_allreduce(True)
    assert C.resolve_hier(None, ("dp",)) is False
    assert C.resolve_hier(None, ("dp_in", "dp_out")) is True
    C.set_hier_allreduce(None)
    # env is a preference: unknown scheme warns once and stays off
    os.environ["APEX_GRAD_COMPRESS"] = "fp4"
    try:
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert C.resolve_compress(None) is None
            assert C.resolve_compress(None) is None
        assert len([w for w in rec
                    if "APEX_GRAD_COMPRESS" in str(w.message)]) == 1
    finally:
        del os.environ["APEX_GRAD_COMPRESS"]
        C._reset_for_tests()
    # same convention for the hier env knob: "true"/"yes" would
    # silently measure the FLAT path under a hierarchical label
    os.environ["APEX_HIER_ALLREDUCE"] = "true"
    try:
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert C.resolve_hier(None, ("a", "b")) is False
        assert any("APEX_HIER_ALLREDUCE" in str(w.message) for w in rec)
    finally:
        del os.environ["APEX_HIER_ALLREDUCE"]
        C._reset_for_tests()
    # per-call False/"off" pins off over any preference
    C.set_grad_compress("int8")
    assert C.resolve_compress(False) is None
    assert C.resolve_compress("off") is None
    assert C.resolve_compress(None) == "int8"
    C.set_grad_compress(None)


def test_snapshot_and_disabled(monkeypatch):
    assert C.snapshot() == {"scheme": None, "hierarchical": False,
                            "block": C.DEFAULT_BLOCK}
    monkeypatch.setenv("APEX_GRAD_COMPRESS", "int8")
    monkeypatch.setenv("APEX_HIER_ALLREDUCE", "1")
    assert C.snapshot()["scheme"] == "int8"
    assert C.snapshot()["hierarchical"] is True
    with C.disabled():
        assert C.resolve_compress(None) is None
        assert C.resolve_hier(None, ("a", "b")) is False
        # explicit per-call demands still honor themselves
        assert C.resolve_compress("int8") == "int8"
    assert C.resolve_compress(None) == "int8"


# ------------------------------------------------- jaxpr byte-identity

def test_ddp_knob_off_jaxpr_byte_identical():
    """With every knob off, allreduce_gradients emits the exact
    pre-collectives jaxpr (the PR-1 invariant class): one psum per
    leaf, same dtype casts, same pre/post scaling."""
    mesh = _mesh(4)
    grads = {"w": jnp.ones((5, 3), jnp.bfloat16),
             "b": jnp.ones((7,), jnp.float32)}

    def legacy(grads, axis_name="dp", gradient_average=True,
               allreduce_always_fp32=False, gradient_predivide_factor=1.0):
        # the pre-ISSUE-8 implementation, verbatim
        world = jax.lax.psum(1, axis_name)

        def reduce_one(g):
            orig = g.dtype
            if allreduce_always_fp32:
                g = g.astype(jnp.float32)
            if gradient_predivide_factor != 1.0:
                g = g / gradient_predivide_factor
            g = jax.lax.psum(g, axis_name)
            if gradient_average:
                post = world / gradient_predivide_factor \
                    if gradient_predivide_factor != 1.0 else world
                g = g / post
            elif gradient_predivide_factor != 1.0:
                g = g * gradient_predivide_factor
            return g.astype(orig) if allreduce_always_fp32 else g

        return jax.tree_util.tree_map(reduce_one, grads)

    for kw in ({}, {"allreduce_always_fp32": True},
               {"gradient_predivide_factor": 2.0},
               {"gradient_average": False}):
        def new_fn(g):
            return allreduce_gradients(g, "dp", **kw)

        def old_fn(g):
            return legacy(g, "dp", **kw)

        sm = lambda f: shard_map(f, mesh=mesh, in_specs=(P(),),
                                 out_specs=P(), check_vma=False)
        assert _jx(sm(new_fn), grads) == _jx(sm(old_fn), grads), kw


def test_zero_knob_off_jaxpr_has_no_quantization_artifacts():
    from apex_tpu.contrib.optimizers import distributed_fused_adam

    mesh = _mesh(4)
    params = {"w": jnp.ones((37,), jnp.float32)}
    grads = {"w": jnp.full((37,), 0.1, jnp.float32)}

    def run_with(**kw):
        tx = distributed_fused_adam(learning_rate=0.1, num_shards=4,
                                    axis_name="dp", **kw)

        def one(p, g):
            st = tx.init(p)
            upd, st = tx.update(g, st, p)
            return upd

        return _jx(shard_map(one, mesh=mesh, in_specs=(P(), P()),
                             out_specs=P(), check_vma=False),
                   params, grads)

    off_default = run_with()
    off_explicit = run_with(grad_compress="off", hier_allreduce=False)
    assert off_default == off_explicit
    assert "int8" not in off_default and "all_to_all" not in off_default
    on = run_with(grad_compress="int8")
    assert "int8" in on and "all_to_all" in on


def test_ef_state_threading_and_ef_init():
    mesh = _mesh(4, names=("dp_in", "dp_out"), shape=(2, 2))
    grads = {"w": jnp.ones((100,), jnp.float32)}

    def probe(g):
        off = C.ef_init(g, ("dp_in", "dp_out"))
        flat = C.ef_init(g, ("dp_in", "dp_out"), compress="int8")
        hier = C.ef_init(g, ("dp_in", "dp_out"), compress="int8",
                         hierarchical=True)
        # threading through allreduce_gradients: returns (tree, state)
        red, new_state = allreduce_gradients(
            g, ("dp_in", "dp_out"), compress="int8", ef_state=flat)
        return (jnp.asarray(0 if off is None else 1),
                jnp.asarray(flat.shape[0]), jnp.asarray(hier.shape[0]),
                new_state, red["w"][0])

    out = jax.jit(shard_map(probe, mesh=mesh, in_specs=(P(),),
                            out_specs=(P(), P(), P(), P(), P()),
                            check_vma=False))(grads)
    assert int(out[0]) == 0          # off -> None (free when off)
    assert int(out[1]) == 100        # flat residual: full payload
    assert int(out[2]) == 50         # hier: the 1/inner piece
    assert out[3].shape == (100,)    # new residual, same shape
    np.testing.assert_allclose(float(out[4]), 1.0, rtol=1e-2)


# -------------------------------------------------- dispatch consult

def _grad_comm_entry(tmp_path, monkeypatch, nelems, choice):
    entry = {"op": "grad_comm", "bucket": dispatch.bucket(n=nelems),
             "dtype": "float32", "backend": "cpu", "choice": choice,
             "ledger": "lg-" + "0" * 10}
    path = tmp_path / "table.jsonl"
    path.write_text(json.dumps(entry) + "\n")
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(path))
    dispatch._reset_for_tests()


def test_dispatch_table_consult_strictly_below_knobs(tmp_path,
                                                     monkeypatch):
    mesh = _mesh(4)
    grads = {"w": jnp.ones((100,), jnp.float32)}
    _grad_comm_entry(tmp_path, monkeypatch, 100, "int8")

    def trace(**kw):
        def f(g):
            t, _ = C.allreduce_tree(g, ("dp",), **kw)
            return t

        return _jx(shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                             check_vma=False), grads)

    # unpinned: the table's int8 choice resolves
    assert "int8" in trace()
    # ...and lands in the consult log (pin-the-label)
    log = {(r["op"], r["bucket"]): r["choice"]
           for r in dispatch.consulted()}
    assert log.get(("grad_comm", dispatch.bucket(n=100))) == "int8"
    # per-call beats the table
    assert "int8" not in trace(compress=False)
    # setter beats the table
    C.set_grad_compress("off")
    assert "int8" not in trace()
    C.set_grad_compress(None)
    # an explicit env off-pin (present but empty/off) blocks the consult
    monkeypatch.setenv("APEX_GRAD_COMPRESS", "off")
    assert "int8" not in trace()
    monkeypatch.delenv("APEX_GRAD_COMPRESS")
    # APEX_DISPATCH=off kills the consult tier entirely
    monkeypatch.setenv("APEX_DISPATCH", "off")
    dispatch._reset_for_tests()
    assert "int8" not in trace()


def test_dispatch_table_hier_choice_needs_factored_axes(tmp_path,
                                                        monkeypatch):
    _grad_comm_entry(tmp_path, monkeypatch, 100, "int8_hier")
    mesh = _mesh(4, names=("dp_in", "dp_out"), shape=(2, 2))
    grads = {"w": jnp.ones((100,), jnp.float32)}

    def trace(axes, mesh):
        def f(g):
            t, _ = C.allreduce_tree(g, axes)
            return t

        return _jx(shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                             check_vma=False), grads)

    # factored declaration: the int8_hier choice stages the reduction
    # (reduce_scatter on the inner axis) AND quantizes the outer hop
    jx = trace(("dp_in", "dp_out"), mesh)
    assert "int8" in jx and "reduce_scatter" in jx
    # flat axis: the hier half of the choice falls back, int8 still on
    # (the one-shot gather-based quantized allreduce — no staging)
    jx_flat = trace(("dp",), _mesh(4))
    assert "int8" in jx_flat and "reduce_scatter" not in jx_flat
    # snapshot with nelems sees the table tier: a table-driven
    # compressed run stamps its cost block (check-7 visibility)
    snap = C.snapshot(nelems=100)
    assert snap["scheme"] == "int8" and snap["hierarchical"] is True
    # without nelems only setter/env tiers are visible
    assert C.snapshot()["scheme"] is None


# ---------------------------------------------- payload accounting

def _toy_cfg():
    from apex_tpu.transformer.testing.minimal import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)


def test_comm_bytes_int8_dp_reduction_at_least_3_5x():
    """The acceptance-criterion assert: comm_from_jaxpr measures a
    >=3.5x dp-axis gradient-payload cut with int8 on (trace-time, no
    device). block=128 int8+bf16 scales is 4/(1+2/128) ~ 3.94x."""
    from apex_tpu.transformer.testing.minimal import training_comm_bytes

    devs = jax.devices()[:8]
    cfg = _toy_cfg()
    base = training_comm_bytes(devs, cfg, (2, 4, 1), num_microbatches=2,
                               micro_batch_size=2, seq_len=16,
                               compress=False, hierarchical=False)
    q = training_comm_bytes(devs, cfg, (2, 4, 1), num_microbatches=2,
                            micro_batch_size=2, seq_len=16,
                            compress="int8", hierarchical=False)
    assert base["dp"] / q["dp"] >= 3.5, (base, q)
    # pp traffic untouched: the knob compresses the grad sync only
    assert base["pp"] == q["pp"]


def test_comm_bytes_hierarchical_cuts_inter_slice_hop():
    from apex_tpu.transformer.testing.minimal import training_comm_bytes

    devs = jax.devices()[:8]
    cfg = _toy_cfg()
    kw = dict(num_microbatches=2, micro_batch_size=2, seq_len=16)
    base = training_comm_bytes(devs, cfg, (2, (2, 2), 1),
                               compress=False, hierarchical=False, **kw)
    hier = training_comm_bytes(devs, cfg, (2, (2, 2), 1),
                               compress=False, hierarchical=True, **kw)
    both = training_comm_bytes(devs, cfg, (2, (2, 2), 1),
                               compress="int8", hierarchical=True, **kw)
    # flat tuple-axis allreduce moves the full payload over BOTH axes;
    # the two-stage reduction moves 1/inner (+gather) over the outer
    assert hier["dp_out"] <= base["dp_out"] * 0.76, (base, hier)
    # composed: the inter-slice hop additionally rides int8 (~3.9x)
    assert both["dp_out"] <= hier["dp_out"] / 3.5, (hier, both)


def test_dryrun_32_64_topology_plans():
    """The widened virtual-topology plans (ISSUE 8): pp=8 and tp=4
    finally exercised, plus hierarchically factored dp pairs."""
    import __graft_entry__
    from apex_tpu.transformer.testing.minimal import dp_axes_of

    t32 = __graft_entry__.dryrun_topologies(32)
    t64 = __graft_entry__.dryrun_topologies(64)
    assert (8, 2, 2) in t32 and (2, 4, 4) in t32
    assert (8, 2, 4) in t64
    assert any(isinstance(dp, tuple) for _, dp, _t in t32)
    assert any(isinstance(dp, tuple) for _, dp, _t in t64)
    for n, topos in ((32, t32), (64, t64)):
        for pp, dp, tp in topos:
            dp_size, dp_names, dp_sizes = dp_axes_of(dp)
            assert pp * dp_size * tp == n, (n, pp, dp, tp)
            if isinstance(dp, tuple):
                assert len(dp_names) == 2 and dp_sizes == tuple(dp)


# ------------------------------------------- ZeRO trajectory parity

def _regression_problem():
    rs = np.random.RandomState(3)
    X = jnp.asarray(rs.randn(32, 40), jnp.float32)
    w_true = jnp.asarray(rs.randn(40), jnp.float32)
    y = X @ w_true
    params = {"w": jnp.zeros((40,), jnp.float32),
              "b": jnp.zeros((1,), jnp.float32)}

    def loss_fn(p):
        pred = X @ p["w"] + p["b"][0]
        return jnp.mean((pred - y) ** 2)

    return params, loss_fn


def _zero_trajectory(steps=20, topology=8, **tx_kw):
    """Per-step losses of `steps` distributed_fused_adam steps on the
    regression objective; grads computed per rank (replicated batch)."""
    from apex_tpu.contrib.optimizers import distributed_fused_adam

    params, loss_fn = _regression_problem()
    if isinstance(topology, tuple):
        mesh = _mesh(topology[0] * topology[1],
                     names=("dp_in", "dp_out"), shape=topology)
        axis = ("dp_in", "dp_out")
        n = topology[0] * topology[1]
    else:
        mesh = _mesh(topology)
        axis, n = "dp", topology
    tx = distributed_fused_adam(learning_rate=0.05, num_shards=n,
                                axis_name=axis, **tx_kw)

    def run(p):
        st = tx.init(p)

        def body(carry, _):
            p, st = carry
            loss, g = jax.value_and_grad(loss_fn)(p)
            upd, st = tx.update(g, st, p)
            p = jax.tree_util.tree_map(jnp.add, p, upd)
            return (p, st), loss

        (_, _), losses = lax.scan(body, (p, st), jnp.arange(steps))
        return losses

    f = jax.jit(shard_map(run, mesh=mesh, in_specs=(P(),), out_specs=P(),
                          check_vma=False))
    return np.asarray(f(params), np.float64)


def _reference_trajectory(steps=20):
    from apex_tpu.optimizers.fused_adam import fused_adam

    params, loss_fn = _regression_problem()
    tx = fused_adam(learning_rate=0.05)

    def run(p):
        st = tx.init(p)

        def body(carry, _):
            p, st = carry
            loss, g = jax.value_and_grad(loss_fn)(p)
            upd, st = tx.update(g, st, p)
            p = jax.tree_util.tree_map(jnp.add, p, upd)
            return (p, st), loss

        (_, _), losses = lax.scan(body, (p, st), jnp.arange(steps))
        return losses

    return np.asarray(jax.jit(run)(params), np.float64)


def test_zero_trajectory_parity_20_steps():
    """ISSUE-8 acceptance: compressed trajectory inside the tolerance
    band of uncompressed over >=20 steps on the 8-device mesh. With
    the knobs off the trajectory is bitwise THE pre-ISSUE-8 ZeRO run
    (byte-identical jaxpr, asserted above — same program, same bits);
    vs the UNSHARDED optimizer the only drift is ZeRO's pre-existing
    flatten/concat reduction-order (last-ulp)."""
    ref = _reference_trajectory()
    flat = _zero_trajectory()
    np.testing.assert_allclose(flat, ref, rtol=2e-6, atol=1e-7)
    comp = _zero_trajectory(grad_compress="int8")
    # tolerance band: per-step relative deviation + both converge
    dev = np.abs(comp - flat) / np.maximum(np.abs(flat), 1e-8)
    assert dev.max() <= 0.06, (dev.max(), comp[-5:], flat[-5:])
    assert comp[-1] < comp[0] * 0.2  # converging (20 adam steps)
    # EF keeps the error from compounding: the last-5 window tracks
    assert np.abs(comp[-5:] - flat[-5:]).mean() <= \
        0.05 * max(flat[0], 1e-3)


@pytest.mark.slow  # second mesh shape = second compile of the same
# program family; the flat-axis twin above keeps the mechanism fast
def test_zero_trajectory_parity_hierarchical_composed():
    flat = _zero_trajectory()
    hier = _zero_trajectory(topology=(2, 4), hier_allreduce=True)
    np.testing.assert_allclose(hier, flat, rtol=1e-5, atol=1e-7)
    both = _zero_trajectory(topology=(2, 4), hier_allreduce=True,
                            grad_compress="int8")
    dev = np.abs(both - flat) / np.maximum(np.abs(flat), 1e-8)
    assert dev.max() <= 0.06, dev.max()
    assert both[-1] < both[0] * 0.2


# ------------------------------------------ ledger/checker plumbing

def test_costs_comm_compression_block_and_validate():
    from apex_tpu.telemetry import costs, ledger

    # nothing compressed -> no stamp (old records stay valid)
    assert costs.comm_compression_block(
        {"scheme": None, "hierarchical": False, "block": 128}) is None
    cc = costs.comm_compression_block(
        {"scheme": "int8", "hierarchical": True, "block": 128},
        {"dp": 400.0})
    block = costs.build(comm={"dp": 100.0}, comm_compression=cc)
    assert block["comm_compression"]["scheme"] == "int8"
    assert block["comm_compression"]["uncompressed_bytes_per_axis"] == \
        {"dp": 400.0}
    assert costs.validate(block) == []
    # malformed stamps are findings (ledger.validate_record teeth)
    for broken, frag in (
            ({"scheme": 5, "hierarchical": False}, "scheme"),
            ({"scheme": "int8", "hierarchical": "yes"}, "hierarchical"),
            ({"scheme": "int8", "hierarchical": True, "block": -1},
             "block"),
            ({"scheme": "int8", "hierarchical": True,
              "uncompressed_bytes_per_axis": {"dp": -4}},
             "uncompressed_bytes_per_axis")):
        bad = dict(block, comm_compression=broken)
        assert any(frag in p for p in costs.validate(bad)), (broken,
                                                             frag)
        rec = ledger.make_record("t", "cpu", 1.0, 4)
        rec["cost"] = bad
        assert any("comm_compression" in p
                   for p in ledger.validate_record(rec))


def test_check7_comm_compression_pin_match():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_bench_labels as cbl
    finally:
        sys.path.pop(0)
    stamp = {"scheme": "int8", "hierarchical": True, "block": 128}
    rec = {"id": "lg-" + "a" * 10, "knobs": {},
           "cost": {"comm_compression": stamp}}
    probs = cbl.comm_compress_problems(rec, rec["id"])
    assert len(probs) == 2  # unpinned scheme AND unpinned hier
    assert any("APEX_GRAD_COMPRESS" in p for p in probs)
    assert any("APEX_HIER_ALLREDUCE" in p for p in probs)
    rec["knobs"] = {"APEX_GRAD_COMPRESS": "int8",
                    "APEX_HIER_ALLREDUCE": "1"}
    assert cbl.comm_compress_problems(rec, rec["id"]) == []
    # span-level blocks are checked too
    rec2 = {"id": "lg-" + "b" * 10, "knobs": {},
            "spans": [{"name": "s", "cost": {"comm_compression": {
                "scheme": "int8", "hierarchical": False}}}]}
    assert any("APEX_GRAD_COMPRESS" in p
               for p in cbl.comm_compress_problems(rec2, rec2["id"]))
    # no stamp, no claim to check
    assert cbl.comm_compress_problems({"id": "x", "cost": {}}, "x") == []


def test_window_report_comm_rows():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import window_report as wr
    finally:
        sys.path.pop(0)
    recs = [{"harness": "profile_comm", "platform": "cpu", "id": "lg-1",
             "cost": {"source": "compiled",
                      "comm_bytes_per_axis": {"dp": 120.0},
                      "comm_compression": {
                          "scheme": "int8", "hierarchical": False,
                          "block": 128,
                          "uncompressed_bytes_per_axis": {"dp": 470.0}}}},
            {"harness": "bench", "platform": "cpu", "id": "lg-2",
             "cost": {"source": None}}]
    led = wr.ledger_summary(recs)
    assert len(led["comm"]) == 1
    row = led["comm"][0]
    assert row["bytes_per_axis"] == {"dp": 120.0}
    assert row["scheme"] == "int8"
    assert row["uncompressed_bytes_per_axis"] == {"dp": 470.0}


def test_grad_comm_rung_group_registered():
    from benchmarks.autotune_steps import rung_groups, shape_info

    for smoke in (True, False):
        groups = {g["name"]: g for g in rung_groups(smoke)}
        g = groups["grad_comm"]
        assert g["op"] == "grad_comm"
        assert g["harness"] == "profile_comm"
        assert g["metric"] == "dp grad sync step"
        assert set(g["variants"]) == {"off", "int8", "hier", "int8_hier"}
        assert g["variants"]["int8_hier"] == {
            "APEX_GRAD_COMPRESS": "int8", "APEX_HIER_ALLREDUCE": "1"}
        assert g["dims"] == {"n": shape_info(smoke)["comm_payload"]}
    # the op is in the dispatch vocabulary (table entries validate)
    assert dispatch.OP_CHOICES["grad_comm"] == (
        "off", "int8", "hier", "int8_hier")


def test_grad_comm_payload_bucket_mirrors_harness():
    """The autotune group's payload dims must land in the SAME pow2
    bucket as the param tree profile_comm actually builds (the
    'dims mirror what the harness builds' convention, enforced).
    eval_shape only — nothing compiles."""
    from benchmarks.autotune_steps import shape_info
    from apex_tpu.transformer.parallel_state import (
        PIPELINE_AXIS, DATA_AXIS, TENSOR_AXIS)
    from apex_tpu.transformer.testing.minimal import (
        TransformerConfig, make_gpt_fns, toy_batch)

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
                (PIPELINE_AXIS, DATA_AXIS, TENSOR_AXIS))
    # profile_comm's SMOKE cfg, verbatim
    S = 32
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=S,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)
    _, init_params = make_gpt_fns(cfg, 1)
    b = toy_batch(cfg.vocab_size, 2, 2, S)
    f = shard_map(
        lambda ids, labels: init_params(
            jax.random.PRNGKey(0), {"ids": ids[0], "labels": labels[0]}),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    shapes = jax.eval_shape(f, b["ids"], b["labels"])
    n = sum(x.size for x in jax.tree_util.tree_leaves(shapes))
    assert dispatch.bucket(n=n) == \
        dispatch.bucket(n=shape_info(True)["comm_payload"])


@pytest.mark.slow  # one real harness subprocess (~60-90s on this box)
def test_profile_comm_smoke_subprocess_e2e(tmp_path):
    from apex_tpu.telemetry import ledger as ledger_mod

    led = tmp_path / "ledger.jsonl"
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               APEX_BENCH_SMOKE="1", APEX_GRAD_COMPRESS="int8",
               APEX_TELEMETRY_LEDGER=str(led), APEX_COST_ANALYSIS="1")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "profile_comm.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "dp grad sync step" in out.stdout
    recs = ledger_mod.read_ledger(str(led))
    rec = next(r for r in recs if r.get("harness") == "profile_comm")
    assert ledger_mod.validate_record(rec) == []
    span = next(s for s in rec["spans"]
                if s["name"] == "dp grad sync step")
    cc = span["cost"]["comm_compression"]
    assert cc["scheme"] == "int8"
    unc = cc["uncompressed_bytes_per_axis"]
    comp = span["cost"]["comm_bytes_per_axis"]
    assert unc["dp"] / comp["dp"] >= 3.5
    # the knob pin rode into the record: check 7 is clean
    assert rec["knobs"].get("APEX_GRAD_COMPRESS") == "int8"
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_bench_labels as cbl
    finally:
        sys.path.pop(0)
    assert cbl.comm_compress_problems(rec, rec["id"]) == []
