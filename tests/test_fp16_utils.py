"""fp16_utils ports (reference tests: tests/L0/run_fp16util)."""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    clip_grad_norm,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
    tofp16,
)
from apex_tpu.optimizers.fused_adam import fused_adam


PARAMS = {
    "dense": {"kernel": jnp.ones((4, 4), jnp.float32),
              "bias": jnp.zeros((4,), jnp.float32)},
    "batchnorm_0": {"scale": jnp.ones((4,), jnp.float32)},
    "step": jnp.asarray(3, jnp.int32),  # non-float leaf stays untouched
}


def test_network_to_half_keeps_norms_fp32():
    half = network_to_half(PARAMS)
    assert half["dense"]["kernel"].dtype == jnp.float16
    assert half["batchnorm_0"]["scale"].dtype == jnp.float32
    assert half["step"].dtype == jnp.int32


def test_tofp16_and_convert_network_bf16():
    assert tofp16(PARAMS)["batchnorm_0"]["scale"].dtype == jnp.float16
    conv = convert_network(PARAMS, jnp.bfloat16)
    assert conv["dense"]["kernel"].dtype == jnp.bfloat16
    assert conv["batchnorm_0"]["scale"].dtype == jnp.float32


def test_prep_param_lists_flat_master_roundtrip():
    """Reference: test_fp16util.py flat_master round trip."""
    model = {"a": jnp.full((2, 3), 1.5, jnp.float16),
             "b": jnp.full((4,), -2.0, jnp.float16)}
    _, master = prep_param_lists(model, flat_master=True)
    assert master.dtype == jnp.float32 and master.shape == (10,)
    back = master_params_to_model_params(model, master, flat_master=True)
    for k in model:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(model[k]))
    grads = jax.tree_util.tree_map(jnp.ones_like, model)
    mg = model_grads_to_master_grads(grads, flat_master=True)
    assert mg.dtype == jnp.float32 and mg.shape == (10,)


def test_clip_grad_norm():
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
    clipped, total = clip_grad_norm(grads, max_norm=1.0)
    np.testing.assert_allclose(float(total), np.sqrt(90 + 160), rtol=1e-6)
    new_total = np.sqrt(sum(
        float(jnp.sum(g ** 2)) for g in jax.tree_util.tree_leaves(clipped)))
    np.testing.assert_allclose(new_total, 1.0, rtol=1e-4)


def test_to_python_float():
    assert to_python_float(jnp.asarray([2.5, 1.0])) == 2.5
    assert to_python_float(jnp.asarray(7)) == 7.0


def test_fp16_optimizer_step_and_overflow():
    """FP16_Optimizer: master weights update, model params track, overflow
    skips (reference: fp16_optimizer semantics)."""
    params = {"w": jnp.full((4,), 2.0, jnp.float16)}
    # init_scale small enough that scaled fp16 grads stay finite (2^16
    # would overflow fp16 here — which the dynamic scaler would then
    # legitimately skip)
    opt = FP16_Optimizer(fused_adam(learning_rate=0.1), params,
                         dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2.0 ** 8},
                         verbose=False)

    def lg(p_):
        def loss_fn(p):
            return jnp.sum(p["w"].astype(jnp.float32) ** 2) * opt.scaler_state.loss_scale
        return jax.value_and_grad(loss_fn)(p_)

    loss = opt.backward(lg, opt.model_params)
    opt.step()
    assert not opt.overflow
    assert float(opt.master_params["w"][0]) < 2.0
    np.testing.assert_allclose(np.asarray(opt.model_params["w"], np.float32),
                               np.asarray(opt.master_params["w"]), atol=1e-2)

    # inf grads → skip + scale halved
    before = opt.master_params["w"]
    scale_before = opt.loss_scale
    opt._grads = {"w": jnp.full((4,), np.inf, jnp.float16)}
    opt.step()
    assert opt.overflow
    np.testing.assert_array_equal(np.asarray(opt.master_params["w"]),
                                  np.asarray(before))
    assert opt.loss_scale == scale_before / 2


def test_fp16_optimizer_state_dict_roundtrip():
    params = {"w": jnp.full((4,), 2.0, jnp.float16)}
    opt = FP16_Optimizer(fused_adam(learning_rate=0.1), params,
                         dynamic_loss_scale=True, verbose=False)
    sd = opt.state_dict()
    opt2 = FP16_Optimizer(fused_adam(learning_rate=0.1), params,
                          dynamic_loss_scale=True, verbose=False)
    opt2.load_state_dict(sd)
    np.testing.assert_array_equal(np.asarray(opt2.master_params["w"]),
                                  np.asarray(opt.master_params["w"]))
