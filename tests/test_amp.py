"""amp frontend + policy tests (reference: tests/L0/run_amp/test_basic_casts.py,
test_promotion.py, test_checkpointing.py semantics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from apex_tpu import amp


# --------------------------- Properties / opt_levels ---------------------------

def test_opt_level_presets():
    p = amp.opt_levels["O2"](amp.Properties())
    assert p.cast_model_type == "half"
    assert p.master_weights is True
    assert p.loss_scale == "dynamic"
    p = amp.opt_levels["O1"](amp.Properties())
    assert p.patch_torch_functions is True
    assert p.cast_model_type is None
    p = amp.opt_levels["O0"](amp.Properties())
    assert p.loss_scale == 1.0
    p = amp.opt_levels["O3"](amp.Properties())
    assert p.master_weights is False


def test_properties_validation():
    p = amp.opt_levels["O1"](amp.Properties())
    with pytest.raises(RuntimeError):
        p.keep_batchnorm_fp32 = True  # O1 forbids explicit BN override
    with pytest.raises(RuntimeError):
        p.master_weights = True
    p2 = amp.opt_levels["O2"](amp.Properties())
    p2.keep_batchnorm_fp32 = "False"
    assert p2.keep_batchnorm_fp32 is False
    with pytest.raises(AttributeError):
        p2.not_an_option = 1


def test_bad_opt_level():
    with pytest.raises(RuntimeError):
        amp.initialize({"w": jnp.zeros(2)}, opt_level="O4")


# --------------------------- initialize: param casting ---------------------------

def _toy_params():
    return {
        "dense": {"kernel": jnp.ones((4, 4), jnp.float32),
                  "bias": jnp.zeros((4,), jnp.float32)},
        "batch_norm": {"scale": jnp.ones((4,), jnp.float32),
                       "bias": jnp.zeros((4,), jnp.float32)},
    }


def test_initialize_o2_casts_except_bn():
    params = amp.initialize(_toy_params(), opt_level="O2", verbosity=0)
    assert params["dense"]["kernel"].dtype == jnp.bfloat16
    assert params["batch_norm"]["scale"].dtype == jnp.float32  # keep_batchnorm_fp32


def test_initialize_o3_casts_everything():
    params = amp.initialize(_toy_params(), opt_level="O3", verbosity=0)
    assert params["dense"]["kernel"].dtype == jnp.bfloat16
    assert params["batch_norm"]["scale"].dtype == jnp.bfloat16


def test_initialize_o1_o0_keep_fp32_params():
    for lvl in ("O0", "O1"):
        params = amp.initialize(_toy_params(), opt_level=lvl, verbosity=0)
        assert params["dense"]["kernel"].dtype == jnp.float32


def test_initialize_fp16_override():
    params = amp.initialize(_toy_params(), opt_level="O2",
                            half_dtype=jnp.float16, verbosity=0)
    assert params["dense"]["kernel"].dtype == jnp.float16


# --------------------------- policy interpreter (O1 analog) ---------------------------

def test_autocast_half_function():
    @amp.half_function
    def mm(a, b):
        return a @ b

    a = jnp.ones((2, 2), jnp.float32)
    with amp.autocast(dtype=jnp.bfloat16):
        out = mm(a, a)
    assert out.dtype == jnp.bfloat16
    out = mm(a, a)  # outside autocast: untouched
    assert out.dtype == jnp.float32


def test_autocast_float_function():
    @amp.float_function
    def softmax(x):
        return jax.nn.softmax(x)

    x = jnp.ones((4,), jnp.bfloat16)
    with amp.autocast():
        out = softmax(x)
    assert out.dtype == jnp.float32


def test_promote_function():
    @amp.promote_function
    def add(a, b):
        return a + b

    a = jnp.ones((2,), jnp.bfloat16)
    b = jnp.ones((2,), jnp.float32)
    with amp.autocast():
        out = add(a, b)
    assert out.dtype == jnp.float32


def test_cast_table_lookup():
    assert amp.lookup_cast("matmul") == "half"
    assert amp.lookup_cast("softmax") == "float"
    assert amp.lookup_cast("add") == "promote"
    assert amp.lookup_cast("cat") == "sequence_promote"
    assert amp.lookup_cast("relu") is None
    with pytest.raises(NotImplementedError):
        amp.lookup_cast("binary_cross_entropy")


def test_cast_for_op():
    x = jnp.ones((2, 2), jnp.float32)
    with amp.autocast(dtype=jnp.bfloat16):
        (xc,) = amp.cast_for_op("matmul", x)
        assert xc.dtype == jnp.bfloat16
        (xf,) = amp.cast_for_op("softmax", jnp.ones((2,), jnp.bfloat16))
        assert xf.dtype == jnp.float32


def test_disable_casts():
    @amp.half_function
    def mm(a, b):
        return a @ b

    a = jnp.ones((2, 2), jnp.float32)
    with amp.autocast(dtype=jnp.bfloat16):
        with amp.disable_casts():
            out = mm(a, a)
    assert out.dtype == jnp.float32


# --------------------------- AmpOptimizer end-to-end ---------------------------

def _quadratic_loss(params, target):
    return jnp.sum((params["w"] - target) ** 2)


def test_amp_optimizer_o2_training_step():
    params32 = {"w": jnp.full((4,), 3.0, jnp.float32)}
    params, opt = amp.initialize(params32, optax.sgd(0.1), opt_level="O2",
                                 verbosity=0)
    assert params["w"].dtype == jnp.bfloat16
    state = opt.init(params)
    assert state.master_params["w"].dtype == jnp.float32
    target = jnp.zeros((4,), jnp.bfloat16)

    grad_fn = amp.value_and_scaled_grad(_quadratic_loss, opt)
    loss, grads, found_inf = grad_fn(params, state, target)
    assert not bool(found_inf)
    new_params, new_state, info = opt.apply_gradients(
        grads, state, params, grads_already_unscaled=True, found_inf=found_inf)
    # sgd on w=3, grad=2*3=6, lr=.1 → w=2.4
    np.testing.assert_allclose(
        np.asarray(new_state.master_params["w"]), np.full(4, 2.4), rtol=1e-2)
    assert new_params["w"].dtype == jnp.bfloat16


def test_amp_optimizer_skip_on_overflow():
    params = {"w": jnp.full((4,), 3.0, jnp.float32)}
    params, opt = amp.initialize(params, optax.sgd(0.1), opt_level="O2",
                                 verbosity=0)
    state = opt.init(params)
    bad_grads = {"w": jnp.full((4,), jnp.inf, jnp.bfloat16)}
    new_params, new_state, info = opt.apply_gradients(bad_grads, state, params)
    assert bool(info["overflow"])
    # params unchanged, scale halved
    np.testing.assert_allclose(np.asarray(new_state.master_params["w"], np.float32),
                               np.asarray(state.master_params["w"], np.float32))
    assert float(new_state.scalers[0].loss_scale) == 2.0 ** 15


def test_amp_optimizer_jit_full_step():
    params = {"w": jnp.full((8,), 5.0, jnp.float32)}
    params, opt = amp.initialize(params, optax.sgd(0.01), opt_level="O2",
                                 verbosity=0)
    state = opt.init(params)

    @jax.jit
    def train_step(params, state, target):
        def loss_fn(p, t):
            return jnp.sum((p["w"].astype(jnp.float32) - t) ** 2)
        grad_fn = amp.value_and_scaled_grad(loss_fn, opt)
        loss, grads, found_inf = grad_fn(params, state, target)
        new_p, new_s, info = opt.apply_gradients(
            grads, state, params, grads_already_unscaled=True,
            found_inf=found_inf)
        return new_p, new_s, loss

    target = jnp.zeros((8,), jnp.float32)
    losses = []
    for _ in range(20):
        params, state, loss = train_step(params, state, target)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_multi_loss_scalers():
    params = {"w": jnp.full((4,), 3.0, jnp.float32)}
    params, opt = amp.initialize(params, optax.sgd(0.1), opt_level="O2",
                                 num_losses=3, verbosity=0)
    state = opt.init(params)
    assert len(state.scalers) == 3
    bad = {"w": jnp.full((4,), jnp.nan, jnp.bfloat16)}
    _, state, _ = opt.apply_gradients(bad, state, params, loss_id=1)
    assert float(state.scalers[1].loss_scale) == 2.0 ** 15
    assert float(state.scalers[0].loss_scale) == 2.0 ** 16  # untouched


def test_update_scaler_advances_one_loss():
    """update_scaler: the shared-apply multi-loss pattern (DCGAN D step)
    — each loss's scale advances from its own overflow flag."""
    params = {"w": jnp.full((4,), 3.0, jnp.float32)}
    params, opt = amp.initialize(params, optax.sgd(0.1), opt_level="O2",
                                 num_losses=2, verbosity=0)
    state = opt.init(params)
    state = opt.update_scaler(state, jnp.bool_(True), loss_id=1)
    assert float(state.scalers[1].loss_scale) == 2.0 ** 15  # backed off
    assert float(state.scalers[0].loss_scale) == 2.0 ** 16  # untouched
    state = opt.update_scaler(state, jnp.bool_(False), loss_id=0)
    assert int(state.scalers[0].unskipped) == 1


def test_amp_state_dict_roundtrip():
    params = {"w": jnp.ones((2,), jnp.float32)}
    params, opt = amp.initialize(params, optax.sgd(0.1), opt_level="O2",
                                 verbosity=0)
    state = opt.init(params)
    bad = {"w": jnp.asarray([jnp.inf, 1.0], jnp.bfloat16)}
    _, state, _ = opt.apply_gradients(bad, state, params)
    sd = amp.state_dict([state])
    assert sd["loss_scaler0"]["loss_scale"] == 2.0 ** 15
    fresh = opt.init(params)
    [restored] = amp.load_state_dict(sd, [fresh])
    assert float(restored.scalers[0].loss_scale) == 2.0 ** 15
