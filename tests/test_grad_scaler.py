"""transformer.amp.GradScaler: the model-parallel found_inf MAX reduction
(reference: apex/transformer/amp/grad_scaler.py:38-49) and the torch-shaped
constructor mapping."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.amp import GradScaler

NDEV = 8


def test_constructor_mapping_and_validation():
    gs = GradScaler(init_scale=2.0 ** 10, growth_interval=500,
                    axis_names=("tp",))
    assert gs.init_scale == 2.0 ** 10
    assert gs.scale_window == 500
    assert gs.axis_names == ("tp",)
    state = gs.init()
    assert float(state.loss_scale) == 2.0 ** 10
    with pytest.raises(AssertionError, match="growth factor"):
        GradScaler(growth_factor=1.0, axis_names=())
    with pytest.raises(AssertionError, match="backoff"):
        GradScaler(backoff_factor=1.5, axis_names=())


def test_found_inf_syncs_over_model_parallel_axes():
    """One tp rank's overflow must make EVERY rank skip: without the pmax,
    TP peers would desynchronize (the bug the reference class exists to
    prevent)."""
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("pp", "tp"))
    gs = GradScaler(axis_names=("pp", "tp"))
    state = gs.init()

    def run(state):
        # only (pp=0, tp=0)'s shard overflows
        rank = jax.lax.axis_index("pp") * 4 + jax.lax.axis_index("tp")
        g = {"w": jnp.where(rank == 0, jnp.inf, 1.0)
             * jnp.ones((2,)) * state.loss_scale}
        _, found_inf = gs.unscale(g, state)
        new_state = gs.update(state, found_inf)
        return found_inf[None], new_state.loss_scale[None]

    found, scales = shard_map(
        run, mesh=mesh, in_specs=(P(),),
        out_specs=(P(("pp", "tp")), P(("pp", "tp"))),
        check_vma=False)(state)
    # every rank observed the overflow and every rank halved its scale
    assert np.all(np.asarray(found))
    np.testing.assert_array_equal(np.asarray(scales),
                                  np.full(NDEV, 2.0 ** 15, np.float32))


def test_found_inf_false_grows_after_window():
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    gs = GradScaler(growth_interval=2, axis_names=("tp",))
    state = gs.init()

    def run(state):
        for _ in range(2):
            g = {"w": jnp.ones((2,)) * state.loss_scale}
            _, found_inf = gs.unscale(g, state)
            state = gs.update(state, found_inf)
        return state.loss_scale[None]

    scale = shard_map(run, mesh=mesh, in_specs=(P(),), out_specs=P("tp"),
                      check_vma=False)(state)
    # 2 clean steps at growth_interval=2 -> one doubling
    np.testing.assert_array_equal(np.asarray(scale),
                                  np.full(2, 2.0 ** 17, np.float32))
