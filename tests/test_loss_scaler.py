"""LossScaler state-machine tests (reference semantics:
apex/amp/scaler.py:38-55,197-217 — init 2**16, x2 every scale_window
unskipped steps, /2 on overflow, min/max clamps)."""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.amp import LossScaler


def test_dynamic_init_and_scale():
    s = LossScaler(loss_scale="dynamic")
    st = s.init()
    assert float(st.loss_scale) == 2.0 ** 16
    loss = jnp.asarray(2.0)
    assert float(s.scale(loss, st)) == 2.0 * 2.0 ** 16


def test_overflow_halves_scale():
    s = LossScaler(loss_scale="dynamic")
    st = s.init()
    grads = {"w": jnp.asarray([1.0, jnp.inf])}
    _, st2, skip = s.unscale_and_update(grads, st)
    assert bool(skip)
    assert float(st2.loss_scale) == 2.0 ** 15
    assert int(st2.unskipped) == 0


def test_growth_after_window():
    s = LossScaler(loss_scale="dynamic", scale_window=3)
    st = s.init()
    grads = {"w": jnp.asarray([1.0, 2.0])}
    for i in range(3):
        _, st, skip = s.unscale_and_update(grads, st)
        assert not bool(skip)
    assert float(st.loss_scale) == 2.0 ** 17
    assert int(st.unskipped) == 0


def test_max_clamp():
    s = LossScaler(loss_scale="dynamic", scale_window=1, max_loss_scale=2.0 ** 17)
    st = s.init()
    grads = {"w": jnp.asarray([1.0])}
    for _ in range(5):
        _, st, _ = s.unscale_and_update(grads, st)
    assert float(st.loss_scale) == 2.0 ** 17


def test_min_clamp():
    s = LossScaler(loss_scale="dynamic", min_loss_scale=2.0 ** 15)
    st = s.init()
    grads = {"w": jnp.asarray([jnp.nan])}
    for _ in range(5):
        _, st, _ = s.unscale_and_update(grads, st)
    assert float(st.loss_scale) == 2.0 ** 15


def test_static_scale():
    s = LossScaler(loss_scale=128.0)
    st = s.init()
    assert float(st.loss_scale) == 128.0
    grads = {"w": jnp.asarray([256.0])}
    unscaled, st2, skip = s.unscale_and_update(grads, st)
    assert not bool(skip)
    assert float(st2.loss_scale) == 128.0
    np.testing.assert_allclose(np.asarray(unscaled["w"]), [2.0])


def test_unscale_values():
    s = LossScaler(loss_scale="dynamic")
    st = s.init()
    g = {"w": jnp.asarray([2.0 ** 16, 2.0 ** 17])}
    unscaled, found_inf = s.unscale(g, st)
    assert not bool(found_inf)
    np.testing.assert_allclose(np.asarray(unscaled["w"]), [1.0, 2.0])


def test_state_dict_roundtrip():
    s = LossScaler(loss_scale="dynamic")
    st = s.init()
    grads = {"w": jnp.asarray([jnp.inf])}
    _, st, _ = s.unscale_and_update(grads, st)
    d = LossScaler.state_dict(st)
    st2 = LossScaler.load_state_dict(s.init(), d)
    assert float(st2.loss_scale) == float(st.loss_scale)
    assert int(st2.unskipped) == int(st.unskipped)


def test_jit_safe():
    s = LossScaler(loss_scale="dynamic")
    st = s.init()

    @jax.jit
    def step(grads, st):
        return s.unscale_and_update(grads, st)

    g_ok = {"w": jnp.asarray([1.0])}
    g_bad = {"w": jnp.asarray([jnp.inf])}
    _, st, skip = step(g_ok, st)
    assert not bool(skip)
    _, st, skip = step(g_bad, st)
    assert bool(skip)
    assert float(st.loss_scale) == 2.0 ** 15
