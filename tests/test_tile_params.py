"""Tile-parameterized kernels (ISSUE 5): interpret-mode parity across
swept tile geometries, per-call/setter/env precedence, and the raising
vs falling-back asymmetry — for all four Pallas op families.

The kernel-test rule (CLAUDE.md): every swept geometry must match the
jnp/dense reference in interpret mode, including the minimum legal
tile, non-divisible edge shapes (which must RAISE per-call and FALL
BACK as preferences), and every backward structure and dtype.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.dispatch import tiles
from apex_tpu.ops import attention_pallas as ap
from apex_tpu.ops import layer_norm_pallas as lnp
from apex_tpu.ops import softmax_pallas as smp
from apex_tpu.ops import xent_pallas as xp
from apex_tpu.ops.attention import _dense_attention


@pytest.fixture(autouse=True)
def _clean_tile_state(monkeypatch):
    """Unpin every tile setter/env knob around each test."""
    for k in ("APEX_LN_BLOCK_ROWS", "APEX_SOFTMAX_BLOCK_ROWS",
              "APEX_ATTN_BLOCK_Q", "APEX_XENT_ROW_BLOCK",
              "APEX_DISPATCH", "APEX_DISPATCH_TABLE"):
        monkeypatch.delenv(k, raising=False)

    def reset():
        lnp.set_block_rows(None)
        smp.set_block_rows(None)
        ap.set_block_q(None)
        xp.set_row_block(None)

    reset()
    yield
    reset()


def _jx(fn, *args):
    """Comparable jaxpr string: pallas_call params embed kernel
    function reprs whose 0x addresses differ per trace — strip them so
    equality means equal lowered structure."""
    import re

    return re.sub(r"0x[0-9a-f]+", "0x",
                  str(jax.make_jaxpr(lambda *a: fn(*a))(*args)))


# ------------------------------------------------------------ layer norm

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("br", [8, 16, 64])  # 8 = the minimum legal tile
def test_layer_norm_tile_parity(dtype, br):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(64, 256), dtype)
    w = jnp.asarray(rs.randn(256), jnp.float32)
    b = jnp.asarray(rs.randn(256), jnp.float32)

    def ref(x, w, b):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * w + b
        return y.astype(x.dtype)

    got = lnp.layer_norm(x, w, b, 1e-5, True, br)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref(x, w, b), np.float32),
                               atol=tol)
    # backward structure at this tile (dx + affine-grad partials)
    g = jax.grad(lambda x, w, b: jnp.sum(
        lnp.layer_norm(x, w, b, 1e-5, True, br).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(x, w, b)
    r = jax.grad(lambda x, w, b: jnp.sum(
        ref(x, w, b).astype(jnp.float32) ** 2), argnums=(0, 1, 2))(x, w, b)
    for gi, ri in zip(g, r):
        np.testing.assert_allclose(np.asarray(gi, np.float32),
                                   np.asarray(ri, np.float32),
                                   atol=3e-1 if dtype == jnp.bfloat16
                                   else 1e-3, rtol=2e-2)


def test_layer_norm_per_call_raises_pref_falls_back():
    x = jnp.ones((64, 256), jnp.float32)
    # non-divisible edge: 48 does not divide 64
    with pytest.raises(ValueError, match="does not divide"):
        lnp.layer_norm(x, None, None, 1e-5, True, 48)
    # sub-minimum tile
    with pytest.raises(ValueError, match="multiple of 8"):
        lnp.layer_norm(x, None, None, 1e-5, True, 4)
    # the same tiles as PREFERENCES fall back to the heuristic silently
    want = np.asarray(lnp.layer_norm(x, None, None, 1e-5, True))
    for pref in (48, 4, 10 ** 9):
        got = lnp.layer_norm(x, None, None, 1e-5, True, None, pref)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


def test_layer_norm_precedence_per_call_over_setter_over_env(monkeypatch):
    x = jnp.ones((64, 256), jnp.float32)

    def grid_of(fn):
        jx = _jx(fn, x)
        assert "pallas_call" in jx
        return jx

    j8 = grid_of(lambda x: lnp.layer_norm(x, None, None, 1e-5, True, 8))
    j16 = grid_of(lambda x: lnp.layer_norm(x, None, None, 1e-5, True, 16))
    assert j8 != j16  # the tile genuinely changes the lowered program
    # env resolves when nothing else is set — read at TRACE time
    monkeypatch.setenv("APEX_LN_BLOCK_ROWS", "16")
    assert grid_of(lambda x: lnp.layer_norm(
        x, None, None, 1e-5, True)) == j16
    # setter beats env
    lnp.set_block_rows(8)
    assert grid_of(lambda x: lnp.layer_norm(
        x, None, None, 1e-5, True)) == j8
    # per-call beats setter
    lnp.set_block_rows(16)
    assert grid_of(lambda x: lnp.layer_norm(
        x, None, None, 1e-5, True, 8)) == j8
    with pytest.raises(ValueError):
        lnp.set_block_rows("big")


# --------------------------------------------------------------- softmax

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bsq", [8, 32, 128])
def test_softmax_tile_parity(causal, bsq):
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 2, 128, 128), jnp.float32)

    def ref(x):
        xf = x * 0.5
        if causal:
            m = jnp.arange(128)[None, :] > jnp.arange(128)[:, None]
            xf = jnp.where(m, jnp.finfo(jnp.float32).min, xf)
        e = jnp.exp(xf - jnp.max(xf, axis=-1, keepdims=True))
        if causal:
            e = jnp.where(m, 0.0, e)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    got = smp.scaled_masked_softmax(x, None, 0.5, causal, True, bsq)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(x)),
                               atol=1e-6)
    gg = jax.grad(lambda x: jnp.sum(smp.scaled_masked_softmax(
        x, None, 0.5, causal, True, bsq) ** 2))(x)
    rg = jax.grad(lambda x: jnp.sum(ref(x) ** 2))(x)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(rg), atol=1e-5)


def test_softmax_per_call_raises_pref_falls_back(monkeypatch):
    x = jnp.ones((1, 1, 128, 128), jnp.bfloat16)
    with pytest.raises(ValueError, match="does not divide"):
        smp.scaled_masked_softmax(x, None, 1.0, False, True, 48)
    want = np.asarray(smp.scaled_masked_softmax(x, None, 1.0, False, True),
                      np.float32)
    got = smp.scaled_masked_softmax(x, None, 1.0, False, True, None, 48)
    np.testing.assert_allclose(np.asarray(got, np.float32), want)
    # setter preference engages per shape; jaxpr proves the tile took
    j32 = _jx(lambda x: smp.scaled_masked_softmax(
        x, None, 1.0, False, True, 32), x)
    smp.set_block_rows(32)
    assert _jx(lambda x: smp.scaled_masked_softmax(
        x, None, 1.0, False, True), x) == j32
    smp.set_block_rows(None)
    monkeypatch.setenv("APEX_SOFTMAX_BLOCK_ROWS", "32")
    assert _jx(lambda x: smp.scaled_masked_softmax(
        x, None, 1.0, False, True), x) == j32


# ------------------------------------------------------------- attention

@pytest.mark.parametrize("bwd_impl", ["monolithic", "split"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_attention_tile_parity_both_backwards(dtype, bwd_impl):
    b, h, s, d = 1, 2, 256, 32
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(b, h, s, d), dtype)
    k = jnp.asarray(rs.randn(b, h, s, d), dtype)
    v = jnp.asarray(rs.randn(b, h, s, d), dtype)
    scale = 1.0 / np.sqrt(d)
    kw = dict(block_q=128) if bwd_impl == "monolithic" \
        else dict(block_q=128, block_k=128)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, True, scale, None, True,
                                    kw.get("block_q"), bwd_impl, 0.0,
                                    None, None, kw.get("block_k"))
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def r(q, k, v):
        y = _dense_attention(q, k, v, True, scale, None)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-4
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for gi, ri in zip(g, ref):
        np.testing.assert_allclose(np.asarray(gi, np.float32),
                                   np.asarray(ri, np.float32), atol=tol,
                                   rtol=1e-2)


def test_attention_bwd_block_q_decoupled_from_fwd():
    """bwd_block_q re-tiles ONLY the backward; fwd keeps the heuristic
    block — and the grads stay reference-exact (the dk/dv accumulation
    across a different number of q blocks)."""
    b, h, s, d = 1, 1, 256, 32
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    def loss(q, **kw):
        return jnp.sum(ap.fused_attention_rows(
            q, q, q, False, 0.2, None, True, **kw) ** 2)

    g0 = jax.grad(loss)(q)
    g1 = jax.grad(lambda x: loss(x, bwd_block_q=32))(q)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), atol=2e-4)
    # fwd jaxpr identical (bwd_block_q is backward-only)...
    assert _jx(lambda x: ap.fused_attention_rows(
        x, x, x, False, 0.2, None, True), q) \
        == _jx(lambda x: ap.fused_attention_rows(
            x, x, x, False, 0.2, None, True, None, None, 0.0, None, 32),
            q)
    # ...while the backward jaxpr differs
    assert _jx(lambda x: jax.grad(loss)(x), q) \
        != _jx(lambda x: jax.grad(
            lambda y: loss(y, bwd_block_q=32))(x), q)


def test_attention_block_k_demands_split_and_validates():
    q = jnp.ones((1, 1, 256, 32), jnp.float32)

    def loss(q, **kw):
        return jnp.sum(ap.fused_attention_rows(
            q, q, q, False, 0.2, None, True, **kw) ** 2)

    # block_k without bwd_impl selects the split structure implicitly
    g = jax.grad(lambda x: loss(x, block_k=128))(q)
    r = jax.grad(lambda x: jnp.sum(
        _dense_attention(x, x, x, False, 0.2, None) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-4)
    # illegal block_k raises (not lane-aligned / non-dividing)
    with pytest.raises(ValueError, match="multiple of 128"):
        jax.grad(lambda x: loss(x, block_k=64))(q)
    with pytest.raises(ValueError, match="monolithic"):
        loss(q, block_k=128, bwd_impl="monolithic")


def test_attention_setter_env_and_pref(monkeypatch):
    q = jnp.ones((1, 1, 256, 32), jnp.float32)

    def fwd(x):
        return ap.fused_attention_rows(x, x, x, False, 0.2, None, True)

    j64 = _jx(lambda x: ap.fused_attention_rows(
        x, x, x, False, 0.2, None, True, 64), q)
    monkeypatch.setenv("APEX_ATTN_BLOCK_Q", "64")
    assert _jx(fwd, q) == j64
    monkeypatch.delenv("APEX_ATTN_BLOCK_Q")
    ap.set_block_q(64)
    assert _jx(fwd, q) == j64
    ap.set_block_q(None)
    # tile_pref (the table-consumer channel) resolves below setter/env
    assert _jx(lambda x: ap.fused_attention_rows(
        x, x, x, False, 0.2, None, True,
        tile_pref=(("block_q", 64),)), q) == j64
    # ...and an illegal pref falls back to the heuristic
    assert _jx(lambda x: ap.fused_attention_rows(
        x, x, x, False, 0.2, None, True,
        tile_pref=(("block_q", 100),)), q) == _jx(fwd, q)


# ------------------------------------------------------------- lm head

@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("br", [8, 64])  # 8 = minimum legal tile
def test_xent_tile_parity(smoothing, br):
    rs = np.random.RandomState(4)
    n, V, hd = 64, 512, 128
    x = jnp.asarray(rs.randn(n, hd), jnp.float32)
    e = jnp.asarray(rs.randn(V, hd), jnp.float32)
    lab = jnp.asarray(rs.randint(0, V, (n,)), jnp.int32)

    def ref(x, e):
        logits = (x @ e.T).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=1)
        nll = lse - logits[jnp.arange(n), lab]
        if smoothing:
            nll = ((1 - smoothing) * (lse - logits[jnp.arange(n), lab])
                   + smoothing * (lse - jnp.mean(logits, axis=1)))
        return nll

    got = xp.linear_cross_entropy(x, e, lab, True, smoothing, br)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(x, e)),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda x, e: jnp.sum(xp.linear_cross_entropy(
        x, e, lab, True, smoothing, br)), argnums=(0, 1))(x, e)
    r = jax.grad(lambda x, e: jnp.sum(ref(x, e)), argnums=(0, 1))(x, e)
    for gi, ri in zip(g, r):
        np.testing.assert_allclose(np.asarray(gi), np.asarray(ri),
                                   rtol=1e-4, atol=1e-4)


def test_xent_knobs_and_trace_time_env(monkeypatch):
    rs = np.random.RandomState(5)
    # n=512 so the heuristic row block (512) sits ABOVE the 1 MB-budget
    # model cap — the vmem_budget knob then visibly re-tiles the trace
    x = jnp.asarray(rs.randn(512, 128), jnp.float32)
    e = jnp.asarray(rs.randn(512, 128), jnp.float32)
    lab = jnp.asarray(rs.randint(0, 512, (512,)), jnp.int32)

    def f(x, **kw):
        return xp.linear_cross_entropy(x, e, lab, True, 0.0, **kw)

    # per-call demands raise on illegal values
    with pytest.raises(ValueError, match="does not divide"):
        f(x, row_block=48)
    with pytest.raises(ValueError, match="vmem_budget"):
        f(x, vmem_budget=17 * 1024 * 1024)
    # vmem_budget re-sizes the heuristic cap — traced program changes
    j_default = _jx(f, x)
    j_small = _jx(lambda x: f(x, vmem_budget=1024 * 1024), x)
    assert j_default != j_small
    # APEX_XENT_ROW_BLOCK is read at TRACE time (no re-import): the
    # import-time module constant is gone
    monkeypatch.setenv("APEX_XENT_ROW_BLOCK", "16")
    j_env = _jx(f, x)
    assert j_env != j_default
    monkeypatch.delenv("APEX_XENT_ROW_BLOCK")
    assert _jx(f, x) == j_default
    # setter (exact block) beats the env cap; per-call beats both
    monkeypatch.setenv("APEX_XENT_ROW_BLOCK", "16")
    xp.set_row_block(64)
    j_set = _jx(f, x)
    assert j_set != j_env
    assert _jx(lambda x: f(x, row_block=16), x) == j_env
    xp.set_row_block(None)
    # pref falls back when illegal
    want = np.asarray(f(x))
    np.testing.assert_allclose(
        np.asarray(f(x, row_block_pref=48)), want, rtol=1e-6)


def test_xent_infeasible_vmem_budget_raises_cleanly():
    """An in-range vmem_budget the shape cannot tile under must raise a
    ValueError naming the budget — not ZeroDivisionError mid-trace
    (h=512, bv=512: the fixed [bv, h] tiles alone exceed 1 MB)."""
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(64, 512), jnp.float32)
    e = jnp.asarray(rs.randn(1024, 512), jnp.float32)
    lab = jnp.asarray(rs.randint(0, 1024, (64,)), jnp.int32)
    with pytest.raises(ValueError, match="no legal row block"):
        xp.linear_cross_entropy(x, e, lab, True, 0.0, None,
                                1024 * 1024)


def test_xent_sharded_accepts_tile_knobs():
    """The vocab-parallel form takes the same knobs (judged on SHARD
    dims) — single-rank shard_map sanity."""
    from jax.sharding import Mesh, PartitionSpec as P

    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(64, 128), jnp.float32)
    e = jnp.asarray(rs.randn(512, 128), jnp.float32)
    lab = jnp.asarray(rs.randint(0, 512, (64,)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))

    from jax import shard_map

    def run(x, e, lab, **kw):
        return shard_map(
            lambda x, e, lab: xp.linear_cross_entropy_sharded(
                x, e, lab, "tp", True, 0.0, True, **kw),
            mesh=mesh, in_specs=(P(), P("tp"), P()), out_specs=P(),
            check_vma=False)(x, e, lab)

    base = np.asarray(run(x, e, lab))
    got = np.asarray(run(x, e, lab, row_block=16))
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-5)


# ----------------------------------------------- shared model coherence

def test_kernel_heuristics_match_shared_model():
    """The kernels' heuristic tiles ARE the shared model's
    default_params — the acceptance bar that extracting the model
    changed no default."""
    assert lnp._row_block(8192, 768, lnp._BWD_ARRAYS) \
        == tiles.default_params("layer_norm",
                                {"rows": 8192, "hidden": 768},
                                "bfloat16")["block_rows"]
    assert smp._sq_block(1024, 1024, smp._BWD_ARRAYS) \
        == tiles.default_params("softmax",
                                {"b": 8, "h": 12, "sq": 1024, "sk": 1024},
                                "bfloat16")["block_rows"]
    assert ap._q_block(1024, 1024) \
        == tiles.default_params(
            "attention",
            {"b": 8, "h": 12, "sq": 1024, "sk": 1024, "d": 64},
            "bfloat16")["block_q"]
    bv = xp._v_chunk(50304)
    assert xp._row_block(8192, 768, bv) \
        == tiles.default_params("lm_head",
                                {"n": 8192, "v": 50304, "h": 768},
                                "bfloat16")["row_block"]


def test_candidates_are_all_legal_and_incumbent_first():
    for op, dims in (
            ("layer_norm", {"rows": 8192, "hidden": 768}),
            ("softmax", {"b": 8, "h": 12, "sq": 1024, "sk": 1024}),
            ("attention", {"b": 8, "h": 12, "sq": 1024, "sk": 1024,
                           "d": 64}),
            ("lm_head", {"n": 8192, "v": 50304, "h": 768})):
        cands = tiles.candidates(op, dims, "bfloat16")
        assert cands, op
        assert cands[0] == tiles.default_params(op, dims, "bfloat16")
        for c in cands:
            assert tiles.legal(op, dims, "bfloat16", c) == [], (op, c)
