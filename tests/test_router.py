"""Fleet router unit suite (ISSUE 19): the routing policies, the
health machine, the circuit breaker + probe schedule, admission
composition, autoscale, and the validated ``router`` ledger block —
all at the unit level over STUB engines (the real-engine failover
parity story lives in tests/test_router_chaos.py). The stubs implement
exactly the engine surface the router documents itself against:
``validate_request`` / ``submit(quiet=, replay=)`` / ``step`` /
``drain_for_failover`` / ``scheduler`` / ``resilience`` / ``events``.
"""

import types

import pytest

from apex_tpu.serving import lifecycle
from apex_tpu.serving import router as router_mod
from apex_tpu.serving.router import (
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTHY,
    REJOINED,
    AutoscalePolicy,
    Replica,
    Router,
    resolve_route_policy,
    resolve_route_replicas,
    router_block,
    validate_health,
)
from apex_tpu.serving.scheduler import Request
from apex_tpu.telemetry import ledger


# ------------------------------------------------------- stub engines


class _StubScheduler:
    def __init__(self):
        self.queue = []
        self.completed = []
        self.shed = []

    def queue_depth(self):
        return len(self.queue)

    def active_indices(self):
        return []


class StubEngine:
    """The documented router-facing engine surface, queue-only: step()
    completes one queued request whole (greedy streams are
    deterministic functions of the prompt here too: rid-seeded)."""

    def __init__(self, *, fail_rounds=0, verdict="degraded_relay",
                 prefill_len=16, page_size=4, num_slots=2,
                 overlap=False):
        self.prefill_len = prefill_len
        self.page_size = page_size
        self.num_slots = num_slots
        self.overlap = overlap
        self.scheduler = _StubScheduler()
        self.rejected = []
        self.resilience = types.SimpleNamespace(
            degraded_rounds=0, last_verdict=None)
        self.events = None
        self.tick = 0
        self.prefix = None
        self.tokens_generated = 0
        self.fail_rounds = fail_rounds
        self._verdict = verdict
        self.submits = []           # (request, replay) in arrival order

    def validate_request(self, request):
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens wants >= 1")

    def submit(self, request, quiet=False, replay=False):
        self.submits.append((request, replay))
        self.scheduler.queue.append(request)
        return None

    def step(self):
        self.tick += 1
        if self.fail_rounds > 0:
            self.fail_rounds -= 1
            self.resilience.last_verdict = self._verdict
            raise RuntimeError("injected replica failure")
        if self.scheduler.queue:
            req = self.scheduler.queue.pop(0)
            req.out_tokens = [req.rid % 7 + i
                              for i in range(req.max_new_tokens)]
            self.tokens_generated += req.max_new_tokens
            self.scheduler.completed.append(req)
        return {}

    def drain_for_failover(self, tick):
        drained, self.scheduler.queue = self.scheduler.queue, []
        return drained


def _req(rid, prompt=None, max_new=4, arrival=0.0):
    return Request(rid=rid, prompt=prompt or [rid + 1, 2, 3, 4, 5],
                   max_new_tokens=max_new, arrival=arrival)


def _fleet(n=2, **kw):
    return [StubEngine(**kw) for _ in range(n)]


def _drain(rt, reqs, guard=200):
    n = 0
    while not all(r.done() for r in reqs):
        rt.step()
        n += 1
        assert n < guard, [r.out_tokens for r in reqs]


# -------------------------------------------------- vocab + resolvers


def test_policy_vocab_matches_ledger():
    # REQUIRED identity: ledger.ROUTER_POLICY_VOCAB deliberately
    # duplicates router.ROUTE_POLICIES (the stdlib-only validator
    # never imports the serving package) — this assertion is the
    # committed sync contract between the two tuples.
    assert ledger.ROUTER_POLICY_VOCAB == router_mod.ROUTE_POLICIES


def test_resolve_route_policy_demand_vs_preference(monkeypatch):
    # per-call unknowns RAISE (explicit request = demand) ...
    with pytest.raises(ValueError, match="unknown routing policy"):
        resolve_route_policy("bogus")
    # ... a demand beats the env preference ...
    monkeypatch.setenv("APEX_ROUTE_POLICY", "prefix_affinity")
    assert resolve_route_policy("least_loaded") == "least_loaded"
    # ... the env preference is honored when well-formed ...
    assert resolve_route_policy() == "prefix_affinity"
    # ... and garbage env falls back to the measured default
    monkeypatch.setenv("APEX_ROUTE_POLICY", "sticky")
    assert resolve_route_policy() == "round_robin"
    monkeypatch.delenv("APEX_ROUTE_POLICY")
    assert resolve_route_policy() == "round_robin"


def test_resolve_route_replicas(monkeypatch):
    assert resolve_route_replicas(3) == 3
    for bad in (0, -1, True, "2", 1.5):
        with pytest.raises(ValueError, match="positive int"):
            resolve_route_replicas(bad)
    monkeypatch.setenv("APEX_ROUTE_REPLICAS", "5")
    assert resolve_route_replicas() == 5
    monkeypatch.setenv("APEX_ROUTE_REPLICAS", "many")
    assert resolve_route_replicas() == 2
    monkeypatch.delenv("APEX_ROUTE_REPLICAS")
    assert resolve_route_replicas() == 2


# ------------------------------------------------------ health machine


def test_validate_health():
    assert validate_health([HEALTHY, DEGRADED, HEALTHY]) == []
    assert validate_health(
        [HEALTHY, DEGRADED, DEAD, DRAINING, REJOINED, HEALTHY]) == []
    assert validate_health([]) == ["empty health history"]
    assert "not 'healthy'" in validate_health([DEGRADED])[0]
    # dead replicas re-enter through DRAINING, never straight to live
    bad = validate_health([HEALTHY, DEGRADED, DEAD, HEALTHY])
    assert any("not a legal" in p for p in bad)


def test_replica_set_state_raises_on_illegal():
    r = Replica(name="r0", engine=StubEngine())
    r.set_state(DEGRADED)
    with pytest.raises(RuntimeError, match="illegal health transition"):
        r.set_state(DRAINING)
    assert r.history == [HEALTHY, DEGRADED]


# ---------------------------------------------------- routing policies


def test_round_robin_cycles_replicas():
    rt = Router(_fleet(3), policy="round_robin")
    for i in range(4):
        assert rt.submit(_req(i)) is None
    assert [r.routed for r in rt.replicas] == [2, 1, 1]
    first = [e.submits[0][0].rid for e in
             (rt.replicas[0].engine, rt.replicas[1].engine,
              rt.replicas[2].engine)]
    assert first == [0, 1, 2]


def test_least_loaded_picks_smallest_then_index():
    rt = Router(_fleet(3), policy="least_loaded")
    rt.replicas[0].engine.scheduler.queue = [_req(90), _req(91)]
    rt.replicas[2].engine.scheduler.queue = [_req(92)]
    order = rt._candidates(_req(1))
    assert [r.name for r in order] == ["r1", "r2", "r0"]
    # ties break by index: drain the queues, r0/r1/r2 all empty
    rt.replicas[0].engine.scheduler.queue = []
    rt.replicas[2].engine.scheduler.queue = []
    assert [r.name for r in rt._candidates(_req(2))] \
        == ["r0", "r1", "r2"]


def test_prefix_affinity_routes_shared_prefix_together():
    rt = Router(_fleet(3), policy="prefix_affinity")
    sys_prompt = [9, 8, 7, 6]       # one full page (page_size=4)
    reqs = [_req(i, prompt=sys_prompt + [10 + i]) for i in range(6)]
    for r in reqs:
        assert rt.submit(r) is None
    # every request sharing the first-page chain lands on ONE replica
    assert sorted(r.routed for r in rt.replicas) == [0, 0, 6]
    # a DIFFERENT first page may hash elsewhere, deterministically
    other = _req(99, prompt=[1, 1, 1, 1, 2])
    assert [r.name for r in rt._candidates(other)] \
        == [r.name for r in rt._candidates(other)]


def test_prefix_affinity_rendezvous_stable_under_death():
    # rendezvous property: removing a NON-winning replica never moves
    # the key — only the dead winner's keys migrate
    rt = Router(_fleet(3), policy="prefix_affinity")
    req = _req(1, prompt=[5, 5, 5, 5, 6])
    order = rt._candidates(req)
    loser = order[-1]
    loser.set_state(DEGRADED)
    loser.set_state(DEAD)
    assert rt._candidates(req)[0] is order[0]


# ------------------------------------------------ admission composition


def test_fleet_vs_replica_vs_no_replica_reasons():
    rt = Router(_fleet(2), fleet_admit=2)
    assert rt.submit(_req(0)) is None
    assert rt.submit(_req(1)) is None
    rej = rt.submit(_req(2))
    assert rej.reason == "fleet_full" and rej.retry_after_ticks >= 1
    assert rt.stats["rejected_fleet"] == 1

    rt2 = Router(_fleet(2), replica_inflight=1)
    assert rt2.submit(_req(0)) is None
    assert rt2.submit(_req(1)) is None
    rej2 = rt2.submit(_req(2))
    assert rej2.reason == "replica_full"
    assert rt2.stats["rejected_replica"] == 1

    rt3 = Router(_fleet(2))
    for r in rt3.replicas:
        r.set_state(DEGRADED)
        r.set_state(DEAD)
    rej3 = rt3.submit(_req(0))
    assert rej3.reason == "no_replica"
    # a full fleet never masks a malformed request
    with pytest.raises(ValueError, match="max_new_tokens"):
        rt3.submit(_req(9, max_new=0))


def test_ctor_demands_raise():
    with pytest.raises(ValueError, match="at least one engine"):
        Router([])
    with pytest.raises(ValueError, match="prefill_len/page_size"):
        Router([StubEngine(), StubEngine(prefill_len=32)])
    with pytest.raises(ValueError, match="overlapped engine"):
        Router([StubEngine(overlap=True)])
    with pytest.raises(ValueError, match="fleet_admit"):
        Router(_fleet(), fleet_admit=-1)
    with pytest.raises(ValueError, match="replica_inflight"):
        Router(_fleet(), replica_inflight=True)
    with pytest.raises(ValueError, match="breaker_failures"):
        Router(_fleet(), breaker_failures=0)
    with pytest.raises(ValueError, match="unknown routing policy"):
        Router(_fleet(), policy="sticky")
    with pytest.raises(ValueError, match="AutoscalePolicy"):
        Router(_fleet(), autoscale="lagged")


def test_autoscale_policy_validation():
    AutoscalePolicy(min_replicas=1)     # defaults validate
    for bad in (0, True, "1"):
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscalePolicy(min_replicas=bad)
    for hw in (0.0, 1.5):
        with pytest.raises(ValueError, match="high_water"):
            AutoscalePolicy(min_replicas=1, high_water=hw)
    with pytest.raises(ValueError, match="lag_rounds"):
        AutoscalePolicy(min_replicas=1, lag_rounds=0)


# ------------------------------------- breaker, probe rejoin, orphans


def test_breaker_trip_failover_and_probe_rejoin():
    good, bad = StubEngine(), StubEngine(fail_rounds=2)
    rt = Router([good, bad], breaker_failures=2, probe_wait_rounds=1,
                probe_attempts=3)
    reqs = [_req(i, max_new=2) for i in range(4)]
    for r in reqs:
        assert rt.submit(r) is None
    _drain(rt, reqs)
    r1 = rt.replicas[1]
    # two consecutive classified failures tripped the breaker, the two
    # requests routed to r1 failed over and replayed through r0
    assert rt.stats["deaths"] == 1
    assert rt.stats["failovers"] == 2
    assert rt.stats["replayed"] >= 2
    assert r1.last_verdict == "degraded_relay"
    assert all(replay for req, replay in good.submits[2:]), \
        good.submits
    # zero loss: all four trace requests completed, none on the dead
    # replica, and the probe fabrication is excluded from completed()
    assert sorted(q.rid for q in rt.completed()) == [0, 1, 2, 3]
    # let the probe schedule run the replica back in
    n = 0
    while r1.state not in (REJOINED, HEALTHY):
        rt.step()
        n += 1
        assert n < 60, r1.history
    rt.step()
    assert r1.state == HEALTHY
    assert validate_health(r1.history) == []
    assert DEAD in r1.history and DRAINING in r1.history \
        and REJOINED in r1.history
    assert rt.stats["probes"] >= 1 and rt.stats["rejoins"] == 1
    assert all(q.rid < router_mod._PROBE_RID_BASE
               for q in rt.completed())


def test_total_outage_parks_orphans_until_rejoin():
    engines = [StubEngine(fail_rounds=1, verdict="wedged")
               for _ in range(2)]
    rt = Router(engines, breaker_failures=1, probe_wait_rounds=1)
    reqs = [_req(i, max_new=2) for i in range(3)]
    for r in reqs:
        assert rt.submit(r) is None
    rt.step()                       # both replicas die this round
    assert all(r.state == DEAD for r in rt.replicas)
    assert rt._orphans, "accepted requests must park, not drop"
    _drain(rt, reqs)                # probes rejoin, orphans replay
    assert sorted(q.rid for q in rt.completed()) == [0, 1, 2]
    assert rt.stats["rejoins"] >= 1
    for r in rt.replicas:
        assert validate_health(r.history) == []


def test_probe_budget_exhausts_and_stays_dead():
    dead = StubEngine(fail_rounds=10 ** 6)
    rt = Router([StubEngine(), dead], breaker_failures=1,
                probe_wait_rounds=1, probe_attempts=2)
    rt.submit(_req(0, max_new=1))
    for _ in range(40):
        rt.step()
    r1 = rt.replicas[1]
    assert r1.state == DEAD
    assert r1.probe_attempts_left == 0
    assert rt.stats["probes"] == 2 and rt.stats["rejoins"] == 0
    assert validate_health(r1.history) == []


# ------------------------------------------------- autoscale + gauges


def test_autoscale_unparks_after_lag():
    rt = Router(_fleet(2), policy="round_robin",
                autoscale=AutoscalePolicy(min_replicas=1,
                                          high_water=0.5,
                                          lag_rounds=2))
    r1 = rt.replicas[1]
    assert r1.parked and not r1.routable()
    reqs = [_req(i, max_new=2) for i in range(5)]
    for r in reqs:
        assert rt.submit(r) is None     # all land on r0 (r1 parked)
    assert rt.replicas[0].routed == 5
    _drain(rt, reqs)
    assert not r1.parked
    assert rt.stats["scale_outs"] == 1


def test_gauge_rows_track_stats():
    rt = Router(_fleet(2))
    reqs = [_req(i, max_new=2) for i in range(3)]
    for r in reqs:
        rt.submit(r)
    _drain(rt, reqs)
    rows = rt.gauge_rows()
    assert len(rows) == rt.tick
    assert rows[-1]["serve_routed"] == rt.stats["routed"] == 3
    assert rows[-1]["serve_failovers"] == 0
    assert all(a["serve_routed"] <= b["serve_routed"]
               for a, b in zip(rows, rows[1:]))
    assert rt.gauge_rows(run="x")[0]["run"] == "x"


def test_fleet_event_log_rebinding():
    lifecycle.enable()
    try:
        rt = Router(_fleet(2))
    finally:
        lifecycle.reset_enabled()
    assert rt.events is not None
    assert all(r.engine.events is rt.events for r in rt.replicas)
    rt.submit(_req(0))
    chain = [e["event"] for e in rt.events.request_events(0)]
    assert chain == ["submitted", "routed"]
    # disabled mode: no log, no recording overhead
    rt2 = Router(_fleet(2))
    assert rt2.events is None


# --------------------------------------- the validated ledger surface


def _driven_block():
    rt = Router(_fleet(2))
    reqs = [_req(i, max_new=3) for i in range(4)]
    done = rt.run_trace(reqs)
    return router_block(rt, done, 1.0, trace_id="tr-unit",
                        arrival_process="poisson",
                        prefix_hit_rate_by_policy={
                            "round_robin": 0.3, "prefix_affinity": 0.4})


def test_router_block_fields_and_validation():
    block = _driven_block()
    # the block carries EXACTLY the schema fields, and validates clean
    assert set(block) == set(ledger.ROUTER_FIELDS)
    assert ledger._validate_router(block) == []
    assert block["completed"] == block["requests"] == 4
    assert block["replicas"] == 2
    assert block["fleet_goodput_tok_s"] == 12.0   # 4 req x 3 tok / 1 s
    assert 0.0 <= block["util_spread"] <= 1.0


def test_router_block_teeth():
    assert ledger._validate_router("x") == ["not a dict"]
    block = _driven_block()
    bad = dict(block, route_policy="sticky")
    assert any("route_policy" in p
               for p in ledger._validate_router(bad))
    missing = {k: v for k, v in block.items() if k != "failovers"}
    assert any("missing field 'failovers'" in p
               for p in ledger._validate_router(missing))
    assert any("util_spread" in p for p in ledger._validate_router(
        dict(block, util_spread=1.5)))
    assert any("prefix_hit_rate_by_policy" in p
               for p in ledger._validate_router(
                   dict(block, prefix_hit_rate_by_policy={"rr": 0.5})))
    assert any("not a non-negative int" in p
               for p in ledger._validate_router(
                   dict(block, failovers=-1)))


def test_check12_router_pin_match_both_directions():
    from tests.conftest import run_check_bench_labels  # noqa: F401
    import importlib.util
    import os
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "check_bench_labels.py")
    spec = importlib.util.spec_from_file_location("_cbl12", tool)
    cbl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cbl)
    block = {"route_policy": "round_robin", "replicas": 2}
    good = {"router": block,
            "knobs": {"APEX_ROUTE_POLICY": "round_robin",
                      "APEX_ROUTE_REPLICAS": "2"}}
    assert cbl.router_problems(good, "lg-x") == []
    # direction 1a: a router block without its pins
    unpinned = {"router": block, "knobs": {}}
    assert len(cbl.router_problems(unpinned, "lg-x")) == 2
    # direction 1b: block and pin disagree
    skew = {"router": block,
            "knobs": {"APEX_ROUTE_POLICY": "prefix_affinity",
                      "APEX_ROUTE_REPLICAS": "2"}}
    assert any("disagrees" in p
               for p in cbl.router_problems(skew, "lg-x"))
    # direction 2: an engaged fleet pin with NO router block
    silent = {"knobs": {"APEX_ROUTE_POLICY": "round_robin"}}
    assert any("no router block" in p
               for p in cbl.router_problems(silent, "lg-x"))


def test_run_trace_raises_on_no_drain():
    # a fleet that cannot drain must fail loudly, not spin: every
    # replica permanently dead with probes exhausted
    engines = [StubEngine(fail_rounds=10 ** 6) for _ in range(2)]
    rt = Router(engines, breaker_failures=1, probe_wait_rounds=1,
                probe_attempts=1)
    with pytest.raises(RuntimeError, match="did not drain"):
        rt.run_trace([_req(0, max_new=1)], max_ticks=50)
