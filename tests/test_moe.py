"""Expert-parallel MoE tests: routing semantics, capacity drops, top-2
gating, and ep=4 all_to_all parity (fwd + grads) vs the single-device
reference on the CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.moe import (
    ExpertParallelMLP,
    MoEConfig,
    load_balancing_loss,
    switch_routing,
)

EP = 4


def test_switch_routing_capacity_and_gates():
    # 4 tokens all prefer expert 0; capacity 2 → tokens 2,3 dropped
    logits = jnp.asarray([[5.0, 0.0], [5.0, 0.0], [5.0, 0.0], [5.0, 0.0]])
    dispatch, combine = switch_routing(logits, 2, capacity=2)
    assert dispatch.shape == (4, 2, 2)
    np.testing.assert_array_equal(
        np.asarray(jnp.sum(dispatch, axis=(1, 2))), [1, 1, 0, 0])
    p = float(jax.nn.softmax(jnp.asarray([5.0, 0.0]))[0])
    np.testing.assert_allclose(
        np.asarray(jnp.sum(combine, axis=(1, 2)))[:2], [p, p], rtol=1e-6)


def test_switch_routing_top2():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(16, 4), jnp.float32)
    dispatch, combine = switch_routing(logits, 4, capacity=16,
                                       num_selected=2)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    top2 = np.sort(probs, axis=-1)[:, -2:].sum(-1)
    np.testing.assert_allclose(np.asarray(jnp.sum(combine, axis=(1, 2))),
                               top2, rtol=1e-5)
    # a token occupies at most one slot per selected expert
    assert float(jnp.max(jnp.sum(dispatch, axis=2))) <= 1.0 + 1e-6


def test_load_balancing_loss_uniform_is_one():
    T, E = 64, 8
    logits = jnp.zeros((T, E))
    # uniform probs; route tokens round-robin via tiny per-token bias
    bias = jax.nn.one_hot(jnp.arange(T) % E, E) * 1e-3
    dispatch, _ = switch_routing(logits + bias, E, capacity=T)
    lbl = float(load_balancing_loss(logits, dispatch))
    np.testing.assert_allclose(lbl, 1.0, rtol=1e-2)


def _moe_ref_and_ep(seed=0):
    """Same tokens through (a) single-device all-local MoE and (b) ep=4
    sharded MoE with tokens split across ranks. Capacity ample → no drops
    → results must match exactly."""
    rs = np.random.RandomState(seed)
    T, H, F, E = 32, 16, 32, 8
    x = jnp.asarray(rs.randn(T, H), jnp.float32)

    cfg_ref = MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                        capacity_factor=float(E), num_selected=2)
    cfg_ep = MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                       capacity_factor=float(E), num_selected=2,
                       expert_parallel_axis="ep")

    ref = ExpertParallelMLP(cfg_ref)
    params = ref.init(jax.random.PRNGKey(1), x)["params"]

    def ref_fwd(params, x):
        return ref.apply({"params": params}, x)

    mesh = Mesh(np.array(jax.devices()[:EP]), ("ep",))
    epm = ExpertParallelMLP(cfg_ep)

    def ep_fwd(params_full, x_loc):
        # shard the reference params: each rank slices its experts
        idx = jax.lax.axis_index("ep")
        e_loc = E // EP
        p_loc = {
            "router": params_full["router"],
            "wi": jax.lax.dynamic_slice_in_dim(params_full["wi"],
                                               idx * e_loc, e_loc, 0),
            "wo": jax.lax.dynamic_slice_in_dim(params_full["wo"],
                                               idx * e_loc, e_loc, 0),
        }
        return epm.apply({"params": p_loc}, x_loc)

    def run_ep(params, x):
        return shard_map(ep_fwd, mesh=mesh, in_specs=(P(), P("ep")),
                         out_specs=P("ep"), check_vma=False)(params, x)

    return params, x, ref_fwd, run_ep


@pytest.mark.slow  # compile-heavy exact parity; routing/dropped-token
# tests stay fast and dryrun_multichip exercises EP fwd+bwd every round
def test_expert_parallel_matches_reference():
    params, x, ref_fwd, run_ep = _moe_ref_and_ep()
    want = ref_fwd(params, x)
    got = run_ep(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # compile-heavy; the fwd/adam parity siblings stay fast
def test_expert_parallel_grads_match_reference():
    params, x, ref_fwd, run_ep = _moe_ref_and_ep(1)
    g = jnp.asarray(np.random.RandomState(9).randn(*x.shape) * 0.1,
                    jnp.float32)

    def loss_ref(params):
        return jnp.sum(ref_fwd(params, x) * g)

    def loss_ep(params):
        return jnp.sum(run_ep(params, x) * g)

    gr = jax.grad(loss_ref)(params)
    ge = jax.grad(loss_ep)(params)
    for k in ("router", "wi", "wo"):
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(ge[k])[0]),
            np.asarray(jax.tree_util.tree_leaves(gr[k])[0]),
            atol=1e-5, rtol=1e-4)


@pytest.mark.slow  # full ExpertParallelMLP compile; routing-level
# dropped-token coverage stays fast (test_switch_routing_capacity_and_gates)
def test_dropped_tokens_produce_zero_output():
    T, H, F, E = 8, 8, 16, 2
    cfg = MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                    capacity_factor=0.25)  # capacity 1 → most tokens drop
    m = ExpertParallelMLP(cfg)
    x = jnp.asarray(np.random.RandomState(3).randn(T, H), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    out = m.apply({"params": params}, x)
    # at most E*capacity = 2 tokens routed; the rest exactly zero
    nonzero = np.asarray(jnp.any(out != 0, axis=-1)).sum()
    assert nonzero <= 2


# ----------------------- MoE inside the GPT stack --------------------------

@pytest.mark.slow
def test_gpt_with_moe_layers_trains():
    """GPTModel with num_moe_experts routes every layer's MLP through the
    MoE; loss and grads stay finite and loss decreases over a few steps."""
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig
    from apex_tpu.optimizers import fused_adam

    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=16, hidden_dropout=0.0,
        attention_dropout=0.0, num_moe_experts=4, moe_top_k=2,
        moe_capacity_factor=2.0)
    model = GPTModel(cfg)
    mesh = Mesh(np.array(jax.devices()[:1]), (TENSOR_AXIS,))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (2, 16)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))
    labels = jnp.asarray(rs.randint(0, 64, (2, 16)), jnp.int32)
    tx = fused_adam(5e-3)

    def train(ids, pos, labels):
        params = model.init(jax.random.PRNGKey(0), ids, pos, None)["params"]
        opt = tx.init(params)

        def loss_fn(p):
            return jnp.mean(model.apply({"params": p}, ids, pos, None,
                                        labels))

        losses = []
        for _ in range(8):
            loss, g = jax.value_and_grad(loss_fn)(params)
            u, opt = tx.update(g, opt, params)
            params = jax.tree_util.tree_map(lambda a, b: a + b, params, u)
            losses.append(loss)
        return jnp.stack(losses)

    losses = np.asarray(jax.jit(shard_map(
        train, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))(ids, pos, labels))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def _moe_tp1(T=16, H=8, F=16, E=4):
    x = jnp.asarray(np.random.RandomState(0).randn(T, H), jnp.float32)
    cfg1 = MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                     capacity_factor=float(E))
    m1 = ExpertParallelMLP(cfg1)
    params = m1.init(jax.random.PRNGKey(0), x)["params"]
    out1, vars1 = m1.apply({"params": params}, x,
                           mutable=["intermediates"])
    return x, params, out1, vars1


def test_collect_moe_aux():
    """collect_moe_aux picks up every layer's sown aux loss."""
    from apex_tpu.transformer.moe import collect_moe_aux

    _, _, _, vars1 = _moe_tp1()
    aux = collect_moe_aux(vars1["intermediates"])
    assert float(aux) > 0.0


@pytest.mark.slow  # second ExpertParallelMLP compile under shard_map;
# the ep dryrun + fast routing tests keep MoE in the fast tier
def test_moe_tp_sharding_matches_tp1():
    """tp=2 expert-ffn sharding reproduces the tp=1 MoE exactly."""
    T, H, F, E = 16, 8, 16, 4
    x, params, out1, _ = _moe_tp1(T, H, F, E)
    cfg2 = MoEConfig(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                     capacity_factor=float(E), tensor_parallel_axis="tp")
    m2 = ExpertParallelMLP(cfg2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))

    def tp_fwd(params_full, x):
        idx = jax.lax.axis_index("tp")
        f_loc = F // 2
        p_loc = {
            "router": params_full["router"],
            "wi": jax.lax.dynamic_slice_in_dim(params_full["wi"],
                                               idx * f_loc, f_loc, 2),
            "wo": jax.lax.dynamic_slice_in_dim(params_full["wo"],
                                               idx * f_loc, f_loc, 1),
        }
        return m2.apply({"params": p_loc}, x)

    out2 = shard_map(tp_fwd, mesh=mesh, in_specs=(P(), P()),
                     out_specs=P(), check_vma=False)(params, x)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out1),
                               atol=1e-5, rtol=1e-5)
