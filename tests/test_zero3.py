"""ZeRO-3 parameter sharding (ISSUE 18, apex_tpu.parallel.zero3):

* 20-step trajectory parity on the 8-device CPU mesh — EXACT for the
  plain gather (the shard optimizer is the same `_adam_flat`
  elementwise math as the per-leaf fused_adam, and the gather
  re-assembles the exact fp32 master), a documented BAND for the
  int8-quantized gather (error-feedback-free: params re-gather fresh
  from the master each step, so the quantization error is a per-step
  forward perturbation that never accumulates — the band must be flat
  in step count), and parity again for the hierarchical two-hop
  gather over a factored dp pair.
* knob semantics per the CLAUDE.md asymmetry: per-call `zero_stage=`
  demands raise (1/2/bool/garbage), the APEX_ZERO_STAGE env
  preference rides `tiles.env_choice` and falls back; the
  `overlap_grad='bucketed'` pairing follows the engine precedent
  (two demands raise, a demand drops the other preference,
  env-vs-env falls back with zero3 yielding).
* the capability rung: `zero3.capability_config()` is PROVEN
  unserveable unsharded — its validated costs block's peak_hbm_bytes
  exceeds the v5e HBM capacity (the CLAUDE.md capability-default
  exception; the argument + queued A/Bs live in PERF.md).
* check-11 teeth (tools/check_bench_labels.parallel_problems): cited
  rows claiming zero3/tp must pin APEX_ZERO_STAGE/APEX_SERVE_TP,
  both directions, with no measurement gate.
"""

import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.parallel import zero3
from apex_tpu.transformer.testing import TransformerConfig
from apex_tpu.transformer.testing.minimal import (
    _resolve_zero_overlap,
    run_minimal_gpt_training,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(pp=1):
    return TransformerConfig(
        hidden_size=64, num_layers=2 * pp, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)


def _run(topology, num_steps, **kw):
    return run_minimal_gpt_training(
        n_devices=8, cfg=_cfg(topology[0]), topology=topology,
        num_microbatches=4, micro_batch_size=2, seq_len=16,
        num_steps=num_steps, return_grad_norms=True, **kw)


# ------------------------------------------------- trajectory parity

def _assert_plain_parity(num_steps):
    base_l, base_g = _run((1, 8, 1), num_steps)
    z3_l, z3_g = _run((1, 8, 1), num_steps, zero_stage=3)
    assert len(z3_l) == num_steps
    assert z3_l == base_l, (
        "plain-gather zero3 trajectory is not exact:",
        list(zip(base_l, z3_l)))
    for g, rg in zip(z3_g, base_g):
        assert abs(g - rg) <= 1e-5 * max(abs(rg), 1e-6), (base_g, z3_g)


def test_zero3_plain_gather_parity_exact():
    """Fast-tier twin of the acceptance bar: 5 steps at (1, 8, 1),
    params dp-sharded with gather-on-use, vs the SAME run unsharded —
    per-step losses bit-for-bit identical (same math, same reduction
    order inside each full-weight forward), grad norms within float
    tolerance (the shard-side norm is a segment_sum re-association)."""
    _assert_plain_parity(5)


@pytest.mark.slow
def test_zero3_plain_gather_20_step_parity_exact():
    """The ISSUE 18 acceptance bar verbatim — 20 steps, exact. The
    5-step fast twin above exercises the identical programs; this run
    only extends the horizon (≈4 min on the 1-core host)."""
    _assert_plain_parity(20)


def test_zero3_int8_gather_band_is_flat():
    """Quantized gather-on-use, error-feedback-free: the int8 gather
    perturbs each step's forward but never the resident fp32 master,
    so the loss deviation stays inside one flat band instead of
    compounding (the contrib ZeRO-2 update gather needs EF for
    exactly the accumulation this design sidesteps)."""
    base_l, _ = _run((1, 8, 1), 8)
    z3_l, z3_g = _run((1, 8, 1), 8, zero_stage=3, compress="int8")
    diffs = [abs(a - b) for a, b in zip(base_l, z3_l)]
    assert all(d <= 5e-3 for d in diffs), (base_l, z3_l)
    # flat in step count: the tail of the run deviates no more than
    # ~the head's band — accumulation would grow it monotonically
    head = max(diffs[:4]) + 1e-4
    assert max(diffs[4:]) <= 5 * head, diffs
    assert all(np.isfinite(g) for g in z3_g)


def test_zero3_hierarchical_gather_parity():
    """Factored (inner, outer) dp pair: the two-hop hierarchical
    gather re-assembles the same full weights (chunk order row-major,
    matching `collectives.axes_index`), so the trajectory tracks the
    unsharded run as tightly as the plain gather."""
    base_l, base_g = _run((1, (4, 2), 1), 5)
    z3_l, z3_g = _run((1, (4, 2), 1), 5, zero_stage=3,
                      hierarchical=True)
    for a, b in zip(base_l, z3_l):
        assert abs(a - b) <= 1e-4, (base_l, z3_l)
    for g, rg in zip(z3_g, base_g):
        assert abs(g - rg) <= 1e-4 * max(abs(rg), 1e-6), (base_g, z3_g)


# ------------------------------------------------------ knob semantics

def test_zero_stage_per_call_demand_raises():
    for bad in (1, 2, True, "3", 4, -1):
        with pytest.raises(ValueError, match="zero_stage"):
            zero3.resolve_zero_stage(bad)
    assert zero3.resolve_zero_stage(0) == 0
    assert zero3.resolve_zero_stage(3) == 3


def test_zero_stage_env_preference(monkeypatch):
    monkeypatch.delenv("APEX_ZERO_STAGE", raising=False)
    assert zero3.resolve_zero_stage() == 0
    monkeypatch.setenv("APEX_ZERO_STAGE", "3")
    assert zero3.resolve_zero_stage() == 3
    # garbage falls back warn-once (env_choice preference semantics)
    monkeypatch.setenv("APEX_ZERO_STAGE", "2")
    assert zero3.resolve_zero_stage() == 0
    # per-call demand wins over the env preference
    monkeypatch.setenv("APEX_ZERO_STAGE", "3")
    assert zero3.resolve_zero_stage(0) == 0


def test_zero3_bucketed_overlap_pairing(monkeypatch):
    monkeypatch.delenv("APEX_ZERO_STAGE", raising=False)
    monkeypatch.delenv("APEX_OVERLAP_GRAD", raising=False)
    # two per-call demands: no honorable order
    with pytest.raises(ValueError, match="cannot be honored"):
        _resolve_zero_overlap(3, "bucketed", 1)
    # zero3 demand drops the bucketed env preference
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    assert _resolve_zero_overlap(3, None, 1) == (3, "off")
    # overlap demand: the zero3 env preference yields
    monkeypatch.delenv("APEX_OVERLAP_GRAD", raising=False)
    monkeypatch.setenv("APEX_ZERO_STAGE", "3")
    assert _resolve_zero_overlap(None, "bucketed", 1) == (0, "bucketed")
    # env-vs-env: zero3 (the newer layer) yields
    monkeypatch.setenv("APEX_OVERLAP_GRAD", "bucketed")
    assert _resolve_zero_overlap(None, None, 1) == (0, "bucketed")
    # both preferences off: defaults
    monkeypatch.delenv("APEX_ZERO_STAGE", raising=False)
    monkeypatch.delenv("APEX_OVERLAP_GRAD", raising=False)
    assert _resolve_zero_overlap(None, None, 1) == (0, "off")


# ------------------------------------------------- the capability rung

def test_capability_config_exceeds_v5e_hbm():
    """The committed infeasibility proof (the CLAUDE.md
    capability-default exception): the ~22B config's unsharded
    serving params + KV cache alone exceed one v5e's HBM, as a
    VALIDATED costs block — nothing materialized (eval_shape)."""
    from apex_tpu.telemetry import costs

    block, verdict = zero3.capability_costs()
    assert verdict == "exceeds-hbm"
    assert block["peak_hbm_bytes"] > costs.V5E_HBM_CAPACITY_BYTES
    assert block["source"] == "eval_shape"
    assert costs.validate(block) == []
    # the margin is structural (>4x), not a rounding artifact
    assert block["peak_hbm_bytes"] > 4 * costs.V5E_HBM_CAPACITY_BYTES


def test_capability_scaled_twin_trains_under_zero3():
    """The scaled-down twin of the capability config (same code path:
    gather-on-use forward, reduce-scatter grads, shard-resident adam)
    TRAINS — finite losses over the 8-way dp mesh."""
    losses, gnorms = _run((1, 8, 1), 2, zero_stage=3)
    assert len(losses) == 2
    assert all(np.isfinite(l) for l in losses), losses
    assert all(np.isfinite(g) for g in gnorms), gnorms


# ------------------------------------------------------- check-11 teeth

def _cbl():
    tool = os.path.join(REPO, "tools", "check_bench_labels.py")
    spec = importlib.util.spec_from_file_location("cbl_zero3", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check11_parallel_pin_match_both_directions():
    cbl = _cbl()

    def rec(knobs, claim):
        r = {"id": "lg-t", "knobs": knobs}
        if claim is not None:
            r["parallel"] = claim
        return r

    claim = {"zero_stage": 3, "tp": 2}
    pins = {"APEX_ZERO_STAGE": "3", "APEX_SERVE_TP": "2"}
    assert cbl.parallel_problems(rec(pins, claim), "lg-t") == []
    # claimed but unpinned
    probs = cbl.parallel_problems(rec({}, claim), "lg-t")
    assert len(probs) == 2 and all("does not pin" in p for p in probs)
    # claimed one program, pinned another
    drift = {"APEX_ZERO_STAGE": "0", "APEX_SERVE_TP": "2"}
    assert any("different programs" in p for p in
               cbl.parallel_problems(rec(drift, claim), "lg-t"))
    # reverse direction: engaged pin with NO claim block at all is a
    # finding (no measurement gate — the pins reshape every number)
    probs = cbl.parallel_problems(
        rec({"APEX_ZERO_STAGE": "3"}, None), "lg-t")
    assert any("omits" in p for p in probs)
    probs = cbl.parallel_problems(
        rec({"APEX_SERVE_TP": "4"}, {"zero_stage": 0}), "lg-t")
    assert any("omits 'tp'" in p for p in probs)
    # off pins with no claim are clean (the legacy rows)
    assert cbl.parallel_problems(
        rec({"APEX_ZERO_STAGE": "0", "APEX_SERVE_TP": "1"}, None),
        "lg-t") == []
    assert cbl.parallel_problems(rec({}, None), "lg-t") == []
