"""CPU-side enforcement of Mosaic's block-shape lowering rules.

Round 5's first device window found two kernels whose interpret-mode
parity was perfect but whose backward failed to LOWER on real TPU
(attention split-bwd stats, layer-norm affine-grad partials): Mosaic
requires each block's last two dims to be (8, 128)-divisible or span
the full array dim, and interpret mode never checks it. This test
mirrors the exact rule from jax's Mosaic lowering
(jax/_src/pallas/mosaic/lowering.py `_check_block_mappings`, incl. the
rank-1 packing variant) and applies it to every ``pallas_call`` found in
the jaxpr of every kernel entry point — so the whole defect class is
caught at test time without a device.
"""

import jax
import jax.numpy as jnp
import pytest
from jax._src import core as jax_core
from jax._src.pallas import core as pallas_core


def _iter_jaxprs(jaxpr):
    """Yield *jaxpr* and every jaxpr reachable through eqn params."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for x in vs:
                if isinstance(x, jax_core.ClosedJaxpr):
                    yield from _iter_jaxprs(x.jaxpr)
                elif isinstance(x, jax_core.Jaxpr):
                    yield from _iter_jaxprs(x)


def _pallas_call_stats(fn, *args):
    """(violations, pallas_call_count) over *fn*'s jaxpr: every
    (kernel, block_shape, array_shape) triple that would fail Mosaic's
    `_check_block_mappings` on device, plus how many pallas_calls were
    seen at all (so composition tests can assert non-vacuity — a
    dispatch gate silently dropping kernels must fail loudly, not pass
    an empty check)."""
    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    bad = []
    count = 0
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name != "pallas_call":
                continue
            count += 1
            gm = eqn.params["grid_mapping"]
            name = eqn.params.get("debug_info")
            for bm in gm.block_mappings:
                bs = pallas_core._get_block_shape(bm.block_shape)
                ashape = bm.array_aval.shape
                rank = len(bs)
                if rank == 0:
                    continue  # scalar-prefetch etc.
                bs0, as0 = bs[-1], ashape[-1]
                if rank >= 2:
                    bs1, as1 = bs[-2], ashape[-2]
                    ok = ((bs0 == as0 or bs0 % 128 == 0)
                          and (bs1 == as1 or bs1 % 8 == 0))
                else:
                    bits = jnp.dtype(bm.array_aval.dtype).itemsize * 8
                    tiling = 128 * (32 // bits)
                    ok = bs0 == as0 or bs0 % tiling == 0
                if not ok:
                    bad.append((str(name), bs, ashape))
    return bad, count


def _mosaic_block_rule_violations(fn, *args):
    return _pallas_call_stats(fn, *args)[0]


def _assert_clean(fn, *args, min_calls=1):
    bad, count = _pallas_call_stats(fn, *args)
    assert not bad, f"Mosaic block-rule violations: {bad}"
    assert count >= min_calls, (
        f"vacuous check: only {count} pallas_calls traced "
        f"(expected >= {min_calls}) — a dispatch gate dropped the kernel")


# ---------------------------------------------------------------------------
# attention rows kernel: every structure the dispatch can reach
# ---------------------------------------------------------------------------

def _attn_args(b=2, h=3, sq=256, sk=256, d=64, dtype=jnp.bfloat16):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (b, h, sq, d), dtype)
    k = jax.random.normal(k2, (b, h, sk, d), dtype)
    v = jax.random.normal(k3, (b, h, sk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("bwd_impl", ["monolithic", "split"])
@pytest.mark.parametrize("seg", [False, True])
def test_attention_rows_grad_specs(bwd_impl, seg):
    from apex_tpu.ops.attention_pallas import fused_attention_rows

    q, k, v = _attn_args()
    segs = None
    if seg:
        s = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        segs = (s, s)

    def loss(q, k, v):
        o = fused_attention_rows(q, k, v, True, 0.125, segs, False, None,
                                 bwd_impl)
        return o.astype(jnp.float32).sum()

    _assert_clean(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


@pytest.mark.parametrize("block_q", [8, 64, 128])
def test_attention_rows_small_blocks_with_segs(block_q):
    """The seg BlockSpec regression: sub-128 q blocks must stay legal
    (the old (1, bq) 2-D layout was not)."""
    from apex_tpu.ops.attention_pallas import fused_attention_rows

    q, k, v = _attn_args()
    s = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)

    def loss(q, k, v):
        o = fused_attention_rows(q, k, v, True, 0.125, (s, s), False,
                                 block_q, "monolithic")
        return o.astype(jnp.float32).sum()

    _assert_clean(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_attention_rows_dropout_specs():
    from apex_tpu.ops.attention_pallas import fused_attention_rows

    q, k, v = _attn_args()
    seed = jnp.ones((1, 1), jnp.int32)

    def loss(q, k, v):
        o = fused_attention_rows(q, k, v, False, 0.125, None, False, None,
                                 None, 0.1, seed)
        return o.astype(jnp.float32).sum()

    _assert_clean(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


# ---------------------------------------------------------------------------
# layer norm: the shapes the round-5 window caught plus odd blockings
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rows,hidden", [(16, 768), (2048, 768), (256, 1024)])
def test_layer_norm_specs(rows, hidden):
    from apex_tpu.ops.layer_norm_pallas import layer_norm

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden),
                          jnp.bfloat16)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def loss(x, w, b):
        return layer_norm(x, w, b).astype(jnp.float32).sum()

    _assert_clean(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)


# ---------------------------------------------------------------------------
# fused softmax + fused linear-CE (device-proven; pinned against drift)
# ---------------------------------------------------------------------------

def test_softmax_specs():
    from apex_tpu.ops.softmax_pallas import scaled_masked_softmax

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 256, 256),
                          jnp.bfloat16)

    def loss(x):
        return scaled_masked_softmax(
            x, None, scale=1.0, causal=True).astype(jnp.float32).sum()

    _assert_clean(jax.grad(loss), x)


def test_xent_specs():
    from apex_tpu.ops.xent_pallas import linear_cross_entropy

    x = jax.random.normal(jax.random.PRNGKey(0), (512, 256), jnp.bfloat16)
    e = jax.random.normal(jax.random.PRNGKey(1), (1024, 256), jnp.bfloat16)
    labels = jnp.zeros((512,), jnp.int32)

    def loss(x, e):
        return linear_cross_entropy(x, e, labels).mean()

    _assert_clean(jax.grad(loss, argnums=(0, 1)), x, e)


def test_xent_sharded_specs():
    from apex_tpu.ops.xent_pallas import linear_cross_entropy_sharded
    from jax.sharding import Mesh, PartitionSpec as P

    import numpy as np

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
    x = jax.random.normal(jax.random.PRNGKey(0), (512, 256), jnp.bfloat16)
    e = jax.random.normal(jax.random.PRNGKey(1), (1024, 256), jnp.bfloat16)
    labels = jnp.zeros((512,), jnp.int32)

    def loss(x, e):
        f = jax.shard_map(
            lambda x, e: linear_cross_entropy_sharded(x, e, labels, "tp"),
            mesh=mesh, in_specs=(P(), P("tp")), out_specs=P(),
            check_vma=False)
        return f(x, e).mean()

    _assert_clean(jax.grad(loss, argnums=(0, 1)), x, e)


# ---------------------------------------------------------------------------
# model-level composition: the exact graphs the step-level A/B rungs
# compile on device (whose round-5 compiles hit the relay wedge before
# Mosaic could check them)
# ---------------------------------------------------------------------------

def _assert_step_graph_clean(model, init_args, loss_fn, min_calls=4):
    """Shared scaffolding for model-level composition checks: init the
    model's params under a 1-device shard_map on TENSOR_AXIS, wrap
    ``loss_fn(params)``'s grad in the same mapping, and assert the traced
    step graph is block-rule clean and non-vacuous."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.parallel_state import TENSOR_AXIS

    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
    params = jax.jit(jax.shard_map(
        lambda *a: model.init(jax.random.PRNGKey(0), *a)["params"],
        mesh=mesh, in_specs=(P(),) * len(init_args), out_specs=P(),
        check_vma=False))(*init_args)

    def step(p):
        f = jax.shard_map(lambda p: jax.grad(loss_fn)(p), mesh=mesh,
                          in_specs=(P(),), out_specs=P(),
                          check_vma=False)
        return f(p)

    _assert_clean(step, params, min_calls=min_calls)


@pytest.mark.slow
@pytest.mark.parametrize("impl,fused,drop", [
    ("rows", False, 0.0),      # APEX_ATTN_IMPL=rows step
    ("flash", True, 0.0),      # APEX_FUSED_LM_HEAD=1 step
    ("rows", False, 0.1),      # in-kernel-dropout training step
])
def test_gpt_step_graph_specs(impl, fused, drop, monkeypatch):
    import numpy as np

    from apex_tpu.ops import attention as attn_mod
    from apex_tpu.ops.attention import set_default_impl
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    # make_jaxpr only TRACES — Mosaic lowering never runs — so the
    # platform gate can be lifted to expose the real TPU kernel graphs
    # on the CPU box (without it the dispatch falls through to dense
    # and the whole check is vacuous)
    monkeypatch.setattr(attn_mod, "_tpu_available", lambda: True)
    prev_impl = attn_mod._DEFAULT_IMPL
    set_default_impl(impl)
    try:
        cfg = TransformerConfig(
            hidden_size=768, num_layers=2, num_attention_heads=12,
            vocab_size=50304, max_position_embeddings=1024,
            hidden_dropout=0.0, attention_dropout=drop, bf16=True,
            fused_lm_head=fused, fused_lm_head_interpret=fused)
        model = GPTModel(cfg)
        b, s = 8, 1024
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                               (b, s))
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)

        def loss_fn(p):
            kw = (dict(deterministic=False,
                       rngs={"dropout": jax.random.PRNGKey(7)})
                  if drop else {})
            per_tok = model.apply({"params": p}, ids, pos, None, labels,
                                  **kw)
            return jnp.mean(per_tok)

        # 2 layers x fwd+bwd attention kernels = at least 4 pallas_calls
        # in every parametrization (the fused-head row adds the CE
        # kernels on top) — the non-vacuity floor
        _assert_step_graph_clean(model, (ids, pos, None), loss_fn)
    finally:
        set_default_impl(prev_impl)


@pytest.mark.slow
def test_bert_padding_dropout_step_graph_specs(monkeypatch):
    """BERT's padding-mask training-with-dropout step — the path that
    feeds [b, s] validity to the rows kernel as segment ids (the exact
    layout the round-5 seg-spec fix changed)."""
    import numpy as np

    from apex_tpu.ops import attention as attn_mod
    from apex_tpu.transformer.testing import BertModel, TransformerConfig

    monkeypatch.setattr(attn_mod, "_tpu_available", lambda: True)
    b, s = 8, 1024
    cfg = TransformerConfig(
        hidden_size=768, num_layers=2, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=s,
        hidden_dropout=0.0, attention_dropout=0.1, bf16=True,
        bert_binary_head=False, fused_attention_dropout=True)
    model = BertModel(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    mask = jnp.ones((b, s), jnp.int32).at[:, s - 64:].set(0)  # tail pads
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)

    def loss_fn(p):
        per_tok, _ = model.apply(
            {"params": p}, ids, mask, lm_labels=labels,
            deterministic=False, rngs={"dropout": jax.random.PRNGKey(3)})
        return jnp.mean(per_tok)

    _assert_step_graph_clean(model, (ids, mask), loss_fn)
