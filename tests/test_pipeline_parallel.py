"""Pipeline-parallel schedule tests on the 8-device CPU mesh.

Port of tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py — the
analytic-loss pattern: deterministic weight fill, closed-form expected loss
computed in fp64-equivalent numpy, schedules compared against it (and
against each other) with no data or tolerance fuzz. Plus test_microbatches.py
and p2p smoke.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
)
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    p2p_communication,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    get_ltor_masks_and_position_ids,
)

NDEV = 8
PP = 4
HID = 6
M = 5  # microbatches


def pp_mesh(pp=PP):
    return Mesh(np.array(jax.devices()[:pp]), ("pp",))


# The deterministic model (reference pattern: weight fill (rank+1)/k):
#   embed:  h = x * e
#   stage p: h = h @ W_p     with W_p = ((p+1)/8) * I + 0.01
#   loss:   mean(h * c)
def stage_weight(p, chunks=1):
    # [chunks, HID, HID] when interleaved
    ws = []
    for v in range(chunks):
        s = p + v * PP
        ws.append(((s + 1) / 8.0) * np.eye(HID) + 0.01)
    w = np.stack(ws).astype(np.float32)
    return w if chunks > 1 else w[0]


def stage_fn(w, h, v):
    return h @ w


def embed_fn(e, mb):
    return mb * e


def loss_fn(c, h, mb):
    return jnp.mean(h * c)


def closed_form(xs, e, ws, c):
    """Sequential reference in numpy float64."""
    losses = []
    for m in range(xs.shape[0]):
        h = xs[m].astype(np.float64) * e
        for w in ws:
            h = h @ w.astype(np.float64)
        losses.append((h * c).mean())
    return np.mean(losses)


@pytest.fixture
def batch():
    rng = np.random.RandomState(0)
    return rng.randn(M, 2, HID).astype(np.float32)


def run_pipeline(batch, chunks=1, forward_only=False, impl=None,
                 num_microbatches=M):
    mesh = pp_mesh()
    stacked = np.stack([stage_weight(p, chunks) for p in range(PP)])
    e = jnp.asarray(1.5)
    c = jnp.asarray(2.0)

    fwd_bwd = (forward_backward_pipelining_without_interleaving if chunks == 1
               else forward_backward_pipelining_with_interleaving)

    def run(mbs, sp):
        sp = sp[0]  # drop the sharded singleton: local stage params
        kwargs = dict(num_microbatches=num_microbatches, axis_name="pp",
                      forward_only=forward_only)
        if chunks > 1:
            kwargs["num_model_chunks"] = chunks
        if impl is not None:
            kwargs["impl"] = impl
        loss, grads = fwd_bwd(
            (stage_fn, embed_fn, loss_fn), mbs, (sp, e, c), **kwargs)
        if grads is None:
            return loss, sp[None], e, c
        return loss, grads[0][None], grads[1], grads[2]

    f = shard_map(run, mesh=mesh, in_specs=(P(), P("pp")),
                  out_specs=(P(), P("pp"), P(), P()),
                  check_vma=False)
    loss, gs, ge, gc = jax.jit(f)(jnp.asarray(batch), jnp.asarray(stacked))
    return np.asarray(loss), np.asarray(gs), np.asarray(ge), np.asarray(gc)


def sequential_reference_grads(batch, chunks=1, num_microbatches=M):
    """jax.grad of the closed-form sequential composition."""
    stacked = jnp.asarray(
        np.stack([stage_weight(p, chunks) for p in range(PP)]))

    def loss_of(args):
        sp, e, c = args
        # virtual stage order: chunk-major — v0p0..v0p3, v1p0..v1p3
        total = 0.0
        for m in range(num_microbatches):
            h = embed_fn(e, jnp.asarray(batch[m]))
            for v in range(chunks):
                for p in range(PP):
                    w = sp[p, v] if chunks > 1 else sp[p]
                    h = stage_fn(w, h, v)
            total = total + loss_fn(c, h, jnp.asarray(batch[m]))
        return total / num_microbatches

    args = (stacked, jnp.asarray(1.5), jnp.asarray(2.0))
    loss, grads = jax.value_and_grad(loss_of)(args)
    return np.asarray(loss), tuple(np.asarray(g) for g in grads)


def test_pipeline_1f1b_loss_matches_closed_form(batch):
    ws = [stage_weight(p) for p in range(PP)]
    want = closed_form(batch, 1.5, ws, 2.0)
    loss, _, _, _ = run_pipeline(batch)
    np.testing.assert_allclose(loss.item(), want, rtol=1e-5)


def test_pipeline_1f1b_grads_match_sequential(batch):
    loss, gs, ge, gc = run_pipeline(batch)
    ref_loss, (rgs, rge, rgc) = sequential_reference_grads(batch)
    np.testing.assert_allclose(loss.item(), ref_loss.item(), rtol=1e-5)
    np.testing.assert_allclose(gs, rgs, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(ge, rge, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gc, rgc, rtol=1e-4, atol=1e-6)


def test_pipeline_interleaved_matches_sequential(batch):
    loss, gs, ge, gc = run_pipeline(batch, chunks=2)
    ref_loss, (rgs, rge, rgc) = sequential_reference_grads(batch, chunks=2)
    np.testing.assert_allclose(loss.item(), ref_loss.item(), rtol=1e-5)
    np.testing.assert_allclose(gs, rgs, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(ge, rge, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gc, rgc, rtol=1e-4, atol=1e-6)


def test_pipeline_1f1b_matches_adscan(batch):
    """The O(pp)-memory 1f1b core and the AD-of-scan core are the same
    function: identical loss and all three grad trees."""
    a = run_pipeline(batch, impl="1f1b")
    b = run_pipeline(batch, impl="adscan")
    for got, want in zip(a, b):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("m", [1, 2])
def test_pipeline_1f1b_fewer_microbatches_than_stages(m):
    """M < pp exercises a pipeline that never reaches steady state —
    every tick is warmup/cooldown masking."""
    rng = np.random.RandomState(1)
    small = rng.randn(m, 2, HID).astype(np.float32)
    loss, gs, ge, gc = run_pipeline(small, impl="1f1b", num_microbatches=m)
    ref_loss, (rgs, rge, rgc) = sequential_reference_grads(
        small, num_microbatches=m)
    np.testing.assert_allclose(loss.item(), ref_loss.item(), rtol=1e-5)
    np.testing.assert_allclose(gs, rgs, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(ge, rge, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gc, rgc, rtol=1e-4, atol=1e-6)


def test_pipeline_impl_knob_validation(batch):
    with pytest.raises(ValueError, match="unknown pipeline impl"):
        run_pipeline(batch, impl="bogus")
    # validation applies on the forward-only path too
    with pytest.raises(ValueError, match="unknown pipeline impl"):
        run_pipeline(batch, impl="bogus", forward_only=True)


def test_pipeline_interleaved_1f1b_matches_sequential(batch):
    """The 1f1b core's virtual-chunk rings (per-chunk save/replay with
    the mirrored cotangent chunk-wrap) against the closed-form
    sequential composition — and against the AD-scan interleaved core."""
    mesh = pp_mesh()
    stacked = np.stack([stage_weight(p, 2) for p in range(PP)])

    def run(impl):
        def body(mbs, sp):
            loss, grads = forward_backward_pipelining_with_interleaving(
                (stage_fn, embed_fn, loss_fn), mbs,
                (sp[0], jnp.asarray(1.5), jnp.asarray(2.0)),
                num_microbatches=M, num_model_chunks=2, axis_name="pp",
                impl=impl)
            return loss, grads[0][None], grads[1], grads[2]

        f = shard_map(body, mesh=mesh, in_specs=(P(), P("pp")),
                      out_specs=(P(), P("pp"), P(), P()), check_vma=False)
        out = jax.jit(f)(jnp.asarray(batch), jnp.asarray(stacked))
        return tuple(np.asarray(o) for o in out)

    got = run("1f1b")
    ref_loss, (rgs, rge, rgc) = sequential_reference_grads(batch, chunks=2)
    np.testing.assert_allclose(got[0].item(), ref_loss.item(), rtol=1e-5)
    np.testing.assert_allclose(got[1], rgs, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got[2], rge, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got[3], rgc, rtol=1e-4, atol=1e-6)
    ad = run("adscan")
    for g, w in zip(got, ad):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-7)


def test_pipeline_forward_only(batch):
    ws = [stage_weight(p) for p in range(PP)]
    want = closed_form(batch, 1.5, ws, 2.0)
    loss, _, _, _ = run_pipeline(batch, forward_only=True)
    np.testing.assert_allclose(loss.item(), want, rtol=1e-5)


def test_no_pipelining_matches_sequential(batch):
    """no-pipelining grad accumulation == mean of per-microbatch grads
    (reference: fwd_bwd_no_pipelining.py:31)."""
    stacked = jnp.asarray(np.stack([stage_weight(p) for p in range(PP)]))

    def full_loss(params, mb):
        sp, e, c = params
        h = embed_fn(e, mb)
        for p in range(PP):
            h = stage_fn(sp[p], h, 0)
        return loss_fn(c, h, mb)

    params = (stacked, jnp.asarray(1.5), jnp.asarray(2.0))
    losses, grads = forward_backward_no_pipelining(
        full_loss, jnp.asarray(batch), params)

    ref_loss, (rgs, rge, rgc) = sequential_reference_grads(batch)
    np.testing.assert_allclose(np.mean(np.asarray(losses)), ref_loss,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads[0]), rgs, rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads[1]), rge, rtol=1e-4,
                               atol=1e-6)


def test_get_forward_backward_func_dispatch():
    """Reference: schedules/__init__.py:19-35."""
    assert (get_forward_backward_func(None, 1)
            is forward_backward_no_pipelining)
    assert (get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving)
    f = get_forward_backward_func(2, 4)
    assert f.func is forward_backward_pipelining_with_interleaving
    assert f.keywords == {"num_model_chunks": 2}


# ------------------------------ microbatches -------------------------------

def test_constant_microbatches():
    """Port of test_microbatches.py."""
    calc = ConstantNumMicroBatches(32, 2, 4)
    assert calc.get() == 4
    assert calc.get_current_global_batch_size() == 32
    with pytest.raises(AssertionError):
        ConstantNumMicroBatches(33, 2, 4)


def test_rampup_zero_ramp_samples():
    """ramp_samples=0 with start < final is an instant ramp, not a
    division by zero (the constructor itself admits ramp_samples >= 0)."""
    calc = RampupBatchsizeNumMicroBatches(
        start_batch_size=4, batch_size_increment=4, ramup_samples=0,
        global_batch_size=8, micro_batch_size=1, data_parallel_size=1)
    assert calc.get_current_global_batch_size() == 8
    assert calc.get() == 8


def test_rampup_microbatches():
    calc = RampupBatchsizeNumMicroBatches(
        start_batch_size=8, batch_size_increment=8, ramup_samples=80,
        global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
    assert calc.get_current_global_batch_size() == 8
    assert calc.get() == 2
    calc.update(40, True)
    assert calc.get_current_global_batch_size() == 8 + 8
    calc.update(100, True)
    assert calc.get_current_global_batch_size() == 32
    assert calc.get() == 8


# ---------------------------------- p2p ------------------------------------

def test_p2p_send_forward_recv_forward():
    """Port of test_p2p_comm.py: each stage receives the previous stage's
    tensor; stage 0 receives zeros."""
    mesh = pp_mesh(NDEV)
    xs = jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)

    f = shard_map(
        lambda x: p2p_communication.send_forward_recv_forward(x, "pp"),
        mesh=mesh, in_specs=(P("pp"),), out_specs=P("pp"), check_vma=False)
    out = np.asarray(f(xs)).ravel()
    np.testing.assert_array_equal(out, [0.0] + list(range(NDEV - 1)))


def test_p2p_send_backward_recv_backward():
    mesh = pp_mesh(NDEV)
    xs = jnp.arange(NDEV, dtype=jnp.float32).reshape(NDEV, 1)
    f = shard_map(
        lambda x: p2p_communication.send_backward_recv_backward(x, "pp"),
        mesh=mesh, in_specs=(P("pp"),), out_specs=P("pp"), check_vma=False)
    out = np.asarray(f(xs)).ravel()
    np.testing.assert_array_equal(out, list(range(1, NDEV)) + [0.0])


# ------------------------------- ltor masks --------------------------------

def test_ltor_masks_and_position_ids():
    data = jnp.asarray([[5, 1, 7, 1, 3]])  # eod = 1
    mask, loss_mask, pos = get_ltor_masks_and_position_ids(
        data, eod_token=1, eod_mask_loss=True)
    assert mask.shape == (1, 1, 5, 5)
    # causal: position 0 can only see itself → masked True above diagonal
    assert bool(mask[0, 0, 0, 1])
    assert not bool(mask[0, 0, 1, 0])
    np.testing.assert_array_equal(np.asarray(loss_mask[0]),
                                  [1, 0, 1, 0, 1])
    np.testing.assert_array_equal(np.asarray(pos[0]), np.arange(5))


def test_ltor_reset_position_ids():
    data = jnp.asarray([[5, 1, 7, 2, 3]])  # eod at index 1
    _, _, pos = get_ltor_masks_and_position_ids(
        data, eod_token=1, reset_position_ids=True)
    np.testing.assert_array_equal(np.asarray(pos[0]), [0, 1, 0, 1, 2])


# ------------------------ deep-factor topologies ---------------------------
# tp=4 and pp=4 programs have size-dependent behaviour (_sharded_init
# slicing, ring wraps, per-stage layer counts) that a (2, 2, 2) mesh never
# compiles — exercise the full factor grid on the 8-device CPU mesh
# (reference: parallel_state.py initialize grid tests).

@pytest.mark.parametrize("topology", [
    # all slow-tier: deep-pp scheduling is covered fast by the analytic
    # PP=4 schedule tests above, and the driver's dryrun_multichip runs
    # the full 3D GPT step (with loss parity) every round
    pytest.param((4, 1, 2), marks=pytest.mark.slow),
    pytest.param((2, 1, 4), marks=pytest.mark.slow),
    pytest.param((4, 2, 1), marks=pytest.mark.slow),
    pytest.param((1, 2, 4), marks=pytest.mark.slow),
])
def test_minimal_gpt_training_deep_topologies(topology):
    from apex_tpu.transformer.testing.minimal import run_minimal_gpt_training

    losses = run_minimal_gpt_training(
        n_devices=8, topology=topology, num_microbatches=4,
        micro_batch_size=1, seq_len=16, num_steps=2)
    assert len(losses) == 2
    assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow  # the driver runs this exact assertion every round via
# __graft_entry__.dryrun_multichip; the slow tier keeps it pytest-visible
def test_minimal_gpt_loss_parity_vs_single_device():
    """The 8-device (pp, dp, tp) first-step loss must equal a sequential
    1-device replay of the same model/init/batch — the same check
    __graft_entry__.dryrun_multichip asserts for the driver."""
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.minimal import (
        reference_first_step_loss,
        run_minimal_gpt_training,
        toy_batch,
    )

    pp, dp, tp = 2, 2, 2
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * pp, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)
    losses = run_minimal_gpt_training(
        n_devices=8, cfg=cfg, topology=(pp, dp, tp), num_microbatches=4,
        micro_batch_size=2, seq_len=16, num_steps=1)
    ref = reference_first_step_loss(
        cfg, pp, toy_batch(cfg.vocab_size, 4, 2 * dp, 16))
    assert abs(losses[0] - ref) <= 0.05, (losses[0], ref)


@pytest.mark.slow  # pytest twin of the round-5 dryrun_multichip check
def test_minimal_gpt_trajectory_and_grad_norm_parity():
    """3 training steps of the (2, 2, 2) run track the sequential
    1-device replay in BOTH per-step loss and unscaled global grad norm
    — the trajectory version of the parity above (a wrong-but-small
    gradient error passes a single-step loss check but not this)."""
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.minimal import (
        reference_training,
        run_minimal_gpt_training,
        toy_batch,
    )

    pp, dp, tp = 2, 2, 2
    cfg = TransformerConfig(
        hidden_size=64, num_layers=2 * pp, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)
    losses, gnorms = run_minimal_gpt_training(
        n_devices=8, cfg=cfg, topology=(pp, dp, tp), num_microbatches=4,
        micro_batch_size=2, seq_len=16, num_steps=3,
        return_grad_norms=True)
    ref_losses, ref_gnorms = reference_training(
        cfg, pp, toy_batch(cfg.vocab_size, 4, 2 * dp, 16), num_steps=3)
    for l, rl in zip(losses, ref_losses):
        assert abs(l - rl) <= 0.05, (losses, ref_losses)
    for g, rg in zip(gnorms, ref_gnorms):
        assert abs(g - rg) <= 0.05 * max(rg, 1e-6), (gnorms, ref_gnorms)


def test_dryrun_multichip_topology_plan_includes_16_way():
    """__graft_entry__.dryrun_multichip(16) (VERDICT #6 remainder) must
    drive the capped factorization (2, 4, 2) AND the deeper explicit
    pp=4/dp=2/tp=2 mesh — asserted on the topology plan here (fast);
    the full 16-way parity run is the slow twin below."""
    import __graft_entry__
    from apex_tpu.transformer.testing.minimal import factorize_mesh

    assert factorize_mesh(16) == (2, 4, 2)
    assert __graft_entry__.dryrun_topologies(16) == [(2, 4, 2), (4, 2, 2)]
    # every plan factorizes its device count exactly (the 32/64 plans
    # may declare dp as an (inner, outer) pair — ISSUE 8; the
    # hierarchical-plan content asserts live in tests/test_collectives)
    from apex_tpu.transformer.testing.minimal import dp_axes_of

    for n in (1, 2, 4, 8, 16, 32, 64):
        for pp, dp, tp in __graft_entry__.dryrun_topologies(n):
            dp_size = dp_axes_of(dp)[0]
            assert pp * dp_size * tp == n, (n, pp, dp, tp)


@pytest.mark.slow  # pytest twin of the driver's dryrun_multichip(16):
# own subprocess because it needs 16 virtual devices (conftest pins 8)
def test_dryrun_multichip_16_parity_subprocess():
    import os
    import subprocess
    import sys

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__; __graft_entry__.dryrun_multichip(16)"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "trajectory + grad-norm parity ok across 2 topologies" \
        in out.stdout
    assert "pp=4/dp=2/tp=2" in out.stdout


@pytest.mark.slow  # the ISSUE-8 widened twin: 32 virtual devices, pp=8
# and a hierarchically factored dp pair under the same parity oracle +
# compressed-vs-uncompressed comm accounting in the MULTICHIP tail
def test_dryrun_multichip_32_parity_subprocess():
    import os
    import subprocess
    import sys

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__; __graft_entry__.dryrun_multichip(32)"],
        capture_output=True, text=True, timeout=3500, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "trajectory + grad-norm parity ok across 4 topologies" \
        in out.stdout
    assert "pp=8/dp=2/tp=2" in out.stdout
    assert "dp=(2, 4)" in out.stdout        # the hierarchical mesh ran
    assert "comm_int8[" in out.stdout       # compressed twin stamped
