"""tools/window_report.py — the window-economics reporter (ISSUE 7
acceptance: "reproduces the round-5 window timeline from committed
artifacts alone"). The golden half runs against the REAL committed
``benchmarks/device_logs_r05`` directory (frozen history — exact
assertions are safe); the ledger/manifest/probe summaries get synthetic
fixtures so the test doesn't chase the live ledger as later rounds
append to it. Jax-free and subprocess-free (the tool itself never
touches a backend)."""

import contextlib
import importlib.util
import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu.resilience import manifest as manifest_mod
from apex_tpu.telemetry import costs, ledger

_spec = importlib.util.spec_from_file_location(
    "window_report", os.path.join(REPO, "tools", "window_report.py"))
wr = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(wr)

R05_LOGS = os.path.join(REPO, "benchmarks", "device_logs_r05")


# ------------------------------------------- round-5 golden timeline


def test_round5_timeline_golden():
    """The committed round-5 logs reconstruct the one 50-minute window
    the round got: where its minutes went, per-program, with the
    verdicts the resilience classifier assigns today."""
    entries, timed = wr.logs_timeline(R05_LOGS)
    by_name = {e["name"]: e for e in entries}

    # the scored bench slot: 3 attempts (3 backend-init banners), a
    # degraded-relay JSON line, 12.4 minutes of window
    bench = by_name["bench.log"]
    assert bench["attempts"] == 3
    assert bench["verdict"] == "degraded_relay"
    assert bench["value"] == 7842.6 and bench["mfu"] == 0.0297
    assert bench["slot_minutes"] == 12.4

    # the §10b wedge signature: banner, then nothing — gpt_rows burned
    # 15 minutes producing no output (the slot the report exists to
    # make visible)
    rows = by_name["gpt_rows.log"]
    assert rows["verdict"] == "no-output" and rows["rows"] == 0
    assert rows["slot_minutes"] == 15.0

    # the final slot is unknowable from logs alone, and bench2's last
    # JSON line classifies as wedged
    assert timed[-1]["name"] == "bench2.log"
    assert timed[-1]["slot_minutes"] is None
    assert timed[-1]["verdict"] == "wedged"

    # table harnesses: rows counted, optimistic "table" verdict
    assert by_name["attention.log"]["verdict"] == "table"
    # 22 measured rows — the Tracer "dispatch overhead ... ms" header
    # is NOT a row (a log holding only the header reads no-output)
    assert by_name["attention.log"]["rows"] == 22

    # timeline is sorted by first banner and every slot is anchored
    starts = [e["starts"][0] for e in timed]
    assert starts == sorted(starts)
    assert [e["name"] for e in timed][:2] == ["attention.log",
                                              "bench.log"]


def test_header_only_log_is_no_output(tmp_path):
    """A run that wedged right after calibration leaves a banner plus
    the Tracer header ("dispatch overhead 82.6 ms subtracted") and no
    measured rows — the report must call that dead slot no-output, not
    a productive "table" (the header's "ms" must not count as a row)."""
    log = tmp_path / "wedged.log"
    log.write_text(
        "WARNING:2026-08-01 09:00:00,123:jax._src.xla_bridge:794: ...\n"
        "params: 124.5M   (method: 32-step lax.scan, 1 dispatch, "
        "dispatch overhead 82.6 ms subtracted)\n")
    entry = wr.parse_log(str(log))
    assert entry["rows"] == 0
    assert entry["verdict"] == "no-output"


def test_round5_window_envelope():
    report = wr.build_report(logs_dir=R05_LOGS)
    w = report["logs"]["window"]
    assert w["start"] == "2026-08-01 08:31:29"
    assert w["last_activity"] == "2026-08-01 09:42:51"
    assert w["minutes"] == 71.4
    assert report["logs"]["unanchored"] == []


def test_round5_report_prints_and_cli_runs():
    report = wr.build_report(logs_dir=R05_LOGS)
    buf = io.StringIO()
    wr.print_report(report, out=buf)
    text = buf.getvalue()
    assert "71.4 min of anchored activity" in text
    assert "gpt_rows.log" in text and "no-output" in text
    # the CLI surface (in-process main; --json appends one JSON line —
    # the driver-interface idiom)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = wr.main(["--ledger", os.devnull, "--logs", R05_LOGS,
                      "--json"])
    assert rc == 0
    last = buf.getvalue().strip().splitlines()[-1]
    parsed = json.loads(last)
    assert parsed["logs"]["window"]["minutes"] == 71.4


# ------------------------------------------------ ledger-side summary


def _seed(path, **extra):
    return ledger.append_record("bench", "cpu", 0.5, 2, path=path,
                                extra=extra)


def test_ledger_summary_counts_and_attribution(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    cost = costs.build(xla_flops=2e12, hbm_bytes=1e10, steps=2,
                       model_flops_per_step=1.2e12, platform="tpu",
                       source="compiled")
    _seed(path, value=1000.0, mfu=0.30, cost=cost,
          compile_cache={"enabled": True, "hits": 5, "misses": 2})
    _seed(path, cost=costs.null_block())
    records = ledger.read_ledger(path)
    led = wr.ledger_summary(records)
    assert led["records"] == 2
    assert led["cost_blocks"] == {"present": 2, "reporting": 1}
    assert led["compile_cache"]["hits"] == 5
    assert len(led["attribution"]) == 1
    a = led["attribution"][0]
    assert a["mfu"] == 0.30 and a["mfu_bound"] == cost["mfu_bound"]
    # and the text report names the measured-vs-bound gap
    buf = io.StringIO()
    wr.print_report({"ledger": led}, out=buf)
    assert "attribution" in buf.getvalue()
    assert "cost blocks: 2 present, 1 with XLA numbers" in buf.getvalue()


def test_committed_ledger_is_summarizable():
    """The real committed ledger always produces a summary (the
    acceptance criterion's 'from committed artifacts alone') — loose
    assertions only; later rounds append records."""
    led = wr.ledger_summary(ledger.read_ledger(
        os.path.join(REPO, "benchmarks", "ledger.jsonl")))
    assert led["records"] >= 34
    assert led["injected"] == 0
    assert "bench" in led["by_harness"]


# ------------------------------------------- manifest + probe summaries


def test_manifest_and_probe_summaries(tmp_path):
    man = str(tmp_path / "manifest.json")
    manifest_mod.record(man, "bench_first", "healthy", rc=0)
    summary = wr.manifest_summary(man)
    assert "bench_first" in summary["cashed"]
    assert summary["verdicts"]["bench_first"] == "healthy"
    assert set(summary["owed"]) | set(summary["cashed"]) >= set(
        manifest_mod.PASS_ROWS)

    probe = tmp_path / "probe_state.json"
    probe.write_text(json.dumps(
        {"ts": 1754000000.0, "verdict": "healthy", "rc": 0,
         "detail": "value=102196"}))
    ps = wr.probe_summary(str(probe))
    assert ps["verdict"] == "healthy" and "at" in ps

    # degradation, never a crash: missing probe file is None, garbage
    # manifest is an error entry — and both print
    assert wr.probe_summary(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2")
    buf = io.StringIO()
    wr.print_report({"manifest": wr.manifest_summary(str(bad)),
                     "probe": wr.probe_summary(str(bad))}, out=buf)
    assert "unreadable" in buf.getvalue()


def test_empty_round_is_a_report_not_an_error(tmp_path):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = wr.main(["--ledger", str(tmp_path / "none.jsonl")])
    assert rc == 0
    assert "nothing to report" in buf.getvalue()


# ------------------------------- serving economics + overlap (ISSUE 11)


def test_serving_economics_and_overlap_sections(tmp_path):
    """A ledger carrying serving/slo blocks and an overlap_bound stamp
    renders the serving-economics section: trace + arrival process,
    goodput vs the decode-scan line, attainment, occupancy
    high-waters, and the overlap column."""
    slo = {"ttft_p50_ms": 5.0, "ttft_p99_ms": 9.0,
           "per_token_p50_ms": 1.0, "per_token_p99_ms": 2.0,
           "goodput_tok_s": 90.0, "slo_attainment": 0.75,
           "slo_ttft_ms": 1000.0, "slo_tpot_ms": 100.0,
           "arrival_process": "diurnal", "offered_load": 2.0,
           "max_queue_depth": 3, "kv_page_high_water": 10,
           # multi-token decode blocks (ISSUE 17)
           "decode_block_k": 4}
    cost = costs.attach_overlap(costs.null_block(), host_ms=0.25)
    rec = ledger.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"serving": {"tokens_per_s": 100.0,
                           "scan_tokens_per_s": 900.0, "p50_ms": 1.0,
                           "p99_ms": 2.0, "trace_id": "tr-abcdef1234",
                           "kv_pages": 24,
                           # dispatch economics (ISSUE 17): 200 tokens
                           # over 50 K-block dispatches = 4.00/dispatch
                           "decode_steps": 50,
                           "tokens_generated": 200},
               "slo": slo, "cost": cost})
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    report = wr.build_report(ledger_path=str(path))
    led = report["ledger"]
    assert len(led["serving"]) == 1
    row = led["serving"][0]
    assert row["trace_id"] == "tr-abcdef1234"
    assert row["slo"]["slo_attainment"] == 0.75
    assert len(led["overlap"]) == 1
    assert led["overlap"][0]["host_ms"] == 0.25

    buf = io.StringIO()
    wr.print_report(report, out=buf)
    text = buf.getvalue()
    assert "serving economics:" in text
    assert "tr-abcdef1234" in text
    assert "arrival=diurnal" in text and "attainment=75%" in text
    # goodput 90 vs scan 900 -> 90% under the scan line
    assert "90% under the scan line" in text
    assert "max queue 3, kv high-water 10/24 pages" in text
    # dispatch economics (ISSUE 17): tokens-per-dispatch readout names
    # the program K it was measured at
    assert ("dispatch economics: 4.00 tokens/dispatch "
            "(200 tok / 50 decode dispatches, decode_block_k=4)") in text
    assert "overlap" in text and "comm+host 0.25 ms" in text


def test_serving_section_absent_without_serving_rows(tmp_path):
    rec = ledger.make_record("bench", "cpu", 0.1, 2)
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    report = wr.build_report(ledger_path=str(path))
    assert report["ledger"]["serving"] == []
    assert report["ledger"]["overlap"] == []
    buf = io.StringIO()
    wr.print_report(report, out=buf)
    assert "serving economics" not in buf.getvalue()
