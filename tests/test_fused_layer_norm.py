"""FusedLayerNorm/FusedRMSNorm forward/backward parity vs torch
(reference: tests/L0/run_fused_layer_norm/test_fused_layer_norm.py —
apex vs torch.nn.LayerNorm across shapes/dtypes, fwd + bwd)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from apex_tpu.normalization import (
    FusedLayerNorm, FusedRMSNorm, fused_layer_norm, fused_rms_norm,
)

SHAPES = [((2, 3, 8), (8,)), ((4, 16), (16,)), ((2, 5, 4, 6), (4, 6))]


@pytest.mark.parametrize("shape,norm_shape", SHAPES)
@pytest.mark.parametrize("affine", [True, False])
def test_layer_norm_forward_vs_torch(shape, norm_shape, affine):
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.rand(*norm_shape).astype(np.float32) + 0.5 if affine else None
    b = rng.randn(*norm_shape).astype(np.float32) if affine else None

    tln = torch.nn.functional.layer_norm(
        torch.tensor(x), norm_shape,
        torch.tensor(w) if affine else None,
        torch.tensor(b) if affine else None, eps=1e-5)
    got = fused_layer_norm(jnp.asarray(x), norm_shape,
                           jnp.asarray(w) if affine else None,
                           jnp.asarray(b) if affine else None, eps=1e-5)
    np.testing.assert_allclose(tln.numpy(), np.asarray(got), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape,norm_shape", SHAPES[:2])
def test_layer_norm_backward_vs_torch(shape, norm_shape):
    rng = np.random.RandomState(1)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.rand(*norm_shape).astype(np.float32) + 0.5
    b = rng.randn(*norm_shape).astype(np.float32)

    xt = torch.tensor(x, requires_grad=True)
    wt = torch.tensor(w, requires_grad=True)
    bt = torch.tensor(b, requires_grad=True)
    torch.nn.functional.layer_norm(xt, norm_shape, wt, bt, eps=1e-5).sum().backward()

    def f(x, w, b):
        return jnp.sum(fused_layer_norm(x, norm_shape, w, b, eps=1e-5))

    gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(xt.grad.numpy(), np.asarray(gx), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(wt.grad.numpy(), np.asarray(gw), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(bt.grad.numpy(), np.asarray(gb), rtol=1e-4, atol=1e-4)


def test_rms_norm_vs_manual():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 16).astype(np.float32)
    w = rng.rand(16).astype(np.float32) + 0.5
    ms = np.mean(x ** 2, axis=-1, keepdims=True)
    want = x / np.sqrt(ms + 1e-5) * w
    got = fused_rms_norm(jnp.asarray(x), (16,), jnp.asarray(w), eps=1e-5)
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-5, atol=1e-5)


def test_half_dtype_output():
    x = jnp.ones((4, 8), jnp.bfloat16)
    out = fused_layer_norm(x, (8,))
    assert out.dtype == jnp.bfloat16  # stats in fp32, output back to input dtype


def test_modules():
    mod = FusedLayerNorm(normalized_shape=(8,))
    x = jnp.ones((2, 8))
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    assert y.shape == (2, 8)
    assert params["params"]["weight"].shape == (8,)

    mod = FusedRMSNorm(normalized_shape=8, elementwise_affine=False)
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    assert y.shape == (2, 8)
