"""Fleet router chaos suite (ISSUE 19): every failover path driven
through REAL ServingEngine replicas on CPU with deterministic fault
plans (``router_kill`` / ``router_wedge`` / ``router_slow`` sites) —
the fleet generalization of tests/test_serving_chaos.py, and the
acceptance invariants:

* killing 1 of N replicas mid-trace loses ZERO accepted requests and
  every surviving stream is token-for-token the unkilled single-engine
  run (greedy decode + shared params = deterministic replay);
* the failed-over chain is ordered (``failover`` before ``replayed``)
  in the ONE fleet event log, and the fleet gauges match the router's
  stats account;
* a wedged replica round (hang > ``step_timeout_s``) is timed out,
  classified ``wedged``, and fails over exactly like a crash;
* a transient failure only DEGRADES below the breaker threshold — the
  replica recovers to healthy without a kill;
* the breaker-tripped replica probes back in through the real engine
  (dead -> draining -> rejoined -> healthy) and the fleet keeps parity
  throughout, prefix-cache refcounts included.
"""

import json

import pytest

from apex_tpu.resilience import faults
from apex_tpu.serving import Request, Router, ServingEngine, lifecycle
from apex_tpu.serving.router import (
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTHY,
    REJOINED,
    validate_health,
)


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


# one full page (page_size=4) of shared system-style prefix + distinct
# tails: the same trace exercises plain routing, failover replay AND
# prefix-refcount composition
_BASE = [5, 9, 13, 2]


def _requests():
    return [Request(rid=i, prompt=_BASE + [20 + i, 30 + i],
                    max_new_tokens=8, arrival=0.0) for i in range(6)]


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from apex_tpu.serving import model as smodel

    params = smodel.init_gpt_params(cfg)
    ref = ServingEngine(cfg, params=params, num_slots=2, page_size=4,
                        num_pages=32, max_seq=32, prefill_len=16,
                        overlap=False)
    reqs = _requests()
    for r in reqs:
        ref.submit(r)
    n = 0
    while not all(r.done() for r in reqs):
        ref.step()
        n += 1
        assert n < 300
    return cfg, params, {r.rid: list(r.out_tokens) for r in reqs}


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Plan isolation (the serving-chaos idiom): no fault plan leaks
    in, and the per-plan ``times`` spend counters reset between
    tests."""
    monkeypatch.delenv("APEX_FAULT_PLAN", raising=False)
    faults._cache["fired"] = {}
    yield
    faults._cache["fired"] = {}


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_seq", 32)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("overlap", False)
    return ServingEngine(cfg, params=params, **kw)


def _fleet_router(cfg, params, n=3, *, engine_kw=None, **router_kw):
    lifecycle.enable()
    try:
        engines = [_engine(cfg, params, **(engine_kw or {}))
                   for _ in range(n)]
        return Router(engines, **router_kw)
    finally:
        lifecycle.reset_enabled()


def _plan(monkeypatch, plan):
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(plan))


def _drain(rt, reqs, guard=120):
    for r in reqs:
        assert rt.submit(r) is None
    n = 0
    while not all(r.done() for r in reqs):
        rt.step()
        n += 1
        assert n < guard, [r.out_tokens for r in reqs]
    rt.step()


def _assert_parity(reqs, ref):
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)


def _assert_fleet_contract(rt):
    assert rt.events.validate_order() == []
    for r in rt.replicas:
        assert validate_health(r.history) == [], (r.name, r.history)
        r.engine.allocator.check_invariants()
        if r.engine.prefix is not None:
            r.engine.prefix.check_invariants()


# ------------------------------------------------- no-chaos baseline


def test_fleet_without_chaos_matches_single_engine(setup):
    """The disabled-mode converse: a healthy 3-replica fleet under
    round_robin produces token-for-token the single-engine streams
    (shared params + greedy decode make replicas interchangeable) and
    spreads the load."""
    cfg, params, ref = setup
    rt = _fleet_router(cfg, params, policy="round_robin")
    reqs = _requests()
    _drain(rt, reqs)
    _assert_parity(reqs, ref)
    assert [r.routed for r in rt.replicas] == [2, 2, 2]
    assert rt.stats["deaths"] == rt.stats["failovers"] == 0
    assert all(r.state == HEALTHY for r in rt.replicas)
    _assert_fleet_contract(rt)


# ------------------------------------- the acceptance kill: zero loss


def test_kill_one_of_three_mid_trace_zero_loss_parity(
        setup, monkeypatch):
    """THE acceptance invariant: chaos-kill 1 of 3 replicas mid-trace
    — zero accepted requests lost, failed-over streams replay
    token-for-token through survivors, the fleet event log orders
    failover before replayed, and the gauges match the stats."""
    cfg, params, ref = setup
    _plan(monkeypatch, [{"site": "router_kill", "kind": "raise",
                         "message": "injected replica death",
                         "match_ctx": {"tick": 2, "replica": "r1"}}])
    rt = _fleet_router(cfg, params, breaker_failures=1,
                       probe_wait_rounds=64)
    reqs = _requests()
    _drain(rt, reqs)
    # zero loss + parity: all six accepted requests completed with the
    # unkilled single-engine streams
    assert sorted(q.rid for q in rt.completed()) == list(range(6))
    _assert_parity(reqs, ref)
    r1 = rt.replicas[1]
    assert r1.state == DEAD and DEAD in r1.history
    assert rt.stats["deaths"] == 1
    assert rt.stats["failovers"] >= 1
    assert rt.stats["replayed"] >= rt.stats["failovers"]
    # the failed-over chains: failover strictly before replayed, and
    # the replay re-admits on a SURVIVOR
    chains = 0
    for q in reqs:
        chain = [e["event"] for e in rt.events.request_events(q.rid)]
        if "failover" in chain:
            chains += 1
            assert chain.index("failover") < chain.index("replayed"), \
                chain
            assert "finished" in chain[chain.index("replayed"):], chain
    assert chains == rt.stats["failovers"]
    # fleet gauges are the stats, sampled per round
    last = rt.gauge_rows()[-1]
    assert last["serve_routed"] == rt.stats["routed"] == 6
    assert last["serve_failovers"] == rt.stats["failovers"]
    assert last["serve_replayed"] == rt.stats["replayed"]
    _assert_fleet_contract(rt)


def test_kill_composes_with_prefix_cache(setup, monkeypatch):
    """Failover drain under the prefix cache: the dead replica's
    shared pages decref cleanly (never freed under live refs), the
    survivors' caches stay consistent, and parity holds — the
    preemption-composition story at fleet scope."""
    cfg, params, ref = setup
    _plan(monkeypatch, [{"site": "router_kill", "kind": "raise",
                         "message": "injected replica death",
                         "match_ctx": {"tick": 2, "replica": "r0"}}])
    # ONE slot per replica: its two requests admit sequentially, so
    # the second's first page actually looks up the page the first
    # registered (a same-round packed prefill can't hit)
    rt = _fleet_router(cfg, params, breaker_failures=1,
                       probe_wait_rounds=64,
                       engine_kw={"prefix_cache": True,
                                  "num_slots": 1})
    reqs = _requests()
    _drain(rt, reqs)
    assert rt.stats["deaths"] == 1
    _assert_parity(reqs, ref)
    # the shared-prefix trace actually shared: survivors hit the page
    assert sum(r.engine.prefix.hit_tokens for r in rt.replicas) > 0
    _assert_fleet_contract(rt)


# ------------------------------------------------ wedge + slow rounds


def test_wedged_replica_timed_out_and_failed_over(setup, monkeypatch):
    """A replica round that HANGS (the relay wedge at fleet scope) is
    timed out by the router's watchdog, classified ``wedged``, and the
    breaker fails it over exactly like a crash — the trace drains with
    parity through the survivors. The timeout arms only after the
    warmup rounds (compile time must not read as a wedge)."""
    cfg, params, ref = setup
    rt = _fleet_router(cfg, params, breaker_failures=1,
                       probe_wait_rounds=64)
    reqs = _requests()
    for r in reqs:
        assert rt.submit(r) is None
    for _ in range(3):              # compile + steady rounds, untimed
        rt.step()
    _plan(monkeypatch, [{"site": "router_wedge", "kind": "hang",
                         "seconds": 1.0,
                         "match_ctx": {"tick": 3, "replica": "r1"}}])
    rt.step_timeout_s = 0.25
    n = 0
    while not all(r.done() for r in reqs):
        rt.step()
        n += 1
        assert n < 120
    rt.step()
    r1 = rt.replicas[1]
    assert r1.state == DEAD
    assert r1.last_verdict == "wedged"
    assert rt.stats["deaths"] == 1
    _assert_parity(reqs, ref)
    assert sorted(q.rid for q in rt.completed()) == list(range(6))
    _assert_fleet_contract(rt)


def test_transient_failure_degrades_below_breaker(setup, monkeypatch):
    """One transient replica failure (router_slow, pinned to a single
    tick) below the breaker threshold: the replica walks healthy ->
    degraded -> healthy — no kill, no failover, full parity. The
    breaker requires CONSECUTIVE failures; a single blip must not
    cost a replica."""
    cfg, params, ref = setup
    _plan(monkeypatch, [{"site": "router_slow", "kind": "raise",
                         "message": "transient relay stall",
                         "match_ctx": {"tick": 2, "replica": "r0"}}])
    rt = _fleet_router(cfg, params, breaker_failures=2)
    reqs = _requests()
    _drain(rt, reqs)
    r0 = rt.replicas[0]
    assert rt.stats["deaths"] == rt.stats["failovers"] == 0
    assert DEGRADED in r0.history
    assert r0.state == HEALTHY
    _assert_parity(reqs, ref)
    _assert_fleet_contract(rt)


# --------------------------------------------- probe rejoin, end to end


def test_breaker_trip_probe_rejoin_full_cycle(setup, monkeypatch):
    """The full health cycle on real engines: two consecutive injected
    failures trip the breaker (dead, drained, replayed), the paced
    probe drives a REAL prefill+decode through the rejoining engine,
    and the replica walks dead -> draining -> rejoined -> healthy —
    while the trace keeps zero-loss parity throughout."""
    cfg, params, ref = setup
    # raise-kind faults fire on EVERY match (`times` caps only deny
    # budgets), so the two consecutive failures are tick-pinned — the
    # later probe rounds fall outside both matches and succeed
    _plan(monkeypatch, [{"site": "router_kill", "kind": "raise",
                         "message": "injected replica death",
                         "match_ctx": {"tick": 2, "replica": "r1"}},
                        {"site": "router_kill", "kind": "raise",
                         "message": "injected replica death",
                         "match_ctx": {"tick": 3, "replica": "r1"}}])
    rt = _fleet_router(cfg, params, breaker_failures=2,
                       probe_wait_rounds=2, probe_attempts=3)
    reqs = _requests()
    _drain(rt, reqs)
    _assert_parity(reqs, ref)
    r1 = rt.replicas[1]
    assert rt.stats["deaths"] == 1
    # post-drain: let the probe schedule run the replica back in
    n = 0
    while r1.state not in (REJOINED, HEALTHY):
        rt.step()
        n += 1
        assert n < 80, r1.history
    rt.step()
    assert r1.state == HEALTHY
    for state in (DEGRADED, DEAD, DRAINING, REJOINED):
        assert state in r1.history, r1.history
    assert rt.stats["probes"] >= 1 and rt.stats["rejoins"] == 1
    # the probe is a router fabrication, never trace load
    assert sorted(q.rid for q in rt.completed()) == list(range(6))
    _assert_fleet_contract(rt)
