"""End-to-end example smoke tests on the 8-device CPU mesh (reference:
tests/L1 runs the real main_amp.py; these are the fast equivalents)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2"])
def test_imagenet_main_amp_smoke(tmp_path, opt_level):
    """The L1 cross-product, shrunk: tiny resnet18 on synthetic data for a
    few steps per opt level; loss must be finite."""
    from examples.imagenet.main_amp import main

    loss = main([
        "--synthetic", "--arch", "resnet18", "--steps", "4",
        "-b", "16", "--image-size", "32", "--num-classes", "10",
        "--opt-level", opt_level, "--print-freq", "2",
        "--checkpoint", str(tmp_path / "ckpt.pkl"),
    ])
    assert np.isfinite(loss)
    assert (tmp_path / "ckpt.pkl").exists()


def test_imagenet_resume_roundtrip(tmp_path):
    from examples.imagenet.main_amp import main

    ck = str(tmp_path / "ckpt.pkl")
    main(["--synthetic", "--arch", "resnet18", "--steps", "3", "-b", "16",
          "--image-size", "32", "--num-classes", "10", "--checkpoint", ck])
    loss = main(["--synthetic", "--arch", "resnet18", "--steps", "3",
                 "-b", "16", "--image-size", "32", "--num-classes", "10",
                 "--checkpoint", ck, "--resume", ck, "--epochs", "2"])
    assert np.isfinite(loss)


def test_dcgan_main_amp_smoke():
    """Multi-model / multi-optimizer / 3-loss amp path."""
    from examples.dcgan.main_amp import main

    loss_d, loss_g = main(["--steps", "3", "-b", "8", "--image-size", "64",
                           "--opt-level", "O1"])
    assert np.isfinite(loss_d) and np.isfinite(loss_g)
