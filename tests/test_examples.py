"""End-to-end example smoke tests on the 8-device CPU mesh (reference:
tests/L1 runs the real main_amp.py; these are the fast equivalents)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.slow
@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2"])
def test_imagenet_main_amp_smoke(tmp_path, opt_level):
    """The L1 cross-product, shrunk: tiny resnet18 on synthetic data for a
    few steps per opt level; loss must be finite."""
    from examples.imagenet.main_amp import main

    loss = main([
        "--synthetic", "--arch", "resnet18", "--steps", "4",
        "-b", "16", "--image-size", "32", "--num-classes", "10",
        "--opt-level", opt_level, "--print-freq", "2",
        "--checkpoint", str(tmp_path / "ckpt.pkl"),
    ])
    assert np.isfinite(loss)
    assert (tmp_path / "ckpt.pkl").exists()


def test_imagenet_lr_schedule_matches_reference_shape():
    """make_lr_schedule: linear 5-epoch warmup, /10 step decay at epochs
    30/60/80 (the reference adjust_learning_rate)."""
    import jax.numpy as jnp

    from examples.imagenet.main_amp import make_lr_schedule

    s = make_lr_schedule(1.0, 100)  # 100 steps/epoch
    assert float(s(jnp.int32(249))) == pytest.approx(0.5, abs=0.01)
    assert float(s(jnp.int32(600))) == pytest.approx(1.0)      # post-warm
    assert float(s(jnp.int32(31 * 100))) == pytest.approx(0.1)
    assert float(s(jnp.int32(61 * 100))) == pytest.approx(0.01)
    assert float(s(jnp.int32(81 * 100))) == pytest.approx(0.001)


@pytest.mark.slow
def test_imagenet_l1_cross_product(tmp_path):
    """The L1 cross-product (reference: tests/L1/common/run_test.sh:22-47
    iterates {O0-O3} x {keep_batchnorm_fp32} x {loss_scale}; compare.py
    then diffs each config's loss/metric trace against a recorded
    baseline run of the SAME config). The portable form of that property:
    every combo trains to a finite loss, and re-running a combo from the
    same seed reproduces the final loss bitwise (the recorded-baseline
    comparison without a stored baseline)."""
    from examples.imagenet.main_amp import main

    def run(opt_level, loss_scale=None, keep_bn=None):
        args = ["--synthetic", "--arch", "resnet18", "--steps", "4",
                "-b", "16", "--image-size", "32", "--num-classes", "10",
                "--opt-level", opt_level, "--deterministic",
                "--checkpoint", str(tmp_path / "ckpt.pkl")]
        if loss_scale is not None:
            args += ["--loss-scale", loss_scale]
        if keep_bn is not None:
            args += ["--keep-batchnorm-fp32", keep_bn]
        return main(args)

    combos = [
        ("O0", None, None),
        ("O1", "dynamic", None),
        ("O2", "dynamic", None),
        ("O3", "128.0", "True"),
    ]
    losses = {}
    for opt_level, loss_scale, keep_bn in combos:
        loss = run(opt_level, loss_scale, keep_bn)
        assert np.isfinite(loss), (opt_level, loss_scale, keep_bn)
        losses[opt_level] = float(loss)
    # run-to-run reproducibility: same config + seed -> identical result
    b = run("O2", "dynamic")
    assert losses["O2"] == float(b), (losses["O2"], float(b))


@pytest.mark.slow
def test_imagenet_resume_roundtrip(tmp_path):
    from examples.imagenet.main_amp import main

    ck = str(tmp_path / "ckpt.pkl")
    main(["--synthetic", "--arch", "resnet18", "--steps", "3", "-b", "16",
          "--image-size", "32", "--num-classes", "10", "--checkpoint", ck])
    loss = main(["--synthetic", "--arch", "resnet18", "--steps", "3",
                 "-b", "16", "--image-size", "32", "--num-classes", "10",
                 "--checkpoint", ck, "--resume", ck, "--epochs", "2"])
    assert np.isfinite(loss)


def _conv_input_dtypes(opt_level):
    """Dtypes of every conv_general_dilated input in the train-step jaxpr
    for the ImageNet model wired the way main_amp.main wires it."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.amp.frontend import Properties, build_policy, opt_levels
    from apex_tpu.models import resnet18

    policy = build_policy(opt_levels[opt_level](Properties()))
    model = resnet18(num_classes=10, dtype=policy.compute_dtype)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    # trace-only: eval_shape the init (no conv compiles), materialize zero
    # params, and inspect the traced jaxpr — nothing executes on device
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x, train=False))
    variables = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    params = amp.initialize(variables["params"], opt_level=opt_level)

    def fwd(p, x):
        out, _ = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x.astype(policy.compute_dtype), train=True,
            mutable=["batch_stats"])
        return out

    jaxpr = jax.make_jaxpr(fwd)(params, x)
    dtypes = set()

    def walk(jpr):
        for eqn in jpr.eqns:
            if eqn.primitive.name == "conv_general_dilated":
                dtypes.update(v.aval.dtype for v in eqn.invars)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr)

    walk(jaxpr.jaxpr)
    assert dtypes, "no convs found in the jaxpr"
    return dtypes


def test_imagenet_o2_computes_convs_in_bf16():
    """O2 must actually change the conv compute dtype (the whole point of
    amp): every conv input under O2 is bf16; under O0 everything is fp32."""
    import jax.numpy as jnp

    assert _conv_input_dtypes("O2") == {jnp.dtype(jnp.bfloat16)}
    assert _conv_input_dtypes("O0") == {jnp.dtype(jnp.float32)}


@pytest.mark.slow
def test_dcgan_main_amp_smoke():
    """Multi-model / multi-optimizer / 3-loss amp path."""
    from examples.dcgan.main_amp import main

    loss_d, loss_g = main(["--steps", "3", "-b", "8", "--image-size", "64",
                           "--opt-level", "O1"])
    assert np.isfinite(loss_d) and np.isfinite(loss_g)


@pytest.mark.slow
def test_imagenet_evaluate_path():
    """--evaluate runs the reference's validate() analog: eval-mode BN,
    prec@1/5 metering, finite loss."""
    from examples.imagenet.main_amp import main

    loss = main(["--synthetic", "--evaluate", "--arch", "resnet18",
                 "--steps", "2", "-b", "16", "--image-size", "32",
                 "--num-classes", "10", "--opt-level", "O2"])
    assert np.isfinite(loss)
