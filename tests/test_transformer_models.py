"""Standalone GPT/BERT model + fused softmax tests.

Ports: tests/L0/run_transformer/test_fused_softmax.py (kernel vs Python
softmax parity), run_gpt_minimal_test.py / run_bert_minimal_test.py
(model forward+backward smoke), plus a TP-invariance check (tp=1 vs tp=4
produce identical loss — the substance of test_layers.py's parity asserts,
composed through a whole model).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import (
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.testing import (
    BertModel,
    GPTModel,
    TransformerConfig,
)
from apex_tpu.transformer.testing.standalone_transformer_lm import (
    attention_mask_func,
)

NDEV = 8


def tp_mesh(tp):
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


def smap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


# ------------------------------ fused softmax ------------------------------

def _ref_softmax(x, mask, scale):
    x = np.asarray(x, np.float64) * scale
    if mask is not None:
        x = np.where(mask, -1e30, x)
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_scaled_masked_softmax_matches_reference():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 4, 8, 16).astype(np.float32)
    mask = rs.rand(2, 1, 8, 16) < 0.3
    got = scaled_masked_softmax(jnp.asarray(x), jnp.asarray(mask), 0.5)
    want = _ref_softmax(x, np.broadcast_to(mask, x.shape), 0.5)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_scaled_upper_triang_masked_softmax_causal():
    rs = np.random.RandomState(1)
    x = rs.randn(3, 8, 8).astype(np.float32)
    got = np.asarray(scaled_upper_triang_masked_softmax(jnp.asarray(x), 1.0))
    causal = np.triu(np.ones((8, 8), bool), k=1)
    want = _ref_softmax(x, np.broadcast_to(causal, x.shape), 1.0)
    np.testing.assert_allclose(got, want, atol=1e-5)
    # strictly-upper entries must be exactly zero
    assert (got[:, causal] == 0).all()


def test_fully_masked_row_emits_zeros():
    x = jnp.ones((1, 1, 4, 8), jnp.float32)
    mask = jnp.ones((1, 1, 4, 8), bool)
    out = np.asarray(scaled_masked_softmax(x, mask, 1.0))
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out, 0)


@pytest.mark.parametrize("mask_type", [AttnMaskType.causal,
                                       AttnMaskType.padding])
def test_fused_scale_mask_softmax_dispatch_and_parity(mask_type):
    """Fused vs torch-style fallback parity (test_fused_softmax.py port)."""
    rs = np.random.RandomState(2)
    b, np_, sq, sk = 2, 4, 32, 32
    x = jnp.asarray(rs.randn(b, np_, sq, sk), jnp.bfloat16)
    if mask_type == AttnMaskType.causal:
        mask = None
    else:
        mask = jnp.asarray(rs.rand(b, 1, sq, sk) < 0.3)

    fused = FusedScaleMaskSoftmax(False, True, mask_type, True,
                                  attention_mask_func, False, None)
    unfused = FusedScaleMaskSoftmax(False, True, mask_type, False,
                                    attention_mask_func, True, None)
    assert fused.is_kernel_available(mask, b, np_, sq, sk)
    assert not unfused.is_kernel_available(mask, b, np_, sq, sk)

    if mask_type == AttnMaskType.causal:
        causal = jnp.triu(jnp.ones((sq, sk), bool), k=1)
        m_for_unfused = jnp.broadcast_to(causal, (b, 1, sq, sk))
    else:
        m_for_unfused = mask
    got = fused(x, mask)
    want = unfused(x, m_for_unfused)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=2e-2)


# ------------------------------ GPT ----------------------------------------

CFG = TransformerConfig(hidden_size=64, num_layers=2, num_attention_heads=4,
                        vocab_size=128, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0)


def _gpt_loss_and_grads(tp):
    mesh = tp_mesh(tp)
    rs = np.random.RandomState(3)
    b, s = 2, 16
    ids = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    model = GPTModel(CFG)

    def run(ids, pos, labels):
        def loss_fn(params):
            per_tok = model.apply({"params": params}, ids, pos, None, labels)
            return jnp.mean(per_tok)

        params = model.init(jax.random.PRNGKey(0), ids, pos, None)["params"]
        loss, grads = jax.value_and_grad(loss_fn)(params)
        # grad of the pp-replicated position embedding is a good
        # tp-invariance probe (word-embedding grads are sharded)
        return loss, grads["embedding"]["position_embeddings"]

    loss, pe_grad = smap(run, mesh, (P(), P(), P()), (P(), P()))(
        ids, pos, labels)
    return np.asarray(loss), np.asarray(pe_grad)


@pytest.mark.slow
def test_gpt_tp_invariance():
    """Loss and grads must not depend on the TP degree."""
    loss1, g1 = _gpt_loss_and_grads(1)
    loss4, g4 = _gpt_loss_and_grads(4)
    assert np.isfinite(loss1)
    np.testing.assert_allclose(loss1, loss4, rtol=1e-4)
    np.testing.assert_allclose(g1, g4, rtol=5e-3, atol=1e-5)


@pytest.mark.slow
def test_gpt_dropout_training_mode():
    """Train-mode dropout (the flax "dropout" rng collection): finite loss
    and grads, key-dependent stochasticity, and deterministic=True exactly
    recovers the dropout-free numerics — the eval/train split the
    reference gets from module.train()/eval()."""
    cfg = TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2, vocab_size=64,
        max_position_embeddings=16, hidden_dropout=0.3,
        attention_dropout=0.3)
    nodrop_cfg = dataclasses.replace(cfg, hidden_dropout=0.0,
                                     attention_dropout=0.0)
    mesh = tp_mesh(2)
    rs = np.random.RandomState(5)
    b, s = 2, 8
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))
    model = GPTModel(cfg)
    model_nodrop = GPTModel(nodrop_cfg)

    def run(ids, pos, labels, seed):
        params = model.init(jax.random.PRNGKey(0), ids, pos, None)["params"]

        def loss_fn(p):
            per_tok = model.apply(
                {"params": p}, ids, pos, None, labels,
                deterministic=False,
                rngs={"dropout": jax.random.fold_in(
                    jax.random.PRNGKey(7), seed)})
            return jnp.mean(per_tok)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        eval_loss = jnp.mean(model.apply(
            {"params": params}, ids, pos, None, labels))
        nodrop_loss = jnp.mean(model_nodrop.apply(
            {"params": params}, ids, pos, None, labels))
        gleaf = grads["embedding"]["position_embeddings"]
        return loss, eval_loss, nodrop_loss, gleaf

    f = smap(run, mesh, (P(), P(), P(), P()), (P(), P(), P(), P()))
    loss_a, eval_loss, nodrop_loss, g = f(ids, pos, labels,
                                          jnp.int32(0))
    loss_b, _, _, _ = f(ids, pos, labels, jnp.int32(1))
    assert np.isfinite(float(loss_a)) and np.isfinite(float(loss_b))
    assert float(loss_a) != float(loss_b), "dropout ignored the rng key"
    assert np.all(np.isfinite(np.asarray(g)))
    # deterministic(default) path == a dropout-free config, bitwise
    np.testing.assert_array_equal(np.asarray(eval_loss),
                                  np.asarray(nodrop_loss))


def test_gpt_logits_shape_and_loss_positive():
    """Trace-only (eval_shape): the gather path's output shape is a
    compile-free property; executing it costs a minute of XLA compile."""
    mesh = tp_mesh(2)
    b, s = 2, 8
    ids = jnp.zeros((b, s), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    model = GPTModel(CFG, parallel_output=False)

    def run(ids, pos):
        params = model.init(jax.random.PRNGKey(0), ids, pos, None)["params"]
        return model.apply({"params": params}, ids, pos, None)

    out = jax.eval_shape(smap(run, mesh, (P(), P()), P()), ids, pos)
    assert out.shape == (b, s, CFG.vocab_size)


# ------------------------------ BERT ---------------------------------------

@pytest.mark.slow
def test_bert_forward_backward():
    mesh = tp_mesh(4)
    rs = np.random.RandomState(4)
    b, s = 2, 16
    ids = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    attn_mask = jnp.ones((b, s), jnp.int32)
    labels = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    model = BertModel(CFG)

    def run(ids, attn_mask, labels):
        def loss_fn(params):
            lm_loss, binary = model.apply({"params": params}, ids, attn_mask,
                                          lm_labels=labels)
            return jnp.mean(lm_loss) + 0.0 * jnp.sum(binary)

        params = model.init(jax.random.PRNGKey(0), ids, attn_mask)["params"]
        loss, grads = jax.value_and_grad(loss_fn)(params)
        leaves = jax.tree_util.tree_leaves(grads)
        finite = jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]).all()
        return loss, finite

    loss, finite = smap(run, mesh, (P(), P(), P()), (P(), P()))(
        ids, attn_mask, labels)
    assert np.isfinite(np.asarray(loss))
    assert bool(finite)


@pytest.mark.slow
@pytest.mark.parametrize("granularity", ["full", "selective"])
def test_recompute_granularity_grads_match(granularity):
    """Recompute must not change values: grads with full/selective
    recompute equal the no-recompute grads."""
    rs = np.random.RandomState(7)
    b, s = 2, 16
    ids = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, CFG.vocab_size, (b, s)))
    mesh = tp_mesh(1)

    def grads_for(cfg):
        model = GPTModel(cfg)

        def run(ids, pos, labels):
            params = model.init(jax.random.PRNGKey(0), ids, pos,
                                None)["params"]
            def loss_fn(p):
                return jnp.mean(model.apply({"params": p}, ids, pos, None,
                                            labels))
            loss, g = jax.value_and_grad(loss_fn)(params)
            return loss, g["embedding"]["position_embeddings"]

        return smap(run, mesh, (P(), P(), P()), (P(), P()))(ids, pos, labels)

    import dataclasses
    l0, g0 = grads_for(CFG)
    l1, g1 = grads_for(dataclasses.replace(CFG,
                                           recompute_granularity=granularity))
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-5,
                               atol=1e-7)


def _sub_jaxprs(val):
    if hasattr(val, "eqns"):          # raw Jaxpr (e.g. shard_map)
        yield val
    elif hasattr(val, "jaxpr"):       # ClosedJaxpr (e.g. pjit)
        yield val.jaxpr
    elif isinstance(val, (list, tuple)):
        for x in val:
            yield from _sub_jaxprs(x)


def _has_ss_aval(jaxpr, size):
    """Any aval of rank >= 3 whose last two dims are (size, size) — the
    materialized-attention-scores signature — anywhere in the jaxpr."""
    for eqn in jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            shp = getattr(getattr(v, "aval", None), "shape", ())
            if len(shp) >= 3 and shp[-1] == size and shp[-2] == size:
                return True
        for val in eqn.params.values():
            for inner in _sub_jaxprs(val):
                if _has_ss_aval(inner, size):
                    return True
    return False


def _attn_dropout_cfgs(s):
    kw = dict(hidden_size=32, num_layers=1, num_attention_heads=2,
              vocab_size=64, max_position_embeddings=s,
              hidden_dropout=0.0, attention_dropout=0.3)
    return (TransformerConfig(fused_attention_dropout=True, **kw),
            TransformerConfig(fused_attention_dropout=False, **kw))


def test_gpt_attention_dropout_routes_fused_no_ss_materialization():
    """Training with attention_dropout > 0 at lane-aligned shapes routes
    through the rows kernel's in-kernel dropout: no [.., s, s] scores
    tensor exists anywhere in the TRAINING jaxpr (with the knob off, it
    does). Pure tracing — no execution (the execution/grad smoke is the
    slow-tier companion below; kernel-level dropout parity lives in
    test_attention_pallas.py)."""
    b, s = 2, 128
    cfg_fused, cfg_dense = _attn_dropout_cfgs(s)
    mesh = tp_mesh(2)
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, 64, (b, s)))

    ss = {}
    for name, cfg in (("fused", cfg_fused), ("dense", cfg_dense)):
        model = GPTModel(cfg)

        # abstract params via eval_shape: the structural check needs no
        # real init (init/eval run the deterministic flash path, whose
        # CPU dense fallback would contaminate the scan)
        def init_fn(ids, pos, model=model):
            return model.init(jax.random.PRNGKey(0), ids, pos,
                              None)["params"]

        def train_loss(params, ids, pos, labels, model=model):
            per_tok = model.apply(
                {"params": params}, ids, pos, None, labels,
                deterministic=False,
                rngs={"dropout": jax.random.PRNGKey(3)})
            return jnp.mean(per_tok)

        params_shape = jax.eval_shape(
            smap(init_fn, mesh, (P(), P()), P()), ids, pos)
        ft = smap(train_loss, mesh, (P(), P(), P(), P()), P())
        jaxpr = jax.make_jaxpr(ft)(params_shape, ids, pos, labels)
        ss[name] = _has_ss_aval(jaxpr.jaxpr, s)

    assert not ss["fused"], \
        "fused dropout path still materializes an [.., s, s] tensor"
    assert ss["dense"], "structural check lost its teeth"


@pytest.mark.slow  # interpret-mode rows kernel fwd + grad on CPU
def test_gpt_attention_dropout_fused_path_trains():
    """Execution smoke of the fused attention-dropout route: finite
    training loss and grads through the in-kernel-dropout custom vjp."""
    b, s = 2, 128
    cfg_fused, _ = _attn_dropout_cfgs(s)
    mesh = tp_mesh(2)
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, 64, (b, s)))
    model = GPTModel(cfg_fused)

    def loss_and_grads(ids, pos, labels):
        params = model.init(jax.random.PRNGKey(0), ids, pos,
                            None)["params"]

        def loss(p):
            per_tok = model.apply(
                {"params": p}, ids, pos, None, labels,
                deterministic=False,
                rngs={"dropout": jax.random.PRNGKey(3)})
            return jnp.mean(per_tok)

        l, g = jax.value_and_grad(loss)(params)
        return l, g

    loss, grads = smap(loss_and_grads, mesh, (P(), P(), P()),
                       (P(), P()))(ids, pos, labels)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()


def test_bert_attention_dropout_routes_fused_no_ss_materialization():
    """BERT's padding-mask training-with-dropout routes through the rows
    kernel with the [b, s] validity expressed as segment ids: no
    [.., s, s] tensor in the training jaxpr (knob off: present)."""
    b, s = 2, 128
    kw = dict(hidden_size=32, num_layers=1, num_attention_heads=2,
              vocab_size=64, max_position_embeddings=s,
              hidden_dropout=0.0, attention_dropout=0.3,
              bert_binary_head=False)
    mesh = tp_mesh(2)
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    mask = jnp.ones((b, s), jnp.int32).at[:, 100:].set(0)  # tail pads
    labels = jnp.asarray(rs.randint(0, 64, (b, s)))

    ss = {}
    for name, fused in (("fused", True), ("dense", False)):
        model = BertModel(TransformerConfig(
            fused_attention_dropout=fused, **kw))

        def train_loss(params, ids, mask, labels, model=model):
            per_tok, _ = model.apply(
                {"params": params}, ids, mask, lm_labels=labels,
                deterministic=False,
                rngs={"dropout": jax.random.PRNGKey(3)})
            return jnp.mean(per_tok)

        def init_fn(ids, mask, model=model):
            return model.init(jax.random.PRNGKey(0), ids, mask)["params"]

        params_shape = jax.eval_shape(
            smap(init_fn, mesh, (P(), P()), P()), ids, mask)
        ft = smap(train_loss, mesh, (P(), P(), P(), P()), P())
        jaxpr = jax.make_jaxpr(ft)(params_shape, ids, mask, labels)
        ss[name] = _has_ss_aval(jaxpr.jaxpr, s)

    assert not ss["fused"], \
        "BERT fused dropout path still materializes an [.., s, s] tensor"
    assert ss["dense"], "structural check lost its teeth"


@pytest.mark.slow  # interpret-mode rows kernel fwd on CPU
def test_bert_fused_dropout_valid_rows_isolated_from_pads():
    """Under the segment-id formulation, valid-position losses are exactly
    invariant to pad-token CONTENT (valid queries never see pad keys);
    pad-position outputs are loss-masked garbage by contract."""
    b, s, n_pad = 2, 128, 28
    model = BertModel(TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=2, vocab_size=64,
        max_position_embeddings=s, hidden_dropout=0.0,
        attention_dropout=0.3, bert_binary_head=False,
        fused_attention_dropout=True))
    mesh = tp_mesh(2)
    rs = np.random.RandomState(8)
    ids = np.asarray(rs.randint(0, 64, (b, s)), np.int32)
    mask = jnp.ones((b, s), jnp.int32).at[:, s - n_pad:].set(0)
    labels = jnp.asarray(rs.randint(0, 64, (b, s)))

    def per_tok_loss(ids, mask, labels):
        params = model.init(jax.random.PRNGKey(0), ids, mask)["params"]
        per_tok, _ = model.apply(
            {"params": params}, ids, mask, lm_labels=labels,
            deterministic=False, rngs={"dropout": jax.random.PRNGKey(5)})
        return per_tok

    f = smap(per_tok_loss, mesh, (P(), P(), P()), P())
    base = np.asarray(f(jnp.asarray(ids), mask, labels))
    ids2 = ids.copy()
    ids2[:, s - n_pad:] = rs.randint(0, 64, (b, n_pad))  # scramble pads
    pert = np.asarray(f(jnp.asarray(ids2), mask, labels))
    # NOTE: init params depend only on shapes, identical across calls
    np.testing.assert_array_equal(base[:, :s - n_pad],
                                  pert[:, :s - n_pad])
    assert np.isfinite(base).all() and np.isfinite(pert).all()


def test_attention_dropout_seed_differs_across_tp_ranks():
    """The dropout-hash seed folds in the TP rank: without it, TP head
    shards would regenerate bit-identical masks for corresponding local
    heads (the flax dropout rng is replicated across the mesh)."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        derive_attention_dropout_seed,
    )

    mesh = tp_mesh(4)
    key = jax.random.PRNGKey(11)
    seeds = smap(
        lambda: derive_attention_dropout_seed(key, "tp").reshape(1),
        mesh, (), P("tp"))()
    seeds = np.asarray(seeds)
    assert len(set(seeds.tolist())) == 4, seeds
