"""Serving SLO observability (apex_tpu.serving.lifecycle, ISSUE 11):
lifecycle event-order invariants under admit/evict churn, gauge
high-waters, seeded Poisson/diurnal trace determinism, disabled-mode
no-op (behavior-identical serving + one-compile contract), the slo
ledger block's arithmetic + validation teeth, and check-9 units in
both directions."""

import json
import os
import warnings

import numpy as np
import pytest

from apex_tpu.serving import (
    ContinuousBatchingScheduler,
    PageAllocator,
    Request,
    ServingEngine,
    lifecycle,
    offered_load,
    resolve_policy,
    synthetic_trace,
)
from apex_tpu.telemetry import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


TRACE_KW = dict(seed=5, n_requests=6, vocab=128, prompt_lo=2,
                prompt_hi=8, new_lo=2, new_hi=8, mean_interarrival=0.5)


@pytest.fixture(scope="module")
def churn_run():
    """ONE lifecycle-enabled engine run over a Poisson trace with more
    requests than slots (admit/evict churn + queueing) — shared by the
    event/gauge/latency tests so the module pays one compile set."""
    import time

    cfg = _cfg()
    lifecycle.enable()
    try:
        eng = ServingEngine(cfg, num_slots=2, page_size=8, num_pages=24,
                            max_seq=64, prefill_len=32)
    finally:
        lifecycle.reset_enabled()
    reqs, trace_id = synthetic_trace(**TRACE_KW)
    t0 = time.perf_counter()
    done = eng.run_trace(reqs)
    eng.step()  # final evict round -> the last 'evicted' events land
    wall = time.perf_counter() - t0
    return eng, done, wall, reqs, trace_id


def test_event_order_invariants_under_churn(churn_run):
    eng, done, _, reqs, _ = churn_run
    log = eng.events
    assert log is not None
    assert log.validate_order() == []
    # every completed request walked the full happy-path chain (the
    # resilience events of ISSUE 15 only appear under their knobs)
    for r in done:
        got = [e["event"] for e in log.request_events(r.rid)]
        assert got == list(lifecycle.CORE_EVENTS), (r.rid, got)
    # churn actually happened: with 2 slots and 6 requests somebody
    # queued, and every request still completed (no starvation)
    assert len(done) == len(reqs)
    assert eng.decode_cache_size() == 1
    eng.allocator.check_invariants()


def test_wall_seam_is_seconds_not_ticks(churn_run):
    """The admit/evict wall seam: every stamp is a host-clock float
    and the per-request stamps are monotone — replay latencies are
    seconds, not tick counts."""
    _, done, wall, _, _ = churn_run
    for r in done:
        for f in (r.enqueue_wall, r.admitted_wall, r.first_token_wall,
                  r.finish_wall):
            assert isinstance(f, float), (r.rid, f)
        assert r.enqueue_wall <= r.admitted_wall \
            <= r.first_token_wall <= r.finish_wall
        # a replayed request's life is bounded by the run wall — a
        # tick count (integers 0..n) would not be
        assert r.finish_wall - r.enqueue_wall <= wall + 1e-6


def test_gauges_and_summary(churn_run):
    eng, _, _, _, _ = churn_run
    log = eng.events
    assert log.gauges, "no gauge samples collected"
    s = log.summary()
    assert s["samples"] == len(log.gauges)
    # 6 requests over 2 slots: the queue was non-empty at some round
    assert s["max_queue_depth"] >= 1
    assert s["max_hol_wait_ms"] > 0
    assert 0 < s["kv_page_high_water"] <= eng.allocator.num_pages - 1
    assert 0 < s["max_slots_active"] <= eng.num_slots
    # per-sample invariants: live pages never exceed capacity, slots
    # never exceed the engine's
    for g in log.gauges:
        assert 0 <= g["serve_kv_pages_live"] < g["serve_kv_pages_total"]
        assert 0 <= g["serve_slots_active"] <= g["serve_num_slots"]


def test_gauge_rows_sink_through_strict_writer(churn_run, tmp_path):
    """The gauge names are REGISTERED metric specs: a strict
    MetricsWriter (which refuses unregistered names) sinks
    gauge_rows() as-is."""
    from apex_tpu.telemetry import metrics

    eng, _, _, _, _ = churn_run
    w = metrics.MetricsWriter(path=str(tmp_path / "gauges.jsonl"),
                              strict=True)
    rows = eng.events.gauge_rows(run="lg-test")
    for row in rows:
        w.append(row)
    back = metrics.read_metrics(str(tmp_path / "gauges.jsonl"))
    assert len(back) == len(rows) and back[0]["run"] == "lg-test"


def test_disabled_mode_is_behavior_identical(churn_run):
    """With lifecycle collection OFF: no log exists, the decode
    program still compiles exactly once, and the generated tokens are
    IDENTICAL to the enabled run's — observability never perturbs
    serving."""
    eng, done, _, _, _ = churn_run
    lifecycle.disable()
    try:
        eng2 = ServingEngine(_cfg(), params=eng.params, num_slots=2,
                             page_size=8, num_pages=24, max_seq=64,
                             prefill_len=32)
        assert eng2.events is None
        reqs2, _ = synthetic_trace(**TRACE_KW)
        done2 = eng2.run_trace(reqs2)
    finally:
        lifecycle.reset_enabled()
    assert eng2.decode_cache_size() == 1
    by_rid = {r.rid: r.out_tokens for r in done}
    assert {r.rid: r.out_tokens for r in done2} == by_rid


def test_enabled_gate_env_and_override(monkeypatch):
    monkeypatch.delenv("APEX_SERVE_EVENTS", raising=False)
    lifecycle.reset_enabled()
    assert not lifecycle.enabled()
    monkeypatch.setenv("APEX_SERVE_EVENTS", "1")
    assert lifecycle.enabled()
    lifecycle.disable()
    try:
        assert not lifecycle.enabled()  # override beats env
    finally:
        lifecycle.reset_enabled()
    assert lifecycle.enabled()


def test_event_log_vocabulary_and_order_detection():
    log = lifecycle.EventLog()
    with pytest.raises(ValueError, match="unknown lifecycle event"):
        log.record("teleported", 0)
    # out-of-order, duplicate, wrong first event, backwards wall —
    # each a named finding
    log.record("admitted", 1, tick=0, wall=1.0)
    log.record("submitted", 1, tick=0, wall=0.5)
    log.record("submitted", 1, tick=0, wall=0.4)
    probs = log.validate_order(1)
    assert any("not 'submitted'" in p for p in probs)
    assert any("out of order" in p for p in probs)
    assert any("duplicate" in p for p in probs)
    assert any("backwards" in p for p in probs)
    assert log.validate_order(99) == ["rid 99: no events"]


# ------------------------------------------------------- load harness


def test_poisson_trace_seeded_determinism():
    r1, t1 = synthetic_trace(**TRACE_KW)
    r2, t2 = synthetic_trace(**TRACE_KW)
    assert t1 == t2
    assert [(r.arrival, r.prompt, r.max_new_tokens) for r in r1] \
        == [(r.arrival, r.prompt, r.max_new_tokens) for r in r2]
    _, t3 = synthetic_trace(**dict(TRACE_KW, seed=6))
    assert t3 != t1


def test_diurnal_trace_deterministic_and_distinct():
    kw = dict(TRACE_KW, arrival="diurnal")
    r1, t1 = synthetic_trace(**kw)
    r2, t2 = synthetic_trace(**kw)
    assert t1 == t2
    _, tp = synthetic_trace(**TRACE_KW)
    assert t1 != tp, "diurnal drew the poisson stream"
    arr = [r.arrival for r in r1]
    assert arr == sorted(arr) and all(a >= 0 for a in arr)
    assert offered_load(r1) > 0


def test_diurnal_rate_actually_modulates():
    """Peak-phase arrivals are denser than trough-phase ones: folding
    arrivals onto the period, the up-swing half (sin > 0, boosted
    rate) must hold decisively more requests than the down-swing half
    — the analytic ratio at depth 0.9 is ~3.7x."""
    period = 100.0
    reqs, _ = synthetic_trace(seed=0, n_requests=300, prompt_lo=2,
                              prompt_hi=4, new_lo=2, new_hi=4,
                              mean_interarrival=1.0, arrival="diurnal",
                              diurnal_period=period, diurnal_depth=0.9)
    phase = np.asarray([r.arrival for r in reqs]) % period
    up = int(np.sum(phase < period / 2))
    down = len(reqs) - up
    assert up > 2 * max(down, 1), (up, down)


def test_arrival_and_policy_asymmetry(monkeypatch):
    """Per-call unknown arrival/policy RAISES; env preferences warn
    once and fall back (the CLAUDE.md knob asymmetry). ``priority``
    entered the vocabulary in ISSUE 13 — it now resolves both ways."""
    with pytest.raises(ValueError, match="unknown arrival"):
        synthetic_trace(arrival="bursty")
    with pytest.raises(ValueError, match="unknown scheduler policy"):
        resolve_policy("lifo")
    with pytest.raises(ValueError, match="unknown scheduler policy"):
        ContinuousBatchingScheduler(2, 4, 8, PageAllocator(16),
                                    policy="lifo")
    from apex_tpu.dispatch import tiles

    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SERVE_SCHED", "lifo")
    with pytest.warns(UserWarning, match="lifo"):
        assert resolve_policy() == "fifo"
    monkeypatch.setenv("APEX_SERVE_SCHED", "fifo")
    assert resolve_policy() == "fifo"
    monkeypatch.setenv("APEX_SERVE_SCHED", "priority")
    assert resolve_policy() == "priority"
    assert resolve_policy("fifo") == "fifo"  # per-call beats env
    monkeypatch.delenv("APEX_SERVE_SCHED")
    assert ContinuousBatchingScheduler(
        2, 4, 8, PageAllocator(16)).policy == "fifo"
    assert ContinuousBatchingScheduler(
        2, 4, 8, PageAllocator(16), policy="priority").policy \
        == "priority"


def test_env_ms_preference_semantics(monkeypatch):
    """env_ms delegates to tiles.env_float — the ONE warn-once
    preference home (shared _warned_env with env_choice)."""
    from apex_tpu.dispatch import tiles

    monkeypatch.delenv("APEX_SERVE_SLO_TTFT_MS", raising=False)
    assert lifecycle.env_ms("APEX_SERVE_SLO_TTFT_MS", 1000.0) == 1000.0
    monkeypatch.setenv("APEX_SERVE_SLO_TTFT_MS", "250.5")
    assert lifecycle.env_ms("APEX_SERVE_SLO_TTFT_MS", 1000.0) == 250.5
    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SERVE_SLO_TTFT_MS", "fast")
    with pytest.warns(UserWarning, match="fast"):
        assert lifecycle.env_ms("APEX_SERVE_SLO_TTFT_MS",
                                1000.0) == 1000.0
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # warn ONCE per (knob, value)
        assert lifecycle.env_ms("APEX_SERVE_SLO_TTFT_MS",
                                1000.0) == 1000.0
    monkeypatch.setenv("APEX_SERVE_SLO_TTFT_MS", "-3")
    tiles._warned_env.clear()
    with pytest.warns(UserWarning):
        assert lifecycle.env_ms("APEX_SERVE_SLO_TTFT_MS",
                                1000.0) == 1000.0


# ------------------------------------------------------- the slo block


def _req(rid, submit, first, finish, n_out):
    r = Request(rid=rid, prompt=[1, 2], max_new_tokens=n_out,
                out_tokens=[0] * n_out)
    r.enqueue_wall, r.first_token_wall, r.finish_wall = \
        submit, first, finish
    return r


def test_slo_block_arithmetic_exact():
    """Hand-built walls -> exact percentiles, attainment and goodput:
    req0 attains both, req1 misses TTFT, req2 misses TPOT, req3 is a
    1-token request judged on TTFT alone."""
    reqs = [
        _req(0, 0.0, 0.050, 0.950, 10),   # ttft 50ms, tpot 100ms
        _req(1, 0.0, 0.400, 0.490, 10),   # ttft 400ms (miss), tpot 10ms
        _req(2, 0.0, 0.010, 2.010, 11),   # ttft 10ms, tpot 200ms (miss)
        _req(3, 0.0, 0.020, 0.020, 1),    # ttft 20ms, no tpot
    ]
    blk = lifecycle.slo_block(reqs, wall_s=2.0, ttft_ms=100.0,
                              tpot_ms=150.0, arrival_process="poisson",
                              offered_load=2.0)
    assert blk["requests"] == 4
    assert blk["ttft_p50_ms"] == 50.0 and blk["ttft_p99_ms"] == 400.0
    assert blk["per_token_p50_ms"] == 100.0
    assert blk["per_token_p99_ms"] == 200.0
    # attaining: req0 (50ms/100ms ok) + req3 (ttft only) = 2/4
    assert blk["slo_attainment"] == 0.5
    # goodput counts THEIR tokens only: (10 + 1) / 2.0 s
    assert blk["goodput_tok_s"] == 5.5
    assert blk["arrival_process"] == "poisson"
    assert blk["offered_load"] == 2.0
    # no log attached: occupancy fields degrade to None, never vanish
    assert blk["max_queue_depth"] is None
    assert blk["kv_page_high_water"] is None
    # all schema fields present (degradation, not omission)
    for f in ledger_mod.SLO_FIELDS:
        assert f in blk, f


def test_slo_block_from_churn_run(churn_run):
    eng, done, wall, reqs, _ = churn_run
    blk = lifecycle.slo_block(done, wall, ttft_ms=10000.0,
                              tpot_ms=10000.0,
                              arrival_process="poisson",
                              offered_load=offered_load(reqs),
                              log=eng.events)
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 extra={"slo": blk})
    assert ledger_mod.validate_record(rec) == []
    assert blk["slo_attainment"] == 1.0  # thresholds are generous
    assert blk["goodput_tok_s"] > 0
    assert blk["max_queue_depth"] >= 1
    assert blk["kv_page_high_water"] > 0


def _good_slo():
    return {"ttft_p50_ms": 5.0, "ttft_p99_ms": 9.0,
            "per_token_p50_ms": 1.0, "per_token_p99_ms": 2.0,
            "goodput_tok_s": 100.0, "slo_attainment": 0.9,
            "slo_ttft_ms": 1000.0, "slo_tpot_ms": 100.0,
            "arrival_process": "poisson", "offered_load": 2.0,
            "max_queue_depth": 3, "kv_page_high_water": 10,
            # resilience economics (ISSUE 15): None = layer disabled
            "shed_rate": None, "preempt_rate": None,
            "degraded_rounds": None,
            # multi-token decode blocks (ISSUE 17): K=1 = single-step
            "decode_block_k": 1}


def test_slo_block_validation_teeth():
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 extra={"slo": _good_slo()})
    assert ledger_mod.validate_record(rec) == []
    cases = [
        ({"ttft_p50_ms": -1}, "ttft_p50_ms"),
        ({"goodput_tok_s": True}, "goodput_tok_s"),
        ({"slo_attainment": 1.5}, "slo_attainment"),
        ({"ttft_p50_ms": 10.0}, "exceeds"),            # p50 > p99
        ({"per_token_p50_ms": 3.0}, "exceeds"),
        ({"arrival_process": ""}, "arrival_process"),
        ({"max_queue_depth": 2.5}, "max_queue_depth"),
        ({"kv_page_high_water": -1}, "kv_page_high_water"),
        # ISSUE 17: K is a required POSITIVE int — a K=0 engine does
        # not exist and None is not a legal degradation here
        ({"decode_block_k": 0}, "decode_block_k"),
        ({"decode_block_k": None}, "decode_block_k"),
        ({"decode_block_k": 2.5}, "decode_block_k"),
    ]
    for mut, needle in cases:
        r = ledger_mod.make_record(
            "profile_serving", "cpu", 0.1, 2,
            extra={"slo": dict(_good_slo(), **mut)})
        probs = ledger_mod.validate_record(r)
        assert any(needle in p for p in probs), (mut, probs)
    # missing field = finding (degradation must be explicit None)
    bad = _good_slo()
    del bad["offered_load"]
    r = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                               extra={"slo": bad})
    assert any("offered_load" in p for p in ledger_mod.validate_record(r))
    # None values are legal degradation
    r = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"slo": dict(_good_slo(), per_token_p50_ms=None,
                           per_token_p99_ms=None, max_queue_depth=None)})
    assert ledger_mod.validate_record(r) == []


# ------------------------------------------------------------- check 9

SLO_PINS = {"APEX_SERVE_SLO_TTFT_MS": "1000", "APEX_SERVE_SLO_TPOT_MS":
            "100", "APEX_SERVE_ARRIVALS": "poisson",
            "APEX_SERVE_SCHED": "fifo"}


def _check9_env(tmp_path, knobs, slo=None):
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2, knobs=knobs,
        extra={"slo": slo or _good_slo()})
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"slo row cites ledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    return ["--perf", str(perf), "--ledger", str(ledger),
            "--table", str(table)]


def test_check9_unpinned_slo_row_fails(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check9_env(tmp_path, {}))
    assert out.returncode == 1
    for knob in SLO_PINS:
        assert knob in out.stdout, knob


def test_check9_pinned_slo_row_clean(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check9_env(tmp_path, dict(SLO_PINS)))
    assert out.returncode == 0, out.stdout


def test_check9_arrival_disagreement_fails(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check9_env(
        tmp_path, dict(SLO_PINS, APEX_SERVE_ARRIVALS="diurnal")))
    assert out.returncode == 1
    assert "different workloads" in out.stdout


def test_check9_threshold_disagreement_fails(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check9_env(
        tmp_path, dict(SLO_PINS, APEX_SERVE_SLO_TTFT_MS="500")))
    assert out.returncode == 1
    assert "threshold the label does not name" in out.stdout


# -------------------------------------------------------- ledger CLI


def test_check9_full_precision_threshold_pin_round_trips(tmp_path):
    """A threshold that needs more than 6 significant digits must
    still pin check-9-clean: the harness writes the pin with repr()
    (exact float round trip), where '%g' would truncate 1000.125 to
    '1000.12' and manufacture a drift finding against its own
    record."""
    from tests.conftest import run_check_bench_labels

    v = 1000.125
    slo = dict(_good_slo(), slo_ttft_ms=v)
    out = run_check_bench_labels(*_check9_env(
        tmp_path, dict(SLO_PINS, APEX_SERVE_SLO_TTFT_MS=repr(v)),
        slo=slo))
    assert out.returncode == 0, out.stdout
    out = run_check_bench_labels(*_check9_env(
        tmp_path, dict(SLO_PINS, APEX_SERVE_SLO_TTFT_MS=f"{v:g}"),
        slo=slo))
    assert out.returncode == 1  # the truncated pin IS drift


def test_ledger_cli_survives_malformed_serving_block(tmp_path, capsys):
    """slo dict + serving NON-dict (both schema findings): status must
    report the findings, not crash on the malformed serving block."""
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"slo": _good_slo(), "serving": ["oops"]})
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    rc = ledger_mod.main(["--ledger", str(path), "status"])
    out = capsys.readouterr().out
    assert rc == 1 and "schema findings: 1" in out
    assert "[?]" in out  # the slo summary line still prints


def test_percentile_nearest_rank_all_q():
    vals = list(range(1, 11))  # 1..10
    assert lifecycle.percentile([], 50) is None
    assert lifecycle.percentile(vals, 50) == 6      # vals[10 // 2]
    assert lifecycle.percentile(vals, 99) == 10
    assert lifecycle.percentile(vals, 10) == 2      # NOT the median
    assert lifecycle.percentile([7.0], 50) == 7.0
    assert lifecycle.percentile([7.0], 99) == 7.0


def test_check9_malformed_pin_value_is_finding_not_crash(tmp_path):
    """A corrupt knob value (JSON list) in a cited slo row is a DRIFT
    finding, never a checker crash — the tool whose job is reporting
    label problems must survive exactly this input."""
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check9_env(
        tmp_path, dict(SLO_PINS, APEX_SERVE_SLO_TTFT_MS=[1000])))
    assert out.returncode == 1
    assert "is not a number" in out.stdout
    assert "checker error" not in out.stdout


def test_ledger_cli_survives_malformed_attainment(tmp_path, capsys):
    """A record whose slo_attainment is malformed (a validator
    finding) must still be summarizable by status/tail — the surface
    that reports the finding cannot crash on it."""
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"slo": dict(_good_slo(), slo_attainment="0.9")})
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    rc = ledger_mod.main(["--ledger", str(path), "status"])
    out = capsys.readouterr().out
    assert rc == 1  # the schema finding IS reported
    assert "schema findings: 1" in out and "attainment=?" in out
    assert ledger_mod.main(["--ledger", str(path), "tail", "1"]) == 0
    assert "slo" in capsys.readouterr().out


def test_ledger_cli_status_summarizes_slo_rows(tmp_path, capsys):
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2, knobs=dict(SLO_PINS),
        extra={"slo": _good_slo(),
               "serving": {"tokens_per_s": 50.0, "p50_ms": 1.0,
                           "p99_ms": 2.0, "trace_id": "tr-0123456789",
                           "kv_pages": 24}})
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    rc = ledger_mod.main(["--ledger", str(path), "status"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serving: 1 row(s), 1 with slo block" in out
    assert "attainment=90%" in out and "tr-0123456789" in out
    rc = ledger_mod.main(["--ledger", str(path), "tail", "1"])
    out = capsys.readouterr().out
    assert rc == 0 and "slo=90%" in out
