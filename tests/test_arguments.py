"""Megatron argument-bundle tests (reference: the consistency checks in
apex/transformer/testing/arguments.py:60-318 exercised via its CLI surface,
plus global_vars singleton discipline) and the config-driven pretrain entry
(BASELINE configs 3 and 4 shapes, shrunk)."""

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.transformer.testing import (
    ArgsError,
    MegatronArgs,
    bert_large_lamb_args,
    gpt_345m_args,
    parse_args,
)
from apex_tpu.transformer.testing import global_vars


BASE = ["--num-layers", "4", "--hidden-size", "64",
        "--num-attention-heads", "4", "--max-position-embeddings", "64",
        "--seq-length", "64", "--micro-batch-size", "2"]


def test_parse_args_derivations():
    a = parse_args(BASE + ["--bf16", "--tensor-model-parallel-size", "2"],
                   world_size=8)
    assert a.data_parallel_size == 4
    assert a.global_batch_size == 8  # mbs * dp
    assert a.ffn_hidden_size == 256  # 4*h default
    assert a.kv_channels == 16  # h / heads
    assert a.params_dtype == jnp.bfloat16
    # bf16 forces fp32 grad accumulation (reference :174-180)
    assert a.accumulate_allreduce_grads_in_fp32


def test_parse_args_tp_clamped_to_world():
    a = parse_args(BASE + ["--tensor-model-parallel-size", "16"],
                   world_size=4)
    assert a.tensor_model_parallel_size == 4


@pytest.mark.parametrize("argv,msg", [
    (BASE + ["--fp16", "--bf16"], "mutually exclusive"),
    (BASE + ["--train-iters", "10", "--train-samples", "10"], "not both"),
    (BASE + ["--train-iters", "10", "--lr-warmup-samples", "5"],
     "lr_warmup_iters"),
    (BASE + ["--lr", "1e-4", "--min-lr", "1e-2"], "min_lr"),
    (BASE + ["--save", "/tmp/x"], "save_interval"),
    (BASE + ["--fp16-lm-cross-entropy"], "fp16"),
    (BASE + ["--recompute-granularity", "selective",
             "--recompute-method", "uniform"], "selective"),
])
def test_parse_args_cross_validation_errors(argv, msg):
    with pytest.raises(ArgsError, match=msg):
        parse_args(argv)


def test_parse_args_seq_length_vs_positions():
    with pytest.raises(ArgsError, match="max_position_embeddings"):
        parse_args(["--num-layers", "2", "--hidden-size", "64",
                    "--num-attention-heads", "4",
                    "--max-position-embeddings", "32",
                    "--seq-length", "64", "--micro-batch-size", "1"])


def test_deprecated_flags_error():
    with pytest.raises(ArgsError, match="micro-batch-size"):
        parse_args(BASE + ["--batch-size", "4"])
    with pytest.raises(ArgsError, match="tensor-model-parallel-size"):
        parse_args(BASE + ["--model-parallel-size", "2"])


def test_sequence_parallel_disables_async_tp_allreduce():
    a = parse_args(BASE + ["--sequence-parallel"], world_size=2)
    assert not a.async_tensor_model_parallel_allreduce


def test_weight_decay_incr_style():
    a = parse_args(BASE + ["--weight-decay", "0.02"])
    assert a.start_weight_decay == a.end_weight_decay == 0.02
    with pytest.raises(ArgsError, match="start_weight_decay"):
        parse_args(BASE + ["--weight-decay-incr-style", "linear"])


def test_virtual_pipeline_validation():
    with pytest.raises(ArgsError, match="pp > 2"):
        parse_args(BASE + ["--num-layers-per-virtual-pipeline-stage", "1",
                           "--pipeline-model-parallel-size", "2"],
                   world_size=8)
    a = parse_args(BASE + ["--num-layers-per-virtual-pipeline-stage", "1",
                           "--pipeline-model-parallel-size", "4"],
                   world_size=8)
    assert a.virtual_pipeline_model_parallel_size == 1


def test_pad_vocab_size():
    a = gpt_345m_args(world_size=2, tensor_model_parallel_size=2)
    assert a.pad_vocab_size(50257) % (128 * 2) == 0


def test_canonical_baseline_configs():
    b = bert_large_lamb_args(world_size=8)
    assert (b.num_layers, b.hidden_size, b.num_attention_heads) == (24, 1024, 16)
    assert b.optimizer == "lamb" and b.bf16
    g = gpt_345m_args(world_size=8, tensor_model_parallel_size=2)
    assert (g.num_layers, g.hidden_size) == (24, 1024)
    assert g.data_parallel_size == 4
    cfg = g.to_transformer_config()
    assert cfg.hidden_size == 1024 and cfg.bf16


def test_global_vars_singletons():
    global_vars.destroy_global_vars()
    args = global_vars.set_global_variables(
        BASE + ["--rampup-batch-size", "2", "2", "8"], world_size=1)
    assert global_vars.get_args() is args
    # rampup: starts at 2 → 1 microbatch of mbs 2
    assert global_vars.get_current_global_batch_size() == 2
    global_vars.update_num_microbatches(8, consistency_check=False)
    assert global_vars.get_current_global_batch_size() >= 2
    t = global_vars.get_timers()
    t("x").start()
    assert t("x").elapsed() >= 0.0
    with pytest.raises(RuntimeError, match="already initialized"):
        global_vars.set_global_variables(BASE, world_size=1)
    global_vars.destroy_global_vars()
    with pytest.raises(RuntimeError, match="not initialized"):
        global_vars.get_args()


@pytest.mark.parametrize("model,opt", [
    pytest.param("gpt", "adam", marks=pytest.mark.slow),
    pytest.param("bert", "lamb", marks=pytest.mark.slow),
])
def test_pretrain_entry_tiny(model, opt):
    """Config-driven pretrain runs both model families (BASELINE configs
    3 and 4, shrunk to CPU-mesh size) with decreasing-or-finite loss."""
    global_vars.destroy_global_vars()
    from examples.transformer.pretrain import main

    out = main(["--model", model, "--num-layers", "2", "--hidden-size", "64",
                "--num-attention-heads", "4",
                "--max-position-embeddings", "64", "--seq-length", "32",
                "--micro-batch-size", "2", "--vocab-size", "256",
                "--make-vocab-size-divisible-by", "32",
                "--tensor-model-parallel-size", "2",
                "--optimizer", opt, "--lr", "1e-3", "--bf16",
                "--train-iters", "4", "--log-interval", "2"])
    assert np.isfinite(out["loss"])


def test_lr_schedule_warmup_and_decay():
    """make_lr_schedule: the Megatron lr group semantics — linear warmup,
    then constant/linear/cosine decay to min_lr over lr_decay_iters."""
    import jax.numpy as jnp

    from examples.transformer.pretrain import make_lr_schedule

    a = parse_args(BASE + ["--lr", "1.0", "--min-lr", "0.1",
                           "--train-iters", "100",
                           "--lr-warmup-iters", "10",
                           "--lr-decay-style", "cosine"])
    s = make_lr_schedule(a)
    assert float(s(jnp.int32(5))) == pytest.approx(0.5)      # warmup
    assert float(s(jnp.int32(10))) == pytest.approx(1.0)     # peak
    assert float(s(jnp.int32(55))) == pytest.approx(0.55, abs=1e-6)  # mid
    assert float(s(jnp.int32(100))) == pytest.approx(0.1)    # floor
    assert float(s(jnp.int32(500))) == pytest.approx(0.1)    # clamped

    lin = make_lr_schedule(parse_args(
        BASE + ["--lr", "1.0", "--train-iters", "100",
                "--lr-decay-style", "linear"]))
    assert float(lin(jnp.int32(50))) == pytest.approx(0.5)
    const = make_lr_schedule(parse_args(
        BASE + ["--lr", "1.0", "--train-iters", "100",
                "--lr-decay-style", "constant"]))
    assert float(const(jnp.int32(99))) == pytest.approx(1.0)


@pytest.mark.slow
def test_pretrain_fp16_dynamic_scaling():
    """--fp16 trains with true float16 params + dynamic loss scaling (the
    reference's mixed-precision group); loss stays finite."""
    global_vars.destroy_global_vars()
    from examples.transformer.pretrain import main

    out = main(["--model", "gpt", "--num-layers", "2", "--hidden-size",
                "64", "--num-attention-heads", "4",
                "--max-position-embeddings", "64", "--seq-length", "32",
                "--micro-batch-size", "2", "--vocab-size", "256",
                "--make-vocab-size-divisible-by", "32",
                "--optimizer", "adam", "--lr", "1e-3", "--fp16",
                "--train-iters", "4", "--log-interval", "2"])
    assert np.isfinite(out["loss"])
    global_vars.destroy_global_vars()


@pytest.mark.slow
def test_pretrain_save_load_resume(tmp_path):
    """--save / --save-interval / --load drive the sharded checkpoint
    manager (reference checkpointing args :646-669): a killed run resumes
    from the latest step and only trains the remaining iters, and
    --finetune loads weights but resets the iteration count."""
    global_vars.destroy_global_vars()
    from examples.transformer.pretrain import main

    base = ["--model", "gpt", "--num-layers", "2", "--hidden-size", "64",
            "--num-attention-heads", "4", "--max-position-embeddings", "64",
            "--seq-length", "32", "--micro-batch-size", "2",
            "--vocab-size", "256", "--make-vocab-size-divisible-by", "32",
            "--optimizer", "adam", "--lr", "1e-3", "--bf16",
            "--log-interval", "2"]
    d = str(tmp_path / "run")

    out1 = main(base + ["--train-iters", "4", "--save", d,
                        "--save-interval", "2"])
    assert np.isfinite(out1["loss"])
    from apex_tpu import checkpoint as ckpt_mod
    with ckpt_mod.CheckpointManager(d) as mgr:
        assert mgr.latest_step() == 4
        steps_saved = mgr.all_steps()
    assert 2 in steps_saved

    global_vars.destroy_global_vars()
    # resume: train-iters 6 continues from iter 4 (one more chunk)
    out2 = main(base + ["--train-iters", "6", "--load", d, "--save", d,
                        "--save-interval", "2"])
    assert np.isfinite(out2["loss"])
    global_vars.destroy_global_vars()
    with ckpt_mod.CheckpointManager(d) as mgr:
        assert mgr.latest_step() == 6

    # finetune: weights load, iteration resets -> trains 0..4 again
    out3 = main(base + ["--train-iters", "4", "--load", d, "--finetune",
                        "--no-load-optim"])
    assert np.isfinite(out3["loss"])
    global_vars.destroy_global_vars()

    # --no-save-optim writes params-only; a full load falls back to
    # params-only with a warning instead of crashing in orbax
    d2 = str(tmp_path / "slim")
    main(base + ["--train-iters", "2", "--save", d2, "--save-interval", "0",
                 "--no-save-optim"])
    global_vars.destroy_global_vars()
    out4 = main(base + ["--train-iters", "4", "--load", d2])
    assert np.isfinite(out4["loss"])
    global_vars.destroy_global_vars()


def test_recompute_granularity_flows_to_model_config():
    a = parse_args(BASE + ["--recompute-granularity", "full"])
    cfg = a.to_transformer_config()
    assert cfg.recompute_granularity == "full"


def test_num_experts_flows_to_model_config():
    a = parse_args(BASE + ["--num-experts", "4"])
    cfg = a.to_transformer_config()
    assert cfg.num_moe_experts == 4
