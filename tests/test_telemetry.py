"""apex_tpu.telemetry: metrics registry/sink round-trip, the zero-cost
rule (disabled telemetry leaves the jitted GPT training step's jaxpr
byte-identical), ledger schema + content-hash ids, and the shared
Tracer. All CPU-tier (the conftest 8-device CPU mesh), fast."""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import telemetry
from apex_tpu.telemetry import ledger, metrics
from apex_tpu.telemetry.tracing import Tracer


@pytest.fixture(autouse=True)
def _reset_telemetry():
    telemetry.reset_enabled()
    yield
    telemetry.reset_enabled()


# --------------------------------------------------------------------------
# metrics registry + sink


def test_registry_round_trip(tmp_path):
    spec = metrics.register("test_custom_metric", unit="ms",
                            description="round-trip fixture")
    assert metrics.spec("test_custom_metric") == spec
    # idempotent for the identical spec, ValueError on a conflicting one
    assert metrics.register("test_custom_metric", unit="ms",
                            description="round-trip fixture") == spec
    with pytest.raises(ValueError):
        metrics.register("test_custom_metric", unit="s")

    path = str(tmp_path / "metrics.jsonl")
    writer = metrics.MetricsWriter(path)
    n = writer.append_steps(
        {"loss": np.asarray([3.0, 2.5, 2.0]),
         "loss_scale": np.asarray([65536.0, 65536.0, 65536.0]),
         "test_custom_metric": np.float32(1.5)},  # scalar broadcasts
        run="lg-0000000000")
    assert n == 3
    writer.append({"run": "lg-0000000000", "tokens_per_sec": 123.4})
    rows = metrics.read_metrics(path)
    assert len(rows) == 4
    assert [r["loss"] for r in rows[:3]] == [3.0, 2.5, 2.0]
    assert all(r["test_custom_metric"] == 1.5 for r in rows[:3])
    assert all(r["run"] == "lg-0000000000" for r in rows)
    assert rows[3]["tokens_per_sec"] == 123.4


def test_writer_strict_mode(tmp_path):
    writer = metrics.MetricsWriter(str(tmp_path / "m.jsonl"), strict=True)
    with pytest.raises(KeyError):
        writer.append_steps({"never_registered_xyz": np.asarray([1.0])})
    # non-strict auto-registers instead of losing the data
    lax_writer = metrics.MetricsWriter(str(tmp_path / "m.jsonl"))
    assert lax_writer.append_steps({"auto_registered_xyz":
                                    np.asarray([1.0])}) == 1
    assert metrics.spec("auto_registered_xyz") is not None


def test_writer_length_handling(tmp_path):
    writer = metrics.MetricsWriter(str(tmp_path / "m.jsonl"))
    # shape-[1] arrays broadcast like scalars (a run-level value riding
    # alongside [K] step arrays)
    n = writer.append_steps({"loss": np.asarray([1.0, 2.0]),
                             "tokens_per_sec": np.asarray([9.0])})
    assert n == 2
    rows = metrics.read_metrics(str(tmp_path / "m.jsonl"))
    assert [r["tokens_per_sec"] for r in rows] == [9.0, 9.0]
    # genuinely mismatched [k] lengths fail up front, not mid-write
    with pytest.raises(ValueError, match="mismatched"):
        writer.append_steps({"a": np.asarray([1.0, 2.0]),
                             "b": np.asarray([1.0, 2.0, 3.0])})


def test_collect_gates_on_enabled():
    telemetry.disable()
    assert telemetry.collect(None, a=jnp.float32(1.0)) is None
    base = {"a": 1}
    assert telemetry.collect(base, b=2) is base  # untouched passthrough
    telemetry.enable()
    out = telemetry.collect(None, a=1.0)
    assert out == {"a": 1.0}
    out2 = telemetry.collect(out, b=2.0)
    assert out2 == {"a": 1.0, "b": 2.0} and out == {"a": 1.0}


def test_enabled_env_default(monkeypatch):
    telemetry.reset_enabled()
    monkeypatch.delenv("APEX_TELEMETRY", raising=False)
    assert not telemetry.enabled()
    monkeypatch.setenv("APEX_TELEMETRY", "1")
    assert telemetry.enabled()
    telemetry.disable()  # programmatic override beats the env
    assert not telemetry.enabled()


# --------------------------------------------------------------------------
# providers


def test_scaler_metrics_provider():
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler()
    state = scaler.init()
    m = scaler.metrics(state)
    assert set(m) == {"loss_scale", "overflow", "unskipped"}
    assert float(m["loss_scale"]) == 2.0 ** 16
    assert not bool(m["overflow"])


def test_grad_norm_stats_provider():
    from apex_tpu.optimizers import grad_norm_stats

    grads = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([[-12.0]])}
    stats = grad_norm_stats(grads)
    assert np.isclose(float(stats["grad_norm"]), 13.0)
    assert float(stats["grad_max"]) == 12.0


def test_stateful_optimizer_stashes_grad_stats():
    from apex_tpu.optimizers import FusedAdam

    params = [jnp.ones((4,)), jnp.ones((2, 2))]
    grads = [jnp.full((4,), 2.0), jnp.zeros((2, 2))]
    opt = FusedAdam(params, lr=1e-3)
    telemetry.disable()
    opt.step(grads)
    assert opt.last_grad_stats is None
    telemetry.enable()
    opt.step(grads)
    assert np.isclose(float(opt.last_grad_stats["grad_norm"]), 4.0)
    assert float(opt.last_grad_stats["grad_max"]) == 2.0


# --------------------------------------------------------------------------
# the zero-cost rule: disabled telemetry never perturbs the measured step


class _TinyLM:
    """Stand-in with GPTModel's apply signature: embed → logits → CE per
    token. bench.make_one_step's telemetry branch is model-independent,
    so byte-identity of the step jaxpr proven on this model IS the
    zero-cost property of the instrumented bench step; the GPTModel
    variant below re-proves it on the flagship model where the
    container's jax supports tracing it (the TPU host; this container's
    jax predates lax.axis_size — the seed's pre-existing skew)."""

    def apply(self, variables, ids, pos, mask, labels):
        p = variables["params"]
        h = p["emb"][ids] + p["posemb"][pos]
        logits = h.astype(jnp.float32) @ p["w"]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return lse - tgt


def _bench_fixture(vocab=64, hidden=16, b=2, s=16):
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers.fused_adam import fused_adam

    model = _TinyLM()
    scaler = LossScaler()
    tx = fused_adam(learning_rate=1e-4)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, vocab, (b, s)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    labels = jnp.asarray(rs.randint(0, vocab, (b, s)), jnp.int32)
    params = {
        "emb": jnp.asarray(rs.randn(vocab, hidden) * 0.1, jnp.bfloat16),
        "posemb": jnp.asarray(rs.randn(s, hidden) * 0.1, jnp.bfloat16),
        "w": jnp.asarray(rs.randn(hidden, vocab) * 0.1, jnp.float32),
    }
    return model, scaler, tx, params, tx.init(params), scaler.init(), \
        ids, pos, labels


def _reference_step_fn(model, scaler, tx):
    """Frozen copy of the pre-telemetry (HEAD) bench.py step body — the
    uninstrumented program every pinned measurement ran."""

    def reference_step(params, opt_state, scaler_state, ids, pos, labels):
        def loss_fn(p):
            per_tok = model.apply({"params": p}, ids, pos, None, labels)
            return jnp.mean(per_tok) * scaler_state.loss_scale

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, found_inf = scaler.unscale(grads, scaler_state)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: jnp.where(found_inf, p, p + u.astype(p.dtype)),
            params, updates)
        new_opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(found_inf, old, new),
            new_opt_state, opt_state)
        return (new_params, new_opt_state, new_scaler_state,
                loss / scaler_state.loss_scale)

    return reference_step


def test_disabled_telemetry_jaxpr_is_byte_identical():
    """The acceptance gate: with telemetry disabled, bench.py's
    instrumented training step traces to a jaxpr byte-identical to the
    uninstrumented (pre-telemetry HEAD) step — observability adds zero
    cost to pinned measurements."""
    import bench

    (model, scaler, tx, params, opt_state, scaler_state,
     ids, pos, labels) = _bench_fixture()
    reference_step = _reference_step_fn(model, scaler, tx)

    args = (params, opt_state, scaler_state, ids, pos, labels)
    telemetry.disable()
    one_step = bench.make_one_step(model, scaler, tx)
    got = str(jax.make_jaxpr(one_step)(*args))
    want = str(jax.make_jaxpr(reference_step)(*args))
    assert got == want, "disabled telemetry changed the step's jaxpr"

    # sanity that the instrumentation exists at all: enabled-mode aux
    # outputs (loss_scale/overflow/grad_norm/...) change the trace.
    # NB a FRESH closure: jax caches traces per function object, so
    # re-tracing the same one_step would return the disabled jaxpr.
    telemetry.enable()
    one_step = bench.make_one_step(model, scaler, tx)
    enabled_jaxpr = str(jax.make_jaxpr(one_step)(*args))
    assert enabled_jaxpr != want
    _, _, _, _, aux = one_step(*args)
    assert aux is not None and {"loss", "loss_scale", "overflow",
                                "grad_norm"} <= set(aux)


def test_disabled_telemetry_jaxpr_gpt_model():
    """The same byte-identity on the flagship GPTModel step bench.py
    actually measures. The model needs a bound tensor-parallel axis
    (shard_map) to trace; where this container's jax predates the APIs
    the model uses (the seed's pre-existing version skew), skip — the
    _TinyLM variant above still pins the mechanism."""
    import bench
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers.fused_adam import fused_adam

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:
        pytest.skip("jax.shard_map unavailable in this container "
                    "(pre-existing skew)")
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=32, num_layers=1, num_attention_heads=4,
        vocab_size=64, max_position_embeddings=16,
        hidden_dropout=0.0, attention_dropout=0.0, bf16=True)
    model = GPTModel(cfg)
    scaler = LossScaler()
    tx = fused_adam(learning_rate=1e-4)
    b, s = 2, 16
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))

    def shmap(f, n):
        return shard_map(f, mesh=mesh, in_specs=(P(),) * n, out_specs=P(),
                         check_vma=False)

    try:
        params = jax.jit(shmap(
            lambda i, p: model.init(jax.random.PRNGKey(0), i, p,
                                    None)["params"], 2))(ids, pos)
    except (AttributeError, TypeError) as e:
        pytest.skip(f"container jax cannot trace GPTModel: {e}")
    opt_state = tx.init(params)
    args = (params, opt_state, scaler.init(), ids, pos, labels)

    telemetry.disable()
    got = str(jax.make_jaxpr(
        shmap(bench.make_one_step(model, scaler, tx), 6))(*args))
    want = str(jax.make_jaxpr(
        shmap(_reference_step_fn(model, scaler, tx), 6))(*args))
    assert got == want, "disabled telemetry changed the GPT step's jaxpr"


def test_aux_stacks_through_scan_and_flushes(tmp_path):
    """The bench.py main() protocol minus the shard_map wrapper: the
    enabled step's aux scalars stack across the K-iteration training
    scan, fetch as [K] arrays, and flush to the metrics sink one row
    per step."""
    import bench
    from jax import lax

    (model, scaler, tx, params, opt_state, scaler_state,
     ids, pos, labels) = _bench_fixture()
    telemetry.enable()
    one_step = bench.make_one_step(model, scaler, tx)
    iters = 3

    def run(params, opt_state, scaler_state, eps, ids, pos, labels):
        def body(carry, _):
            p, o, ss = carry
            p, o, ss, loss, aux = one_step(p, o, ss, ids, pos, labels)
            return (p, o, ss), (loss, aux)

        (params, opt_state, scaler_state), (losses, aux) = lax.scan(
            body, (params, opt_state, scaler_state), jnp.arange(iters))
        return params, opt_state, scaler_state, losses + eps, aux

    out = jax.jit(run)(params, opt_state, scaler_state, jnp.float32(0.0),
                       ids, pos, labels)
    aux = out[4]
    assert {"loss", "loss_scale", "overflow", "grad_norm"} <= set(aux)
    assert all(np.asarray(v).shape == (iters,) for v in aux.values())
    np.testing.assert_allclose(np.asarray(aux["loss"]),
                               np.asarray(out[3]), rtol=1e-5)
    assert float(aux["grad_norm"][0]) > 0

    writer = metrics.MetricsWriter(str(tmp_path / "m.jsonl"))
    n = writer.append_steps({k: np.asarray(v) for k, v in aux.items()},
                            run="lg-0000000000")
    assert n == iters
    rows = metrics.read_metrics(str(tmp_path / "m.jsonl"))
    assert [r["step"] for r in rows] == [0, 1, 2]
    assert rows[0]["loss_scale"] == 2.0 ** 16

    # disabled: the same scan carries no aux at all (fresh closures —
    # jax caches traces per function object)
    telemetry.disable()
    one_step = bench.make_one_step(model, scaler, tx)

    def run_disabled(params, opt_state, scaler_state, eps, ids, pos,
                     labels):
        def body(carry, _):
            p, o, ss = carry
            p, o, ss, loss, aux = one_step(p, o, ss, ids, pos, labels)
            return (p, o, ss), (loss, aux)

        (params, opt_state, scaler_state), (losses, aux) = lax.scan(
            body, (params, opt_state, scaler_state), jnp.arange(iters))
        return params, opt_state, scaler_state, losses + eps, aux

    out = jax.jit(run_disabled)(params, opt_state, scaler_state,
                                jnp.float32(0.0), ids, pos, labels)
    assert out[4] is None


def test_disabled_aux_is_empty_pytree():
    """aux=None contributes no outputs: scan/jit treat the 5-tuple step
    exactly like the old 4-tuple one."""
    import bench

    (model, scaler, tx, params, opt_state, scaler_state,
     ids, pos, labels) = _bench_fixture()
    telemetry.disable()
    one_step = bench.make_one_step(model, scaler, tx)
    out = one_step(params, opt_state, scaler_state, ids, pos, labels)
    assert out[4] is None
    assert jax.tree_util.tree_leaves(out[4]) == []


# --------------------------------------------------------------------------
# ledger


def test_ledger_record_schema_and_content_id(tmp_path):
    rec = ledger.make_record(
        harness="unit", platform="cpu", dispatch_overhead_ms=1.5, k=8,
        relay={"degraded": False, "kind": None}, knobs={"APEX_X": "1"},
        git="deadbeef", ts=1234.0)
    assert ledger.validate_record(rec) == []
    assert rec["id"].startswith("lg-") and len(rec["id"]) == 13
    # content-hash id: edits after the fact are detectable
    tampered = dict(rec, dispatch_overhead_ms=68.0)
    assert any("does not match record content" in p
               for p in ledger.validate_record(tampered))

    path = str(tmp_path / "ledger.jsonl")
    rid = ledger.append_record(
        harness="unit", platform="cpu", dispatch_overhead_ms=1.5, k=8,
        path=path)
    records = ledger.read_ledger(path)
    assert [r["id"] for r in records] == [rid]
    assert ledger.validate_record(records[0]) == []
    # missing required fields are findings
    assert any("missing field" in p
               for p in ledger.validate_record({"id": "lg-0"}))


def test_ledger_knob_pins():
    pins = ledger.knob_pins({"APEX_ATTN_IMPL": "rows", "PATH": "/bin",
                             "APEX_BENCH_K": "128"})
    assert pins == {"APEX_ATTN_IMPL": "rows", "APEX_BENCH_K": "128"}


def test_ledger_smoke_skip(tmp_path, monkeypatch):
    # smoke-mode runs don't pollute the measurement ledger by default...
    monkeypatch.setenv("APEX_BENCH_SMOKE", "1")
    monkeypatch.delenv("APEX_TELEMETRY_LEDGER", raising=False)
    assert ledger.append_record("unit", "cpu", 1.0, 2) is None
    # ...but an explicit APEX_TELEMETRY_LEDGER is honored verbatim
    path = str(tmp_path / "l.jsonl")
    monkeypatch.setenv("APEX_TELEMETRY_LEDGER", path)
    rid = ledger.append_record("unit", "cpu", 1.0, 2)
    assert rid is not None and ledger.read_ledger(path)[0]["id"] == rid


def test_ledger_write_never_raises(monkeypatch):
    # a read-only checkout must not break the bench contract
    assert ledger.append_record(
        "unit", "cpu", 1.0, 2, path="/nonexistent-dir/l.jsonl") is None


def test_read_ledger_reports_corrupt_line(tmp_path):
    path = tmp_path / "l.jsonl"
    path.write_text('{"ok": 1}\nnot json\n')
    with pytest.raises(ValueError, match="2"):
        ledger.read_ledger(str(path))


# --------------------------------------------------------------------------
# tracer


def test_tracer_scan_time_and_ledger(tmp_path, monkeypatch):
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    tracer = Tracer(k=4, overhead=0.0, peak_flops=1e12)

    def make_body(eps, x):
        def body(carry, _):
            carry = carry + eps * jnp.sum(x)
            return carry, carry
        return body

    span = tracer.scan_time("unit-row", make_body, jnp.float32(0.0),
                            (jnp.ones((8,)),), flops_per_iter=16.0,
                            extra={"case": "unit"})
    assert span.seconds is not None and span.seconds > 0
    assert span.k == 4 and span.overhead_s == 0.0
    rec = span.as_record()
    assert rec["method"] == "scan-chain" and rec["case"] == "unit"
    assert "ms" in span.format_row(1e12)

    # wrap= is applied around the run function before jit
    wrapped = []
    tracer.scan_time("wrapped-row", make_body, jnp.float32(0.0),
                     (jnp.ones((4,)),),
                     wrap=lambda run: wrapped.append(run) or run)
    assert len(wrapped) == 1

    path = str(tmp_path / "ledger.jsonl")
    rid = tracer.flush_ledger("unit_harness", path=path)
    records = ledger.read_ledger(path)
    assert records[0]["id"] == rid
    assert records[0]["harness"] == "unit_harness"
    assert records[0]["platform"] == "cpu"
    assert [s["name"] for s in records[0]["spans"]] == ["unit-row",
                                                        "wrapped-row"]
    assert ledger.validate_record(records[0]) == []


def test_tracer_on_fail_span():
    tracer = Tracer(k=2, overhead=0.0)

    def boom(*args):
        raise RuntimeError("kernel does not lower")

    span = tracer.time_call("bad-row", boom, (1,), (2,), on_fail="span")
    assert span.seconds is None and "kernel does not lower" in span.error
    assert span.as_record()["error"]
    assert "FAILED" in span.format_row()
    with pytest.raises(RuntimeError):
        tracer.time_call("bad-row", boom, (1,), (2,))


def test_timing_reexports():
    # benchmarks/_timing.py stays the documented import surface
    from benchmarks import _timing

    assert _timing.Tracer is Tracer
    assert callable(_timing.sync)
    assert callable(_timing.measure_dispatch_overhead)
    assert _timing.bench_k(True) == 2


def test_bench_json_fields_in_fabricated_timeout_record():
    """The watchdog's fabricated timeout record carries the structured
    timed_out/relay_degraded stamps the lazy cap and the driver key on."""
    import bench
    import subprocess

    class FakeProc:
        returncode = None

        def communicate(self, timeout=None):
            if timeout is not None and not getattr(self, "_killed", False):
                raise subprocess.TimeoutExpired("bench", timeout)
            return "", None

        def terminate(self):
            self._killed = True

        def kill(self):
            self._killed = True

    state = {"child": None}
    orig = subprocess.Popen
    subprocess.Popen = lambda *a, **kw: FakeProc()
    os.environ["APEX_BENCH_TIMEOUT"] = "1"
    try:
        line, rec, rc = bench._attempt_once(state)
    finally:
        subprocess.Popen = orig
        del os.environ["APEX_BENCH_TIMEOUT"]
    assert rc is None
    assert rec["timed_out"] is True and rec["relay_degraded"] is True
    assert "error" in rec and json.loads(line) == rec
