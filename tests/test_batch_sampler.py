"""DP-sharded pretraining batch samplers (port of the reference's
tests/L0/run_transformer/test_batch_sampler.py coverage: sharding
disjointness, drop_last, consumed-samples resume, per-epoch shuffles)."""

import numpy as np

from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


def _all_rank_batches(cls, total, consumed, mbs, dp, **kw):
    return [list(cls(total_samples=total, consumed_samples=consumed,
                     micro_batch_size=mbs, data_parallel_rank=r,
                     data_parallel_size=dp, **kw))
            for r in range(dp)]


def test_sequential_sampler_shards_disjoint_and_complete():
    per_rank = _all_rank_batches(MegatronPretrainingSampler, 24, 0, 3, 4)
    # every rank: 2 micro-batches of 3
    assert all(len(b) == 2 and all(len(mb) == 3 for mb in b)
               for b in per_rank)
    flat = sorted(i for b in per_rank for mb in b for i in mb)
    assert flat == list(range(24))  # disjoint + complete


def test_sequential_sampler_drop_last_and_tail():
    tail = _all_rank_batches(MegatronPretrainingSampler, 26, 0, 3, 4)
    flat = sorted(i for b in tail for mb in b for i in mb)
    assert flat == list(range(24))  # 2 tail samples dropped
    keep = _all_rank_batches(MegatronPretrainingSampler, 26, 0, 3, 4,
                             drop_last=False)
    # the 2 tail samples surface as one final short global batch
    assert any(len(mb) < 3 for b in keep for mb in b)
    flat_keep = sorted(i for b in keep for mb in b for i in mb)
    assert set(range(24)) <= set(flat_keep)


def test_sequential_sampler_resume():
    full = list(MegatronPretrainingSampler(
        total_samples=24, consumed_samples=0, micro_batch_size=3,
        data_parallel_rank=1, data_parallel_size=4))
    resumed = list(MegatronPretrainingSampler(
        total_samples=24, consumed_samples=12, micro_batch_size=3,
        data_parallel_rank=1, data_parallel_size=4))
    assert resumed == full[1:]  # 12 consumed == one global batch skipped


def test_random_sampler_epoch_determinism_and_disjoint():
    per_rank = _all_rank_batches(
        MegatronPretrainingRandomSampler, 48, 0, 4, 2)
    again = _all_rank_batches(
        MegatronPretrainingRandomSampler, 48, 0, 4, 2)
    assert per_rank == again  # same epoch -> same permutation
    flat = sorted(i for b in per_rank for mb in b for i in mb)
    assert flat == list(range(48))  # rank buckets are disjoint + complete
    # next epoch (consumed == one full pass) shuffles differently
    nxt = _all_rank_batches(MegatronPretrainingRandomSampler, 48, 48, 4, 2)
    assert nxt != per_rank
    flat_nxt = sorted(i for b in nxt for mb in b for i in mb)
    assert flat_nxt == list(range(48))


def test_random_sampler_mid_epoch_resume():
    full = list(MegatronPretrainingRandomSampler(
        total_samples=48, consumed_samples=0, micro_batch_size=4,
        data_parallel_rank=0, data_parallel_size=2))
    resumed = list(MegatronPretrainingRandomSampler(
        total_samples=48, consumed_samples=16, micro_batch_size=4,
        data_parallel_rank=0, data_parallel_size=2))
    assert resumed == full[2:]  # 16 consumed == 2 global batches skipped
