"""The documented-no-op knob audit (VERDICT item 9, finished).

docs/API.md's "Accepted-but-inert knobs (no-op on TPU)" table and the
code must agree EXACTLY — both directions:

* every knob the table documents as inert exists in the code's
  registries (`parallel.distributed.NOOP_KNOBS`,
  `testing.arguments.INERT_CUDA_KNOBS`, amp's ``cast_model_outputs``)
  and is mechanically UNREAD outside its defining module, and
* every registered inert knob is documented.

The original spot-check found "most are, not all": the old table listed
``masked_softmax_fusion`` as a no-op while the field actually flows
into ``TransformerConfig`` and gates the ``FusedScaleMaskSoftmax``
fused path — this suite asserts that class of drift can't come back
(a registry entry that is consumed anywhere fails the inertness scan;
a consumed knob snuck into the doc table fails the exact-match).
"""

import dataclasses
import inspect
import os
import re
import sys
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu.parallel.distributed import (  # noqa: E402
    NOOP_KNOBS,
    DistributedDataParallel,
)
from apex_tpu.transformer.testing.arguments import (  # noqa: E402
    INERT_CUDA_KNOBS,
    MegatronArgs,
    parse_args,
)

API_MD = os.path.join(REPO, "docs", "API.md")
AMP_INERT = ("cast_model_outputs",)


def documented_noop_knobs():
    """Knob names from the FIRST cell of each row of API.md's
    'Accepted-but-inert knobs' table."""
    with open(API_MD) as f:
        text = f.read()
    start = text.index("### Accepted-but-inert knobs")
    section = text[start:]
    end = section.find("\n## ")
    if end != -1:
        section = section[:end]
    names = set()
    for line in section.splitlines():
        if not line.startswith("|") or line.startswith("|---"):
            continue
        first_cell = line.split("|")[1]
        if first_cell.strip() == "Knob":
            continue
        for token in re.findall(r"`([^`]+)`", first_cell):
            idents = re.findall(r"[A-Za-z_][A-Za-z0-9_]*", token)
            if idents:
                names.add(idents[-1])
    return names


def _py_files(*roots):
    for root in roots:
        for dirpath, _dirs, files in os.walk(os.path.join(REPO, root)):
            if "__pycache__" in dirpath:
                continue
            for fn in files:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _attribute_reads(field, exclude_suffixes, roots=("apex_tpu",
                                                     "examples")):
    """Files (outside *exclude_suffixes*) containing an attribute access
    of *field* — the mechanical inertness probe: an inert knob may be
    stored, but nothing may READ it off an object."""
    pat = re.compile(r"\." + re.escape(field) + r"\b")
    hits = []
    for path in _py_files(*roots):
        if any(path.endswith(sfx) for sfx in exclude_suffixes):
            continue
        with open(path) as f:
            if pat.search(f.read()):
                hits.append(os.path.relpath(path, REPO))
    return hits


def test_doc_table_matches_code_registries_exactly():
    code = set(NOOP_KNOBS) | set(INERT_CUDA_KNOBS) | set(AMP_INERT)
    doc = documented_noop_knobs()
    assert doc == code, (
        f"docs/API.md no-op table drifted from the code registries: "
        f"documented-but-unregistered={sorted(doc - code)}, "
        f"registered-but-undocumented={sorted(code - doc)}")


def test_registered_knobs_are_accepted_by_their_surfaces():
    fields = {f.name for f in dataclasses.fields(MegatronArgs)}
    missing = set(INERT_CUDA_KNOBS) - fields
    assert not missing, (
        f"INERT_CUDA_KNOBS not accepted by MegatronArgs: {missing} — "
        "a documented no-op must at least be ACCEPTED (reference parity)")
    params = set(inspect.signature(
        DistributedDataParallel.__init__).parameters)
    missing = set(NOOP_KNOBS) - params
    assert not missing, f"NOOP_KNOBS not DDP ctor params: {missing}"
    from apex_tpu.amp.frontend import initialize

    assert set(AMP_INERT) <= set(inspect.signature(initialize).parameters)


def test_registered_megatron_knobs_are_mechanically_inert():
    """No file outside testing/arguments.py may read any INERT field
    off an object — `masked_softmax_fusion` (a REAL knob the old table
    misdocumented) fails exactly this probe, which is why it is not in
    the registry."""
    for field in INERT_CUDA_KNOBS:
        hits = _attribute_reads(field, ("testing/arguments.py",))
        assert not hits, (
            f"MegatronArgs.{field} is registered inert but read in "
            f"{hits} — either drop it from INERT_CUDA_KNOBS (+ the "
            f"API.md table) or remove the consumer")
    # the converse control: the knob the audit evicted IS consumed
    assert _attribute_reads("masked_softmax_fusion",
                            ("testing/arguments.py",)), (
        "masked_softmax_fusion no longer consumed anywhere — it may "
        "belong back in the inert table")
    # ...and nothing bridged into TransformerConfig can be inert
    from apex_tpu.transformer.testing import arguments as args_mod

    bridge_src = inspect.getsource(MegatronArgs.to_transformer_config)
    for field in INERT_CUDA_KNOBS:
        assert f"self.{field}" not in bridge_src, (
            f"{field} is bridged to TransformerConfig — not inert")
    assert "self.masked_softmax_fusion" in bridge_src
    del args_mod


def test_registered_ddp_knobs_are_mechanically_inert():
    # scoped to the package: the DDP knobs are ctor arguments, and an
    # example's own argparse namespace reusing a name (imagenet's
    # `--prof` step cap) is not a read of the DDP knob
    for field in NOOP_KNOBS:
        hits = _attribute_reads(field, ("parallel/distributed.py",),
                                roots=("apex_tpu",))
        assert not hits, (f"DDP `{field}` is registered inert but read "
                          f"in {hits}")


def test_amp_cast_model_outputs_recorded_not_consumed():
    hits = _attribute_reads("cast_model_outputs", ("amp/frontend.py",
                                                   "amp/_amp_state.py"))
    assert not hits, f"cast_model_outputs consumed in {hits}"


def test_ddp_warns_on_every_nondefault_noop_knob():
    nondefault = {
        "message_size": 1, "delay_allreduce": True,
        "num_allreduce_streams": 2, "retain_allreduce_buffers": True,
        "allreduce_trigger_params": ["w"], "allreduce_communicators": "c",
        "gradient_average_split_factor": 2.0, "prof": True,
    }
    assert set(nondefault) == set(NOOP_KNOBS)
    for name, value in nondefault.items():
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            DistributedDataParallel(**{name: value})
        assert any(name in str(w.message) for w in caught), (
            f"non-default `{name}` did not warn")
    # defaults stay silent
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        DistributedDataParallel()
    assert not caught


def test_persist_layer_norm_is_accepted_cli_to_dataclass():
    """The audit found the doc promising `MegatronArgs.persist_layer_norm`
    while the dataclass lacked the field — it now exists end-to-end
    (accepted, recorded, inert)."""
    args = MegatronArgs(num_layers=2, hidden_size=64,
                        num_attention_heads=4,
                        max_position_embeddings=32, micro_batch_size=1,
                        persist_layer_norm=True).finalize()
    assert args.persist_layer_norm is True
    args = parse_args(["--num-layers", "2", "--hidden-size", "64",
                       "--num-attention-heads", "4",
                       "--max-position-embeddings", "32",
                       "--micro-batch-size", "1", "--persist-layer-norm"])
    assert args.persist_layer_norm is True
