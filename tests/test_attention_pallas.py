"""VMEM-row fused attention kernel vs the dense reference (interpret mode
on CPU; the real-TPU timing comparison lives in
benchmarks/profile_attention.py). Reference envelope: the fmha /
fast_multihead_attn fwd+bwd parity tests (contrib/test/fmha,
contrib/test/multihead_attn)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops import attention_pallas as ap
from apex_tpu.ops.attention import _dense_attention


def _qkv(rs, b, h, sq, sk, d, dtype):
    q = jnp.asarray(rs.randn(b, h, sq, d), dtype)
    k = jnp.asarray(rs.randn(b, h, sk, d), dtype)
    v = jnp.asarray(rs.randn(b, h, sk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fwd_matches_dense(causal, dtype):
    b, h, s, d = 2, 3, 256, 64
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs, b, h, s, s, d, dtype)
    assert ap.supported(s, s, d)
    scale = 1.0 / np.sqrt(d)
    got = ap.fused_attention_rows(q, k, v, causal, scale, None, True)
    want = _dense_attention(q, k, v, causal, scale, None)
    assert got.dtype == q.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2 if dtype == jnp.bfloat16 else 2e-5)


def test_fwd_cross_lengths():
    b, h, sq, sk, d = 2, 2, 128, 384, 32
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs, b, h, sq, sk, d, jnp.float32)
    scale = 0.17
    got = ap.fused_attention_rows(q, k, v, False, scale, None, True)
    want = _dense_attention(q, k, v, False, scale, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_fwd_segment_ids_and_masked_rows():
    """Packed varlen batch; one query segment has no keys at all in the
    cross-length case -> those rows must be exactly 0 (dense semantics)."""
    b, h, s, d = 2, 2, 128, 32
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    seg_q = jnp.asarray(rs.randint(0, 3, (b, s)), jnp.int32)
    # kv only carries segments {0, 1}: queries in segment 2 see no keys
    seg_kv = jnp.asarray(rs.randint(0, 2, (b, s)), jnp.int32)
    scale = 1.0 / np.sqrt(d)
    got = ap.fused_attention_rows(q, k, v, False, scale, (seg_q, seg_kv),
                                  True)
    want = _dense_attention(q, k, v, False, scale, (seg_q, seg_kv))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    empty = np.asarray(seg_q) == 2
    assert empty.any()
    np.testing.assert_array_equal(
        np.asarray(got)[empty.nonzero()[0][0], :,
                        empty.nonzero()[1][0]], 0.0)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grads_match_dense(causal, dtype):
    b, h, s, d = 2, 2, 128, 64
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, b, h, s, s, d, dtype)
    tgt = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def loss(fn):
        def go(q, k, v):
            y = fn(q, k, v)
            return jnp.mean((y.astype(jnp.float32) - tgt) ** 2)
        return go

    gq, gk, gv = jax.grad(loss(
        lambda q, k, v: ap.fused_attention_rows(q, k, v, causal, scale,
                                                None, True)),
        argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(loss(
        lambda q, k, v: _dense_attention(q, k, v, causal, scale, None)),
        argnums=(0, 1, 2))(q, k, v)
    tol = 5e-3 if dtype == jnp.bfloat16 else 1e-5
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        assert g.dtype == dtype
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(r, np.float32), atol=tol)


def test_grads_segment_ids_multiblock():
    """Grid with several q blocks (exercises the dk/dv accumulation) +
    segment masking in backward."""
    b, h, s, d = 1, 2, 512, 32
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    seg = jnp.asarray(np.sort(rs.randint(0, 4, (b, s)), axis=1), jnp.int32)
    scale = 1.0 / np.sqrt(d)
    # force a multi-block q grid by shrinking the budget
    orig = ap._VMEM_BUDGET
    ap._VMEM_BUDGET = 128 * 1024
    try:
        assert ap._q_block(s, s) < s
        def f(q, k, v):
            y = ap.fused_attention_rows(q, k, v, True, scale, (seg, seg),
                                        True)
            return jnp.sum(y * jnp.cos(jnp.arange(d, dtype=jnp.float32)))
        gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    finally:
        ap._VMEM_BUDGET = orig

    def r(q, k, v):
        y = _dense_attention(q, k, v, True, scale, (seg, seg))
        return jnp.sum(y * jnp.cos(jnp.arange(d, dtype=jnp.float32)))

    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


@pytest.mark.parametrize("bwd_impl", ["monolithic", "split"])
@pytest.mark.parametrize("dtype", [
    jnp.float32, pytest.param(jnp.bfloat16, marks=pytest.mark.slow)])
def test_chunked_causal_matches_dense(dtype, bwd_impl):
    """block_q=128 at s=512 engages the causal-skip (chunked) kernels;
    parity incl. grads against dense proves the guarded-skip logic and
    the dP-garbage masking. bwd_impl is pinned per case so the chunked
    monolithic backward keeps gradient coverage alongside split."""
    b, h, s, d = 1, 2, 512, 64
    rs = np.random.RandomState(5)
    q, k, v = _qkv(rs, b, h, s, s, d, dtype)
    scale = 1.0 / np.sqrt(d)
    from apex_tpu.ops.attention_pallas import _chunked
    assert _chunked(True, 128, s, s)
    tgt = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    def loss(fn):
        def go(q, k, v):
            y = fn(q, k, v)
            return jnp.mean((y.astype(jnp.float32) - tgt) ** 2)
        return go

    y = ap.fused_attention_rows(q, k, v, True, scale, None, True, 128)
    want = _dense_attention(q, k, v, True, scale, None)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2 if dtype == jnp.bfloat16 else 2e-5)
    gq, gk, gv = jax.grad(loss(
        lambda q, k, v: ap.fused_attention_rows(q, k, v, True, scale, None,
                                                True, 128, bwd_impl)),
        argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(loss(
        lambda q, k, v: _dense_attention(q, k, v, True, scale, None)),
        argnums=(0, 1, 2))(q, k, v)
    tol = 5e-3 if dtype == jnp.bfloat16 else 1e-5
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(r, np.float32), atol=tol)


@pytest.mark.parametrize("bwd_impl", [
    "monolithic", pytest.param("split", marks=pytest.mark.slow)])
def test_chunked_causal_with_segments(bwd_impl):
    b, h, s, d = 1, 1, 384, 32
    rs = np.random.RandomState(6)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    seg = jnp.asarray(np.sort(rs.randint(0, 3, (b, s)), axis=1), jnp.int32)
    scale = 1.0 / np.sqrt(d)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, True, scale, (seg, seg),
                                    True, 128, bwd_impl)
        return jnp.sum(jnp.sin(y))

    def r(q, k, v):
        y = _dense_attention(q, k, v, True, scale, (seg, seg))
        return jnp.sum(jnp.sin(y))

    np.testing.assert_allclose(float(f(q, k, v)), float(r(q, k, v)),
                               rtol=1e-5)
    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


def test_block_q_validation():
    q = jnp.ones((1, 1, 256, 32))
    with pytest.raises(ValueError):
        ap.fused_attention_rows(q, q, q, True, 0.2, None, True, 100)


def test_supported_predicate():
    assert ap.supported(1024, 1024, 64)
    assert ap.supported(2048, 2048, 64)
    assert not ap.supported(1024, 1000, 64)   # sk not lane-aligned
    assert not ap.supported(1024, 1024, 512)  # d too large
    # giant sk: q block would fall below the minimum
    assert not ap.supported(8, 512 * 1024, 64)


@pytest.mark.parametrize("impl", ["monolithic", "split"])
@pytest.mark.parametrize("causal", [False, True])
def test_bwd_impls_match_dense(impl, causal):
    """Both backward structures (q-major accumulating kernel; split
    dq + k-major dkv passes) produce dense-reference gradients."""
    b, h, s, d = 1, 2, 256, 32
    rs = np.random.RandomState(7)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, causal, scale, None, True,
                                    None, impl)
        return jnp.sum(jnp.sin(y))

    def r(q, k, v):
        y = _dense_attention(q, k, v, causal, scale, None)
        return jnp.sum(jnp.sin(y))

    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


def test_bwd_split_segments_rectangular():
    """Split backward with segment ids and sq != sk (multi-q-block and
    multi-k-block grids with the k-major pass)."""
    b, h, sq, sk, d = 1, 1, 256, 512, 32
    rs = np.random.RandomState(8)
    q, k, v = _qkv(rs, b, h, sq, sk, d, jnp.float32)
    seg_q = jnp.asarray(np.sort(rs.randint(0, 3, (b, sq)), axis=1),
                        jnp.int32)
    seg_kv = jnp.asarray(np.sort(rs.randint(0, 3, (b, sk)), axis=1),
                         jnp.int32)
    scale = 1.0 / np.sqrt(d)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, False, scale, (seg_q, seg_kv),
                                    True, 128, "split")
        return jnp.sum(jnp.sin(y))

    def r(q, k, v):
        y = _dense_attention(q, k, v, False, scale, (seg_q, seg_kv))
        return jnp.sum(jnp.sin(y))

    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


def test_bwd_split_bf16_matches_dense():
    """Split backward at bf16 (non-chunked): the stats round-trip and the
    k-major P reconstruction stay within bf16 tolerance of dense."""
    b, h, s, d = 1, 2, 256, 32
    rs = np.random.RandomState(9)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.bfloat16)
    scale = 1.0 / np.sqrt(d)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, True, scale, None, True,
                                    None, "split")
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    def r(q, k, v):
        y = _dense_attention(q, k, v, True, scale, None)
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(ref, np.float32), atol=4e-2)


@pytest.mark.slow  # split-bwd causal+rectangular corner; the impl matrix
# and bf16/segment split tests keep split-bwd covered fast
def test_bwd_split_causal_rectangular():
    """Causal with sq != sk: the k-major pass's absolute row/column
    bookkeeping (col0 offsets, chunk-skip reach) must match dense's
    col > row convention when the k grid outnumbers the q grid."""
    b, h, sq, sk, d = 1, 1, 256, 512, 32
    rs = np.random.RandomState(10)
    q, k, v = _qkv(rs, b, h, sq, sk, d, jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, True, scale, None, True,
                                    128, "split")
        return jnp.sum(jnp.sin(y))

    def r(q, k, v):
        y = _dense_attention(q, k, v, True, scale, None)
        return jnp.sum(jnp.sin(y))

    np.testing.assert_allclose(float(f(q, k, v)), float(r(q, k, v)),
                               rtol=1e-5)
    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


# ----------------------------- dropout ------------------------------------

def _dense_mscale(seed, b, h, sq, sk, p):
    """Dense [b, h, sq, sk] keep-scale built from the kernel's own hash
    (tile-layout independent, so the full-array build is exact)."""
    out = np.zeros((b, h, sq, sk), np.float32)
    seed = jnp.asarray(seed, jnp.int32)
    for ib in range(b):
        for ih in range(h):
            out[ib, ih] = np.asarray(ap._dropout_mscale(
                seed, jnp.int32(ib), jnp.int32(ih), 0, sq, sk, p, h))
    return jnp.asarray(out)


def test_dropout_fwd_bwd_matches_dense_with_same_mask():
    """Exact parity: dense attention with the hash mask applied to the
    probabilities == the kernel, for the output AND all three grads."""
    b, h, s, d, p = 2, 3, 256, 32, 0.3
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    scale = 1.0 / np.sqrt(d)
    seed = jnp.asarray([[42]], jnp.int32)
    mscale = _dense_mscale(seed, b, h, s, s, p)

    def f(q, k, v):
        y = ap.fused_attention_rows(q, k, v, False, scale, None, True,
                                    None, None, p, seed)
        return jnp.sum(jnp.sin(y))

    def r(q, k, v):
        sc = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        probs = jax.nn.softmax(sc, axis=-1) * mscale
        y = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        return jnp.sum(jnp.sin(y))

    np.testing.assert_allclose(float(f(q, k, v)), float(r(q, k, v)),
                               rtol=1e-5)
    gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, ref in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   atol=2e-4)


def test_dropout_segments_and_statistics():
    """Dropout composes with segment masking; drop rate ~ p and the
    surviving probs are scaled by 1/(1-p) (inverted dropout)."""
    b, h, s, d, p = 2, 2, 256, 32, 0.25
    rs = np.random.RandomState(4)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    seg = jnp.asarray(rs.randint(0, 3, (b, s)), jnp.int32)
    scale = 1.0 / np.sqrt(d)
    seed = jnp.asarray([[7]], jnp.int32)
    got = ap.fused_attention_rows(q, k, v, False, scale, (seg, seg), True,
                                  None, None, p, seed)
    assert np.isfinite(np.asarray(got)).all()
    # statistics of the mask itself
    ms = np.asarray(_dense_mscale(seed, b, h, s, s, p))
    drop_rate = (ms == 0).mean()
    assert abs(drop_rate - p) < 0.01, drop_rate
    np.testing.assert_allclose(ms[ms > 0], 1.0 / (1.0 - p), rtol=1e-6)
    # expectation: averaging many independent masks recovers the
    # no-dropout output (checked on the mask mean, which is what enters
    # linearly)
    assert abs(ms.mean() - 1.0) < 0.01


def test_dropout_determinism_and_seed_sensitivity():
    b, h, s, d, p = 1, 2, 128, 32, 0.5
    rs = np.random.RandomState(5)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    scale = 1.0 / np.sqrt(d)
    a1 = ap.fused_attention_rows(q, k, v, False, scale, None, True,
                                 None, None, p, jnp.asarray([[3]], jnp.int32))
    a2 = ap.fused_attention_rows(q, k, v, False, scale, None, True,
                                 None, None, p, jnp.asarray([[3]], jnp.int32))
    b2 = ap.fused_attention_rows(q, k, v, False, scale, None, True,
                                 None, None, p, jnp.asarray([[4]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert np.abs(np.asarray(a1) - np.asarray(b2)).max() > 1e-3


def test_dropout_zero_p_equals_base_kernel():
    b, h, s, d = 1, 2, 128, 32
    rs = np.random.RandomState(6)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    scale = 1.0 / np.sqrt(d)
    base = ap.fused_attention_rows(q, k, v, True, scale, None, True)
    zero = ap.fused_attention_rows(q, k, v, True, scale, None, True,
                                   None, None, 0.0,
                                   jnp.asarray([[9]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(base), np.asarray(zero))


def test_dropout_knob_validation():
    b, h, s, d = 1, 1, 128, 32
    rs = np.random.RandomState(7)
    q, k, v = _qkv(rs, b, h, s, s, d, jnp.float32)
    with pytest.raises(ValueError, match="monolithic"):
        ap.fused_attention_rows(q, k, v, False, 0.1, None, True, None,
                                "split", 0.3, jnp.asarray([[1]], jnp.int32))
    with pytest.raises(ValueError, match="dropout_seed"):
        ap.fused_attention_rows(q, k, v, False, 0.1, None, True, None,
                                None, 0.3, None)
    for bad_p in (1.0, 1.5, -0.1):
        with pytest.raises(ValueError, match="outside"):
            ap.fused_attention_rows(q, k, v, False, 0.1, None, True, None,
                                    None, bad_p,
                                    jnp.asarray([[1]], jnp.int32))


def test_supported_dropout_gate_tighter():
    """The dropout backward's 6-array working set shrinks the viable q
    block: a shape can fit the plain kernel but not the dropout one —
    supported(dropout=True) must say so (callers gate dispatch on it;
    an un-gated call would hit a zero q block)."""
    sq = sk = 65536  # bq cap: 4-array 9 -> block 8; 6-array 6 -> 0
    assert ap.supported(sq, sk, 64)
    assert not ap.supported(sq, sk, 64, dropout=True)
    # and an un-gated dropout call at that shape refuses loudly rather
    # than dividing by zero in the grid computation
    q = jnp.zeros((1, 1, sq, 64), jnp.bfloat16)
    with pytest.raises(ValueError, match="unsupported"):
        ap.fused_attention_rows(q, q, q, False, 0.1, None, True, None,
                                None, 0.3, jnp.asarray([[1]], jnp.int32))
