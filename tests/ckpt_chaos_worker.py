"""Subprocess driver for the checkpoint chaos twins
(tests/test_checkpoint_chaos.py): save a deterministic state at each
requested step through the REAL DurableCheckpointer, under whatever
``APEX_FAULT_PLAN`` rides the environment — the SIGKILL/corruption/
stale-manifest faults fire inside the real commit path, and the parent
test asserts the on-disk durability invariants afterwards.

Usage: python tests/ckpt_chaos_worker.py <dir> <step> [<step> ...]
(run with PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu like every local
CPU subprocess — CLAUDE.md).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from apex_tpu import checkpoint as ckpt  # noqa: E402


def state_at(step):
    """Deterministic per-step state so the parent can assert the PRIOR
    checkpoint survived bitwise."""
    base = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    return {"w": base + float(step),
            "emb": (base[:, :2] * step).astype(jnp.bfloat16),
            "count": jnp.asarray(step, jnp.int32)}


def main():
    directory = sys.argv[1]
    steps = [int(s) for s in sys.argv[2:]]
    writer = ckpt.DurableCheckpointer(directory, max_to_keep=10,
                                      async_save=False)
    for step in steps:
        writer.save(step, state_at(step), meta={"step": step})
        print(f"committed {step}", flush=True)
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
