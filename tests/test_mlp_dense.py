"""fused_dense + MLP parity tests
(reference: tests/L0/run_mlp/test_mlp.py — MLP vs unfused sequential;
apex/fused_dense tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from apex_tpu.fused_dense import (
    FusedDense, FusedDenseGeluDense, fused_dense_function,
    fused_dense_gelu_dense_function,
)
from apex_tpu.mlp import MLP, mlp_function
from apex_tpu import amp


def test_fused_dense_vs_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(16, 8).astype(np.float32)
    b = rng.randn(16).astype(np.float32)
    want = torch.nn.functional.linear(
        torch.tensor(x), torch.tensor(w), torch.tensor(b)).numpy()
    got = fused_dense_function(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-5, atol=1e-5)


def test_fused_dense_gelu_dense_vs_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 8).astype(np.float32)
    w1 = rng.randn(16, 8).astype(np.float32)
    b1 = rng.randn(16).astype(np.float32)
    w2 = rng.randn(8, 16).astype(np.float32)
    b2 = rng.randn(8).astype(np.float32)
    h = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w1),
                                   torch.tensor(b1))
    h = torch.nn.functional.gelu(h)
    want = torch.nn.functional.linear(h, torch.tensor(w2),
                                      torch.tensor(b2)).numpy()
    got = fused_dense_gelu_dense_function(
        jnp.asarray(x), jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2),
        jnp.asarray(b2))
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
def test_mlp_vs_torch_sequential(activation):
    """Reference: run_mlp/test_mlp.py — fused MLP vs torch Sequential."""
    mlp_sizes = [7, 16, 8, 4]
    rng = np.random.RandomState(2)
    x = rng.randn(5, 7).astype(np.float32)
    ws = [rng.randn(mlp_sizes[i + 1], mlp_sizes[i]).astype(np.float32)
          for i in range(3)]
    bs = [rng.randn(mlp_sizes[i + 1]).astype(np.float32) for i in range(3)]

    h = torch.tensor(x)
    for i in range(3):
        h = torch.nn.functional.linear(h, torch.tensor(ws[i]),
                                       torch.tensor(bs[i]))
        if i < 2:
            if activation == "relu":
                h = torch.relu(h)
            elif activation == "sigmoid":
                h = torch.sigmoid(h)
    want = h.numpy()
    got = mlp_function(jnp.asarray(x), [jnp.asarray(w) for w in ws],
                       [jnp.asarray(b) for b in bs], activation)
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-4, atol=1e-4)


def test_mlp_backward_vs_torch():
    mlp_sizes = [4, 8, 2]
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    ws = [rng.randn(8, 4).astype(np.float32), rng.randn(2, 8).astype(np.float32)]
    bs = [rng.randn(8).astype(np.float32), rng.randn(2).astype(np.float32)]

    xt = torch.tensor(x, requires_grad=True)
    wts = [torch.tensor(w, requires_grad=True) for w in ws]
    bts = [torch.tensor(b, requires_grad=True) for b in bs]
    h = torch.relu(torch.nn.functional.linear(xt, wts[0], bts[0]))
    h = torch.nn.functional.linear(h, wts[1], bts[1])
    h.sum().backward()

    def f(x, ws, bs):
        return jnp.sum(mlp_function(x, ws, bs, "relu"))

    gx, gws, gbs = jax.grad(f, argnums=(0, 1, 2))(
        jnp.asarray(x), [jnp.asarray(w) for w in ws],
        [jnp.asarray(b) for b in bs])
    np.testing.assert_allclose(xt.grad.numpy(), np.asarray(gx), rtol=1e-4,
                               atol=1e-5)
    for wt, gw in zip(wts, gws):
        np.testing.assert_allclose(wt.grad.numpy(), np.asarray(gw), rtol=1e-4,
                                   atol=1e-5)


def test_mlp_bad_activation():
    with pytest.raises(TypeError):
        mlp_function(jnp.ones((2, 2)), [jnp.ones((2, 2))], [None], "tanh")


def test_modules_and_autocast():
    mod = MLP(mlp_sizes=[4, 8, 2])
    x = jnp.ones((3, 4))
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    assert y.shape == (3, 2)
    with amp.autocast(dtype=jnp.bfloat16):
        y16 = mod.apply(params, x)
    assert y16.dtype == jnp.bfloat16  # matmuls ran in the policy dtype

    d = FusedDense(in_features=4, out_features=6)
    params = d.init(jax.random.PRNGKey(0), x)
    assert d.apply(params, x).shape == (3, 6)

    g = FusedDenseGeluDense(in_features=4, intermediate_features=8,
                            out_features=4)
    params = g.init(jax.random.PRNGKey(0), x)
    assert g.apply(params, x).shape == (3, 4)
