"""Pallas fused scale-mask-softmax kernel vs the jnp reference path.

Parity is pinned in Pallas interpret mode on CPU (same discipline as
tests/test_layer_norm_pallas.py); the TPU head-to-head timing lives in
benchmarks/profile_softmax.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import softmax_pallas
from apex_tpu.transformer.functional.fused_softmax import (
    scaled_masked_softmax as jnp_masked,
    scaled_upper_triang_masked_softmax as jnp_causal,
)

B, NP, SQ, SK = 2, 3, 16, 128


def _x(dtype, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(B, NP, SQ, SK) * 2.0, dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("scale", [1.0, 0.125])
def test_causal_forward_matches_reference(dtype, scale):
    x = _x(dtype)
    got = softmax_pallas.scaled_masked_softmax(
        x, None, scale, causal=True, interpret=True)
    want = jnp_causal(x.reshape(-1, SQ, SK), scale).reshape(x.shape)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=2e-2 if
                               dtype == jnp.bfloat16 else 1e-6)


@pytest.mark.parametrize("head_axis", [1, NP])
def test_masked_forward_matches_reference(head_axis):
    x = _x(jnp.float32, seed=1)
    rs = np.random.RandomState(2)
    mask = jnp.asarray(rs.rand(B, head_axis, SQ, SK) < 0.3)
    got = softmax_pallas.scaled_masked_softmax(
        x, mask, 0.5, causal=False, interpret=True)
    want = jnp_masked(x, jnp.broadcast_to(mask, x.shape), 0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_fully_masked_rows_are_zero_with_zero_grads():
    x = _x(jnp.float32, seed=3)
    mask = jnp.zeros((B, 1, SQ, SK), bool).at[:, :, 0, :].set(True)

    def f(x):
        y = softmax_pallas.scaled_masked_softmax(
            x, mask, 1.0, causal=False, interpret=True)
        return jnp.sum(y * jnp.cos(y)), y

    (_, y), g = jax.value_and_grad(f, has_aux=True)(x)
    assert np.all(np.asarray(y[:, :, 0, :]) == 0.0)
    assert np.all(np.asarray(g[:, :, 0, :]) == 0.0)
    assert np.all(np.isfinite(np.asarray(g)))


@pytest.mark.parametrize("causal", [
    pytest.param(False, marks=pytest.mark.slow), True])
def test_grads_match_reference(causal):
    x = _x(jnp.float32, seed=4)
    rs = np.random.RandomState(5)
    w = jnp.asarray(rs.randn(*x.shape), jnp.float32)
    mask = jnp.asarray(rs.rand(B, 1, SQ, SK) < 0.2)

    def f_pallas(x):
        y = softmax_pallas.scaled_masked_softmax(
            x, mask, 0.7, causal=causal, interpret=True)
        return jnp.sum(y * w)

    def f_ref(x):
        m = jnp.broadcast_to(mask, x.shape)
        if causal:
            tri = jnp.arange(SK)[None, :] > jnp.arange(SQ)[:, None]
            m = m | tri
        return jnp.sum(jnp_masked(x, m, 0.7) * w)

    np.testing.assert_allclose(np.asarray(jax.grad(f_pallas)(x)),
                               np.asarray(jax.grad(f_ref)(x)),
                               atol=1e-5, rtol=1e-4)


def test_supported_predicate():
    assert softmax_pallas.supported(SQ, SK)
    assert not softmax_pallas.supported(SQ, 100)     # lane misalignment
    assert not softmax_pallas.supported(7, SK)       # rows not blockable
    with pytest.raises(ValueError):
        softmax_pallas.scaled_masked_softmax(
            jnp.zeros((1, 1, 7, SK)), None, 1.0, False, True)


def test_fused_scale_mask_softmax_pallas_dispatch(monkeypatch):
    """FusedScaleMaskSoftmax(use_pallas=) routes the fused path through the
    kernel (spied — the test must not pass vacuously via the fallback) and
    matches the jnp fused path."""
    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.functional.fused_softmax import (
        FusedScaleMaskSoftmax)

    calls = []
    real = softmax_pallas.scaled_masked_softmax

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(softmax_pallas, "scaled_masked_softmax", spy)

    def mask_func(x, m):
        return jnp.where(m, -10000.0, x)

    # b*np must satisfy the ported batch_per_block predicate (8 at sk=128)
    # and the causal path requires sq == sk
    b, np_, sq = 4, 2, SK
    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(b, np_, sq, SK) * 2.0, jnp.bfloat16)
    for fs_kwargs, mask, expect_kernel in [
        (dict(attn_mask_type=AttnMaskType.causal), None, True),
        # causal + explicit mask: both paths must ignore the mask (the
        # reference's causal kernel takes none) — toggling use_pallas
        # must never change numerics
        (dict(attn_mask_type=AttnMaskType.causal),
         jnp.asarray(np.random.RandomState(8).rand(b, 1, sq, SK) < 0.3),
         True),
        (dict(attn_mask_type=AttnMaskType.padding),
         jnp.asarray(np.random.RandomState(7).rand(b, 1, sq, SK) < 0.3),
         True),
        # key-padding-shaped mask: unsupported by the kernel's BlockSpec
        # broadcast — must fall back to the jnp path, not crash
        (dict(attn_mask_type=AttnMaskType.padding),
         jnp.asarray(np.random.RandomState(9).rand(b, 1, 1, SK) < 0.3),
         False),
    ]:
        fs_jnp = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            scaled_masked_softmax_fusion=True, mask_func=mask_func,
            softmax_in_fp32=True, scale=0.25, **fs_kwargs)
        fs_pl = FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            scaled_masked_softmax_fusion=True, mask_func=mask_func,
            softmax_in_fp32=True, scale=0.25, use_pallas=True,
            _pallas_interpret=True, **fs_kwargs)
        assert fs_jnp.is_kernel_available(mask, b, np_, sq, SK)
        before = len(calls)
        got, want = fs_pl(x, mask), fs_jnp(x, mask)
        assert (len(calls) > before) == expect_kernel, \
            f"unexpected dispatch for {fs_kwargs}, mask={getattr(mask, 'shape', None)}"
        assert got.dtype == want.dtype == x.dtype
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=2e-2)

    # use_pallas WITHOUT interpret on a non-TPU backend must silently
    # fall back to the jnp path (the cfg.softmax_use_pallas knob set on
    # a CPU run), never crash in pallas_call
    fs_cpu = FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=True,
        attn_mask_type=AttnMaskType.causal,
        scaled_masked_softmax_fusion=True, mask_func=mask_func,
        softmax_in_fp32=True, scale=0.25, use_pallas=True)
    before = len(calls)
    got = fs_cpu(x, None)
    assert len(calls) == before, "kernel must not run on CPU w/o interpret"
    assert got.shape == x.shape

    # the Generic (unbounded-seq) variant shares the kernel dispatch
    from apex_tpu.transformer.functional.fused_softmax import (
        GenericFusedScaleMaskSoftmax)

    mask = jnp.asarray(np.random.RandomState(10).rand(b, 1, sq, SK) < 0.3)
    gen_jnp = GenericFusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=True, mask_func=mask_func,
        softmax_in_fp32=True, scale=0.25)
    gen_pl = GenericFusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=True, mask_func=mask_func,
        softmax_in_fp32=True, scale=0.25, use_pallas=True,
        _pallas_interpret=True)
    before = len(calls)
    got, want = gen_pl(x, mask), gen_jnp(x, mask)
    assert len(calls) > before
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=2e-2)
