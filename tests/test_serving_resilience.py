"""Serving-resilience unit surfaces (ISSUE 15): knob asymmetry of the
four layers, the lifecycle transition machine's suspension cycles, the
slo block's resilience fields + ledger teeth, check 9's resilience
pin rules (both directions), the scheduler's growth/victim/requeue
arithmetic (stdlib-only — no engine), the prefix-cache flush, the
guarded-dispatch watchdog, and the window_report/gauge plumbing."""

import json
import os

import pytest

from apex_tpu import resilience as res_mod
from apex_tpu.serving import lifecycle
from apex_tpu.serving import resilience as serve_res
from apex_tpu.serving.kv_cache import PageAllocator
from apex_tpu.serving.prefix_cache import PrefixCache
from apex_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)
from apex_tpu.telemetry import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ knob asymmetry


def test_resolve_admit_asymmetry(monkeypatch):
    monkeypatch.delenv("APEX_SERVE_ADMIT", raising=False)
    assert serve_res.resolve_admit() == 0          # built-in OFF
    assert serve_res.resolve_admit(4) == 4
    assert serve_res.resolve_admit(0) == 0         # explicit off
    assert serve_res.resolve_admit(False) == 0
    for bad in (-1, 2.5, "8", True):
        with pytest.raises(ValueError, match="admit="):
            serve_res.resolve_admit(bad)
    monkeypatch.setenv("APEX_SERVE_ADMIT", "16")
    assert serve_res.resolve_admit() == 16
    monkeypatch.setenv("APEX_SERVE_ADMIT", "0")
    assert serve_res.resolve_admit() == 0          # env off-pin
    monkeypatch.setenv("APEX_SERVE_ADMIT", "lots")
    assert serve_res.resolve_admit() == 0          # garbage ignored


@pytest.mark.parametrize("resolve,env", [
    (serve_res.resolve_shed, "APEX_SERVE_SHED"),
    (serve_res.resolve_preempt, "APEX_SERVE_PREEMPT"),
    (serve_res.resolve_recover, "APEX_SERVE_RECOVER"),
])
def test_resolve_flag_asymmetry(resolve, env, monkeypatch):
    monkeypatch.delenv(env, raising=False)
    assert resolve() is False
    assert resolve(True) is True
    assert resolve(False) is False
    with pytest.raises(ValueError):
        resolve("yes")                              # demand: raises
    monkeypatch.setenv(env, "1")
    assert resolve() is True
    monkeypatch.setenv(env, "0")
    assert resolve() is False
    monkeypatch.setenv(env, "on")                   # preference: falls
    assert resolve() is False


def test_rejected_is_frozen_structured():
    r = serve_res.Rejected("queue_full", 3)
    assert (r.reason, r.retry_after_ticks) == ("queue_full", 3)
    with pytest.raises((AttributeError, TypeError)):
        r.reason = "other"


# -------------------------------------------------- guarded dispatch


def test_guarded_dispatch_passes_result_through():
    assert serve_res.guarded_dispatch(lambda: 41 + 1, 5.0, "decode") \
        == 42


def test_guarded_dispatch_timeout_is_wedged():
    import time

    with pytest.raises(serve_res.DispatchFailure) as ei:
        serve_res.guarded_dispatch(lambda: time.sleep(1.0), 0.05,
                                   "decode")
    assert ei.value.verdict == res_mod.WEDGED
    assert ei.value.phase == "decode"


def test_guarded_dispatch_crash_is_degraded_relay():
    def boom():
        raise OSError("connection reset")

    with pytest.raises(serve_res.DispatchFailure) as ei:
        serve_res.guarded_dispatch(boom, 5.0, "prefill")
    assert ei.value.verdict == res_mod.DEGRADED_RELAY
    assert "connection reset" in ei.value.detail
    assert isinstance(ei.value.__cause__, OSError)


def test_serving_envelope_constants_exist():
    """The §6 serving entries live in the ONE envelope home."""
    assert res_mod.SERVE_DISPATCH_TIMEOUT_S > 0
    assert res_mod.SERVE_ROUND_ATTEMPTS >= 1
    assert res_mod.SERVE_ROUND_RETRY_WAIT_S >= 0


# --------------------------------------------- lifecycle order machine


def _log(chain, rid=0):
    log = lifecycle.EventLog()
    for i, ev in enumerate(chain):
        log.record(ev, rid, tick=i, wall=float(i))
    return log


def test_validate_order_accepts_suspension_cycles():
    for chain in (
        # preempted mid-stream, re-admitted, finishes
        ("submitted", "admitted", "prefill_done", "first_token",
         "preempted", "resubmitted", "admitted", "finished",
         "evicted"),
        # degraded round before any token; prefill seam after
        ("submitted", "admitted", "degraded_round", "resubmitted",
         "admitted", "prefill_done", "first_token", "finished",
         "evicted"),
        # two suspension cycles
        ("submitted", "admitted", "prefill_done", "first_token",
         "preempted", "resubmitted", "admitted", "degraded_round",
         "resubmitted", "admitted", "finished", "evicted"),
        # terminal paths
        ("submitted", "rejected"),
        ("submitted", "shed"),
        ("submitted", "admitted", "preempted", "resubmitted", "shed"),
    ):
        assert _log(chain).validate_order() == [], chain


def test_validate_order_rejects_bad_resilience_chains():
    cases = [
        # a suspension must be followed by resubmitted
        (("submitted", "admitted", "preempted", "admitted"),
         "out of order"),
        # the first-token seam fires once across cycles
        (("submitted", "admitted", "prefill_done", "first_token",
          "preempted", "resubmitted", "admitted", "prefill_done"),
         "duplicate"),
        # nothing after a terminal reject
        (("submitted", "rejected", "admitted"), "out of order"),
        # finished needs a first token
        (("submitted", "admitted", "finished"), "'finished' before"),
        # shed is once-only
        (("submitted", "shed", "shed"), "duplicate"),
    ]
    for chain, needle in cases:
        probs = _log(chain).validate_order()
        assert any(needle in p for p in probs), (chain, probs)


def test_core_events_is_the_happy_path():
    assert _log(lifecycle.CORE_EVENTS).validate_order() == []
    assert set(lifecycle.CORE_EVENTS) < set(lifecycle.EVENTS)


def test_gauges_carry_resilience_counters():
    log = lifecycle.EventLog()
    log.sample_gauges(tick=0, wall=0.0, slots_active=1, num_slots=2,
                      queue_depth=0, kv_pages_live=1, kv_pages_total=8,
                      hol_wait_s=0.0, rejected=2, shed=1, preempted=3,
                      resubmitted=4, degraded_rounds=1)
    row = log.gauge_rows()[0]
    assert row["serve_rejected"] == 2
    assert row["serve_shed"] == 1
    assert row["serve_preempted"] == 3
    assert row["serve_resubmitted"] == 4
    assert row["serve_degraded_rounds"] == 1
    from apex_tpu.telemetry import metrics

    for name in ("serve_rejected", "serve_shed", "serve_preempted",
                 "serve_resubmitted", "serve_degraded_rounds"):
        assert metrics.spec(name) is not None, name


# -------------------------------------------- slo block + ledger teeth


def _slo(**resilience):
    return lifecycle.slo_block(
        [], 1.0, ttft_ms=100.0, tpot_ms=10.0,
        arrival_process="poisson", offered_load=1.0,
        resilience=resilience or None)


def test_slo_block_resilience_fields_none_when_disabled():
    blk = _slo()
    assert blk["shed_rate"] is None
    assert blk["preempt_rate"] is None
    assert blk["degraded_rounds"] is None
    blk = _slo(shed_rate=0.25, preempt_rate=0.125, degraded_rounds=2)
    assert blk["shed_rate"] == 0.25
    assert blk["preempt_rate"] == 0.125
    assert blk["degraded_rounds"] == 2
    for f in ("shed_rate", "preempt_rate", "degraded_rounds"):
        assert f in ledger_mod.SLO_FIELDS


def test_ledger_validates_resilience_fields():
    good = _slo(shed_rate=0.5, preempt_rate=0.0, degraded_rounds=0)
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 extra={"slo": good})
    assert ledger_mod.validate_record(rec) == []
    cases = [
        ({"shed_rate": 1.5}, "shed_rate"),
        ({"preempt_rate": -0.1}, "preempt_rate"),
        ({"preempt_rate": True}, "preempt_rate"),
        ({"degraded_rounds": -1}, "degraded_rounds"),
        ({"degraded_rounds": 2.5}, "degraded_rounds"),
    ]
    for mut, needle in cases:
        r = ledger_mod.make_record(
            "profile_serving", "cpu", 0.1, 2,
            extra={"slo": dict(good, **mut)})
        probs = ledger_mod.validate_record(r)
        assert any(needle in p for p in probs), (mut, probs)
    # a missing resilience field is a finding (presence teeth)
    bad = dict(good)
    del bad["shed_rate"]
    r = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                               extra={"slo": bad})
    assert any("shed_rate" in p
               for p in ledger_mod.validate_record(r))


def test_resilience_stats_rates():
    st = serve_res.ResilienceStats(shed=1, preempted=2,
                                   submit_attempts=4, admissions=8,
                                   degraded_rounds=3)
    on = st.rates(shed_on=True, preempt_on=True, recover_on=True)
    assert on == {"shed_rate": 0.25, "preempt_rate": 0.25,
                  "degraded_rounds": 3}
    off = st.rates(shed_on=False, preempt_on=False, recover_on=False)
    assert off == {"shed_rate": None, "preempt_rate": None,
                   "degraded_rounds": None}


# ----------------------------------------------------- check 9 teeth


def _check9(tmp_path, knobs, slo):
    from tests.conftest import run_check_bench_labels

    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 knobs=knobs, extra={"slo": slo})
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"| row | 1 ms | x |\n\nledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    return run_check_bench_labels(
        "--perf", str(perf), "--ledger", str(ledger),
        "--table", str(table))


BASE_PINS = {"APEX_SERVE_SLO_TTFT_MS": "100.0",
             "APEX_SERVE_SLO_TPOT_MS": "10.0",
             "APEX_SERVE_ARRIVALS": "poisson",
             "APEX_SERVE_SCHED": "fifo"}


def test_check9_resilience_pin_teeth(tmp_path):
    engaged = _slo(shed_rate=0.2, preempt_rate=0.1, degraded_rounds=1)
    # engaged rates + all pins non-off: clean
    pins = dict(BASE_PINS, APEX_SERVE_SHED="1", APEX_SERVE_PREEMPT="1",
                APEX_SERVE_RECOVER="1")
    out = _check9(tmp_path, pins, engaged)
    assert out.returncode == 0, out.stdout
    # a non-None rate with the pin MISSING is drift
    out = _check9(tmp_path, BASE_PINS, engaged)
    assert out.returncode == 1
    assert "does not pin APEX_SERVE_SHED" in out.stdout
    assert "does not pin APEX_SERVE_PREEMPT" in out.stdout
    assert "does not pin APEX_SERVE_RECOVER" in out.stdout
    # a non-None rate under an OFF pin is drift the other way
    out = _check9(tmp_path, dict(pins, APEX_SERVE_SHED="0"), engaged)
    assert out.returncode == 1
    assert "APEX_SERVE_SHED='0' (off)" in out.stdout
    # disabled block (all None) needs no resilience pins at all
    out = _check9(tmp_path, BASE_PINS, _slo())
    assert out.returncode == 0, out.stdout


# ------------------------------------- scheduler growth / requeue unit


def _sched(num_pages=8, preempt=True, policy=None):
    alloc = PageAllocator(num_pages)
    return ContinuousBatchingScheduler(2, 4, 4, alloc, policy=policy,
                                       preempt=preempt)


def test_overcommit_reserves_prompt_pages_only():
    sch = _sched(num_pages=16)
    r = Request(rid=0, prompt=[1] * 6, max_new_tokens=10)  # 4 total
    sch.submit(r, tick=0)
    [i] = sch.admit(0)
    assert len(sch.slots[i].pages) == 2          # ceil(6/4), not 4
    assert sch.slots[i].known == [1] * 6
    full = _sched(num_pages=16, preempt=False)
    full.submit(Request(rid=0, prompt=[1] * 6, max_new_tokens=10),
                tick=0)
    [j] = full.admit(0)
    assert len(full.slots[j].pages) == 4         # the full reservation


def test_grow_extends_then_preempts_youngest():
    sch = _sched(num_pages=6)                    # 5 allocatable
    a = Request(rid=0, prompt=[1] * 6, max_new_tokens=10)
    b = Request(rid=1, prompt=[2] * 6, max_new_tokens=10)
    sch.submit(a, tick=0)
    sch.submit(b, tick=0)
    ia, ib = sch.admit(0)
    assert sch.allocator.free_count == 1
    assert sch.grow(ia, 3, tick=1)               # takes the last page
    assert sch.allocator.free_count == 0
    # b's growth must preempt — the youngest (b itself is youngest:
    # same tick, higher rid) gets requeued and grow reports False
    b_pages = list(sch.slots[ib].pages)
    assert sch.grow(ib, 3, tick=2) is False
    assert sch.slots[ib] is None
    assert [r.rid for r in sch.take_preempted()] == [1]
    assert b.resume_tokens is None               # no tokens yet: fresh
    assert b in sch.queue
    assert sch.allocator.free_count == len(b_pages)
    sch.allocator.check_invariants()
    # a's further growth now succeeds from the freed pages
    assert sch.grow(ia, 4, tick=3)


def test_grow_prefers_lowest_priority_victim():
    sch = _sched(num_pages=6, policy="priority")
    hi = Request(rid=0, prompt=[1] * 6, max_new_tokens=10, priority=5)
    lo = Request(rid=1, prompt=[2] * 6, max_new_tokens=10, priority=0)
    sch.submit(hi, tick=0)
    sch.submit(lo, tick=0)
    admitted = sch.admit(0)
    i_hi = next(i for i in admitted
                if sch.slots[i].request.rid == 0)
    sch.grow(i_hi, 3, tick=1)
    # hi needs a 4th page: the LOW-priority slot is the victim even
    # though it is not the youngest admission order
    assert sch.grow(i_hi, 4, tick=2) is True
    assert [r.rid for r in sch.take_preempted()] == [1]
    sch.allocator.check_invariants()


def test_requeue_stashes_stream_and_respects_prefix_refs():
    alloc = PageAllocator(16)
    prefix = PrefixCache(alloc, 4)
    sch = ContinuousBatchingScheduler(2, 4, 4, alloc, prefix=prefix,
                                      preempt=True)
    r = Request(rid=0, prompt=[1, 2, 3, 4, 5, 6], max_new_tokens=8)
    sch.submit(r, tick=0)
    [i] = sch.admit(0)
    # simulate generated tokens, then a mid-stream requeue
    r.out_tokens = [10, 11, 12]
    req = sch.requeue_slot(i, tick=3)
    assert req is r
    assert r.resume_tokens == [1, 2, 3, 4, 5, 6, 10, 11, 12]
    assert r.preemptions == 1
    assert sch.slots[i] is None and r in sch.queue
    alloc.check_invariants()
    # re-admission: known = the resumed stream, prefix lookup skipped
    [j] = sch.admit(4)
    assert sch.slots[j].known == r.resume_tokens
    assert sch.slots[j].prefix_hit == 0


def test_prefix_flush_refuses_live_refs_then_frees_all():
    alloc = PageAllocator(16)
    pc = PrefixCache(alloc, 4)
    owner = ("req", 0)
    pages = alloc.alloc(owner, 2)
    adopted, _ = pc.register([1, 2, 3, 4, 5, 6, 7, 8], pages, owner)
    pc.acquire(adopted)
    with pytest.raises(AssertionError, match="live references"):
        pc.flush()
    pc.release(adopted)
    freed = pc.flush()
    assert freed == len(adopted)
    assert pc.nodes == {} and pc.tails == {} and pc.refs == {}
    alloc.free(owner)
    alloc.check_invariants()
    assert alloc.free_count == 15


def test_scripted_alloc_deny_times_budget(monkeypatch):
    from apex_tpu.resilience import faults

    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "serve_alloc", "kind": "deny", "times": 2}]))
    faults._cache["fired"] = {}
    sch = _sched(num_pages=16)
    r = Request(rid=0, prompt=[1] * 4, max_new_tokens=4)
    sch.submit(r, tick=0)
    assert sch.admit(0) == []        # denied (1/2)
    assert sch.admit(1) == []        # denied (2/2)
    [i] = sch.admit(2)               # budget spent: grant resumes
    assert sch.slots[i] is not None
    faults._cache["fired"] = {}


def test_finished_slot_is_never_a_victim():
    """A slot whose request already finished (awaiting next round's
    evict) must not be preempted: its pages free at the evict anyway,
    and a preempted-after-finished chain is forbidden by the
    lifecycle machine — the grower self-preempts instead."""
    sch = _sched(num_pages=6)                    # 5 allocatable
    a = Request(rid=0, prompt=[1] * 6, max_new_tokens=1)
    b = Request(rid=1, prompt=[2] * 6, max_new_tokens=10)
    sch.submit(a, tick=0)
    sch.submit(b, tick=0)
    ia, ib = sch.admit(0)
    a.out_tokens = [7]                           # a finished at prefill
    assert sch.grow(ib, 3, tick=1)               # drains the free list
    assert sch.grow(ib, 4, tick=1) is False      # pressure: b needs more
    preempted = sch.take_preempted()
    assert [r.rid for r in preempted] == [1]     # b self-preempted
    assert sch.slots[ia] is not None             # a kept its seat
    assert a.preemptions == 0
    sch.allocator.check_invariants()


# -------------------------------------------- slow overload e2e twin


@pytest.mark.slow
def test_serving_resilience_rung_e2e(tmp_path, shared_smoke_cache_dir):
    """The `serving_resilience` rung end-to-end at smoke shapes on the
    session-shared smoke compile cache: one profile_serving run under
    the rung's exact env (diurnal trace, admission bound, shedder,
    preemption) emits ONE validated ledger record whose slo block
    carries non-None shed/preempt rates, whose knobs pin all four
    resilience knobs at the resolved values, and which is check-9
    clean against the produced artifacts — the heavy overload twin of
    the fast chaos suite."""
    import subprocess
    import sys

    from tests.conftest import run_check_bench_labels

    ledger = tmp_path / "ledger.jsonl"
    env = dict(os.environ, APEX_BENCH_SMOKE="1",
               APEX_TELEMETRY_LEDGER=str(ledger),
               APEX_COMPILE_CACHE="1",
               APEX_COMPILE_CACHE_DIR=shared_smoke_cache_dir,
               APEX_SERVE_ARRIVALS="diurnal", APEX_SERVE_ADMIT="32",
               APEX_SERVE_SHED="1", APEX_SERVE_PREEMPT="1",
               PALLAS_AXON_POOL_IPS="")
    env.pop("APEX_FAULT_PLAN", None)
    env.pop("APEX_SERVE_RECOVER", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "profile_serving.py"),
         "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = ledger_mod.read_ledger(str(ledger))[-1]
    assert ledger_mod.validate_record(rec) == []
    slo = rec["slo"]
    assert slo["arrival_process"] == "diurnal"
    assert slo["shed_rate"] is not None and 0 <= slo["shed_rate"] <= 1
    assert slo["preempt_rate"] is not None \
        and 0 <= slo["preempt_rate"] <= 1
    assert slo["degraded_rounds"] is None    # recover stays off
    knobs = rec["knobs"]
    assert knobs["APEX_SERVE_ADMIT"] == "32"
    assert knobs["APEX_SERVE_SHED"] == "1"
    assert knobs["APEX_SERVE_PREEMPT"] == "1"
    assert knobs["APEX_SERVE_RECOVER"] == "0"
    # check 9 (incl. the resilience teeth) clean on the produced row
    perf = tmp_path / "PERF.md"
    perf.write_text(f"| row | 1 ms | x |\n\nledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    out = run_check_bench_labels(
        "--perf", str(perf), "--ledger", str(ledger),
        "--table", str(table))
    assert out.returncode == 0, out.stdout


# ------------------------------------------------------ window_report


def test_window_report_prints_resilience_counts(tmp_path, capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "window_report", os.path.join(REPO, "tools",
                                      "window_report.py"))
    wr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wr)
    slo = _slo(shed_rate=0.2, preempt_rate=0.05, degraded_rounds=2)
    rec = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"serving": {"tokens_per_s": 10.0, "p50_ms": 1.0,
                           "p99_ms": 2.0, "trace_id": "tr-abc",
                           "kv_pages": 8},
               "slo": slo})
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    report = wr.build_report(ledger_path=str(ledger))
    wr.print_report(report)
    out = capsys.readouterr().out
    assert "shed=20%" in out
    assert "preempt=5%" in out
    assert "degraded_rounds=2" in out
    # the --json line carries the whole slo dict wholesale
    assert report["ledger"]["serving"][0]["slo"]["shed_rate"] == 0.2
