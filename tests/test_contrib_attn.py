"""Contrib attention + transducer + sparsity tests.

Ports: apex/contrib/test/multihead_attn (fast attn vs
torch.nn.MultiheadAttention parity → here vs a naive jnp reference),
test/fmha (varlen packed attention vs per-sequence dense attention),
test/transducer (joint + loss vs the pure-loop _transducer_ref pattern),
test/sparsity (2:4 mask validity + pruned-stays-pruned through training).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.contrib.fmha import fmha_varlen
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    transducer_joint,
    transducer_loss,
)
from apex_tpu.optimizers.fused_adam import fused_adam


# --------------------------- multihead attention ---------------------------

def _naive_mha(x_q, x_kv, wq, wk, wv, wo, heads):
    """Plain numpy MHA, [s, b, e] layout, no bias."""
    sq, b, e = x_q.shape
    d = e // heads
    q = x_q @ wq
    k = x_kv @ wk
    v = x_kv @ wv
    q = q.reshape(sq, b * heads, d).transpose(1, 0, 2) / np.sqrt(d)
    k = k.reshape(x_kv.shape[0], b * heads, d).transpose(1, 0, 2)
    v = v.reshape(x_kv.shape[0], b * heads, d).transpose(1, 0, 2)
    s = q @ k.transpose(0, 2, 1)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ctx = (p @ v).transpose(1, 0, 2).reshape(sq, b, e)
    return ctx @ wo


def test_self_multihead_attn_matches_naive():
    rs = np.random.RandomState(0)
    s, b, e, h = 8, 2, 16, 4
    x = jnp.asarray(rs.randn(s, b, e), jnp.float32)
    mod = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    variables = mod.init(jax.random.PRNGKey(0), x, x, x)
    out, _ = mod.apply(variables, x, x, x, is_training=False)

    win = np.asarray(variables["params"]["in_proj"]["kernel"])  # [e, 3e]
    wq, wk, wv = win[:, :e], win[:, e:2 * e], win[:, 2 * e:]
    wo = np.asarray(variables["params"]["out_proj"]["kernel"])
    want = _naive_mha(np.asarray(x), np.asarray(x), wq, wk, wv, wo, h)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)


def test_self_multihead_attn_fast_matches_default_with_grads():
    """impl="fast" (flash route for the unmasked/no-dropout case) and
    impl="default" (materialized scores) are the same math — values and
    input grads must agree."""
    rs = np.random.RandomState(2)
    s, b, e, h = 8, 2, 16, 4
    x = jnp.asarray(rs.randn(s, b, e), jnp.float32)
    fast = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="fast")
    slow = SelfMultiheadAttn(embed_dim=e, num_heads=h, impl="default")
    variables = fast.init(jax.random.PRNGKey(0), x, x, x)

    def loss(mod, x):
        out, _ = mod.apply(variables, x, x, x, is_training=False)
        return jnp.sum(out ** 2)

    lf, gf = jax.value_and_grad(lambda x: loss(fast, x))(x)
    ls, gs = jax.value_and_grad(lambda x: loss(slow, x))(x)
    np.testing.assert_allclose(float(lf), float(ls), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gs), atol=1e-4)


def test_self_multihead_attn_norm_add_residual():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 2, 8), jnp.float32)
    mod = SelfMultiheadAttn(embed_dim=8, num_heads=2, include_norm_add=True)
    variables = mod.init(jax.random.PRNGKey(0), x, x, x)
    out, _ = mod.apply(variables, x, x, x, is_training=False)
    # residual path: zeroing attention output params must give out == x
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, variables)
    out0, _ = mod.apply(zeroed, x, x, x, is_training=False)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(x), atol=1e-6)
    assert not np.allclose(np.asarray(out), np.asarray(x))


def test_self_attn_additive_and_padding_masks():
    rs = np.random.RandomState(2)
    s, b, e = 6, 2, 8
    x = jnp.asarray(rs.randn(s, b, e), jnp.float32)
    mod = SelfMultiheadAttn(embed_dim=e, num_heads=2, mask_additive=True,
                            bias=True)
    variables = mod.init(jax.random.PRNGKey(0), x, x, x)
    add_mask = jnp.where(
        jnp.triu(jnp.ones((s, s), bool), 1), -1e9, 0.0)[None]
    out_m, _ = mod.apply(variables, x, x, x, attn_mask=add_mask,
                         is_training=False)
    assert np.isfinite(np.asarray(out_m)).all()
    # padding mask: masking key 5 must change outputs
    kp = jnp.zeros((b, s), bool).at[:, 5].set(True)
    out_kp, _ = mod.apply(variables, x, x, x, key_padding_mask=kp,
                          is_training=False)
    out_plain, _ = mod.apply(variables, x, x, x, is_training=False)
    assert not np.allclose(np.asarray(out_kp), np.asarray(out_plain))


def test_encdec_multihead_attn_shapes_and_grad():
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(5, 2, 8), jnp.float32)
    kv = jnp.asarray(rs.randn(7, 2, 8), jnp.float32)
    mod = EncdecMultiheadAttn(embed_dim=8, num_heads=2)
    variables = mod.init(jax.random.PRNGKey(0), q, kv)
    out, _ = mod.apply(variables, q, kv, is_training=False)
    assert out.shape == (5, 2, 8)

    def loss(v):
        o, _ = mod.apply(v, q, kv, is_training=False)
        return jnp.sum(o ** 2)

    g = jax.grad(loss)(variables)
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))


# ------------------------------- fmha --------------------------------------

def test_fmha_varlen_matches_per_sequence_attention():
    rs = np.random.RandomState(4)
    h, d = 2, 8
    seqlens = [5, 3, 7]
    cu = np.concatenate([[0], np.cumsum(seqlens)]).astype(np.int32)
    total = cu[-1]
    qkv = rs.randn(total, 3, h, d).astype(np.float32)

    out = fmha_varlen(jnp.asarray(qkv), jnp.asarray(cu),
                      is_training=False)
    out = np.asarray(out)

    for i, sl in enumerate(seqlens):
        s0, s1 = cu[i], cu[i + 1]
        q, k, v = qkv[s0:s1, 0], qkv[s0:s1, 1], qkv[s0:s1, 2]
        for head in range(h):
            s = (q[:, head] / np.sqrt(d)) @ k[:, head].T
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want = p @ v[:, head]
            np.testing.assert_allclose(out[s0:s1, head], want, atol=1e-4)


def test_fmha_padding_tokens_isolated():
    """Tokens past cu_seqlens[-1] (padding) must not influence any real
    sequence (regression: padding used to join the last segment)."""
    rs = np.random.RandomState(11)
    cu = jnp.asarray([0, 3, 5], jnp.int32)  # 5 real tokens, 3 padding
    qkv = rs.randn(8, 3, 2, 4).astype(np.float32)
    out1 = np.asarray(fmha_varlen(jnp.asarray(qkv), cu, is_training=False))
    qkv2 = qkv.copy()
    qkv2[5:] = 1e6  # garbage in the padding region
    out2 = np.asarray(fmha_varlen(jnp.asarray(qkv2), cu, is_training=False))
    np.testing.assert_allclose(out1[:5], out2[:5], atol=1e-5)
    assert np.isfinite(out2).all()


def test_fmha_no_cross_sequence_leakage():
    """Changing sequence 2's content must not affect sequence 1's output."""
    rs = np.random.RandomState(5)
    cu = jnp.asarray([0, 4, 8], jnp.int32)
    qkv = rs.randn(8, 3, 2, 4).astype(np.float32)
    out1 = np.asarray(fmha_varlen(jnp.asarray(qkv), cu, is_training=False))
    qkv2 = qkv.copy()
    qkv2[4:] += 100.0
    out2 = np.asarray(fmha_varlen(jnp.asarray(qkv2), cu, is_training=False))
    np.testing.assert_allclose(out1[:4], out2[:4], atol=1e-5)


def test_fmha_dropout_routes_fused_and_is_isolated():
    """Dropout training at lane-aligned totals takes the fused VMEM-row
    kernel (no [total, total] HBM probs). Semantics under dropout:
    deterministic per rng key, rng-sensitive, cross-sequence isolated."""
    from apex_tpu.ops import attention_pallas

    rs = np.random.RandomState(12)
    h, d, total = 2, 32, 256
    cu = jnp.asarray([0, 100, 200, 256], jnp.int32)
    qkv = jnp.asarray(rs.randn(total, 3, h, d), jnp.float32)
    assert attention_pallas.supported(total, total, d)  # fused path taken

    key = jax.random.PRNGKey(0)
    a1 = np.asarray(fmha_varlen(qkv, cu, p_dropout=0.2, rng=key))
    a2 = np.asarray(fmha_varlen(qkv, cu, p_dropout=0.2, rng=key))
    b1 = np.asarray(fmha_varlen(qkv, cu, p_dropout=0.2,
                                rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(a1, a2)
    assert np.abs(a1 - b1).max() > 1e-4
    # eval path unaffected by the rng
    ev = np.asarray(fmha_varlen(qkv, cu, p_dropout=0.2, is_training=False))
    assert np.abs(a1 - ev).max() > 1e-4  # dropout actually drops

    # isolation holds under dropout: perturbing sequence 3 leaves
    # sequences 1-2 (tokens < 200) unchanged
    qkv2 = np.asarray(qkv).copy()
    qkv2[200:] += 100.0
    c1 = np.asarray(fmha_varlen(jnp.asarray(qkv2), cu, p_dropout=0.2,
                                rng=key))
    np.testing.assert_allclose(a1[:200], c1[:200], atol=1e-5)


def test_fmha_dropout_grads_finite_and_match_masked_dense():
    """Grad flows through the fused dropout path; parity against the
    dense reference using the kernel's own replayed mask."""
    from apex_tpu.ops import attention_pallas as ap

    rs = np.random.RandomState(13)
    h, d, total, p = 2, 32, 128, 0.3
    cu = jnp.asarray([0, 60, 128], jnp.int32)
    qkv = jnp.asarray(rs.randn(total, 3, h, d), jnp.float32)
    key = jax.random.PRNGKey(3)

    def loss(qkv):
        return jnp.sum(fmha_varlen(qkv, cu, p_dropout=p, rng=key) ** 2)

    g = jax.grad(loss)(qkv)
    assert np.isfinite(np.asarray(g)).all()

    # dense reference with the identical hash mask
    seed = jax.random.randint(key, (1, 1), -2**31, 2**31 - 1, jnp.int32)
    seg = np.repeat([0, 1], [60, 68])
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    same = (seg[:, None] == seg[None, :])
    out = np.asarray(fmha_varlen(qkv, cu, p_dropout=p, rng=key))
    for head in range(h):
        ms = np.asarray(ap._dropout_mscale(
            seed[0, 0], jnp.int32(0), jnp.int32(head), 0, total, total,
            p, h))
        s = (np.asarray(q[:, head]) / np.sqrt(d)) @ np.asarray(k[:, head]).T
        s = np.where(same, s, -1e30)
        pr = np.exp(s - s.max(-1, keepdims=True))
        pr /= pr.sum(-1, keepdims=True)
        want = (pr * ms) @ np.asarray(v[:, head])
        np.testing.assert_allclose(out[:, head], want, atol=1e-4)


# ----------------------------- transducer ----------------------------------

def test_transducer_joint_dense_and_packed():
    rs = np.random.RandomState(6)
    B, T, U, H = 2, 4, 3, 5
    f = jnp.asarray(rs.randn(B, T, H), jnp.float32)
    g = jnp.asarray(rs.randn(B, U, H), jnp.float32)
    f_len = jnp.asarray([4, 2])
    g_len = jnp.asarray([3, 2])
    out = transducer_joint(f, g, f_len, g_len)
    want = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
    np.testing.assert_allclose(np.asarray(out)[0], want[0], atol=1e-6)
    # don't-care region zeroed
    np.testing.assert_array_equal(np.asarray(out)[1, 2:], 0)
    np.testing.assert_array_equal(np.asarray(out)[1, :, 2:], 0)

    # packed form
    batch_offset = jnp.cumsum(f_len * g_len)
    packed_batch = int(batch_offset[-1])
    packed = transducer_joint(f, g, f_len, g_len, pack_output=True,
                              batch_offset=batch_offset,
                              packed_batch=packed_batch)
    assert packed.shape == (packed_batch, H)
    # row for (b=1, t=1, u=1): offset 12 + 1*2 + 1
    np.testing.assert_allclose(np.asarray(packed)[12 + 3],
                               want[1, 1, 1], atol=1e-6)


def _transducer_loss_ref(x, label, f_len, y_len, blank):
    """Pure-loop alpha recurrence (the reference test's
    _transducer_ref.py pattern)."""
    x = np.asarray(x, np.float64)
    lp = x - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(
        -1, keepdims=True)) - x.max(-1, keepdims=True)
    T, U, _ = lp.shape
    alpha = np.full((T, U), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0 and u <= y_len:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, label[u - 1]])
            if cands and not (t == 0 and u == 0):
                alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[f_len - 1, y_len] + lp[f_len - 1, y_len, blank])


def test_transducer_loss_matches_reference_loop():
    rs = np.random.RandomState(7)
    B, T, U, V = 3, 6, 4, 8
    x = rs.randn(B, T, U, V).astype(np.float32)
    label = rs.randint(1, V, (B, U - 1))
    f_len = np.asarray([6, 4, 5])
    y_len = np.asarray([3, 2, 1])
    got = np.asarray(transducer_loss(
        jnp.asarray(x), jnp.asarray(label), jnp.asarray(f_len),
        jnp.asarray(y_len), blank_idx=0))
    for b in range(B):
        want = _transducer_loss_ref(x[b], label[b], f_len[b], y_len[b], 0)
        np.testing.assert_allclose(got[b], want, rtol=1e-4)


@pytest.mark.slow  # grad-of-associative-scan compile; the loss-value
# reference-loop parity test stays fast
def test_transducer_loss_grad_finite():
    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(2, 4, 3, 5), jnp.float32)
    label = jnp.asarray(rs.randint(1, 5, (2, 2)))
    g = jax.grad(lambda x_: jnp.sum(transducer_loss(
        x_, label, jnp.asarray([4, 3]), jnp.asarray([2, 1]))))(x)
    assert np.isfinite(np.asarray(g)).all()


# ------------------------------ sparsity -----------------------------------

def test_create_mask_m4n2():
    rs = np.random.RandomState(9)
    w = jnp.asarray(rs.randn(8, 16), jnp.float32)
    mask = np.asarray(create_mask(w, "m4n2_1d"))
    groups = mask.reshape(-1, 4)
    np.testing.assert_array_equal(groups.sum(-1), 2)
    # kept entries are the top-2 |w| per group
    wg = np.abs(np.asarray(w)).reshape(-1, 4)
    for i in range(wg.shape[0]):
        kept = set(np.nonzero(groups[i])[0])
        top2 = set(np.argsort(wg[i])[-2:])
        assert kept == top2


def test_asp_prune_and_stay_pruned():
    rs = np.random.RandomState(10)
    params = {"dense": {"kernel": jnp.asarray(rs.randn(8, 8), jnp.float32),
                        "bias": jnp.asarray(rs.randn(8), jnp.float32)}}
    asp = ASP()
    params2, tx = asp.prune_trained_model(params, fused_adam(
        learning_rate=0.1))
    mask = np.asarray(asp.masks["dense"]["kernel"])
    assert mask.sum() == mask.size // 2
    # bias not eligible → mask of ones
    np.testing.assert_array_equal(
        np.asarray(asp.masks["dense"]["bias"]), 1)

    state = tx.init(params2)
    for _ in range(3):
        grads = jax.tree_util.tree_map(
            lambda p: jnp.ones_like(p), params2)
        updates, state = tx.update(grads, state, params2)
        params2 = jax.tree_util.tree_map(lambda p, u: p + u, params2,
                                         updates)
    w = np.asarray(params2["dense"]["kernel"])
    np.testing.assert_array_equal(w[mask == 0], 0)
    assert (np.asarray(params2["dense"]["bias"]) != 0).all()
