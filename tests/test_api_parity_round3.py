"""Round-3 API-surface parity additions: parallel_state split predicates
and group getters, 1D chunk split/gather, unwrap_model, HaloPadder,
MaskSoftmaxDropout, standalone-model helpers (ports of the reference
surfaces listed in each test's docstring)."""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.utils import (
    gather_split_1d_tensor,
    split_tensor_into_1d_equal_chunks,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    param_is_not_shared,
    unwrap_model,
)


@pytest.fixture
def state_guard():
    yield
    ps.destroy_model_parallel()


def test_parallel_state_split_predicates(state_guard):
    """apex/transformer/parallel_state.py:423-460: encoder/decoder stage
    predicates against a (pp=4, split=2) topology, evaluated per-stage
    on the 8-device mesh."""
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2)

    def probe():
        return (
            jnp.int32(ps.is_pipeline_stage_before_split()),
            jnp.int32(ps.is_pipeline_stage_after_split()),
            jnp.int32(ps.is_pipeline_stage_at_split()),
            jnp.int32(ps.is_rank_in_embedding_group()),
            jnp.int32(ps.is_rank_in_position_embedding_group()),
            jnp.int32(ps.is_rank_in_encoder_relative_position_embedding_group()),
            jnp.int32(ps.is_rank_in_decoder_relative_position_embedding_group()),
            ps.get_pipeline_model_parallel_next_rank(),
            ps.get_pipeline_model_parallel_prev_rank(),
        )

    outs = shard_map(
        lambda: tuple(jnp.reshape(o, (1, 1, 1)) for o in probe()),
        mesh=mesh, in_specs=(), out_specs=P("pp", "dp", "tp"),
        check_vma=False)()
    # reduce over the (dp, tp) replicas — all equal per stage
    by_stage = [np.asarray(o)[:, 0, 0] for o in outs]
    before, after, at, emb, pos, enc_rel, dec_rel, nxt, prv = by_stage
    np.testing.assert_array_equal(before, [1, 1, 0, 0])   # rank < 2
    np.testing.assert_array_equal(after, [0, 0, 1, 1])    # rank >= 2
    np.testing.assert_array_equal(at, [0, 1, 0, 0])       # rank 1 only
    np.testing.assert_array_equal(emb, [1, 0, 1, 1])      # {0, split, last}
    # under an interleaved schedule, first/last members only count on
    # their first/last virtual chunk (reference parallel_state.py:395)
    ps._STATE.virtual_pipeline_model_parallel_size = 2
    ps.set_virtual_pipeline_model_parallel_rank(1)
    emb_v = np.asarray(shard_map(
        lambda: jnp.reshape(jnp.int32(ps.is_rank_in_embedding_group()),
                            (1, 1, 1)),
        mesh=mesh, in_specs=(), out_specs=P("pp", "dp", "tp"),
        check_vma=False)())[:, 0, 0]
    np.testing.assert_array_equal(emb_v, [0, 0, 1, 1])  # chunk 1: last+split
    ps.set_virtual_pipeline_model_parallel_rank(0)
    emb_v0 = np.asarray(shard_map(
        lambda: jnp.reshape(jnp.int32(ps.is_rank_in_embedding_group()),
                            (1, 1, 1)),
        mesh=mesh, in_specs=(), out_specs=P("pp", "dp", "tp"),
        check_vma=False)())[:, 0, 0]
    np.testing.assert_array_equal(emb_v0, [1, 0, 1, 0])  # chunk 0: first+split
    np.testing.assert_array_equal(pos, [1, 0, 1, 0])      # {0, split}
    np.testing.assert_array_equal(enc_rel, [1, 1, 0, 0])
    np.testing.assert_array_equal(dec_rel, [0, 0, 1, 1])
    np.testing.assert_array_equal(nxt, [1, 2, 3, 0])      # ring-wrapped
    np.testing.assert_array_equal(prv, [3, 0, 1, 2])


def test_parallel_state_degenerate_and_host_getters(state_guard):
    """No-split / pp=1 short-circuits return concrete values host-side
    (reference short-circuits, parallel_state.py:426-447), and the
    bookkeeping getters round-trip."""
    assert ps.is_unitialized()
    assert ps.get_rank_info() == (0, 0, 0, 0)
    ps.initialize_model_parallel(tensor_model_parallel_size_=8)
    assert not ps.is_unitialized()
    assert ps.is_pipeline_stage_before_split() is True
    assert ps.is_pipeline_stage_after_split() is True
    assert ps.is_pipeline_stage_at_split() is True  # reference composition
    assert ps.is_rank_in_embedding_group() is True  # pp == 1
    assert ps.get_data_parallel_src_rank() == 0
    for group_fn in (ps.get_position_embedding_group,
                     ps.get_encoder_relative_position_embedding_group,
                     ps.get_decoder_relative_position_embedding_group):
        assert group_fn() == ps.PIPELINE_AXIS


def test_split_gather_1d_round_trip(state_guard):
    """apex/transformer/utils.py:21-48: per-rank equal 1D chunks and the
    gathering inverse."""
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    x = jnp.arange(8 * 6, dtype=jnp.float32).reshape(8, 6)

    def chunk_and_gather(t):
        chunk = split_tensor_into_1d_equal_chunks(t)
        return chunk, gather_split_1d_tensor(chunk)

    chunks, gathered = shard_map(
        chunk_and_gather, mesh=mesh, in_specs=(P(),),
        out_specs=(P("tp"), P()), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(chunks), np.arange(48.0))
    # gather reassembles the full flat tensor on every rank
    np.testing.assert_allclose(np.asarray(gathered), np.arange(48.0))


def test_unwrap_model_and_shared_params():
    """apex/transformer/pipeline_parallel/utils.py:181-196."""
    class Wrapper:
        def __init__(self, module):
            self.module = module

    assert unwrap_model(3) == 3
    assert unwrap_model([1, 2]) == [1, 2]
    inner = object()
    assert unwrap_model(Wrapper(Wrapper(inner)),
                        module_instances=(Wrapper,)) is inner
    assert param_is_not_shared(jnp.zeros(3))

    class SharedParam:
        shared = True

    assert not param_is_not_shared(SharedParam())


def test_mask_softmax_dropout_matches_manual():
    """apex/contrib/multihead_attn/mask_softmax_dropout_func.py:6-60:
    additive and boolean mask paths, eval == plain softmax, train
    dropout keeps the inverted-scaling expectation."""
    from apex_tpu.contrib.multihead_attn import mask_softmax_dropout

    rs = np.random.RandomState(0)
    heads, b, sq, sk = 2, 3, 4, 5
    x = jnp.asarray(rs.randn(b * heads, sq, sk), jnp.float32)

    # eval, no mask == softmax
    out = mask_softmax_dropout(False, heads, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jax.nn.softmax(x, -1)), rtol=1e-6)

    # additive mask shifts scores before the softmax
    add_mask = jnp.asarray(rs.randn(b * heads, sq, sk), jnp.float32)
    out = mask_softmax_dropout(False, heads, x, add_mask,
                               mask_additive=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jax.nn.softmax(x + add_mask, -1)),
        rtol=1e-6)

    # boolean mask zeroes the masked keys
    bool_mask = jnp.zeros((b * heads, sq, sk), bool).at[:, :, -1].set(True)
    out = mask_softmax_dropout(False, heads, x, bool_mask)
    assert np.asarray(out)[..., -1].max() < 1e-4

    # fully-masked rows emit all-zeros (reference kernel semantics,
    # same as FusedScaleMaskSoftmax), not uniform attention
    full_mask = bool_mask.at[0].set(True)
    out = mask_softmax_dropout(False, heads, x, full_mask)
    np.testing.assert_array_equal(np.asarray(out)[0], 0.0)

    # train-time dropout: zeros appear, survivors are scaled up
    out = mask_softmax_dropout(True, heads, x, dropout_prob=0.5,
                               dropout_rng=jax.random.PRNGKey(0))
    o = np.asarray(out)
    assert (o == 0).any()
    ref = np.asarray(jax.nn.softmax(x, -1))
    nz = o != 0
    np.testing.assert_allclose(o[nz], (ref * 2)[nz], rtol=1e-5)

    # missing rng under training dropout is loud
    with pytest.raises(ValueError, match="dropout_rng"):
        mask_softmax_dropout(True, heads, x, dropout_prob=0.5)


def test_halo_padder_pads_from_neighbors():
    """apex/contrib/bottleneck/halo_exchangers.py:118-165."""
    from apex_tpu.contrib.bottleneck import (HaloExchangerSendRecv,
                                             HaloPadder)

    mesh = Mesh(np.array(jax.devices()[:4]), ("spatial",))
    y = jnp.arange(4 * 2 * 3 * 2, dtype=jnp.float32).reshape(4, 2, 3, 2)
    padder = HaloPadder(HaloExchangerSendRecv("spatial", 4))
    out = shard_map(lambda t: padder(t, 1), mesh=mesh,
                    in_specs=(P("spatial"),), out_specs=P("spatial"),
                    check_vma=False)(y)
    out = np.asarray(out).reshape(4, 4, 3, 2)
    yn = np.asarray(y)
    np.testing.assert_allclose(out[:, 1:-1], yn)
    np.testing.assert_allclose(out[1, 0], yn[0, -1])
    np.testing.assert_allclose(out[2, -1], yn[3, 0])
    np.testing.assert_array_equal(out[0, 0], 0)
    padder.wait()  # no-op parity

    # NCHW path (the reference's explicit_nhwc=False): H is dim 2
    y_nchw = jnp.transpose(y, (0, 3, 1, 2))
    out2 = shard_map(lambda t: padder(t, 1, explicit_nhwc=False),
                     mesh=mesh, in_specs=(P("spatial"),),
                     out_specs=P("spatial"), check_vma=False)(y_nchw)
    np.testing.assert_allclose(
        np.asarray(out2).reshape(4, 2, 4, 3),
        np.transpose(out, (0, 3, 1, 2)))


def test_standalone_helpers():
    """standalone_transformer_lm.py:130-151 + :1038-1096."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        get_linear_layer, get_num_layers, init_method_normal)

    layer = get_linear_layer(4, 7, init_method_normal(0.02))
    params = layer.init(jax.random.PRNGKey(0), jnp.ones((2, 4)))
    assert params["params"]["kernel"].shape == (4, 7)
    np.testing.assert_array_equal(np.asarray(params["params"]["bias"]), 0)

    class Args:
        num_layers = 12
        pipeline_model_parallel_size = 4
        transformer_pipeline_model_parallel_size = 4
        pipeline_model_parallel_split_rank = None
        standalone_embedding_stage = False

    assert get_num_layers(Args, False) == 3
    Args.pipeline_model_parallel_size = 1
    assert get_num_layers(Args, False) == 12

    # encoder-decoder split: 12 layers over (2 enc, 2 dec) ranks
    Args.pipeline_model_parallel_size = 4
    Args.pipeline_model_parallel_split_rank = 2
    assert get_num_layers(Args, True, before_split=True) == 6
    assert get_num_layers(Args, True, before_split=False) == 6

    # standalone embedding stage: rank 0 carries no transformer layers
    Args.pipeline_model_parallel_split_rank = None
    Args.standalone_embedding_stage = True
    Args.transformer_pipeline_model_parallel_size = 3
    assert get_num_layers(Args, False, pipeline_rank=0) == 0
    assert get_num_layers(Args, False, pipeline_rank=1) == 4


def test_amp_legacy_handles():
    """apex/amp/handle.py:22-218: AmpHandle.scale_loss yields the scaled
    loss against the live scaler state; NoOpHandle passes through."""
    from apex_tpu import amp
    from apex_tpu.amp import AmpHandle, NoOpHandle
    from apex_tpu.optimizers.fused_adam import fused_adam

    params = {"w": jnp.ones(3, jnp.float32)}
    params, opt = amp.initialize(params, fused_adam(1e-2), opt_level="O2")
    state = opt.init(params)

    handle = AmpHandle(opt, state)
    # reference surface: is_active is a METHOD (handle.py:179)
    assert handle.is_active() and handle.has_cache
    with handle.scale_loss(jnp.float32(2.0)) as scaled:
        np.testing.assert_allclose(float(scaled),
                                   2.0 * float(state.scalers[0].loss_scale))
    assert handle.wrap_optimizer(opt) is opt
    handle._deactivate()
    with handle.scale_loss(jnp.float32(2.0)) as scaled:
        assert float(scaled) == 2.0

    noop = NoOpHandle()
    assert not noop.is_active()
    noop._clear_cache()
    with noop._disable_casts():
        pass
    with noop.scale_loss(jnp.float32(5.0)) as scaled:
        assert float(scaled) == 5.0

    # a bare active handle refuses to silently skip scaling
    with pytest.raises(RuntimeError, match="no amp optimizer"):
        with AmpHandle().scale_loss(jnp.float32(1.0)):
            pass
    # per-call state override + threading via update_state
    bare = AmpHandle(opt)
    with pytest.raises(RuntimeError, match="no amp state"):
        with bare.scale_loss(jnp.float32(1.0)):
            pass
    bare.update_state(state)
    with bare.scale_loss(jnp.float32(1.0)) as scaled:
        np.testing.assert_allclose(float(scaled),
                                   float(state.scalers[0].loss_scale))


def test_tp_attribute_helpers():
    """apex/transformer/tensor_parallel/layers.py:46-100."""
    from apex_tpu.transformer.tensor_parallel.layers import (
        copy_tensor_model_parallel_attributes,
        param_is_not_tensor_parallel_duplicate,
        set_defaults_if_not_set_tensor_model_parallel_attributes,
        set_tensor_model_parallel_attributes,
    )

    class P:
        pass

    p = P()
    set_tensor_model_parallel_attributes(p, True, 0, 1)
    assert p.tensor_model_parallel and p.partition_dim == 0
    q = P()
    copy_tensor_model_parallel_attributes(q, p)
    assert q.tensor_model_parallel and q.partition_stride == 1
    r = P()
    set_defaults_if_not_set_tensor_model_parallel_attributes(r)
    assert r.tensor_model_parallel is False and r.partition_dim == -1
    # sharded params and plain leaves count once; replicated attr-tagged
    # params only on rank 0
    assert param_is_not_tensor_parallel_duplicate(p)
    assert param_is_not_tensor_parallel_duplicate(jnp.ones(2))
    assert param_is_not_tensor_parallel_duplicate(r, rank=0)
    assert not param_is_not_tensor_parallel_duplicate(r, rank=1)
    # attribute-less leaf: defaults are implied, no crash
    set_defaults_if_not_set_tensor_model_parallel_attributes(jnp.ones(2))


def test_functional_tp_linear_matches_module():
    """layers.py:272-434: the functional linear equals x @ w^T + b and
    its tp-input grad is psummed (via copy_to region)."""
    from apex_tpu.transformer.tensor_parallel.layers import (
        linear_with_grad_accumulation_and_async_allreduce as tp_linear)

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(4, 6), jnp.float32)
    w = jnp.asarray(rs.randn(2, 5, 6), jnp.float32)  # per-rank shard
    b = jnp.asarray(rs.randn(5), jnp.float32)

    def run(w_shard):
        y = tp_linear(x, w_shard[0], b, async_grad_allreduce=True)
        return y

    y = shard_map(run, mesh=mesh, in_specs=(P("tp"),),
                  out_specs=P("tp"), check_vma=False)(w)
    y = np.asarray(y).reshape(2, 4, 5)
    for r in range(2):
        np.testing.assert_allclose(
            y[r], np.asarray(x) @ np.asarray(w[r]).T + np.asarray(b),
            rtol=2e-5, atol=2e-5)


def test_misc_compat_surfaces():
    """toRNNBackend, mem-buff registry, FusedSGD momenta, FutureTensor,
    schedule compat shims, named mask patterns."""
    from apex_tpu.RNN.models import toRNNBackend
    from apex_tpu.RNN.rnn_backend import RNN
    from apex_tpu.contrib.sparsity.sparse_masklib import (create_mask,
                                                          m4n2_1d,
                                                          mn_1d_best)
    from apex_tpu.optimizers.fused_sgd import fused_sgd, get_momentums
    from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
        FutureTensor)
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        custom_backward, free_output_tensor)
    from apex_tpu.transformer.tensor_parallel import memory as tp_memory

    m = toRNNBackend("GRU", 4, 8, num_layers=2, bidirectional=True)
    assert isinstance(m, RNN) and m.bidirectional

    buf = tp_memory.allocate_mem_buff("parity_test", 64, jnp.float32, False)
    assert tp_memory.get_mem_buff("parity_test") is buf
    with pytest.raises(AssertionError, match="already allocated"):
        tp_memory.allocate_mem_buff("parity_test", 64, jnp.float32, False)

    tx = fused_sgd(1e-2, momentum=0.9)
    bufs = get_momentums(tx.init({"w": jnp.ones(3)}))
    assert len(bufs) == 1 and bufs[0].shape == (3,)

    ft = FutureTensor(jnp.ones(2))
    np.testing.assert_array_equal(np.asarray(ft.get()), 1.0)
    waited = []
    ft = FutureTensor(jnp.ones(2), waitfunc=lambda: waited.append(1))
    ft.get(); ft.get()
    assert waited == [1]  # wait fires once

    free_output_tensor([jnp.ones(2)], True)  # no-op
    _, vjp = jax.vjp(lambda x: 3.0 * x, jnp.ones(2))
    (g,) = custom_backward(vjp, jnp.ones(2))
    np.testing.assert_array_equal(np.asarray(g), 3.0)
    with pytest.raises(TypeError, match="vjp"):
        custom_backward(jnp.ones(2), jnp.ones(2))

    w = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    np.testing.assert_array_equal(np.asarray(m4n2_1d(w)),
                                  np.asarray(create_mask(w, "m4n2_1d")))
    np.testing.assert_array_equal(np.asarray(mn_1d_best(w, 4, 2)),
                                  np.asarray(m4n2_1d(w)))


@pytest.mark.slow  # 6s of tiny-surface compiles; behavior-parity
# coverage retained in the slow tier, name-parity in check_api_parity
def test_testing_commons(state_guard):
    """apex/transformer/testing/commons.py:83-296: IdentityLayer,
    ToyParallelMLP, set_random_seed, initialize_distributed,
    print_separator; plus the standalone-model building blocks extracted
    with reference names (NoopTransformerLayer, Pooler,
    bias_dropout_add, bert mask/position helpers)."""
    from apex_tpu.transformer.testing import (
        IdentityLayer, NoopTransformerLayer, Pooler, ToyParallelMLP,
        bert_extended_attention_mask, bert_position_ids,
        get_bias_dropout_add, initialize_distributed, print_separator,
        set_random_seed)

    key = set_random_seed(123)
    mesh = initialize_distributed()
    assert mesh is ps.get_mesh()
    print_separator("commons parity")

    il = IdentityLayer(size=(4,))
    v = il.init(key)
    np.testing.assert_array_equal(np.asarray(il.apply(v)),
                                  np.asarray(v["params"]["weight"]))

    mlp = ToyParallelMLP(hidden_size=8)
    x = jnp.ones((4, 2, 8), jnp.float32)
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("tp",))

    def run(x):
        variables = mlp.init(jax.random.PRNGKey(0), x)
        return mlp.apply(variables, x)

    y = shard_map(run, mesh=mesh2, in_specs=(P(),), out_specs=P(),
                  check_vma=False)(x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()

    h = jnp.ones((3, 2, 8))
    assert (NoopTransformerLayer().apply({}, h) == h).all()

    # eval-mode bias_dropout_add: residual + (x + bias)
    f = get_bias_dropout_add(False)
    np.testing.assert_allclose(
        np.asarray(f(h, jnp.zeros(8), h, 0.1)), 2 * np.asarray(h))
    # training without an rng is loud
    with pytest.raises(ValueError, match="rng"):
        get_bias_dropout_add(True)(h, jnp.zeros(8), h, 0.5)

    ids = jnp.zeros((2, 5), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(bert_position_ids(ids)),
        np.broadcast_to(np.arange(5), (2, 5)))
    em = bert_extended_attention_mask(
        jnp.asarray([[1, 1, 0]], jnp.int32))
    assert em.shape == (1, 1, 3, 3)
    assert not em[0, 0, 0, 1] and em[0, 0, 0, 2]  # pad key masked

    # pooler: tanh(dense(first token))
    pooler = Pooler(8)
    hv = jnp.asarray(np.random.RandomState(0).randn(3, 2, 8), jnp.float32)
    pv = pooler.init(jax.random.PRNGKey(0), hv)
    out = pooler.apply(pv, hv)
    assert out.shape == (2, 8)
    assert np.abs(np.asarray(out)).max() <= 1.0


@pytest.mark.slow
def test_decoder_layer_cross_attention_path():
    """The LayerType.decoder branch (cross-attention + its
    bias_dropout_add) — previously uncovered."""
    from apex_tpu.transformer.enums import LayerType
    from apex_tpu.transformer.testing import (ParallelTransformerLayer,
                                              TransformerConfig)

    cfg = TransformerConfig(hidden_size=16, num_layers=1,
                            num_attention_heads=2, vocab_size=32,
                            max_position_embeddings=8,
                            hidden_dropout=0.0, attention_dropout=0.0)
    layer = ParallelTransformerLayer(cfg, layer_type=LayerType.decoder)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    s, b = 6, 2
    rs = np.random.RandomState(0)
    hidden = jnp.asarray(rs.randn(s, b, 16), jnp.float32)
    enc_out = jnp.asarray(rs.randn(s, b, 16), jnp.float32)
    causal = jnp.triu(jnp.ones((s, s), bool), 1)[None, None]
    no_mask = jnp.zeros((1, 1, s, s), bool)

    def run(hidden, enc_out):
        variables = layer.init(jax.random.PRNGKey(0), hidden, causal,
                               enc_out, no_mask, True)
        return layer.apply(variables, hidden, causal, enc_out, no_mask,
                           True)

    out = shard_map(run, mesh=mesh, in_specs=(P(), P()),
                    out_specs=P(), check_vma=False)(hidden, enc_out)
    assert out.shape == (s, b, 16)
    assert np.isfinite(np.asarray(out)).all()


def test_softmax_function_class_surface():
    """apex/transformer/functional/fused_softmax.py:21-125: the
    autograd-Function class names dispatch to the same math as the
    functional forms."""
    from apex_tpu.transformer.functional import (
        GenericScaledMaskedSoftmax, ScaledMaskedSoftmax,
        ScaledUpperTriangMaskedSoftmax, scaled_masked_softmax,
        scaled_upper_triang_masked_softmax)

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(3, 4, 4), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(ScaledUpperTriangMaskedSoftmax.apply(x, 0.5)),
        np.asarray(scaled_upper_triang_masked_softmax(x, 0.5)))
    x4 = x[:, None]
    mask = jnp.zeros_like(x4, bool).at[..., -1].set(True)
    np.testing.assert_array_equal(
        np.asarray(ScaledMaskedSoftmax.apply(x4, mask, 2.0)),
        np.asarray(scaled_masked_softmax(x4, mask, 2.0)))
    np.testing.assert_array_equal(
        np.asarray(GenericScaledMaskedSoftmax.apply(x4, mask, 2.0)),
        np.asarray(scaled_masked_softmax(x4, mask, 2.0)))


def test_amp_init_legacy_entry():
    """apex/amp/amp.py:68-96: amp.init returns a handle; disabled ->
    NoOpHandle passthrough."""
    from apex_tpu import amp

    h = amp.init(enabled=False)
    assert not h.is_active()
    with h.scale_loss(jnp.float32(3.0)) as s:
        assert float(s) == 3.0
    with pytest.warns(UserWarning, match="no effect"):
        h2 = amp.init(loss_scale=128.0, verbose=True)
    assert isinstance(h2, amp.AmpHandle) and h2.is_active() and h2.verbose


def test_2d_sparsity_patterns():
    """apex/contrib/sparsity/sparse_masklib.py:53-141: 2D n:m masks —
    every 4x4 block 2:4 sparse along BOTH rows and columns (so the
    transpose is also 2:4), best >= greedy magnitude, best block choice
    brute-force optimal, create_mask dispatch."""
    from apex_tpu.contrib.sparsity import (compute_valid_2d_patterns,
                                           create_mask, m4n2_2d_best,
                                           m4n2_2d_greedy, mn_2d_greedy)

    pats = compute_valid_2d_patterns(4, 2)
    assert pats.shape[0] == 90
    assert (pats.sum(1) == 2).all() and (pats.sum(2) == 2).all()

    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(8, 12), jnp.float32)
    mb = np.asarray(m4n2_2d_best(w))
    mg = np.asarray(m4n2_2d_greedy(w))
    # best guarantees exactly 2 per row AND column of every block;
    # greedy (like the reference's) only guarantees the upper bound —
    # admission can strand a row/column below n
    blocks = mb.reshape(2, 4, 3, 4).transpose(0, 2, 1, 3).reshape(-1, 4, 4)
    assert (blocks.sum(1) == 2).all() and (blocks.sum(2) == 2).all()
    gblocks = mg.reshape(2, 4, 3, 4).transpose(0, 2, 1, 3).reshape(-1, 4, 4)
    assert (gblocks.sum(1) <= 2).all() and (gblocks.sum(2) <= 2).all()
    aw = np.abs(np.asarray(w))
    assert (aw * mb).sum() >= (aw * mg).sum() - 1e-5
    best_manual = max((aw[:4, :4] * p).sum() for p in pats)
    np.testing.assert_allclose((aw[:4, :4] * mb[:4, :4]).sum(),
                               best_manual, rtol=1e-6)
    # greedy leaves the ragged tail unmasked (reference behavior)
    g2 = np.asarray(mn_2d_greedy(jnp.asarray(rs.randn(6, 10),
                                             jnp.float32), 4, 2))
    assert (g2[4:, :] == 1).all() and (g2[:, 8:] == 1).all()
    np.testing.assert_array_equal(
        np.asarray(create_mask(w, "m4n2_2d_best")), mb)
    np.testing.assert_array_equal(
        np.asarray(create_mask(w, "m4n2_2d_greedy")), mg)
    # typo'd algorithm suffix is loud, not silently greedy
    with pytest.raises(ValueError, match="unsupported"):
        create_mask(w, "m4n2_2d_bset")
    # 4D conv weights dispatch through the reference's channels-minor
    # reshape (mask shape matches; each flattened row group 2:4 along C_in)
    w4 = jnp.asarray(rs.randn(8, 8, 3, 3), jnp.float32)
    m4 = np.asarray(create_mask(w4, "m4n2_2d_best"))
    assert m4.shape == w4.shape
    flat = m4.transpose(2, 3, 0, 1).reshape(-1, 8)
    fb = flat.reshape(-1, 4, 2, 4).transpose(0, 2, 1, 3).reshape(-1, 4, 4)
    assert (fb.sum(1) == 2).all() and (fb.sum(2) == 2).all()


def test_small_reference_helpers(state_guard):
    """print_rank_0/print_rank_last/is_last_rank/get_micro_batch_size,
    manual_rms_norm, jit_dropout_add, parallel_state rank/world-size
    setters."""
    import io
    from contextlib import redirect_stdout

    from apex_tpu.contrib.multihead_attn import jit_dropout_add
    from apex_tpu.normalization.fused_layer_norm import (fused_rms_norm,
                                                         manual_rms_norm)
    from apex_tpu.transformer.pipeline_parallel.utils import (
        destroy_microbatch_calculator, get_micro_batch_size, is_last_rank,
        print_rank_0, print_rank_last, setup_microbatch_calculator)

    # single-process: rank 0 IS the last rank; both printers fire
    assert is_last_rank()
    buf = io.StringIO()
    with redirect_stdout(buf):
        print_rank_0("hello-r0")
        print_rank_last("hello-rl")
    assert "hello-r0" in buf.getvalue() and "hello-rl" in buf.getvalue()

    setup_microbatch_calculator(0, None, 16, 2, 2)
    try:
        assert get_micro_batch_size() == 2
    finally:
        destroy_microbatch_calculator()

    x = jnp.asarray(np.random.RandomState(0).randn(3, 8), jnp.float32)
    wgt = jnp.ones(8, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(manual_rms_norm(x, 8, wgt, 1e-5)),
        np.asarray(fused_rms_norm(x, 8, wgt, 1e-5)))

    out = jit_dropout_add(x, x, 0.0, False)
    np.testing.assert_allclose(np.asarray(out), 2 * np.asarray(x))
    with pytest.raises(ValueError, match="rng"):
        jit_dropout_add(x, x, 0.5, True)

    # rank/world-size setter overrides round-trip on the host
    ps.set_tensor_model_parallel_world_size(4)
    ps.set_pipeline_model_parallel_world_size(2)
    assert ps.get_tensor_model_parallel_world_size() == 4
    assert ps.get_pipeline_model_parallel_world_size() == 2
    ps.set_tensor_model_parallel_rank(3)
    ps.set_pipeline_model_parallel_rank(1)
    assert ps.get_tensor_model_parallel_rank() == 3
    assert ps.get_pipeline_model_parallel_rank() == 1
    # the overrides propagate into the derived predicates host-side
    # (reference: predicates route through get_*_rank)
    assert ps.is_pipeline_last_stage() is True          # rank 1 of pp=2
    assert not ps.is_pipeline_first_stage()
    assert ps.get_pipeline_model_parallel_next_rank() == 0
    assert ps.get_pipeline_model_parallel_prev_rank() == 0
    # get_rank_info still gates on full initialization, as the
    # reference does (returns the zero tuple when no mesh exists)
    assert ps.get_rank_info() == (0, 0, 0, 0)


def test_distributed_test_base():
    """apex/transformer/testing/distributed_test_base.py:27-130: the
    unittest base drives an in-process SPMD test on the virtual mesh."""
    import unittest

    from apex_tpu.transformer.testing import (DistributedTestBase,
                                              NcclDistributedTestBase,
                                              UccDistributedTestBase)

    class MyDistTest(NcclDistributedTestBase):
        def test_psum_over_tp(self):
            mesh = self.initialize_model_parallel(
                tensor_model_parallel_size=self.world_size)
            out = shard_map(
                lambda: jnp.reshape(
                    jax.lax.psum(jnp.float32(1.0), "tp"), (1, 1, 1)),
                mesh=mesh, in_specs=(), out_specs=P("pp", "dp", "tp"),
                check_vma=False)()
            assert float(np.asarray(out)[0, 0, 0]) == self.world_size

    suite = unittest.defaultTestLoader.loadTestsFromTestCase(MyDistTest)
    result = unittest.TextTestRunner(verbosity=0).run(suite)
    assert result.wasSuccessful(), result.failures + result.errors
    assert not ps.model_parallel_is_initialized()  # tearDown cleaned up

    assert NcclDistributedTestBase.DISTRIBUTED_BACKEND == "nccl"
    assert UccDistributedTestBase.DISTRIBUTED_BACKEND == "ucc"
    assert DistributedTestBase.DISTRIBUTED_BACKEND == "xla"
    t = MyDistTest("test_psum_over_tp")
    assert t.world_size == 4  # min(devices, 4), reference rule


@pytest.mark.slow
def test_transformer_language_model():
    """standalone_transformer_lm.py:1240-1420: the Embedding/trunk/pooler
    composite and the get_language_model factory; tied logits flow from
    the returned word table."""
    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.testing import (TransformerConfig,
                                              get_language_model,
                                              parallel_lm_logits)

    cfg = TransformerConfig(hidden_size=16, num_layers=1,
                            num_attention_heads=2, vocab_size=32,
                            max_position_embeddings=8,
                            hidden_dropout=0.0, attention_dropout=0.0)
    lm, key = get_language_model(cfg, num_tokentypes=2, add_pooler=True,
                                 encoder_attn_mask_type=AttnMaskType.padding)
    assert key == "language_model"

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    b, s = 2, 6
    ids = jnp.ones((b, s), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    toks = jnp.zeros((b, s), jnp.int32)
    no_mask = jnp.zeros((1, 1, s, s), bool)

    def run(ids, pos, toks):
        variables = lm.init(jax.random.PRNGKey(0), ids, pos, no_mask,
                            toks)
        enc, pooled, word = lm.apply(variables, ids, pos, no_mask, toks)
        logits = parallel_lm_logits(enc, word, parallel_output=False)
        return enc, pooled, logits

    enc, pooled, logits = shard_map(
        run, mesh=mesh, in_specs=(P(), P(), P()),
        out_specs=(P(), P(), P()), check_vma=False)(ids, pos, toks)
    assert enc.shape == (s, b, 16)
    assert pooled.shape == (b, 16)
    assert logits.shape == (s, b, 32)  # vocab gathered over tp ranks
    for a in (enc, pooled, logits):
        assert np.isfinite(np.asarray(a)).all()


@pytest.mark.slow
def test_bert_sequence_parallel_path():
    """BERT under sequence_parallel=True (newly wired end-to-end:
    embedding scatter, trunk, LM-head gather, pooler gather): per-token
    losses must match the sequence_parallel=False model with identical
    params."""
    from apex_tpu.transformer.testing import BertModel, TransformerConfig

    kw = dict(hidden_size=16, num_layers=1, num_attention_heads=2,
              vocab_size=32, max_position_embeddings=8,
              hidden_dropout=0.0, attention_dropout=0.0,
              bert_binary_head=True)
    cfg_sp = TransformerConfig(sequence_parallel=True, **kw)
    cfg_np = TransformerConfig(sequence_parallel=False, **kw)
    bm_sp, bm_np = BertModel(cfg_sp), BertModel(cfg_np)

    rs = np.random.RandomState(0)
    b, s = 2, 8
    ids = jnp.asarray(rs.randint(0, 32, (b, s)), jnp.int32)
    mask = jnp.ones((b, s), jnp.int32)
    labels = jnp.asarray(rs.randint(0, 32, (b, s)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))

    def run(model):
        def f(ids, mask, labels):
            variables = model.init(jax.random.PRNGKey(0), ids, mask)
            loss, binary = model.apply(variables, ids, mask,
                                       lm_labels=labels)
            return loss, binary
        return shard_map(f, mesh=mesh, in_specs=(P(), P(), P()),
                         out_specs=(P(), P()), check_vma=False)(
            ids, mask, labels)

    loss_sp, bin_sp = run(bm_sp)
    loss_np, bin_np = run(bm_np)
    np.testing.assert_allclose(np.asarray(loss_sp), np.asarray(loss_np),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(bin_sp), np.asarray(bin_np),
                               rtol=2e-4, atol=2e-4)


def test_api_parity_audit_tool():
    """tools/check_api_parity.py: every public reference export resolves
    in apex_tpu or is documented-N/A (skips where the reference tree is
    absent)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ref = "/root/reference/apex"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not available")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "check_api_parity.py"),
         "--reference", ref],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert ", 0 MISSING" in out.stdout, out.stdout

    # scoped mode: name collisions across modules can't mask a gap
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "check_api_parity.py"),
         "--reference", ref, "--per-module"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "MISSING" not in out.stdout, out.stdout
    # every mapped group actually audited (none silently skipped)
    assert out.stdout.count("— ok") >= 20, out.stdout


def test_round3_small_surface_behaviors(state_guard):
    """Behavioral coverage for the last parity batch: amp.master_params
    (O2 masters / O1 fallback / eager raise), sparse_masklib.fill,
    MultiTensorApply.check_avail, CudaRNGStatesTracker alias,
    DistributedFusedAdam.init_params structural check."""
    from apex_tpu import amp
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistributedFusedAdam)
    from apex_tpu.contrib.sparsity.sparse_masklib import fill
    from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
        MultiTensorApply)
    from apex_tpu.optimizers.fused_adam import fused_adam
    from apex_tpu.transformer.tensor_parallel.random import (
        CudaRNGStatesTracker, RngStateTracker)

    assert abs(fill(jnp.asarray([1.0, 0.0, 2.0, 0.0])) - 0.5) < 1e-9
    assert fill(jnp.zeros(4)) == 0.0
    assert MultiTensorApply.check_avail() is None
    assert CudaRNGStatesTracker is RngStateTracker
    tr = CudaRNGStatesTracker()
    tr.add("s", 3)
    k1, k2 = tr.fork("s"), tr.fork("s")
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    params = {"w": jnp.ones(3)}
    p2, opt2 = amp.initialize(dict(params), fused_adam(1e-2),
                              opt_level="O2")
    st2 = opt2.init(p2)
    masters = amp.master_params(st2)
    assert isinstance(masters, list) and masters[0].dtype == jnp.float32
    p1, opt1 = amp.initialize(dict(params), fused_adam(1e-2),
                              opt_level="O1")
    st1 = opt1.init(p1)
    assert amp.master_params(st1, p1)[0] is p1["w"]  # O1 fallback
    with pytest.raises(ValueError, match="no fp32 masters"):
        amp.master_params(st1)  # eager, at the call

    # init_params: registration hook — state stays lazy (created by
    # step() inside the traced region); subsets accepted and ignored
    # per the reference's default path
    dopt = DistributedFusedAdam([jnp.ones(8)], lr=1e-2, num_shards=8)
    assert dopt.init_params() is None          # pre-step
    assert dopt.init_params([jnp.ones(2)]) is None  # subset: no error

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def one_step(g):
        dopt.step([g])
        return jnp.reshape(dopt.init_params().count.astype(jnp.float32),
                           (1,))

    out = shard_map(one_step, mesh=mesh, in_specs=(P(),),
                    out_specs=P("dp"), check_vma=False)(jnp.ones(8))
    assert np.asarray(out).shape == (8,)       # live state visible
