"""Round-3 API-surface parity additions: parallel_state split predicates
and group getters, 1D chunk split/gather, unwrap_model, HaloPadder,
MaskSoftmaxDropout, standalone-model helpers (ports of the reference
surfaces listed in each test's docstring)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.utils import (
    gather_split_1d_tensor,
    split_tensor_into_1d_equal_chunks,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    param_is_not_shared,
    unwrap_model,
)


@pytest.fixture
def state_guard():
    yield
    ps.destroy_model_parallel()


def test_parallel_state_split_predicates(state_guard):
    """apex/transformer/parallel_state.py:423-460: encoder/decoder stage
    predicates against a (pp=4, split=2) topology, evaluated per-stage
    on the 8-device mesh."""
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2)

    def probe():
        return (
            jnp.int32(ps.is_pipeline_stage_before_split()),
            jnp.int32(ps.is_pipeline_stage_after_split()),
            jnp.int32(ps.is_pipeline_stage_at_split()),
            jnp.int32(ps.is_rank_in_embedding_group()),
            jnp.int32(ps.is_rank_in_position_embedding_group()),
            jnp.int32(ps.is_rank_in_encoder_relative_position_embedding_group()),
            jnp.int32(ps.is_rank_in_decoder_relative_position_embedding_group()),
            ps.get_pipeline_model_parallel_next_rank(),
            ps.get_pipeline_model_parallel_prev_rank(),
        )

    outs = shard_map(
        lambda: tuple(jnp.reshape(o, (1, 1, 1)) for o in probe()),
        mesh=mesh, in_specs=(), out_specs=P("pp", "dp", "tp"),
        check_vma=False)()
    # reduce over the (dp, tp) replicas — all equal per stage
    by_stage = [np.asarray(o)[:, 0, 0] for o in outs]
    before, after, at, emb, pos, enc_rel, dec_rel, nxt, prv = by_stage
    np.testing.assert_array_equal(before, [1, 1, 0, 0])   # rank < 2
    np.testing.assert_array_equal(after, [0, 0, 1, 1])    # rank >= 2
    np.testing.assert_array_equal(at, [0, 1, 0, 0])       # rank 1 only
    np.testing.assert_array_equal(emb, [1, 0, 1, 1])      # {0, split, last}
    np.testing.assert_array_equal(pos, [1, 0, 1, 0])      # {0, split}
    np.testing.assert_array_equal(enc_rel, [1, 1, 0, 0])
    np.testing.assert_array_equal(dec_rel, [0, 0, 1, 1])
    np.testing.assert_array_equal(nxt, [1, 2, 3, 0])      # ring-wrapped
    np.testing.assert_array_equal(prv, [3, 0, 1, 2])


def test_parallel_state_degenerate_and_host_getters(state_guard):
    """No-split / pp=1 short-circuits return concrete values host-side
    (reference short-circuits, parallel_state.py:426-447), and the
    bookkeeping getters round-trip."""
    assert ps.is_unitialized()
    assert ps.get_rank_info() == (0, 0, 0, 0)
    ps.initialize_model_parallel(tensor_model_parallel_size_=8)
    assert not ps.is_unitialized()
    assert ps.is_pipeline_stage_before_split() is True
    assert ps.is_pipeline_stage_after_split() is True
    assert ps.is_pipeline_stage_at_split() is True  # reference composition
    assert ps.is_rank_in_embedding_group() is True  # pp == 1
    assert ps.get_data_parallel_src_rank() == 0
    for group_fn in (ps.get_position_embedding_group,
                     ps.get_encoder_relative_position_embedding_group,
                     ps.get_decoder_relative_position_embedding_group):
        assert group_fn() == ps.PIPELINE_AXIS


def test_split_gather_1d_round_trip(state_guard):
    """apex/transformer/utils.py:21-48: per-rank equal 1D chunks and the
    gathering inverse."""
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    x = jnp.arange(8 * 6, dtype=jnp.float32).reshape(8, 6)

    def chunk_and_gather(t):
        chunk = split_tensor_into_1d_equal_chunks(t)
        return chunk, gather_split_1d_tensor(chunk)

    chunks, gathered = shard_map(
        chunk_and_gather, mesh=mesh, in_specs=(P(),),
        out_specs=(P("tp"), P()), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(chunks), np.arange(48.0))
    # gather reassembles the full flat tensor on every rank
    np.testing.assert_allclose(np.asarray(gathered), np.arange(48.0))


def test_unwrap_model_and_shared_params():
    """apex/transformer/pipeline_parallel/utils.py:181-196."""
    class Wrapper:
        def __init__(self, module):
            self.module = module

    assert unwrap_model(3) == 3
    assert unwrap_model([1, 2]) == [1, 2]
    inner = object()
    assert unwrap_model(Wrapper(Wrapper(inner)),
                        module_instances=(Wrapper,)) is inner
    assert param_is_not_shared(jnp.zeros(3))

    class SharedParam:
        shared = True

    assert not param_is_not_shared(SharedParam())


def test_mask_softmax_dropout_matches_manual():
    """apex/contrib/multihead_attn/mask_softmax_dropout_func.py:6-60:
    additive and boolean mask paths, eval == plain softmax, train
    dropout keeps the inverted-scaling expectation."""
    from apex_tpu.contrib.multihead_attn import mask_softmax_dropout

    rs = np.random.RandomState(0)
    heads, b, sq, sk = 2, 3, 4, 5
    x = jnp.asarray(rs.randn(b * heads, sq, sk), jnp.float32)

    # eval, no mask == softmax
    out = mask_softmax_dropout(False, heads, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jax.nn.softmax(x, -1)), rtol=1e-6)

    # additive mask shifts scores before the softmax
    add_mask = jnp.asarray(rs.randn(b * heads, sq, sk), jnp.float32)
    out = mask_softmax_dropout(False, heads, x, add_mask,
                               mask_additive=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jax.nn.softmax(x + add_mask, -1)),
        rtol=1e-6)

    # boolean mask zeroes the masked keys
    bool_mask = jnp.zeros((b * heads, sq, sk), bool).at[:, :, -1].set(True)
    out = mask_softmax_dropout(False, heads, x, bool_mask)
    assert np.asarray(out)[..., -1].max() < 1e-4

    # fully-masked rows emit all-zeros (reference kernel semantics,
    # same as FusedScaleMaskSoftmax), not uniform attention
    full_mask = bool_mask.at[0].set(True)
    out = mask_softmax_dropout(False, heads, x, full_mask)
    np.testing.assert_array_equal(np.asarray(out)[0], 0.0)

    # train-time dropout: zeros appear, survivors are scaled up
    out = mask_softmax_dropout(True, heads, x, dropout_prob=0.5,
                               dropout_rng=jax.random.PRNGKey(0))
    o = np.asarray(out)
    assert (o == 0).any()
    ref = np.asarray(jax.nn.softmax(x, -1))
    nz = o != 0
    np.testing.assert_allclose(o[nz], (ref * 2)[nz], rtol=1e-5)

    # missing rng under training dropout is loud
    with pytest.raises(ValueError, match="dropout_rng"):
        mask_softmax_dropout(True, heads, x, dropout_prob=0.5)


def test_halo_padder_pads_from_neighbors():
    """apex/contrib/bottleneck/halo_exchangers.py:118-165."""
    from apex_tpu.contrib.bottleneck import (HaloExchangerSendRecv,
                                             HaloPadder)

    mesh = Mesh(np.array(jax.devices()[:4]), ("spatial",))
    y = jnp.arange(4 * 2 * 3 * 2, dtype=jnp.float32).reshape(4, 2, 3, 2)
    padder = HaloPadder(HaloExchangerSendRecv("spatial", 4))
    out = shard_map(lambda t: padder(t, 1), mesh=mesh,
                    in_specs=(P("spatial"),), out_specs=P("spatial"),
                    check_vma=False)(y)
    out = np.asarray(out).reshape(4, 4, 3, 2)
    yn = np.asarray(y)
    np.testing.assert_allclose(out[:, 1:-1], yn)
    np.testing.assert_allclose(out[1, 0], yn[0, -1])
    np.testing.assert_allclose(out[2, -1], yn[3, 0])
    np.testing.assert_array_equal(out[0, 0], 0)
    padder.wait()  # no-op parity


def test_standalone_helpers():
    """standalone_transformer_lm.py:130-151 + :1038-1096."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        get_linear_layer, get_num_layers, init_method_normal)

    layer = get_linear_layer(4, 7, init_method_normal(0.02))
    params = layer.init(jax.random.PRNGKey(0), jnp.ones((2, 4)))
    assert params["params"]["kernel"].shape == (4, 7)
    np.testing.assert_array_equal(np.asarray(params["params"]["bias"]), 0)

    class Args:
        num_layers = 12
        pipeline_model_parallel_size = 4
        transformer_pipeline_model_parallel_size = 4
        pipeline_model_parallel_split_rank = None
        standalone_embedding_stage = False

    assert get_num_layers(Args, False) == 3
    Args.pipeline_model_parallel_size = 1
    assert get_num_layers(Args, False) == 12

    # encoder-decoder split: 12 layers over (2 enc, 2 dec) ranks
    Args.pipeline_model_parallel_size = 4
    Args.pipeline_model_parallel_split_rank = 2
    assert get_num_layers(Args, True, before_split=True) == 6
    assert get_num_layers(Args, True, before_split=False) == 6

    # standalone embedding stage: rank 0 carries no transformer layers
    Args.pipeline_model_parallel_split_rank = None
    Args.standalone_embedding_stage = True
    Args.transformer_pipeline_model_parallel_size = 3
    assert get_num_layers(Args, False, pipeline_rank=0) == 0
    assert get_num_layers(Args, False, pipeline_rank=1) == 4
