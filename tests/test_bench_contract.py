"""The driver-facing bench.py contract: one parseable JSON line with the
required fields, produced end-to-end in CPU smoke mode. A broken bench at
driver time means no headline measurement for the round, so this is
regression-tested like any other interface."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_json_contract(tmp_path):
    # one attempt with a sub-test-timeout budget: bench's own timeout
    # path then fires first on a slow box, yielding a deterministic
    # error-JSON line instead of subprocess.run SIGKILLing the watchdog
    # (which would bypass its SIGTERM flush and orphan the inner child)
    ledger_path = str(tmp_path / "ledger.jsonl")
    env = dict(os.environ, APEX_BENCH_SMOKE="1", APEX_BENCH_ATTEMPTS="1",
               APEX_BENCH_TIMEOUT="420", APEX_TELEMETRY="1",
               APEX_TELEMETRY_LEDGER=ledger_path,
               APEX_TELEMETRY_PATH=str(tmp_path / "metrics.jsonl"))
    env.pop("JAX_PLATFORMS", None)  # smoke_mode forces CPU itself
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    # the driver reads ONE JSON line — a second (e.g. per-attempt debug
    # record) is a contract break even if the last line is well-formed
    assert len(lines) == 1, out.stdout[-2000:]
    rec = json.loads(lines[-1])
    for field in ("metric", "value", "unit", "vs_baseline", "mfu",
                  "dispatch_overhead_ms", "relay_degraded", "ledger_id",
                  "compile_cache"):
        assert field in rec, rec
    # warm-start telemetry block, well-formed whatever the knob state
    assert set(rec["compile_cache"]) == {"enabled", "dir", "hits",
                                         "misses", "warm_age_s"}
    assert rec["unit"] == "tokens/s"
    assert rec["value"] > 0, rec
    assert "error" not in rec, rec
    assert rec["relay_degraded"] is False, rec
    # the invocation landed in the run ledger, and the printed line
    # points at exactly that record
    sys.path.insert(0, REPO)
    from apex_tpu.telemetry import ledger as tledger

    records = tledger.read_ledger(ledger_path)
    assert rec["ledger_id"] in {r["id"] for r in records}, records
    for r in records:
        assert tledger.validate_record(r) == [], r
    # the in-step metrics (APEX_TELEMETRY=1) reached the JSONL sink
    from apex_tpu.telemetry import read_metrics

    rows = read_metrics(str(tmp_path / "metrics.jsonl"))
    step_rows = [r for r in rows if "loss_scale" in r]
    assert len(step_rows) >= 3, rows  # smoke runs a 3-iteration scan
    assert all(r.get("run") == rec["ledger_id"] for r in step_rows)


def _fake_rec(value, b16):
    return {"metric": "gpt2s_train_tokens_per_sec (tpu)", "value": value,
            "unit": "tokens/s", "vs_baseline": 1.0, "mfu": 0.4,
            "config": {"batch": 16 if b16 else 8, "fused_lm_head": False}}


def test_ladder_attempt_one_is_default_config(monkeypatch):
    """Attempt 1 is ALWAYS the plain measured-default config — a one-run
    relay window must yield the clean headline, with A/Bs riding the later
    attempts (VERDICT r4 #7). Pinned directly on _config_ladder so a
    ladder reorder cannot slip past the behavioral tests below."""
    sys.path.insert(0, REPO)
    import bench

    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_BENCH_BATCH", "APEX_BENCH_SMOKE"):
        monkeypatch.delenv(k, raising=False)
    for attempts in (1, 2, 3, 5):
        ladder = bench._config_ladder(attempts, smoke=False)
        assert len(ladder) == attempts
        assert ladder[0] == {}, (
            f"attempt 1 must be the default config, got {ladder[0]}")


def test_watchdog_single_healthy_attempt_is_clean_headline(monkeypatch,
                                                           capsys):
    """A window exactly one attempt long (APEX_BENCH_ATTEMPTS=1) with a
    healthy default-config measurement prints that line as the headline —
    valid JSON, no 'note'/'error', default config, rc 0."""
    sys.path.insert(0, REPO)
    import bench

    calls = []

    def fake_attempt(state, extra_env=None, **kw):
        calls.append(dict(extra_env or {}))
        rec = _fake_rec(100.0, False)
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "1")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("{")]
    assert rc == 0
    assert calls == [{}]  # the one attempt ran the default config
    assert len(out) == 1
    rec = json.loads(out[0])
    assert rec["value"] == 100.0
    assert "note" not in rec and "error" not in rec
    assert rec["config"]["batch"] == 8


def test_watchdog_config_ladder(monkeypatch, capsys):
    """The retry ladder A/Bs the b=16 amortization config: both configs
    get a healthy attempt, the higher-throughput line wins, exactly one
    JSON line is printed."""
    sys.path.insert(0, REPO)
    import bench

    calls = []

    def fake_attempt(state, extra_env=None, **kw):
        b16 = (extra_env or {}).get("APEX_BENCH_BATCH") == "16"
        calls.append(b16)
        rec = _fake_rec(120.0 if b16 else 100.0, b16)
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("{")]
    assert rc == 0
    assert calls == [False, True]  # both configs, then early stop
    assert len(out) == 1
    rec = json.loads(out[0])
    assert rec["value"] == 120.0 and rec["config"]["batch"] == 16


def test_watchdog_ladder_retries_unhealthy_config(monkeypatch, capsys):
    """A degraded base attempt gets retried on the flap-retry slot after
    the b=16 attempt lands healthy; an explicit knob pin disables the
    ladder entirely."""
    sys.path.insert(0, REPO)
    import bench

    calls = []

    def fake_attempt(state, extra_env=None, **kw):
        b16 = (extra_env or {}).get("APEX_BENCH_BATCH") == "16"
        calls.append(b16)
        if len(calls) == 1:
            rec = dict(_fake_rec(5.0, b16), note="relay degraded",
                       degraded_kind="relay")
        else:
            rec = _fake_rec(120.0 if b16 else 100.0, b16)
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("{")]
    assert rc == 0
    assert calls == [False, True, False]  # degraded b=8 base retried last
    assert json.loads(out[0])["value"] == 120.0

    # explicit pin: the ladder collapses to the caller's env verbatim
    calls.clear()
    monkeypatch.setenv("APEX_FUSED_LM_HEAD", "1")

    def fake_pinned(state, extra_env=None, **kw):
        merged = dict(os.environ, **(extra_env or {}))
        fused = merged.get("APEX_FUSED_LM_HEAD") == "1"
        calls.append(fused)
        # the pin is a fused-head pin, not a batch pin: the fabricated
        # record keeps the default batch
        rec = dict(_fake_rec(120.0, False))
        rec["config"]["fused_lm_head"] = fused
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_pinned)
    rc = bench._watchdog()
    capsys.readouterr()
    assert rc == 0
    assert calls == [True]  # pinned config, healthy first attempt, done


def test_watchdog_ladder_retries_degraded_b16_config(monkeypatch, capsys):
    """The spare attempt goes to whichever config lacks a healthy line —
    including one whose original slot already ran (b=16 degraded on
    attempt 2 gets attempt 3)."""
    sys.path.insert(0, REPO)
    import bench

    calls = []

    def fake_attempt(state, extra_env=None, **kw):
        b16 = (extra_env or {}).get("APEX_BENCH_BATCH") == "16"
        calls.append(b16)
        if len(calls) == 2:  # the b=16 slot flaps
            rec = dict(_fake_rec(5.0, b16), note="relay degraded",
                       degraded_kind="relay")
        else:
            rec = _fake_rec(130.0 if b16 else 100.0, b16)
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("{")]
    assert rc == 0
    assert calls == [False, True, True]  # b=16 retried on the spare slot
    assert json.loads(out[0])["value"] == 130.0


def test_watchdog_cpu_only_box_runs_once(monkeypatch, capsys):
    """A clean first-attempt CPU line (no TPU hardware) collapses the
    ladder: no second full bench for a CPU 'A/B'."""
    sys.path.insert(0, REPO)
    import bench

    calls = []

    def fake_attempt(state, extra_env=None, **kw):
        calls.append((extra_env or {}).get("APEX_BENCH_BATCH") == "16")
        rec = dict(_fake_rec(90.0, False),
                   metric="gpt2s_train_tokens_per_sec (cpu)")
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("{")]
    assert rc == 0
    assert calls == [False]
    assert json.loads(out[0])["value"] == 90.0


def test_watchdog_lazy_cap_after_timeout(monkeypatch, capsys):
    """A first attempt that rides its entire budget without a JSON line
    (rc None + fabricated timed_out record — the wedge signature) arms a
    900s cap for the remaining attempts; completed attempts (healthy or
    degraded, any length) never arm it."""
    sys.path.insert(0, REPO)
    import bench

    caps = []

    def fake_timeout_attempt(state, extra_env=None, timeout_cap=None, **kw):
        caps.append(timeout_cap)
        rec = {"metric": "gpt2s_train_tokens_per_sec (tpu)", "value": 0,
               "unit": "tokens/s", "vs_baseline": 0, "mfu": None,
               "timed_out": True, "relay_degraded": True,
               "error": "bench timed out after 1800s"}
        return json.dumps(rec), rec, None   # rc None = timeout path

    monkeypatch.setattr(bench, "_attempt_once", fake_timeout_attempt)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    capsys.readouterr()
    assert rc == 1  # error line only: no real measurement
    assert caps == [None, 900, 900]

    # a COMPLETED degraded attempt (rc 0) must not arm the cap
    caps.clear()

    def fake_degraded_attempt(state, extra_env=None, timeout_cap=None, **kw):
        caps.append(timeout_cap)
        rec = dict(_fake_rec(5.0, False), note="relay degraded",
                   degraded_kind="relay")
        return json.dumps(rec), rec, 0

    monkeypatch.setattr(bench, "_attempt_once", fake_degraded_attempt)
    rc = bench._watchdog()
    capsys.readouterr()
    assert rc == 0
    assert caps == [None, None, None]


def test_watchdog_real_error_record_does_not_arm_cap(monkeypatch, capsys):
    """A REAL error record forwarded after a teardown wedge (rc None,
    no timed_out stamp — e.g. the calibration-flap line printed before
    the child wedged) must NOT arm the lazy cap: the attempt completed
    its measurement; only riding the whole budget with no JSON line is
    wedge evidence (ADVICE r5 on the old any-rc-None-error condition)."""
    sys.path.insert(0, REPO)
    import bench

    caps = []

    def fake_teardown_wedge(state, extra_env=None, timeout_cap=None, **kw):
        caps.append(timeout_cap)
        rec = {"metric": "gpt2s_train_tokens_per_sec (tpu)", "value": 0,
               "unit": "tokens/s", "vs_baseline": 0, "mfu": None,
               "error": "non-positive step time after overhead "
                        "subtraction (relay flap straddled the "
                        "calibration); measurement unusable"}
        # rc None: the child printed the record, then wedged in teardown
        return json.dumps(rec), rec, None

    monkeypatch.setattr(bench, "_attempt_once", fake_teardown_wedge)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_BENCH_ATTEMPTS", "3")
    monkeypatch.delenv("APEX_BENCH_SMOKE", raising=False)
    for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_REMAT", "APEX_BENCH_BATCH"):
        monkeypatch.delenv(k, raising=False)
    rc = bench._watchdog()
    capsys.readouterr()
    assert rc == 1  # error line only: no real measurement
    assert caps == [None, None, None]
