"""The driver-facing bench.py contract: one parseable JSON line with the
required fields, produced end-to-end in CPU smoke mode. A broken bench at
driver time means no headline measurement for the round, so this is
regression-tested like any other interface."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_json_contract():
    # one attempt with a sub-test-timeout budget: bench's own timeout
    # path then fires first on a slow box, yielding a deterministic
    # error-JSON line instead of subprocess.run SIGKILLing the watchdog
    # (which would bypass its SIGTERM flush and orphan the inner child)
    env = dict(os.environ, APEX_BENCH_SMOKE="1", APEX_BENCH_ATTEMPTS="1",
               APEX_BENCH_TIMEOUT="420")
    env.pop("JAX_PLATFORMS", None)  # smoke_mode forces CPU itself
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    # the driver reads ONE JSON line — a second (e.g. per-attempt debug
    # record) is a contract break even if the last line is well-formed
    assert len(lines) == 1, out.stdout[-2000:]
    rec = json.loads(lines[-1])
    for field in ("metric", "value", "unit", "vs_baseline", "mfu"):
        assert field in rec, rec
    assert rec["unit"] == "tokens/s"
    assert rec["value"] > 0, rec
    assert "error" not in rec, rec
