"""Durability layer unit tests (`apex_tpu.checkpoint.DurableCheckpointer`).

The commit protocol's invariants (atomic tmp+rename, content-hash
manifest, torn/corrupt/stale fallback), the bounded-queue async mode
with backpressure, the telemetry block, and the zero-cost rule: the
checkpoint layer lives entirely at the scan boundary on the host, so
an enabled writer never changes the jitted training step's jaxpr.
Chaos twins driving the same invariants through scripted fault plans
and real subprocesses live in tests/test_checkpoint_chaos.py; the
bitwise resume-parity runs live in tests/test_resume_parity.py.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import checkpoint as ckpt


def _mesh():
    return Mesh(np.asarray(jax.devices()).reshape(4, 2), ("dp", "tp"))


def _state(mesh=None):
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(16, 8), jnp.float32)
    if mesh is not None:
        w = jax.device_put(w, NamedSharding(mesh, P("dp", "tp")))
    return {
        "params": {"w": w,
                   "emb": jnp.asarray(rs.randn(8, 4) * 0.1, jnp.bfloat16)},
        "count": jnp.asarray(3, jnp.int32),
        "overflow": jnp.asarray(False),
        "rng": jax.random.PRNGKey(7),
    }


def _assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def test_sync_roundtrip_values_shardings_and_dtypes(tmp_path):
    """One sync save commits atomically; restore reproduces every leaf
    bitwise (incl. bf16, bool, int scalars, PRNGKey) and places sharded
    leaves back onto the template's shardings."""
    mesh = _mesh()
    state = _state(mesh)
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    manifest = w.save(5, state, meta={"step": 5, "knob_pins": {}})
    assert manifest["step"] == 5
    assert manifest["id"] == ckpt.manifest_id(manifest)
    # the committed data file hashes to the manifest's sha256
    assert ckpt._sha256_file(ckpt._data_path(str(tmp_path), 5)) \
        == manifest["sha256"]
    restored, m = w.restore_latest(state)
    assert m["id"] == manifest["id"]
    _assert_tree_equal(restored, state)
    assert restored["params"]["w"].sharding == state["params"]["w"].sharding
    assert restored["params"]["emb"].dtype == jnp.bfloat16
    assert (m.get("meta") or {}).get("step") == 5


def test_retention_keeps_newest(tmp_path):
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, max_to_keep=2,
                                 async_save=False)
    for step in (1, 2, 3, 4):
        w.save(step, state)
    assert w.all_steps() == [3, 4]
    snap = w.snapshot()
    assert snap["saves"] == 4 and snap["last_step"] == 4
    assert snap["commit_ms"] is not None and snap["queue_depth"] == 0


def test_torn_data_file_is_never_a_candidate(tmp_path):
    """A data file without a manifest (crash between the two renames)
    is invisible: latest_step and the restore walk skip it."""
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, state)
    w.save(2, state)
    os.remove(ckpt._manifest_path(str(tmp_path), 2))  # torn step 2
    assert w.latest_step() == 1
    restored, m = w.restore_latest(state)
    assert m["step"] == 1
    _assert_tree_equal(restored, state)


def test_corrupt_latest_falls_back_one_step(tmp_path, capsys):
    """Bytes that no longer hash to the manifest (truncation/disk rot)
    are never restored — the walk falls back to the previous retained
    step and says why on stderr."""
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, state)
    scaled = jax.tree_util.tree_map(
        lambda x: (x * 2).astype(x.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, state)
    w.save(2, scaled)
    with open(ckpt._data_path(str(tmp_path), 2), "r+b") as f:
        f.seek(40)
        f.write(b"\x00\x00")
    restored, m = w.restore_latest(state)
    assert m["step"] == 1
    _assert_tree_equal(restored, state)
    assert "hash mismatch" in capsys.readouterr().err


def test_truncated_data_file_falls_back(tmp_path):
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, state)
    w.save(2, state)
    with open(ckpt._data_path(str(tmp_path), 2), "r+b") as f:
        f.truncate(16)
    _, m = w.restore_latest(state)
    assert m["step"] == 1


def test_stale_manifest_step_is_refused(tmp_path):
    """A manifest whose step field disagrees with its filename (the
    stale-step tamper mode) must not restore as the filename's step —
    trajectory provenance would silently lie."""
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, state)
    w.save(2, state)
    mpath = ckpt._manifest_path(str(tmp_path), 2)
    with open(mpath) as f:
        m = json.load(f)
    m["step"] = 1  # tamper
    with open(mpath, "w") as f:
        json.dump(m, f)
    _, got = ckpt.restore_durable(str(tmp_path), state)
    assert got["step"] == 1
    assert ckpt.read_durable_manifest(str(tmp_path), 1)["id"] == got["id"]


def test_pinned_step_restore_raises_on_invalid(tmp_path):
    """Explicit request ≠ preference: a pinned-step restore of an
    invalid checkpoint raises instead of silently restoring another."""
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, state)
    w.save(2, state)
    with open(ckpt._data_path(str(tmp_path), 2), "r+b") as f:
        f.truncate(10)
    with pytest.raises(ValueError, match="hash mismatch"):
        w.restore(2, state)
    # ...while the valid pinned step restores fine
    restored, m = w.restore(1, state)
    assert m["step"] == 1


def test_template_mismatch_is_skipped_not_misrestored(tmp_path):
    """A checkpoint whose tree does not match the restore template
    (different run shape) is skipped, never force-fit."""
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, {"a": jnp.ones((4,))})
    other = {"a": jnp.ones((8,))}
    restored, m = w.restore_latest(other)
    assert restored is None and m is None


def test_async_commits_drain_on_flush(tmp_path):
    state = _state()
    w = ckpt.DurableCheckpointer(tmp_path, max_to_keep=5,
                                 async_save=True, queue_size=2)
    for step in (1, 2, 3):
        w.save(step, state)
    w.flush()
    assert w.all_steps() == [1, 2, 3]
    snap = w.snapshot()
    assert snap["saves"] == 3 and snap["errors"] == 0
    assert snap["async"] is True
    w.close()
    restored, m = ckpt.restore_durable(str(tmp_path), state)
    assert m["step"] == 3
    _assert_tree_equal(restored, state)


def test_async_bounded_queue_applies_backpressure(tmp_path, monkeypatch):
    """A serializer that cannot keep up BLOCKS the caller (bounded
    queue) instead of growing host memory or dropping checkpoints:
    with a 1-deep queue and a stalled commit (the slow-disk fault,
    via the real APEX_FAULT_PLAN path), the third save cannot return
    before the first commit finishes."""
    stall = 0.4
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps([
        {"site": "ckpt_commit", "kind": "hang", "seconds": stall,
         "match_ctx": {"phase": "serialized", "step": 1}}]))
    state = {"a": jnp.ones((4,))}
    w = ckpt.DurableCheckpointer(tmp_path, max_to_keep=5,
                                 async_save=True, queue_size=1)
    t0 = time.perf_counter()
    w.save(1, state)   # worker picks this up and stalls in commit
    w.save(2, state)   # fills the 1-deep queue
    w.save(3, state)   # must BLOCK until the stalled commit drains
    blocked = time.perf_counter() - t0
    w.flush()
    assert blocked >= stall * 0.5, \
        f"third save returned in {blocked:.3f}s — no backpressure"
    assert w.all_steps() == [1, 2, 3]
    # the stall is visible in telemetry: the slow commit's commit_ms
    assert w.snapshot()["saves"] == 3
    w.close()


def test_async_commit_error_is_telemetry_not_crash(tmp_path,
                                                   monkeypatch):
    """A failing background commit must never kill the training
    process or the writer thread — the failure lands in the telemetry
    block and the NEXT save still commits."""
    state = {"a": jnp.ones((4,))}
    w = ckpt.DurableCheckpointer(tmp_path, async_save=True, queue_size=2)
    real_commit = w._commit
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real_commit(*a, **k)

    monkeypatch.setattr(w, "_commit", flaky)
    w.save(1, state)
    w.save(2, state)
    w.flush()
    snap = w.snapshot()
    assert snap["errors"] == 1 and "disk full" in snap["last_error"]
    assert snap["saves"] == 1
    assert w.all_steps() == [2]
    w.close()


def test_enabled_checkpointing_is_jaxpr_byte_identical(monkeypatch,
                                                       tmp_path):
    """The zero-cost rule for the durability layer: the writer lives
    entirely at the scan boundary (host side), so tracing the bench
    training step with checkpointing armed — writer constructed, a
    save committed — yields a jaxpr byte-identical to the
    checkpointing-disabled trace."""
    import bench
    from tests.test_telemetry import _bench_fixture

    (model, scaler, tx, params, opt_state, scaler_state,
     ids, pos, labels) = _bench_fixture()
    args = (params, opt_state, scaler_state, ids, pos, labels)

    from apex_tpu import telemetry

    telemetry.disable()
    monkeypatch.delenv("APEX_CKPT_DIR", raising=False)
    want = str(jax.make_jaxpr(bench.make_one_step(model, scaler, tx))(
        *args))

    monkeypatch.setenv("APEX_CKPT_DIR", str(tmp_path))
    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, {"params": params, "opt": opt_state})
    got = str(jax.make_jaxpr(bench.make_one_step(model, scaler, tx))(
        *args))
    assert got == want, \
        "enabled checkpointing changed the training step's jaxpr"


def test_snapshot_block_shape_matches_ledger_validation(tmp_path):
    """The writer's telemetry block passes the ledger's checkpoint-
    block validation — the schema bench.py stamps into records."""
    from apex_tpu.telemetry import ledger

    w = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    w.save(1, {"a": jnp.ones((2,))})
    rec = ledger.make_record(
        harness="bench", platform="cpu", dispatch_overhead_ms=1.0, k=3,
        knobs={}, git="abc", ts=1.0,
        extra={"checkpoint": w.snapshot(),
               "resumed_from": {"ckpt": "ck-0123456789", "step": 3,
                                "pins": {}}})
    assert ledger.validate_record(rec) == []


def test_concurrent_saves_from_training_thread_are_ordered(tmp_path):
    """Saves issued while earlier commits are still queued land in
    step order (one worker drains the queue FIFO)."""
    state = {"a": jnp.ones((2,))}
    w = ckpt.DurableCheckpointer(tmp_path, max_to_keep=10,
                                 async_save=True, queue_size=2)
    done = threading.Event()

    def trainer():
        for s in range(1, 6):
            w.save(s, state)
        done.set()

    t = threading.Thread(target=trainer)
    t.start()
    t.join(timeout=30)
    assert done.is_set()
    w.close()
    assert w.all_steps() == [1, 2, 3, 4, 5]
