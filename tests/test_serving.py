"""Serving stack (apex_tpu.serving, ISSUE 10): decode/prefill logits
parity per dtype, paged-allocator invariants, scheduler no-starvation,
int8 weight-quant parity band, jaxpr stability across admit/evict, and
the serving ledger block's validation + check-8 teeth."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.serving import (
    ContinuousBatchingScheduler,
    PageAllocator,
    Request,
    ServingEngine,
    init_cache,
    synthetic_trace,
)
from apex_tpu.serving import model as smodel
from apex_tpu.serving import quant as quant_mod
from apex_tpu.serving.kv_cache import pages_needed
from apex_tpu.telemetry import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(bf16=False):
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=bf16)


@pytest.fixture(scope="module")
def f32_setup():
    cfg = _cfg(False)
    return cfg, smodel.init_gpt_params(cfg)


@pytest.fixture(scope="module")
def bf16_setup():
    cfg = _cfg(True)
    return cfg, smodel.init_gpt_params(cfg)


def _oneshot_logits(cfg, params, tokens):
    """GPTModel.apply over the full sequence — the training stack's
    own numbers, the parity oracle for the serving forward."""
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel

    model = GPTModel(cfg)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
    ids = jnp.asarray(tokens, jnp.int32)[None, :]
    pos = jnp.arange(len(tokens), dtype=jnp.int32)[None, :]
    return jax.jit(jax.shard_map(
        lambda p, i, po: model.apply({"params": p}, i, po, None),
        mesh=mesh, in_specs=(P(),) * 3, out_specs=P(),
        check_vma=False))(params, ids, pos)[0]


def _decode_rollout(cfg, params, prompt, n_new, ps=8, qparams=None):
    """Model-level prefill + n_new greedy decode steps over one
    request's paged cache; returns (tokens, per-step logits)."""
    max_pages = pages_needed(len(prompt) + n_new, ps)
    n_pages = max_pages + 2
    cache = init_cache(cfg.num_layers, cfg.num_attention_heads,
                       n_pages, ps, cfg.head_dim,
                       smodel.compute_dtype(cfg))
    pt = np.zeros((2, max_pages), np.int32)
    pt[0] = np.arange(1, max_pages + 1)       # row 1 = null spare
    S = len(prompt)
    ids = jnp.asarray(prompt, jnp.int32)
    positions = jnp.arange(S, dtype=jnp.int32)
    seg = jnp.ones((S,), jnp.int32)
    token_rows = jnp.zeros((S,), jnp.int32)
    cache, logits0 = smodel.prefill(
        params, cache, ids, positions, seg, token_rows,
        jnp.asarray(pt), jnp.asarray([S - 1], jnp.int32), cfg=cfg)
    tok = int(jnp.argmax(logits0[0].astype(jnp.float32)))
    toks, steps = [tok], []
    pt1 = jnp.asarray(pt[:1])
    for i in range(n_new - 1):
        cache, nxt, lg = smodel.decode_step(
            params, cache, jnp.asarray([tok], jnp.int32),
            jnp.asarray([S + 1 + i], jnp.int32), pt1, cfg=cfg,
            qparams=qparams)
        steps.append(np.asarray(lg[0].astype(jnp.float32)))
        tok = int(nxt[0])
        toks.append(tok)
    return toks, logits0, steps


@pytest.mark.parametrize("setup,atol,name", [
    ("f32_setup", 2e-4, "f32"), ("bf16_setup", 0.35, "bf16")],
    ids=["f32", "bf16"])
def test_decode_matches_prefill_per_dtype(setup, atol, name, request):
    """Token-by-token decode over the paged cache equals the one-shot
    forward of the SAME weights over >= 32 generated tokens: greedy
    tokens identical, per-step logits within dtype tolerance (the
    ISSUE 10 acceptance parity)."""
    cfg, params = request.getfixturevalue(setup)
    rs = np.random.RandomState(0)
    prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, 6)]
    n_new = 33
    toks, logits0, steps = _decode_rollout(cfg, params, prompt, n_new)
    full = prompt + toks
    oneshot = np.asarray(
        _oneshot_logits(cfg, params, full).astype(jnp.float32))
    greedy = np.argmax(oneshot, axis=-1)
    p = len(prompt)
    assert toks == [int(t) for t in greedy[p - 1:p - 1 + n_new]], (
        f"{name}: greedy decode diverged from the one-shot forward")
    # prefill's next-token logits == one-shot logits at the last
    # prompt position
    np.testing.assert_allclose(
        np.asarray(logits0[0].astype(jnp.float32)), oneshot[p - 1],
        atol=atol)
    # every decode step's logits vs the one-shot row at its position
    for i, lg in enumerate(steps):
        np.testing.assert_allclose(lg, oneshot[p + i], atol=atol,
                                   err_msg=f"{name} step {i}")


def test_allocator_invariants_under_churn():
    alloc = PageAllocator(32)
    rs = np.random.RandomState(1)
    live = set()
    for step in range(200):
        if live and rs.rand() < 0.4:
            victim = rs.choice(sorted(live))
            alloc.free(("req", int(victim)))
            live.discard(int(victim))
        else:
            rid = step
            got = alloc.alloc(("req", rid), int(rs.randint(1, 5)))
            if got is not None:
                live.add(rid)
        alloc.check_invariants()
    for rid in list(live):
        alloc.free(("req", rid))
    alloc.check_invariants()
    assert alloc.free_count == 31  # free-list round trip (page 0 held)
    # exhaustion is all-or-nothing: state unchanged on refusal
    assert alloc.alloc(("req", "big"), 99) is None
    alloc.check_invariants()
    assert alloc.free_count == 31


def test_scheduler_no_starvation_fifo():
    """More requests than slots/pages: strict FIFO admission with
    head-of-line blocking — admission order equals arrival order and
    every request completes (no starvation under churn)."""
    alloc = PageAllocator(16)
    sch = ContinuousBatchingScheduler(2, 8, 8, alloc)
    reqs = [Request(rid=i, prompt=[1] * 4, max_new_tokens=4,
                    arrival=0) for i in range(8)]
    for r in reqs:
        sch.submit(r)
    tick = 0
    while len(sch.completed) < len(reqs):
        assert tick < 100
        sch.evict_done(tick)
        sch.admit(tick)
        for i in sch.active_indices():
            slot = sch.slots[i]
            slot.pos += 1
            slot.request.out_tokens.append(0)
        alloc.check_invariants()
        tick += 1
    order = [r.rid for r in sorted(reqs,
                                   key=lambda r: (r.admitted_tick,
                                                  r.rid))]
    assert order == list(range(8)), "admission violated FIFO arrival"
    assert all(r.done() for r in reqs)


def test_scheduler_refuses_impossible_request_at_submit():
    """An over-max_seq request raises at submit(), before anything is
    enqueued — one malformed submission can never crash a later
    scheduler round and take the serving loop down."""
    sch = ContinuousBatchingScheduler(2, 4, 8, PageAllocator(16))
    with pytest.raises(ValueError, match="exceed the per-slot table"):
        sch.submit(Request(rid=0, prompt=[1] * 30, max_new_tokens=10))
    assert not sch.queue
    sch.submit(Request(rid=1, prompt=[1] * 20, max_new_tokens=10))
    assert len(sch.queue) == 1


def test_int8_quant_parity_band(f32_setup):
    """Quantized decode logits track the full-precision ones within
    the int8 tolerance band, and the greedy tokens stay mostly
    aligned over the rollout."""
    cfg, params = f32_setup
    rs = np.random.RandomState(2)
    prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, 6)]
    qp = smodel.quantize_decode_params(params, cfg)
    toks, lg0, steps = _decode_rollout(cfg, params, prompt, 12)
    qtoks, qlg0, qsteps = _decode_rollout(cfg, params, prompt, 12,
                                          qparams=qp)
    # same trajectory => positionwise comparable logits; compare while
    # the token streams agree (a flip decorrelates everything after)
    agree = 0
    for i, (a, b) in enumerate(zip(toks, qtoks)):
        if a != b:
            break
        agree += 1
        if i > 0:
            scale = max(1.0, float(np.max(np.abs(steps[i - 1]))))
            assert float(np.max(np.abs(
                steps[i - 1] - qsteps[i - 1]))) < 0.25 * scale, (
                f"int8 logits drifted outside the band at step {i}")
    assert agree >= 8, (
        f"int8 greedy stream diverged after {agree} tokens (band too "
        f"loose to be real quantization, not a broken matmul)")


def test_quant_knob_asymmetry(monkeypatch):
    with pytest.raises(ValueError):
        quant_mod.quantize_weight(jnp.zeros((4, 4), jnp.int32))
    with pytest.raises(ValueError):
        quant_mod.set_weight_quant("yes")
    monkeypatch.setenv("APEX_SERVE_WEIGHT_QUANT", "1")
    assert quant_mod.resolve() is True
    monkeypatch.setenv("APEX_SERVE_WEIGHT_QUANT", "0")
    assert quant_mod.resolve() is False
    from apex_tpu.dispatch import tiles

    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SERVE_WEIGHT_QUANT", "maybe")
    with pytest.warns(UserWarning, match="maybe"):
        assert quant_mod.resolve() is False  # default OFF
    monkeypatch.delenv("APEX_SERVE_WEIGHT_QUANT")
    quant_mod.set_weight_quant(True)
    try:
        assert quant_mod.resolve() is True
        assert quant_mod.resolve(per_call=False) is False  # call wins
    finally:
        quant_mod.set_weight_quant(None)


def test_quant_roundtrip_accuracy():
    rs = np.random.RandomState(3)
    w = jnp.asarray(rs.randn(16, 32), jnp.float32)
    wq, scale = quant_mod.quantize_weight(w)
    deq = np.asarray(wq, np.float32) * np.asarray(scale)[:, None]
    err = np.max(np.abs(deq - np.asarray(w)))
    assert err <= np.max(np.abs(np.asarray(w))) / 127.0 + 1e-6
    zero_row = jnp.zeros((1, 8), jnp.float32)
    wq0, s0 = quant_mod.quantize_weight(zero_row)
    assert float(s0[0]) == 0.0 and np.all(np.asarray(wq0) == 0)


def test_decode_jaxpr_stable_across_admit_evict(f32_setup):
    """The acceptance contract: admitting/evicting requests changes
    array VALUES only — the decode program compiles exactly once."""
    cfg, params = f32_setup
    eng = ServingEngine(cfg, params=params, num_slots=2, page_size=8,
                        num_pages=24, max_seq=64, prefill_len=32)
    a = Request(rid=0, prompt=[3, 5, 7, 9], max_new_tokens=10)
    b = Request(rid=1, prompt=[2, 4], max_new_tokens=3)
    eng.submit(a)
    eng.step()
    size_before = eng.decode_cache_size()
    eng.step(arrivals=[b])        # admit mid-stream
    while not (a.done() and b.done()):
        eng.step()
    eng.step()                    # final evict round
    assert size_before == eng.decode_cache_size() == 1, (
        "decode step recompiled across scheduler events")
    assert eng.allocator.free_count == 23
    eng.allocator.check_invariants()


def test_serving_config_refusals():
    """Unsupported TransformerConfig options are explicit refusals at
    engine build, never silent numeric drift."""
    import dataclasses

    for field, val in (("hidden_dropout", 0.1),
                       ("apply_query_key_layer_scaling", True),
                       ("num_moe_experts", 2),
                       ("sequence_parallel", True)):
        bad = dataclasses.replace(_cfg(False), **{field: val})
        with pytest.raises(ValueError, match="serving does not"):
            smodel.check_serving_config(bad)


def test_serving_block_validation():
    good = {"tokens_per_s": 100.0, "p50_ms": 5.0, "p99_ms": 9.0,
            "trace_id": "tr-0123456789", "kv_pages": 64}
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 extra={"serving": dict(good)})
    assert ledger_mod.validate_record(rec) == []
    for field, bad in (("tokens_per_s", -1), ("p99_ms", True),
                       ("trace_id", "lg-x"), ("kv_pages", 0)):
        r = ledger_mod.make_record(
            "profile_serving", "cpu", 0.1, 2,
            extra={"serving": dict(good, **{field: bad})})
        assert any(field in p for p in ledger_mod.validate_record(r)), \
            field
    r = ledger_mod.make_record(
        "profile_serving", "cpu", 0.1, 2,
        extra={"serving": dict(good, p50_ms=10.0)})
    assert any("exceeds" in p for p in ledger_mod.validate_record(r))


def _check8_env(tmp_path, knobs):
    block = {"tokens_per_s": 10.0, "p50_ms": 1.0, "p99_ms": 2.0,
             "trace_id": "tr-0123456789", "kv_pages": 8}
    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 knobs=knobs,
                                 extra={"serving": block})
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"serving row cites ledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    return ["--perf", str(perf), "--ledger", str(ledger),
            "--table", str(table)]


def test_check8_unpinned_serving_row_fails(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check8_env(tmp_path, {}))
    assert out.returncode == 1
    assert "APEX_SERVE_WEIGHT_QUANT" in out.stdout
    assert "APEX_DECODE_ATTN_IMPL" in out.stdout
    # multi-token decode blocks (ISSUE 17): the block size is a third
    # compiled-program axis the citation must pin
    assert "APEX_SERVE_DECODE_K" in out.stdout
    # KV tier (ISSUE 20): int8 cache and swap restore are different
    # cache tiers the citation must pin too
    assert "APEX_SERVE_KV_QUANT" in out.stdout
    assert "APEX_SERVE_KV_SWAP" in out.stdout


def test_check8_pinned_serving_row_clean(tmp_path):
    from tests.conftest import run_check_bench_labels

    out = run_check_bench_labels(*_check8_env(
        tmp_path, {"APEX_SERVE_WEIGHT_QUANT": "0",
                   "APEX_DECODE_ATTN_IMPL": "jnp",
                   "APEX_SERVE_DECODE_K": "1",
                   "APEX_SERVE_KV_QUANT": "0",
                   "APEX_SERVE_KV_SWAP": "0"}))
    assert out.returncode == 0, out.stdout


def test_dryrun_serving_contract():
    """The always-working driver contract (same as dryrun_multichip):
    prefill -> decode -> detokenized continuation with a mid-stream
    admission, in-process."""
    sys.path.insert(0, REPO)
    import __graft_entry__ as graft

    graft.dryrun_serving()


def test_profile_serving_smoke_emits_validated_row(tmp_path):
    """CPU end-to-end proof (ISSUE 10 + ISSUE 11 acceptance): one
    subprocess ``profile_serving.py --smoke`` run emits a ledger
    record whose serving AND slo blocks validate, whose knobs pin the
    dispatch choices (check 8) and the SLO thresholds / arrival
    process / scheduler policy (check 9 clean by construction, run
    against the produced ledger), and whose record renders the
    window_report serving-economics section."""
    ledger = tmp_path / "ledger.jsonl"
    env = dict(os.environ, APEX_TELEMETRY_LEDGER=str(ledger),
               PALLAS_AXON_POOL_IPS="")
    env.pop("APEX_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "profile_serving.py"),
         "--smoke"],
        env=env, cwd=REPO, text=True, capture_output=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = ledger_mod.read_ledger(str(ledger))
    rec = recs[-1]
    assert ledger_mod.validate_record(rec) == []
    sv = rec["serving"]
    assert sv["tokens_per_s"] > 0 and sv["p50_ms"] <= sv["p99_ms"]
    assert sv["trace_id"].startswith("tr-") and sv["kv_pages"] > 0
    assert rec["knobs"].get("APEX_SERVE_WEIGHT_QUANT") in ("0", "1")
    assert rec["knobs"].get("APEX_DECODE_ATTN_IMPL") in ("jnp",
                                                         "pallas")
    # ISSUE 11: the slo block, its pins, and the overlap stamp
    slo = rec["slo"]
    assert slo["arrival_process"] == rec["knobs"]["APEX_SERVE_ARRIVALS"]
    assert slo["goodput_tok_s"] is not None \
        and 0 <= slo["slo_attainment"] <= 1
    assert slo["max_queue_depth"] is not None \
        and slo["kv_page_high_water"] is not None
    assert float(rec["knobs"]["APEX_SERVE_SLO_TTFT_MS"]) \
        == slo["slo_ttft_ms"]
    assert float(rec["knobs"]["APEX_SERVE_SLO_TPOT_MS"]) \
        == slo["slo_tpot_ms"]
    assert rec["knobs"]["APEX_SERVE_SCHED"] == "fifo"
    ob = rec["cost"]["overlap_bound"]
    assert ob["host_ms"] is not None and ob["host_ms"] >= 0
    # check 9 passes on the produced row (cited from a scratch PERF)
    from tests.conftest import run_check_bench_labels

    perf = tmp_path / "PERF.md"
    perf.write_text(f"serving slo row cites ledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    out = run_check_bench_labels(
        "--perf", str(perf), "--ledger", str(ledger),
        "--table", str(table))
    assert out.returncode == 0, out.stdout
    # window_report renders the serving economics from the same ledger
    import io
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "window_report", os.path.join(REPO, "tools",
                                      "window_report.py"))
    wr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wr)
    report = wr.build_report(ledger_path=str(ledger))
    buf = io.StringIO()
    wr.print_report(report, out=buf)
    text = buf.getvalue()
    assert "serving economics:" in text
    assert sv["trace_id"] in text and "attainment=" in text
    assert "overlap" in text
