"""Pallas layer-norm kernel vs the jnp reference (interpret mode on CPU;
the real-TPU timing comparison lives in benchmarks/profile_layernorm.py).
Reference envelope: csrc/layer_norm_cuda_kernel.cu fwd/bwd parity tests in
tests/L0/run_fused_layer_norm."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import fused_layer_norm
from apex_tpu.ops import layer_norm_pallas as lnp


@pytest.mark.parametrize("rows,hidden", [(64, 128), (32, 768), (16, 1024)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fwd_matches_jnp(rows, hidden, dtype):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(rows, hidden) * 2 + 1, dtype)
    w = jnp.asarray(rs.rand(hidden) + 0.5, jnp.float32)
    b = jnp.asarray(rs.randn(hidden), jnp.float32)
    assert lnp.supported(rows, hidden)
    got = lnp.layer_norm(x, w, b, 1e-5, True)
    want = fused_layer_norm(x, (hidden,), w, b, 1e-5)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=2e-2 if dtype == jnp.bfloat16 else 1e-5)


def test_fwd_no_affine():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(16, 256), jnp.float32)
    got = lnp.layer_norm(x, None, None, 1e-5, True)
    want = fused_layer_norm(x, (256,), None, None, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grads_match_jnp(dtype):
    rows, hidden = 32, 384
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(rows, hidden), dtype)
    w = jnp.asarray(rs.rand(hidden) + 0.5, jnp.float32)
    b = jnp.asarray(rs.randn(hidden), jnp.float32)
    tgt = jnp.asarray(rs.randn(rows, hidden), jnp.float32)

    def loss_pallas(x, w, b):
        y = lnp.layer_norm(x, w, b, 1e-5, True)
        return jnp.sum((y.astype(jnp.float32) - tgt) ** 2)

    def loss_jnp(x, w, b):
        y = fused_layer_norm(x, (hidden,), w, b, 1e-5)
        return jnp.sum((y.astype(jnp.float32) - tgt) ** 2)

    gx, gw, gb = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
    rx, rw, rb = jax.grad(loss_jnp, argnums=(0, 1, 2))(x, w, b)
    tol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               atol=tol, rtol=tol)


def test_unsupported_shapes_detected():
    assert not lnp.supported(64, 100)  # hidden not 128-aligned
    assert not lnp.supported(7, 128)   # rows with no pow2 block >= 8
