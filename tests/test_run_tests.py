"""The console suite runner (apex-tpu-test -> apex_tpu/_run_tests.py,
the port of the reference's tests/L0/run_test.py suite selection) must
know about every test file in this directory — a new test file that is
not in any suite would silently never run under the entry point."""

import os

from apex_tpu import _run_tests


def test_every_test_file_belongs_to_a_suite():
    here = os.path.dirname(os.path.abspath(__file__))
    files = {f for f in os.listdir(here)
             if f.startswith("test_") and f.endswith(".py")}
    covered = {f for suite in _run_tests.SUITES.values() for f in suite}
    missing = files - covered
    assert not missing, (
        f"test files not in any apex-tpu-test suite: {sorted(missing)}")
    # and nothing stale: every listed file must exist
    stale = covered - files
    assert not stale, f"suite entries without files: {sorted(stale)}"
