"""Tile-parameter dispatch (ISSUE 5): table ``params`` payloads,
the shared tile-validity model's checker surface, the consult log, the
jaxpr-level proof that an unpinned consult re-tiles every consuming op
family, check 4 of tools/check_bench_labels.py, and the
autotune_tiles driver's winner/resume/budget/hysteresis logic against
a stubbed measurer.
"""

import importlib
import json
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import dispatch
from apex_tpu.dispatch import tiles
from apex_tpu.ops import attention, attention_pallas
from apex_tpu.telemetry import ledger
from apex_tpu.transformer.functional import fused_softmax as fsm

fln = importlib.import_module("apex_tpu.normalization.fused_layer_norm")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("APEX_DISPATCH", "APEX_DISPATCH_TABLE",
              "APEX_PALLAS_INTERPRET", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_FUSED_LM_HEAD", "APEX_LN_BLOCK_ROWS",
              "APEX_SOFTMAX_BLOCK_ROWS", "APEX_ATTN_BLOCK_Q",
              "APEX_XENT_ROW_BLOCK"):
        monkeypatch.delenv(k, raising=False)

    def reset():
        dispatch._reset_for_tests()
        attention.reset_default_impl()
        attention_pallas.reset_bwd_impl()
        attention_pallas.set_block_q(None)
        fln.USE_PALLAS = None
        fsm.USE_PALLAS = None

    reset()
    yield
    reset()


def _jx(fn, *args):
    return re.sub(r"0x[0-9a-f]+", "0x",
                  str(jax.make_jaxpr(lambda *a: fn(*a))(*args)))


LID = "lg-" + "0" * 10


def _payload(value, ledger_id=LID, **kw):
    return dict({"value": value, "ledger": ledger_id, "pins": {}}, **kw)


def _entry(op, dims, dtype, choice, params=None, backend="cpu",
           ledger_id=LID, **kw):
    return dispatch.make_entry(op, dims, dtype, backend, choice,
                               ledger_id, params=params, **kw)


def _table(tmp_path, monkeypatch, *entries):
    path = tmp_path / "table.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in entries))
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(path))
    dispatch._reset_for_tests()
    return str(path)


# ------------------------------------------------- tile model (checker)

def test_parse_bucket_roundtrip():
    dims = dict(b=8, sq=1024, sk=1024, h=16, d=64)  # pow2 = fixpoint
    assert tiles.parse_bucket(dispatch.bucket(**dims)) == dims
    # non-pow2 dims parse back as their ROUNDED bucket values — the
    # shape the committed legality guarantee is stated at
    assert tiles.parse_bucket(dispatch.bucket(h=12)) == {"h": 16}
    assert tiles.parse_bucket("garbage!") is None
    assert tiles.parse_bucket("") is None


def test_validate_payload_legality_at_bucket_dims():
    bucket = dispatch.bucket(rows=8192, hidden=768)
    ok = tiles.validate_payload("layer_norm", bucket, "bfloat16",
                                _payload({"block_rows": 128}))
    assert ok == []
    bad = tiles.validate_payload("layer_norm", bucket, "bfloat16",
                                 _payload({"block_rows": 100}))
    assert any("multiple of 8" in p for p in bad)
    # over-budget tile
    over = tiles.validate_payload("layer_norm", bucket, "bfloat16",
                                  _payload({"block_rows": 8192}))
    assert any("VMEM budget" in p for p in over)
    # unknown param name
    unk = tiles.validate_payload("layer_norm", bucket, "bfloat16",
                                 _payload({"block_quux": 8}))
    assert any("unknown param" in p for p in unk)
    # missing citation
    nocite = tiles.validate_payload("layer_norm", bucket, "bfloat16",
                                    {"value": {"block_rows": 128}})
    assert any("cite" in p for p in nocite)


def test_runtime_value_skips_malformed_payloads():
    assert tiles.runtime_value("layer_norm",
                               _payload({"block_rows": 64})) \
        == {"block_rows": 64}
    for bad in ("x", {}, {"value": {}}, {"value": {"block_rows": "64"}},
                {"value": {"nope": 64}}, {"value": {"block_rows": True}}):
        assert tiles.runtime_value("layer_norm", bad) is None


def test_validate_params_citation_and_pins():
    rec = ledger.make_record("autotune_tiles", "cpu", 0.5, 2,
                             knobs={"APEX_DISPATCH": "off"}, git="abc",
                             ts=1.0)
    by_id = {rec["id"]: rec}
    e = _entry("layer_norm", dict(rows=8192, hidden=768), "bfloat16",
               "pallas",
               params=_payload({"block_rows": 128}, rec["id"],
                               pins={"APEX_DISPATCH": "off"}),
               ledger_id=rec["id"])
    assert dispatch.validate_params(e, by_id) == []
    # no payload = no findings
    assert dispatch.validate_params(
        _entry("layer_norm", dict(rows=8192, hidden=768), "bfloat16",
               "pallas", ledger_id=rec["id"]), by_id) == []
    # unresolvable params citation
    stale = dict(e, params=_payload({"block_rows": 128}, "lg-ffffffffff"))
    assert any("no ledger record" in p
               for p in dispatch.validate_params(stale, by_id))
    # pin drift vs the cited record
    drift = dict(e, params=_payload({"block_rows": 128}, rec["id"],
                                    pins={"APEX_DISPATCH": "on"}))
    assert any("does not match" in p
               for p in dispatch.validate_params(drift, by_id))
    # fault-stamped citation is refused
    frec = dict(rec, fault_plan="fp-deadbeef")
    assert any("FAULT-INJECTED" in p
               for p in dispatch.validate_params(e, {rec["id"]: frec}))
    # illegal tile at the bucket dims is a finding
    illegal = dict(e, params=_payload({"block_rows": 100}, rec["id"]))
    assert any("multiple of 8" in p
               for p in dispatch.validate_params(illegal, by_id))


# --------------------------------------------- lookup_params + consults

def test_lookup_params_and_consult_log(tmp_path, monkeypatch):
    dims = dict(rows=64, hidden=256)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas",
                  params=_payload({"block_rows": 16})))
    choice, params = dispatch.lookup_params(
        "layer_norm", dtype="float32", backend="cpu", **dims)
    assert choice == "pallas" and params == {"block_rows": 16}
    rows = dispatch.snapshot()["consulted"]
    assert rows == [{"op": "layer_norm", "bucket": "hidden256-rows64",
                     "dtype": "float32", "backend": "cpu",
                     "choice": "pallas", "params": {"block_rows": 16}}]


def test_lookup_params_malformed_payload_falls_back(tmp_path, monkeypatch):
    dims = dict(rows=64, hidden=256)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas",
                  params={"value": {"block_rows": "not-an-int"}}))
    choice, params = dispatch.lookup_params(
        "layer_norm", dtype="float32", backend="cpu", **dims)
    assert choice == "pallas" and params is None  # skip-and-fallback
    # ...and the call still works end-to-end on the heuristic tile
    x = jnp.ones((64, 256), jnp.float32)
    y = fln.fused_layer_norm(x, 256)
    assert np.isfinite(np.asarray(y)).all()


# ------------------------- jaxpr proof: consult re-tiles every family

def test_layer_norm_table_params_change_lowered_blocks(tmp_path,
                                                      monkeypatch):
    """THE acceptance proof: an unpinned consult with a params payload
    lowers different block shapes than the same consult without it."""
    x = jnp.ones((64, 256), jnp.float32)
    dims = dict(rows=64, hidden=256)

    def f(x):
        return fln.fused_layer_norm(x, 256)

    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas"))
    j_heuristic = _jx(f, x)
    assert "pallas_call" in j_heuristic
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas",
                  params=_payload({"block_rows": 8})))
    j_tiled = _jx(f, x)
    assert "pallas_call" in j_tiled
    assert j_tiled != j_heuristic
    # numerics unchanged by the re-tile
    got = np.asarray(f(x))
    monkeypatch.delenv("APEX_DISPATCH_TABLE")
    dispatch._reset_for_tests()
    np.testing.assert_allclose(got, np.asarray(f(x)), atol=1e-6)


def test_layer_norm_setter_and_per_call_beat_table_params(tmp_path,
                                                          monkeypatch):
    from apex_tpu.ops import layer_norm_pallas as lnp

    x = jnp.ones((64, 256), jnp.float32)
    dims = dict(rows=64, hidden=256)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas",
                  params=_payload({"block_rows": 8})))

    def f(x, **kw):
        return fln.fused_layer_norm(x, 256, **kw)

    j_table = _jx(f, x)
    # kernel tile setter outranks the table payload
    lnp.set_block_rows(16)
    j_setter = _jx(f, x)
    assert j_setter != j_table
    # per-call block_rows outranks the setter
    assert _jx(lambda x: f(x, block_rows=8), x) == j_table
    lnp.set_block_rows(None)
    assert _jx(f, x) == j_table


def test_softmax_table_params_change_lowered_blocks(tmp_path, monkeypatch):
    from apex_tpu.transformer.enums import AttnMaskType

    x = jnp.ones((2, 2, 128, 128), jnp.bfloat16)
    dims = dict(b=2, h=2, sq=128, sk=128)

    def make(block_rows=None):
        return fsm.FusedScaleMaskSoftmax(
            input_in_fp16=False, input_in_bf16=True,
            attn_mask_type=AttnMaskType.padding,
            scaled_masked_softmax_fusion=True, mask_func=None,
            softmax_in_fp32=True, scale=None, block_rows=block_rows)

    _table(tmp_path, monkeypatch,
           _entry("softmax", dims, "bfloat16", "pallas"))
    j_heuristic = _jx(lambda x: make()(x, None), x)
    _table(tmp_path, monkeypatch,
           _entry("softmax", dims, "bfloat16", "pallas",
                  params=_payload({"block_rows": 16})))
    j_tiled = _jx(lambda x: make()(x, None), x)
    assert "pallas_call" in j_tiled and j_tiled != j_heuristic
    # the instance-level per-call demand beats the table payload
    assert _jx(lambda x: make(block_rows=16)(x, None), x) == j_tiled
    # an illegal instance demand raises (asymmetry preserved)
    with pytest.raises(ValueError, match="does not divide"):
        make(block_rows=48)(x, None)


def test_attention_table_params_change_lowered_blocks(tmp_path,
                                                      monkeypatch):
    q = jnp.zeros((1, 2, 256, 32), jnp.float32)
    dims = dict(b=1, h=2, sq=256, sk=256, d=32)

    def f(q):
        return attention.fused_attention(q, q, q, causal=True)

    _table(tmp_path, monkeypatch,
           _entry("attention", dims, "float32", "rows"))
    j_heuristic = _jx(f, q)
    assert "pallas_call" in j_heuristic
    _table(tmp_path, monkeypatch,
           _entry("attention", dims, "float32", "rows",
                  params=_payload({"block_q": 32})))
    j_tiled = _jx(f, q)
    assert "pallas_call" in j_tiled and j_tiled != j_heuristic


def test_attention_bwd_table_params_reach_backward(tmp_path, monkeypatch):
    """attention_bwd params (bwd_block_q) re-tile the BACKWARD of an
    unpinned rows call — even though the impl entry itself is the
    monolithic default."""
    q = jnp.ones((1, 1, 256, 32), jnp.float32)
    dims = dict(b=1, h=1, sq=256, sk=256, d=32)

    def loss(q):
        return jnp.sum(attention_pallas.fused_attention_rows(
            q, q, q, False, 0.2, None, True) ** 2)

    j_default = _jx(lambda x: jax.grad(loss)(x), q)
    _table(tmp_path, monkeypatch,
           _entry("attention_bwd", dims, "float32", "monolithic",
                  params=_payload({"bwd_block_q": 32})))
    j_tiled = _jx(lambda x: jax.grad(loss)(x), q)
    assert j_tiled != j_default
    # grads still reference-exact under the table tile
    from apex_tpu.ops.attention import _dense_attention

    g = jax.grad(loss)(q)
    r = jax.grad(lambda x: jnp.sum(
        _dense_attention(x, x, x, False, 0.2, None) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-4)


def test_attention_bwd_dropout_never_consults_the_table(tmp_path,
                                                        monkeypatch):
    """Dropout forces the monolithic backward BEFORE any attention_bwd
    table consult: a consult whose choice can never be honored must not
    land in the snapshot()/ledger consult log (pin-the-label)."""
    q = jnp.ones((1, 1, 256, 32), jnp.float32)
    seed = jnp.zeros((1, 1), jnp.int32)
    dims = dict(b=1, h=1, sq=256, sk=256, d=32)
    _table(tmp_path, monkeypatch,
           _entry("attention_bwd", dims, "float32", "split"))

    def loss(q):
        return jnp.sum(attention_pallas.fused_attention_rows(
            q, q, q, False, 0.2, None, True, None, None, 0.1, seed) ** 2)

    jax.grad(loss)(q)
    assert not any(r["op"] == "attention_bwd"
                   for r in dispatch.snapshot()["consulted"])
    # ...while the dropout-free backward does consult it
    def loss2(q):
        return jnp.sum(attention_pallas.fused_attention_rows(
            q, q, q, False, 0.2, None, True) ** 2)

    jax.grad(loss2)(q)
    assert any(r["op"] == "attention_bwd" and r["choice"] == "split"
               for r in dispatch.snapshot()["consulted"])


def test_lm_head_table_params_change_lowered_blocks(tmp_path,
                                                    monkeypatch):
    from tests.test_dispatch import _gpt

    f, args, cfg = _gpt()
    dims = dict(n=32, v=512, h=128)
    _table(tmp_path, monkeypatch,
           _entry("lm_head", dims, "float32", "fused"))
    j_heuristic = _jx(f, *args)
    assert "pallas_call" in j_heuristic
    _table(tmp_path, monkeypatch,
           _entry("lm_head", dims, "float32", "fused",
                  params=_payload({"row_block": 8})))
    j_tiled = _jx(f, *args)
    assert "pallas_call" in j_tiled and j_tiled != j_heuristic


# ----------------------------------------------------- check 4 (tool)

def test_check_tool_validates_params_payloads(tmp_path):
    """tools/check_bench_labels.py check 4 — in-process main() (the
    subprocess CLI path is already covered by test_dispatch.py)."""
    from tools import check_bench_labels as tool

    rec = ledger.make_record("autotune_tiles", "cpu", 0.5, 2,
                             knobs={"APEX_DISPATCH": "off"}, git="abc",
                             ts=1.0)
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n")
    ok_entry = _entry("layer_norm", dict(rows=8192, hidden=768),
                      "bfloat16", "pallas",
                      params=_payload({"block_rows": 128}, rec["id"],
                                      pins={"APEX_DISPATCH": "off"}),
                      ledger_id=rec["id"])

    def run(entry):
        tpath = tmp_path / "table.jsonl"
        tpath.write_text(json.dumps(entry) + "\n")
        dispatch._reset_for_tests()
        return tool.main(["--perf", str(perf), "--ledger", str(lpath),
                          "--table", str(tpath)])

    assert run(ok_entry) == 0
    # illegal tile at bucket dims
    assert run(dict(ok_entry, params=_payload(
        {"block_rows": 100}, rec["id"]))) == 1
    # unresolvable params citation
    assert run(dict(ok_entry, params=_payload(
        {"block_rows": 128}, "lg-ffffffffff"))) == 1
    # params pin drift
    assert run(dict(ok_entry, params=_payload(
        {"block_rows": 128}, rec["id"],
        pins={"APEX_DISPATCH": "on"}))) == 1
    # malformed payload (runtime would skip-and-fallback; here: FAIL)
    assert run(dict(ok_entry, params={"value": {"block_rows": "x"},
                                      "ledger": rec["id"]})) == 1


def test_committed_table_params_validate():
    """The shipped table's params payloads (the CPU demonstration
    sweep) validate against the committed ledger — tier-1 gate on the
    real artifacts."""
    entries, problems = dispatch.load_table(dispatch.default_path())
    assert problems == []
    recs = ledger.read_ledger()
    by_id = {r.get("id"): r for r in recs}
    with_params = [e for e in entries.values() if "params" in e]
    # the committed demonstration sweep: >= 2 op families carry params
    assert len({e["op"] for e in with_params}) >= 2, with_params
    for e in with_params:
        assert e["backend"] == "cpu"  # never leaks into TPU dispatch
        assert dispatch.validate_params(e, by_id) == [], e


# ------------------------------------------------ autotune_tiles driver

def _seed_ledger(tmp_path, n=1):
    recs = [ledger.make_record("autotune_tiles", "cpu", 0.5, 2,
                               knobs={"APEX_DISPATCH": "off"}, git="abc",
                               ts=float(i)) for i in range(n)]
    path = tmp_path / "ledger.jsonl"
    path.write_text("".join(json.dumps(r, sort_keys=True) + "\n"
                            for r in recs))
    return [r["id"] for r in recs], str(path)


def _fake_runner(values, ledger_id):
    """Stub for autotune_tiles.run_candidate: params-tuple -> ms."""

    def runner(group, params, smoke, ledger_path, timeout, log_dir, tag):
        key = (group["op"], tuple(sorted(params.items())))
        if key not in values:
            return None
        return {"value": values[key], "unit": "ms", "params": params,
                "ledger": ledger_id}
    return runner


def test_autotune_tiles_winner_resume_and_hysteresis(tmp_path,
                                                     monkeypatch):
    from benchmarks import autotune_tiles as at

    ids, lpath = _seed_ledger(tmp_path)
    table = tmp_path / "table.jsonl"
    g = at.sweep_groups(True)[1]  # layer_norm rows=1024 hidden=256
    cands = tiles.candidates(g["op"], g["dims"], g["dtype"], 3)
    # challenger wins by > flip margin
    vals = {(g["op"], tuple(sorted(c.items()))): 10.0 + i
            for i, c in enumerate(cands)}
    best_key = (g["op"], tuple(sorted(cands[-1].items())))
    vals[best_key] = 5.0
    rc = at.main(["--smoke", "--only", "layer_norm", "--table",
                  str(table), "--ledger", lpath],
                 runner=_fake_runner(vals, ids[0]))
    assert rc == 0
    entries, problems = dispatch.load_table(str(table))
    assert problems == []
    e = entries[(g["op"], dispatch.bucket(**g["dims"]), g["dtype"],
                 "cpu")]
    assert e["choice"] == "pallas"
    assert e["params"]["value"] == cands[-1]
    assert e["params"]["ledger"] == ids[0]
    assert e["params"]["pins"] == {"APEX_DISPATCH": "off"}

    # resume: cashed groups are SKIPPED (an exploding runner proves it)
    def boom(*a, **kw):
        raise AssertionError("re-measured a cashed tile rung")

    rc = at.main(["--smoke", "--only", "layer_norm", "--table",
                  str(table), "--ledger", lpath], runner=boom)
    assert rc == 0

    # hysteresis: a 1% challenger keeps the heuristic incumbent
    table2 = tmp_path / "table2.jsonl"
    vals2 = {(g["op"], tuple(sorted(c.items()))): 10.0 for c in cands}
    vals2[best_key] = 9.95
    rc = at.main(["--smoke", "--only", "layer_norm", "--table",
                  str(table2), "--ledger", lpath],
                 runner=_fake_runner(vals2, ids[0]))
    assert rc == 0
    entries, _ = dispatch.load_table(str(table2))
    e = next(e for e in entries.values() if "params" in e)
    assert e["params"]["value"] == cands[0]  # the heuristic tile


def test_autotune_tiles_preserves_step_level_choice(tmp_path,
                                                    monkeypatch):
    """An existing entry for the key keeps its step-level choice and
    citation; the sweep only attaches params — and refuses to attach
    params to an entry whose choice is NOT the swept kernel."""
    from benchmarks import autotune_tiles as at

    ids, lpath = _seed_ledger(tmp_path)
    g = at.sweep_groups(True)[1]
    cands = tiles.candidates(g["op"], g["dims"], g["dtype"], 3)
    vals = {(g["op"], tuple(sorted(c.items()))): 10.0 for c in cands}
    runner = _fake_runner(vals, ids[0])

    # case 1: existing pallas-choice entry — params attach, choice kept
    table = tmp_path / "table.jsonl"
    prior = _entry(g["op"], g["dims"], g["dtype"], "pallas",
                   ledger_id=ids[0], rung="gpt_ln_pallas")
    table.write_text(json.dumps(prior) + "\n")
    dispatch._reset_for_tests()
    assert at.main(["--smoke", "--only", "layer_norm", "--table",
                    str(table), "--ledger", lpath], runner=runner) == 0
    entries, _ = dispatch.load_table(str(table))
    e = next(iter(entries.values()))
    assert e["rung"] == "gpt_ln_pallas" and e["ledger"] == ids[0]
    assert e["params"]["value"] == cands[0]

    # case 2: existing jnp-choice entry — sweep does NOT attach
    table2 = tmp_path / "table2.jsonl"
    prior2 = _entry(g["op"], g["dims"], g["dtype"], "jnp",
                    ledger_id=ids[0])
    table2.write_text(json.dumps(prior2) + "\n")
    dispatch._reset_for_tests()
    assert at.main(["--smoke", "--only", "layer_norm", "--table",
                    str(table2), "--ledger", lpath], runner=runner) == 1
    entries, _ = dispatch.load_table(str(table2))
    assert "params" not in next(iter(entries.values()))


def test_autotune_tiles_budget_drops_are_loud(tmp_path, capsys):
    from benchmarks import autotune_tiles as at

    ids, lpath = _seed_ledger(tmp_path)

    def boom(*a, **kw):
        raise AssertionError("no child may launch at budget 0")

    rc = at.main(["--smoke", "--table", str(tmp_path / "t.jsonl"),
                  "--ledger", lpath, "--budget-s", "0"], runner=boom)
    out = capsys.readouterr().out
    assert rc == 1
    assert "BUDGET DROPPED" in out
    for g in at.sweep_groups(True):
        assert f"{g['op']}/{dispatch.bucket(**g['dims'])}" in out


def test_autotune_tiles_refuses_committed_table_under_fault_plan(
        monkeypatch):
    from benchmarks import autotune_tiles as at

    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "autotune_budget", "kind": "set_budget",
          "budget_s": 0}]))
    with pytest.raises(SystemExit, match="refusing to write"):
        at.main(["--smoke"])


@pytest.mark.slow
def test_autotune_tiles_smoke_end_to_end(tmp_path):
    """The real thing, one family: child subprocesses on CPU, a params
    payload with resolving ledger ids, resume on re-run."""
    import os
    import subprocess
    import sys

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(REPO, "benchmarks", "autotune_tiles.py")
    table = tmp_path / "table.jsonl"
    lpath = tmp_path / "ledger.jsonl"
    args = [sys.executable, script, "--smoke", "--only", "layer_norm",
            "--table", str(table), "--ledger", str(lpath),
            "--max-candidates", "2", "--out", str(tmp_path / "logs")]
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(args, capture_output=True, text=True,
                         timeout=420, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    entries, problems = dispatch.load_table(str(table))
    assert problems == [] and len(entries) == 2, out.stdout
    ids = {r["id"] for r in ledger.read_ledger(str(lpath))}
    by_id = {r["id"]: r for r in ledger.read_ledger(str(lpath))}
    for e in entries.values():
        assert e["params"]["ledger"] in ids
        assert dispatch.validate_params(e, by_id) == [], e
    out2 = subprocess.run(args, capture_output=True, text=True,
                          timeout=120, env=env)
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert out2.stdout.count("— skip") == 2, out2.stdout
