"""Test harness configuration.

Multi-chip behaviour is tested on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU analog of the
reference's single-node multi-process NCCL test base
(apex/transformer/testing/distributed_test_base.py:27-45).

NB: the ``JAX_PLATFORMS`` env var is overridden by the axon TPU plugin in
this environment; ``jax.config.update("jax_platforms", ...)`` is what
actually forces the CPU backend. XLA_FLAGS must still be set before the
backend initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"
if "xla_backend_optimization_level" not in _flags:
    # tests assert semantics, not speed: the CPU backend's O2 pipeline
    # roughly doubles suite compile time for identical pass/fail results
    _flags += " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = _flags.strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# version-compat shims (jax.shard_map on older jax) BEFORE any test
# module import — test files `from jax import shard_map` directly
from apex_tpu import _compat  # noqa: E402

_compat.install()

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def shared_smoke_cache_dir(tmp_path_factory):
    """ONE persistent compile cache for every subprocess smoke-harness
    deep path in the suite (test_compile_cache's scored-line test seeds
    it; test_resilience's chaos deep-path tests reuse it; ISSUE 14
    extended it to test_overlap's profile_overlap smoke CLI — the PR 6
    fast-tier rule: deeper cache sharing, not demotion) — each smoke
    program is identical across its users, so each re-compile after
    the first was pure fast-tier wall time (CLAUDE.md ~5 min budget).
    Tests that assert cold-vs-warm cache SEMANTICS keep their own
    fresh dirs."""
    return str(tmp_path_factory.mktemp("shared_smoke_compile_cache"))


_CBL_MODULE = None


def run_check_bench_labels(*args):
    """Drive tools/check_bench_labels.py main() IN-PROCESS (module
    loaded once per session) and return a subprocess.run-shaped
    ``SimpleNamespace(returncode, stdout, stderr)``. The one shared
    implementation of the fast-tier trim that replaced ~20 × ~3-4s
    checker subprocesses (test_bench_labels keeps a single real CLI
    invocation for the script surface)."""
    import contextlib
    import importlib.util
    import io
    import types

    global _CBL_MODULE
    if _CBL_MODULE is None:
        tool = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "check_bench_labels.py")
        spec = importlib.util.spec_from_file_location(
            "check_bench_labels", tool)
        _CBL_MODULE = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_CBL_MODULE)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        try:
            rc = _CBL_MODULE.main(list(args))
        except SystemExit as e:  # argparse error paths
            rc = e.code if isinstance(e.code, int) else 1
    return types.SimpleNamespace(returncode=rc, stdout=buf.getvalue(),
                                 stderr="")
