"""Test harness configuration.

Multi-chip behaviour is tested on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU analog of the
reference's single-node multi-process NCCL test base
(apex/transformer/testing/distributed_test_base.py:27-45). Must run before
any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
