"""tools/check_bench_labels.py — the PERF.md-caption/ledger cross-check
runs in the tier-1 suite (like tools/check_api_parity.py) and passes on
the repo's own corrected PERF.md + seeded ledger; a seeded drift
fixture (the §10 "68–75 ms over an 82.6 ms log" class) must fail."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu.telemetry import ledger
from tests.conftest import run_check_bench_labels

TOOL = os.path.join(REPO, "tools", "check_bench_labels.py")


# the checker runs IN-PROCESS (conftest.run_check_bench_labels — module
# loaded once): each of the ~20 invocations below used to be a fresh
# subprocess (~4s of python + apex_tpu import apiece — the fast tier's
# single biggest fixed cost); the CLI entry itself keeps one real
# subprocess test (test_repo_perf_and_ledger_are_clean_via_cli)
def _run(*args):
    if "--ledger" in args and "--table" not in args:
        # fixture ledgers can't resolve the COMMITTED dispatch table's
        # citations — point the table check at an empty file so these
        # tests exercise exactly the caption/ledger checks they seed
        args = (*args, "--table", os.devnull)
    return run_check_bench_labels(*args)


def _seed(tmp_path, overhead_ms=82.6):
    rec = ledger.make_record(
        harness="profile_attention", platform="tpu",
        dispatch_overhead_ms=overhead_ms, k=128,
        relay={"degraded": False, "kind": None}, knobs={}, git="abc",
        ts=1000.0)
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    return rec, str(lpath)


def test_repo_perf_and_ledger_are_clean():
    """The tier-1 gate: the committed PERF.md + benchmarks/ledger.jsonl
    pass (the §10 caption now states the cited log's 82.6 ms)."""
    out = _run("--verbose")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_repo_perf_and_ledger_are_clean_via_cli():
    """The same gate through the real CLI entry (the one subprocess
    invocation this file keeps — the in-process `_run` above covers the
    logic; this covers the script surface the driver calls)."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run([sys.executable, TOOL, "--verbose"],
                         capture_output=True, text=True, timeout=120,
                         env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_seeded_drift_fixture_fails(tmp_path):
    rec, lpath = _seed(tmp_path)
    perf = tmp_path / "PERF.md"
    perf.write_text(
        "# fixture\n\nAttention rows (dispatch overhead 68–75 ms "
        f"subtracted; ledger:{rec['id']}):\n\n| a | b |\n")
    out = _run("--perf", str(perf), "--ledger", lpath)
    assert out.returncode == 1, out.stdout
    assert "label drift" in out.stdout


def test_matching_caption_passes(tmp_path):
    rec, lpath = _seed(tmp_path)
    perf = tmp_path / "PERF.md"
    perf.write_text(
        "# fixture\n\nAttention rows (dispatch overhead 82.6 ms "
        f"subtracted; ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", lpath)
    assert out.returncode == 0, out.stdout
    # a range caption passes only when it brackets the measured value
    perf.write_text(
        "# fixture\n\nrows (dispatch overhead 80–85 ms subtracted; "
        f"ledger:{rec['id']}):\n")
    assert _run("--perf", str(perf), "--ledger", lpath).returncode == 0


def test_ab_paragraph_with_two_citations_passes(tmp_path):
    """A comparison paragraph citing TWO records with different
    overheads is legitimate: each stated overhead must match at least
    one cited record, not all of them."""
    rec_a = ledger.make_record("profile_attention", "tpu", 68.3, 128,
                               git="abc", ts=1000.0, knobs={})
    rec_b = ledger.make_record("profile_attention", "tpu", 82.6, 128,
                               git="abc", ts=2000.0, knobs={})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text("".join(json.dumps(r, sort_keys=True) + "\n"
                             for r in (rec_a, rec_b)))
    perf = tmp_path / "PERF.md"
    perf.write_text(
        "# fixture\n\npre-fix run (dispatch overhead 68.3 ms; "
        f"ledger:{rec_a['id']}) vs post-fix (dispatch overhead 82.6 ms; "
        f"ledger:{rec_b['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 0, out.stdout
    # ...but an overhead NEITHER record measured still fails
    perf.write_text(
        f"# fixture\n\nrows (dispatch overhead 75.0 ms; "
        f"ledger:{rec_a['id']} ledger:{rec_b['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1 and "label drift" in out.stdout


def test_unresolved_citation_fails(tmp_path):
    _, lpath = _seed(tmp_path)
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n\nrows (ledger:lg-ffffffffff):\n")
    out = _run("--perf", str(perf), "--ledger", lpath)
    assert out.returncode == 1
    assert "no ledger record" in out.stdout


def test_tampered_record_fails(tmp_path):
    rec, _ = _seed(tmp_path)
    tampered = dict(rec, dispatch_overhead_ms=68.0)  # id now stale
    lpath = tmp_path / "tampered.jsonl"
    lpath.write_text(json.dumps(tampered, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n\nno citations here\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1
    assert "does not match record content" in out.stdout


def test_corrupt_ledger_fails(tmp_path):
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text("not json\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1
    assert "unparseable" in out.stdout


def test_truncated_ledger_line_fails_with_line_number(tmp_path):
    """A line truncated mid-record (a SIGTERM/flap landing mid-append)
    must FAIL the tier-1 check naming file:lineno — never crash the
    checker with a raw JSONDecodeError traceback."""
    rec, _ = _seed(tmp_path)
    good = json.dumps(rec, sort_keys=True)
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(good + "\n" + good[:37] + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout
    assert f"{lpath}:2:" in out.stdout, out.stdout
    assert "Traceback" not in out.stderr and "Traceback" not in out.stdout


def test_scalar_truncated_ledger_line_fails_not_crashes(tmp_path):
    """The nastier truncation: a line cut down to a bare JSON scalar
    still PARSES (`42`), and used to reach the validators as a non-dict
    and crash with an AttributeError — it must be a line-numbered
    finding instead."""
    rec, _ = _seed(tmp_path)
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n42\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout + out.stderr
    assert f"{lpath}:2:" in out.stdout
    assert "not a JSON object" in out.stdout
    assert "Traceback" not in out.stderr and "Traceback" not in out.stdout


def test_fault_stamped_record_citation_is_drift(tmp_path, monkeypatch):
    """A PERF.md caption citing a record produced under APEX_FAULT_PLAN
    (chaos injection) is label drift: injected runs are not
    measurements."""
    monkeypatch.setenv(
        "APEX_FAULT_PLAN",
        json.dumps([{"site": "verdict", "kind": "degraded"}]))
    rec = ledger.make_record(
        harness="bench", platform="tpu", dispatch_overhead_ms=80.0,
        k=16, knobs={}, git="abc", ts=1000.0)
    monkeypatch.delenv("APEX_FAULT_PLAN")
    assert rec["fault_plan"].startswith("fp-")
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nrows (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout
    assert "FAULT-INJECTED" in out.stdout


# ------------------------------------------------ check 5: resume provenance

def _resumed_record(knobs, saved_pins, **extra):
    return ledger.make_record(
        harness="bench", platform="tpu", dispatch_overhead_ms=80.0,
        k=16, knobs=knobs, git="abc", ts=1000.0,
        extra=dict({"resumed_from": {"ckpt": "ck-0123456789ab"[:13],
                                     "step": 32, "pins": saved_pins}},
                   **extra))


def test_resumed_record_with_matching_pins_passes(tmp_path):
    """A resumed run whose measurement pins equal its checkpoint's is
    citable — resume provenance alone is not drift."""
    rec = _resumed_record({"APEX_REMAT": "selective"},
                          {"APEX_REMAT": "selective"})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nresumed row (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 0, out.stdout


def test_resumed_record_with_pin_drift_is_refused(tmp_path):
    """check 5: the restored run's knobs differ from the checkpoint's
    saved pins — the timing row mixes two configs under one label."""
    rec = _resumed_record({"APEX_REMAT": "none"},
                          {"APEX_REMAT": "selective"})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nresumed row (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout
    assert "DIFFERENT measurement pins" in out.stdout
    assert "APEX_REMAT" in out.stdout


def test_infra_knob_difference_is_not_pin_drift(tmp_path):
    """Paths/attempt counters (ledger.INFRA_KNOB_PREFIXES) legitimately
    differ between the saving and the resuming run — not drift."""
    rec = _resumed_record(
        {"APEX_CKPT_RESUME": "1", "APEX_BENCH_ATTEMPT": "2"},
        {"APEX_BENCH_TIMEOUT": "900"})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nresumed row (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 0, out.stdout


def test_cold_start_claim_refuses_resumed_record(tmp_path):
    """check 5: a paragraph claiming a cold start must not cite a
    record that restored checkpointed state, whatever its
    compile-cache counters say."""
    rec = _resumed_record({}, {})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(
        f"# fixture\n\nCold-start compile tax row "
        f"(ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout
    assert "not a cold start" in out.stdout
    # ...and the same citation in a non-cold paragraph is fine
    perf.write_text(f"# fixture\n\nresumed row (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 0, out.stdout


def test_malformed_resume_provenance_is_a_finding(tmp_path):
    rec = ledger.make_record(
        harness="bench", platform="tpu", dispatch_overhead_ms=80.0,
        k=16, knobs={}, git="abc", ts=1000.0,
        extra={"resumed_from": {"ckpt": "ck-0123456789", "step": 32,
                                "pins": "not-a-dict"}})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nrow (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 1, out.stdout


def _seed_mfu(tmp_path, mfu, value=102196.0, b=8, s=1024,
              model_flops=None, peak=197e12):
    """A bench-style record carrying an MFU claim + cost block (check 6:
    the MFU must be arithmetically consistent with the block's flops)."""
    from apex_tpu.telemetry import costs

    if model_flops is None:
        # the consistent value: mfu = model_flops * value / (b*s*peak)
        model_flops = mfu * b * s * peak / value
    cost = dict(costs.null_block(), source="compiled", steps=128,
                model_flops_per_step=model_flops, peak_flops=peak)
    rec = ledger.make_record(
        harness="bench", platform="tpu", dispatch_overhead_ms=82.6,
        k=128, relay={"degraded": False, "kind": None}, knobs={},
        git="abc", ts=1000.0,
        extra={"value": value, "mfu": mfu, "cost": cost,
               "config": {"batch": b, "s": s}})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nbench b={b} (ledger:{rec['id']}):\n")
    return rec, str(lpath), str(perf)


def test_check6_consistent_mfu_passes(tmp_path):
    rec, lpath, perf = _seed_mfu(tmp_path, mfu=0.387)
    out = _run("--perf", perf, "--ledger", lpath)
    assert out.returncode == 0, out.stdout


def test_check6_mfu_cost_drift_fails(tmp_path):
    """A headline MFU that disagrees with its own record's flops
    accounting is the label-drift class in an attribution costume —
    check 6 fails tier-1 on it."""
    rec, lpath, perf = _seed_mfu(tmp_path, mfu=0.45,
                                 model_flops=0.387 * 8 * 1024 * 197e12
                                 / 102196.0)
    out = _run("--perf", perf, "--ledger", lpath)
    assert out.returncode == 1, out.stdout
    assert "MFU/cost arithmetic drift" in out.stdout


def test_check6_null_degraded_block_is_skipped(tmp_path):
    """No block, no claim to check: a null-degraded cost block (the
    backend couldn't report) never fails check 6."""
    from apex_tpu.telemetry import costs

    rec = ledger.make_record(
        harness="bench", platform="tpu", dispatch_overhead_ms=82.6,
        k=128, knobs={}, git="abc", ts=1000.0,
        extra={"value": 102196.0, "mfu": 0.387,
               "cost": costs.null_block(),
               "config": {"batch": 8, "s": 1024}})
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"# fixture\n\nbench b=8 (ledger:{rec['id']}):\n")
    out = _run("--perf", str(perf), "--ledger", str(lpath))
    assert out.returncode == 0, out.stdout


def test_check6_applies_to_dispatch_table_citations(tmp_path):
    """The table side carries the same arithmetic teeth as PERF.md
    captions."""
    rec, lpath, _ = _seed_mfu(tmp_path, mfu=0.45,
                              model_flops=0.387 * 8 * 1024 * 197e12
                              / 102196.0)
    perf = tmp_path / "PERF.md"
    perf.write_text("# no citations\n")
    table = tmp_path / "table.jsonl"
    table.write_text(json.dumps({
        "op": "bench_batch", "bucket": "b8", "dtype": "bfloat16",
        "backend": "tpu", "choice": "8",
        "ledger": rec["id"], "pins": {}}) + "\n")
    out = _run("--perf", str(perf), "--ledger", lpath,
               "--table", str(table))
    assert out.returncode == 1, out.stdout
    assert "MFU/cost arithmetic drift" in out.stdout
