"""Channel-permutation search tests (reference:
apex/contrib/sparsity/permutation_tests/ + permutation_search_kernels).

Strategy per SURVEY §4: verify the search against an independent dense
brute force (all 35 canonical pair groupings, recomputed here from first
principles) and assert the reference's own quality invariants: permuted
2:4 keeps strictly more magnitude than naive 2:4 on structured weights,
and the single-pair case is exactly optimal.
"""

import itertools

import numpy as np
import pytest

from apex_tpu.contrib.sparsity import (
    ASP,
    accelerated_search_for_good_permutation,
    create_mask,
    efficacy,
    exhaustive_search,
    magnitude_after_pruning_rows,
    progressive_channel_swap,
    sum_after_2_to_4,
)
from apex_tpu.contrib.sparsity.permutation_search import _pair_permutations


def naive_kept(mat):
    """Independent numpy 2:4 kept-magnitude (top-2 |w| per group of 4)."""
    a = np.abs(mat).reshape(mat.shape[0], -1, 4)
    return float(np.sort(a, axis=-1)[..., 2:].sum())


def brute_force_pair_optimal(mat8):
    """All 35 distinct 4+4 groupings of 8 columns, dense numpy."""
    best = -1.0
    for ga in itertools.combinations(range(8), 4):
        if 0 not in ga:
            continue
        gb = tuple(c for c in range(8) if c not in ga)
        kept = naive_kept(mat8[:, list(ga + gb)])
        best = max(best, kept)
    return best


def test_pair_permutations_canonical():
    perms = _pair_permutations()
    assert perms.shape == (35, 8)
    for p in perms:
        assert sorted(p) == list(range(8))
    # distinct groupings
    keys = {tuple(sorted(p[:4])) for p in perms}
    assert len(keys) == 35


def test_sum_after_2_to_4_matches_numpy():
    rs = np.random.RandomState(0)
    m = rs.randn(16, 32).astype(np.float32)
    assert np.isclose(float(sum_after_2_to_4(m)), naive_kept(m), rtol=1e-6)


def test_single_pair_exhaustive_is_optimal():
    """With 8 columns the stripe-pair search IS the full search space —
    its result must equal the dense brute force exactly."""
    rs = np.random.RandomState(1)
    for seed in range(3):
        m = np.random.RandomState(seed).randn(32, 8).astype(np.float32)
        permuted, perm, improvement = exhaustive_search(
            m, escape_attempts=0)
        assert np.allclose(permuted, m[:, perm])
        assert np.isclose(naive_kept(permuted), brute_force_pair_optimal(m),
                          rtol=1e-6)
        assert improvement >= -1e-6


def test_exhaustive_search_beats_naive_on_structured_weights():
    """Correlated columns are the case permutation exists for: naive
    grouping wastes magnitude, a permutation recovers it (reference:
    permutation_tests README rationale)."""
    rs = np.random.RandomState(0)
    # 4 "big" column blocks interleaved with small ones so naive groups
    # pair big-with-big (forced to drop a big weight)
    base = rs.randn(64, 8).astype(np.float32)
    m = np.concatenate([base * 10.0, base * 0.1], axis=1)  # cols 0-7 big
    order = np.asarray([0, 8, 1, 9, 2, 10, 3, 11, 4, 12, 5, 13, 6, 14, 7,
                        15])
    m_bad = m[:, np.argsort(order)]  # big columns packed together

    naive = naive_kept(m_bad)
    permuted, perm, improvement = exhaustive_search(m_bad,
                                                    escape_attempts=4,
                                                    seed=0)
    assert improvement > 0
    assert naive_kept(permuted) > naive
    # efficacy vs the unstructured bound must improve
    total = float(np.abs(m_bad).sum())
    optimal = float(magnitude_after_pruning_rows(m_bad))
    eff = efficacy(total - optimal, total - naive,
                   total - naive_kept(permuted))
    assert eff > 0


def test_progressive_channel_swap_improves():
    rs = np.random.RandomState(0)
    base = rs.randn(32, 8).astype(np.float32)
    m = np.concatenate([base * 10.0, base * 0.1], axis=1)
    naive = naive_kept(m)
    permuted, perm, improvement = progressive_channel_swap(
        m, max_attempts=400, seed=0)
    assert np.allclose(permuted, m[:, perm])
    assert improvement > 0
    assert naive_kept(permuted) > naive


def test_search_deterministic_on_fixed_seed():
    m = np.random.RandomState(7).randn(32, 16).astype(np.float32)
    p1 = accelerated_search_for_good_permutation(
        m, {"strategy": "exhaustive", "escape_attempts": 2, "seed": 3})
    p2 = accelerated_search_for_good_permutation(
        m, {"strategy": "exhaustive", "escape_attempts": 2, "seed": 3})
    assert np.array_equal(p1, p2)


def test_asp_allow_permutation_masks():
    """ASP with allow_permutation=True: masks stay valid 2:4 in the
    permuted domain, keep >= the naive mask's magnitude, and the stored
    permutation reproduces the mask."""
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    base = rs.randn(16, 8).astype(np.float32)
    w = np.concatenate([base * 10.0, base * 0.1], axis=1)
    params = {"dense": {"kernel": jnp.asarray(w)}}

    asp = ASP()
    asp.init_model_for_pruning(params, allow_permutation=True,
                               permutation_search_options={
                                   "escape_attempts": 2})
    masks = asp.compute_sparse_masks(params)
    mask = np.asarray(masks["dense"]["kernel"])
    assert mask.shape == w.shape

    (name, perm), = asp.permutations.items()
    # mask is 2:4 in the permuted domain
    mp = mask[:, perm].reshape(16, -1, 4)
    assert (mp.sum(-1) == 2).all()
    # kept magnitude >= naive mask's kept magnitude
    naive_mask = np.asarray(create_mask(jnp.asarray(w), "m4n2_1d"))
    assert (np.abs(w) * mask).sum() >= (np.abs(w) * naive_mask).sum()
