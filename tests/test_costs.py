"""The attribution layer (ISSUE 7): apex_tpu.telemetry.costs cost-block
schema + derivations, the _compat cost/memory normalizers across every
observed jax-0.4.37 shape variant, comm-volume accounting from jaxprs
(incl. the multichip training step), the tiles.py VMEM validation hook,
profiler-capture artifact stamps, the ledger inspection CLI, and the
PR-1 invariant: asking XLA to count a program's flops leaves the traced
jaxpr byte-identical. All CPU-tier, fast (jaxpr traces + one tiny AOT
compile; no subprocesses)."""

import contextlib
import io
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import _compat
from apex_tpu.dispatch import tiles
from apex_tpu.telemetry import costs, ledger, profiling


# ---------------------------------------------------------------- build()


def test_build_derives_floors_and_mfu_bound():
    """The analytic roofline arithmetic: floors = flops/peak and
    bytes/bw, step floor = max, MFU bound = model flops at the floor
    over peak."""
    peak = costs.V5E_PEAK_BF16_FLOPS
    bw = costs.V5E_HBM_BYTES_PER_S
    block = costs.build(
        xla_flops=peak * 1e-3,            # 1 ms/step compute floor
        hbm_bytes=bw * 2e-3,              # 2 ms/step bandwidth floor
        steps=10, model_flops_per_step=peak * 0.9e-3,  # 0.9ms of "model"
        platform="tpu", source="compiled")
    assert block["steps"] == 10  # metadata, never a divisor
    assert block["xla_flops_per_step"] == pytest.approx(peak * 1e-3)
    assert block["compute_floor_ms"] == pytest.approx(1.0)
    assert block["bandwidth_floor_ms"] == pytest.approx(2.0)
    assert block["step_floor_ms"] == pytest.approx(2.0)  # max of the two
    # mfu_bound = model_flops / floor_seconds / peak = 0.9ms-of-peak / 2ms
    assert block["mfu_bound"] == pytest.approx(0.45, abs=1e-4)
    assert costs.validate(block) == []


def test_build_peak_hbm_from_memory_analysis():
    mem = {"argument_size_in_bytes": 100, "output_size_in_bytes": 50,
           "temp_size_in_bytes": 30, "alias_size_in_bytes": 40,
           "generated_code_size_in_bytes": 5}
    block = costs.build(memory=mem, steps=1)
    assert block["peak_hbm_bytes"] == 100 + 50 + 30 + 5 - 40
    assert block["memory"]["temp_size_in_bytes"] == 30
    assert costs.validate(block) == []


def test_build_cpu_platform_has_no_roofline():
    """No committed envelope off-TPU: floors and bound stay None (the
    same rule as bench.py's mfu=None on CPU)."""
    block = costs.build(xla_flops=1e9, hbm_bytes=1e6, steps=1,
                        platform="cpu", source="lowered")
    assert block["peak_flops"] is None
    assert block["compute_floor_ms"] is None
    assert block["mfu_bound"] is None
    assert costs.validate(block) == []


def test_null_block_is_valid_and_all_none():
    block = costs.null_block()
    assert set(block) == set(costs.FIELDS)
    assert all(v is None for v in block.values())
    assert costs.validate(block) == []


def test_capture_without_stage_degrades_not_raises():
    block = costs.capture(lowered=None, compiled=None, steps=4,
                          model_flops_per_step=123.0, platform="cpu")
    assert block["source"] is None
    assert block["xla_flops_per_step"] is None
    assert block["model_flops_per_step"] == 123.0
    assert costs.validate(block) == []


def test_capture_real_aot_stage_reports_xla_numbers():
    """One tiny real AOT pair on CPU: the capture path reads flops and
    memory from the actual jax surfaces through the _compat
    normalizers."""
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((16, 16), jnp.float32)
    lowered = f.lower(x)
    compiled = lowered.compile()
    block = costs.capture(lowered=lowered, compiled=compiled, steps=1,
                          platform="cpu")
    assert block["source"] in ("compiled", "lowered")
    assert block["xla_flops_per_step"] and block["xla_flops_per_step"] > 0
    assert costs.validate(block) == []


def test_memory_key_tuples_stay_in_sync():
    """costs._MEMORY_KEYS (consumer: build/validate) must equal
    _compat._MEMORY_FIELDS (producer: memory_analysis_dict) — the
    tuples are deliberately duplicated (costs stays stdlib-only at
    import; _compat imports jax at module top), so drift between them
    would silently null memory fields and skew peak_hbm_bytes with
    validate() still passing."""
    from apex_tpu import _compat

    assert costs._MEMORY_KEYS == _compat._MEMORY_FIELDS


def test_xla_counts_scan_body_once_calibration():
    """The calibration behind build()'s no-division rule: XLA's
    cost_analysis counts a lax.scan body ONCE, not × trip count, so
    the analyses' numbers are per-step already for a K-scan program.
    If a jax upgrade changes the counting, this fails loudly and
    build()'s semantics must be revisited — otherwise every stamped
    floor/mfu_bound silently goes ~K× wrong again."""
    from apex_tpu import _compat

    def body(c, _):
        return c @ c, None

    x = jnp.ones((64, 64), jnp.float32)
    one = jax.jit(lambda x: x @ x).lower(x)
    scan16 = jax.jit(
        lambda x: jax.lax.scan(body, x, None, length=16)[0]).lower(x)
    f_one = _compat.cost_analysis_dict(one)["flops"]
    f_scan = _compat.cost_analysis_dict(scan16)["flops"]
    assert f_one > 0
    # one body + loop overhead, nowhere near 16 bodies
    assert f_one <= f_scan < 2 * f_one

    block = costs.capture(lowered=scan16, steps=16, platform="cpu")
    assert block["steps"] == 16
    assert block["xla_flops_per_step"] == pytest.approx(f_scan)


def test_capture_escape_hatch_env(monkeypatch):
    """APEX_COST_ANALYSIS=0 skips the XLA reads outright but still
    stamps a (degraded) block — degradation, never omission."""
    monkeypatch.setenv("APEX_COST_ANALYSIS", "0")
    assert costs.enabled(default=True) is False
    f = jax.jit(lambda x: x + 1)
    lowered = f.lower(jnp.ones(4))
    block = costs.capture(lowered=lowered, compiled=None, steps=2,
                          platform="cpu")
    assert block["source"] is None
    assert block["xla_flops_per_step"] is None
    monkeypatch.setenv("APEX_COST_ANALYSIS", "1")
    assert costs.enabled(default=False) is True


# ------------------------------------------------------ validate() teeth


@pytest.mark.parametrize("mutate, frag", [
    (lambda b: b.pop("mfu_bound"), "missing field"),
    (lambda b: b.update(xla_flops_per_step=-1.0), "non-negative"),
    (lambda b: b.update(source="guessed"), "source"),
    (lambda b: b.update(steps=0), "steps"),
    (lambda b: b.update(memory={"argument_size_in_bytes": "big"}),
     "memory.argument_size_in_bytes"),
    (lambda b: b.update(comm_bytes_per_axis={"dp": -5}),
     "comm_bytes_per_axis"),
])
def test_validate_rejects_malformed(mutate, frag):
    block = costs.null_block()
    mutate(block)
    problems = costs.validate(block)
    assert problems and any(frag in p for p in problems), problems


def test_validate_record_polices_cost_block(tmp_path):
    """ledger.validate_record runs the cost validator on every record
    carrying the block — a malformed block is a schema finding."""
    rec = ledger.make_record("bench", "cpu", 0.5, 2, git="abc", ts=1.0,
                             extra={"cost": costs.null_block()})
    assert ledger.validate_record(rec) == []
    bad = dict(costs.null_block(), mfu_bound=-2.0)
    rec2 = ledger.make_record("bench", "cpu", 0.5, 2, git="abc", ts=1.0,
                              extra={"cost": bad})
    assert any("cost:" in p for p in ledger.validate_record(rec2))


# ------------------------------------------------- _compat normalizers


class _Stage:
    def __init__(self, raw=None, raise_=False, absent=False):
        if not absent:
            self._raw, self._raise = raw, raise_
            self.cost_analysis = self._call
            self.memory_analysis = self._call

    def _call(self):
        if self._raise:
            raise NotImplementedError("backend can't report")
        return self._raw


class _MemStats:
    """The CompiledMemoryStats extension-object variant: attributes,
    not keys."""
    argument_size_in_bytes = 64
    output_size_in_bytes = 32
    temp_size_in_bytes = 128
    alias_size_in_bytes = 16
    generated_code_size_in_bytes = 8


def test_cost_analysis_dict_variants():
    # absent method (old stages, custom wrappers)
    assert _compat.cost_analysis_dict(object()) is None
    # returns None / raises (unimplemented backend)
    assert _compat.cost_analysis_dict(_Stage(raw=None)) is None
    assert _compat.cost_analysis_dict(_Stage(raise_=True)) is None
    # Lowered-style flat dict: passed through
    assert _compat.cost_analysis_dict(
        _Stage(raw={"flops": 10.0})) == {"flops": 10.0}
    # Compiled-style list of per-computation dicts: key-wise sum
    out = _compat.cost_analysis_dict(_Stage(raw=[
        {"flops": 10.0, "bytes accessed": 4.0},
        {"flops": 5.0, "transcendentals": 1.0}]))
    assert out == {"flops": 15.0, "bytes accessed": 4.0,
                   "transcendentals": 1.0}
    # degenerate lists
    assert _compat.cost_analysis_dict(_Stage(raw=[])) is None
    assert _compat.cost_analysis_dict(_Stage(raw=["hlo"])) is None
    assert _compat.cost_analysis_dict(_Stage(raw={})) is None
    assert _compat.cost_analysis_dict(_Stage(raw=42)) is None


def test_memory_analysis_dict_variants():
    assert _compat.memory_analysis_dict(object()) is None
    assert _compat.memory_analysis_dict(_Stage(raw=None)) is None
    assert _compat.memory_analysis_dict(_Stage(raise_=True)) is None
    # extension-object variant (attribute read)
    out = _compat.memory_analysis_dict(_Stage(raw=_MemStats()))
    assert out == {"argument_size_in_bytes": 64,
                   "output_size_in_bytes": 32,
                   "temp_size_in_bytes": 128,
                   "alias_size_in_bytes": 16,
                   "generated_code_size_in_bytes": 8}
    # plain-dict variant (key filter; missing fields degrade to 0)
    out = _compat.memory_analysis_dict(
        _Stage(raw={"temp_size_in_bytes": 7, "host_temp_size_in_bytes": 9}))
    assert out["temp_size_in_bytes"] == 7
    assert out["argument_size_in_bytes"] == 0
    assert "host_temp_size_in_bytes" not in out
    # all-zero stats carry no information -> "can't report"
    assert _compat.memory_analysis_dict(
        _Stage(raw={"temp_size_in_bytes": 0})) is None


def test_real_jax_0437_surfaces_normalize():
    """Calibration against the container's actual jax: whatever shapes
    Lowered/Compiled return here, the normalizers fold them into the
    one flat shape (or None) — this is the test that breaks loudly on
    a jax upgrade that changes the surface."""
    f = jax.jit(lambda x: jnp.tanh(x @ x))
    lowered = f.lower(jnp.ones((8, 8), jnp.float32))
    compiled = lowered.compile()
    for stage in (lowered, compiled):
        ca = _compat.cost_analysis_dict(stage)
        assert ca is None or (isinstance(ca, dict) and all(
            isinstance(v, (int, float)) for v in ca.values()))
    ma = _compat.memory_analysis_dict(compiled)
    assert ma is None or set(ma) == set(_compat._MEMORY_FIELDS)
    # at least one of the surfaces must report on CPU jax-0.4.37 —
    # otherwise the whole attribution layer is silently dark
    assert _compat.cost_analysis_dict(compiled) is not None \
        or _compat.cost_analysis_dict(lowered) is not None


# ---------------------------------------------------- comm accounting


def test_comm_from_jaxpr_counts_psum_per_axis():
    mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(4, 2),
                             ("dp", "tp"))

    def f(x):
        return jax.lax.psum(x, "dp") + jax.lax.psum(x, "tp")

    g = jax.shard_map(f, mesh=mesh,
                      in_specs=jax.sharding.PartitionSpec("dp"),
                      out_specs=jax.sharding.PartitionSpec("dp"),
                      check_vma=False)
    x = jnp.ones((8, 16), jnp.float32)
    comm = costs.comm_from_jaxpr(jax.make_jaxpr(g)(x))
    # per-participant payload: the (2,16) f32 shard = 128 bytes per psum
    assert comm == {"dp": 128, "tp": 128}


def test_comm_from_jaxpr_multiplies_scan_trip_count():
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))

    def body(c, _):
        return jax.lax.psum(c, "dp"), ()

    def f(x):
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    g = jax.shard_map(f, mesh=mesh,
                      in_specs=jax.sharding.PartitionSpec(),
                      out_specs=jax.sharding.PartitionSpec(),
                      check_vma=False)
    x = jnp.ones((4,), jnp.float32)  # 16 bytes per psum, x5 iterations
    comm = costs.comm_from_jaxpr(jax.make_jaxpr(g)(x))
    assert comm == {"dp": 80}


def test_comm_from_jaxpr_no_collectives_is_empty_and_never_raises():
    jaxpr = jax.make_jaxpr(lambda x: x * 2)(jnp.ones(4))
    assert costs.comm_from_jaxpr(jaxpr) == {}
    assert costs.comm_from_jaxpr(object()) == {}  # unknown shape: {}


def test_training_comm_bytes_multichip_topology():
    """The dryrun MULTICHIP comm accounting (ROADMAP item 3 seed): a
    (pp=2, dp=2, tp=2) minimal-GPT training step traced to a jaxpr
    reports nonzero collective payload on the axes that exist, and a
    size-1 axis is filtered (its collectives move nothing)."""
    from apex_tpu.transformer.testing.minimal import training_comm_bytes
    from apex_tpu.transformer.testing import TransformerConfig

    devices = jax.devices()
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=2,
        vocab_size=64, max_position_embeddings=8, hidden_dropout=0.0,
        attention_dropout=0.0, bf16=True,
        apply_query_key_layer_scaling=False)
    comm = training_comm_bytes(devices, cfg, (2, 2, 2),
                               num_microbatches=2, micro_batch_size=1,
                               seq_len=8)
    assert comm.get("tp", 0) > 0, comm   # tensor-parallel matmul psums
    assert comm.get("dp", 0) > 0, comm   # grad allreduce
    comm2 = training_comm_bytes(devices, cfg, (2, 4, 1),
                                num_microbatches=2, micro_batch_size=1,
                                seq_len=8)
    assert "tp" not in comm2, comm2      # size-1 axis filtered


# ------------------------------------------------ starvation economics


def test_starvation_verdicts(monkeypatch):
    monkeypatch.delenv("APEX_STARVE_HBM_BYTES", raising=False)
    cap = costs.V5E_HBM_CAPACITY_BYTES
    assert costs.starvation(cap + 1, "tpu") == "exceeds-hbm"
    # no committed threshold: nothing below capacity is flagged
    assert costs.starvation(cap - 1, "tpu") is None
    monkeypatch.setenv("APEX_STARVE_HBM_BYTES", str(2 ** 30))
    assert costs.starvation(2 ** 30 + 1, "tpu") == "starvation-risk"
    assert costs.starvation(2 ** 30 - 1, "tpu") is None
    assert costs.starvation(None, "tpu") is None
    assert costs.starvation(0, "tpu") is None


# ------------------------------------------- tiles VMEM validation hook


def test_tiles_model_vmem_and_compare():
    dims = {"rows": 4096, "hidden": 1024}
    model = tiles.model_vmem_bytes("layer_norm", dims, "float32")
    assert isinstance(model, int) and model > 0
    # within the coarse 4x band in either direction
    res = tiles.compare_vmem("layer_norm", dims, "float32", None,
                             xla_bytes=model * 3)
    assert res["within"] is True and res["ratio"] == 3.0
    # order-of-magnitude drift is the failure the hook exists to catch
    res = tiles.compare_vmem("layer_norm", dims, "float32", None,
                             xla_bytes=model * 10)
    assert res["within"] is False
    # either side unable to report -> None, never a crash
    assert tiles.compare_vmem("layer_norm", dims, "float32", None,
                              xla_bytes=None) is None
    assert tiles.compare_vmem("nope", dims, "float32", None,
                              xla_bytes=100) is None


# --------------------------------------------------- profiler artifacts


def test_artifact_block_hashes_and_tamper_evidence(tmp_path):
    d = tmp_path / "capture"
    d.mkdir()
    (d / "trace.pb").write_bytes(b"abc")
    (d / "meta.json").write_bytes(b"{}")
    block = profiling.artifact_block(str(d))
    assert block["files"] == 2 and block["bytes"] == 5
    assert profiling.validate_block(block) == []
    # tamper evidence: editing a file changes the stamped hash
    (d / "trace.pb").write_bytes(b"abX")
    assert profiling.artifact_block(str(d))["sha256"] != block["sha256"]
    # empty/unreadable dir reports zero files, hash None — still valid
    empty = profiling.artifact_block(str(tmp_path / "nope"))
    assert empty["files"] == 0 and empty["sha256"] is None
    assert profiling.validate_block(empty) == []


def test_profile_validate_block_teeth():
    assert profiling.validate_block("x") == ["profile is not a dict"]
    bad = {"dir": 3, "files": -1, "bytes": "many", "sha256": "short"}
    problems = profiling.validate_block(bad)
    assert len(problems) == 4, problems
    # files without a content hash: the tamper-evidence gap
    assert profiling.validate_block(
        {"dir": "d", "files": 2, "bytes": 5, "sha256": None})


def test_profile_refusal_under_fault_plan(monkeypatch):
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps({"faults": []}))
    assert profiling.refusal() is not None
    monkeypatch.delenv("APEX_FAULT_PLAN")
    assert profiling.refusal() is None


def test_profile_trace_degrades_without_jax_profiler(tmp_path,
                                                     monkeypatch):
    """The feature-detect contract: a backend without a working
    jax.profiler still runs the body (traced=False)."""
    import jax.profiler as jp

    def boom(*a, **k):
        raise RuntimeError("no profiler on this backend")

    monkeypatch.setattr(jp, "trace", boom)
    ran = []
    with profiling.trace(str(tmp_path)) as traced:
        ran.append(traced)
    assert ran == [False]


def test_profile_knob_parsing(monkeypatch):
    monkeypatch.delenv("APEX_PROFILE_TIMEOUT", raising=False)
    assert profiling.timeout_s() == profiling.DEFAULT_TIMEOUT_S
    monkeypatch.setenv("APEX_PROFILE_TIMEOUT", "120")
    assert profiling.timeout_s() == 120
    monkeypatch.setenv("APEX_PROFILE_TIMEOUT", "bogus")
    assert profiling.timeout_s() == profiling.DEFAULT_TIMEOUT_S
    monkeypatch.setenv("APEX_PROFILE_DIR", str("/tmp/x"))
    assert profiling.profile_root() == "/tmp/x"
    monkeypatch.setenv("APEX_PROFILE_CAPTURE", "1")
    assert profiling.requested() is True
    monkeypatch.setenv("APEX_PROFILE_INNER", "1")
    assert profiling.capture_active() is True


# ------------------------------------------------- ledger inspection CLI


def _cli(*args):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = ledger.main(list(args))
    return rc, buf.getvalue()


def _seed_ledger(tmp_path, n=3):
    path = str(tmp_path / "ledger.jsonl")
    ids = []
    for i in range(n):
        rec = ledger.append_record(
            "bench" if i else "profile_gpt", "cpu", 0.5, 2,
            path=path, extra={"cost": costs.null_block(),
                              "value": 100.0 + i})
        ids.append(rec)
    return path, ids


def test_ledger_cli_status_tail_show(tmp_path):
    path, ids = _seed_ledger(tmp_path)
    rc, out = _cli("--ledger", path, "status")
    assert rc == 0
    assert "3 record(s)" in out and "schema findings: 0" in out
    rc, out = _cli("--ledger", path, "tail", "2")
    assert rc == 0
    assert len(out.strip().splitlines()) == 2
    assert ids[-1] in out and "value=102.0" in out
    rc, out = _cli("--ledger", path, "show", ids[0])
    assert rc == 0
    shown = json.loads(out)
    assert shown["id"] == ids[0] and shown["harness"] == "profile_gpt"


def test_ledger_cli_missing_and_corrupt(tmp_path):
    rc, out = _cli("--ledger", str(tmp_path / "nope.jsonl"), "status")
    assert rc == 1 and "no ledger" in out
    path, ids = _seed_ledger(tmp_path, n=1)
    rc, out = _cli("--ledger", path, "show", "lg-nonexistent")
    assert rc == 1 and "no record" in out
    with open(path, "a") as f:
        f.write("{truncated\n")
    rc, out = _cli("--ledger", path, "status")
    assert rc == 1 and "CORRUPT" in out


def test_ledger_cli_flags_schema_findings(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    rec = ledger.make_record("bench", "cpu", 0.5, 2, git="abc", ts=1.0,
                             extra={"cost": {"not": "a block"}})
    with open(path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    rc, out = _cli("--ledger", path, "status")
    assert rc == 1 and "schema findings: 1" in out
    rc, out = _cli("--ledger", path, "show", rec["id"])
    assert rc == 1 and "FINDING" in out


# ------------------------------------------- the disabled-is-free proof


def test_cost_capture_leaves_jaxpr_byte_identical():
    """PR-1 invariant for the attribution layer: running the XLA
    analyses (lower + cost_analysis + memory_analysis + a jaxpr comm
    walk) does not perturb the program it describes — the jaxpr traced
    after a capture is byte-identical to one traced before, and
    identical to a capture-disabled process's trace."""

    def step(params, x):
        h = jnp.tanh(x @ params["w"])
        return {"w": params["w"] - 1e-3 * (h.T @ x)}, h.sum()

    f = jax.jit(step)
    params = {"w": jnp.ones((16, 16), jnp.float32)}
    x = jnp.ones((8, 16), jnp.float32)
    before = str(jax.make_jaxpr(step)(params, x))
    lowered = f.lower(params, x)
    block = costs.capture(lowered=lowered, compiled=lowered.compile(),
                          steps=1, platform="cpu")
    costs.comm_from_jaxpr(jax.make_jaxpr(step)(params, x))
    assert block["source"] is not None
    after = str(jax.make_jaxpr(step)(params, x))
    assert before == after


# ------------------------------------------------- overlap_bound (ISSUE 11)


def test_overlap_bound_arithmetic_and_degradation():
    """compute floor vs comm+host: hideable = min, best overlapped
    step = max; absent inputs degrade field-by-field and an all-absent
    call returns None (the stamp only exists where it says
    something)."""
    assert costs.overlap_bound(1.0) is None
    ob = costs.overlap_bound(2.0, host_ms=0.5, comm_ms=1.0)
    assert ob["comm_host_ms"] == pytest.approx(1.5)
    assert ob["hideable_ms"] == pytest.approx(1.5)   # min(2.0, 1.5)
    assert ob["bound_step_ms"] == pytest.approx(2.0)  # max
    ob = costs.overlap_bound(None, host_ms=0.5)
    assert ob["compute_floor_ms"] is None
    assert ob["comm_ms"] is None
    assert ob["comm_host_ms"] == pytest.approx(0.5)
    assert ob["hideable_ms"] is None and ob["bound_step_ms"] is None


def test_build_stamps_overlap_bound_and_validates():
    peak = costs.V5E_PEAK_BF16_FLOPS
    block = costs.build(xla_flops=peak * 2e-3, steps=4, platform="tpu",
                        source="compiled", host_ms=0.7, comm_ms=0.3)
    ob = block["overlap_bound"]
    assert ob["compute_floor_ms"] == pytest.approx(2.0)
    assert ob["comm_host_ms"] == pytest.approx(1.0)
    assert ob["hideable_ms"] == pytest.approx(1.0)
    assert ob["bound_step_ms"] == pytest.approx(2.0)
    assert costs.validate(block) == []
    # a block WITHOUT the stamp stays clean (optional, like
    # comm_compression — legacy records keep validating)
    assert costs.validate(costs.build(steps=1)) == []


def test_attach_overlap_onto_existing_block():
    block = costs.build(xla_flops=costs.V5E_PEAK_BF16_FLOPS * 1e-3,
                        steps=2, platform="tpu", source="compiled")
    out = costs.attach_overlap(block, host_ms=2.5)
    assert out["overlap_bound"]["hideable_ms"] == pytest.approx(1.0)
    assert out["overlap_bound"]["bound_step_ms"] == pytest.approx(2.5)
    assert "overlap_bound" not in block  # attach copies, never mutates
    # null-degraded base (CPU smoke): the measured host side survives
    out = costs.attach_overlap(costs.null_block(), host_ms=0.2)
    assert out["overlap_bound"]["host_ms"] == pytest.approx(0.2)
    assert out["overlap_bound"]["hideable_ms"] is None
    assert costs.validate(out) == []
    # nothing measured -> block returned untouched, no stamp
    assert "overlap_bound" not in costs.attach_overlap(
        costs.null_block())


def test_overlap_bound_validate_teeth():
    block = costs.build(steps=1, host_ms=1.0)
    good = costs.validate(block)
    assert good == []
    bad = dict(block, overlap_bound="fast")
    assert any("not a dict" in p for p in costs.validate(bad))
    bad = dict(block, overlap_bound=dict(block["overlap_bound"],
                                         host_ms=-1))
    assert any("host_ms" in p for p in costs.validate(bad))
    missing = dict(block["overlap_bound"])
    del missing["comm_host_ms"]
    bad = dict(block, overlap_bound=missing)
    assert any("comm_host_ms" in p for p in costs.validate(bad))
    # ledger.validate_record carries the same teeth via costs.validate
    rec = ledger.make_record("x", "cpu", 0.1, 2, extra={"cost": bad})
    assert any("comm_host_ms" in p for p in ledger.validate_record(rec))
