"""apex_tpu.data ImageFolder pipeline: scan, transforms, prefetch
determinism, and the real-data path of the ImageNet example."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from apex_tpu import data as apex_data

pytestmark = pytest.mark.skipif(not apex_data.imagefolder.HAVE_PIL,
                                reason="Pillow not installed")


@pytest.fixture()
def fake_tree(tmp_path):
    from PIL import Image

    rs = np.random.RandomState(0)
    for split in ("train", "val"):
        for cls in ("ants", "bees"):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(6):
                arr = rs.randint(0, 255, (50, 40, 3), np.uint8)
                Image.fromarray(arr).save(d / f"img_{i}.jpg")
    return tmp_path


def test_imagefolder_scan(fake_tree):
    ds = apex_data.ImageFolder(fake_tree / "train")
    assert ds.classes == ["ants", "bees"]
    assert len(ds) == 12
    paths, labels = zip(*ds.samples)
    assert sorted(set(labels)) == [0, 1]
    assert all(p.endswith(".jpg") for p in paths)


def test_transforms_shape_and_range(fake_tree):
    from PIL import Image

    ds = apex_data.ImageFolder(fake_tree / "train")
    with Image.open(ds.samples[0][0]) as img:
        tr = apex_data.train_transform(32)(img)
        ev = apex_data.eval_transform(48, 32)(img)
    for out in (tr, ev):
        assert out.shape == (32, 32, 3) and out.dtype == np.float32
        # /255.0 normalization is inclusive at 1.0: JPEG compression can
        # saturate pixels to 255 even though the fixture draws < 255
        assert 0.0 <= out.min() and out.max() <= 1.0


def test_prefetch_batches_and_determinism(fake_tree):
    ds = apex_data.ImageFolder(fake_tree / "train")
    # the RANDOM transform: per-sample seeded rngs make augmentation
    # deterministic under a fixed (seed, epoch) across thread schedules
    tf = apex_data.train_transform(32)

    def run():
        return list(apex_data.prefetch(ds, 5, tf, shuffle=True,
                                       drop_last=True, seed=7, epoch=1,
                                       num_workers=3, prefetch_batches=2))

    a, b = run(), run()
    assert len(a) == 12 // 5  # drop_last
    for (ia, la), (ib, lb) in zip(a, b):
        assert ia.shape == (5, 32, 32, 3) and la.shape == (5,)
        np.testing.assert_array_equal(ia, ib)  # same seed+epoch → identical
        np.testing.assert_array_equal(la, lb)
    # a different epoch shuffles differently
    c = list(apex_data.prefetch(ds, 5, tf, shuffle=True, drop_last=True,
                                seed=7, epoch=2))
    assert not all(np.array_equal(x[1], y[1]) for x, y in zip(a, c))


def test_prefetch_shard_equalizes_batch_counts(fake_tree):
    """Uneven dataset / world: every rank must get the SAME number of
    batches (an SPMD consumer runs one collective per batch), and the
    ranks' samples must not overlap."""
    ds = apex_data.ImageFolder(fake_tree / "train")
    ds.samples = ds.samples[:11]  # odd count across world=2
    tf = apex_data.eval_transform(48, 32)

    def batches(rank):
        return list(apex_data.prefetch(ds, 2, tf, shuffle=True, seed=3,
                                       epoch=0, shard=(rank, 2)))

    b0, b1 = batches(0), batches(1)
    assert len(b0) == len(b1) == 2  # 11 -> 10 shared -> 5/rank -> 2 each
    # disjointness via the decoded pixels (deterministic transform)
    flat0 = {x.tobytes() for imgs, _ in b0 for x in imgs}
    flat1 = {x.tobytes() for imgs, _ in b1 for x in imgs}
    assert not (flat0 & flat1)


@pytest.mark.slow
def test_dcgan_example_trains_on_real_images(fake_tree):
    """The DCGAN example's image-folder path (reference --dataset folder):
    two steps on PIL-decoded reals, finite D/G losses."""
    from examples.dcgan.main_amp import main

    lossD, lossG = main([str(fake_tree / "train"), "--steps", "2",
                         "-b", "8", "--image-size", "64",
                         "--ngf", "8", "--ndf", "8", "--nz", "16"])
    assert np.isfinite(lossD) and np.isfinite(lossG)


@pytest.mark.slow
def test_imagenet_example_trains_on_real_images(fake_tree):
    """The example's real-data path end to end: train 2 steps + the
    --evaluate path on the PIL-decoded fake tree (2 classes; the NOTE
    branch overrides --num-classes)."""
    from PIL import Image

    from examples.imagenet.main_amp import main

    # grow the train split so b=8 (divisible by the 8-device mesh) still
    # yields 3 batches — step 0 is compile-excluded, so at least two
    # measured steps feed the returned average loss
    rs = np.random.RandomState(1)
    for cls in ("ants", "bees"):
        d = fake_tree / "train" / cls
        for i in range(6, 14):
            arr = rs.randint(0, 255, (50, 40, 3), np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.jpg")

    ck = str(fake_tree / "ckpt.pkl")
    loss = main([str(fake_tree), "--arch", "resnet18", "--steps", "3",
                 "-b", "8", "--image-size", "32", "--opt-level", "O2",
                 "--checkpoint", ck])
    assert np.isfinite(loss) and loss > 0.0
    # --evaluate returns the average val loss (full val set: 12 images,
    # b=8 -> 1 batch, with the tail-drop NOTE printed)
    val_loss = main([str(fake_tree), "--arch", "resnet18",
                     "-b", "8", "--image-size", "32", "--opt-level", "O2",
                     "--checkpoint", ck, "--resume", ck, "--evaluate"])
    assert np.isfinite(val_loss) and val_loss > 0.0
