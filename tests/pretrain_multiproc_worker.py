"""2-process worker for the multi-host transformer pretrain test
(launched by ``python -m apex_tpu.parallel.multiproc`` from
tests/test_multiproc.py). Each process owns 1 virtual CPU device; the
(dp=2, tp=1) mesh spans both, so grad pmean and found_inf pmax cross
process boundaries."""

import os
import sys

import jax

# CPU backend BEFORE distributed init (axon plugin owns the default)
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                "..")))

import numpy as np  # noqa: E402


def run():
    from apex_tpu.transformer.testing import global_vars
    from examples.transformer.pretrain import main

    tp = os.environ.get("APEX_TEST_TP", "1")  # tp=2 -> TP over DCN
    global_vars.destroy_global_vars()
    out = main(["--model", "gpt", "--num-layers", "2", "--hidden-size",
                "64", "--num-attention-heads", "4",
                "--max-position-embeddings", "64", "--seq-length", "32",
                "--micro-batch-size", "2", "--vocab-size", "256",
                "--make-vocab-size-divisible-by", "32",
                "--tensor-model-parallel-size", tp,
                "--optimizer", "adam", "--lr", "1e-3", "--bf16",
                "--train-iters", "4", "--log-interval", "2"])
    assert np.isfinite(out["loss"]), out
    assert jax.process_count() == 2
    print(f"PRETRAIN_MULTIPROC_OK rank={jax.process_index()} "
          f"loss={out['loss']:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(run())
