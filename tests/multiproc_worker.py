"""Worker for the multiproc 2-process smoke test (launched by
tests/test_multiproc.py via ``python -m apex_tpu.parallel.multiproc``).

Mirrors what the reference's distributed test base does in each spawned
rank (apex/transformer/testing/distributed_test_base.py:58-78): init the
process group, run one collective, check the result.
"""

import os
import sys

import jax

# Force the CPU backend BEFORE distributed init: the axon TPU plugin owns
# the default platform in this environment and cannot be shared by two
# processes (same trick as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")

from apex_tpu.parallel.multiproc import init_distributed  # noqa: E402


def main():
    ran = init_distributed()
    assert ran, "worker must be launched by apex_tpu.parallel.multiproc"
    import jax.numpy as jnp

    rank = jax.process_index()
    world = jax.process_count()
    assert world == int(os.environ["APEX_TPU_NUM_PROCESSES"])

    n_local = jax.local_device_count()
    # psum over ALL global devices (2 processes x local devices)
    x = jnp.broadcast_to(jnp.float32(rank + 1), (n_local, 1))
    total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
    want = sum((r + 1) * n_local for r in range(world))
    got = float(total[0, 0])
    assert got == want, f"psum mismatch: got {got}, want {want}"
    print(f"MULTIPROC_OK rank={rank}/{world} psum={got}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
