"""apex_tpu.dispatch — the per-shape measured-dispatch table.

Pins the subsystem's contract: precedence (per-call knob > process-wide
setter > table entry > built-in default), the explicit-request-raises /
preference-falls-back asymmetry, table-miss and corrupt-line fallback,
and — the acceptance bar — that a table entry REALLY changes the traced
program end-to-end for every consulting op family (LN, softmax,
attention, LM head, remat, LAMB), plus the autotune driver's
winner/resume/budget/hysteresis logic against a stubbed measurer.
"""

import importlib
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import dispatch
from apex_tpu.ops import attention, attention_pallas
from apex_tpu.telemetry import ledger
from apex_tpu.transformer.functional import fused_softmax as fsm

# the REAL module, not the function the package re-exports under the
# same name — `from apex_tpu.normalization import fused_layer_norm`
# resolves to the function, and setting USE_PALLAS on it silently
# changes nothing (the pre-round-6 APEX_LN_PALLAS bug; see
# fused_layer_norm.set_use_pallas)
fln = importlib.import_module("apex_tpu.normalization.fused_layer_norm")


@pytest.fixture(autouse=True)
def _clean_dispatch(monkeypatch):
    """Unpin every process-wide knob and drop table caches around each
    test — precedence tests must start from the shipped (unpinned)
    state."""
    for k in ("APEX_DISPATCH", "APEX_DISPATCH_TABLE",
              "APEX_PALLAS_INTERPRET", "APEX_ATTN_IMPL", "APEX_LN_PALLAS",
              "APEX_FUSED_LM_HEAD", "APEX_REMAT", "APEX_LAMB_IMPL"):
        monkeypatch.delenv(k, raising=False)

    def reset():
        dispatch._reset_for_tests()
        attention.reset_default_impl()
        attention_pallas.reset_bwd_impl()
        fln.USE_PALLAS = None
        fsm.USE_PALLAS = None

    reset()
    yield
    reset()


def _jx(fn, *args):
    """Trace with a FRESH function object. jax's jit trace cache is
    keyed on the function identity, so re-tracing the same lambda after
    a table change would reuse the stale jaxpr — "trace-time consult"
    means exactly that: a process re-building its functions (as jit
    users do per trace) sees the table; an already-traced program does
    not."""
    return str(jax.make_jaxpr(lambda *a: fn(*a))(*args))


def _table(tmp_path, monkeypatch, *entries):
    path = tmp_path / "table.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in entries))
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(path))
    dispatch._reset_for_tests()
    return str(path)


def _entry(op, dims, dtype, choice, backend="cpu", ledger_id="lg-" + "0" * 10,
           **kw):
    return dispatch.make_entry(op, dims, dtype, backend, choice, ledger_id,
                               **kw)


# ------------------------- table mechanics ---------------------------------

def test_bucket_rounds_up_to_pow2_and_sorts_dims():
    assert dispatch.bucket(sq=1000, b=7) == "b8-sq1024"
    assert dispatch.bucket(b=8) == "b8"  # exact pow2 unchanged
    assert dispatch.bucket(n=1) == "n1"
    # producers and consumers cannot disagree on dim order
    assert dispatch.bucket(a=2, z=2) == dispatch.bucket(z=2, a=2)


def test_lookup_miss_and_off_switch(tmp_path, monkeypatch):
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dict(rows=64, hidden=256), "float32",
                  "pallas"))
    hit = dict(rows=64, hidden=256)
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           **hit) == "pallas"
    # miss: different bucket / dtype / backend / op
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           rows=8192, hidden=256) is None
    assert dispatch.lookup("layer_norm", dtype="bfloat16", backend="cpu",
                           **hit) is None
    assert dispatch.lookup("layer_norm", dtype="float32", backend="tpu",
                           **hit) is None
    assert dispatch.lookup("softmax", dtype="float32", backend="cpu",
                           **hit) is None
    # APEX_DISPATCH=off disables the table wholesale
    monkeypatch.setenv("APEX_DISPATCH", "off")
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           **hit) is None


def test_corrupt_line_falls_back_but_good_lines_survive(tmp_path,
                                                        monkeypatch):
    path = tmp_path / "table.jsonl"
    good = _entry("layer_norm", dict(rows=64, hidden=256), "float32",
                  "pallas")
    path.write_text("{not json!!\n" + json.dumps(good) + "\n"
                    + json.dumps({"op": "softmax"}) + "\n")
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(path))
    dispatch._reset_for_tests()
    entries, problems = dispatch.load_table()
    assert len(entries) == 1 and len(problems) == 2  # corrupt + incomplete
    # runtime dispatch still serves the good entry — a corrupt line
    # degrades to the built-in default for ITS key only
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           rows=64, hidden=256) == "pallas"


def test_invalid_choice_is_a_miss(tmp_path, monkeypatch):
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dict(rows=64, hidden=256), "float32",
                  "warp_shuffle"))
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           rows=64, hidden=256) is None


def test_last_entry_wins_append_to_update(tmp_path, monkeypatch):
    dims = dict(rows=64, hidden=256)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dims, "float32", "pallas"),
           _entry("layer_norm", dims, "float32", "jnp"))
    assert dispatch.lookup("layer_norm", dtype="float32", backend="cpu",
                           **dims) == "jnp"


def test_validate_entry_pins_against_ledger():
    rec = ledger.make_record("profile_gpt", "cpu", 0.5, 2,
                             knobs={"APEX_ATTN_IMPL": "rows"}, git="abc",
                             ts=1.0)
    by_id = {rec["id"]: rec}
    ok = _entry("attention", dict(b=8), "bfloat16", "rows",
                ledger_id=rec["id"], pins={"APEX_ATTN_IMPL": "rows"})
    assert dispatch.validate_entry(ok, by_id) == []
    # unresolvable citation
    bad = dict(ok, ledger="lg-ffffffffff")
    assert any("no ledger record" in p
               for p in dispatch.validate_entry(bad, by_id))
    # pin disagrees with what the record measured — label drift
    drift = dict(ok, pins={"APEX_ATTN_IMPL": "flash"})
    assert any("does not match" in p
               for p in dispatch.validate_entry(drift, by_id))
    # pin says unset but the record pinned it
    unset = dict(ok, pins={"APEX_ATTN_IMPL": None})
    assert any("pinned" in p for p in dispatch.validate_entry(unset, by_id))
    # unknown vocabulary
    vocab = dict(ok, choice="dense")
    assert any("not in" in p for p in dispatch.validate_entry(vocab, by_id))


# ------------------------- precedence: attention ----------------------------

def _q(b=1, h=2, s=128, d=32, dtype=jnp.float32):
    return jnp.zeros((b, h, s, d), dtype)


def test_attention_precedence(tmp_path, monkeypatch):
    q = _q()
    _table(tmp_path, monkeypatch,
           _entry("attention", dict(b=1, h=2, sq=128, sk=128, d=32),
                  "float32", "rows"))
    # table entry drives the unpinned choice
    assert attention._effective_impl(None, q, q) == ("rows", True)
    # process-wide setter beats the table
    attention.set_default_impl("flash")
    assert attention._effective_impl(None, q, q) == ("flash", False)
    # per-call knob beats everything
    assert attention._effective_impl("rows", q, q) == ("rows", False)
    # explicit un-honorable request raises (never silently falls back)
    with pytest.raises(ValueError):
        attention.fused_attention(q, q, q, impl="bogus")
    with pytest.raises(ValueError):
        attention.set_default_impl("bogus")


def test_attention_table_flip_changes_traced_program(tmp_path, monkeypatch):
    q = _q()

    def f(q):
        return attention.fused_attention(q, q, q, causal=True)

    default_jx = _jx(f, q)
    assert "pallas_call" not in default_jx  # cpu default: dense path
    _table(tmp_path, monkeypatch,
           _entry("attention", dict(b=1, h=2, sq=128, sk=128, d=32),
                  "float32", "rows"))
    table_jx = _jx(f, q)
    # the CPU-measured table choice runs the rows kernel in interpret
    # mode — the way it was measured (autotune --smoke)
    assert "pallas_call" in table_jx


def test_attention_bwd_precedence(tmp_path, monkeypatch):
    q = _q()
    _table(tmp_path, monkeypatch,
           _entry("attention_bwd", dict(b=1, h=2, sq=128, sk=128, d=32),
                  "float32", "split"))
    assert attention_pallas._effective_bwd_impl(q, q) == "split"
    attention_pallas.set_bwd_impl("monolithic")
    assert attention_pallas._effective_bwd_impl(q, q) == "monolithic"
    attention_pallas.reset_bwd_impl()
    assert attention_pallas._effective_bwd_impl(q, q) == "split"
    # miss at another bucket -> built-in default
    big = jnp.zeros((1, 2, 256, 32), jnp.float32)
    assert attention_pallas._effective_bwd_impl(big, big) == "monolithic"


def test_attention_bwd_explicit_split_still_raises_when_ineligible():
    # the asymmetry survives the table layer: an explicit per-call
    # bwd_impl="split" on an ineligible shape raises (sq/bq > 32 chunks)
    q = jnp.zeros((1, 1, 8192, 64), jnp.bfloat16)

    def loss(q):
        return attention_pallas.fused_attention_rows(
            q, q, q, False, 1.0, None, True, None, "split").sum()

    with pytest.raises(ValueError, match="split bwd ineligible"):
        jax.grad(loss)(q)


# ------------------------- precedence: layer norm ---------------------------

def test_layer_norm_precedence_and_flip(tmp_path, monkeypatch):
    x = jnp.ones((64, 256), jnp.float32)

    def f(x):
        return fln.fused_layer_norm(x, 256)

    assert "pallas_call" not in _jx(f, x)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dict(rows=64, hidden=256), "float32",
                  "pallas"))
    # table drives the unpinned choice; cpu entry -> interpret kernel
    assert "pallas_call" in _jx(f, x)
    # numerics parity: toggling the table never changes semantics
    got = np.asarray(f(x))
    dispatch._reset_for_tests()
    monkeypatch.delenv("APEX_DISPATCH_TABLE")
    want = np.asarray(f(x))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_layer_norm_setter_and_per_call_beat_table(tmp_path, monkeypatch):
    x = jnp.ones((64, 256), jnp.float32)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dict(rows=64, hidden=256), "float32",
                  "pallas"))

    def f(x):
        return fln.fused_layer_norm(x, 256)

    # module-level setter (False) pins ABOVE the table
    fln.USE_PALLAS = False
    assert "pallas_call" not in _jx(f, x)
    # ...and True is still gated on a real TPU (preference falls back)
    fln.USE_PALLAS = True
    assert "pallas_call" not in _jx(f, x)
    fln.USE_PALLAS = None
    # per-call use_pallas=False pins below nothing — it wins outright
    assert "pallas_call" not in _jx(
        lambda x: fln.fused_layer_norm(x, 256, use_pallas=False), x)
    # table applies again once unpinned
    assert "pallas_call" in _jx(f, x)
    # a table hit for an UNSUPPORTED shape falls back silently
    # (preference semantics: hidden not lane-aligned)
    _table(tmp_path, monkeypatch,
           _entry("layer_norm", dict(rows=64, hidden=100), "float32",
                  "pallas"))
    x2 = jnp.ones((64, 100), jnp.float32)
    assert "pallas_call" not in _jx(
        lambda x: fln.fused_layer_norm(x, 100), x2)


# ------------------------- precedence: softmax ------------------------------

def _softmax_inst(use_pallas=None):
    from apex_tpu.transformer.enums import AttnMaskType

    return fsm.FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=True,
        attn_mask_type=AttnMaskType.padding,
        scaled_masked_softmax_fusion=True,
        mask_func=None, softmax_in_fp32=True, scale=None,
        use_pallas=use_pallas)


def test_softmax_precedence_and_flip(tmp_path, monkeypatch):
    x = jnp.ones((2, 2, 128, 128), jnp.bfloat16)
    sm = _softmax_inst()

    def f(x):
        return sm(x, None)

    assert "pallas_call" not in _jx(f, x)
    _table(tmp_path, monkeypatch,
           _entry("softmax", dict(b=2, h=2, sq=128, sk=128), "bfloat16",
                  "pallas"))
    assert "pallas_call" in _jx(f, x)
    # module setter beats table
    fsm.set_use_pallas(False)
    assert "pallas_call" not in _jx(f, x)
    fsm.set_use_pallas(None)
    # per-instance pin beats everything
    sm_pinned = _softmax_inst(use_pallas=False)
    assert "pallas_call" not in _jx(lambda x: sm_pinned(x, None), x)
    with pytest.raises(ValueError):
        fsm.set_use_pallas("yes")


# ------------------------- model: LM head + remat ---------------------------

def _gpt(tmp_path=None, monkeypatch=None, **cfg_kw):
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    cfg = TransformerConfig(
        hidden_size=128, num_layers=2, num_attention_heads=4,
        vocab_size=512, max_position_embeddings=32, hidden_dropout=0.0,
        attention_dropout=0.0, **cfg_kw)
    model = GPTModel(cfg)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    rs = np.random.RandomState(0)
    b, s = 2, 16
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))

    def run(ids, pos, labels):
        params = model.init(jax.random.PRNGKey(0), ids, pos, None)["params"]
        return model.apply({"params": params}, ids, pos, None, labels)

    from jax import shard_map

    f = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                  check_vma=False)
    return f, (ids, pos, labels), cfg


def test_lm_head_table_flip(tmp_path, monkeypatch):
    f, args, cfg = _gpt()
    assert "pallas_call" not in _jx(f, *args)
    # n = b*s = 32, v = 512, h = 128 (the model's trace-time lookup key)
    _table(tmp_path, monkeypatch,
           _entry("lm_head", dict(n=32, v=512, h=128), "float32", "fused"))
    assert "pallas_call" in _jx(f, *args)
    # config pin (False) beats the table
    f2, args2, _ = _gpt(fused_lm_head=False)
    assert "pallas_call" not in _jx(f2, *args2)


def test_remat_table_flip_and_none_pin(tmp_path, monkeypatch):
    f, args, cfg = _gpt()
    default_jx = _jx(f, *args)
    assert "remat" not in default_jx
    _table(tmp_path, monkeypatch,
           _entry("remat", dict(b=2, s=16, h=128, layers=2), "float32",
                  "full"))
    assert "remat" in _jx(f, *args)
    # explicit "none" pins recompute OFF above the table
    f2, args2, _ = _gpt(recompute_granularity="none")
    assert "remat" not in _jx(f2, *args2)
    # explicit "selective" still honored with the table present
    f3, args3, _ = _gpt(recompute_granularity="selective")
    assert "remat" in _jx(f3, *args3)


# ------------------------- precedence: FusedLAMB ----------------------------

def test_lamb_table_flip_and_precedence(tmp_path, monkeypatch):
    from apex_tpu.optimizers.fused_lamb import fused_lamb

    params = {"w": jnp.ones((128, 128), jnp.float32)}
    grads = {"w": jnp.full((128, 128), 1e-3, jnp.float32)}

    def jx_of(tx):
        st = tx.init(params)
        return str(jax.make_jaxpr(
            lambda g, s, p: tx.update(g, s, p))(grads, st, params))

    default_jx = jx_of(fused_lamb(1e-3))
    _table(tmp_path, monkeypatch,
           _entry("lamb", dict(n=16384), "float32", "one_pass"))
    table_jx = jx_of(fused_lamb(1e-3))
    assert table_jx != default_jx  # one_pass = segment-sum flat sweep
    assert "segment" in table_jx or "scatter" in table_jx
    # env preference beats table
    monkeypatch.setenv("APEX_LAMB_IMPL", "two_pass")
    assert jx_of(fused_lamb(1e-3)) == default_jx
    # per-call impl beats env
    monkeypatch.setenv("APEX_LAMB_IMPL", "one_pass")
    assert jx_of(fused_lamb(1e-3, impl="two_pass")) == default_jx


# ------------------------- autotune driver ----------------------------------

def _seed_ledger(tmp_path, n=1):
    recs = [ledger.make_record("profile_gpt", "cpu", 0.5, 2, knobs={},
                               git="abc", ts=float(i)) for i in range(n)]
    path = tmp_path / "ledger.jsonl"
    path.write_text("".join(json.dumps(r, sort_keys=True) + "\n"
                            for r in recs))
    return [r["id"] for r in recs], str(path)


def _fake_measure(values):
    """Stub for autotune_steps._measure: rung.variant -> value (ms or
    tokens/s per the group's unit), all citing the seeded ledger id."""

    def measure(group, vname, venv, ctx):
        key = f"{group['name']}.{vname}"
        if key not in values:
            return None
        unit = "tokens/s" if group.get("metric") == "tokens_per_sec" \
            else "ms"
        return {"value": values[key], "unit": unit,
                "ledger": values.get("_ledger"),
                "pins": dict(venv) if isinstance(venv, dict) else {},
                "n_params": 1000}
    return measure


def test_autotune_writes_winner_and_resumes(tmp_path, monkeypatch):
    from benchmarks import autotune_steps as at

    ids, lpath = _seed_ledger(tmp_path)
    table = tmp_path / "table.jsonl"
    vals = {"gpt_rows.flash": 50.0, "gpt_rows.rows": 40.0,
            "_ledger": ids[0]}
    monkeypatch.setattr(at, "_measure", _fake_measure(vals))
    rc = at.main(["--smoke", "--only", "gpt_rows", "--table", str(table),
                  "--ledger", lpath])
    assert rc == 0
    entries, problems = dispatch.load_table(str(table))
    assert problems == [] and len(entries) == 1
    e = next(iter(entries.values()))
    assert e["choice"] == "rows" and e["ledger"] == ids[0]
    assert e["pins"] == {"APEX_ATTN_IMPL": "rows"}
    assert e["measured"]["flash"]["value"] == 50.0

    # second invocation: the cashed rung is SKIPPED (resume contract) —
    # a measurer that explodes proves no measurement ran
    def boom(*a, **kw):
        raise AssertionError("re-measured a cashed rung")

    monkeypatch.setattr(at, "_measure", boom)
    rc = at.main(["--smoke", "--only", "gpt_rows", "--table", str(table),
                  "--ledger", lpath])
    assert rc == 0

    # ...but a STALE entry (ledger id no longer resolves) re-runs
    stale = dict(e, ledger="lg-ffffffffff")
    table.write_text(json.dumps(stale) + "\n")
    dispatch._reset_for_tests()
    monkeypatch.setattr(at, "_measure", _fake_measure(vals))
    assert at.main(["--smoke", "--only", "gpt_rows", "--table", str(table),
                    "--ledger", lpath]) == 0
    entries, _ = dispatch.load_table(str(table))
    assert next(iter(entries.values()))["ledger"] == ids[0]


def test_autotune_flip_margin_keeps_default(tmp_path, monkeypatch):
    from benchmarks import autotune_steps as at

    ids, lpath = _seed_ledger(tmp_path)
    table = tmp_path / "table.jsonl"
    # rows ahead by 1% — inside the hysteresis margin
    vals = {"gpt_rows.flash": 50.0, "gpt_rows.rows": 49.5,
            "_ledger": ids[0]}
    monkeypatch.setattr(at, "_measure", _fake_measure(vals))
    assert at.main(["--smoke", "--only", "gpt_rows", "--table", str(table),
                    "--ledger", lpath]) == 0
    entries, _ = dispatch.load_table(str(table))
    assert next(iter(entries.values()))["choice"] == "flash"


def test_autotune_budget_drops_are_loud(tmp_path, monkeypatch, capsys):
    from benchmarks import autotune_steps as at

    ids, lpath = _seed_ledger(tmp_path)
    table = tmp_path / "table.jsonl"
    monkeypatch.setattr(at, "_measure", _fake_measure(
        {"gpt_rows.flash": 50.0, "gpt_rows.rows": 40.0, "_ledger": ids[0]}))
    rc = at.main(["--smoke", "--only", "gpt_rows,gpt_ln_pallas",
                  "--table", str(table), "--ledger", lpath,
                  "--budget-s", "0"])
    out = capsys.readouterr().out
    assert rc == 1  # dropped rungs are a nonzero exit, not a silent cap
    assert "BUDGET DROPPED" in out


def test_autotune_failed_variant_is_not_an_entry(tmp_path, monkeypatch):
    from benchmarks import autotune_steps as at

    ids, lpath = _seed_ledger(tmp_path)
    table = tmp_path / "table.jsonl"
    monkeypatch.setattr(at, "_measure", _fake_measure({"_ledger": ids[0]}))
    rc = at.main(["--smoke", "--only", "gpt_rows", "--table", str(table),
                  "--ledger", lpath])
    assert rc == 1
    entries, _ = dispatch.load_table(str(table))
    assert entries == {}


@pytest.mark.slow
def test_autotune_smoke_end_to_end(tmp_path):
    """The real thing, two rungs: subprocess harness runs on CPU, table
    entries written with resolving ledger ids, second invocation resumes
    (skips both rungs without re-measuring)."""
    import os
    import subprocess
    import sys
    import time

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(REPO, "benchmarks", "autotune_steps.py")
    table = tmp_path / "table.jsonl"
    lpath = tmp_path / "ledger.jsonl"
    args = [sys.executable, script, "--smoke", "--only",
            "gpt_ln_pallas,lamb_one_pass", "--table", str(table),
            "--ledger", str(lpath), "--repeats", "1",
            "--out", str(tmp_path / "logs")]
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(args, capture_output=True, text=True,
                         timeout=420, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    entries, problems = dispatch.load_table(str(table))
    assert problems == [] and len(entries) == 2, out.stdout
    ids = {r["id"] for r in ledger.read_ledger(str(lpath))}
    for e in entries.values():
        assert e["ledger"] in ids
    # resume: the second invocation must skip both rungs, fast
    t0 = time.time()
    out2 = subprocess.run(args, capture_output=True, text=True,
                          timeout=120, env=env)
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert out2.stdout.count("— skip") == 2, out2.stdout
    assert time.time() - t0 < 60


# ------------------------- tool integration ---------------------------------

def test_check_tool_validates_table(tmp_path):
    """tools/check_bench_labels.py check 3: unresolvable citations and
    pin drift in the dispatch table fail tier-1. Driven in-process
    (tests/test_bench_labels.py covers the CLI surface once) — each of
    the four invocations here used to be a ~3s subprocess."""
    from tests.conftest import run_check_bench_labels

    rec = ledger.make_record("profile_gpt", "cpu", 0.5, 2,
                             knobs={"APEX_ATTN_IMPL": "rows"}, git="abc",
                             ts=1.0)
    lpath = tmp_path / "ledger.jsonl"
    lpath.write_text(json.dumps(rec, sort_keys=True) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text("# fixture\n")

    def run(table_lines):
        tpath = tmp_path / "table.jsonl"
        tpath.write_text("".join(table_lines))
        return run_check_bench_labels("--perf", str(perf), "--ledger",
                                      str(lpath), "--table", str(tpath))

    ok = _entry("attention", dict(b=8), "bfloat16", "rows",
                ledger_id=rec["id"], pins={"APEX_ATTN_IMPL": "rows"})
    out = run([json.dumps(ok) + "\n"])
    assert out.returncode == 0, out.stdout
    # unresolvable ledger id
    out = run([json.dumps(dict(ok, ledger="lg-ffffffffff")) + "\n"])
    assert out.returncode == 1 and "no ledger record" in out.stdout
    # pin drift vs the cited record
    out = run([json.dumps(dict(ok, pins={"APEX_ATTN_IMPL": "flash"}))
               + "\n"])
    assert out.returncode == 1 and "does not match" in out.stdout
    # a corrupt line is a finding here (runtime would fall back)
    out = run(["{corrupt\n", json.dumps(ok) + "\n"])
    assert out.returncode == 1 and "unparseable" in out.stdout


def test_committed_table_validates_against_committed_ledger():
    """The shipped apex_tpu/dispatch/table.jsonl resolves against
    benchmarks/ledger.jsonl — the tier-1 gate on the real artifacts
    (the full check also runs in test_bench_labels.py)."""
    entries, problems = dispatch.load_table(dispatch.default_path())
    assert problems == []
    assert len(entries) >= 6  # the six autotune rung groups, CPU-measured
    recs = ledger.read_ledger()
    by_id = {r.get("id"): r for r in recs}
    for e in entries.values():
        assert dispatch.validate_entry(e, by_id) == [], e
    # the committed CPU pass demonstrates a real selection flip
    # end-to-end: the bench_batch rung's measured amortization win
    assert any(e["op"] == "bench_batch" and e["choice"] != "2"
               for e in entries.values())


def test_committed_bench_batch_entry_drives_bench(monkeypatch):
    """The committed flip reaches the consuming program: bench.py's CPU
    smoke batch is table-driven (b=4, the measured amortization win)
    unless pinned or the table is off — the traced program genuinely
    changes with the table."""
    import os
    import sys

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, REPO)
    import bench
    from apex_tpu.transformer.testing import TransformerConfig

    cfg = TransformerConfig(hidden_size=128, num_layers=2,
                            num_attention_heads=4, vocab_size=512,
                            max_position_embeddings=128)
    assert bench._default_batch(cfg, 2, s=128) == 4  # committed entry
    monkeypatch.setenv("APEX_DISPATCH", "off")
    assert bench._default_batch(cfg, 2, s=128) == 2  # built-in default
    monkeypatch.delenv("APEX_DISPATCH")
    monkeypatch.setenv("APEX_BENCH_BATCH", "8")
    assert bench._default_batch(cfg, 2, s=128) == 8  # env pin wins
