"""Flight recorder + heartbeat supervisor (ISSUE 16).

Three layers, mirroring the subsystem:

* unit — `apex_tpu.telemetry.flight` (disabled no-op, beat fields,
  stream merge, torn-line tolerance, status line),
  `resilience.classify_inflight` verdicts, the `flight_reap` ledger
  validator's teeth, and the supervisor's pool-restore / threshold
  helpers;
* supervisor — `apex_tpu.resilience.flight_watch` run in-process over
  tiny stdlib children: a heartbeat-silent child is reaped at the
  silence threshold (way under its cap, classified record banked), a
  slow-but-beating child is never reaped early, a beat-free child
  keeps pre-PR full-cap semantics;
* e2e chaos — bench.py under the real supervisor with the scripted
  `flight_silent` wedge (reaped early, emergency partial banked, row
  stays owed) and the `heartbeat`-hang slow twin (completes, no reap),
  riding the session smoke compile cache; plus the jaxpr-identity
  assertion for the disabled mode (the zero-cost contract).

window_report's flight-primary attribution is tested here too; the
round-5 golden (fallback path unchanged) stays in
tests/test_window_report.py.
"""

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import resilience  # noqa: E402
from apex_tpu.resilience import flight_watch  # noqa: E402
from apex_tpu.telemetry import flight  # noqa: E402
from apex_tpu.telemetry import ledger as tledger  # noqa: E402

BENCH = os.path.join(REPO, "bench.py")
PROBE_SH = os.path.join(REPO, "benchmarks", "probe_and_collect.sh")
RUN_ALL_SH = os.path.join(REPO, "benchmarks", "run_all_tpu.sh")


@pytest.fixture(autouse=True)
def _clean_flight_env(monkeypatch):
    """Every test starts with the recorder disarmed and no stale
    supervisor knobs — the disabled default IS the contract."""
    for k in ("APEX_FLIGHT_DIR", "APEX_FLIGHT_ROW", "APEX_FLIGHT_SILENCE",
              "APEX_FLIGHT_GRACE", "APEX_FLIGHT_POOL_RESTORE",
              "APEX_BENCH_ATTEMPT", "APEX_FAULT_PLAN"):
        monkeypatch.delenv(k, raising=False)


# ----------------------------------------------------- recorder unit


def test_disabled_is_noop(monkeypatch, tmp_path):
    assert not flight.enabled() and flight.flight_dir() is None
    assert flight.beat("proc_start") is None
    assert flight.newest_beat() is None
    assert flight.status_line() == "flight: disabled (APEX_FLIGHT_DIR unset)"
    assert list(tmp_path.iterdir()) == []  # nothing written anywhere


def test_phase_vocabulary_is_pinned():
    """window_report's attribution pairs and the supervisor's wedge
    signature are keyed on these exact names."""
    assert flight.PHASES == (
        "proc_start", "backend_init", "compile_start", "compile_done",
        "dispatch", "fetch", "attempt_start", "attempt_done", "flush")


def test_beat_fields_env_defaults_and_overrides(monkeypatch, tmp_path):
    monkeypatch.setenv("APEX_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("APEX_FLIGHT_ROW", "gpt_rows")
    monkeypatch.setenv("APEX_BENCH_ATTEMPT", "2")
    rec = flight.beat("dispatch", batch=8)
    assert rec["phase"] == "dispatch" and rec["pid"] == os.getpid()
    assert isinstance(rec["ts"], float) and isinstance(rec["mono"], float)
    assert rec["label"] == "gpt_rows" and rec["attempt"] == 2
    assert rec["batch"] == 8
    # explicit args beat the env defaults
    rec2 = flight.beat("fetch", label="xent", attempt=5)
    assert rec2["label"] == "xent" and rec2["attempt"] == 5
    # a malformed attempt env NEVER raises — the beat still lands
    monkeypatch.setenv("APEX_BENCH_ATTEMPT", "bogus")
    rec3 = flight.beat("flush")
    assert rec3 is not None and "attempt" not in rec3
    beats = flight.read_beats(str(tmp_path))
    assert [b["phase"] for b in beats] == ["dispatch", "fetch", "flush"]
    assert all(b["pid"] == os.getpid() for b in beats)


def test_unwritable_dir_degrades_to_missing_beat(monkeypatch, tmp_path):
    """The recorder must not be able to kill the flight it records."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("file where the dir should go")
    monkeypatch.setenv("APEX_FLIGHT_DIR", str(blocker))
    assert flight.beat("dispatch") is None  # degraded, not raised


def test_read_beats_merges_sorts_and_skips_torn_lines(tmp_path):
    a = tmp_path / "flight-11.jsonl"
    a.write_text(
        json.dumps({"mono": 5.0, "phase": "fetch", "pid": 11}) + "\n"
        + '{"mono": 9.0, "phase": "tr')  # torn final line (reaped writer)
    b = tmp_path / "flight-22.jsonl"
    b.write_text(
        json.dumps({"mono": 1.0, "phase": "proc_start", "pid": 22}) + "\n"
        + json.dumps({"mono": "?", "phase": "noclock"}) + "\n")
    (tmp_path / "other.log").write_text("not a flight stream\n")
    beats = flight.read_beats(str(tmp_path))
    # non-numeric mono sorts first (-inf), numeric ascending; torn line
    # and the non-flight file are invisible
    assert [x.get("phase") for x in beats] == ["noclock", "proc_start",
                                               "fetch"]
    assert flight.newest_beat(str(tmp_path))["phase"] == "fetch"


def test_status_line_and_cli(monkeypatch, tmp_path, capsys):
    d = str(tmp_path / "fl")
    assert flight.status_line(d) == f"flight: no heartbeats under {d}"
    monkeypatch.setenv("APEX_FLIGHT_DIR", d)
    flight.beat("compile_start", label="bench_first", attempt=1)
    line = flight.status_line(d)
    assert line.startswith("flight: compile_start (")
    assert "row=bench_first" in line and "attempt=1" in line
    assert flight.main(["status", "--dir", d]) == 0
    assert "flight: compile_start" in capsys.readouterr().out


def test_ledger_status_rides_the_heartbeat_line(monkeypatch, tmp_path,
                                                capsys):
    """`python -m apex_tpu.telemetry.ledger status` answers "is anything
    alive RIGHT NOW" when a flight dir is armed."""
    d = str(tmp_path / "fl")
    lp = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("APEX_TELEMETRY_LEDGER", lp)
    tledger.append_record("bench", "cpu", 0.5, 2, path=lp)
    monkeypatch.setenv("APEX_FLIGHT_DIR", d)
    flight.beat("dispatch", label="bench")
    assert tledger.main(["--ledger", lp, "status"]) == 0
    out = capsys.readouterr().out
    assert "flight: dispatch" in out and "row=bench" in out


def test_heartbeat_fault_slows_but_never_silences(monkeypatch, tmp_path):
    """The chaos hook fires AFTER the beat lands: a scripted per-beat
    hang stretches wall time while beats keep arriving — the
    slow-but-beating shape the supervisor must not reap."""
    monkeypatch.setenv("APEX_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "heartbeat", "kind": "hang", "seconds": 0.5}]))
    t0 = time.perf_counter()
    rec = flight.beat("dispatch")
    assert time.perf_counter() - t0 >= 0.5
    assert rec is not None
    assert [b["phase"] for b in flight.read_beats(str(tmp_path))] \
        == ["dispatch"]


# ----------------------------------------- in-flight classification


def test_classify_inflight_verdicts():
    ci = resilience.classify_inflight
    now = 1000.0
    # no beats / no numeric mono stamps: nothing proves life = silent
    assert ci([], now) == resilience.SILENT
    assert ci([{"mono": "x"}, {"mono": True}], now) == resilience.SILENT
    # §6 defaults: advancing under FLIGHT_ADVANCE_S, silent at
    # FLIGHT_SILENCE_S, slow in between
    assert ci([{"mono": now - 10}], now) == resilience.ADVANCING
    assert ci([{"mono": now - resilience.FLIGHT_ADVANCE_S - 40}], now) \
        == resilience.SLOW
    assert ci([{"mono": now - resilience.FLIGHT_SILENCE_S}], now) \
        == resilience.SILENT
    # overrides: chaos tests pin seconds-scale thresholds
    assert ci([{"mono": now - 2}], now, silence_s=1.0) == resilience.SILENT
    assert ci([{"mono": now - 0.5}], now, advance_s=0.2) == resilience.SLOW
    # the newest stamp decides, wherever it sits in the list
    assert ci([{"mono": now - 500}, {"mono": now - 1}], now) \
        == resilience.ADVANCING


def test_inflight_verdict_vocabulary():
    assert resilience.INFLIGHT_VERDICTS == (
        resilience.ADVANCING, resilience.SLOW, resilience.SILENT)
    assert 143 in resilience.TIMEOUT_RCS  # the supervisor's reap rc


# ------------------------------------------- flight_reap validation


def _reap_block(**over):
    block = {"row": "bench_first", "verdict": resilience.SILENT,
             "reason": "silence", "silence_s": 300.0, "timeout_s": 1500.0,
             "elapsed_s": 420.0, "beats": 7, "age_s": 310.2,
             "last_phase": "compile_start"}
    block.update(over)
    return block


def _reap_rec(**over):
    return tledger.make_record(
        "flight_reap", "shell", None, None, git="abc", ts=1.0,
        extra={"flight_reap": _reap_block(**over)})


def test_flight_reap_record_validates_clean():
    assert tledger.validate_record(_reap_rec()) == []
    # null age/last_phase = a beat-free child reaped at cap: legal
    assert tledger.validate_record(
        _reap_rec(reason="cap", beats=0, age_s=None,
                  last_phase=None)) == []


def test_flight_reap_validator_teeth():
    """Each malformed field is a named finding — a record that claims
    the wrong reap story must not pass the ledger gate
    (check_bench_labels runs validate_record over every record)."""
    cases = [
        (dict(verdict="speedy"), "flight_reap.verdict"),
        (dict(reason="boredom"), "flight_reap.reason"),
        (dict(row=""), "flight_reap.row"),
        (dict(elapsed_s=-1), "flight_reap.elapsed_s"),
        (dict(silence_s=None), "flight_reap.silence_s"),
        (dict(timeout_s=True), "flight_reap.timeout_s"),
        (dict(beats="7"), "flight_reap.beats"),
        (dict(age_s=-2.0), "flight_reap.age_s"),
        (dict(last_phase=3), "flight_reap.last_phase"),
    ]
    for over, needle in cases:
        problems = tledger.validate_record(_reap_rec(**over))
        assert any(needle in p for p in problems), (over, problems)
    rec = tledger.make_record("flight_reap", "shell", None, None,
                              git="abc", ts=1.0,
                              extra={"flight_reap": "reaped"})
    assert any("not a dict" in p for p in tledger.validate_record(rec))


# --------------------------------------------- supervisor unit layer


def test_threshold_precedence():
    th = flight_watch._threshold
    assert th(2.0, "5", 300) == 2.0       # CLI wins
    assert th(None, "5", 300) == 5.0      # then the raw env value
    assert th(None, "bogus", 300) == 300.0  # unparseable -> constant
    assert th(None, None, 300) == 300.0
    assert th(0.0, "5", 300) == 0.0       # zero is a LEGAL threshold
    assert th(None, "0.25", 300) == 0.25  # fractional seconds too


def test_child_env_pool_restore(monkeypatch, tmp_path):
    """The shell relay-proofs the supervisor (PALLAS_AXON_POOL_IPS=);
    the child must get the variable's ORIGINAL state back so a TPU rung
    dials the relay exactly as it did under bare timeout."""
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("APEX_FLIGHT_POOL_RESTORE", flight_watch.POOL_UNSET)
    env = flight_watch._child_env(str(tmp_path), "bench_first")
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert "APEX_FLIGHT_POOL_RESTORE" not in env  # marker is consumed
    assert env["APEX_FLIGHT_DIR"] == str(tmp_path)
    assert env["APEX_FLIGHT_ROW"] == "bench_first"
    monkeypatch.setenv("APEX_FLIGHT_POOL_RESTORE", "10.1.2.3")
    env = flight_watch._child_env(None, None)
    assert env["PALLAS_AXON_POOL_IPS"] == "10.1.2.3"
    assert "APEX_FLIGHT_DIR" not in env and "APEX_FLIGHT_ROW" not in env
    # no marker at all: the variable passes through untouched
    monkeypatch.delenv("APEX_FLIGHT_POOL_RESTORE", raising=False)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "keepme")
    assert flight_watch._child_env(None, None)[
        "PALLAS_AXON_POOL_IPS"] == "keepme"


# ------------------------------------------ supervisor over children
# (tiny stdlib children; seconds-scale thresholds keep these fast)

_SILENT_CHILD = """\
import json, os, time
d = os.environ["APEX_FLIGHT_DIR"]
os.makedirs(d, exist_ok=True)
with open(os.path.join(d, "flight-%d.jsonl" % os.getpid()), "a") as f:
    f.write(json.dumps({"ts": time.time(), "mono": time.monotonic(),
                        "phase": "compile_start",
                        "pid": os.getpid()}) + "\\n")
time.sleep(600)
"""

_BEATING_CHILD = """\
import json, os, time
d = os.environ["APEX_FLIGHT_DIR"]
os.makedirs(d, exist_ok=True)
p = os.path.join(d, "flight-%d.jsonl" % os.getpid())
for i in range(8):
    with open(p, "a") as f:
        f.write(json.dumps({"ts": time.time(), "mono": time.monotonic(),
                            "phase": "dispatch",
                            "pid": os.getpid()}) + "\\n")
    time.sleep(0.4)
"""


@contextlib.contextmanager
def _restored_signals():
    """flight_watch.main installs SIGTERM/SIGINT handlers; the pytest
    process must get its own back."""
    old = {s: signal.getsignal(s) for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        yield
    finally:
        for s, h in old.items():
            signal.signal(s, h)


def _supervise(tmp_path, monkeypatch, child_src, timeout, silence,
               row="row_under_test", grace="5"):
    monkeypatch.setenv("APEX_TELEMETRY_LEDGER",
                       str(tmp_path / "ledger.jsonl"))
    fdir = str(tmp_path / "flight")
    t0 = time.perf_counter()
    with _restored_signals():
        rc = flight_watch.main(
            ["--timeout", str(timeout), "--row", row, "--flight-dir", fdir,
             "--silence", str(silence), "--grace", grace, "--",
             sys.executable, "-c", child_src])
    wall = time.perf_counter() - t0
    path = tmp_path / "ledger.jsonl"
    records = tledger.read_ledger(str(path)) if path.exists() else []
    return rc, wall, [r for r in records
                      if r.get("harness") == "flight_reap"]


def test_silent_child_reaped_at_silence_threshold(tmp_path, monkeypatch):
    """One beat, then the stream stops: reaped at ~silence_s, nowhere
    near the 120 s cap, with a classified + validated flight_reap
    record banked and the TIMEOUT_RCS exit that keeps the row owed."""
    rc, wall, reaps = _supervise(tmp_path, monkeypatch, _SILENT_CHILD,
                                 timeout=120, silence=1.5,
                                 row="wedge_row")
    assert rc == 143 and rc in resilience.TIMEOUT_RCS
    assert wall < 30, f"reap took {wall:.1f}s — not an early reap"
    assert len(reaps) == 1
    fr = reaps[0]["flight_reap"]
    assert fr["row"] == "wedge_row" and fr["reason"] == "silence"
    assert fr["verdict"] == resilience.SILENT
    assert fr["beats"] >= 1 and fr["last_phase"] == "compile_start"
    assert fr["age_s"] >= 1.5 and fr["timeout_s"] == 120.0
    assert tledger.validate_record(reaps[0]) == []


def test_slow_beating_child_is_never_reaped_early(tmp_path, monkeypatch):
    """Beats arriving under the silence threshold keep the run alive to
    its own exit — a degraded-relay crawl is supervised, not killed."""
    rc, wall, reaps = _supervise(tmp_path, monkeypatch, _BEATING_CHILD,
                                 timeout=60, silence=1.5, row="slow_row")
    assert rc == 0 and reaps == []
    assert wall >= 2.5  # it genuinely ran its slow course


def test_beat_free_child_keeps_the_full_cap(tmp_path, monkeypatch):
    """No beats ever: pre-PR semantics. Only a stream that STOPPED
    proves instrumentation was there to go quiet — an uninstrumented
    child is reaped at its cap (reason=cap), never at the silence
    threshold."""
    rc, wall, reaps = _supervise(tmp_path, monkeypatch,
                                 "import time; time.sleep(600)",
                                 timeout=2, silence=0.5, row="bare_row")
    assert rc == 143
    assert wall >= 2, "a beat-free child must keep its full cap"
    assert len(reaps) == 1
    fr = reaps[0]["flight_reap"]
    assert fr["reason"] == "cap" and fr["beats"] == 0
    assert fr["age_s"] is None and fr["last_phase"] is None
    assert tledger.validate_record(reaps[0]) == []


def test_unlaunchable_command_is_127(tmp_path, monkeypatch):
    monkeypatch.setenv("APEX_TELEMETRY_LEDGER",
                       str(tmp_path / "ledger.jsonl"))
    with _restored_signals():
        rc = flight_watch.main(
            ["--timeout", "5", "--flight-dir", str(tmp_path / "fl"),
             "--", "/nonexistent-cmd-apex-flight-test"])
    assert rc == 127


def test_shell_wiring_for_flight_surfaces():
    """run_all_tpu.sh rungs go through the supervisor; the --status
    surface prints the newest heartbeat (bash -n sits in
    tests/test_resilience.py)."""
    run_all = open(RUN_ALL_SH).read()
    assert "apex_tpu.resilience.flight_watch" in run_all
    assert "--flight-dir" in run_all and "APEX_FLIGHT_POOL_RESTORE" in run_all
    probe = open(PROBE_SH).read()
    assert "apex_tpu.telemetry.flight status" in probe
    assert "APEX_FLIGHT_DIR" in probe


# ------------------------------------- window_report flight primary


def _wr():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "window_report_flight", os.path.join(REPO, "tools",
                                             "window_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_window_report_flight_primary_attribution(tmp_path):
    """Exact minute attribution from mono deltas (compile_start ->
    compile_done, dispatch -> fetch) plus the reap account's
    reclaimed minutes."""
    wr = _wr()
    d = tmp_path / "flight"
    d.mkdir()
    base = 1754000000.0
    beats = [
        {"ts": base + m, "mono": m, "phase": ph, "pid": 11,
         "label": "bench_first"}
        for m, ph in ((10, "proc_start"), (20, "compile_start"),
                      (80, "compile_done"), (90, "dispatch"),
                      (120, "fetch"), (121, "flush"))]
    (d / "flight-11.jsonl").write_text(
        "".join(json.dumps(b) + "\n" for b in beats))
    lp = str(tmp_path / "ledger.jsonl")
    tledger.append_record(
        "flight_reap", "shell", None, None, path=lp,
        extra={"flight_reap": _reap_block(
            row="gpt_rows", timeout_s=600.0, elapsed_s=30.0,
            silence_s=20.0, beats=4, age_s=21.0,
            last_phase="compile_done")})
    rep = wr.build_report(ledger_path=lp, flight_dir=str(d))
    fl = rep["flight"]
    (proc,) = fl["processes"]
    assert proc["label"] == "bench_first" and proc["pid"] == 11
    assert proc["compile_minutes"] == 1.0    # 60 s compile
    assert proc["measure_minutes"] == 0.5    # 30 s dispatch->fetch
    assert proc["last_phase"] == "flush" and not proc["compile_open"]
    assert fl["by_label"]["bench_first"]["compile_minutes"] == 1.0
    (reap,) = fl["reaps"]
    assert reap["row"] == "gpt_rows"
    assert reap["reclaimed_minutes"] == 9.5  # (600-30)/60
    assert fl["reclaimed_minutes"] == 9.5
    buf = io.StringIO()
    wr.print_report(rep, out=buf)
    text = buf.getvalue()
    assert "primary timeline" in text
    assert "reclaimed 9.5 min" in text and "gpt_rows" in text


def test_window_report_fallback_tag_only_with_flight_present(tmp_path):
    """Without a flight dir the logs section is NOT demoted (the
    round-5 golden path is unchanged); with both, the banner-inference
    section is explicitly tagged fallback."""
    wr = _wr()
    logs = os.path.join(REPO, "benchmarks", "device_logs_r05")
    rep = wr.build_report(logs_dir=logs)
    buf = io.StringIO()
    wr.print_report(rep, out=buf)
    assert "fallback timeline" not in buf.getvalue()
    d = tmp_path / "flight"
    d.mkdir()
    (d / "flight-9.jsonl").write_text(json.dumps(
        {"ts": 1754000000.0, "mono": 1.0, "phase": "proc_start",
         "pid": 9}) + "\n")
    rep = wr.build_report(logs_dir=logs, flight_dir=str(d))
    buf = io.StringIO()
    wr.print_report(rep, out=buf)
    text = buf.getvalue()
    assert "(fallback timeline — banner inference)" in text
    assert "71.4 min of anchored activity" in text  # account unchanged


def test_window_report_watch_is_bounded(tmp_path, capsys, monkeypatch):
    wr = _wr()
    d = tmp_path / "flight"
    monkeypatch.setenv("APEX_FLIGHT_DIR", str(d))
    flight.beat("dispatch", label="bench_first")
    rc = wr.main(["--flight", str(d), "--watch", "--iterations", "1",
                  "--interval", "0.01"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "flight: dispatch" in out


# --------------------------------------------------- bench e2e chaos
# (real CPU smoke runs; shared suite smoke compile cache)


@pytest.fixture
def chaos_cache_dir(shared_smoke_cache_dir):
    return shared_smoke_cache_dir


def _bench_under_watch(tmp_path, chaos_cache_dir, plan, silence,
                       timeout=600):
    env = dict(os.environ)
    for k in ("APEX_WARM_ONLY", "APEX_CKPT_RESUME", "APEX_FLIGHT_DIR",
              "APEX_FLIGHT_ROW", "APEX_BENCH_ATTEMPT"):
        env.pop(k, None)
    env.update(
        PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        APEX_BENCH_SMOKE="1", APEX_BENCH_INNER="1",
        APEX_COMPILE_CACHE="1", APEX_COMPILE_CACHE_DIR=chaos_cache_dir,
        APEX_CKPT_DIR=str(tmp_path / "ckpt"),
        APEX_TELEMETRY_LEDGER=str(tmp_path / "ledger.jsonl"),
        APEX_BENCH_BASELINE=str(tmp_path / "baseline.json"),
        APEX_FAULT_PLAN=json.dumps(plan))
    fdir = str(tmp_path / "flight")
    t0 = time.perf_counter()
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.resilience.flight_watch",
         "--timeout", str(timeout), "--row", "bench_first",
         "--flight-dir", fdir, "--silence", str(silence), "--grace", "20",
         "--", sys.executable, BENCH],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    wall = time.perf_counter() - t0
    path = tmp_path / "ledger.jsonl"
    records = tledger.read_ledger(str(path)) if path.exists() else []
    return out, wall, records, fdir


def test_chaos_flight_silent_wedge_reaped_early_partial_banked(
        tmp_path, chaos_cache_dir):
    """The round-5 gpt_rows shape, end-to-end: beats flowed
    (proc_start..compile_done), then the process went quiet with the
    scan-boundary partial already committed. The supervisor reaps at
    the silence threshold — way under the 600 s cap — the SIGTERM
    grace lets the emergency flush bank the partial, the classified
    flight_reap record is fault-stamped and valid, and exit 143 keeps
    the manifest row owed."""
    from apex_tpu import checkpoint as ckpt

    plan = [{"site": "flight_silent", "kind": "hang"}]
    out, wall, records, fdir = _bench_under_watch(
        tmp_path, chaos_cache_dir, plan, silence=20)
    assert out.returncode == 143, (out.stdout, out.stderr[-2000:])
    assert out.returncode in resilience.TIMEOUT_RCS  # row stays owed
    assert wall < 240, f"{wall:.0f}s — the 600s slot was burnt, not saved"
    # the heartbeat stream shows the flight up to the wedge
    phases = [b["phase"] for b in flight.read_beats(fdir)]
    assert "proc_start" in phases and "compile_done" in phases
    assert "fetch" not in phases  # it never reached the timed region
    # the emergency flush banked the scan-boundary partial (step 3 in
    # smoke: step0 + iters)
    assert "emergency checkpoint committed" in out.stderr
    steps = ckpt.durable_steps(str(tmp_path / "ckpt"))
    assert steps and steps[-1] == 3
    # the classified, fault-stamped, schema-valid reap record
    reaps = [r for r in records if r.get("harness") == "flight_reap"]
    assert len(reaps) == 1, out.stderr[-2000:]
    fr = reaps[0]["flight_reap"]
    assert fr["row"] == "bench_first" and fr["reason"] == "silence"
    assert fr["verdict"] == resilience.SILENT
    assert fr["last_phase"] == "compile_done" and fr["age_s"] >= 20
    assert reaps[0]["fault_plan"].startswith("fp-")
    assert tledger.validate_record(reaps[0]) == []


def test_chaos_slow_beating_bench_survives_to_completion(
        tmp_path, chaos_cache_dir):
    """The twin: every beat hangs 1 s (wall time stretches, beats keep
    arriving) — the supervisor must NOT reap before the cap; the run
    completes with its one JSON line and no reap record."""
    plan = [{"site": "heartbeat", "kind": "hang", "seconds": 1}]
    out, wall, records, fdir = _bench_under_watch(
        tmp_path, chaos_cache_dir, plan, silence=20)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec.get("metric", "").startswith("gpt2s_train_tokens_per_sec")
    assert [r for r in records if r.get("harness") == "flight_reap"] == []
    phases = [b["phase"] for b in flight.read_beats(fdir)]
    assert "flush" in phases  # the full flight landed


def test_flight_enabled_is_jaxpr_byte_identical(monkeypatch, tmp_path):
    """The zero-cost contract: beats are host-side file appends that
    never touch a traced program — tracing the bench training step with
    the recorder armed (beats emitted) yields a jaxpr byte-identical to
    the disabled trace."""
    import jax

    import bench
    from apex_tpu import telemetry
    from tests.test_telemetry import _bench_fixture

    (model, scaler, tx, params, opt_state, scaler_state,
     ids, pos, labels) = _bench_fixture()
    args = (params, opt_state, scaler_state, ids, pos, labels)

    telemetry.disable()
    monkeypatch.delenv("APEX_FLIGHT_DIR", raising=False)
    want = str(jax.make_jaxpr(bench.make_one_step(model, scaler, tx))(
        *args))

    monkeypatch.setenv("APEX_FLIGHT_DIR", str(tmp_path))
    assert flight.beat("compile_start") is not None  # recorder live
    got = str(jax.make_jaxpr(bench.make_one_step(model, scaler, tx))(
        *args))
    assert got == want, "an armed flight recorder changed the jaxpr"
