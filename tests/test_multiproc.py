"""Multi-host (multi-process) smoke: the multiproc launcher spawns 2
localhost processes that form a jax.distributed cluster over DCN-equivalent
loopback and psum across it (reference:
apex/transformer/testing/distributed_test_base.py:27-78 spawns NCCL
process groups the same way)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.slow  # two fresh jax processes (~15s); pure jax.distributed
# smoke orthogonal to repo code changes — slow tier keeps it exercised
def test_multiproc_two_process_psum():
    env = dict(os.environ)
    env["MASTER_PORT"] = "29531"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", "--nproc", "2",
         os.path.join(REPO, "tests", "multiproc_worker.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (
        f"launcher rc={out.returncode}\nstdout:\n{out.stdout}\n"
        f"stderr:\n{out.stderr}")
    assert out.stdout.count("MULTIPROC_OK") == 2, out.stdout


@pytest.mark.slow
def test_imagenet_example_two_process():
    """The flagship example multi-host: 2 processes x 1 device, global
    mesh, cross-process DDP psum + SyncBatchNorm stats, rank-0 checkpoint
    (the reference's 2-GPU torch.distributed.launch L1 configuration)."""
    env = dict(os.environ)
    env["MASTER_PORT"] = "29541"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", "--nproc", "2",
         os.path.join(REPO, "tests", "imagenet_multiproc_worker.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (
        f"rc={out.returncode}\nstdout:\n{out.stdout[-3000:]}\n"
        f"stderr:\n{out.stderr[-3000:]}")
    assert out.stdout.count("IMAGENET_MULTIPROC_OK") == 2, out.stdout


@pytest.mark.slow
@pytest.mark.parametrize("tp,port", [("1", "29543"), ("2", "29545")])
def test_pretrain_example_two_process(tp, port):
    """The transformer pretrain entry multi-host over 2 processes:
    tp=1 -> (dp=2, tp=1): grad pmean + found_inf pmax cross the
    DCN-equivalent loopback; tp=2 -> (dp=1, tp=2): the TENSOR-parallel
    collectives (TP all-reduces, vocab-parallel CE) cross it."""
    env = dict(os.environ)
    env["MASTER_PORT"] = port
    env["APEX_TEST_TP"] = tp
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", "--nproc", "2",
         os.path.join(REPO, "tests", "pretrain_multiproc_worker.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (
        f"rc={out.returncode}\nstdout:\n{out.stdout[-3000:]}\n"
        f"stderr:\n{out.stderr[-3000:]}")
    assert out.stdout.count("PRETRAIN_MULTIPROC_OK") == 2, out.stdout


@pytest.mark.slow
def test_simple_distributed_example_two_process():
    """The reference's examples/simple/distributed walkthrough, 2-process:
    DDP grad averaging + amp O1 must converge (final loss printed by rank
    0 and well below the ~1.3 starting MSE)."""
    env = dict(os.environ)
    env["MASTER_PORT"] = "29537"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # one device per process: the conftest's 8-device flag would make a
    # 16-device gloo mesh and slow every one of the 500 dispatches
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", "--nproc", "2",
         os.path.join(REPO, "examples", "simple", "distributed",
                      "distributed_data_parallel.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    import re
    m = re.search(r"final loss = ([0-9.]+)", out.stdout)
    assert m, out.stdout
    assert float(m.group(1)) < 1.0
