"""Multi-host (multi-process) smoke: the multiproc launcher spawns 2
localhost processes that form a jax.distributed cluster over DCN-equivalent
loopback and psum across it (reference:
apex/transformer/testing/distributed_test_base.py:27-78 spawns NCCL
process groups the same way)."""

import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_multiproc_two_process_psum():
    env = dict(os.environ)
    env["MASTER_PORT"] = "29531"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", "--nproc", "2",
         os.path.join(REPO, "tests", "multiproc_worker.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (
        f"launcher rc={out.returncode}\nstdout:\n{out.stdout}\n"
        f"stderr:\n{out.stderr}")
    assert out.stdout.count("MULTIPROC_OK") == 2, out.stdout
