"""2-process worker for the multi-host ImageNet example test (launched by
``python -m apex_tpu.parallel.multiproc`` from tests/test_multiproc.py).

Each process owns 1 virtual CPU device; main_amp's mesh spans both, so the
DDP grad psum and the SyncBatchNorm Welford psum run across process
boundaries — the DCN analog of the reference's 2-GPU L1 runs.
"""

import os
import sys

import jax

# CPU backend BEFORE distributed init (axon plugin owns the default)
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                "..")))

import numpy as np  # noqa: E402

from examples.imagenet.main_amp import main  # noqa: E402


def run():
    loss = main(["--synthetic", "--arch", "resnet18", "--steps", "3",
                 "-b", "8", "--image-size", "32", "--num-classes", "10",
                 "--opt-level", "O2",
                 "--checkpoint", os.path.join(
                     os.environ.get("TMPDIR", "/tmp"),
                     f"imagenet_mp_{os.getpid()}.pkl")])
    assert np.isfinite(loss), loss
    assert jax.process_count() == 2
    print(f"IMAGENET_MULTIPROC_OK rank={jax.process_index()} "
          f"loss={loss:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(run())
