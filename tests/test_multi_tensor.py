"""Port of the multi_tensor kernel micro-tests
(reference: tests/L0/run_amp/test_multi_tensor_{scale,axpby,l2norm}.py):
fused ops vs per-tensor reference math, across dtype grids + overflow
injection."""

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import (
    multi_tensor_applier,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_l2norm_per_tensor,
    flatten,
    unflatten,
)

SHAPES = [(3,), (4, 5), (2, 3, 4), (1,)]
DTYPES = [jnp.float32, jnp.bfloat16, jnp.float16]


def _make(shapes, dtype, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(*s), dtype=dtype) for s in shapes]


def test_flatten_unflatten_roundtrip():
    ts = _make(SHAPES, jnp.float32)
    flat = flatten(ts)
    assert flat.shape == (sum(int(np.prod(s)) for s in SHAPES),)
    back = unflatten(flat, ts)
    for a, b in zip(ts, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("in_dtype", DTYPES)
@pytest.mark.parametrize("out_dtype", [jnp.float32, jnp.float16])
def test_scale(in_dtype, out_dtype):
    srcs = _make(SHAPES, in_dtype)
    dsts = _make(SHAPES, out_dtype, seed=1)
    outs, noop = multi_tensor_applier(multi_tensor_scale, [srcs, dsts], 0.5)
    assert int(noop) == 0
    for s, o in zip(srcs, outs):
        assert o.dtype == out_dtype
        np.testing.assert_allclose(
            np.asarray(s, np.float32) * 0.5, np.asarray(o, np.float32),
            rtol=1e-2 if out_dtype != jnp.float32 else 1e-6)


def test_scale_overflow_flag():
    srcs = _make(SHAPES, jnp.float32)
    srcs[1] = srcs[1].at[0, 0].set(jnp.inf)
    _, noop = multi_tensor_scale([srcs, srcs], 1.0)
    assert int(noop) == 1
    srcs[1] = srcs[1].at[0, 0].set(jnp.nan)
    _, noop = multi_tensor_scale([srcs, srcs], 1.0)
    assert int(noop) == 1


def test_axpby():
    xs = _make(SHAPES, jnp.float32, seed=2)
    ys = _make(SHAPES, jnp.float32, seed=3)
    outs, noop = multi_tensor_axpby([xs, ys, xs], 2.0, -3.0)
    assert int(noop) == 0
    for x, y, o in zip(xs, ys, outs):
        np.testing.assert_allclose(
            2.0 * np.asarray(x) - 3.0 * np.asarray(y), np.asarray(o), rtol=1e-6)


def test_l2norm():
    ts = _make(SHAPES, jnp.float32, seed=4)
    got = float(multi_tensor_l2norm(ts))
    want = np.sqrt(sum(np.sum(np.asarray(t) ** 2) for t in ts))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    g, per = multi_tensor_l2norm_per_tensor(ts)
    np.testing.assert_allclose(float(g), want, rtol=1e-6)
    for t, p in zip(ts, np.asarray(per)):
        np.testing.assert_allclose(np.linalg.norm(np.asarray(t).ravel()), p, rtol=1e-5)
