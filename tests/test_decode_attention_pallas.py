"""Decode-attention family (ops/decode_attention_pallas.py, ISSUE 10):
interpret-mode parity vs the jnp gather reference, tile legality and
knob asymmetry, and the dispatch wiring of the fifth family."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import dispatch
from apex_tpu.dispatch import tiles
from apex_tpu.ops import decode_attention_pallas as dap

B, H, P, PS, D, MAXP = 4, 4, 16, 32, 64, 4
SCALE = 1.0 / np.sqrt(D)


def _data(dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, H, D), dtype)
    k = jnp.asarray(rs.randn(H, P, PS, D), dtype)
    v = jnp.asarray(rs.randn(H, P, PS, D), dtype)
    # distinct non-contiguous pages per slot; page 0 stays null
    pt = jnp.asarray(np.stack([
        rs.permutation(np.arange(1, P))[:MAXP] for _ in range(B)]),
        jnp.int32)
    # lengths cover: mid-page, page-aligned, full, inactive
    lens = jnp.asarray([5, PS, MAXP * PS, 0], jnp.int32)
    return q, k, v, pt, lens


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_kernel_matches_reference(dtype):
    q, k, v, pt, lens = _data(dtype)
    want = dap.decode_attention_reference(q, k, v, pt, lens, SCALE)
    got = dap.decode_attention_pallas(q, k, v, pt, lens, SCALE,
                                      interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=1e-5 if dtype == jnp.float32 else 5e-2)
    # inactive slot -> exact zeros (the fully-masked-row contract)
    assert np.all(np.asarray(got, np.float32)[3] == 0.0)


@pytest.mark.parametrize("bh", [1, 2, 4])
def test_block_h_sweep_parity(bh):
    q, k, v, pt, lens = _data()
    want = dap.decode_attention_reference(q, k, v, pt, lens, SCALE)
    got = dap.decode_attention_pallas(q, k, v, pt, lens, SCALE,
                                      block_h=bh, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_per_call_tile_raises_setter_falls_back():
    q, k, v, pt, lens = _data()
    # per-call demand on an illegal tile raises with the model verdict
    with pytest.raises(ValueError, match="does not divide"):
        dap.decode_attention_pallas(q, k, v, pt, lens, SCALE,
                                    block_h=3, interpret=True)
    # the process-wide setter is a preference: an illegal pin falls
    # back to the heuristic silently (parity still holds)
    dap.set_block_h(3)
    try:
        want = dap.decode_attention_reference(q, k, v, pt, lens, SCALE)
        got = dap.decode_attention_pallas(q, k, v, pt, lens, SCALE,
                                          interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)
    finally:
        dap.set_block_h(None)
    with pytest.raises(ValueError):
        dap.set_block_h(-2)


def test_impl_demand_asymmetry(monkeypatch):
    q, k, v, pt, lens = _data()
    with pytest.raises(ValueError, match="unknown decode-attention"):
        dap.decode_attention(q, k, v, pt, lens, impl="dense")
    # jnp demand with a pallas tile knob is un-honorable
    with pytest.raises(ValueError, match="block_h"):
        dap.decode_attention(q, k, v, pt, lens, impl="jnp", block_h=2)
    # env preference with garbage warns once and falls back to jnp
    monkeypatch.setenv("APEX_DECODE_ATTN_IMPL", "banana")
    tiles._warned_env.clear()
    with pytest.warns(UserWarning, match="banana"):
        out = dap.decode_attention(q, k, v, pt, lens)
    want = dap.decode_attention_reference(q, k, v, pt, lens, SCALE)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=1e-6)
    with pytest.raises(ValueError):
        dap.set_decode_impl("banana")
    # a "pallas" PREFERENCE that falls back on unsupported geometry
    # (d too large) must still raise for a per-call tile demand: the
    # path actually taken is jnp, and per-call knobs raise
    monkeypatch.delenv("APEX_DECODE_ATTN_IMPL")
    big_d = 1024
    qb = jnp.zeros((2, 2, big_d), jnp.float32)
    kb = jnp.zeros((2, 4, 8, big_d), jnp.float32)
    ptb = jnp.zeros((2, 2), jnp.int32)
    lb = jnp.zeros((2,), jnp.int32)
    dap.set_decode_impl("pallas")
    try:
        out = dap.decode_attention(qb, kb, kb, ptb, lb)  # falls back
        assert out.shape == qb.shape
        with pytest.raises(ValueError, match="jnp path"):
            dap.decode_attention(qb, kb, kb, ptb, lb, block_h=2)
    finally:
        dap.set_decode_impl(None)


def test_default_is_jnp_and_table_flips_to_pallas(tmp_path,
                                                  monkeypatch):
    """Measured-dispatch: the built-in default is the jnp gather path
    (no device row yet); a backend-keyed table entry flips an UNPINNED
    call to the pallas kernel in interpret mode — jaxpr-level proof."""
    q, k, v, pt, lens = _data()

    def jaxpr_of():
        return str(jax.make_jaxpr(
            lambda *a: dap.decode_attention(*a, sm_scale=SCALE))(
                q, k, v, pt, lens))

    monkeypatch.delenv("APEX_DECODE_ATTN_IMPL", raising=False)
    dispatch._reset_for_tests()
    assert "pallas" not in jaxpr_of()  # built-in default: jnp
    table = tmp_path / "table.jsonl"
    entry = dispatch.make_entry(
        "decode_attention",
        dict(b=B, h=H, pages=MAXP, ps=PS, d=D), jnp.float32, "cpu",
        "pallas", "lg-0000000000",
        params={"value": {"block_h": 2}, "ledger": "lg-0000000000"})
    table.write_text(json.dumps(entry) + "\n")
    monkeypatch.setenv("APEX_DISPATCH_TABLE", str(table))
    dispatch._reset_for_tests()
    try:
        assert "pallas" in jaxpr_of()  # table entry engaged (interpret)
        consults = dispatch.consulted()
        row = next(r for r in consults
                   if r["op"] == "decode_attention")
        assert row["choice"] == "pallas"
        assert row["params"] == {"block_h": 2}
    finally:
        dispatch._reset_for_tests()


def test_tile_model_surface():
    """The fifth family in the shared tile model: legality verdicts,
    heuristic default, candidate enumeration all-legal."""
    dims = dict(b=B, h=12, pages=MAXP, ps=PS, d=D)
    assert tiles.legal("decode_attention", dims, jnp.bfloat16,
                       {"block_h": 5})  # does not divide 12
    assert not tiles.legal("decode_attention", dims, jnp.bfloat16,
                           {"block_h": 4})
    base = tiles.default_params("decode_attention", dims, jnp.bfloat16)
    assert base and base["block_h"] >= 1 and 12 % base["block_h"] == 0
    cands = tiles.candidates("decode_attention", dims, jnp.bfloat16)
    assert cands and cands[0] == base  # incumbent first (hysteresis)
    assert {"block_h": 12} in cands    # the all-heads tile is swept
    for c in cands:
        assert not tiles.legal("decode_attention", dims, jnp.bfloat16,
                               c), c
    assert tiles.model_vmem_bytes(
        "decode_attention", dims, jnp.bfloat16,
        {"block_h": 4}) == tiles.decode_vmem_bytes(4, PS, D, 2)


def test_dispatch_vocabulary_registered():
    assert dispatch.OP_CHOICES["decode_attention"] == ("jnp", "pallas")
    assert tiles.PARAM_KEYS["decode_attention"] == ("block_h",)
    assert tiles.DIM_KEYS["decode_attention"] == (
        "b", "h", "pages", "ps", "d")
