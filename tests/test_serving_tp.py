"""TP-sharded serving (ISSUE 18, apex_tpu.serving.tp):

The SAME two jitted serving programs run over a `(tp,)` GSPMD mesh —
params device_put with Megatron column/row NamedShardings (whole heads
per chip), the paged KV cache sharded on its leading head axis — and
must be TOKEN-FOR-TOKEN identical to the single-device engine across
tp ∈ {1, 2, 4} on the 8-device CPU mesh, under every host-side layer
(stochastic sampling lanes, prefix-cache sharing/COW, KV-pressure
preemption + replay). The one-compile contract
(``decode_cache_size()==1`` / ``prefill_cache_size()<=1``) holds on
the mesh with all generation layers engaged. Knob semantics per the
CLAUDE.md asymmetry: per-call ``tp=`` demands raise on un-honorable
widths, the APEX_SERVE_TP preference falls back, and the
``weight_quant`` pairing follows the spec-decode precedent.
"""

import numpy as np
import pytest

import jax

from apex_tpu.serving import (
    Request,
    SamplingParams,
    ServingEngine,
)
from apex_tpu.serving import tp as tp_mod


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from apex_tpu.serving import model as smodel

    return cfg, smodel.init_gpt_params(cfg)


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 48)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prefill_len", 40)
    return ServingEngine(cfg, params=params, **kw)


def _requests(**kw):
    rs = np.random.RandomState(3)
    return [Request(rid=i, prompt=[int(t) for t in rs.randint(0, 128, 5 + i)],
                    max_new_tokens=8, **kw) for i in range(3)]


def _drive(eng, reqs):
    for r in reqs:
        eng.submit(r)
    while any(not r.done() for r in reqs):
        eng.step()
    eng.step()  # final evict round
    return {r.rid: list(r.out_tokens) for r in reqs}


def _assert_contract(eng):
    assert eng.decode_cache_size() == 1, eng.decode_cache_size()
    assert eng.prefill_cache_size() <= 1, eng.prefill_cache_size()
    eng.allocator.check_invariants()


# ------------------------------------------------ token-for-token parity

@pytest.mark.parametrize("tp", [2, 4])
def test_tp_greedy_parity(setup, tp):
    """Greedy prefill + decode at tp must equal the tp=1 engine
    token-for-token — GSPMD re-partitions the same programs; the
    numerics (fp32-accumulated matmuls, psum'd row-parallel outputs)
    must not drift past argmax boundaries."""
    cfg, params = setup
    ref = _drive(_engine(cfg, params), _requests())
    eng = _engine(cfg, params, tp=tp)
    assert eng.tp == tp and eng.mesh is not None
    got = _drive(eng, _requests())
    assert got == ref, (tp, got, ref)
    _assert_contract(eng)


def test_tp_sampling_parity(setup):
    """Stochastic lanes ride as replicated VALUE arrays (threefry
    keys, temps, top-k/p) — per-request determinism must survive the
    mesh: same seeds, same tokens at tp=2 as at tp=1."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.8, top_k=16, seed=11)
    ref = _drive(_engine(cfg, params, sampling=True),
                 _requests(sampling=sp))
    eng = _engine(cfg, params, sampling=True, tp=2)
    got = _drive(eng, _requests(sampling=sp))
    assert got == ref, (got, ref)
    _assert_contract(eng)


def test_tp_prefix_cache_parity(setup):
    """Prefix sharing is host-side page accounting; the shared pages
    live SHARDED on the mesh and the hit path re-references them for
    a later stream — token parity and a real hit on both engines."""
    cfg, params = setup
    rs = np.random.RandomState(5)
    shared = [int(t) for t in rs.randint(0, 128, 20)]  # 2.5 pages @ 8
    reqs = lambda: [Request(rid=i, prompt=list(shared) + [20 + i],
                            max_new_tokens=8) for i in range(2)]

    def seq_drive(eng):
        # sequential streams so the second's lookup HITS the pages the
        # first registered (one prefill batch would mask the hit path)
        out = {}
        for r in reqs():
            out.update(_drive(eng, [r]))
        return out

    ref_eng = _engine(cfg, params, prefix_cache=True)
    ref = seq_drive(ref_eng)
    eng = _engine(cfg, params, prefix_cache=True, tp=2)
    got = seq_drive(eng)
    assert got == ref, (got, ref)
    assert eng.prefix.hit_tokens > 0 and ref_eng.prefix.hit_tokens > 0
    _assert_contract(eng)


def test_tp_preemption_replay_parity(setup):
    """KV-pressure preemption on the mesh: a pool too small for both
    streams' peaks (chaos-suite sizing — 16 positions over 4-token
    pages, 5 allocatable) forces a mid-stream preempt; the replay
    dispatches the same packed prefill program (sharded cache rebuilt
    page-for-page) — token parity with the uncontended engine."""
    cfg, params = setup
    reqs = lambda: [Request(rid=i, prompt=[1 + i, 2, 3, 4, 5, 6],
                            max_new_tokens=10) for i in range(2)]
    ref = _drive(_engine(cfg, params, page_size=4, num_pages=32,
                         max_seq=16), reqs())
    eng = _engine(cfg, params, page_size=4, num_pages=6, max_seq=16,
                  preempt=True, tp=2)
    got = _drive(eng, reqs())
    assert got == ref, (got, ref)
    assert eng.resilience.preempted >= 1, eng.resilience
    _assert_contract(eng)


def test_tp_one_compile_with_all_layers(setup):
    """The jaxpr-stability contract held on the mesh with sampling +
    speculative decode + prefix cache all enabled: exactly ONE decode
    program and ONE (shared admission/verify) prefill program."""
    cfg, params = setup
    reqs = [Request(rid=i, prompt=[9, 9, 4, 2, 9, 9, 4][:(4 + i)],
                    max_new_tokens=8,
                    sampling=SamplingParams(temperature=0.0, seed=i))
            for i in range(3)]
    eng = _engine(cfg, params, sampling=True, spec_decode=3,
                  prefix_cache=True, tp=2)
    _drive(eng, reqs)
    assert eng.spec_k == 3
    _assert_contract(eng)
    assert eng.mesh is not None


# ------------------------------------------------------- knob semantics

def test_resolve_serve_tp_demands_raise():
    for bad in (True, 0, -1, 2.0, "2"):
        with pytest.raises(ValueError, match="tp"):
            tp_mod.resolve_serve_tp(bad, n_heads=4)
    # whole-heads split: 4 heads cannot honor tp=3
    with pytest.raises(ValueError, match="whole heads"):
        tp_mod.resolve_serve_tp(3, n_heads=4)
    # more chips than visible
    with pytest.raises(ValueError, match="visible"):
        tp_mod.resolve_serve_tp(2, n_heads=4, n_devices=1)
    assert tp_mod.resolve_serve_tp(2, n_heads=4, n_devices=8) == 2


def test_serve_tp_env_preference(monkeypatch):
    monkeypatch.delenv("APEX_SERVE_TP", raising=False)
    assert tp_mod.resolve_serve_tp(n_heads=4) == 1
    monkeypatch.setenv("APEX_SERVE_TP", "2")
    assert tp_mod.resolve_serve_tp(n_heads=4) == 2
    # un-honorable env widths fall back to 1 (preference semantics)
    monkeypatch.setenv("APEX_SERVE_TP", "3")
    assert tp_mod.resolve_serve_tp(n_heads=4) == 1
    monkeypatch.setenv("APEX_SERVE_TP", "2")
    assert tp_mod.resolve_serve_tp(n_heads=4, n_devices=1) == 1
    # garbage rides the one-home env_int warn-once parser
    monkeypatch.setenv("APEX_SERVE_TP", "two")
    assert tp_mod.resolve_serve_tp(n_heads=4) == 1
    # per-call demand wins over the env preference
    monkeypatch.setenv("APEX_SERVE_TP", "4")
    assert tp_mod.resolve_serve_tp(2, n_heads=4) == 2


@pytest.mark.parametrize("tp", [1, 2])
def test_tp_weight_quant_composes(setup, monkeypatch, tp):
    """tp x weight_quant composition (ISSUE 20 satellite — formerly a
    two-demand raise): the int8 decode records shard along the same
    Megatron split as their float weights (tp.qparams_shardings), and
    the sharded-record engine is token-for-token the tp=1 quantized
    engine. Column records carry their per-out-channel scales on the
    split dim; row records replicate theirs (they land after the
    psum)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.parallel_state import TENSOR_AXIS

    cfg, params = setup
    monkeypatch.delenv("APEX_SERVE_TP", raising=False)
    monkeypatch.delenv("APEX_SERVE_WEIGHT_QUANT", raising=False)
    ref = _drive(_engine(cfg, params, weight_quant=True), _requests())
    eng = _engine(cfg, params, tp=tp, weight_quant=True)
    assert eng.tp == tp and eng.weight_quant \
        and eng.qparams is not None
    got = _drive(eng, _requests())
    assert got == ref, (tp, got, ref)
    _assert_contract(eng)
    if tp > 1:
        rec = eng.qparams["layers"][0]
        assert rec["qkv"]["wq"].sharding.spec == P(TENSOR_AXIS, None)
        assert rec["qkv"]["scale"].sharding.spec == P(TENSOR_AXIS)
        assert rec["dense"]["wq"].sharding.spec == P(None, TENSOR_AXIS)
        assert rec["dense"]["scale"].sharding.spec == P()
        assert eng.qparams["word_logits"]["wq"].sharding.spec == P()
    # both env preferences honored together now — nothing falls back
    monkeypatch.setenv("APEX_SERVE_TP", "2")
    monkeypatch.setenv("APEX_SERVE_WEIGHT_QUANT", "1")
    eng = _engine(cfg, params)
    assert eng.tp == 2 and eng.weight_quant \
        and eng.qparams is not None


def test_tp_default_off(setup, monkeypatch):
    """tp=1 engines are byte-identical to the pre-TP build: no mesh,
    no device_put, params untouched (the measured-dispatch default)."""
    cfg, params = setup
    monkeypatch.delenv("APEX_SERVE_TP", raising=False)
    eng = _engine(cfg, params)
    assert eng.tp == 1 and eng.mesh is None
    assert eng.params is params
