"""Bitwise resume parity (ISSUE 6): training resumed from a durable
checkpoint at step k is trajectory-identical to the uninterrupted run.

The state surface is the full TrainState the durability layer claims
to cover: params, ZeRO-sharded DistributedFusedAdam optimizer state
(per-rank flat shards on the 8-device CPU mesh's dp axis), GradScaler
state, and the RNG stream (keyed on the GLOBAL step, so a resumed run
draws exactly the noise the uninterrupted run would have drawn).
Plus the end-to-end twin: ``bench.py --resume`` restores and continues
with provenance stamped in its JSON line and content-hashed ledger
record.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import checkpoint as ckpt  # noqa: E402
from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: E402
    DistAdamState, distributed_fused_adam)
from apex_tpu.transformer.amp.grad_scaler import GradScaler  # noqa: E402
from apex_tpu.telemetry import ledger as tledger  # noqa: E402

BENCH = os.path.join(REPO, "bench.py")


def _harness():
    """The mini amp+ZeRO training harness: one jitted k-step advance
    whose RNG stream is keyed on the global step."""
    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    rs = np.random.RandomState(3)
    params = {"w": jnp.asarray(rs.randn(24, 4), jnp.float32),
              "b": jnp.asarray(rs.randn(8), jnp.float32)}
    tx = distributed_fused_adam(learning_rate=0.05, num_shards=n,
                                axis_name="dp")
    scaler = GradScaler(axis_names=())
    state_specs = DistAdamState(count=P(), m=P("dp"), v=P("dp"),
                                master=P("dp"))
    init = shard_map(lambda p: tx.init(p), mesh=mesh, in_specs=(P(),),
                     out_specs=state_specs, check_vma=False)

    def k_steps(k):
        def body(params, opt_state, ss, rng, t0):
            for i in range(k):
                key = jax.random.fold_in(rng, t0 + i)  # global-step RNG
                grads = {
                    name: jax.random.normal(
                        jax.random.fold_in(key, j), p.shape, p.dtype)
                    * 0.1 * ss.loss_scale
                    for j, (name, p) in enumerate(sorted(params.items()))
                }
                g, found = scaler.unscale(grads, ss)
                ss = scaler.update(ss, found)
                updates, opt_state = tx.update(g, opt_state, params)
                params = jax.tree_util.tree_map(
                    lambda a, u: jnp.where(found, a,
                                           a + u.astype(a.dtype)),
                    params, updates)
            return params, opt_state, ss

        return jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(), state_specs, P(), P(), P()),
            out_specs=(P(), state_specs, P()), check_vma=False))

    return params, init, scaler, k_steps, state_specs


def _assert_bitwise(a, b, what):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{what}: resumed trajectory diverged"), a, b)


def test_bitwise_resume_parity_zero_gradscaler_rng(tmp_path):
    """4 uninterrupted steps == 2 steps → durable save → restore (into
    a freshly built template, as a new process would) → 2 more steps,
    bitwise, across params + ZeRO-sharded opt state + GradScaler state
    + the RNG stream."""
    params0, init, scaler, k_steps, _ = _harness()
    rng = jax.random.PRNGKey(42)
    opt0 = init(params0)
    ss0 = scaler.init()
    step2 = k_steps(2)

    # uninterrupted: 4 steps
    p_a, o_a, ss_a = step2(params0, opt0, ss0, rng, jnp.int32(0))
    p_a, o_a, ss_a = step2(p_a, o_a, ss_a, rng, jnp.int32(2))

    # interrupted twin: 2 steps, durable save at k=2
    p_b, o_b, ss_b = step2(params0, opt0, ss0, rng, jnp.int32(0))
    writer = ckpt.DurableCheckpointer(tmp_path, async_save=False)
    manifest = writer.save(
        2, {"params": p_b, "opt": o_b, "scaler": ss_b, "rng": rng},
        meta={"step": 2, "knob_pins": {}})
    assert manifest["step"] == 2

    # resume: a FRESH template (what a new process builds from init),
    # restored through a fresh writer — nothing rides process state
    tmpl = {"params": params0, "opt": init(params0),
            "scaler": scaler.init(), "rng": jax.random.PRNGKey(0)}
    restored, m = ckpt.DurableCheckpointer(
        tmp_path, async_save=False).restore_latest(tmpl)
    assert m["id"] == manifest["id"]
    # ZeRO shards restored onto their dp sharding
    assert restored["opt"].m.sharding.spec == o_b.m.sharding.spec
    p_c, o_c, ss_c = step2(restored["params"], restored["opt"],
                           restored["scaler"], restored["rng"],
                           jnp.int32(2))

    _assert_bitwise(p_a, p_c, "params")
    _assert_bitwise(
        {"m": o_a.m, "v": o_a.v, "master": o_a.master,
         "count": o_a.count},
        {"m": o_c.m, "v": o_c.v, "master": o_c.master,
         "count": o_c.count}, "ZeRO opt state")
    _assert_bitwise(ss_a, ss_c, "GradScaler state")


def test_resume_after_corrupt_latest_matches_shorter_uninterrupted(
        tmp_path):
    """Composition with the durability walk: when the NEWEST checkpoint
    is corrupt, resume falls back one retained step and the trajectory
    from there still matches the uninterrupted run bitwise — stale
    progress, never wrong progress."""
    params0, init, scaler, k_steps, _ = _harness()
    rng = jax.random.PRNGKey(42)
    opt0, ss0 = init(params0), scaler.init()
    step2 = k_steps(2)

    p, o, ss = step2(params0, opt0, ss0, rng, jnp.int32(0))
    writer = ckpt.DurableCheckpointer(tmp_path, max_to_keep=3,
                                      async_save=False)
    writer.save(2, {"params": p, "opt": o, "scaler": ss, "rng": rng},
                meta={"step": 2})
    p4, o4, ss4 = step2(p, o, ss, rng, jnp.int32(2))
    writer.save(4, {"params": p4, "opt": o4, "scaler": ss4, "rng": rng},
                meta={"step": 4})
    with open(ckpt._data_path(str(tmp_path), 4), "r+b") as f:
        f.truncate(64)  # the wedge tore the newest checkpoint

    tmpl = {"params": params0, "opt": init(params0),
            "scaler": scaler.init(), "rng": jax.random.PRNGKey(0)}
    restored, m = writer.restore_latest(tmpl)
    assert m["step"] == 2  # fell back past the torn step 4
    p_r, o_r, ss_r = step2(restored["params"], restored["opt"],
                           restored["scaler"], restored["rng"],
                           jnp.int32(2))
    _assert_bitwise(p4, p_r, "params (resumed from fallback step)")
    _assert_bitwise(ss4, ss_r, "scaler state")


# ------------------------------------------------------ bench e2e twin

@pytest.fixture
def chaos_cache_dir(shared_smoke_cache_dir):
    return shared_smoke_cache_dir


def _bench_smoke(tmp_path, chaos_cache_dir, resume=False, extra=None):
    env = dict(os.environ)
    for k in ("APEX_WARM_ONLY", "APEX_FAULT_PLAN", "APEX_CKPT_RESUME"):
        env.pop(k, None)
    env.update(
        PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        APEX_BENCH_SMOKE="1", APEX_BENCH_INNER="1",
        APEX_COMPILE_CACHE="1", APEX_COMPILE_CACHE_DIR=chaos_cache_dir,
        APEX_CKPT_DIR=str(tmp_path / "ckpt"),
        APEX_TELEMETRY_LEDGER=str(tmp_path / "ledger.jsonl"),
        APEX_BENCH_BASELINE=str(tmp_path / "baseline.json"),
        **(extra or {}))
    if resume:
        env["APEX_CKPT_RESUME"] = "1"
    out = subprocess.run([sys.executable, BENCH], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("{")][-1]
    return json.loads(line), out


@pytest.mark.slow  # 3 full bench subprocess runs (~33s): the producer-
#                    side e2e twin. Its invariants keep fast coverage —
#                    resume/restore via the library-level parity tests
#                    above, the checker side via check 5's unit tests —
#                    so the fast tier holds the ~5-min convention.
def test_bench_resume_e2e_provenance_in_line_and_ledger(
        tmp_path, chaos_cache_dir):
    """Run 1 banks a final checkpoint (telemetry block in the JSON
    line); run 2 under --resume semantics restores it, continues from
    its step, and stamps ``resumed_from`` (ckpt id + step + pins)
    into both the JSON line and the content-hashed ledger record."""
    rec1, _ = _bench_smoke(tmp_path, chaos_cache_dir)
    # two commits: the scan-boundary save (step 3 — banked BEFORE the
    # timed dispatch, so a hard wedge there loses nothing) + the final
    assert rec1["checkpoint"]["saves"] == 2
    assert rec1["checkpoint"]["last_step"] == 6  # 2 scans x smoke K=3
    assert "resumed_from" not in rec1
    ckpt_dir = str(tmp_path / "ckpt")
    manifest = ckpt.latest_durable_manifest(ckpt_dir)
    assert manifest["step"] == 6

    rec2, out2 = _bench_smoke(tmp_path, chaos_cache_dir, resume=True)
    prov = rec2["resumed_from"]
    assert prov["ckpt"] == manifest["id"]
    assert prov["step"] == 6
    assert "pin_drift" not in prov
    assert rec2["checkpoint"]["last_step"] == 12  # continued, not reset
    assert f"resumed from {manifest['id']}" in out2.stderr

    records = tledger.read_ledger(str(tmp_path / "ledger.jsonl"))
    bench_recs = [r for r in records if r.get("harness") == "bench"]
    assert bench_recs[-1]["resumed_from"] == prov
    # provenance is INSIDE the content-hashed id: the record validates,
    # and stripping the provenance breaks its own id
    assert tledger.validate_record(bench_recs[-1]) == []
    stripped = {k: v for k, v in bench_recs[-1].items()
                if k != "resumed_from"}
    assert tledger.record_id(stripped) != bench_recs[-1]["id"]

    # ...and a THIRD run resuming under a different measurement pin
    # (APEX_REMAT=none vs the checkpoint's unset): the run proceeds but
    # the provenance names the drift — the hook check_bench_labels
    # check 5 refuses citations on
    rec3, _ = _bench_smoke(tmp_path, chaos_cache_dir, resume=True,
                           extra={"APEX_REMAT": "none"})
    prov3 = rec3["resumed_from"]
    assert prov3["pins"].get("APEX_REMAT") is None
    assert prov3["pin_drift"]["APEX_REMAT"] == [None, "none"]
