"""Contrib tier-1 tests.

Ports: apex/contrib/test/xentropy/test_label_smoothing.py (fused CE vs
reference incl. smoothing + grads), contrib clip_grad tests, focal loss vs
naive sigmoid-focal reference, index_mul_2d fwd/bwd vs dense ops,
conv_bias_relu vs unfused, group BN stat sharing over mesh subgroups.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.layer_norm import FastLayerNorm
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss


# ------------------------------- xentropy ----------------------------------

def _ce_ref(logits, labels, smoothing=0.0):
    x = np.asarray(logits, np.float64)
    lse = np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1)) \
        + x.max(-1)
    nll = lse - np.take_along_axis(x, labels[:, None], -1)[:, 0]
    if smoothing:
        mean_all = lse - x.mean(-1)
        return (1 - smoothing) * nll + smoothing * mean_all
    return nll


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_matches_reference(smoothing):
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(8, 32), jnp.float32)
    labels = jnp.asarray(rs.randint(0, 32, (8,)))
    got = softmax_cross_entropy_loss(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(got),
                               _ce_ref(logits, np.asarray(labels), smoothing),
                               rtol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_grad_matches_autodiff(smoothing):
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(4, 16), jnp.float32)
    labels = jnp.asarray(rs.randint(0, 16, (4,)))

    def fused(x):
        return jnp.sum(softmax_cross_entropy_loss(x, labels, smoothing))

    def plain(x):
        logp = jax.nn.log_softmax(x)
        nll = -jnp.take_along_axis(logp, labels[:, None], -1)[:, 0]
        if smoothing:
            nll = (1 - smoothing) * nll - smoothing * jnp.mean(logp, -1)
        return jnp.sum(nll)

    np.testing.assert_allclose(np.asarray(jax.grad(fused)(logits)),
                               np.asarray(jax.grad(plain)(logits)),
                               atol=1e-5)


def test_xentropy_half_to_float():
    logits = jnp.ones((2, 8), jnp.bfloat16)
    labels = jnp.zeros((2,), jnp.int32)
    assert softmax_cross_entropy_loss(logits, labels, 0.0,
                                      True).dtype == jnp.float32
    assert softmax_cross_entropy_loss(logits, labels, 0.0,
                                      False).dtype == jnp.bfloat16


# ------------------------------- clip_grad ---------------------------------

def test_clip_grad_norm_scales_and_noops():
    grads = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
    total = float(np.sqrt(3 * 16 + 4 * 9))
    clipped, norm = clip_grad_norm_(grads, max_norm=total * 2)
    np.testing.assert_allclose(float(norm), total, rtol=1e-6)
    # above max_norm → untouched
    np.testing.assert_allclose(np.asarray(clipped["a"]), 4.0, rtol=1e-5)
    clipped, _ = clip_grad_norm_(grads, max_norm=1.0)
    new_norm = np.sqrt(sum(float(jnp.sum(g ** 2))
                           for g in jax.tree_util.tree_leaves(clipped)))
    np.testing.assert_allclose(new_norm, 1.0, rtol=1e-4)


def test_clip_grad_norm_inf_norm():
    grads = [jnp.asarray([1.0, -5.0]), jnp.asarray([2.0])]
    _, norm = clip_grad_norm_(grads, 10.0, norm_type=float("inf"))
    assert float(norm) == 5.0


def test_clip_grad_norm_nonfinite_raises():
    with pytest.raises(RuntimeError):
        clip_grad_norm_([jnp.asarray([np.inf])], 1.0,
                        error_if_nonfinite=True)


# ------------------------------- focal loss --------------------------------

def test_focal_loss_matches_naive():
    """vs a naive per-element sigmoid focal loss (the contrib test's
    reference implementation pattern)."""
    rs = np.random.RandomState(2)
    n_anchor, n_cls = 16, 8
    logits = rs.randn(n_anchor, n_cls).astype(np.float32)
    targets = rs.randint(-2, n_cls, (n_anchor,))
    npos = np.float32(max((targets >= 0).sum(), 1))
    alpha, gamma = 0.25, 2.0

    got = float(focal_loss(jnp.asarray(logits), jnp.asarray(targets),
                           jnp.asarray(npos), n_cls, alpha, gamma))

    x = logits.astype(np.float64)
    p = 1 / (1 + np.exp(-x))
    want = 0.0
    for i in range(n_anchor):
        if targets[i] == -2:
            continue
        for c in range(n_cls):
            y = 1.0 if targets[i] == c else 0.0
            pt = p[i, c] * y + (1 - p[i, c]) * (1 - y)
            at = alpha * y + (1 - alpha) * (1 - y)
            want += -at * (1 - pt) ** gamma * np.log(pt)
    np.testing.assert_allclose(got, want / npos, rtol=1e-4)


def test_focal_loss_grad_finite():
    logits = jnp.zeros((4, 4), jnp.float32)
    targets = jnp.asarray([0, 1, -1, -2])
    g = jax.grad(lambda x: focal_loss(x, targets, jnp.float32(2.0), 4,
                                      0.25, 2.0))(logits)
    assert np.isfinite(np.asarray(g)).all()
    # ignored anchor (-2) must get zero grad
    np.testing.assert_array_equal(np.asarray(g)[3], 0)


# ------------------------------ index_mul_2d -------------------------------

def test_index_mul_2d_fwd_bwd():
    rs = np.random.RandomState(3)
    in1 = jnp.asarray(rs.randn(10, 4), jnp.float32)
    in2 = jnp.asarray(rs.randn(6, 4), jnp.float32)
    idx = jnp.asarray(rs.randint(0, 10, (6,)))
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(in1)[np.asarray(idx)]
                               * np.asarray(in2), rtol=1e-6)

    def fused(a, b):
        return jnp.sum(index_mul_2d(a, b, idx) ** 2)

    def plain(a, b):
        return jnp.sum((jnp.take(a, idx, axis=0) * b) ** 2)

    ga, gb = jax.grad(fused, argnums=(0, 1))(in1, in2)
    ga2, gb2 = jax.grad(plain, argnums=(0, 1))(in1, in2)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ga2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb2), atol=1e-5)


# ------------------------------ conv_bias_relu -----------------------------

def test_conv_bias_relu_variants():
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 8, 8, 3), jnp.float32)
    w = jnp.asarray(rs.randn(3, 3, 3, 5) * 0.1, jnp.float32)
    b = jnp.asarray(rs.randn(5), jnp.float32)
    mask = jnp.asarray(rs.rand(2, 8, 8, 5) < 0.5, jnp.float32)
    scale = jnp.asarray(rs.rand(5) + 0.5, jnp.float32)

    from jax import lax
    raw = lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

    np.testing.assert_allclose(
        np.asarray(ConvBiasReLU.apply(x, w, b, 1, 1)),
        np.maximum(np.asarray(raw) + np.asarray(b), 0), atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ConvBias.apply(x, w, b, 1, 1)),
        np.asarray(raw) + np.asarray(b), atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ConvBiasMaskReLU.apply(x, w, b, mask, 1, 1)),
        np.maximum((np.asarray(raw) + np.asarray(b)) * np.asarray(mask), 0),
        atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ConvFrozenScaleBiasReLU.apply(x, w, scale, b, 1, 1)),
        np.maximum(np.asarray(raw) * np.asarray(scale) + np.asarray(b), 0),
        atol=1e-4)


# ------------------------------ group BN -----------------------------------

def test_groupbn_parity_with_plain_bn():
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(4, 6, 6, 8), jnp.float32)
    bn = BatchNorm2d_NHWC(num_features=8)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(vars_, x, mutable=["batch_stats"])
    xf = np.asarray(x)
    want = (xf - xf.mean((0, 1, 2))) / np.sqrt(xf.var((0, 1, 2)) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)


def test_groupbn_fuse_relu_and_residual():
    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(2, 4, 4, 3), jnp.float32)
    z = jnp.asarray(rs.randn(2, 4, 4, 3), jnp.float32)
    bn = BatchNorm2d_NHWC(num_features=3, fuse_relu=True)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(vars_, x, z, mutable=["batch_stats"])
    assert (np.asarray(y) >= 0).all()


@pytest.mark.slow  # multi-subgroup shard_map compile; the plain
# group-BN parity test stays fast
def test_group_bn_stats_shared_across_subgroups():
    """bn_group=2 over an 8-wide dp axis: stats equal within pairs,
    differ across pairs (reference: bn_group semantics)."""
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(16, 4, 4, 3), jnp.float32)
    bn = GroupBatchNorm2d(num_features=3, group_size=2, axis_name="dp")

    def run(x):
        vars_ = bn.init(jax.random.PRNGKey(0), x)
        y, new_vars = bn.apply(vars_, x, mutable=["batch_stats"])
        return y, new_vars["batch_stats"]["running_mean"]

    y, means = shard_map(run, mesh=mesh, in_specs=(P("dp"),),
                         out_specs=(P("dp"), P("dp")), check_vma=False)(x)
    means = np.asarray(means).reshape(8, 3)
    for pair in range(4):
        np.testing.assert_allclose(means[2 * pair], means[2 * pair + 1],
                                   rtol=1e-5)
    assert not np.allclose(means[0], means[2])


def test_fast_layer_norm_alias():
    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(4, 768), jnp.float32)
    ln = FastLayerNorm(768)
    vars_ = ln.init(jax.random.PRNGKey(0), x)
    y = ln.apply(vars_, x)
    xf = np.asarray(x)
    want = (xf - xf.mean(-1, keepdims=True)) \
        / np.sqrt(xf.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)
