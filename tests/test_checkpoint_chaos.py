"""Chaos twins for the checkpoint durability invariants (ISSUE 6).

Every new fault mode is scripted through ``APEX_FAULT_PLAN``
(apex_tpu.resilience.faults) and fired inside the REAL commit path
(tests/ckpt_chaos_worker.py subprocesses; bench.py itself for the
emergency-save path), asserting the committed behaviors:

* SIGKILL mid-commit (between the data rename and the manifest rename)
  leaves a torn file that is NEVER restored — the prior checkpoint
  stays the newest valid one, bitwise intact,
* SIGKILL before the data rename leaves no visible artifact at all,
* a post-commit corrupted/truncated data file fails the manifest hash
  check and the restore walk falls back one step,
* a stale-step manifest tamper (step field vs filename) is refused,
* bench.py's SIGTERM path (the watchdog's terminate-with-grace)
  flushes an emergency checkpoint + a ``bench_emergency_save`` ledger
  record next to its best JSON line,
* the watchdog's own SIGTERM record (``bench_watchdog``) reports the
  newest committed checkpoint on disk, so a terminated window
  self-describes what ``--resume`` will pick up.

Fast-keeping rule: the worker subprocesses never touch a backend
beyond jax import (~3-4 s each); only the bench emergency-save twin
pays a real CPU smoke run, and it shares the suite-wide smoke compile
cache (tests/conftest.py).
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import checkpoint as ckpt  # noqa: E402
from apex_tpu.telemetry import ledger as tledger  # noqa: E402
from tests.ckpt_chaos_worker import state_at  # noqa: E402

WORKER = os.path.join(REPO, "tests", "ckpt_chaos_worker.py")
BENCH = os.path.join(REPO, "bench.py")


def _run_worker(ckpt_dir, steps, plan):
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               APEX_FAULT_PLAN=json.dumps(plan))
    return subprocess.run(
        [sys.executable, WORKER, str(ckpt_dir)] + [str(s) for s in steps],
        env=env, capture_output=True, text=True, timeout=120)


def _assert_restores_step(ckpt_dir, template_step, want_step):
    restored, manifest = ckpt.restore_durable(
        str(ckpt_dir), state_at(template_step))
    assert manifest is not None, "no valid checkpoint survived"
    assert manifest["step"] == want_step
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        restored, state_at(want_step))


def test_chaos_sigkill_between_renames_never_tears_a_restore(tmp_path):
    """The torn window: SIGKILL lands after the data rename, before the
    manifest rename. The step-2 data file exists on disk but is
    invisible to the restore walk; step 1 restores bitwise intact."""
    plan = [{"site": "ckpt_commit", "kind": "sigkill",
             "match_ctx": {"phase": "data_visible", "step": 2}}]
    out = _run_worker(tmp_path, [1, 2], plan)
    assert out.returncode == -signal.SIGKILL
    assert "committed 1" in out.stdout and "DONE" not in out.stdout
    # the torn artifact is there — and ignored
    assert os.path.exists(ckpt._data_path(str(tmp_path), 2))
    assert not os.path.exists(ckpt._manifest_path(str(tmp_path), 2))
    assert ckpt.durable_steps(str(tmp_path)) == [1]
    _assert_restores_step(tmp_path, 1, want_step=1)


def test_chaos_sigkill_before_data_rename_leaves_prior_intact(tmp_path):
    """SIGKILL during serialization (pre-rename): no step-2 artifact
    becomes visible at all; the prior checkpoint is untouched."""
    plan = [{"site": "ckpt_commit", "kind": "sigkill",
             "match_ctx": {"phase": "serialized", "step": 2}}]
    out = _run_worker(tmp_path, [1, 2], plan)
    assert out.returncode == -signal.SIGKILL
    assert not os.path.exists(ckpt._data_path(str(tmp_path), 2))
    _assert_restores_step(tmp_path, 1, want_step=1)


def test_chaos_damaged_and_stale_checkpoints_chain_fallback(tmp_path):
    """The three post-commit damage modes in ONE worker run (each fault
    targets its own step, so one subprocess proves all three AND that
    the fallback walk chains): step 4's manifest is stale-tampered
    (claims step 1), step 3's data file is corrupted, step 2's is
    truncated — restore refuses 4, 3 and 2 in turn and lands on the
    intact step 1, bitwise."""
    plan = [
        {"site": "ckpt_data", "kind": "truncate_file", "keep_bytes": 32,
         "match_ctx": {"step": 2}},
        {"site": "ckpt_data", "kind": "corrupt_file", "offset": 64,
         "match_ctx": {"step": 3}},
        {"site": "ckpt_manifest", "kind": "set_field", "field": "step",
         "value": 1, "match_ctx": {"step": 4}},
    ]
    out = _run_worker(tmp_path, [1, 2, 3, 4], plan)
    assert out.returncode == 0, out.stderr[-2000:]
    assert ckpt.durable_steps(str(tmp_path)) == [1, 2, 3, 4]  # committed
    _assert_restores_step(tmp_path, 1, want_step=1)  # ...4, 3, 2 refused


def test_chaos_slow_disk_stall_still_commits(tmp_path):
    """The slow-disk commit stall: the commit takes the injected stall
    but COMMITS — durability degrades to latency, never to loss — and
    the stall is visible in the worker's commit telemetry."""
    plan = [{"site": "ckpt_commit", "kind": "hang", "seconds": 1.0,
             "match_ctx": {"phase": "serialized", "step": 2}}]
    t0 = time.perf_counter()
    out = _run_worker(tmp_path, [1, 2], plan)
    wall = time.perf_counter() - t0
    assert out.returncode == 0, out.stderr[-2000:]
    assert wall >= 1.0
    assert ckpt.durable_steps(str(tmp_path)) == [1, 2]
    _assert_restores_step(tmp_path, 2, want_step=2)


# --------------------------------------------------- bench e2e twins
# (one real CPU smoke run each; shared suite smoke compile cache)

@pytest.fixture
def chaos_cache_dir(shared_smoke_cache_dir):
    return shared_smoke_cache_dir


def _bench_env(tmp_path, chaos_cache_dir, plan=None, **extra):
    env = dict(os.environ)
    for k in ("APEX_WARM_ONLY", "APEX_FAULT_PLAN", "APEX_CKPT_RESUME"):
        env.pop(k, None)
    env.update(
        PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        APEX_BENCH_SMOKE="1",
        APEX_COMPILE_CACHE="1", APEX_COMPILE_CACHE_DIR=chaos_cache_dir,
        APEX_CKPT_DIR=str(tmp_path / "ckpt"),
        APEX_TELEMETRY_LEDGER=str(tmp_path / "ledger.jsonl"),
        APEX_BENCH_BASELINE=str(tmp_path / "baseline.json"),
        **extra)
    if plan is not None:
        env["APEX_FAULT_PLAN"] = json.dumps(plan)
    return env


def test_chaos_sigterm_during_final_save_flushes_emergency_ckpt(
        tmp_path, chaos_cache_dir):
    """The watchdog-terminate path end-to-end: a wedge strikes at the
    final save (injected hang), the outer SIGTERM lands — the inner
    bench commits its staged scan-boundary state as an emergency
    checkpoint and appends a ``bench_emergency_save`` ledger record,
    then exits 143. Nothing that ran in the window is lost."""
    plan = [{"site": "final_save", "kind": "hang"}]
    env = _bench_env(tmp_path, chaos_cache_dir, plan,
                     APEX_BENCH_INNER="1")
    err_path = tmp_path / "stderr.log"
    with open(err_path, "w") as errf:
        proc = subprocess.Popen([sys.executable, BENCH], env=env,
                                stdout=subprocess.PIPE, stderr=errf,
                                text=True)
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if "site=final_save" in err_path.read_text():
                break
            time.sleep(0.25)
        assert proc.poll() is None, (
            f"bench exited early rc={proc.returncode}: "
            f"{err_path.read_text()[-2000:]}")
        proc.terminate()
        proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 143
    assert "emergency checkpoint committed" in err_path.read_text()
    # the staged scan-boundary state (warm scan's output: step0+iters
    # = 3 in smoke) was committed with a valid manifest
    ckpt_dir = str(tmp_path / "ckpt")
    steps = ckpt.durable_steps(ckpt_dir)
    assert steps and steps[-1] == 3
    manifest = ckpt.read_durable_manifest(ckpt_dir, 3)
    assert ckpt._verify_durable(ckpt_dir, 3, manifest) is None
    records = tledger.read_ledger(str(tmp_path / "ledger.jsonl"))
    es = [r for r in records
          if r.get("harness") == "bench_emergency_save"]
    assert len(es) == 1
    assert es[0]["terminated"] == "SIGTERM" and es[0]["ckpt_step"] == 3
    # two commits: the scan-boundary save + the emergency recommit
    assert es[0]["checkpoint"]["saves"] == 2
    assert es[0]["fault_plan"].startswith("fp-")
    assert tledger.validate_record(es[0]) == []


# re-promoted to tier-1 (ISSUE 7 fast-tier trim): rides the session
# smoke compile cache (chaos_cache_dir), ~5s warm — the watchdog-side
# ckpt_on_disk reporting comes back under tier-1 teeth instead of
# staying demoted
def test_chaos_watchdog_sigterm_record_reports_disk_checkpoint(
        tmp_path, chaos_cache_dir):
    """The watchdog's own termination record (``bench_watchdog``) must
    name the newest COMMITTED checkpoint on disk — what `--resume`
    will pick up next window — even when the in-flight child hangs
    before any backend work."""
    ckpt_dir = tmp_path / "ckpt"
    seeded = ckpt.DurableCheckpointer(ckpt_dir, async_save=False)
    manifest = seeded.save(7, {"w": jnp.ones((4,))}, meta={"step": 7})
    plan = [{"site": "backend_init", "kind": "sigterm_parent"}]
    env = _bench_env(tmp_path, chaos_cache_dir, plan,
                     APEX_BENCH_ATTEMPTS="1", APEX_BENCH_TIMEOUT="60")
    out = subprocess.run([sys.executable, BENCH], env=env,
                         capture_output=True, text=True, timeout=300)
    records = tledger.read_ledger(str(tmp_path / "ledger.jsonl"))
    wd = [r for r in records if r.get("harness") == "bench_watchdog"]
    assert len(wd) == 1, (out.stdout, out.stderr[-2000:])
    assert wd[0]["terminated"] == "SIGTERM"
    assert wd[0]["ckpt_on_disk"] == {"last_step": 7,
                                     "id": manifest["id"]}
    assert tledger.validate_record(wd[0]) == []
