"""Multi-token decode blocks (ISSUE 17): K decode steps per device
dispatch in ONE ``lax.scan`` program. The headline invariant is
token-for-token parity with the K=1 engine under EVERY layer
combination — greedy serial + overlapped rounds, sampling-lane RNG
determinism (the counter folds inside the scan), prefix-cache sharing,
mid-block preemption requeue/replay, shed/admit at block boundaries,
and a chaos ``serve_decode`` hang recovering the whole K-block — plus
the one-compile contract (``decode_cache_size() == 1`` per engine; K
is a static key, budgets/warmup feeds are values), the knob-asymmetry
surface of ``resolve_decode_k`` × ``spec_decode``, and the ledger /
check-8 teeth for the ``decode_block_k`` field."""

import json
import os

import pytest

from apex_tpu.resilience import faults
from apex_tpu.serving import (
    Request,
    SamplingParams,
    ServingEngine,
    lifecycle,
    synthetic_trace,
)
from apex_tpu.serving import model as smodel

from apex_tpu.telemetry import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KS = (2, 4, 8)


def _cfg():
    from apex_tpu.transformer.testing import TransformerConfig

    return TransformerConfig(
        hidden_size=64, num_layers=2, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, bf16=False)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    params = smodel.init_gpt_params(cfg)
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("APEX_FAULT_PLAN", raising=False)
    faults._cache["fired"] = {}
    yield
    faults._cache["fired"] = {}


def _engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 48)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prefill_len", 40)
    return ServingEngine(cfg, params=params, **kw)


def _run(cfg, params, k, trace_kw=None, **kw):
    eng = _engine(cfg, params, decode_k=k, **kw)
    tkw = dict(seed=3, n_requests=8, vocab=128, prompt_lo=4,
               prompt_hi=12, new_lo=3, new_hi=10)
    tkw.update(trace_kw or {})
    reqs, _ = synthetic_trace(**tkw)
    out = eng.run_trace(reqs)
    return {r.rid: list(r.out_tokens) for r in out}, eng


def _contract(eng):
    assert eng.decode_cache_size() == 1, eng.decode_cache_size()
    assert eng.prefill_cache_size() <= 1, eng.prefill_cache_size()
    eng.allocator.check_invariants()
    if eng.prefix is not None:
        eng.prefix.check_invariants()


# ------------------------------------------------------ knob asymmetry


def test_resolve_decode_k_knob_asymmetry(monkeypatch):
    """Per-call decode_k= is a DEMAND (raises on un-honorable);
    APEX_SERVE_DECODE_K is a PREFERENCE through the one-home
    positive-int parser (garbage warns once, falls back to 1)."""
    monkeypatch.delenv("APEX_SERVE_DECODE_K", raising=False)
    for bad in (True, False, 0, -1, 1.5, "4"):
        with pytest.raises(ValueError):
            smodel.resolve_decode_k(bad)
    assert smodel.resolve_decode_k(4) == 4
    assert smodel.resolve_decode_k() == 1
    monkeypatch.setenv("APEX_SERVE_DECODE_K", "4")
    assert smodel.resolve_decode_k() == 4
    # a per-call demand outranks the env preference
    assert smodel.resolve_decode_k(2) == 2
    from apex_tpu.dispatch import tiles

    tiles._warned_env.clear()
    monkeypatch.setenv("APEX_SERVE_DECODE_K", "fast")
    with pytest.warns(UserWarning, match="fast"):
        assert smodel.resolve_decode_k() == 1


def test_decode_k_times_spec_decode_pairing(setup, monkeypatch):
    """The established two-demands-raise / demand-drops-preference /
    env-falls-back asymmetry across the decode_k × spec_decode pair
    (both batch multiple tokens per dispatch; the verify rollback
    assumes ONE pending token per round)."""
    cfg, params = setup
    monkeypatch.delenv("APEX_SERVE_DECODE_K", raising=False)
    monkeypatch.delenv("APEX_SPEC_DECODE", raising=False)
    # two per-call demands: no honorable order -> raise
    with pytest.raises(ValueError, match="decode_k"):
        _engine(cfg, params, decode_k=4, spec_decode=3)
    # per-call K-block demand drops the env draft preference
    monkeypatch.setenv("APEX_SPEC_DECODE", "3")
    eng = _engine(cfg, params, decode_k=4)
    assert eng.decode_k == 4 and eng.spec_k == 0
    assert eng.spec_stats is None
    monkeypatch.delenv("APEX_SPEC_DECODE")
    # env K preference yields to a per-call spec demand
    monkeypatch.setenv("APEX_SERVE_DECODE_K", "4")
    eng = _engine(cfg, params, spec_decode=3)
    assert eng.decode_k == 1 and eng.spec_k == 3
    # env vs env: K falls back to 1 (the committed measurement backs
    # the spec layer; the K-block row is still queued in PERF.md §2)
    monkeypatch.setenv("APEX_SPEC_DECODE", "3")
    eng = _engine(cfg, params)
    assert eng.decode_k == 1 and eng.spec_k == 3


# --------------------------------------------------- parity vs K=1


def test_greedy_parity_and_dispatch_amortization(setup):
    """THE acceptance invariant: every K emits the K=1 engine's tokens
    token-for-token, with one compiled decode program, while
    ``decode_steps`` (DISPATCH count — the ~65 ms relay unit) drops."""
    cfg, params = setup
    base, e1 = _run(cfg, params, 1)
    for k in KS:
        got, ek = _run(cfg, params, k)
        assert got == base, k
        _contract(ek)
        assert ek.tokens_generated == e1.tokens_generated
        assert ek.decode_steps < e1.decode_steps, \
            (k, ek.decode_steps, e1.decode_steps)


def test_overlap_rounds_dispatch_k_blocks(setup):
    """The overlapped round defers the SAME K-block fetch: parity with
    the serial K=1 stream under overlap=True for every K."""
    cfg, params = setup
    base, _ = _run(cfg, params, 1)
    for k in KS:
        got, ek = _run(cfg, params, k, overlap=True)
        assert got == base, k
        _contract(ek)


def test_sampling_rng_determinism_across_k(setup):
    """Sampling lanes fold the per-step generation index inside the
    scan: seeded streams are identical whatever the block size (the
    (key, counter) draw depends on neither K nor batch shape)."""
    cfg, params = setup

    def run(k):
        eng = _engine(cfg, params, decode_k=k, sampling=True)
        reqs, _ = synthetic_trace(seed=5, n_requests=6, vocab=128,
                                  prompt_lo=4, prompt_hi=10,
                                  new_lo=3, new_hi=8)
        for r in reqs:
            r.sampling = SamplingParams(temperature=0.9, top_k=20,
                                        seed=100 + r.rid)
        out = eng.run_trace(reqs)
        assert eng.decode_cache_size() == 1
        return {r.rid: list(r.out_tokens) for r in out}

    base = run(1)
    for k in KS:
        assert run(k) == base, k


def test_prefix_cache_parity_across_k(setup):
    """Shared-prefix COW pages under K-block decode: the block's page
    writes land past the shared span, so hits/refcounts/streams all
    match the K=1 engine."""
    cfg, params = setup

    def run(k):
        return _run(cfg, params, k, prefix_cache=True, trace_kw=dict(
            system_prompt=[7, 9, 11, 13, 5, 3]))

    base, _ = run(1)
    for k in KS:
        got, ek = run(k)
        assert got == base, k
        _contract(ek)


def test_preemption_midblock_requeue_replay_parity(setup):
    """A pool too small for every stream's peak forces mid-block
    grant refusals: victims requeue with their partial tokens (the
    ordinary ``resume_tokens`` replay path) and every K's final
    streams are token-for-token the K=1 engine's — preemption never
    drops a request, so parity is over the FULL trace."""
    cfg, params = setup

    def run(k):
        return _run(cfg, params, k, preempt=True, page_size=4,
                    num_pages=9, max_seq=32, prefill_len=32,
                    trace_kw=dict(n_requests=10, new_lo=8, new_hi=24))

    base, e1 = run(1)
    assert e1.resilience.preempted > 0, \
        "trace did not exercise preemption — tighten the pool"
    for k in KS:
        got, ek = run(k)
        assert got == base, k
        _contract(ek)
        assert ek.resilience.preempted > 0, k


def test_shed_admit_armed_but_untriggered_is_pure_addition(setup):
    """The disabled-mode converse under K-blocks: admission control +
    shedding ARMED but never triggering (roomy queue bound, huge TTFT
    threshold) leave every K's streams token-for-token the K=1
    engine's — the queue layers are pure additions at every block
    size."""
    cfg, params = setup

    def run(k):
        return _run(cfg, params, k, shed=True, admit=16,
                    shed_ttft_ms=1e9, trace_kw=dict(
                        n_requests=12, mean_interarrival=0.1))

    base, e1 = run(1)
    assert e1.resilience.shed == 0 and e1.resilience.rejected == 0
    for k in KS:
        got, ek = run(k)
        assert got == base, k
        _contract(ek)


def test_shed_admit_trigger_at_block_boundaries(setup):
    """Queue-side layers under real overload act at K-tick (block)
    granularity: a one-slot K=4 engine with a bounded queue and a
    tiny TTFT threshold rejects the overflow at submit, sheds the
    queue-stuck requests between blocks (never mid-block — shed
    requests have NO tokens), and the survivors' streams stay
    token-for-token the uncontended engine's (per-request streams
    do not depend on the admission set)."""
    cfg, params = setup
    ref_reqs = [Request(rid=i, prompt=[1 + i, 2, 3],
                        max_new_tokens=12, arrival=0)
                for i in range(6)]
    ref_eng = _engine(cfg, params, decode_k=4)
    ref = {r.rid: list(r.out_tokens)
           for r in ref_eng.run_trace(ref_reqs)}
    lifecycle.enable()
    try:
        eng = _engine(cfg, params, num_slots=1, decode_k=4,
                      shed=True, shed_ttft_ms=1.0, admit=4)
    finally:
        lifecycle.reset_enabled()
    reqs = [Request(rid=i, prompt=[1 + i, 2, 3], max_new_tokens=12,
                    arrival=0) for i in range(6)]
    done = eng.run_trace(reqs)
    assert eng.resilience.rejected > 0      # admit bound at submit
    assert eng.resilience.shed > 0          # deadline shedder fired
    assert len(done) + len(eng.scheduler.shed) \
        + len(eng.rejected) == 6            # every request settles once
    for r in eng.scheduler.shed:
        assert not r.out_tokens             # shed only BETWEEN blocks
        assert r.shed_tick is not None
    for r in done:
        assert list(r.out_tokens) == ref[r.rid], r.rid
    assert eng.events.validate_order() == []
    _contract(eng)


# -------------------------------------------- chaos: whole-block unit


def test_chaos_decode_hang_recovers_whole_k_block(setup, monkeypatch):
    """The watchdog treats the K-block as its dispatch unit: a wedged
    K=4 block times out ONCE, every in-flight request requeues (no
    partial block tokens leak), and the replay finishes token-for-token
    the healthy K=1 streams."""
    cfg, params = setup
    reqs = [Request(rid=0, prompt=[1, 2, 3, 4, 5, 6],
                    max_new_tokens=10),
            Request(rid=1, prompt=[7, 8, 9, 10, 11, 12],
                    max_new_tokens=10)]
    ref_eng = _engine(cfg, params)
    for r in reqs:
        ref_eng.submit(r)
    while not all(r.done() for r in reqs):
        ref_eng.step()
    ref = {r.rid: list(r.out_tokens) for r in reqs}

    lifecycle.enable()
    try:
        eng = _engine(cfg, params, decode_k=4, recover=True,
                      dispatch_timeout_s=60, round_retry_wait_s=0)
    finally:
        lifecycle.reset_enabled()
    reqs = [Request(rid=0, prompt=[1, 2, 3, 4, 5, 6],
                    max_new_tokens=10),
            Request(rid=1, prompt=[7, 8, 9, 10, 11, 12],
                    max_new_tokens=10)]
    for r in reqs:
        eng.submit(r)
    eng.step()          # prefill + K-block decode compile (tick 0)
    eng.step()          # a steady-state block (tick 1)
    monkeypatch.setenv("APEX_FAULT_PLAN", json.dumps(
        [{"site": "serve_decode", "kind": "hang", "seconds": 1.0,
          "match_ctx": {"tick": 2}}]))
    eng.dispatch_timeout_s = 0.25
    degraded = []
    n = 0
    while not all(r.done() for r in reqs):
        out = eng.step()
        if out.get("degraded"):
            degraded.append(out["degraded"])
        n += 1
        assert n < 100
    eng.step()
    assert len(degraded) == 1
    assert degraded[0]["verdict"] == "wedged"
    assert degraded[0]["phase"] == "decode"
    assert eng.resilience.degraded_rounds == 1
    for r in reqs:
        assert r.out_tokens == ref[r.rid], (r.rid, r.out_tokens)
    assert eng.events.validate_order() == []
    _contract(eng)


# ----------------------------------------------- one-compile contract


def test_one_compile_contract_with_layers_on(setup):
    """K is a STATIC program key; per-lane budgets, the warmup feed
    and sampling counters ride as values — so a K=4 engine with
    sampling + prefix cache on over a churning trace still compiles
    exactly ONE decode program and at most one prefill program."""
    cfg, params = setup
    eng = _engine(cfg, params, decode_k=4, sampling=True,
                  prefix_cache=True, num_pages=64)
    reqs, _ = synthetic_trace(seed=9, n_requests=8, vocab=128,
                              prompt_lo=4, prompt_hi=12, new_lo=2,
                              new_hi=9, system_prompt=[3, 1, 4, 1, 5])
    for i, r in enumerate(reqs):
        if i % 2:
            r.sampling = SamplingParams(temperature=0.8, top_k=16,
                                        seed=r.rid)
    eng.run_trace(reqs)
    eng.step()
    assert eng.decode_cache_size() == 1, \
        "the K-block program recompiled — a budget/warmup input " \
        "leaked into the compile key"
    assert eng.prefill_cache_size() <= 1
    _contract(eng)


# ------------------------------------------------- ledger / check 8


def _check8(tmp_path, knobs, extra):
    from tests.conftest import run_check_bench_labels

    rec = ledger_mod.make_record("profile_serving", "cpu", 0.1, 2,
                                 knobs=knobs, extra=extra)
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(rec) + "\n")
    perf = tmp_path / "PERF.md"
    perf.write_text(f"multitok row cites ledger:{rec['id']}\n")
    table = tmp_path / "table.jsonl"
    table.write_text("")
    return run_check_bench_labels(
        "--perf", str(perf), "--ledger", str(ledger),
        "--table", str(table))


def _record(decode_block_k, **knobs):
    from tests.test_serving_slo import SLO_PINS, _good_slo

    pins = {"APEX_SERVE_WEIGHT_QUANT": "0",
            "APEX_DECODE_ATTN_IMPL": "jnp",
            "APEX_SERVE_KV_QUANT": "0", "APEX_SERVE_KV_SWAP": "0",
            **SLO_PINS, **knobs}
    slo = dict(_good_slo(), decode_block_k=decode_block_k)
    serving = {"tokens_per_s": 10.0, "p50_ms": 1.0, "p99_ms": 2.0,
               "trace_id": "tr-0123456789", "kv_pages": 8}
    return pins, {"serving": serving, "slo": slo}


def test_check8_serving_row_must_pin_decode_k(tmp_path):
    pins, extra = _record(4)
    out = _check8(tmp_path, pins, extra)
    assert out.returncode == 1
    assert "APEX_SERVE_DECODE_K" in out.stdout


def test_check8_decode_k_pin_and_block_must_agree(tmp_path):
    # pin names K=4 but the engine ran K=1: different programs
    pins, extra = _record(1, APEX_SERVE_DECODE_K="4")
    out = _check8(tmp_path, pins, extra)
    assert out.returncode == 1
    assert "different decode programs" in out.stdout
    # the other direction: block claims K=4 under a K=1 pin
    pins, extra = _record(4, APEX_SERVE_DECODE_K="1")
    out = _check8(tmp_path, pins, extra)
    assert out.returncode == 1
    assert "different decode programs" in out.stdout
    # a corrupt pin is a FINDING, never a checker crash
    pins, extra = _record(4, APEX_SERVE_DECODE_K="turbo")
    out = _check8(tmp_path, pins, extra)
    assert out.returncode == 1
    assert "not a number" in out.stdout


def test_check8_matching_decode_k_row_clean(tmp_path):
    pins, extra = _record(4, APEX_SERVE_DECODE_K="4")
    out = _check8(tmp_path, pins, extra)
    assert out.returncode == 0, out.stdout
