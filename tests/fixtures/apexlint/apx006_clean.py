"""APX006 clean twin: the jax import is deferred to call time (the
documented lazy pattern)."""


def f():
    import jax

    return jax.devices()
