"""APX000 fixture: a pragma naming an unknown rule."""

# apexlint: disable=APX999 — no such rule
X = 1
