"""APX005 fixture with stale citations.

reference: missing_file.py:5 — the file does not exist; and
reference: ok.py:999 is far out of range.
"""
