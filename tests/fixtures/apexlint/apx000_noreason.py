"""APX000 fixture: a pragma without a reason."""
import time


def f():
    return time.time()  # apexlint: disable=APX004
