"""APX006 fixture: a RELATIVE module-level import reaching jax — the
walk must resolve it against the module's own package."""
from .helper_rel import helper


def f():
    return helper()
