"""APX002 clean twin: reads through the one-home parsers, plus env
WRITES (which are pins, not reads)."""
import os

from apex_tpu.dispatch.tiles import env_flag, env_int


def helper_reads():
    return env_flag("APEX_DOCED") or env_int("APEX_INFRA_X")


def pins_for_child():
    os.environ["APEX_FIX_RAW"] = "1"
    return dict(os.environ, APEX_FIX_CHILD="1")
