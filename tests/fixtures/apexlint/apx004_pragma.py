"""APX004 pragma twin: a line-level suppression with a reason."""
import time


def budget_clock():
    # apexlint: disable=APX004 — fixture: budget wall clock, not a measured row
    return time.perf_counter()
