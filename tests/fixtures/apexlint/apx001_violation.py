"""APX001 fixture: import-time env reads — module level, decorator
argument, and a default-argument expression (all run at import)."""
import os

MODULE_LEVEL = os.environ.get("APEX_FIX_IMPORT")


def at_call_time(default=os.getenv("APEX_FIX_DEFAULT")):
    return default


def _env_helpers_also_count():
    pass


from apex_tpu.dispatch.tiles import env_flag  # noqa: E402

HELPER_AT_IMPORT = env_flag("APEX_FIX_HELPER")
