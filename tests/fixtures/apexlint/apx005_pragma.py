"""APX005 pragma twin.

# apexlint: disable=APX005 — fixture: upstream file renamed; citation kept for history
reference: missing_file.py:5 stays cited on purpose here.
"""
