"""APX004 file-level pragma twin."""
# apexlint: disable-file=APX004 — fixture: whole file is pre-Tracer legacy
import time


def a():
    return time.time()


def b():
    return time.perf_counter()
