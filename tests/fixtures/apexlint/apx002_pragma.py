"""APX002 pragma twin."""
import os


def raw_read():
    # apexlint: disable=APX002 — fixture: this module is the knob's one home
    return os.environ.get("APEX_FIX_RAW")
