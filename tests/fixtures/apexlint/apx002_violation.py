"""APX002 fixture: raw APEX_* read outside any designated reader."""
import os as _renamed_os

NAME = "APEX_FIX_CONST"


def raw_reads():
    a = _renamed_os.environ.get("APEX_FIX_RAW")
    b = _renamed_os.environ[NAME]          # module-constant resolution
    c = "APEX_FIX_PRESENT" in _renamed_os.environ
    return a, b, c
