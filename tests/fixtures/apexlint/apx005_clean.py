"""APX005 clean twin.

reference: ok.py:3 resolves (file exists, line in range), and a range
citation reference: sub/deep.py:1-4 resolves too. A repo-internal
mention like ledger.py:1 is a self-citation, not a reference one.
"""
