"""APX001 pragma twin: the violation survives, visibly."""
import os

# apexlint: disable=APX001,APX002 — fixture: demonstrates a reasoned suppression
MODULE_LEVEL = os.environ.get("APEX_FIX_IMPORT")
