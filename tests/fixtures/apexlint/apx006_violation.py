"""APX006 fixture: a stdlib-only claimant importing numpy at module
level (placed at a claimed path by the test)."""
import numpy as np


def f():
    return np.zeros(1)
