"""APX006 fixture: clean itself, but reaches jax through an explicit
in-package module-level import."""
from apex_tpu.helper_mod import helper


def f():
    return helper()
