"""APX004 clean twin: no naked timing (a real harness would use
telemetry.tracing.Tracer/Span)."""


def measure(tracer, f, x):
    return tracer.time_call("row", f, x)
