"""APX004 fixture: naked timing in a harness."""
import time
from time import perf_counter


def measure(x, f):
    t0 = time.time()
    t1 = perf_counter()
    f(x).block_until_ready()
    return t0, t1
