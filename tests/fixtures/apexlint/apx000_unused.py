"""APX000 fixture: a reasoned pragma that suppresses nothing —
reported as unused, never a failure."""

# apexlint: disable=APX004 — fixture: nothing to suppress here
X = 1
