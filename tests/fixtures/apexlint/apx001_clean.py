"""APX001 clean twin: the same knobs read inside function bodies."""
import os


def trace_time():
    return os.getenv("APEX_FIX_DEFAULT") or os.environ.get("APEX_FIX_IMPORT")
