"""The transitive hop: imports jax at module level."""
import jax


def helper():
    return jax.devices()
