import time, numpy as np, jax, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.optimizers.fused_adam import fused_adam
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.testing import GPTModel, TransformerConfig

cfg = TransformerConfig(hidden_size=768, num_layers=12, num_attention_heads=12,
                        vocab_size=50304, max_position_embeddings=1024,
                        hidden_dropout=0.0, attention_dropout=0.0, bf16=True)
model = GPTModel(cfg)
mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
tx = fused_adam(learning_rate=1e-4)
b, s = 8, 1024
rs = np.random.RandomState(0)
ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
def shmap(f, n):
    return jax.shard_map(f, mesh=mesh, in_specs=(P(),)*n, out_specs=P(), check_vma=False)
params = jax.jit(shmap(lambda i,p: model.init(jax.random.PRNGKey(0), i, p, None)["params"], 2))(ids, pos)
opt_state = jax.jit(lambda p: tx.init(p))(params)

def plain_step(params, opt_state, ids, pos, labels):
    def local(params, opt_state, ids, pos, labels):
        loss, grads = jax.value_and_grad(lambda p: jnp.mean(model.apply({"params": p}, ids, pos, None, labels)))(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(lambda p,u: p+u.astype(p.dtype), params, updates)
        return new_params, new_opt, loss
    return jax.shard_map(local, mesh=mesh, in_specs=(P(),)*5, out_specs=P(), check_vma=False)(params, opt_state, ids, pos, labels)

step = jax.jit(plain_step, donate_argnums=(0,1))
params, opt_state, loss = step(params, opt_state, ids, pos, labels); float(loss)
params, opt_state, loss = step(params, opt_state, ids, pos, labels); float(loss)
for i in range(4):
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, ids, pos, labels)
    float(loss)
    print(f"plain adam step: {(time.perf_counter()-t0)*1000:.1f} ms")
