import time, numpy as np, jax, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.testing import GPTModel, TransformerConfig

cfg = TransformerConfig(hidden_size=768, num_layers=12, num_attention_heads=12,
                        vocab_size=50304, max_position_embeddings=1024,
                        hidden_dropout=0.0, attention_dropout=0.0, bf16=True)
model = GPTModel(cfg)
mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
b, s = 8, 1024
rs = np.random.RandomState(0)
ids_all = jnp.asarray(rs.randint(0, cfg.vocab_size, (10, b, s)), jnp.int32)
pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
labels_all = jnp.asarray(rs.randint(0, cfg.vocab_size, (10, b, s)), jnp.int32)

def shmap(f, n):
    return jax.shard_map(f, mesh=mesh, in_specs=(P(),)*n, out_specs=P(), check_vma=False)

params = jax.jit(shmap(lambda i,p: model.init(jax.random.PRNGKey(0), i, p, None)["params"], 2))(ids_all[0], pos)

def bench(name, f, arg_batches):
    jax.block_until_ready(f(*arg_batches[0]))
    t0 = time.perf_counter()
    outs = [f(*a) for a in arg_batches[1:]]
    vals = [float(o) for o in outs]
    dt = (time.perf_counter()-t0)/len(outs)
    print(f"{name}: {dt*1000:.1f} ms  ({b*s/dt:.0f} tok/s)  loss0={vals[0]:.3f}")

fwd = jax.jit(shmap(lambda p,i,po,l: jnp.mean(model.apply({"params":p}, i, po, None, l)), 4))
bench("fwd+loss", fwd, [(params, ids_all[k], pos, labels_all[k]) for k in range(10)])

vg = jax.jit(shmap(lambda p,i,po,l: jax.value_and_grad(lambda pp: jnp.mean(model.apply({"params":pp}, i, po, None, l)))(p)[0], 4))
bench("fwd+bwd", vg, [(params, ids_all[k], pos, labels_all[k]) for k in range(10)])
