"""Benchmark: flagship GPT training-step throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.

The measured program is the full apex-equivalent training step — bf16
forward/backward (amp O2 semantics), dynamic loss scaling, fused Adam —
on a GPT-2-small-shaped model, single chip.

Measurement method (see PERF.md for the calibration experiments): K steps
are chained inside ONE ``lax.scan`` under a single jit dispatch, and
completion is observed with a 1-element device fetch. On the axon-tunneled
TPU backend each dispatch costs ~65 ms of fixed relay latency and
``block_until_ready`` resolves before device execution finishes — a
per-step dispatch loop therefore measures the tunnel, not the chip (rounds
1-2 of this repo did exactly that, reporting ~7.6k tokens/s for a program
whose device time is ~20x faster). The measured per-dispatch overhead is
subtracted from the scan total.

``vs_baseline`` is the ratio against the recorded first-measurement
baseline in BENCH_BASELINE.json (created on first run; the reference repo
publishes no numbers to compare against — see BASELINE.md). The baseline
key is suffixed with the measurement method (``_scan``) — ratios against
the rounds-1/2 per-dispatch numbers would be method artifacts, not perf.
``mfu`` = model FLOPs (6*N*tokens) / step-time / chip bf16 peak.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers.fused_adam import fused_adam
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    # GPT-2 small shapes on TPU; tiny on CPU (local smoke)
    if on_tpu:
        cfg = TransformerConfig(
            hidden_size=768, num_layers=12, num_attention_heads=12,
            vocab_size=50304, max_position_embeddings=1024,
            hidden_dropout=0.0, attention_dropout=0.0, bf16=True)
        # b=16 doubles the round-2 batch while staying in the
        # known-to-compile envelope of the tunneled remote-compile helper
        # (b=32 compiles stalled it — see PERF.md); override to taste
        b = int(os.environ.get("APEX_BENCH_BATCH", "16"))
        s, iters = 1024, 16
        peak_flops = 197e12  # v5e bf16
    else:
        cfg = TransformerConfig(
            hidden_size=128, num_layers=2, num_attention_heads=4,
            vocab_size=512, max_position_embeddings=128,
            hidden_dropout=0.0, attention_dropout=0.0, bf16=True)
        b, s, iters = 2, 128, 3
        peak_flops = None

    model = GPTModel(cfg)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
    scaler = LossScaler()
    tx = fused_adam(learning_rate=1e-4)

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)

    from benchmarks._timing import measure_dispatch_overhead, sync

    def shmap(f, n_in):
        return jax.shard_map(f, mesh=mesh, in_specs=(P(),) * n_in,
                             out_specs=P(), check_vma=False)

    params = jax.jit(shmap(
        lambda ids, pos: model.init(jax.random.PRNGKey(0), ids, pos,
                                    None)["params"], 2))(ids, pos)
    opt_state = jax.jit(lambda p: tx.init(p))(params)
    scaler_state = scaler.init()

    def one_step(params, opt_state, scaler_state, ids, pos, labels):
        def loss_fn(p):
            per_tok = model.apply({"params": p}, ids, pos, None, labels)
            return jnp.mean(per_tok) * scaler_state.loss_scale

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, found_inf = scaler.unscale(grads, scaler_state)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: jnp.where(found_inf, p, p + u.astype(p.dtype)),
            params, updates)
        new_opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(found_inf, old, new),
            new_opt_state, opt_state)
        return (new_params, new_opt_state, new_scaler_state,
                loss / scaler_state.loss_scale)

    def run(params, opt_state, scaler_state, eps, ids, pos, labels):
        def local(params, opt_state, scaler_state, eps, ids, pos, labels):
            def body(carry, _):
                p, o, ss = carry
                p, o, ss, loss = one_step(p, o, ss, ids, pos, labels)
                return (p, o, ss), loss

            (params, opt_state, scaler_state), losses = lax.scan(
                body, (params, opt_state, scaler_state), jnp.arange(iters))
            # adding the traced eps (0 warm / 1e-30 timed) to the output
            # varies the call signature-values between warmup and timing,
            # defeating any same-args result caching in the relay; the
            # compute chain itself is kept live by the params carry
            return params, opt_state, scaler_state, losses + eps

        return jax.shard_map(
            local, mesh=mesh, in_specs=(P(),) * 7, out_specs=P(),
            check_vma=False)(params, opt_state, scaler_state, eps, ids, pos,
                             labels)

    # donate params/opt/scaler state so XLA updates them in place across
    # the scan (the training-loop aliasing a real deployment would have)
    step = jax.jit(run, donate_argnums=(0, 1, 2))

    overhead = measure_dispatch_overhead(iters)

    # compile + warm + drain (donated inputs: rebind the carried state)
    print(f"# compiling {iters}-step scan at b={b} s={s} ...",
          file=sys.stderr, flush=True)
    params, opt_state, scaler_state, losses = step(
        params, opt_state, scaler_state, jnp.float32(0.0), ids, pos, labels)
    sync(losses)
    print("# compiled; timing", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    out = step(params, opt_state, scaler_state, jnp.float32(1e-30), ids, pos,
               labels)
    sync(out[3])
    dt = (time.perf_counter() - t0 - overhead) / iters

    tokens_per_sec = b * s / dt
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    mfu = None
    if peak_flops:
        mfu = round(6.0 * n_params * b * s / dt / peak_flops, 4)

    # The same program measured 37.6% MFU device-side (PERF.md §1); an MFU
    # below 5% on TPU means the relay — not the chip — dominated the
    # measurement (observed during the round-3 outage: ~34 s/dispatch).
    # Only meaningful at MXU-feeding batch sizes (the threshold was
    # calibrated at b=8/16) — tiny APEX_BENCH_BATCH overrides are exempt.
    degraded = on_tpu and mfu is not None and mfu < 0.05 and b >= 8

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_BASELINE.json")
    key = f"gpt_tokens_per_sec_{platform}_scan"
    baselines = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baselines = json.load(f)
    if key not in baselines and not degraded and (not on_tpu or b >= 8):
        # never seed the recorded baseline from a degraded-relay run, nor
        # from a sub-calibration TPU batch the degraded detector can't
        # judge (the CPU smoke's fixed b=2 self-seeds as before)
        baselines[key] = tokens_per_sec
        with open(baseline_path, "w") as f:
            json.dump(baselines, f, indent=1)
    # no recorded baseline (degraded run refused to seed one): report 0,
    # the same "not comparable" sentinel the watchdog's error line uses
    vs_baseline = tokens_per_sec / baselines[key] if key in baselines else 0.0

    result = {
        "metric": f"gpt2s_train_tokens_per_sec ({platform})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
        "mfu": mfu,
        "dispatch_overhead_ms": round(overhead * 1e3, 1),
    }
    if degraded:
        result["note"] = (
            "TPU relay degraded during this run (per-step time far outside "
            "the device envelope measured in PERF.md §1: 82.5 ms/step, "
            "37.6% MFU at b=8); value reflects tunnel latency, not the chip")
    print(json.dumps(result))


def _watchdog():
    """Run main() in a subprocess with a hard timeout: a wedged TPU relay
    (observed round 3 — even backend init hangs, PERF.md §6) must produce
    an honest JSON error line, not hang the caller forever."""
    import subprocess

    env = dict(os.environ, APEX_BENCH_INNER="1")
    timeout = int(os.environ.get("APEX_BENCH_TIMEOUT", "1800"))
    try:
        # capture stdout (the JSON line) only; stderr is inherited so the
        # '# compiling ...' liveness prints stream during the slow compile
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, timeout=timeout,
                             stdout=subprocess.PIPE, text=True)
        sys.stdout.write(out.stdout)
        return out.returncode
    except subprocess.TimeoutExpired as e:
        def as_text(x):
            return x.decode(errors="replace") if isinstance(x, bytes) else (
                x or "")

        # (stderr streamed live — only stdout was piped)
        # the child may have printed its result and then wedged in backend
        # teardown — forward a completed JSON line rather than zeroing it
        for line in reversed(as_text(e.stdout).splitlines()):
            if line.startswith("{") and line.rstrip().endswith("}"):
                print(line)
                return 0
        print(json.dumps({
            "metric": "gpt2s_train_tokens_per_sec (tpu)",
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0,
            "mfu": None,
            "error": f"bench timed out after {timeout}s (TPU relay "
                     "unresponsive — see PERF.md §6; device-side numbers "
                     "for this tree are in PERF.md §1)",
        }))
        return 0


if __name__ == "__main__":
    if os.environ.get("APEX_BENCH_INNER") == "1":
        main()
    else:
        sys.exit(_watchdog())
